"""Fresh-process time-to-first-result for the lane-grid solve: tracing
(re-paid every process) vs an AOT export replay (utils/aot.py).

Both modes enable the persistent XLA compilation cache, so the A/B
isolates exactly the cost jax.export removes: trace + lower. Protocol —
run each mode twice in FRESH processes; the second invocation is the
measurement (first populates the XLA cache / AOT store):

    python benches/aot_glm.py --aot off   # populate, then again: measure
    python benches/aot_glm.py --aot on    # populate, then again: measure

Row count is deliberately small (524k): tracing/lowering cost depends on
the program structure, not the row count, and the data build would
otherwise dominate the wall clock.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--aot", choices=["on", "off"], default="off")
    p.add_argument("--rows", type=int, default=1 << 19)
    p.add_argument("--dir", default="/tmp/photon_aot_bench")
    args = p.parse_args()

    from photon_tpu.utils.compile_cache import enable_compilation_cache

    os.makedirs(args.dir, exist_ok=True)
    enable_compilation_cache(os.path.join(args.dir, "xla_cache"))

    import jax
    import jax.numpy as jnp

    import bench
    from photon_tpu.models.training import (_lane_solve, lane_weight_arrays,
                                            make_objective)
    from photon_tpu.ops.losses import TaskType
    from photon_tpu.optim.config import OptimizerConfig
    from photon_tpu.optim.regularization import l2

    t0 = time.perf_counter()
    batch, _ = bench.sparse_problem(rows=args.rows)
    jax.block_until_ready(batch.X.dense)
    t_data = time.perf_counter() - t0

    cfg = OptimizerConfig(max_iters=bench.S_ITERS, tolerance=0.0, reg=l2(),
                          reg_weight=0.0, history=5,
                          lane_history_dtype="bfloat16")
    weights = list(bench.S_GRID)
    l2s, l1s, static_cfg = lane_weight_arrays(cfg, weights)
    d = batch.X.n_features
    obj = make_objective(TaskType.LOGISTIC_REGRESSION, cfg, d)
    w0 = jnp.zeros((d,), jnp.float32)

    def fn(batch, w0, obj, l2s):
        return _lane_solve(obj, batch, w0, l2s, None, static_cfg)

    t0 = time.perf_counter()
    if args.aot == "on":
        from photon_tpu.utils.aot import AotStore

        store = AotStore(os.path.join(args.dir, "aot"))
        # The key carries the closure-captured static config: avals alone
        # can't see it, and a stale replay would silently measure the old
        # program (AotStore.call docstring).
        res = store.call(f"lane_grid@{args.rows}x{d}|{static_cfg}", fn,
                         batch, w0, obj, l2s)
    else:
        res = jax.jit(fn)(batch, w0, obj, l2s)
    jax.device_get(jnp.sum(res.w))
    t_first = time.perf_counter() - t0
    print(f"aot={args.aot}: data {t_data:.1f}s, "
          f"first result {t_first:.1f}s (trace+compile+solve)")


if __name__ == "__main__":
    main()
