"""The composed flagship: `run_training` end to end at ≥10M rows from
Avro files on disk (VERDICT r4 item 2 — BASELINE-config-4 evidence
through the PRODUCT path, not synthetic in-memory arrays).

Upstream GameTrainingDriver runs its 100M-row ads-CTR job from HDFS:
read → index → validate → train (fixed + per-user + per-item) → validate
AUC → save. This drives the same pipeline: block-encoded Avro on disk
(benches/_flagship_data.py), streaming ingestion auto-tripped by header
row counts, both random effects, validation AUC from the driver's own
evaluator — and reports the per-phase timings PERF.md records.

Run: python benches/flagship_e2e.py [--rows 10000000] [--runs 2]
Data files cache under --data-dir and are reused across runs (the second
process run measures the persistent-compilation-cache story end to end).

Round 6 — the 100M-row regime (BASELINE config 4's actual number):
`--rows 100000000` exceeds the per-chip HBM budget (est. ~17.6 GB
device-resident vs the 16 GiB default of --hbm-budget-gb), so the driver
auto-trips into STREAMED-OBJECTIVE mode: the fixed shard stays on host in
chunks and every fixed-effect L-BFGS iteration accumulates value+gradient
over streamed device chunks (the literal treeAggregate analog,
optim/streamed.py); random-effect shards and scalars stay resident. Peak
HBM is O(chunk + RE data + solver state), not O(dataset).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import argparse
import time

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=10_000_000)
    p.add_argument("--val-rows", type=int, default=1_000_000)
    p.add_argument("--users", type=int, default=100_000)
    p.add_argument("--items", type=int, default=50_000)
    p.add_argument("--sweeps", type=int, default=2)
    p.add_argument("--data-dir", default="/tmp/flagship_data")
    p.add_argument("--out-dir", default="/tmp/flagship_out")
    p.add_argument("--runs", type=int, default=1,
                   help="driver invocations (2nd is jit-warm in-process)")
    p.add_argument("--fixed-only", action="store_true",
                   help="also fit the fixed effect alone for the AUC gap")
    p.add_argument("--hbm-budget-gb", type=float, default=16.0,
                   help="per-chip HBM budget for the streamed-objective "
                        "auto-trip (16 = v5e; --rows 100000000 exceeds it "
                        "and engages the out-of-HBM path)")
    p.add_argument("--objective-chunk-rows", type=int, default=1 << 20,
                   help="host chunk height for streamed-objective shards")
    p.add_argument("--mesh", type=int, default=0,
                   help="shard the fit over an N-device mesh (0 = single "
                        "device). With the streamed objective engaged, "
                        "every host chunk row-shards across the mesh — the "
                        "pod-scale out-of-HBM regime — and the auto-trip "
                        "budgets against the POOLED HBM (per-chip budget "
                        "x N)")
    p.add_argument("--game-e2e-leg", action="store_true",
                   help="also run bench.py's game_e2e leg (the composed "
                        "pod-scale GAME fit: streamed+mesh blocked-ELL "
                        "fixed effect, entity-sharded random-effect "
                        "buckets, host margin-cache score exchange — vs "
                        "the resident single-chip fit) and print its "
                        "JSON line. The full-driver form of the same "
                        "regime is --rows past the HBM budget plus "
                        "--mesh N")
    p.add_argument("--game-re-leg", action="store_true",
                   help="also run bench.py's game_re leg (the pipelined + "
                        "straggler-compacted random-effect block loop vs "
                        "the sequential one, skewed entity sizes) and "
                        "print its JSON line")
    p.add_argument("--checkpoint-dir", default=None,
                   help="enable crash-consistent snapshots of the run's "
                        "solver state in this directory "
                        "(photon_tpu/checkpoint; relative paths land "
                        "under the run's out dir). A killed run rerun "
                        "with --resume restores the last committed "
                        "snapshot and finishes bit-identically")
    p.add_argument("--resume", action="store_true",
                   help="restore from --checkpoint-dir's last committed "
                        "snapshot (also appends to the run's existing "
                        "telemetry JSONL instead of truncating it)")
    p.add_argument("--checkpoint-leg", action="store_true",
                   help="also run bench.py's checkpoint_overhead leg "
                        "(streamed-dense solve with async snapshots "
                        "every K evaluations vs none; rows·iters/s "
                        "delta + snapshot bytes/s) and print its JSON "
                        "line")
    p.add_argument("--xprof-dir", default=None,
                   help="wrap each driver run in jax.profiler.start_trace/"
                        "stop_trace writing an XProf capture here, so the "
                        "telemetry spans (mirrored to TraceAnnotation) and "
                        "the attribution ledger's phases line up with the "
                        "device timeline on real TPUs")
    p.add_argument("--serving-leg", action="store_true",
                   help="also run bench.py's serving_qps leg (closed-loop "
                        "online scoring over a zipf entity mix through "
                        "the photon_tpu/serving micro-batching "
                        "dispatcher; QPS + p50/p95/p99 latency, with the "
                        "never-retraces assertion) and print its JSON "
                        "line")
    p.add_argument("--ingest-leg", action="store_true",
                   help="also run bench.py's ingest_throughput leg (cold "
                        "worker-pool Avro decode + cache build vs the "
                        "decode-once mmap'd chunk cache, plus the "
                        "stall-driven prefetch's upload-stall share of a "
                        "streamed pass) and print its JSON line")
    p.add_argument("--tuning-e2e-leg", action="store_true",
                   help="also run bench.py's tuning_e2e leg (the "
                        "lane-batched cost-aware tuner: 256 configs "
                        "through GP-proposed fixed-chunk lane rounds "
                        "with successive halving and warm survivor "
                        "re-solves, vs the point-at-a-time tuner "
                        "architecture — with the two-signature "
                        "no-retrace bound asserted live) and print its "
                        "JSON line")
    p.add_argument("--serving-slo-leg", action="store_true",
                   help="also run bench.py's open-loop serving_slo leg "
                        "(fixed arrival-rate sweep with the admission "
                        "policy armed: SLO verdict line + the graceful-"
                        "degradation curve past saturation — shed "
                        "fraction rises, served p99 stays bounded, zero "
                        "lost futures) and print its JSON line")
    args = p.parse_args()

    import _flagship_data as fd
    from photon_tpu.drivers.train import TrainingParams, run_training

    mesh = None
    if args.mesh:
        from photon_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(n_devices=args.mesh)

    os.makedirs(args.data_dir, exist_ok=True)
    train_path = os.path.join(args.data_dir, f"train_{args.rows}.avro")
    val_path = os.path.join(args.data_dir, f"val_{args.val_rows}.avro")
    truth = fd.planted_truth(args.users, args.items, seed=0)
    for path, rows, seed in ((train_path, args.rows, 1),
                             (val_path, args.val_rows, 2)):
        if os.path.exists(path):
            print(f"reusing {path} ({os.path.getsize(path) / 1e9:.2f} GB)")
            continue
        t0 = time.perf_counter()
        fd.write_flagship_avro(path, rows, args.users, args.items, truth,
                               seed=seed)
        dt = time.perf_counter() - t0
        print(f"wrote {path}: {rows} rows, "
              f"{os.path.getsize(path) / 1e9:.2f} GB in {dt:.0f}s "
              f"({rows / dt:,.0f} rec/s)", flush=True)

    def params(coords, tag):
        return TrainingParams(
            train_path=train_path,
            validation_path=val_path,
            output_dir=os.path.join(args.out_dir, tag),
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_resume=args.resume,
            feature_shards=fd.FEATURE_SHARDS,
            coordinates=coords,
            entity_fields=["userId", "itemId"],
            n_sweeps=args.sweeps,
            streaming=None,  # tri-state auto: 10M rows must trip it
            # tri-state auto: 100M rows exceed the budget and must trip
            # the out-of-HBM streamed objective; 10M stays resident
            streamed_objective=None,
            hbm_budget_bytes=int(args.hbm_budget_gb * 2**30),
            objective_chunk_rows=args.objective_chunk_rows,
            evaluators=["AUC"],
            # one cache across every run/tag (per-run output dirs would
            # each get a fresh default cache and defeat the 2nd-run story)
            compilation_cache_dir=os.path.join(
                os.path.abspath(args.out_dir), "xla_cache"),
        )

    import json

    from photon_tpu import profiling, telemetry

    for run in range(args.runs):
        # each driver invocation records a telemetry run (spans for the
        # driver phases, stall/eval/retrace counters, live iteration
        # events from any streamed solve — JSONL under the run's out
        # dir, compact report embedded in the JSON line printed below)
        # AND an attribution ledger (photon_tpu/profiling: per-program
        # modeled FLOPs/bytes vs measured wall, compile accounting —
        # ledger.json beside the telemetry JSONL)
        jsonl = os.path.join(args.out_dir, f"game_r{run}",
                             "telemetry.jsonl")
        ledger_json = os.path.join(args.out_dir, f"game_r{run}",
                                   "ledger.json")
        # a --resume rerun APPENDS to the dead run's event log (the sink
        # repairs a crash-torn tail record first) instead of truncating
        trun = telemetry.start_run(f"flagship_r{run}", jsonl_path=jsonl,
                                   append=args.resume)
        profiling.start_ledger(f"flagship_r{run}")
        if args.xprof_dir:
            import jax

            jax.profiler.start_trace(args.xprof_dir)
        t0 = time.perf_counter()
        try:
            out = run_training(params(fd.COORDINATES, f"game_r{run}"),
                               mesh=mesh)
        finally:
            if args.xprof_dir:
                import jax

                jax.profiler.stop_trace()
        total = time.perf_counter() - t0
        telemetry.finish_run()
        ledger_report = profiling.finish_ledger()
        # photon: allow(durable_write, bench-run report artifact — nothing resumes from it; a torn file just re-runs the bench)
        with open(ledger_json, "w") as fh:
            json.dump(ledger_report, fh)
        cluster_json = None
        if args.mesh:
            # mesh runs also get the cross-rank view beside the ledger:
            # this process's event log as rank 0 (a multi-process launch
            # drops its p<k>.jsonl files into the same directory and the
            # same call merges them all), spans wall-clock aligned
            from photon_tpu.telemetry.aggregate import (aggregate_cluster,
                                                        rank_files)

            cluster_json = os.path.join(args.out_dir, f"game_r{run}",
                                        "cluster_report.json")
            rank_map = {0: jsonl}
            rank_map.update(rank_files(os.path.dirname(jsonl)))
            cluster = aggregate_cluster(rank_map)
            cluster["timeline"] = cluster["timeline"][:256]
            # photon: allow(durable_write, bench-run report artifact — nothing resumes from it; a torn file just re-runs the bench)
            with open(cluster_json, "w") as fh:
                json.dump(cluster, fh)
        phases = {k: round(v, 1) for k, v in sorted(out.timings.items())}
        print(f"run {run}: total {total:.0f}s  phases {phases}", flush=True)
        print(f"run {run}: validation AUC {out.best.validation_score:.4f} "
              f"({args.sweeps} sweeps, fixed + per_user + per_item)",
              flush=True)
        print(json.dumps({"run": run, "total_s": round(total, 1),
                          "telemetry_jsonl": jsonl,
                          "ledger_json": ledger_json,
                          **({"cluster_report_json": cluster_json}
                             if cluster_json else {}),
                          "telemetry": trun.report_compact()}),
              flush=True)

    if args.fixed_only:
        t0 = time.perf_counter()
        out = run_training(params({"fixed": fd.COORDINATES["fixed"]},
                                  "fixed_only"), mesh=mesh)
        print(f"fixed-only: total {time.perf_counter() - t0:.0f}s  "
              f"AUC {out.best.validation_score:.4f}", flush=True)

    if args.game_e2e_leg:
        # bench.py's game_e2e leg verbatim: the composed pod-scale GAME
        # fit measured against its resident twin, beside the full-driver
        # flagship run above.
        import bench

        ge = bench.game_e2e_problem()
        res = bench.run_game_e2e(ge, streamed=False)
        stm = bench.run_game_e2e(ge, streamed=True)
        print(json.dumps({
            "leg": "game_e2e",
            "rows_iters_per_sec_aggregate":
                round(stm["rows_iters_per_sec"], 1),
            "resident_rows_iters_per_sec":
                round(res["rows_iters_per_sec"], 1),
            "streamed_over_resident":
                round(stm["rows_iters_per_sec"]
                      / res["rows_iters_per_sec"], 3),
            "n_chips": stm["n_chips"],
            "beyond_resident_ok": bool(stm.get("beyond_resident_ok",
                                               False))}), flush=True)

    if args.game_re_leg:
        # The SAME leg bench.py's JSON line carries (one problem
        # definition, two numbers): the random-effect block-loop rate with
        # and without the round-8 pipeline + straggler compaction.
        import bench

        ds_gr, rows_gr = bench.game_re_problem()
        seq = bench.run_game_re(ds_gr, rows_gr, pipelined=False)
        pipe = bench.run_game_re(ds_gr, rows_gr, pipelined=True)
        print(json.dumps({
            "leg": "game_re",
            "rows_iters_per_sec_per_chip": round(pipe, 1),
            "sequential_rows_iters_per_sec_per_chip": round(seq, 1),
            "speedup_vs_sequential": round(pipe / seq, 3)}), flush=True)

    if args.checkpoint_leg:
        # bench.py's checkpoint_overhead leg verbatim: the elasticity tax
        # of async snapshots on the streamed-dense solve, beside the
        # flagship run they protect.
        import bench

        ck = bench.run_checkpoint_overhead()
        print(json.dumps({
            "leg": "checkpoint_overhead",
            "rows_iters_per_sec": round(ck["rows_iters_per_sec"], 1),
            "baseline_rows_iters_per_sec":
                round(ck["baseline_rows_iters_per_sec"], 1),
            "overhead_pct": round(ck["overhead_pct"], 2),
            "cadence_evals": ck["cadence_evals"],
            "snapshots": ck["snapshots"],
            "snapshot_bytes_per_sec":
                round(ck["snapshot_bytes_per_sec"], 1)}), flush=True)

    if args.ingest_leg:
        # bench.py's ingest_throughput leg verbatim: the round-14 data
        # plane measured beside the flagship run it feeds.
        import bench

        ing = bench.run_ingest(bench.ingest_problem())
        print(json.dumps({
            "leg": "ingest_throughput",
            "cold_rows_per_sec": round(ing["cold_rows_per_sec"], 1),
            "cached_rows_per_sec": round(ing["cached_rows_per_sec"], 1),
            "cached_over_cold": round(ing["cached_over_cold"], 2),
            "upload_stall_pct": round(ing["upload_stall_pct"], 2),
            "stalled_passes": ing["stalled_passes"]}), flush=True)

    if args.tuning_e2e_leg:
        # bench.py's tuning_e2e leg verbatim: the lane-batched tuner's
        # configs-per-wall-clock measured against the point-at-a-time
        # architecture, beside the flagship runs it would tune.
        import bench

        tu = bench.run_tuning_e2e(bench.tuning_problem())
        print(json.dumps({
            "leg": "tuning_e2e",
            "configs_per_sec": round(tu["configs_per_sec"], 2),
            "sequential_configs_per_sec":
                round(tu["sequential_configs_per_sec"], 2),
            "speedup_vs_sequential":
                round(tu["speedup_vs_sequential"], 2),
            "n_configs": tu["n_configs"],
            "n_rounds": tu["n_rounds"]}), flush=True)

    if args.serving_leg or args.serving_slo_leg:
        # bench.py's serving legs verbatim: the online-scoring regime
        # (many tiny micro-batched requests) measured and retrace-checked
        # beside the training flagship it serves.
        import bench

        sv_ladder, sv_pool = bench.serving_problem()
        capacity = None
        if args.serving_leg:
            stats = bench.run_serving(sv_ladder, sv_pool)
            capacity = stats["qps"]
            print(json.dumps({
                "leg": "serving_qps",
                "qps": round(stats["qps"], 1),
                "p50_ms": round(stats["p50_ms"], 3),
                "p95_ms": round(stats["p95_ms"], 3),
                "p99_ms": round(stats["p99_ms"], 3),
                "n_requests": stats["n_requests"]}), flush=True)
        if args.serving_slo_leg:
            # the open-loop overload face: fixed arrival rates, admission
            # policy armed, SLO verdict + degradation curve. Calibrates
            # its own capacity unless the closed-loop leg just ran.
            slo = bench.run_serving_slo(sv_ladder, sv_pool,
                                        capacity_qps=capacity)
            print(json.dumps({
                "leg": "serving_slo",
                "sustained_qps": round(slo["sustained_qps"], 1),
                "p99_ms": round(slo["p99_ms"], 3),
                "overload_p99_ms": round(slo["overload_p99_ms"], 3),
                "overload_shed_pct": slo["overload_shed_pct"],
                "lost_futures": slo["lost_futures"],
                "ok": slo["ok"],
                "verdict": slo["verdict"],
                "curve": slo["curve"]}), flush=True)


if __name__ == "__main__":
    main()
