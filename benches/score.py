"""Scoring-driver throughput: chunked native-decode → device score →
vectorized ScoredItemAvro write, vs the native ingest decode rate
(VERDICT r3 item 2's target: scoring within ~2x of native ingest rec/s).

Run: python benches/score.py [--rows 200000]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import tempfile
import time

import numpy as np

if os.environ.get("PHOTON_BENCH_CPU"):
    # The axon TPU plugin overrides JAX_PLATFORMS env filtering; forcing
    # the config BEFORE backend init is the only way to pin plain CPU
    # (same trick as tests/conftest.py). Without this the "device" legs
    # of the bench measure the remote-tunnel round trip, not the compute.
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=200_000)
    p.add_argument("--bag-nnz", type=int, default=12)
    p.add_argument("--codec", default="deflate")
    args = p.parse_args()

    from photon_tpu.data.avro_io import write_avro
    from photon_tpu.data.ingest import (
        GameDataConfig,
        read_game_data,
        training_example_schema,
    )
    from photon_tpu.data.feature_bags import FeatureShardConfig
    from photon_tpu.drivers import (
        ScoringParams, TrainingParams, run_scoring, run_training,
    )

    rng = np.random.default_rng(0)
    n, k = args.rows, args.bag_nnz
    root = tempfile.mkdtemp(prefix="score_bench_")
    schema = training_example_schema(feature_bags=("features",),
                                     entity_fields=("memberId",))

    def gen(path, rows, seed):
        r = np.random.default_rng(seed)
        names = [f"f{j}" for j in range(5000)]
        recs = [{
            "response": float(r.integers(0, 2)),
            "offset": None, "weight": None, "uid": f"uid_{seed}_{i}",
            "memberId": f"m{r.integers(0, 1000)}",
            "features": [
                {"name": names[int(v)], "term": "",
                 "value": float(r.normal())}
                for v in r.integers(0, 5000, size=k)
            ],
        } for i in range(rows)]
        write_avro(path, recs, schema)

    train_path = os.path.join(root, "train.avro")
    gen(train_path, 4000, 1)
    shards = {"all": FeatureShardConfig(bags=("features",))}
    model_out = os.path.join(root, "model")
    run_training(TrainingParams(
        train_path=train_path, output_dir=model_out,
        feature_shards={"all": {"bags": ["features"]}},
        coordinates={"fixed": {"feature_shard": "all", "reg_type": "l2",
                               "reg_weight": 1.0, "max_iters": 10}},
        sparse_k=k + 1, data_validation="disabled"))

    data_path = os.path.join(root, "score_data")
    os.makedirs(data_path)
    per_file = args.rows // 4
    for fi in range(4):
        gen(os.path.join(data_path, f"part-{fi}.avro"), per_file, 10 + fi)
    n = per_file * 4
    sz = sum(os.path.getsize(os.path.join(data_path, f))
             for f in os.listdir(data_path))
    print(f"scoring input: {n} records, {sz / 1e6:.1f} MB, 4 files")

    # reference point: raw native ingest decode of the same data
    cfg = GameDataConfig(shards=shards, entity_fields=("memberId",))
    t0 = time.perf_counter()
    read_game_data(data_path, cfg, use_native=True, sparse_k=k + 1)
    dt_ingest = time.perf_counter() - t0
    print(f"native ingest:   {n / dt_ingest:12.0f} rec/s  ({dt_ingest:.2f} s)")

    # Two passes: the first pays the per-shape XLA compiles (a fixed cost —
    # chunk heights quantize to a handful of shapes), the second is the
    # steady-state throughput a long job sees. Evaluators off in the timed
    # pass: the ingest reference decodes only, so compare like with like.
    for label in ("cold", "warm"):
        out_dir = os.path.join(root, f"scored_{label}")
        t0 = time.perf_counter()
        out = run_scoring(ScoringParams(
            model_dir=os.path.join(model_out, "best_model"),
            data_path=data_path, output_dir=out_dir,
            feature_shards={"all": {"bags": ["features"]}},
            entity_fields=["memberId"], uid_field="uid",
            sparse_k=k + 1, output_codec=args.codec,
            evaluators=["RMSE"]))
        dt_score = time.perf_counter() - t0
        assert out.scores.shape[0] == n
        print(f"scoring driver ({label}): {n / dt_score:10.0f} rec/s  "
              f"({dt_score:.2f} s, codec={args.codec})")
    print(f"scoring / ingest ratio (warm): {dt_ingest / dt_score:.2f}x "
          f"(>= 0.5 meets the 'within ~2x of ingest' bar)")


if __name__ == "__main__":
    main()
