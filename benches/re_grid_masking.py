"""Convergence-masked lanes in the RE grid: the A/B (VERDICT r3 item 8).

Question: a lane-axis GAME grid runs every (entity, lane) solve in
lock-step — each chunk iterates until its SLOWEST lane converges, with
converged lanes' updates masked (jax's batched `lax.while_loop`
select-masks carries but still executes every member's FLOPs). Can
masking converged lanes recover the cost of a skewed grid, or is the
per-lane-adaptive sequential path the only structure that does?

Method: one random-effect coordinate (2000 entities x 8 rows), 4-lane
reg-weight grids of three difficulty profiles, vectorized (lane-axis) vs
sequential (per-lane adaptive) paths, warm wall-clock best-of-N.

Run: PHOTON_BENCH_CPU=1 python benches/re_grid_masking.py
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np

if os.environ.get("PHOTON_BENCH_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--entities", type=int, default=2000)
    p.add_argument("--rows-per", type=int, default=8)
    p.add_argument("--reps", type=int, default=3)
    args = p.parse_args()

    from photon_tpu.game.dataset import GameData
    from photon_tpu.game.estimator import (
        GameEstimator,
        RandomEffectConfig,
    )
    from photon_tpu.ops.losses import TaskType
    from photon_tpu.optim import regularization as reg
    from photon_tpu.optim.config import OptimizerConfig

    rng = np.random.default_rng(0)
    E, m = args.entities, args.rows_per
    n = E * m
    d = 4
    ids = np.repeat([f"e{i}" for i in range(E)], m)
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[:, -1] = 1.0  # intercept
    true_w = rng.normal(size=(E, d)).astype(np.float32)
    margin = np.einsum("nd,nd->n", X, true_w[np.repeat(np.arange(E), m)])
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    data = GameData.build(y, {"s": X}, {"ent": ids})

    def make_estimator():
        return GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs={"re": RandomEffectConfig(
                "ent", "s",
                OptimizerConfig(max_iters=60, tolerance=1e-7, reg=reg.l2(),
                                reg_weight=1.0))},
            n_sweeps=1, warm_start=False, vectorized_grid=True)

    def grid_of(weights):
        est = make_estimator()
        return est, [
            {"re": RandomEffectConfig(
                "ent", "s",
                OptimizerConfig(max_iters=60, tolerance=1e-7, reg=reg.l2(),
                                reg_weight=float(w)))}
            for w in weights
        ]

    profiles = {
        "uniform fast (4x l2=100)": [100.0] * 4,
        "uniform slow (4x l2=1e-3)": [1e-3] * 4,
        "skewed (100, 10, 1, 1e-3)": [100.0, 10.0, 1.0, 1e-3],
    }

    import dataclasses as dc

    def run(est, grid, vectorize):
        est2 = dc.replace(est, vectorized_grid=vectorize)
        return est2.fit(data, config_grid=grid)

    print(f"RE grid A/B: {E} entities x {m} rows, d={d}, 4 lanes, "
          f"1 sweep, logistic")
    for label, weights in profiles.items():
        row = {}
        for mode, vec in (("lane-axis", True), ("sequential", False)):
            est, grid = grid_of(weights)
            run(est, grid, vec)  # warm the jit caches
            best = float("inf")
            for _ in range(args.reps):
                t0 = time.perf_counter()
                out = run(est, grid, vec)
                best = min(best, time.perf_counter() - t0)
            row[mode] = best
            del out
        ratio = row["sequential"] / row["lane-axis"]
        verdict = (f"lane-axis {ratio:.2f}x faster" if ratio >= 1
                   else f"sequential {1 / ratio:.2f}x faster")
        print(f"  {label:28s}: lane-axis {row['lane-axis'] * 1e3:7.0f} ms  "
              f"sequential {row['sequential'] * 1e3:7.0f} ms  ({verdict})")


if __name__ == "__main__":
    main()
