"""Avro ingest throughput: C++ columnar decoder vs pure Python
(SURVEY.md §6's ingest numbers; reference: AvroDataReader on the JVM).

Run: python benches/ingest.py [--rows 20000]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import os
import tempfile
import time

import numpy as np

if os.environ.get("PHOTON_BENCH_CPU"):
    # The axon TPU plugin overrides JAX_PLATFORMS env filtering; pin plain
    # CPU before backend init (as tests/conftest.py does) so the decode
    # numbers aren't contaminated by tunnel transfers in coo_to_matrix.
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=20_000)
    p.add_argument("--bag-nnz", type=int, default=12)
    args = p.parse_args()

    from photon_tpu.data.avro_io import write_avro
    from photon_tpu.data.ingest import (
        GameDataConfig,
        read_game_data,
        training_example_schema,
    )
    from photon_tpu.data.feature_bags import FeatureShardConfig

    rng = np.random.default_rng(0)
    n, k = args.rows, args.bag_nnz
    schema = training_example_schema(feature_bags=("features",),
                                     entity_fields=("memberId",))
    records = [{
        "response": float(rng.integers(0, 2)),
        "offset": None, "weight": None, "uid": str(i),
        "memberId": f"m{rng.integers(0, 1000)}",
        "features": [
            {"name": f"f{rng.integers(0, 5000)}", "term": "",
             "value": float(rng.normal())}
            for _ in range(k)
        ],
    } for i in range(n)]
    path = os.path.join(tempfile.mkdtemp(), "bench.avro")
    write_avro(path, records, schema)
    print(f"wrote {n} records ({os.path.getsize(path) / 1e6:.1f} MB)")

    cfg = GameDataConfig(
        shards={"all": FeatureShardConfig(bags=("features",))},
        entity_fields=("memberId",),
    )
    for name, use_native in (("python", False), ("native C++", True)):
        t0 = time.perf_counter()
        data, _ = read_game_data(path, cfg, use_native=use_native)
        dt = time.perf_counter() - t0
        assert data.n == n
        print(f"{name:10s}: {dt:6.2f}s  ({n / dt:,.0f} rec/s)")

    # streaming (bounded-memory chunks) must hold the one-shot throughput
    from photon_tpu.data.streaming import (
        build_index_maps_streaming,
        iter_game_chunks,
    )

    maps = build_index_maps_streaming(path, cfg)
    for name, use_native in (("stream py", False), ("stream C++", True)):
        t0 = time.perf_counter()
        stream, chunks = iter_game_chunks(path, cfg, maps, chunk_rows=8192,
                                          sparse_k=args.bag_nnz + 1,  # + intercept
                                          use_native=use_native)
        total = sum(chunk.n for chunk in chunks)
        dt = time.perf_counter() - t0
        assert total == n
        print(f"{name:10s}: {dt:6.2f}s  ({n / dt:,.0f} rec/s; "
              f"peak arena {stream.peak_arena_bytes / 1e6:.1f} MB)")

    # Exotic-schema leg (VERDICT r3 item 3): extra fields the round-3
    # planner rejected — nested record, map, enum, wide union — now skip
    # natively via generic skip programs instead of dropping the whole job
    # to the pure-Python road.
    schema2 = dict(schema)
    schema2["fields"] = schema["fields"] + [
        {"name": "meta", "type": {"type": "record", "name": "Meta",
                                  "fields": [
                                      {"name": "a", "type": "long"},
                                      {"name": "b", "type": ["null",
                                                             "string",
                                                             "double"]}]}},
        {"name": "tags", "type": {"type": "map", "values": "string"}},
        {"name": "kind", "type": {"type": "enum", "name": "Kind",
                                  "symbols": ["A", "B"]}},
    ]
    recs2 = [dict(r, meta={"a": i, "b": None}, tags={"t": "v"},
                  kind="AB"[i % 2]) for i, r in enumerate(records)]
    path2 = os.path.join(os.path.dirname(path), "bench_exotic.avro")
    write_avro(path2, recs2, schema2)
    t0 = time.perf_counter()
    data, _ = read_game_data(path2, cfg, use_native=True)
    dt = time.perf_counter() - t0
    assert data.n == n
    print(f"exotic C++: {dt:6.2f}s  ({n / dt:,.0f} rec/s — schema the "
          "round-3 planner rejected, still native)")

    # Consumed-exotic leg (VERDICT r4 item 5): the CONSUMED columns
    # themselves in exotic shapes — union-wrapped bag, long-valued map
    # bag, 3-branch scalar union, wide entity union — previously one such
    # column dropped the whole job to the Python record decoder (~10x).
    ntv = {"type": "record", "name": "NTV3", "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"}]}
    schema3 = {"type": "record", "name": "ConsumedExotic", "fields": [
        {"name": "response", "type": "double"},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "weight", "type": ["null", "long", "string"],
         "default": None},
        {"name": "memberId",
         "type": ["null", "string", {"type": "array", "items": "int"}],
         "default": None},
        {"name": "features", "type": ["null", {"type": "array",
                                               "items": ntv}],
         "default": None},
        {"name": "ctx", "type": [{"type": "map", "values": "long"},
                                 "null"]},
    ]}
    recs3 = [{"response": r["response"], "offset": None,
              "weight": None if i % 3 else 2,
              "memberId": r["memberId"],
              "features": None if i % 13 == 7 else r["features"],
              "ctx": None if i % 5 == 2 else {"c1": i % 9, "c2": 3}}
             for i, r in enumerate(records)]
    cfg3 = GameDataConfig(
        shards={"all": FeatureShardConfig(bags=("features", "ctx"))},
        entity_fields=("memberId",),
        optional_entity_fields=("memberId",),
    )
    path3 = os.path.join(os.path.dirname(path), "bench_consumed.avro")
    write_avro(path3, recs3, schema3)
    for name, use_native in (("consumed py", False), ("consumed C++", True)):
        t0 = time.perf_counter()
        data, _ = read_game_data(path3, cfg3, use_native=use_native)
        dt = time.perf_counter() - t0
        assert data.n == n
        note = " — every consumed column exotic, still native" \
            if use_native else ""
        print(f"{name:12s}: {dt:6.2f}s  ({n / dt:,.0f} rec/s{note})")


if __name__ == "__main__":
    main()
