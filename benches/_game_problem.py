"""Shared synthetic GAME problem for the benches (game_scale / game_auc):
one definition so the two PERF.md tables describe the SAME workload."""
from __future__ import annotations

import numpy as np


def add_game_args(parser) -> None:
    parser.add_argument("--rows", type=int, default=1_000_000)
    parser.add_argument("--entities", type=int, default=50_000)
    parser.add_argument("--d-fixed", type=int, default=64)
    parser.add_argument("--d-re", type=int, default=8)


def planted_effects(d_fixed: int, d_re: int, entities: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=d_fixed).astype(np.float32) * 0.3
    u_true = rng.normal(size=(entities, d_re)).astype(np.float32)
    return w_true, u_true


def make_game_data(n_rows: int, entities: int, w_true, u_true, seed: int):
    """(GameData, y) rows of the planted mixed-effect logistic model."""
    from photon_tpu.game.dataset import GameData

    rng = np.random.default_rng(seed)
    d_fixed, d_re = w_true.shape[0], u_true.shape[1]
    Xf = rng.normal(size=(n_rows, d_fixed)).astype(np.float32)
    Xr = rng.normal(size=(n_rows, d_re)).astype(np.float32)
    ids = rng.integers(0, entities, size=n_rows)
    margin = Xf @ w_true + np.einsum("nd,nd->n", Xr, u_true[ids])
    y = (rng.uniform(size=n_rows) < 1 / (1 + np.exp(-margin))).astype(
        np.float32)
    return GameData.build(y, shards={"fixed": Xf, "re": Xr},
                          entity_ids={"member": ids}), y


def default_configs():
    """The benches' coordinate configs (fixed + per-member RE)."""
    from photon_tpu.game.estimator import (
        FixedEffectConfig,
        RandomEffectConfig,
    )
    from photon_tpu.optim.config import OptimizerConfig
    from photon_tpu.optim.regularization import l2

    cfg_f = OptimizerConfig(max_iters=30, reg=l2(), reg_weight=1.0)
    cfg_r = OptimizerConfig(max_iters=15, reg=l2(), reg_weight=5.0)
    return cfg_f, cfg_r, {
        "fixed": FixedEffectConfig("fixed", cfg_f),
        "per_member": RandomEffectConfig("member", "re", cfg_r),
    }
