"""GAME at scale on one chip (SURVEY.md §6's secondary numbers).

Synthetic mixed-effect logistic problem — 1M rows, 64-dim fixed effect,
50k entities × 8-dim random effects — measuring cold fit (compile +
2 sweeps), warm refit, scoring, and AUC vs the fixed effect alone.

Run: python benches/game_scale.py [--rows 1000000] [--entities 50000]

Grid mode (--grid N): N-point reg-weight grid over BOTH coordinates,
vectorized (lane-axis coordinate descent, game.grid) vs sequential —
the reference's model-selection workflow, one Spark job per point there.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main() -> None:
    from _game_problem import add_game_args, make_game_data, planted_effects
    from _game_problem import default_configs

    p = argparse.ArgumentParser()
    add_game_args(p)
    p.add_argument("--sweeps", type=int, default=2)
    p.add_argument("--grid", type=int, default=0,
                   help="N-point reg grid: vectorized vs sequential timing")
    args = p.parse_args()

    import jax.numpy as jnp

    from photon_tpu.evaluation.metrics import auc
    from photon_tpu.game.estimator import (
        FixedEffectConfig,
        GameEstimator,
        RandomEffectConfig,
    )
    from photon_tpu.game.scoring import score_game
    from photon_tpu.models.training import train_glm
    from photon_tpu.data.dataset import make_batch
    from photon_tpu.ops.losses import TaskType
    from photon_tpu.optim.regularization import l2
    from photon_tpu.optim.config import OptimizerConfig

    n, E = args.rows, args.entities
    w_true, u_true = planted_effects(args.d_fixed, args.d_re, E)
    t0 = time.perf_counter()
    data, y = make_game_data(n, E, w_true, u_true, seed=1)
    Xf = np.asarray(data.shards["fixed"])
    print(f"data gen + GameData.build: {time.perf_counter() - t0:.1f}s "
          f"({n} rows, {E} entities)")

    cfg_f, cfg_r, coordinate_configs = default_configs()

    if args.grid:
        import dataclasses
        import itertools

        if args.grid < 2:
            p.error("--grid needs at least 2 points (the vectorized path "
                    "only engages for true multi-point grids)")
        G = args.grid
        wf = np.logspace(-1, 1, max(2, int(np.ceil(np.sqrt(G)))))
        wr = np.logspace(0, 1.5, max(2, int(np.ceil(G / len(wf)))))
        pairs = list(itertools.product(wf, wr))[:G]
        grid = [{
            "fixed": FixedEffectConfig(
                "fixed", dataclasses.replace(cfg_f, reg_weight=float(a))),
            "per_member": RandomEffectConfig(
                "member", "re",
                dataclasses.replace(cfg_r, reg_weight=float(b))),
        } for a, b in pairs]

        def run(vectorized):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs=coordinate_configs,
                n_sweeps=args.sweeps, warm_start=False,
                vectorized_grid=vectorized)
            if vectorized:
                assert est.would_vectorize(grid, data=data), \
                    "grid would not take the vectorized path"
            t0 = time.perf_counter()
            out = est.fit(data, config_grid=grid)
            cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            est.fit(data, config_grid=grid)
            warm = time.perf_counter() - t0
            return out, cold, warm

        rv, cold_v, warm_v = run(True)
        rs, cold_s, warm_s = run(False)
        print(f"grid {len(grid)} points, {args.sweeps} sweeps:")
        print(f"  vectorized (lane-axis): cold {cold_v:.1f}s warm {warm_v:.1f}s")
        print(f"  sequential:             cold {cold_s:.1f}s warm {warm_s:.1f}s")
        print(f"  warm speedup: {warm_s / warm_v:.1f}x")
        for a, b in zip(rv, rs):
            dv = abs(a.descent.objective_history[-1]
                     - b.descent.objective_history[-1])
            assert dv / abs(b.descent.objective_history[-1]) < 1e-2, dv
        return

    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configs=coordinate_configs,
        n_sweeps=args.sweeps,
    )
    t0 = time.perf_counter()
    results = est.fit(data)
    cold = time.perf_counter() - t0
    print(f"cold fit ({args.sweeps} sweeps, incl. compile): {cold:.1f}s")

    t0 = time.perf_counter()
    est.fit(data)
    warm = time.perf_counter() - t0
    print(f"warm refit ({args.sweeps} sweeps): {warm:.1f}s "
          f"(~{warm / args.sweeps:.1f}s/sweep)")

    model = results[0].model
    dd = data.to_device()  # one transfer; repeated scoring is device-resident
    scores = np.asarray(score_game(model, dd))  # warm-up (compile)
    t0 = time.perf_counter()
    scores = np.asarray(score_game(model, dd))
    print(f"scoring {n} rows (device-resident): "
          f"{time.perf_counter() - t0:.1f}s")

    game_auc = float(auc(jnp.asarray(scores), jnp.asarray(y)))
    fe_only, _ = train_glm(make_batch(Xf, y), TaskType.LOGISTIC_REGRESSION,
                           OptimizerConfig(max_iters=30, reg=l2(),
                                           reg_weight=1.0))
    fe_auc = float(auc(fe_only.score(jnp.asarray(Xf)), jnp.asarray(y)))
    print(f"AUC: GAME {game_auc:.4f} vs fixed-effect-only {fe_auc:.4f}")


if __name__ == "__main__":
    main()
