"""GAME at BASELINE-config-4 shape: per-user + per-item random effects at
≥10M rows on one chip (VERDICT r3 item 4 — the 100M-row ads-CTR config,
scaled to what one v5e's HBM holds comfortably).

bf16 storage for the (wide) fixed shard — half the tunnel transfer and
HBM, f32 accumulation in the matvec — and f32 for the narrow per-entity
shards. Measures host bucketing, data placement, cold fit (compile +
sweeps), warm refit, scoring, and AUC vs the fixed effect alone.

Run: python benches/game_10m.py [--rows 10000000]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np

if os.environ.get("PHOTON_BENCH_CPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, default=10_000_000)
    p.add_argument("--users", type=int, default=100_000)
    p.add_argument("--items", type=int, default=50_000)
    p.add_argument("--d-fixed", type=int, default=32)
    p.add_argument("--d-re", type=int, default=4)
    p.add_argument("--sweeps", type=int, default=2)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from photon_tpu.evaluation.metrics import auc
    from photon_tpu.game.dataset import GameData
    from photon_tpu.game.estimator import (
        FixedEffectConfig,
        GameEstimator,
        RandomEffectConfig,
    )
    from photon_tpu.game.scoring import score_game
    from photon_tpu.data.dataset import make_batch
    from photon_tpu.models.training import train_glm
    from photon_tpu.ops.losses import TaskType
    from photon_tpu.optim.config import OptimizerConfig
    from photon_tpu.optim.regularization import l2

    n, U, I = args.rows, args.users, args.items
    df, dr = args.d_fixed, args.d_re
    rng = np.random.default_rng(0)

    t0 = time.perf_counter()
    w_true = (rng.normal(size=df) * 0.3).astype(np.float32)
    u_true = rng.normal(size=(U, dr)).astype(np.float32)
    i_true = rng.normal(size=(I, dr)).astype(np.float32)
    Xf = rng.normal(size=(n, df)).astype(np.float32)
    Xu = rng.normal(size=(n, dr)).astype(np.float32)
    Xi = rng.normal(size=(n, dr)).astype(np.float32)
    uid = rng.integers(0, U, size=n)
    iid = rng.integers(0, I, size=n)
    margin = (Xf @ w_true + np.einsum("nd,nd->n", Xu, u_true[uid])
              + np.einsum("nd,nd->n", Xi, i_true[iid]))
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    print(f"host data gen: {time.perf_counter() - t0:.1f}s "
          f"({n} rows, {U} users + {I} items, d_fixed={df} bf16, "
          f"d_re={dr} f32)")

    # bf16 on HOST first (half the tunnel bytes), then ONE device_put; the
    # per-entity shards stay host numpy — entity bucketing gathers them on
    # host anyway (stream_to_device's feature_dtype does the same cast for
    # the Avro-file road; synthetic data skips the ingest pass).
    t0 = time.perf_counter()
    Xf_dev = jax.device_put(Xf.astype(jnp.bfloat16))
    jax.block_until_ready(Xf_dev)
    print(f"fixed shard -> device (bf16, "
          f"{Xf_dev.nbytes / 1e9:.2f} GB): {time.perf_counter() - t0:.1f}s")
    del Xf

    data = GameData.build(
        y, shards={"fixed": Xf_dev, "u_re": Xu, "i_re": Xi},
        entity_ids={"user": uid, "item": iid})

    cfg_f = OptimizerConfig(max_iters=30, reg=l2(), reg_weight=1.0)
    cfg_r = OptimizerConfig(max_iters=15, reg=l2(), reg_weight=5.0)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configs={
            "fixed": FixedEffectConfig("fixed", cfg_f),
            "per_user": RandomEffectConfig("user", "u_re", cfg_r),
            "per_item": RandomEffectConfig("item", "i_re", cfg_r),
        },
        n_sweeps=args.sweeps)

    t0 = time.perf_counter()
    out = est.fit(data)[0]
    jax.block_until_ready(out.model.coordinates["fixed"].model.weights)
    cold = time.perf_counter() - t0
    print(f"cold fit ({args.sweeps} sweeps, 3 coordinates, incl. XLA "
          f"compile + entity bucketing + RE transfers): {cold:.1f}s")

    t0 = time.perf_counter()
    out = est.fit(data)[0]
    jax.block_until_ready(out.model.coordinates["fixed"].model.weights)
    warm = time.perf_counter() - t0
    print(f"warm refit ({args.sweeps} sweeps): {warm:.1f}s "
          f"({n * args.sweeps / warm:.2e} row-sweeps/sec)")

    t0 = time.perf_counter()
    margin_hat = score_game(out.model, data)
    mh = np.asarray(margin_hat)
    t_score = time.perf_counter() - t0
    game_auc = float(auc(mh, y))

    fixed_only, _ = train_glm(
        make_batch(Xf_dev, y), TaskType.LOGISTIC_REGRESSION, cfg_f)
    f_auc = float(auc(np.asarray(fixed_only.score(Xf_dev)), y))
    print(f"scoring {n} rows: {t_score:.1f}s")
    print(f"AUC: GAME {game_auc:.3f} vs fixed-only {f_auc:.3f}")


if __name__ == "__main__":
    main()
