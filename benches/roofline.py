"""Roofline accounting + row scaling for the headline sparse leg
(VERDICT r3 item 5): how much of each iteration is data-proportional X
work vs d-linear solver-state bookkeeping, what HBM bandwidth the chip
actually achieves, and how throughput grows as rows amortize the d-term.

Per margin-cached L-BFGS iteration the traffic model is:
  X passes: 2 x (hot dense block n x 1024 bf16 + COO tail ~n*33*(4+2)B)
  state:    two-loop recursion reads 2m (d,) f32 vectors + ~6 more (d,)
            touches (w/g/s/y updates, dot products), d = 10M, m = 5
so t_iter ≈ t_state + n * b_row / BW. Measuring rows·iters/s at several
row counts fits both terms directly.

Run: python benches/roofline.py [--rows 524288 1048576 2097152]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--rows", type=int, nargs="+",
                   default=[1 << 19, 1 << 20, 1 << 21])
    args = p.parse_args()

    import jax

    import bench

    results = []
    for n in args.rows:
        t0 = time.perf_counter()
        batch, _ = bench.sparse_problem(rows=n)
        jax.block_until_ready(batch.X.dense)
        t_load = time.perf_counter() - t0
        value = bench.run_sparse(batch)
        iters_per_s = value / n
        t_iter = 1.0 / iters_per_s
        # bytes per iteration: 2 X passes + L-BFGS state traffic. Tail
        # bytes come from the ACTUAL compacted tail (to_hybrid keeps only
        # cold nnz there — ~5% of the 33/row; counting all of them would
        # overstate achieved bandwidth ~9%).
        hot = n * bench.S_DENSE * 2              # bf16 dense block
        X = batch.X
        # per-ITERATION tail traffic: the matvec pass reads the row-major
        # arrays, the rmatvec pass reads the buckets — each once, so the
        # sum is already both passes (only the hot block is read twice)
        tail_mv = int(X.tail_pcols.nbytes + X.tail_vals.nbytes
                      + X.row_bounds.nbytes)
        tail_rmv = int(sum(br.nbytes + bv.nbytes
                           for br, bv in zip(X.bucket_rows, X.bucket_vals)))
        tail = tail_mv + tail_rmv
        x_bytes = 2 * hot + tail
        state_bytes = (2 * 5 + 6) * bench.S_FEATURES * 4
        gbs = (x_bytes + state_bytes) / t_iter / 1e9
        print(f"rows={n:>8d}: {value:.3e} rows*iters/s  "
              f"({t_iter * 1e3:.1f} ms/iter, load {t_load:.0f}s, "
              f"~{gbs:.0f} GB/s vs 819 peak)")
        results.append((n, t_iter))
        del batch

    if len(results) >= 2:
        # least-squares fit t_iter = t_state + n * t_row
        ns = np.array([r[0] for r in results], np.float64)
        ts = np.array([r[1] for r in results], np.float64)
        A = np.stack([np.ones_like(ns), ns], axis=1)
        (t_state, t_row), *_ = np.linalg.lstsq(A, ts, rcond=None)
        print(f"fit: t_iter ≈ {t_state * 1e3:.1f} ms (d-linear state) + "
              f"rows × {t_row * 1e9:.2f} ns/row")
        # per-row X bytes from the last measured problem's real tail share
        # (hot block twice per iteration, tail arrays once each)
        bw_rows = (bench.S_DENSE * 2 * 2 + tail / ns[-1]) / t_row
        print(f"  X-pass effective bandwidth: {bw_rows / 1e9:.0f} GB/s; "
              f"state share at 524k rows: "
              f"{t_state / (t_state + (1 << 19) * t_row) * 100:.0f}%, "
              f"at 2M rows: "
              f"{t_state / (t_state + (1 << 21) * t_row) * 100:.0f}%")


if __name__ == "__main__":
    main()
