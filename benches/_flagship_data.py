"""Flagship dataset generator: BASELINE-config-4-shaped GAME training data
(two random effects) stream-encoded to Avro container files on disk.

The reference's 100M-row ads-CTR job reads TrainingExampleAvro from HDFS;
this writes the same record SHAPE — response double, two entity-id string
columns, three NameTermValue feature bags — at 10M+ rows in minutes by
exploiting a fixed-width layout: constant-length feature names and
entity-id strings make every record the same byte length, so a whole
container block encodes as one numpy template fill (no per-record
write_datum loop, which caps near 10^4 rec/s).

Ground truth: fixed weights w, per-user u and per-item v effects; the
margin is Xf·w + Xu·u[user] + Xi·v[item], so a correct GAME fit separates
all three (the AUC gap vs fixed-only is the signal the driver's
validation metrics must reproduce).
"""
from __future__ import annotations

import numpy as np

from photon_tpu.data.avro_io import AvroBlockWriter

D_FIXED = 32
D_RE = 4


def flagship_schema() -> dict:
    ntv = {"type": "record", "name": "NTVF", "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "float"}]}
    return {"type": "record", "name": "FlagshipExampleAvro", "fields": [
        {"name": "response", "type": "double"},
        {"name": "userId", "type": "string"},
        {"name": "itemId", "type": "string"},
        {"name": "fixed", "type": {"type": "array", "items": ntv}},
        {"name": "u_re", "type": {"type": "array", "items": "NTVF"}},
        {"name": "i_re", "type": {"type": "array", "items": "NTVF"}},
    ]}


def _varint_zigzag(v: int) -> bytes:
    z = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _string(s: str) -> bytes:
    b = s.encode()
    return _varint_zigzag(len(b)) + b


def _template():
    """(template row bytes, slot index arrays) for the fixed-width record:
    every per-row byte position is precomputed once."""
    buf = bytearray()
    slots = {}

    def mark(name, width):
        slots.setdefault(name, []).extend(range(len(buf), len(buf) + width))
        buf.extend(b"\x00" * width)

    mark("response", 8)
    buf += _varint_zigzag(7) + b"u"
    mark("uid", 6)
    buf += _varint_zigzag(6) + b"i"
    mark("iid", 5)
    # fixed bag: one array block of D_FIXED entries, then end marker
    buf += _varint_zigzag(D_FIXED)
    for j in range(D_FIXED):
        buf += _string(f"f{j:02d}") + _varint_zigzag(0)
        mark("fv", 4)
    buf += _varint_zigzag(0)
    for bag in ("uv", "iv"):
        buf += _varint_zigzag(D_RE)
        for j in range(D_RE):
            buf += _string(f"r{j}") + _varint_zigzag(0)
            mark(bag, 4)
        buf += _varint_zigzag(0)
    return (np.frombuffer(bytes(buf), np.uint8),
            {k: np.asarray(v, np.int64) for k, v in slots.items()})


def _digits(ids, width):
    """(n, width) ASCII digit bytes of integer ids, zero-padded."""
    cols = [(ids // 10 ** (width - 1 - k)) % 10 + 48 for k in range(width)]
    return np.stack(cols, axis=1).astype(np.uint8)


def planted_truth(users: int, items: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    w = (rng.normal(size=D_FIXED) * 0.3).astype(np.float32)
    u = rng.normal(size=(users, D_RE)).astype(np.float32)
    v = rng.normal(size=(items, D_RE)).astype(np.float32)
    return w, u, v


def write_flagship_avro(path, n_rows: int, users: int, items: int,
                        truth, seed: int, rows_per_block: int = 32768,
                        codec: str = "null") -> None:
    """Stream `n_rows` records to `path`, one numpy-filled container block
    at a time (bounded memory: one block's bytes + its feature draws)."""
    w, u, v = truth
    template, slots = _template()
    rng = np.random.default_rng(seed)
    with AvroBlockWriter(path, flagship_schema(), codec=codec) as writer:
        done = 0
        while done < n_rows:
            b = min(rows_per_block, n_rows - done)
            Xf = rng.normal(size=(b, D_FIXED)).astype(np.float32)
            Xu = rng.normal(size=(b, D_RE)).astype(np.float32)
            Xi = rng.normal(size=(b, D_RE)).astype(np.float32)
            uid = rng.integers(0, users, size=b)
            iid = rng.integers(0, items, size=b)
            margin = (Xf @ w + np.einsum("nd,nd->n", Xu, u[uid])
                      + np.einsum("nd,nd->n", Xi, v[iid]))
            y = (rng.uniform(size=b)
                 < 1 / (1 + np.exp(-margin))).astype(np.float64)
            block = np.tile(template, (b, 1))
            block[:, slots["response"]] = y.astype("<f8").view(
                np.uint8).reshape(b, 8)
            block[:, slots["uid"]] = _digits(uid, 6)
            block[:, slots["iid"]] = _digits(iid, 5)
            block[:, slots["fv"]] = Xf.astype("<f4").view(
                np.uint8).reshape(b, 4 * D_FIXED)
            block[:, slots["uv"]] = Xu.astype("<f4").view(
                np.uint8).reshape(b, 4 * D_RE)
            block[:, slots["iv"]] = Xi.astype("<f4").view(
                np.uint8).reshape(b, 4 * D_RE)
            writer.write_block(b, block.tobytes())
            done += b


FEATURE_SHARDS = {
    "fixed": {"bags": ["fixed"], "has_intercept": True},
    "u_re": {"bags": ["u_re"], "has_intercept": False},
    "i_re": {"bags": ["i_re"], "has_intercept": False},
}

COORDINATES = {
    "fixed": {"feature_shard": "fixed", "reg_type": "l2",
              "reg_weight": 1.0, "max_iters": 30},
    "per_user": {"feature_shard": "u_re", "entity_name": "userId",
                 "reg_type": "l2", "reg_weight": 5.0, "max_iters": 15},
    "per_item": {"feature_shard": "i_re", "entity_name": "itemId",
                 "reg_type": "l2", "reg_weight": 5.0, "max_iters": 15},
}
