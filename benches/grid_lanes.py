"""Grid-lane scaling (docs/PERF.md's tables): aggregate throughput of the
vmapped reg-weight sweep vs lane count, on either headline leg.

The sparse leg is the round-5 flagship question: the single-lane
10M-feature solve is d-state-bound (~19.4 ms/iter of L-BFGS bookkeeping +
59.3 ns/row of X work, benches/roofline.py), so lanes that share every X
pass should multiply rows·iters/s until the (G, d) solver state saturates
HBM. Timing closes with an O(1)-byte readback (device_results=True):
fetching the (G, 10M) coefficient block would put G×40 MB of tunnel
transfer inside the timed region.

Run: python benches/grid_lanes.py --leg sparse --lanes 1 2 4 8
     python benches/grid_lanes.py --leg dense  --lanes 8 16 32
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--leg", choices=["sparse", "dense"], default="sparse")
    p.add_argument("--lanes", type=int, nargs="+", default=[1, 2, 4, 8])
    p.add_argument("--reps", type=int, default=3)
    p.add_argument("--rows", type=int, default=None,
                   help="sparse-leg row count (default bench.S_ROWS)")
    p.add_argument("--history-dtype", default=None,
                   help="lane solver S/Y storage dtype (e.g. bfloat16); "
                        "prints per-lane final losses for the quality A/B")
    p.add_argument("--reg", choices=["l2", "elastic"], default="l2",
                   help="elastic = elastic_net(0.5): the sweep rides the "
                        "lane-minor OWL-QN road (L1 production shape)")
    p.add_argument("--opt", choices=["lbfgs", "tron"], default="lbfgs",
                   help="tron: the sweep rides the lane-minor margin-"
                        "cached TRON (smooth reg only)")
    args = p.parse_args()
    if args.opt == "tron" and args.reg == "elastic":
        # lane_weight_arrays force-routes any L1 sweep to OWL-QN (upstream
        # rule), so this combination would silently measure the OWL-QN
        # solver under a TRON label.
        p.error("--opt tron requires --reg l2 (L1 sweeps always run OWL-QN)")
    if args.opt == "tron" and args.leg == "sparse":
        import bench  # the guard must track the sparse leg's REAL default

        if (args.rows or bench.S_ROWS) > 1 << 20:
            # docs/PERF.md: the TRON lane program at the 2M-row shape
            # reproducibly crashes the remote-compile service; 1M compiles
            # and runs. Refuse the documented-fatal default instead of
            # taking the shared compiler down.
            p.error("--opt tron on the sparse leg needs --rows <= 1048576 "
                    "(the 2M-row TRON lane program kills the remote "
                    "compile service; docs/PERF.md)")

    import jax
    import jax.numpy as jnp

    import bench
    from photon_tpu.models.training import train_glm_grid
    from photon_tpu.ops.losses import TaskType
    from photon_tpu.optim.config import OptimizerConfig, OptimizerType
    from photon_tpu.optim.regularization import elastic_net, l2

    if args.leg == "sparse":
        rows = args.rows or bench.S_ROWS
        t0 = time.perf_counter()
        batch, _ = bench.sparse_problem(rows=rows)
        jax.block_until_ready(batch.X.dense)
        print(f"sparse problem ({rows} rows x {bench.S_FEATURES} features) "
              f"loaded in {time.perf_counter() - t0:.0f}s")
        iters_cfg = bench.S_ITERS
    else:
        rows = bench.D_ROWS
        batch = bench.dense_problem()
        jax.block_until_ready(batch.X)
        iters_cfg = bench.D_ITERS
    cfg = OptimizerConfig(
        optimizer=(OptimizerType.TRON if args.opt == "tron"
                   else OptimizerType.LBFGS),
        max_iters=iters_cfg, tolerance=0.0,
        reg=elastic_net(0.5) if args.reg == "elastic" else l2(),
        reg_weight=0.0, history=5,
        lane_history_dtype=args.history_dtype)

    dev = jax.devices()[0]
    for g in args.lanes:
        weights = list(np.geomspace(1e-4, 1e-2, g)) if g > 1 else [1e-3]

        def run():
            res, _ = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION,
                                    cfg, weights, device_results=True)
            # O(1)-byte readback closes the timing (see module docstring);
            # the (G,) final losses ride along for the quality A/B.
            return jax.device_get((jnp.sum(res.w), jnp.sum(res.iterations),
                                   res.value))

        try:
            t0 = time.perf_counter()
            _, iters, losses = run()  # compile + autotune
            t_compile = time.perf_counter() - t0
            best = float("inf")
            for _ in range(args.reps):
                t0 = time.perf_counter()
                _, iters, losses = run()
                best = min(best, time.perf_counter() - t0)
        except Exception as e:  # OOM at some G is an answer, not a crash
            print(f"G={g:3d}: FAILED ({type(e).__name__}: {str(e)[:200]})")
            continue
        stats = dev.memory_stats() or {}
        peak = stats.get("peak_bytes_in_use", 0) / 2**30
        agg = rows * int(iters) / best
        print(f"G={g:3d}: {best * 1e3:7.0f} ms  {int(iters):4d} lane-iters  "
              f"{agg:.3e} rows*iters/s aggregate  "
              f"({agg / g:.3e}/lane, compile {t_compile:.0f}s, "
              f"peak HBM {peak:.1f} GiB)")
        print(f"       final losses: "
              + " ".join(f"{v:.8e}" for v in np.asarray(losses)))


if __name__ == "__main__":
    main()
