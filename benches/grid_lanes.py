"""Grid-lane scaling (docs/PERF.md's table): throughput of the vmapped
reg-weight sweep vs lane count on the headline bench problem.

Run: python benches/grid_lanes.py [--lanes 8 16 32]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--lanes", type=int, nargs="+", default=[8, 16, 32])
    p.add_argument("--reps", type=int, default=4)
    args = p.parse_args()

    import jax

    import bench
    from photon_tpu.models.training import train_glm_grid
    from photon_tpu.ops.losses import TaskType
    from photon_tpu.optim.config import OptimizerConfig
    from photon_tpu.optim.regularization import l2

    batch = jax.device_put(bench.make_problem())
    jax.block_until_ready(batch.X)
    cfg = OptimizerConfig(max_iters=bench.MAX_ITERS, tolerance=0.0,
                          reg=l2(), reg_weight=0.0)
    for g in args.lanes:
        weights = list(np.geomspace(1e-4, 1e-2, g))

        def run():
            return train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                                  weights)

        grid = run()  # compile
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            grid = run()
            best = min(best, time.perf_counter() - t0)
        iters = sum(int(r.iterations) for _, r in grid)
        print(f"G={g:3d}: {best * 1e3:6.0f} ms  {iters:4d} lane-iters  "
              f"{bench.N_ROWS * iters / best:.3e} rows*iters/sec")


if __name__ == "__main__":
    main()
