"""Wall-clock-to-target-AUC for a GAME fit (the BASELINE.json metric shape).

Synthetic mixed-effect logistic problem (per-member random effects over a
64-dim fixed effect), held-out validation AUC measured after EVERY
coordinate-descent sweep; reports the wall-clock to reach the converged AUC
minus 1e-4 (BASELINE.json's AUC-parity tolerance), with and without the
one-time XLA compile.

Run: python benches/game_auc.py [--rows 1000000] [--entities 50000]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import argparse
import time

import numpy as np


def main() -> None:
    from _game_problem import add_game_args, make_game_data, planted_effects
    from _game_problem import default_configs

    p = argparse.ArgumentParser()
    add_game_args(p)
    p.add_argument("--max-sweeps", type=int, default=5)
    args = p.parse_args()

    import jax.numpy as jnp

    from photon_tpu.evaluation.metrics import auc
    from photon_tpu.game.estimator import GameEstimator
    from photon_tpu.game.scoring import score_game
    from photon_tpu.ops.losses import TaskType

    n, E = args.rows, args.entities
    n_val = max(n // 10, 1)
    w_true, u_true = planted_effects(args.d_fixed, args.d_re, E)

    t0 = time.perf_counter()
    data, _ = make_game_data(n, E, w_true, u_true, seed=1)
    val, y_val = make_game_data(n_val, E, w_true, u_true, seed=2)
    print(f"data gen: {time.perf_counter() - t0:.1f}s "
          f"({n} train rows, {n_val} val rows, {E} entities)")

    _, _, coordinate_configs = default_configs()
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configs=coordinate_configs,
        n_sweeps=1,
    )
    val_dev = val.to_device()

    # One sweep at a time, warm-starting from the previous model — identical
    # to one fit with n_sweeps=k, but instrumented per sweep.
    models = None
    aucs, sweep_secs = [], []
    for sweep in range(args.max_sweeps):
        t0 = time.perf_counter()
        (r,) = est.fit(data, initial_models=models)
        models = dict(r.model.coordinates)
        dt = time.perf_counter() - t0
        scores = score_game(r.model, val_dev)
        a = float(auc(jnp.asarray(scores), jnp.asarray(y_val)))
        sweep_secs.append(dt)
        aucs.append(a)
        print(f"sweep {sweep + 1}: {dt:.1f}s  val AUC {a:.4f}")

    target = max(aucs) - 1e-4  # BASELINE.json's AUC-parity tolerance
    hit = next(i for i, a in enumerate(aucs) if a >= target)
    to_target = sum(sweep_secs[:hit + 1])
    # Warm time-to-target: re-fit from scratch with everything compiled —
    # what a production re-train (same shapes) pays.
    t0 = time.perf_counter()
    models = None
    for _ in range(hit + 1):
        (r,) = est.fit(data, initial_models=models)
        models = dict(r.model.coordinates)
    warm = time.perf_counter() - t0
    scores = score_game(r.model, val_dev)
    a_warm = float(auc(jnp.asarray(scores), jnp.asarray(y_val)))
    assert a_warm >= target - 1e-3, (a_warm, target)
    print(f"target AUC {target:.4f} reached at sweep {hit + 1}")
    print(f"wall-clock to target: {to_target:.1f}s incl. one-time XLA "
          f"compile; {warm:.1f}s compiled (fresh re-fit, same shapes)")


if __name__ == "__main__":
    main()
