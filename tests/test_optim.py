"""Optimizer convergence tests.

Mirrors the reference's optimization suite (LBFGSTest, OWLQNTest, TRONTest:
convergence on convex problems, agreement between optimizers, L1 sparsity).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.optim.lbfgs import minimize_lbfgs
from photon_tpu.optim.owlqn import minimize_owlqn
from photon_tpu.optim.tron import minimize_tron


def _logistic_problem(rng, n=500, d=15, seed_scale=0.5):
    X = rng.normal(size=(n, d)).astype(np.float32)
    wt = (rng.normal(size=d) * seed_scale).astype(np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-X @ wt))).astype(np.float32)
    Xj, yj = jnp.asarray(X), jnp.asarray(y)

    def vg(w):
        z = Xj @ w
        return (
            jnp.sum(jax.nn.softplus(z) - yj * z),
            Xj.T @ (jax.nn.sigmoid(z) - yj),
        )

    def hvp(w, v):
        s = jax.nn.sigmoid(Xj @ w)
        return Xj.T @ (s * (1 - s) * (Xj @ v))

    return X, y, vg, hvp


def test_lbfgs_quadratic():
    A = jnp.diag(jnp.array([1.0, 10.0, 100.0], jnp.float32))
    b = jnp.array([1.0, 2.0, 3.0], jnp.float32)
    vg = jax.value_and_grad(lambda w: 0.5 * w @ A @ w - b @ w)
    res = minimize_lbfgs(vg, jnp.zeros(3), max_iters=60, tolerance=1e-9)
    np.testing.assert_allclose(res.w, [1.0, 0.2, 0.03], atol=1e-3)
    assert bool(res.converged)


def test_lbfgs_rosenbrock():
    def rosen(w):
        return jnp.sum(100.0 * (w[1:] - w[:-1] ** 2) ** 2 + (1.0 - w[:-1]) ** 2)

    res = minimize_lbfgs(jax.value_and_grad(rosen), jnp.zeros(6),
                         max_iters=300, tolerance=1e-10)
    np.testing.assert_allclose(res.w, np.ones(6), atol=1e-3)


def test_lbfgs_matches_sklearn_l2_logistic(rng):
    from sklearn.linear_model import LogisticRegression

    X, y, vg, _ = _logistic_problem(rng)
    lam = 1.0

    def vg_l2(w):
        f, g = vg(w)
        return f + 0.5 * lam * w @ w, g + lam * w

    res = minimize_lbfgs(vg_l2, jnp.zeros(X.shape[1]), max_iters=300)
    sk = LogisticRegression(C=1.0 / lam, fit_intercept=False, tol=1e-10,
                            max_iter=5000).fit(X, y)
    np.testing.assert_allclose(res.w, sk.coef_[0], atol=2e-3)


def test_tron_matches_lbfgs(rng):
    X, y, vg, hvp = _logistic_problem(rng)
    lam = 0.5

    def vg_l2(w):
        f, g = vg(w)
        return f + 0.5 * lam * w @ w, g + lam * w

    def hvp_l2(w, v):
        return hvp(w, v) + lam * v

    rl = minimize_lbfgs(vg_l2, jnp.zeros(X.shape[1]), max_iters=300)
    rt = minimize_tron(vg_l2, hvp_l2, jnp.zeros(X.shape[1]), max_iters=100)
    assert bool(rt.converged)
    np.testing.assert_allclose(rt.w, rl.w, atol=2e-3)


def test_owlqn_matches_sklearn_l1(rng):
    from sklearn.linear_model import LogisticRegression

    X, y, vg, _ = _logistic_problem(rng, n=400, d=20)
    lam = 10.0
    res = minimize_owlqn(vg, jnp.zeros(20), lam, max_iters=300)
    # Pure-L1 baseline, spelled per sklearn version: before 1.8,
    # penalty="l1" is the ONLY way to get L1 out of liblinear
    # (l1_ratio is silently ignored there and the fit is L2 — the
    # baseline objective then lands ~7 units above the true L1 optimum);
    # penalty= is deprecated in 1.8 and removed in 1.10, where
    # l1_ratio=1.0 takes over.
    import sklearn

    if tuple(int(v) for v in sklearn.__version__.split(".")[:2]) >= (1, 8):
        kw = {"l1_ratio": 1.0}
    else:
        kw = {"penalty": "l1"}
    sk = LogisticRegression(C=1.0 / lam,
                            solver="liblinear", fit_intercept=False,
                            tol=1e-9, max_iter=3000, **kw).fit(X, y)
    wsk = sk.coef_[0]

    def F(w):
        z = X @ w
        return np.sum(np.logaddexp(0, z) - y * z) + lam * np.abs(w).sum()

    # Two-sided: our objective matches the sklearn optimum (within f32 noise),
    # not merely "no worse" — guards against the baseline silently degrading.
    assert abs(float(res.value) - F(wsk)) <= 1e-2 * max(1.0, F(wsk))
    # And produce a genuinely sparse solution.
    assert int((np.asarray(res.w) != 0).sum()) < 20


def test_owlqn_zero_l1_matches_lbfgs(rng):
    X, y, vg, _ = _logistic_problem(rng, n=300, d=10)

    def vg_l2(w):
        f, g = vg(w)
        return f + 0.5 * w @ w, g + w

    r0 = minimize_owlqn(vg_l2, jnp.zeros(10), 0.0, max_iters=200)
    r1 = minimize_lbfgs(vg_l2, jnp.zeros(10), max_iters=200)
    np.testing.assert_allclose(r0.w, r1.w, atol=2e-3)


def test_vmapped_lbfgs(rng):
    """The random-effect pattern: many independent solves under one vmap."""
    A = jnp.diag(jnp.array([1.0, 5.0, 25.0], jnp.float32))
    bs = jnp.asarray(rng.normal(size=(16, 3)).astype(np.float32))

    def solve(b):
        vg = jax.value_and_grad(lambda w: 0.5 * w @ A @ w - b @ w)
        return minimize_lbfgs(vg, jnp.zeros(3), max_iters=60, tolerance=1e-8).w

    ws = jax.jit(jax.vmap(solve))(bs)
    exact = np.asarray(bs) / np.array([1.0, 5.0, 25.0])
    np.testing.assert_allclose(ws, exact, atol=2e-3)


def test_loss_history_tracking():
    A = jnp.diag(jnp.array([1.0, 10.0], jnp.float32))
    b = jnp.array([1.0, 1.0], jnp.float32)
    vg = jax.value_and_grad(lambda w: 0.5 * w @ A @ w - b @ w)
    res = minimize_lbfgs(vg, jnp.zeros(2), max_iters=50)
    h = res.history()
    assert len(h) == int(res.iterations) + 1
    assert h[-1] <= h[0]


def test_line_search_failure_reports_failed_not_converged():
    """A non-descending objective (grad lies) must end as failed, not
    converged — the reference distinguishes Breeze line-search failure
    from convergence (ADVICE r1, medium)."""
    import jax.numpy as jnp

    def lying_vg(w):
        # f increases along the claimed descent direction.
        return jnp.sum(jnp.abs(w)), jnp.ones_like(w)

    res = minimize_lbfgs(lying_vg, jnp.zeros(3), max_iters=20)
    assert bool(res.failed)
    assert not bool(res.converged)


def test_grad_norm_history_tracking():
    A = jnp.diag(jnp.array([1.0, 10.0], jnp.float32))
    b = jnp.array([1.0, 1.0], jnp.float32)
    vg = jax.value_and_grad(lambda w: 0.5 * w @ A @ w - b @ w)
    res = minimize_lbfgs(vg, jnp.zeros(2), max_iters=50)
    gh = res.grad_history()
    assert len(gh) == int(res.iterations) + 1
    assert gh[-1] < gh[0]


def test_tron_nan_region_shrinks_not_grows():
    """A trial point landing where f is NaN must shrink the trust region
    (a NaN rho compares False to every threshold and would otherwise grow
    it forever, silently stalling with failed=False)."""
    def vg(w):
        sq = jnp.sum(w * w)
        f = -jnp.log(1.0 - sq) + 10.0 * jnp.sum(w)
        g = 2.0 * w / (1.0 - sq) + 10.0
        return f, g

    def hvp(w, v):
        return jax.jvp(lambda u: vg(u)[1], (w,), (v,))[1]

    res = minimize_tron(vg, hvp, jnp.zeros(2), max_iters=60)
    # Must make real progress into the interior (true min has f < -5).
    assert np.isfinite(float(res.value)) and float(res.value) < -5.0
    assert not bool(res.failed)
