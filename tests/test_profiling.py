"""The attribution-ledger round's tier-1 coverage.

Three planes:

- `sentinel` — the noise-aware bench gate's acceptance matrix, on
  SYNTHETIC histories (pure python, no jax): a genuine regression is
  caught, normal best-of noise passes, a brand-new leg is admitted
  without tripping, a missing/short history degrades to warn-only, and
  lower-is-better legs gate in the right direction — plus the
  `bench.py --gate` CLI end to end (exit 1 on a synthetically regressed
  trajectory, exit 0 on the repo's real one: THE acceptance bars).
- `model` — static cost estimates are the arithmetic they claim:
  dot_general FLOPs from dimension numbers, scan-length multipliers,
  while-trip hints, collective payload bytes.
- `ledger` — attribution + utilization ∈ (0, 1] on a real instrumented
  streamed solve, compile accounting, detached-state no-ops, and the
  `python -m photon_tpu.profiling --report --json` CLI (the acceptance
  criterion's exact command) as a subprocess.

The umbrella selfcheck (7 subprocesses) is marked ``slow`` — tier-1
runs ``-m 'not slow'`` and each sub-CLI is already exercised on its own.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_tpu import profiling
from photon_tpu.profiling import sentinel

# Deliberately NOT release_programs-marked: this module compiles only a
# handful of tiny single-device programs (the 96×5 streamed solve shares
# shapes with test_telemetry's), and the marker's module-teardown
# jax.clear_caches() would force every LATER module to recompile —
# tens of seconds against the tier-1 870 s budget.

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------------ sentinel
def _wrap(legs, metric=None, value=None):
    parsed = {"legs": dict(legs)}
    if metric is not None:
        parsed["metric"], parsed["value"] = metric, value
    return {"n": 5, "rc": 0, "parsed": parsed}


def _history(leg="dense_rate", base=1e8, jitter=(1.0, 1.02, 0.98, 1.01, 0.99)):
    return [(f"BENCH_r{i:02d}.json", {leg: base * j})
            for i, j in enumerate(jitter, start=1)]


class TestSentinel:
    def test_regression_is_caught(self):
        hist = _history()
        v = sentinel.gate({"dense_rate": 0.5e8}, hist)["dense_rate"]
        assert v.status == "regressed" and v.z > sentinel.DEFAULT_Z

    def test_normal_noise_passes(self):
        hist = _history()
        for wobble in (0.95, 1.0, 1.05, 1.25):
            v = sentinel.gate({"dense_rate": 1e8 * wobble},
                              hist)["dense_rate"]
            assert v.status == "ok", (wobble, v.to_json())

    def test_improvement_never_trips(self):
        v = sentinel.gate({"dense_rate": 5e8}, _history())["dense_rate"]
        assert v.status == "ok"

    def test_new_leg_admitted_without_tripping(self):
        verdicts = sentinel.gate({"dense_rate": 1e8, "brand_new_leg": 1.0},
                                 _history())
        assert verdicts["brand_new_leg"].status == "new"
        assert verdicts["dense_rate"].status == "ok"

    def test_short_history_degrades_to_warn_only(self):
        short = _history(jitter=(1.0, 1.01))  # < MIN_HISTORY rounds
        v = sentinel.gate({"dense_rate": 0.1e8}, short)["dense_rate"]
        assert v.status == "new"  # admitted, never "regressed"

    def test_missing_history_degrades_to_warn_only(self):
        v = sentinel.gate({"dense_rate": 0.1e8}, [])["dense_rate"]
        assert v.status == "no-history"

    def test_lower_better_legs_gate_in_the_right_direction(self):
        hist = _history(leg="serving_p99_ms", base=2.0)
        worse = sentinel.gate({"serving_p99_ms": 9.0}, hist)
        better = sentinel.gate({"serving_p99_ms": 0.5}, hist)
        assert worse["serving_p99_ms"].status == "regressed"
        assert better["serving_p99_ms"].status == "ok"

    def test_changed_sparse_legs_admit_correctly(self):
        """The round-12 blocked-ELL swap as the sentinel sees it: a big
        IMPROVEMENT on the existing sparse throughput legs is 'ok' (the
        bad side is one-sided), the brand-new pad-waste leg admits as
        'new', and pad waste gates LOWER-better once it has history."""
        leg = "sparse10m_single_lane_rows_iters_per_sec_per_chip"
        hist = _history(leg=leg, base=1.87e7)
        verdicts = sentinel.gate(
            {leg: 5 * 1.87e7, "sparse10m_tail_pad_waste": 0.11}, hist)
        assert verdicts[leg].status == "ok"          # 5x is not a regression
        assert verdicts[leg].z < 0                   # ... and z says "better"
        assert verdicts["sparse10m_tail_pad_waste"].status == "new"
        # pad waste is a lower-better cost once history exists
        assert sentinel.lower_is_better("sparse10m_tail_pad_waste")
        whist = _history(leg="sparse10m_tail_pad_waste", base=0.1)
        worse = sentinel.gate({"sparse10m_tail_pad_waste": 0.9},
                              whist)["sparse10m_tail_pad_waste"]
        assert worse.status == "regressed"
        better = sentinel.gate({"sparse10m_tail_pad_waste": 0.01},
                               whist)["sparse10m_tail_pad_waste"]
        assert better.status == "ok"

    def test_multihost_legs_admit_correctly(self):
        """The round-17 spine legs as the sentinel sees them: the priced
        DCN wire bill gates LOWER-better (a grown psum payload means
        something besides the gradient started riding DCN), the launch
        wall gates lower-better via "_ms", and the verified process
        count is a topology fact the sentinel must never gate."""
        assert sentinel.lower_is_better("multihost_e2e_dcn_bytes_per_eval")
        assert sentinel.lower_is_better("multihost_e2e_launch_4p_wall_ms")
        legs = sentinel.leg_values({"legs": {
            "multihost_e2e_dcn_bytes_per_eval": 196.0,
            "multihost_e2e_launch_4p_wall_ms": 9000.0,
            "multihost_e2e_n_processes": 4,
        }})
        assert "multihost_e2e_n_processes" not in legs
        assert legs["multihost_e2e_dcn_bytes_per_eval"] == 196.0
        hist = _history(leg="multihost_e2e_dcn_bytes_per_eval", base=196.0)
        worse = sentinel.gate(
            {"multihost_e2e_dcn_bytes_per_eval": 24576.0},
            hist)["multihost_e2e_dcn_bytes_per_eval"]
        assert worse.status == "regressed"
        same = sentinel.gate(
            {"multihost_e2e_dcn_bytes_per_eval": 196.0},
            hist)["multihost_e2e_dcn_bytes_per_eval"]
        assert same.status == "ok"

    def test_serving_kernel_legs_admit_correctly(self):
        """The round-20 serving_quantized_kernels legs as the sentinel
        sees them: both admit as 'new' beside existing serving history
        (the same-fingerprint rule still applies — `_history` pairs are
        env-None series), QPS gates higher-better, and the p99 gates
        LOWER-better via "_ms" once it has history — the fused kernel's
        whole claim is the tail."""
        hist = _history(leg="serving_quantized_p99_ms", base=2.0)
        verdicts = sentinel.gate(
            {"serving_quantized_p99_ms": 2.0,
             "serving_quantized_kernels_qps": 900.0,
             "serving_quantized_kernels_p99_ms": 1.4}, hist)
        assert verdicts["serving_quantized_kernels_qps"].status == "new"
        assert verdicts["serving_quantized_kernels_p99_ms"].status == "new"
        assert sentinel.lower_is_better("serving_quantized_kernels_p99_ms")
        assert not sentinel.lower_is_better("serving_quantized_kernels_qps")
        khist = _history(leg="serving_quantized_kernels_p99_ms", base=1.4)
        worse = sentinel.gate(
            {"serving_quantized_kernels_p99_ms": 6.0},
            khist)["serving_quantized_kernels_p99_ms"]
        assert worse.status == "regressed"
        better = sentinel.gate(
            {"serving_quantized_kernels_p99_ms": 0.7},
            khist)["serving_quantized_kernels_p99_ms"]
        assert better.status == "ok"

    def test_layout_split_legs_are_excluded(self):
        """hot/tail split + width-bucket counts are layout CONFIG facts —
        a retuned d_dense moves them by design, so they never gate."""
        verdicts = sentinel.gate(
            {"sparse10m_hot_nnz_frac": 0.7, "sparse10m_tail_nnz_frac": 0.3,
             "sparse10m_ell_width_buckets": 3, "dense_rate": 1e8},
            _history())
        assert "sparse10m_hot_nnz_frac" not in verdicts
        assert "sparse10m_tail_nnz_frac" not in verdicts
        assert "sparse10m_ell_width_buckets" not in verdicts

    def test_config_legs_are_not_gated(self):
        hist = _history(leg="streamed_mesh_n_chips", base=8.0)
        verdicts = sentinel.gate({"streamed_mesh_n_chips": 4.0}, hist)
        assert "streamed_mesh_n_chips" not in verdicts

    def test_ingest_leg_admission(self):
        """The round-14 ingest_throughput legs as the sentinel sees them:
        brand-new legs admit without tripping the gate that merges them;
        the throughput legs + the cached/cold ratio gate higher-better,
        the upload-stall share and the stalled-pass count LOWER-better
        (more stalling at the same workload = the plane got slower);
        once history exists a cached-rate collapse regresses."""
        verdicts = sentinel.gate(
            {"ingest_throughput_cold_rows_per_sec": 3.0e4,
             "ingest_throughput_cached_rows_per_sec": 9.0e5,
             "ingest_throughput_cached_over_cold": 30.0,
             "ingest_throughput_upload_stall_pct": 0.8,
             "ingest_stalled_passes": 0.0,
             "dense_rate": 1e8},
            _history())
        for leg in ("ingest_throughput_cold_rows_per_sec",
                    "ingest_throughput_cached_rows_per_sec",
                    "ingest_throughput_cached_over_cold",
                    "ingest_throughput_upload_stall_pct",
                    "ingest_stalled_passes"):
            assert verdicts[leg].status == "new", leg
        assert verdicts["dense_rate"].status == "ok"
        # directions
        assert not sentinel.lower_is_better(
            "ingest_throughput_cached_rows_per_sec")
        assert not sentinel.lower_is_better(
            "ingest_throughput_cached_over_cold")
        assert sentinel.lower_is_better(
            "ingest_throughput_upload_stall_pct")
        assert sentinel.lower_is_better("ingest_stalled_passes")
        # with history: a cached-rate collapse regresses, a stall-share
        # rise regresses, improvements never trip
        hist = _history(leg="ingest_throughput_cached_rows_per_sec",
                        base=9.0e5)
        worse = sentinel.gate(
            {"ingest_throughput_cached_rows_per_sec": 1.0e5}, hist)
        assert worse["ingest_throughput_cached_rows_per_sec"].status == \
            "regressed"
        shist = _history(leg="ingest_throughput_upload_stall_pct", base=1.0)
        worse = sentinel.gate(
            {"ingest_throughput_upload_stall_pct": 60.0}, shist)
        assert worse["ingest_throughput_upload_stall_pct"].status == \
            "regressed"
        better = sentinel.gate(
            {"ingest_throughput_upload_stall_pct": 0.01}, shist)
        assert better["ingest_throughput_upload_stall_pct"].status == "ok"

    def test_kernel_leg_admission(self):
        """The round-15 kernel-variant leg as the sentinel sees it: a
        brand-new leg admits without tripping the gate that merges it,
        the backend string never becomes a leg, and with history the
        rate gates higher-better like any throughput leg."""
        verdicts = sentinel.gate(
            {"blocked_ell_kernel_rows_iters_per_sec_per_chip": 1.0e7,
             "dense_rate": 1e8},
            _history())
        assert verdicts[
            "blocked_ell_kernel_rows_iters_per_sec_per_chip"].status == \
            "new"
        assert verdicts["dense_rate"].status == "ok"
        legs = sentinel.leg_values(
            {"legs": {"blocked_ell_kernel_backend": "cpu-interpret",
                      "blocked_ell_kernel_rows_iters_per_sec_per_chip":
                          1.0e7}})
        assert "blocked_ell_kernel_backend" not in legs
        assert "blocked_ell_kernel_rows_iters_per_sec_per_chip" in legs
        hist = _history(
            leg="blocked_ell_kernel_rows_iters_per_sec_per_chip",
            base=1.0e7)
        worse = sentinel.gate(
            {"blocked_ell_kernel_rows_iters_per_sec_per_chip": 1.0e6},
            hist)
        assert worse[
            "blocked_ell_kernel_rows_iters_per_sec_per_chip"].status == \
            "regressed"

    def test_serving_quantized_leg_admission(self):
        """The round-15 quantized-rung legs as the sentinel sees them:
        new legs admit, QPS gates higher-better, p99 and the measured
        probe margin maxdiff LOWER-better — a louder quantization at
        the same throughput is a regression."""
        verdicts = sentinel.gate(
            {"serving_quantized_qps": 2.1e4,
             "serving_quantized_p99_ms": 4.5,
             "serving_quantized_margin_maxdiff": 0.02,
             "dense_rate": 1e8},
            _history())
        for leg in ("serving_quantized_qps", "serving_quantized_p99_ms",
                    "serving_quantized_margin_maxdiff"):
            assert verdicts[leg].status == "new", leg
        assert not sentinel.lower_is_better("serving_quantized_qps")
        assert sentinel.lower_is_better("serving_quantized_p99_ms")
        assert sentinel.lower_is_better("serving_quantized_margin_maxdiff")
        hist = _history(leg="serving_quantized_margin_maxdiff", base=0.02)
        worse = sentinel.gate(
            {"serving_quantized_margin_maxdiff": 0.5}, hist)
        assert worse["serving_quantized_margin_maxdiff"].status == \
            "regressed"
        better = sentinel.gate(
            {"serving_quantized_margin_maxdiff": 0.001}, hist)
        assert better["serving_quantized_margin_maxdiff"].status == "ok"

    def test_game_e2e_leg_admission(self):
        """The round-13 game_e2e legs as the sentinel sees them: the new
        throughput legs admit as 'new' without tripping the gate that
        merges them, the chip count is a config leg (never gated), the
        beyond-resident bool is skipped by leg_values, and once history
        exists the aggregate gates like any throughput leg."""
        verdicts = sentinel.gate(
            {"game_e2e_rows_iters_per_sec_aggregate": 2.7e5,
             "game_e2e_resident_rows_iters_per_sec": 4.6e5,
             "game_e2e_streamed_over_resident": 0.6,
             "game_e2e_n_chips": 8.0,
             "dense_rate": 1e8},
            _history())
        assert verdicts[
            "game_e2e_rows_iters_per_sec_aggregate"].status == "new"
        assert verdicts[
            "game_e2e_resident_rows_iters_per_sec"].status == "new"
        assert verdicts["game_e2e_streamed_over_resident"].status == "new"
        assert "game_e2e_n_chips" not in verdicts
        assert verdicts["dense_rate"].status == "ok"
        # bools never become legs (beyond_resident_ok is an existence
        # proof, not a performance quantity)
        legs = sentinel.leg_values(
            {"legs": {"game_e2e_beyond_resident_ok": True,
                      "game_e2e_rows_iters_per_sec_aggregate": 2.7e5}})
        assert "game_e2e_beyond_resident_ok" not in legs
        assert "game_e2e_rows_iters_per_sec_aggregate" in legs
        # with history, the aggregate gates higher-better
        hist = _history(leg="game_e2e_rows_iters_per_sec_aggregate",
                        base=2.7e5)
        worse = sentinel.gate(
            {"game_e2e_rows_iters_per_sec_aggregate": 0.5e5}, hist)
        assert worse[
            "game_e2e_rows_iters_per_sec_aggregate"].status == "regressed"

    def test_refresh_e2e_leg_admission(self):
        """The round-14 continual legs as the sentinel sees them: the new
        speedup/wall legs admit as 'new' without tripping the gate that
        merges them, the touched fraction is a config fact (never
        gated), the wall legs gate LOWER-better once history exists, and
        the speedup gates higher-better."""
        verdicts = sentinel.gate(
            {"refresh_e2e_speedup_vs_full_retrain": 120.0,
             "refresh_e2e_wall_ms": 850.0,
             "refresh_e2e_full_retrain_wall_ms": 95000.0,
             "refresh_e2e_touched_frac": 0.02,
             "dense_rate": 1e8},
            _history())
        assert verdicts[
            "refresh_e2e_speedup_vs_full_retrain"].status == "new"
        assert verdicts["refresh_e2e_wall_ms"].status == "new"
        assert verdicts["refresh_e2e_full_retrain_wall_ms"].status == "new"
        assert "refresh_e2e_touched_frac" not in verdicts
        assert verdicts["dense_rate"].status == "ok"
        # the refresh wall is a latency-like cost: lower is better
        assert sentinel.lower_is_better("refresh_e2e_wall_ms")
        whist = _history(leg="refresh_e2e_wall_ms", base=800.0)
        worse = sentinel.gate({"refresh_e2e_wall_ms": 9000.0},
                              whist)["refresh_e2e_wall_ms"]
        better = sentinel.gate({"refresh_e2e_wall_ms": 200.0},
                               whist)["refresh_e2e_wall_ms"]
        assert worse.status == "regressed" and better.status == "ok"
        # the speedup is a rate: a collapse toward 1x regresses
        shist = _history(leg="refresh_e2e_speedup_vs_full_retrain",
                         base=120.0)
        collapsed = sentinel.gate(
            {"refresh_e2e_speedup_vs_full_retrain": 2.0},
            shist)["refresh_e2e_speedup_vs_full_retrain"]
        assert collapsed.status == "regressed"

    def test_serving_slo_leg_admission(self):
        """The overload-round serving_slo legs as the sentinel sees them:
        new legs admit without tripping the gate that merges them; the
        direction map gates sustained QPS higher-better, p99 and shed
        percentage LOWER-better (more shedding at the same offered rate
        means the tier got slower); the SLO target is a chosen config
        bar (excluded) and the bool verdict is skipped by type."""
        verdicts = sentinel.gate(
            {"serving_slo_sustained_qps": 6500.0,
             "serving_slo_p99_ms": 9.0,
             "serving_slo_overload_p99_ms": 130.0,
             "serving_slo_overload_shed_pct": 55.0,
             "serving_slo_target_ms": 50.0,
             "dense_rate": 1e8},
            _history())
        for leg in ("serving_slo_sustained_qps", "serving_slo_p99_ms",
                    "serving_slo_overload_p99_ms",
                    "serving_slo_overload_shed_pct"):
            assert verdicts[leg].status == "new", leg
        assert "serving_slo_target_ms" not in verdicts  # config bar
        assert verdicts["dense_rate"].status == "ok"
        legs = sentinel.leg_values(
            {"legs": {"serving_slo_ok": True,
                      "serving_slo_sustained_qps": 6500.0}})
        assert "serving_slo_ok" not in legs  # bool verdict, not a leg
        # directions
        assert not sentinel.lower_is_better("serving_slo_sustained_qps")
        assert sentinel.lower_is_better("serving_slo_p99_ms")
        assert sentinel.lower_is_better("serving_slo_overload_shed_pct")
        # a sustained-QPS collapse regresses; shedding MORE at the same
        # offered rate regresses; shedding less is an improvement
        qhist = _history(leg="serving_slo_sustained_qps", base=6500.0)
        assert sentinel.gate({"serving_slo_sustained_qps": 800.0}, qhist)[
            "serving_slo_sustained_qps"].status == "regressed"
        shist = _history(leg="serving_slo_overload_shed_pct", base=40.0)
        assert sentinel.gate({"serving_slo_overload_shed_pct": 90.0},
                             shist)["serving_slo_overload_shed_pct"
                                    ].status == "regressed"
        assert sentinel.gate({"serving_slo_overload_shed_pct": 5.0},
                             shist)["serving_slo_overload_shed_pct"
                                    ].status == "ok"

    def test_observability_leg_admission(self):
        """The round-19 observability legs as the sentinel sees them:
        the staleness gauge (rows-changed -> servable seconds) and the
        slowest-exemplar latency admit as 'new' and gate LOWER-better
        (staler models and fatter tails are the regressions these legs
        exist to catch); the nested exemplar list riding the serving_slo
        sub-dict is structure, not a leg."""
        verdicts = sentinel.gate(
            {"refresh_e2e_staleness_s": 4.2,
             "serving_slo_exemplar_slowest_ms": 31.0,
             "dense_rate": 1e8},
            _history())
        assert verdicts["refresh_e2e_staleness_s"].status == "new"
        assert verdicts["serving_slo_exemplar_slowest_ms"].status == "new"
        assert verdicts["dense_rate"].status == "ok"
        # directions: both are freshness/latency costs
        assert sentinel.lower_is_better("refresh_e2e_staleness_s")
        assert sentinel.lower_is_better("serving_slo_exemplar_slowest_ms")
        # a model going stale regresses; getting fresher is ok
        shist = _history(leg="refresh_e2e_staleness_s", base=4.0)
        assert sentinel.gate({"refresh_e2e_staleness_s": 300.0}, shist)[
            "refresh_e2e_staleness_s"].status == "regressed"
        assert sentinel.gate({"refresh_e2e_staleness_s": 1.0}, shist)[
            "refresh_e2e_staleness_s"].status == "ok"
        # exemplar dicts, the health snapshot, and verdict strings are
        # invisible to leg_values — only scalar legs gate
        legs = sentinel.leg_values(
            {"legs": {"refresh_e2e_staleness_s": 4.2,
                      "serving_slo": {"exemplars": [
                          {"total_ms": 31.0, "slowest_hop": "queue_wait"}]},
                      "health": {"verdict": "OK"}}})
        assert legs == {"refresh_e2e_staleness_s": 4.2}

    def test_tuning_e2e_leg_admission(self):
        """The round-16 lane-tuner legs as the sentinel sees them: the
        configs-per-second rates and the speedup admit as 'new' and gate
        higher-better (a collapse toward point-at-a-time parity is the
        regression the leg exists to catch); the config count is a
        chosen budget, never gated."""
        verdicts = sentinel.gate(
            {"tuning_e2e_configs_per_sec": 62.0,
             "tuning_e2e_sequential_configs_per_sec": 5.9,
             "tuning_e2e_speedup_vs_sequential": 10.7,
             "tuning_e2e_n_configs": 256.0,
             "dense_rate": 1e8},
            _history())
        for leg in ("tuning_e2e_configs_per_sec",
                    "tuning_e2e_sequential_configs_per_sec",
                    "tuning_e2e_speedup_vs_sequential"):
            assert verdicts[leg].status == "new", leg
            assert not sentinel.lower_is_better(leg)
        assert "tuning_e2e_n_configs" not in verdicts  # config budget
        assert verdicts["dense_rate"].status == "ok"
        shist = _history(leg="tuning_e2e_speedup_vs_sequential", base=10.7)
        assert sentinel.gate({"tuning_e2e_speedup_vs_sequential": 1.1},
                             shist)["tuning_e2e_speedup_vs_sequential"
                                    ].status == "regressed"
        rhist = _history(leg="tuning_e2e_configs_per_sec", base=62.0)
        assert sentinel.gate({"tuning_e2e_configs_per_sec": 90.0},
                             rhist)["tuning_e2e_configs_per_sec"
                                    ].status == "ok"

    def test_leg_values_flattens_headline_and_skips_dups(self):
        legs = sentinel.leg_values({
            "metric": "headline", "value": 2.0,
            "legs": {"a": 1.0, "a_vs_baseline": 0.1, "b": True}})
        assert legs == {"headline": 2.0, "a": 1.0}

    def test_history_loader_tolerates_null_and_garbage(self, tmp_path):
        (tmp_path / "BENCH_r01.json").write_text('{"parsed": null}')
        (tmp_path / "BENCH_r02.json").write_text("not json")
        (tmp_path / "BENCH_r03.json").write_text(
            json.dumps(_wrap({"a": 1.0})))
        hist = sentinel.load_history(str(tmp_path))
        assert hist == [("BENCH_r03.json", {"a": 1.0}, None)]

    def test_same_env_slices_single_environment_series(self):
        """A leg's history series is single-environment: ``same_env``
        keeps only rounds whose host fingerprint matches the candidate's
        (the r06 TPU→CPU exclusion policy, automated at the r10
        container-host swap). Legacy pairs/rounds with no fingerprint
        form their own env-``None`` series."""
        hist = [("r1", {"rate": 1.00e8}, "hostA"),
                ("r2", {"rate": 1.01e8}, "hostA"),
                ("r3", {"rate": 0.99e8}, "hostA"),
                ("r4", {"rate": 1.02e8}, None)]
        assert [h[0] for h in sentinel.same_env(hist, "hostA")] == \
            ["r1", "r2", "r3"]
        assert sentinel.same_env(hist, None) == [hist[3]]
        assert sentinel.same_env(hist, "hostB") == []
        # bare (name, legs) pairs (the test/legacy shape) are env None
        assert sentinel.same_env(_history(), None) == _history()
        # a collapse judged against a DIFFERENT host's rounds is
        # warn-only, not a regression — nothing is comparable
        v = sentinel.gate({"rate": 0.3e8},
                          sentinel.same_env(hist, "hostB"))
        assert v["rate"].status == "no-history"

    def test_host_env_fingerprint_shape(self):
        env = sentinel.host_env()
        assert isinstance(env, str) and "/nproc=" in env
        assert env == sentinel.host_env()  # deterministic on one host

    def test_gate_main_env_break_restarts_gating(self, tmp_path, capsys):
        """End-to-end host break: a collapsed round on a SWAPPED host
        fingerprint admits warn-only (new series), and the same collapse
        three rounds INTO the new series trips the gate again."""
        self._write_rounds(tmp_path, [1e8, 1.01e8, 0.99e8, 1.02e8])

        def _env_round(i, v):
            d = _wrap({"rate": v})
            d["parsed"]["env"] = "other-cpu/nproc=1"
            (tmp_path / f"BENCH_r{i:02d}.json").write_text(json.dumps(d))

        _env_round(5, 0.4e8)
        rc = sentinel.gate_main(["--gate"], bench_dir=str(tmp_path))
        out = capsys.readouterr().out
        assert rc == 0
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["ok"] and doc["env"] == "other-cpu/nproc=1"
        assert doc["n_history_rounds"] == 0  # the old host's rounds
        # rebuild MIN_HISTORY strength on the new host, then collapse
        for i, v in enumerate((1e8, 1.01e8, 0.99e8), start=5):
            _env_round(i, v)
        _env_round(8, 0.4e8)
        rc = sentinel.gate_main(["--gate"], bench_dir=str(tmp_path))
        out = capsys.readouterr().out
        assert rc == 1 and "rate: regressed" in out
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["n_history_rounds"] == 3  # the old host still sliced

    def _write_rounds(self, tmp_path, values, leg="rate"):
        for i, v in enumerate(values, start=1):
            (tmp_path / f"BENCH_r{i:02d}.json").write_text(
                json.dumps(_wrap({leg: v})))

    def test_gate_main_exit_codes(self, tmp_path, capsys):
        # regressed trajectory: last round collapses -> exit 1, with a
        # one-line verdict per leg in the output
        self._write_rounds(tmp_path, [1e8, 1.01e8, 0.99e8, 1.02e8, 0.4e8])
        rc = sentinel.gate_main(["--gate"], bench_dir=str(tmp_path))
        out = capsys.readouterr().out
        assert rc == 1 and "rate: regressed" in out
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["regressed"] == ["rate"] and not doc["ok"]
        # healthy trajectory -> exit 0
        self._write_rounds(tmp_path, [1e8, 1.01e8, 0.99e8, 1.02e8, 1.05e8])
        assert sentinel.gate_main(["--gate"],
                                  bench_dir=str(tmp_path)) == 0

    def test_gate_real_trajectory_passes(self, capsys):
        """The gate over the repo's own BENCH_r0*.json history exits 0
        (the acceptance bar) — in-process; the bench.py CLI wiring is
        covered once by the synthetic-regression subprocess below."""
        rc = sentinel.gate_main(["--gate"], bench_dir=_REPO)
        out = capsys.readouterr().out
        assert rc == 0, out
        doc = json.loads(out.strip().splitlines()[-1])
        assert doc["ok"] and doc["schema"] == sentinel.SCHEMA_VERSION

    def test_bench_gate_cli_synthetic_regression(self, tmp_path):
        """bench.py --gate --gate-dir <regressed trajectory>: exit 1."""
        self._write_rounds(tmp_path, [1e8, 1.0e8, 1.01e8, 0.99e8, 0.3e8])
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, os.path.join(_REPO, "bench.py"), "--gate",
             "--gate-dir", str(tmp_path)],
            capture_output=True, text=True, env=env, timeout=120)
        assert proc.returncode == 1, proc.stdout + proc.stderr


# ------------------------------------------------------------------- model
class TestStaticModel:
    def test_dot_general_flops(self):
        import jax
        import jax.numpy as jnp

        x = jnp.zeros((32, 8), jnp.float32)
        w = jnp.zeros((8, 4), jnp.float32)
        cost = profiling.estimate_fn(lambda a, b: a @ b, (x, w))
        assert cost.dot_flops == 2 * 32 * 8 * 4
        # operand-traffic proxy: inputs + outputs of the matmul
        assert cost.bytes >= (32 * 8 + 8 * 4 + 32 * 4) * 4
        del jax

    def test_elementwise_and_transcendental(self):
        import jax.numpy as jnp

        x = jnp.zeros((64,), jnp.float32)
        cost = profiling.estimate_fn(lambda a: jnp.tanh(a * 2.0), (x,))
        assert cost.transcendentals == 64
        assert cost.flops >= 128  # mul + tanh

    def test_scan_length_multiplies(self):
        import jax
        import jax.numpy as jnp

        def f(xs):
            return jax.lax.scan(lambda c, x: (c + x, x * 2.0),
                                jnp.zeros((16,)), xs)

        cost = profiling.estimate_fn(f, (jnp.zeros((5, 16)),))
        # 5 trips x (add 16 + mul 16) = 160 elementwise FLOPs
        assert cost.flops == 5 * 32

    def test_while_trip_hint(self):
        import jax
        import jax.numpy as jnp

        def f(x):
            return jax.lax.while_loop(lambda c: c[1] < 3,
                                      lambda c: (c[0] * 2.0, c[1] + 1),
                                      (x, 0))

        x = jnp.zeros((16,), jnp.float32)
        c1 = profiling.estimate_fn(f, (x,), while_trips=1)
        c10 = profiling.estimate_fn(f, (x,), while_trips=10)
        assert c1.while_loops == 1 and c1.lower_bound
        assert not c10.lower_bound
        assert c10.flops > c1.flops  # body cost scales with the hint

    def test_gather_costed_per_slice_not_per_table(self):
        """Round 12: a w-gather over a big table charges per-index granule
        traffic (the honest sparse cost), NOT the whole table's bytes."""
        import jax.numpy as jnp

        from photon_tpu.profiling.model import GATHER_GRANULE_BYTES

        d, m = 100_000, 64
        table = jnp.zeros((d,), jnp.float32)
        idx = jnp.zeros((m,), jnp.int32)
        cost = profiling.estimate_fn(lambda t, i: t[i], (table, idx))
        table_bytes = d * 4
        # scalar slices: m granules on the random side
        assert cost.gather_bytes == m * GATHER_GRANULE_BYTES
        assert cost.bytes < table_bytes  # the table is NOT charged
        # index + output move too
        assert cost.bytes >= cost.gather_bytes + m * 4

    def test_wide_gather_slices_charge_slice_bytes(self):
        import jax.numpy as jnp

        d, g, m = 1000, 64, 16  # 256-byte slices > the 32 B granule
        table = jnp.zeros((d, g), jnp.float32)
        idx = jnp.zeros((m,), jnp.int32)
        cost = profiling.estimate_fn(lambda t, i: t[i], (table, idx))
        assert cost.gather_bytes == m * g * 4

    def test_collective_payload_bytes(self):
        import jax

        fn = lambda x: jax.lax.psum(x, "i")  # noqa: E731
        closed = jax.make_jaxpr(fn, axis_env=[("i", 4)])(
            np.zeros((128,), np.float32))
        cost = profiling.estimate_jaxpr(closed)
        assert cost.collective_bytes == 128 * 4


# ------------------------------------------------------------------- ledger
class TestLedger:
    def test_detached_is_noop(self):
        assert profiling.current_ledger() is None
        assert not profiling.enabled()
        assert not profiling.needs_note("anything")
        with profiling.measure("p", "ph") as m:
            assert m is None
        profiling.attribute("p", "ph", 1.0)  # no-op, no error
        profiling.record_signature("p", (1.0,))

    def test_attribution_and_utilization(self):
        import jax.numpy as jnp

        with profiling.ledger("t", peaks=(1e9, 1e9)) as led:
            x = jnp.zeros((64, 64), jnp.float32)
            led.note_program("mm", lambda a: a @ a, (x,))
            led.attribute("mm", "phase", 0.01, calls=10)
            rep = led.report()
        (entry,) = rep["attribution"]
        assert entry["program"] == "mm" and entry["calls"] == 10
        assert entry["flops_modeled"] == 10 * 2 * 64 ** 3
        assert 0.0 < entry["utilization"] <= 1.0
        assert entry["bound"] in ("compute", "bandwidth")
        # the note's trace enters both compile accounts
        assert rep["programs"]["mm"]["retraces"] == 1
        assert rep["compile"]["wall_s"] > 0.0

    def test_utilization_clamped_into_unit_interval(self):
        import jax.numpy as jnp

        with profiling.ledger("t", peaks=(1.0, 1.0)) as led:  # absurd peaks
            x = jnp.zeros((8, 8), jnp.float32)
            led.note_program("mm", lambda a: a @ a, (x,))
            led.attribute("mm", "phase", 1e-6)
            entry = led.report()["attribution"][0]
        assert entry["utilization"] == 1.0

    def test_dispatch_books_compile_on_new_signature_only(self):
        import jax.numpy as jnp

        with profiling.ledger("t") as led:
            x = jnp.zeros((4,), jnp.float32)
            with led.dispatch("prog", (x,)):
                pass
            with led.dispatch("prog", (x,)):  # same signature: no retrace
                pass
            with led.dispatch("prog", (jnp.zeros((8,), jnp.float32),)):
                pass
            rep = led.report()
        prog = rep["programs"]["prog"]
        assert prog["retraces"] == 2
        entry = rep["attribution"][0]
        assert entry["phase"] == "dispatch" and entry["calls"] == 3

    def test_note_error_is_contained(self):
        with profiling.ledger("t") as led:
            led.note_program("bad", lambda: 1 / 0, ())
            rep = led.report()
        assert "ZeroDivisionError" in rep["programs"]["bad"]["note_error"]

    def test_instrumented_streamed_solve(self):
        """The tentpole wiring end to end IN-PROCESS: a streamed-dense
        train_glm under an attached ledger yields per-program entries
        with static estimates, measured durations, and utilization in
        (0, 1] — and zero ledger entries when detached."""
        from photon_tpu.data.dataset import chunk_batch, make_batch
        from photon_tpu.models.training import train_glm
        from photon_tpu.ops.losses import TaskType
        from photon_tpu.optim.config import OptimizerConfig
        from photon_tpu.optim.regularization import l2

        rng = np.random.default_rng(0)
        X = rng.normal(size=(96, 5)).astype(np.float32)
        y = (rng.uniform(size=96) < 0.5).astype(np.float32)
        cb = chunk_batch(make_batch(X, y), 32)
        cfg = OptimizerConfig(max_iters=4, tolerance=1e-7, reg=l2(),
                              reg_weight=0.1, history=3)
        with profiling.ledger("solve") as led:
            train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)
            rep = led.report()
        entries = [e for e in rep["attribution"]
                   if e["program"].startswith("streamed.")]
        assert len(entries) >= 2  # init + direction at minimum
        for e in entries:
            assert e["seconds"] > 0.0
            assert e["flops_modeled"] > 0.0 and e["bytes_modeled"] > 0.0
            assert 0.0 < e["utilization"] <= 1.0
        assert rep["compile"]["retraces"] >= 1

    def test_report_cli_json(self):
        """`python -m photon_tpu.profiling --report --json` — THE
        acceptance command — on a small streamed-dense run: every
        streamed attribution entry carries static FLOP/byte estimates,
        a measured duration, and a utilization fraction in (0, 1]."""
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # the CLI self-provisions its platform
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-m", "photon_tpu.profiling", "--report",
             "--json", "--rows", "2048", "--chunk-rows", "512"],
            capture_output=True, text=True, env=env, cwd=_REPO,
            timeout=300)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
        entries = [e for e in doc["ledger"]["attribution"]
                   if e["program"].startswith("streamed.")]
        assert entries, doc["ledger"]["attribution"]
        for e in entries:
            assert e["seconds"] > 0.0
            assert e["flops_modeled"] > 0.0 and e["bytes_modeled"] > 0.0
            assert 0.0 < e["utilization"] <= 1.0
        assert doc["ledger"]["compile"]["retraces"] >= 1
        # the gate verdicts ride along (the repo has a BENCH history)
        assert doc["gate"]


@pytest.mark.slow
def test_umbrella_selfcheck_cli():
    """`python -m photon_tpu --selfcheck --json`: every per-package
    selftest — including the pod-scale GAME e2e smoke (tiny rows,
    mesh 2) and the continual-flywheel loop — aggregates into one
    verdict."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_tpu", "--selfcheck", "--json"],
        capture_output=True, text=True, env=env, timeout=1800)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout.strip().splitlines()[-1])
    assert doc["ok"]
    from photon_tpu.__main__ import SUITES

    assert set(doc["suites"]) == {name for name, _ in SUITES}
    assert set(doc["suites"]) >= {"analysis", "lint", "telemetry",
                                  "serving", "checkpoint", "profiling",
                                  "game", "continual", "ingest",
                                  "kernels"}
    assert doc["suites"]["game"]["ok"]
    assert doc["suites"]["continual"]["ok"]
    assert doc["suites"]["lint"]["ok"]
