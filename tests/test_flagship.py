"""Flagship compose, small scale: the 10M-row product-path run
(benches/flagship_e2e.py) in miniature — block-encoded Avro on disk →
run_training with auto-tripped streaming → validation AUC — so the full
composition is pinned in CI before the at-scale bench pays for it.
"""
import importlib.util
import sys
from pathlib import Path

import numpy as np
import pytest

BENCHES = Path(__file__).resolve().parent.parent / "benches"
spec = importlib.util.spec_from_file_location("_flagship_data",
                                              BENCHES / "_flagship_data.py")
_fd = importlib.util.module_from_spec(spec)
sys.modules.setdefault("_flagship_data", _fd)
spec.loader.exec_module(_fd)

from photon_tpu.data.avro_io import read_avro  # noqa: E402
from photon_tpu.drivers.train import TrainingParams, run_training  # noqa: E402


def test_block_encoder_matches_generic_reader(tmp_path):
    """The fixed-width template encoder must produce byte-valid Avro: the
    generic per-record reader decodes it back to the planted records."""
    truth = _fd.planted_truth(50, 30, seed=1)
    path = tmp_path / "flag.avro"
    _fd.write_flagship_avro(path, 300, 50, 30, truth, seed=2,
                            rows_per_block=128)
    recs = read_avro(str(path))
    assert len(recs) == 300
    r = recs[0]
    assert set(r) == {"response", "userId", "itemId", "fixed", "u_re",
                      "i_re"}
    assert r["response"] in (0.0, 1.0)
    assert r["userId"].startswith("u") and len(r["userId"]) == 7
    assert r["itemId"].startswith("i") and len(r["itemId"]) == 6
    assert [e["name"] for e in r["fixed"]] == [f"f{j:02d}"
                                               for j in range(32)]
    assert [e["name"] for e in r["u_re"]] == ["r0", "r1", "r2", "r3"]
    assert all(np.isfinite(e["value"]) for e in r["fixed"])
    # deterministic: same seed reproduces the same bytes
    path2 = tmp_path / "flag2.avro"
    _fd.write_flagship_avro(path2, 300, 50, 30, truth, seed=2,
                            rows_per_block=128)
    recs2 = read_avro(str(path2))
    assert recs2[5]["fixed"][3]["value"] == recs[5]["fixed"][3]["value"]


def test_flagship_driver_small_scale(tmp_path):
    """The composed product path at test size: streaming auto-trips from
    header row counts, both random effects train, and validation AUC
    clearly beats the planted noise floor."""
    users, items = 40, 25
    truth = _fd.planted_truth(users, items, seed=3)
    _fd.write_flagship_avro(tmp_path / "train.avro", 2000, users, items,
                            truth, seed=4, rows_per_block=256)
    _fd.write_flagship_avro(tmp_path / "val.avro", 800, users, items,
                            truth, seed=5, rows_per_block=256)
    seen_streaming = {}
    from photon_tpu.data import streaming as streaming_mod

    orig = streaming_mod.iter_game_chunks

    def spy(*a, **kw):
        seen_streaming["hit"] = True
        return orig(*a, **kw)

    streaming_mod.iter_game_chunks = spy
    try:
        out = run_training(TrainingParams(
            train_path=str(tmp_path / "train.avro"),
            validation_path=str(tmp_path / "val.avro"),
            output_dir=str(tmp_path / "out"),
            feature_shards=_fd.FEATURE_SHARDS,
            coordinates=_fd.COORDINATES,
            entity_fields=["userId", "itemId"],
            n_sweeps=2,
            streaming=None,                 # tri-state AUTO
            streaming_threshold_rows=1000,  # 2000 rows > 1000 → trips
            evaluators=["AUC"],
        ))
    finally:
        streaming_mod.iter_game_chunks = orig
    assert seen_streaming.get("hit"), "auto threshold did not trip streaming"
    assert out.best.validation_score is not None
    assert out.best.validation_score > 0.75, out.best.validation_score
    assert {"read", "train"} <= set(out.timings)
