"""The jaxpr contract checker itself (photon_tpu/analysis): walker
recursion through every higher-order primitive, and one known-VIOLATION
fixture per rule — each of the five rules must provably fire on a program
that breaks its contract, or the zero-violation registry check means
nothing.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from photon_tpu.analysis import (
    ContractSpec,
    TraceSignatureLog,
    check_contract,
    collective_counts,
    const_bytes,
    count_primitives,
    sites,
    trace_signature,
    weak_type_drift,
)
from photon_tpu.parallel.mesh import make_mesh, shard_map

# Trace-heavy, not compile-heavy — but a handful of fixtures do build
# shard_map programs; keep the suite's executable envelope tidy anyway.
pytestmark = pytest.mark.release_programs


def _violations(build, rule=None, **spec_kw):
    spec = ContractSpec(name="fixture", build=build, **spec_kw)
    out = check_contract(spec)
    if rule is None:
        return out
    return [v for v in out if v.rule == rule]


# ------------------------------------------------------------------ walker
class TestWalker:
    def test_nested_scan_in_while_in_pjit(self):
        """The canonical solver nesting: jit(while(scan(...))) — the
        walker finds primitives at every level and reports loop depth."""

        def scan_body(c, _):
            return c * 2.0, jnp.sin(c)

        def while_body(c):
            c2, s = lax.scan(scan_body, c, None, length=3)
            return c2 + jnp.sum(s) + jnp.cos(c2)

        @jax.jit
        def f(x):
            return lax.while_loop(lambda c: jnp.sum(c) < 10.0, while_body,
                                  jnp.tanh(x))

        jaxpr = jax.make_jaxpr(f)(jnp.ones(3))
        counts = count_primitives(jaxpr)
        assert counts["sin"] == 1 and counts["cos"] == 1 \
            and counts["tanh"] == 1
        depth = {s.name: s.loop_depth for s in sites(jaxpr)}
        assert depth["tanh"] == 0  # pjit does not multiply execution
        assert depth["cos"] == 1  # while body
        assert depth["sin"] == 2  # scan inside while
        paths = {s.name: s.path for s in sites(jaxpr)}
        assert paths["sin"] == ("pjit", "while", "scan")

    def test_cond_branches(self):
        """`cond` carries its branches as a TUPLE param — both must be
        walked (the naive params.values() isinstance walk misses them)."""

        def f(x):
            return lax.cond(jnp.sum(x) > 0,
                            lambda z: jnp.sin(z), lambda z: jnp.cos(z), x)

        counts = count_primitives(jax.make_jaxpr(f)(jnp.ones(3)))
        assert counts["sin"] == 1 and counts["cos"] == 1

    def test_shard_map_sub_jaxpr(self, mesh8):
        def f(x):
            return shard_map(lambda v: lax.psum(jnp.sin(v), "data"),
                             mesh=mesh8, in_specs=P("data"),
                             out_specs=P())(x)

        jaxpr = jax.make_jaxpr(f)(jnp.ones(16))
        assert collective_counts(jaxpr) == {"psum": 1}
        assert count_primitives(jaxpr)["sin"] == 1
        (site,) = [s for s in sites(jaxpr) if s.name == "psum"]
        assert "shard_map" in site.path

    def test_custom_vjp_branch(self):
        @jax.custom_vjp
        def f(x):
            return jnp.sin(x)

        def fwd(x):
            return jnp.sin(x), x

        def bwd(res, ct):
            return (ct * jnp.cos(res),)

        f.defvjp(fwd, bwd)
        # primal trace: the walker descends into fun_jaxpr
        counts = count_primitives(jax.make_jaxpr(lambda x: f(x * 2.0))(
            jnp.ones(3)))
        assert counts["sin"] == 1
        # grad trace: the bwd branch's cos is reachable too
        counts_g = count_primitives(jax.make_jaxpr(
            jax.grad(lambda x: jnp.sum(f(x))))(jnp.ones(3)))
        assert counts_g["cos"] == 1

    def test_const_bytes(self):
        big = np.ones((1024, 256), np.float32)  # 1 MiB closure

        jaxpr = jax.make_jaxpr(lambda x: x @ jnp.asarray(big))(
            jnp.ones(1024))
        assert const_bytes(jaxpr) >= big.nbytes


# ----------------------------------------------- rule violation fixtures
class TestRuleFires:
    def test_collective_budget_overrun(self, mesh8):
        """Two psums against a one-psum budget: the streamed regression
        this rule exists for (a psum inside a chunk partial)."""

        def build():
            def body(v):
                return lax.psum(v, "data") + lax.psum(v * v, "data")

            fn = lambda x: shard_map(body, mesh=mesh8,  # noqa: E731
                                     in_specs=P("data"),
                                     out_specs=P("data"))(x)
            return fn, (jnp.ones(16),)

        out = _violations(build, "collective-budget",
                          collectives={"psum": 1})
        assert out and "2 `psum` against a budget of 1" in out[0].message

    def test_collective_budget_unexpected_kind(self, mesh8):
        """An all_gather nobody declared is drift even when psum matches."""

        def build():
            def body(v):
                return jnp.sum(lax.all_gather(v, "data")) + lax.psum(
                    jnp.sum(v), "data")

            fn = lambda x: shard_map(body, mesh=mesh8,  # noqa: E731
                                     in_specs=P("data"),
                                     out_specs=P())(x)
            return fn, (jnp.ones(16),)

        out = _violations(build, "collective-budget",
                          collectives={"psum": 1})
        assert out and "all_gather" in out[0].message

    def test_forbidden_primitive(self):
        def build():
            idx = jnp.zeros((4, 1), jnp.int32)
            fn = lambda x: x.at[idx[:, 0]].add(1.0)  # noqa: E731
            return fn, (jnp.ones(8),)

        out = _violations(build, "collective-budget",
                          forbid=("scatter-add",))
        assert out and "scatter-add" in out[0].message

    def test_transfer_lint_callback_in_loop(self):
        """A host callback inside a scan body: a round-trip per
        iteration, the exact anti-pattern the rule names."""

        def build():
            def body(c, _):
                v = jax.pure_callback(
                    np.sin, jax.ShapeDtypeStruct((), jnp.float32), c)
                return c + v, None

            fn = lambda x: lax.scan(body, x, None, length=3)[0]  # noqa: E731
            return fn, (jnp.float32(1.0),)

        out = _violations(build, "transfer-lint")
        assert out and "EVERY iteration" in out[0].message

    def test_transfer_lint_device_put(self):
        def build():
            fn = lambda x: jax.device_put(x) + 1.0  # noqa: E731
            return fn, (jnp.ones(4),)

        assert _violations(build, "transfer-lint")

    def test_dtype_policy_f64_leak(self):
        from jax.experimental import enable_x64

        def build():
            fn = lambda x: jnp.sum(x.astype(jnp.float64))  # noqa: E731
            return fn, (jnp.ones(4),)

        with enable_x64():
            out = _violations(build, "dtype-policy")
        assert out and "float64" in out[0].message

    def test_dtype_policy_bf16_accumulation(self):
        """jnp.sum upcasts bf16 itself, so the reachable bf16 accumulators
        are cumsum-style scans (and bf16 psums) — cumsum stays bf16."""

        def build():
            fn = lambda x: x.cumsum()[-1]  # noqa: E731
            return fn, (jnp.ones(64, jnp.bfloat16),)

        out = _violations(build, "dtype-policy")
        assert out and "bfloat16" in out[0].message

    def test_dtype_policy_bf16_matmul_needs_f32_out(self):
        def build():
            fn = lambda a, b: a @ b  # bf16 x bf16 -> bf16  # noqa: E731
            return fn, (jnp.ones((8, 4), jnp.bfloat16),
                        jnp.ones((4, 8), jnp.bfloat16))

        out = _violations(build, "dtype-policy")
        assert out and "preferred_element_type" in out[0].message
        # the policy-compliant form is clean: bf16 in, f32 accumulate
        ok = lambda a, b: jnp.matmul(  # noqa: E731
            a, b, preferred_element_type=jnp.float32)
        assert not _violations(
            lambda: (ok, (jnp.ones((8, 4), jnp.bfloat16),
                          jnp.ones((4, 8), jnp.bfloat16))), "dtype-policy")

    def test_const_bloat(self):
        big = np.ones((1 << 20,), np.float32)  # 4 MB baked closure

        def build():
            fn = lambda x: jnp.sum(x * jnp.asarray(big))  # noqa: E731
            return fn, (jnp.ones(1 << 20),)

        out = _violations(build, "const-bloat", max_const_bytes=1 << 20)
        assert out and "4.2 MB" in out[0].message
        # a bigger budget accepts the same program
        assert not _violations(build, "const-bloat",
                               max_const_bytes=8 << 20)

    def test_retrace_hazard_weak_arg(self):
        def build():
            return (lambda x, s: x * s), (jnp.ones(4), 0.5)

        out = _violations(build, "retrace-hazard")
        assert out and "weak-typed" in out[0].message

    def test_retrace_hazard_captured_scalar_const(self):
        scale = jnp.float32(3.0)  # device scalar baked into the closure

        def build():
            return (lambda x: x * scale), (jnp.ones(4),)

        out = _violations(build, "retrace-hazard")
        assert out and "captured scalar" in out[0].message

    def test_clean_program_no_violations(self):
        def build():
            fn = lambda x, s: jnp.sum(x * s)  # noqa: E731
            return fn, (jnp.ones(4), np.float32(0.5))

        assert _violations(build) == []


# ------------------------------------------------ trace-signature registry
class TestTraceSignatures:
    def test_weak_drift_detected(self):
        log = TraceSignatureLog()
        log.record("phi", (jnp.ones(8), 0.5))  # Python-scalar caller
        log.record("phi", (jnp.ones(8), np.float32(0.5)))  # array caller
        hazards = log.hazards()
        assert len(hazards) == 1 and hazards[0][0] == "phi"

    def test_legit_shape_change_is_not_drift(self):
        log = TraceSignatureLog()
        log.record("solve", (jnp.ones(8),))
        log.record("solve", (jnp.ones(16),))  # new shape = new program
        assert log.hazards() == []

    def test_identical_signatures_dedupe(self):
        log = TraceSignatureLog()
        a = log.record("f", (jnp.ones(4),))
        b = log.record("f", (jnp.zeros(4),))  # values differ, aval equal
        assert a == b and len(log.signatures("f")) == 1

    def test_weak_type_drift_predicate(self):
        a = trace_signature((jnp.ones(3), 1.0))
        b = trace_signature((jnp.ones(3), np.float32(1.0)))
        c = trace_signature((jnp.ones(3), np.float64(1.0)))
        assert weak_type_drift(a, b)
        assert not weak_type_drift(a, a)
        assert not weak_type_drift(b, c)  # dtype change: a real retrace

    def test_concurrent_record_is_safe(self):
        # Round-18 regression: _seen is mutated from serving threads while
        # hazards() iterates — must not lose entries or raise RuntimeError.
        import threading

        log = TraceSignatureLog()
        args = [(jnp.ones(4), 0.5), (jnp.ones(4), np.float32(0.5))]
        errs: list = []

        def pound(i: int) -> None:
            try:
                for k in range(200):
                    log.record(f"fn{(i + k) % 4}", args[k % 2])
                    log.hazards()
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        ts = [threading.Thread(target=pound, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert errs == []
        hazards = log.hazards()
        assert sorted(h[0] for h in hazards) == ["fn0", "fn1", "fn2", "fn3"]
        for name in ("fn0", "fn1", "fn2", "fn3"):
            assert len(log.signatures(name)) == 2
