"""Streaming mode of the training driver: bounded host arena, bit-identical
models vs the one-shot read, chunk-merged statistics feeding
summarization/normalization, weight-form down-sampling (VERDICT r3 item 1:
wire the streaming layer into the product path the reference's
AvroDataReader + GameTrainingDriver represent)."""
import os

import numpy as np
import pytest

from photon_tpu.data.avro_io import write_avro
from photon_tpu.data.ingest import training_example_schema
from photon_tpu.data.statistics import FeatureSummary
from photon_tpu.drivers import TrainingParams, run_training


def _write_parts(root, n_files=3, rows_per_file=220, seed=0):
    """Multi-file GAME input with small container blocks so streaming sees
    many chunk boundaries."""
    rng = np.random.default_rng(seed)
    schema = training_example_schema(feature_bags=("global", "puser"),
                                     entity_fields=("userId",))
    os.makedirs(root, exist_ok=True)
    for fi in range(n_files):
        records = []
        for i in range(rows_per_file):
            age = float(rng.normal())
            ctr = float(rng.normal(2.0, 3.0))  # non-unit stats for norm tests
            u = int(rng.integers(0, 11))
            margin = 1.1 * age - 0.3 * (ctr - 2.0) + 0.2 * (u - 5)
            y = float(rng.uniform() < 1 / (1 + np.exp(-margin)))
            records.append({
                "response": y, "offset": None,
                "weight": 2.0 if i % 7 == 0 else None,
                "uid": f"r{fi}_{i}", "userId": f"u{u}",
                "global": [
                    {"name": "age", "term": "", "value": age},
                    {"name": "ctr", "term": "", "value": ctr},
                ],
                "puser": [{"name": "bias", "term": "", "value": 1.0}],
            })
        write_avro(root / f"part-{fi:03d}.avro", records, schema,
                   block_records=64)
    return root


FEATURE_SHARDS = {
    "fixedShard": {"bags": ["global"], "has_intercept": True},
    "userShard": {"bags": ["puser"], "has_intercept": False},
}
COORDINATES = {
    "fixed": {"feature_shard": "fixedShard", "reg_type": "l2",
              "reg_weight": 0.5, "max_iters": 40},
    "perUser": {"feature_shard": "userShard", "entity_name": "userId",
                "reg_type": "l2", "reg_weight": 2.0, "max_iters": 20},
}


def _params(root, out, **kw):
    base = dict(
        train_path=str(root / "train"),
        validation_path=str(root / "val"),
        output_dir=str(out),
        feature_shards=FEATURE_SHARDS,
        coordinates=COORDINATES,
        entity_fields=["userId"],
        n_sweeps=2,
    )
    base.update(kw)
    return TrainingParams(**base)


@pytest.fixture(scope="module")
def stream_job(tmp_path_factory):
    root = tmp_path_factory.mktemp("stream_job")
    _write_parts(root / "train", n_files=3, rows_per_file=220, seed=1)
    _write_parts(root / "val", n_files=2, rows_per_file=110, seed=2)
    return root


class TestStreamingTrainingDriver:
    def test_bit_identical_vs_one_shot(self, stream_job, tmp_path):
        """Multi-file input, no mesh: the streamed driver path must produce
        the SAME model as the one-shot read, bit for bit (chunks are
        block-aligned, maps mirror the one-shot assignment, shapes match)."""
        a = run_training(_params(stream_job, tmp_path / "one_shot",
                                 streaming=False))
        b = run_training(_params(stream_job, tmp_path / "streamed",
                                 streaming=True, streaming_chunk_rows=128))
        assert a.best.validation_score == pytest.approx(
            b.best.validation_score, rel=0, abs=0)
        fa, fb = a.best.model.coordinates, b.best.model.coordinates
        assert set(fa) == set(fb)
        wa = np.asarray(fa["fixed"].model.coefficients.means)
        wb = np.asarray(fb["fixed"].model.coefficients.means)
        np.testing.assert_array_equal(wa, wb)
        np.testing.assert_array_equal(fa["perUser"].entity_keys,
                                      fb["perUser"].entity_keys)
        np.testing.assert_array_equal(
            np.asarray(fa["perUser"].coefficients),
            np.asarray(fb["perUser"].coefficients))

    def test_bounded_arena_on_mesh(self, stream_job, tmp_path, mesh8,
                                   monkeypatch):
        """Streaming onto the 8-device mesh keeps the host chunk arena
        bounded by ~2 chunks regardless of file count, and the fit still
        converges (pad rows are weight-0)."""
        import photon_tpu.data.streaming as streaming_mod

        captured = []
        real = streaming_mod.iter_game_chunks

        def spy(*a, **kw):
            stream, it = real(*a, **kw)
            captured.append(stream)
            return stream, it

        monkeypatch.setattr(streaming_mod, "iter_game_chunks", spy)
        out = run_training(
            _params(stream_job, tmp_path / "mesh_out", streaming=True,
                    streaming_chunk_rows=128),
            mesh=mesh8)
        assert out.best.validation_score is not None
        assert np.isfinite(out.best.validation_score)
        assert captured, "driver never went through the chunk stream"
        for st in captured:
            # 128-row chunks close at 64-record block boundaries → ≤191
            # rows/chunk; arena contract is ≤ ~2 live chunks.
            per_row = st.peak_arena_bytes / (2 * 191)
            assert st.peak_arena_bytes > 0
            assert per_row < 4096, (
                f"peak arena {st.peak_arena_bytes}B implies >4KB/row — "
                "the stream is materializing more than ~2 chunks")

    def test_auto_threshold_resolves_streaming(self, stream_job, tmp_path,
                                               monkeypatch):
        """streaming=None auto-enables from the block-header row counts —
        and never mutates the caller's params object."""
        import photon_tpu.data.streaming as streaming_mod

        calls = []
        real = streaming_mod.iter_game_chunks

        def spy(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(streaming_mod, "iter_game_chunks", spy)
        p = _params(stream_job, tmp_path / "auto_on",
                    streaming_threshold_rows=100)  # 660 rows > 100
        run_training(p)
        assert p.streaming is None  # config object stays a reusable tri-state
        assert calls, "auto threshold did not engage the chunk stream"
        calls.clear()
        p2 = _params(stream_job, tmp_path / "auto_off",
                     streaming_threshold_rows=10_000_000)
        run_training(p2)
        assert p2.streaming is None
        assert not calls

    def test_streamed_stats_feed_normalization_and_summaries(
            self, stream_job, tmp_path):
        """Chunk-merged summaries equal the one-shot pass to fp accuracy and
        feed normalization without a device readback."""
        a = run_training(_params(
            stream_job, tmp_path / "ns_one_shot", streaming=False,
            normalization="scale_with_standard_deviation",
            summarization_output_dir="summaries"))
        b = run_training(_params(
            stream_job, tmp_path / "ns_streamed", streaming=True,
            streaming_chunk_rows=128, normalization="scale_with_standard_deviation",
            summarization_output_dir="summaries"))
        for shard in FEATURE_SHARDS:
            sa = FeatureSummary.load(
                str(tmp_path / "ns_one_shot" / "summaries" / f"{shard}.json"))
            sb = FeatureSummary.load(
                str(tmp_path / "ns_streamed" / "summaries" / f"{shard}.json"))
            assert sa.count == sb.count
            np.testing.assert_allclose(sa.mean, sb.mean, rtol=1e-6,
                                       atol=1e-9)
            np.testing.assert_allclose(sa.variance, sb.variance, rtol=1e-5,
                                       atol=1e-9)
            np.testing.assert_array_equal(sa.num_nonzeros, sb.num_nonzeros)
        wa = np.asarray(a.best.model.coordinates["fixed"].model.coefficients.means)
        wb = np.asarray(b.best.model.coordinates["fixed"].model.coefficients.means)
        # factors differ in the last f32 ulps (f32 device pass vs f64
        # chunk merge), amplified through solver convergence
        np.testing.assert_allclose(wa, wb, rtol=2e-3, atol=1e-4)

    @pytest.mark.tier2
    def test_weight_form_down_sampling_matches_row_form(self, stream_job,
                                                        tmp_path):
        """Streaming down-sampling (weight-0 rows) selects the same rows as
        the row-dropping sampler and converges to the same model."""
        a = run_training(_params(stream_job, tmp_path / "ds_rows",
                                 streaming=False, down_sampling_rate=0.6,
                                 seed=7))
        b = run_training(_params(stream_job, tmp_path / "ds_weights",
                                 streaming=True, streaming_chunk_rows=128,
                                 down_sampling_rate=0.6, seed=7))
        wa = np.asarray(a.best.model.coordinates["fixed"].model.coefficients.means)
        wb = np.asarray(b.best.model.coordinates["fixed"].model.coefficients.means)
        np.testing.assert_allclose(wa, wb, rtol=2e-3, atol=2e-4)

    def test_streaming_resume_signature_stable(self, stream_job, tmp_path):
        """Resumed grid points survive a second streamed run (signatures
        resolve the tri-state the same way both runs)."""
        def make():
            return _params(
                stream_job, tmp_path / "resume_out", streaming=True,
                streaming_chunk_rows=128, output_mode="ALL", resume=True,
                warm_start=False,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": [0.1, 1.0]},
                })

        first = run_training(make())
        again = run_training(make())
        assert first.n_resumed == 0
        assert again.n_resumed == len(again.results)


class TestDownSampleWeights:
    def test_matches_row_selection_binary(self):
        from photon_tpu.data.sampling import (
            binary_down_sample,
            down_sample_weights,
        )

        rng = np.random.default_rng(3)
        y = (rng.uniform(size=500) < 0.3).astype(np.float32)
        w = rng.uniform(0.5, 2.0, 500).astype(np.float32)
        idx, w_rows = binary_down_sample(y, 0.4, w, seed=11)
        w_full = down_sample_weights(y, 0.4, w, seed=11, binary=True)
        np.testing.assert_array_equal(np.nonzero(w_full > 0)[0], idx)
        np.testing.assert_allclose(w_full[idx], w_rows, rtol=1e-6)

    def test_matches_row_selection_default(self):
        from photon_tpu.data.sampling import (
            default_down_sample,
            down_sample_weights,
        )

        rng = np.random.default_rng(4)
        y = rng.normal(size=300).astype(np.float32)
        idx, w_rows = default_down_sample(300, 0.5, None, seed=5)
        w_full = down_sample_weights(y, 0.5, None, seed=5, binary=False)
        np.testing.assert_array_equal(np.nonzero(w_full > 0)[0], idx)
        np.testing.assert_allclose(w_full[idx], w_rows, rtol=1e-6)


class TestSummaryMerge:
    def test_merge_equals_one_shot(self):
        rng = np.random.default_rng(9)
        X = rng.normal(50.0, 3.0, (1000, 6)).astype(np.float32)
        X[rng.uniform(size=X.shape) < 0.3] = 0.0
        full = FeatureSummary.compute(X)
        merged = FeatureSummary.compute(X[:256])
        for lo in range(256, 1000, 256):
            merged = merged.merge(FeatureSummary.compute(X[lo:lo + 256]))
        assert merged.count == full.count
        np.testing.assert_allclose(merged.mean, full.mean, rtol=1e-6)
        np.testing.assert_allclose(merged.variance, full.variance, rtol=1e-4)
        np.testing.assert_array_equal(merged.num_nonzeros, full.num_nonzeros)
        np.testing.assert_allclose(merged.norm_l2, full.norm_l2, rtol=1e-6)
        np.testing.assert_array_equal(merged.minimum, full.minimum)
        np.testing.assert_array_equal(merged.maximum, full.maximum)


class TestWeightAwareREDataset:
    """Weight-0 rows (streamed down-sampling, mesh padding) never poison
    random-effect training: zero-weight entities are dropped to the
    unseen-entity convention, and capped active sets prefer carrying rows."""

    def test_zero_weight_entity_dropped(self):
        from photon_tpu.game.dataset import GameData, RandomEffectDataset

        rng = np.random.default_rng(0)
        n = 24
        ids = np.array([f"e{i % 4}" for i in range(20)] + [""] * 4)
        w = np.ones(n, np.float32)
        w[20:] = 0.0  # the mesh-pad tail
        data = GameData.build(
            rng.normal(size=n).astype(np.float32),
            {"s": rng.normal(size=(n, 3)).astype(np.float32)},
            {"ent": ids}, weights=w)
        ds = RandomEffectDataset.build(data, "ent", "s")
        assert "" not in set(ds.entity_keys.tolist())
        assert ds.n_entities == 4
        # pad rows carry the unseen-entity id E -> they score the zero row
        assert (np.asarray(ds.entity_dense)[20:] == 4).all()

    def test_capped_active_set_prefers_carrying_rows(self):
        from photon_tpu.game.dataset import GameData, RandomEffectDataset

        rng = np.random.default_rng(1)
        n = 60
        ids = np.array([f"e{i % 4}" for i in range(n)])
        w = np.ones(n, np.float32)
        e0_rows = np.nonzero(ids == "e0")[0]
        w[e0_rows[:8]] = 0.0  # 8 of e0's 15 rows are weight-0
        data = GameData.build(
            rng.normal(size=n).astype(np.float32),
            {"s": rng.normal(size=(n, 3)).astype(np.float32)},
            {"ent": ids}, weights=w)
        ds = RandomEffectDataset.build(data, "ent", "s", active_cap=5, seed=0)
        # every entity still has >= 5 carrying rows, so all 4x5 active
        # slots must be weight-carrying (weight-0 rows never displace them)
        carrying_in_blocks = sum(
            int((np.asarray(b.weights) > 0).sum()) for b in ds.blocks)
        assert carrying_in_blocks == 4 * 5
