"""Incremental training / informative priors (SURVEY.md §5 checkpoint-resume
via priors; reference: function.PriorDistribution, --initial-model flow)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.dataset import make_batch
from photon_tpu.models.training import train_glm
from photon_tpu.models.variance import VarianceComputationType
from photon_tpu.ops.losses import TaskType
from photon_tpu.ops.objective import Objective
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.prior import PriorDistribution


class TestPriorDistribution:
    def test_from_coefficients(self):
        p = PriorDistribution.from_coefficients(
            np.array([1.0, 2.0]), np.array([0.5, 0.25]), scale=2.0)
        np.testing.assert_allclose(p.precision_diag, [4.0, 8.0])
        assert p.precision_full is None

    def test_both_precisions_rejected(self):
        with pytest.raises(ValueError):
            PriorDistribution(np.zeros(2), np.ones(2), np.eye(2))

    def test_missing_variances_default(self):
        p = PriorDistribution.from_coefficients(np.zeros(3),
                                                default_precision=7.0)
        np.testing.assert_allclose(p.precision_diag, 7.0)


class TestFullPrecisionObjective:
    def test_value_grad_hvp_vs_autodiff(self, rng):
        n, d = 100, 5
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = rng.normal(size=n).astype(np.float32)
        A = rng.normal(size=(d, d)).astype(np.float32)
        P = A @ A.T + np.eye(d, dtype=np.float32)
        mu = rng.normal(size=d).astype(np.float32)
        obj = Objective(
            task=TaskType.LINEAR_REGRESSION, l2=0.3,
            prior_mean=jnp.asarray(mu),
            prior_full_precision=jnp.asarray(P),
        )
        batch = make_batch(X, y)
        w = jnp.asarray(rng.normal(size=d), jnp.float32)
        f, g = obj.value_and_grad(w, batch)
        g_auto = jax.grad(lambda w: obj.value_and_grad(w, batch)[0])(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_auto),
                                   rtol=1e-4, atol=1e-3)
        v = jnp.asarray(rng.normal(size=d), jnp.float32)
        hv = obj.hvp(w, batch, v)
        hv_auto = jax.jvp(
            lambda w: jax.grad(lambda u: obj.value_and_grad(u, batch)[0])(w),
            (w,), (v,))[1]
        np.testing.assert_allclose(np.asarray(hv), np.asarray(hv_auto),
                                   rtol=1e-3, atol=1e-2)
        H = obj.full_hessian(w, batch)
        np.testing.assert_allclose(np.asarray(jnp.diag(H)),
                                   np.asarray(obj.hess_diag(w, batch)),
                                   rtol=1e-3, atol=1e-2)

    def test_sequential_bayes_equals_joint_for_linear(self, rng):
        """Stage-1 posterior (full Hessian) as stage-2 prior must reproduce
        the joint solve exactly for quadratic objectives."""
        n, d = 400, 6
        X = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32)
        y = (X @ w_true + 0.1 * rng.normal(size=n)).astype(np.float32)
        X1, y1, X2, y2 = X[:200], y[:200], X[200:], y[200:]
        lam = 2.0
        cfg = OptimizerConfig(max_iters=200, tolerance=1e-12,
                              reg=reg.l2(), reg_weight=lam)
        m1, _ = train_glm(make_batch(X1, y1), TaskType.LINEAR_REGRESSION, cfg)
        obj1 = Objective(task=TaskType.LINEAR_REGRESSION, l2=lam)
        H1 = obj1.full_hessian(m1.weights, make_batch(X1, y1))
        prior = PriorDistribution.from_hessian(np.asarray(m1.weights),
                                               np.asarray(H1))
        cfg2 = OptimizerConfig(max_iters=200, tolerance=1e-12)  # no extra reg
        m2, _ = train_glm(make_batch(X2, y2), TaskType.LINEAR_REGRESSION,
                          cfg2, prior=prior)
        m_joint, _ = train_glm(make_batch(X, y), TaskType.LINEAR_REGRESSION, cfg)
        np.testing.assert_allclose(np.asarray(m2.weights),
                                   np.asarray(m_joint.weights),
                                   rtol=1e-3, atol=1e-3)

    def test_strong_diag_prior_pins_solution(self, rng):
        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (rng.uniform(size=200) < 0.5).astype(np.float32)
        mu = np.array([1.0, -1.0, 0.5, 0.0], np.float32)
        prior = PriorDistribution(mu, precision_diag=np.full(4, 1e6, np.float32))
        m, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                         OptimizerConfig(max_iters=100), prior=prior)
        np.testing.assert_allclose(np.asarray(m.weights), mu, atol=5e-3)

    def test_prior_exclusive_with_explicit_args(self, rng):
        X = rng.normal(size=(10, 2)).astype(np.float32)
        y = np.zeros(10, np.float32)
        with pytest.raises(ValueError, match="prior OR"):
            train_glm(make_batch(X, y), TaskType.LINEAR_REGRESSION,
                      OptimizerConfig(max_iters=5),
                      prior=PriorDistribution.from_coefficients(np.zeros(2)),
                      prior_mean=jnp.zeros(2))


class TestGameIncremental:
    def _data(self, rng, n=300, E=6):
        from photon_tpu.game.dataset import GameData

        user = rng.integers(0, E, n)
        Xr = rng.normal(size=(n, 2)).astype(np.float32)
        u = rng.normal(size=(E, 2)).astype(np.float32)
        m = np.einsum("nd,nd->n", Xr, u[user])
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-m))).astype(np.float32)
        return GameData.build(
            y, shards={"r": Xr},
            entity_ids={"user": np.asarray([f"u{i}" for i in user])}), u

    def test_random_effect_prior_pins_seen_entities(self, rng):
        from photon_tpu.game.dataset import RandomEffectDataset
        from photon_tpu.game.model import RandomEffectModel
        from photon_tpu.game.random_effect import RandomEffectCoordinate

        data, _ = self._data(rng)
        ds = RandomEffectDataset.build(data, "user", "r")
        E, d = ds.n_entities, ds.dim
        # prior: half the entities, tiny variances (pinned), distinct means
        keys = ds.entity_keys[: E // 2]
        pin = np.arange(1, len(keys) + 1, dtype=np.float32)
        prior_model = RandomEffectModel(
            entity_name="user", feature_shard="r",
            task=TaskType.LOGISTIC_REGRESSION,
            coefficients=jnp.asarray(np.stack([pin, -pin], 1)),
            entity_keys=np.asarray(keys),
            key_to_index={k: i for i, k in enumerate(keys.tolist())},
            variances=jnp.full((len(keys), d), 1e-8),
        )
        coord = RandomEffectCoordinate(
            ds, TaskType.LOGISTIC_REGRESSION,
            OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=0.1))
        model, _ = coord.train(np.zeros(data.n, np.float32), prior=prior_model)
        got = np.asarray(model.coefficients)
        np.testing.assert_allclose(got[: E // 2, 0], pin, atol=1e-2)
        np.testing.assert_allclose(got[: E // 2, 1], -pin, atol=1e-2)
        # unseen entities trained freely — not pinned to zero-prior means
        assert not np.allclose(got[E // 2:], 0.0)

    @pytest.mark.tier2
    def test_estimator_incremental_beats_cold_start_on_new_batch(self, rng):
        """Second-batch training with first-batch priors must track the
        pooled solution better than training on the second batch alone."""
        from photon_tpu.game.estimator import GameEstimator, RandomEffectConfig

        E = 6
        data1, _ = self._data(rng, n=1200, E=E)
        data2, _ = self._data(rng, n=60, E=E)  # tiny second batch
        cfg = {"re": RandomEffectConfig(
            "user", "r",
            OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=1.0))}

        est = GameEstimator(TaskType.LOGISTIC_REGRESSION, cfg, n_sweeps=1,
                            variance=VarianceComputationType.SIMPLE)
        m1 = est.fit(data1)[0].model

        inc = GameEstimator(TaskType.LOGISTIC_REGRESSION, cfg, n_sweeps=1,
                            incremental=frozenset({"re"}))
        m_inc = inc.fit(data2, initial_models=dict(m1.coordinates))[0].model
        cold = GameEstimator(TaskType.LOGISTIC_REGRESSION, cfg, n_sweeps=1)
        m_cold = cold.fit(data2)[0].model

        from photon_tpu.game.dataset import GameData

        pooled = GameData.build(
            np.concatenate([data1.y, data2.y]),
            shards={"r": np.concatenate(
                [np.asarray(data1.shards["r"]), np.asarray(data2.shards["r"])])},
            entity_ids={"user": np.concatenate(
                [data1.entity_ids["user"], data2.entity_ids["user"]])},
        )
        m_pool = est.fit(pooled)[0].model

        def dist(a, b):
            ka = {k: i for i, k in enumerate(a.entity_keys.tolist())}
            kb = {k: i for i, k in enumerate(b.entity_keys.tolist())}
            common = sorted(set(ka) & set(kb))
            A = np.asarray(a.coefficients)[[ka[k] for k in common]]
            B = np.asarray(b.coefficients)[[kb[k] for k in common]]
            return float(np.abs(A - B).mean())

        assert dist(m_inc["re"], m_pool["re"]) < dist(m_cold["re"], m_pool["re"])

    def test_incremental_requires_initial_model(self, rng):
        from photon_tpu.game.estimator import GameEstimator, RandomEffectConfig

        data, _ = self._data(rng, n=100)
        est = GameEstimator(
            TaskType.LOGISTIC_REGRESSION,
            {"re": RandomEffectConfig("user", "r",
                                      OptimizerConfig(max_iters=5))},
            incremental=frozenset({"re"}),
        )
        with pytest.raises(ValueError, match="initial_models"):
            est.fit(data)
