"""Margin-cached L-BFGS vs the generic solver: identical math, fewer X passes.

Parity pinned across every objective feature the margin path must preserve:
dense/sparse X, normalization (shift+scale margins), priors, intercept
reg-mask, shard_map psum, and the vmapped per-entity path.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
import scipy.sparse as sp
from photon_tpu.parallel.mesh import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_tpu.data.dataset import make_batch
from photon_tpu.data.matrix import from_scipy_csr
from photon_tpu.data.normalization import NormalizationContext, NormalizationType
from photon_tpu.models.training import make_objective, solve, train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.lbfgs import minimize_lbfgs, minimize_lbfgs_margin


def _problem(rng, n=600, d=8, task=TaskType.LOGISTIC_REGRESSION):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
    if task is TaskType.LINEAR_REGRESSION:
        y = (X @ w + 0.1 * rng.normal(size=n)).astype(np.float32)
    else:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w)))).astype(
            np.float32)
    return make_batch(X, y, weights=rng.uniform(0.5, 2, n).astype(np.float32))


def _both(obj, batch, d, **kw):
    w0 = jnp.zeros((d,), jnp.float32)
    classic = minimize_lbfgs(lambda w: obj.value_and_grad(w, batch), w0, **kw)
    margin = minimize_lbfgs_margin(obj, batch, w0, **kw)
    return classic, margin


@pytest.mark.parametrize("task", [TaskType.LOGISTIC_REGRESSION,
                                  TaskType.LINEAR_REGRESSION,
                                  TaskType.POISSON_REGRESSION,
                                  TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM])
def test_matches_classic_dense(task, rng):
    batch = _problem(rng, task=task)
    obj = make_objective(task, OptimizerConfig(reg=reg.l2(), reg_weight=0.5),
                         8, intercept_index=None)
    classic, margin = _both(obj, batch, 8)
    assert bool(margin.converged) and not bool(margin.failed)
    np.testing.assert_allclose(np.asarray(margin.w), np.asarray(classic.w),
                               atol=5e-4)
    np.testing.assert_allclose(float(margin.value), float(classic.value),
                               rtol=1e-5)


def test_matches_classic_sparse(rng):
    M = sp.random(500, 40, density=0.2, random_state=0, format="csr",
                  dtype=np.float32)
    y = (rng.uniform(size=500) < 0.5).astype(np.float32)
    batch = make_batch(from_scipy_csr(M), y)
    obj = make_objective(TaskType.LOGISTIC_REGRESSION,
                         OptimizerConfig(reg=reg.l2(), reg_weight=0.3), 40,
                         intercept_index=None)
    classic, margin = _both(obj, batch, 40, tolerance=1e-9, max_iters=200)
    # Sparse problems have near-flat directions: both solvers reach the same
    # objective value; coefficients may differ slightly along the flat.
    np.testing.assert_allclose(float(margin.value), float(classic.value),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(margin.w), np.asarray(classic.w),
                               atol=5e-3)


def test_matches_with_normalization_and_prior(rng):
    n, d = 500, 6
    X = (rng.normal(size=(n, d)) * rng.uniform(0.5, 5, d) + 2).astype(
        np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    norm = NormalizationContext.build(
        X, NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
        intercept_index=None)
    cfg = OptimizerConfig(reg=reg.l2(), reg_weight=0.5)
    pm = jnp.asarray(rng.normal(size=d), jnp.float32) * 0.1
    pp = jnp.full((d,), 0.5, jnp.float32)
    obj = make_objective(TaskType.LOGISTIC_REGRESSION, cfg, d,
                         normalization=norm, intercept_index=None,
                         prior_mean=pm, prior_precision=pp)
    batch = make_batch(X, y)
    classic, margin = _both(obj, batch, d, tolerance=1e-9, max_iters=200)
    assert bool(margin.converged)
    np.testing.assert_allclose(float(margin.value), float(classic.value),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(margin.w), np.asarray(classic.w),
                               atol=5e-3)


def test_matches_with_standardization_shifts(rng):
    """STANDARDIZATION has shifts: exercises the gsum/backprop-shift terms
    in the margin-space methods (phi_at / grad_at_margin)."""
    n, d = 400, 5
    Xf = (rng.normal(size=(n, d)) * rng.uniform(0.5, 4, d) + 3).astype(
        np.float32)
    X = np.concatenate([Xf, np.ones((n, 1), np.float32)], axis=1)  # intercept
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    norm = NormalizationContext.build(X, NormalizationType.STANDARDIZATION,
                                      intercept_index=-1)
    cfg = OptimizerConfig(reg=reg.l2(), reg_weight=0.5,
                          regularize_intercept=False)
    obj = make_objective(TaskType.LOGISTIC_REGRESSION, cfg, d + 1,
                         normalization=norm, intercept_index=-1)
    assert obj.norm_shifts is not None  # the path under test
    batch = make_batch(X, y)
    classic, margin = _both(obj, batch, d + 1, tolerance=1e-9, max_iters=200)
    assert bool(margin.converged)
    np.testing.assert_allclose(float(margin.value), float(classic.value),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(margin.w), np.asarray(classic.w),
                               atol=5e-3)


def test_matches_under_shard_map(rng, mesh8):
    n, d = 1024, 6
    batch = _problem(rng, n=n, d=d)
    obj_l = make_objective(TaskType.LOGISTIC_REGRESSION,
                           OptimizerConfig(reg=reg.l2(), reg_weight=1.0), d,
                           intercept_index=None)
    obj_d = make_objective(TaskType.LOGISTIC_REGRESSION,
                           OptimizerConfig(reg=reg.l2(), reg_weight=1.0), d,
                           axis_name="data", intercept_index=None)
    w0 = jnp.zeros((d,), jnp.float32)
    local = minimize_lbfgs_margin(obj_l, batch, w0)

    @jax.jit
    def run(batch, w0):
        return shard_map(
            lambda b, w: minimize_lbfgs_margin(obj_d, b, w).w,
            mesh=mesh8, in_specs=(P("data"), P()), out_specs=P(),
        )(batch, w0)

    w_sharded = run(jax.device_put(batch, NamedSharding(mesh8, P("data"))),
                    jax.device_put(w0, NamedSharding(mesh8, P())))
    np.testing.assert_allclose(np.asarray(w_sharded), np.asarray(local.w),
                               atol=2e-4)


def test_vmapped_per_entity(rng):
    """The GAME random-effect shape: vmap over a block of entity problems."""
    B, n, d = 16, 64, 4
    X = rng.normal(size=(B, n, d)).astype(np.float32)
    w_true = rng.normal(size=(B, d)).astype(np.float32)
    p = 1 / (1 + np.exp(-np.einsum("bnd,bd->bn", X, w_true)))
    y = (rng.uniform(size=(B, n)) < p).astype(np.float32)
    cfg = OptimizerConfig(reg=reg.l2(), reg_weight=1.0)
    obj = make_objective(TaskType.LOGISTIC_REGRESSION, cfg, d,
                         intercept_index=None)

    def one(Xb, yb):
        return solve(obj, make_batch(Xb, yb),
                     jnp.zeros((d,), jnp.float32), cfg)

    res = jax.jit(jax.vmap(one))(jnp.asarray(X), jnp.asarray(y))
    assert res.w.shape == (B, d)
    assert bool(res.converged.all())
    # spot-check one block against the classic solver
    classic = minimize_lbfgs(
        lambda w: obj.value_and_grad(w, make_batch(X[3], y[3])),
        jnp.zeros((d,), jnp.float32))
    # default-tolerance solves stopping at slightly different iterates:
    # the margin path's ray-expanded line search rounds differently in f32
    np.testing.assert_allclose(np.asarray(res.w[3]), np.asarray(classic.w),
                               atol=2e-3)


class TestTronMargin:
    def test_matches_classic_tron(self, rng):
        from photon_tpu.optim.tron import minimize_tron, minimize_tron_margin

        batch = _problem(rng, n=800, d=10)
        obj = make_objective(TaskType.LOGISTIC_REGRESSION,
                             OptimizerConfig(reg=reg.l2(), reg_weight=0.5),
                             10, intercept_index=None)
        w0 = jnp.zeros((10,), jnp.float32)
        classic = minimize_tron(
            lambda w: obj.value_and_grad(w, batch),
            lambda w, v: obj.hvp(w, batch, v), w0, tolerance=1e-9,
            max_iters=100)
        margin = minimize_tron_margin(obj, batch, w0, tolerance=1e-9,
                                      max_iters=100)
        assert bool(margin.converged) and not bool(margin.failed)
        np.testing.assert_allclose(float(margin.value), float(classic.value),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(margin.w),
                                   np.asarray(classic.w), atol=1e-3)

    def test_tron_with_normalization(self, rng):
        from photon_tpu.optim.tron import minimize_tron, minimize_tron_margin

        n, d = 500, 6
        X = (rng.normal(size=(n, d)) * rng.uniform(0.5, 4, d)).astype(
            np.float32)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        norm = NormalizationContext.build(
            X, NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
            intercept_index=None)
        obj = make_objective(TaskType.LOGISTIC_REGRESSION,
                             OptimizerConfig(reg=reg.l2(), reg_weight=0.5),
                             d, normalization=norm, intercept_index=None)
        batch = make_batch(X, y)
        w0 = jnp.zeros((d,), jnp.float32)
        classic = minimize_tron(
            lambda w: obj.value_and_grad(w, batch),
            lambda w, v: obj.hvp(w, batch, v), w0)
        margin = minimize_tron_margin(obj, batch, w0)
        np.testing.assert_allclose(float(margin.value), float(classic.value),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(margin.w),
                                   np.asarray(classic.w), atol=2e-3)

    def test_tron_vmapped(self, rng):
        from photon_tpu.optim.tron import minimize_tron_margin

        B, n, d = 8, 64, 4
        X = rng.normal(size=(B, n, d)).astype(np.float32)
        y = (rng.uniform(size=(B, n)) < 0.5).astype(np.float32)
        obj = make_objective(TaskType.LOGISTIC_REGRESSION,
                             OptimizerConfig(reg=reg.l2(), reg_weight=1.0),
                             d, intercept_index=None)
        res = jax.jit(jax.vmap(lambda Xb, yb: minimize_tron_margin(
            obj, make_batch(Xb, yb), jnp.zeros((d,), jnp.float32))))(
                jnp.asarray(X), jnp.asarray(y))
        assert res.w.shape == (B, d)
        assert bool(res.converged.all())


def test_reg_weight_grid_shares_compilation(rng):
    """Different reg weights must hit the SAME jit cache entry — the
    reference's grid search / GP tuner sweeps weights, and a retrace per
    point costs ~2s on TPU (l2 is a traced Objective leaf, the static
    config is weight-normalized)."""
    from photon_tpu.models.training import _train_run

    batch = _problem(rng, n=512, d=6)
    before = _train_run._cache_size()
    for rw in (1e-3, 1e-1, 1.0, 30.0):
        train_glm(batch, TaskType.LOGISTIC_REGRESSION,
                  OptimizerConfig(max_iters=10, reg=reg.l2(), reg_weight=rw))
    assert _train_run._cache_size() == before + 1


def test_train_glm_end_to_end_unchanged(rng):
    """train_glm (now margin-solver-backed) still matches sklearn-grade
    results: planted coefficients recovered."""
    n, d = 4000, 10
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w_true)))).astype(
        np.float32)
    m, r = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                     OptimizerConfig(max_iters=100, tolerance=1e-8))
    assert bool(r.converged)
    np.testing.assert_allclose(np.asarray(m.coefficients.means), w_true,
                               atol=0.25)
