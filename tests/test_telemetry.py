"""Telemetry spine tests: span nesting/exception safety, cross-thread
counter aggregation, the JSONL sink round-trip, the live streamed-solver
iteration stream (events == OptResult.loss_history, single-chip and
mesh), the resident debug-callback tap's on/off result parity, the GAME
descent event stream, photon_logger level semantics, and the
telemetry-off-is-free contract.

Marked `release_programs`: the tap tests arm/disarm `resident_tap`
(which clears jit caches by design) and the mesh test compiles 8-device
shard_map programs — both put this module in the executable-accumulation
regime tests/conftest.py's marker exists for.
"""
import json
import logging
import os
import threading
import time

import numpy as np
import pytest

import jax

from photon_tpu import telemetry
from photon_tpu.telemetry import trace
from photon_tpu.telemetry.aggregate import aggregate_cluster, rank_files
from photon_tpu.telemetry.health import (CRITICAL, DEGRADED, OK,
                                         HealthMonitor, QuantileDigest,
                                         WatchRule, report_from_jsonl,
                                         snapshot)
from photon_tpu.data.dataset import chunk_batch, make_batch
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim import regularization as reg

pytestmark = pytest.mark.release_programs


@pytest.fixture(autouse=True)
def _detached():
    """No test may leak an attached run (or an armed tap) into the rest
    of the suite."""
    yield
    telemetry.finish_run()


def _problem(rng, n=240, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y


_CFG = OptimizerConfig(max_iters=8, tolerance=1e-7, reg=reg.l2(),
                       reg_weight=0.1, history=4)


# ------------------------------------------------------------------- spans
class TestSpans:
    def test_nesting_paths_and_depths(self):
        r = telemetry.start_run("t")
        with telemetry.span("outer", phase="x"):
            with telemetry.span("inner"):
                pass
        with telemetry.span("sibling"):
            pass
        by_path = {s.path: s for s in r.spans}
        assert set(by_path) == {"outer/inner", "outer", "sibling"}
        assert by_path["outer/inner"].depth == 1
        assert by_path["sibling"].depth == 0
        assert by_path["outer"].attrs == {"phase": "x"}
        # children complete (and record) before their parents
        assert r.spans[0].name == "inner"
        assert all(s.seconds >= 0.0 for s in r.spans)

    def test_exception_safety(self):
        r = telemetry.start_run("t")
        with pytest.raises(ValueError):
            with telemetry.span("outer"):
                with telemetry.span("boom"):
                    raise ValueError("x")
        by_path = {s.path: s for s in r.spans}
        assert by_path["outer/boom"].error == "ValueError"
        assert by_path["outer"].error == "ValueError"
        # the stack unwound: a new span is top-level again
        with telemetry.span("after"):
            pass
        assert {s.path for s in r.spans} >= {"after"}
        assert [s for s in r.spans if s.path == "after"][0].depth == 0

    def test_noop_without_run(self):
        assert telemetry.current_run() is None
        with telemetry.span("ignored") as rec:
            assert rec is None
        telemetry.count("ignored")
        telemetry.iteration("ignored", 0, 1.0)  # must not raise

    def test_phase_timers_feed_spans(self):
        from photon_tpu.utils.timing import PhaseTimers

        r = telemetry.start_run("t")
        timers = PhaseTimers(span_prefix="train.")
        with timers("read"):
            pass
        with timers("read"):
            pass
        assert sum(1 for s in r.spans if s.path == "train.read") == 2
        assert timers.summary()["read"] >= 0.0
        telemetry.finish_run()
        with timers("read"):  # detached: pure stopwatch, no crash
            pass


# ---------------------------------------------------------------- counters
class TestCounters:
    def test_thread_aggregation(self):
        r = telemetry.start_run("t")

        def bump():
            for _ in range(2000):
                telemetry.count("bumps")
                telemetry.count("weighted", 0.5)

        threads = [threading.Thread(target=bump) for _ in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert r.counters["bumps"] == 16000.0
        assert r.counters["weighted"] == pytest.approx(8000.0)

    def test_gauges_keep_last(self):
        r = telemetry.start_run("t")
        telemetry.gauge("depth", 2)
        telemetry.gauge("depth", 4)
        assert r.gauges["depth"] == 4

    def test_record_signature_counts_new_traces(self):
        import jax.numpy as jnp

        r = telemetry.start_run("t")
        telemetry.record_signature("prog", (jnp.ones(3),))
        telemetry.record_signature("prog", (jnp.ones(3),))  # same sig
        telemetry.record_signature("prog", (jnp.ones(4),))  # new shape
        assert r.counters["retrace.new_signatures"] == 2.0
        # weak-type drift surfaces in the report
        telemetry.record_signature("drift", (jnp.float32(1.0),))
        telemetry.record_signature("drift", (1.0,))
        assert "drift" in r.report()["retrace"]["weak_type_hazards"]


# ------------------------------------------------------------------- JSONL
class TestJsonlSink:
    def test_round_trip(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        r = telemetry.start_run("rt", jsonl_path=path)
        with telemetry.span("a"):
            with telemetry.span("b"):
                pass
        telemetry.count("c1", 3)
        telemetry.iteration("solver", 0, 1.5, grad_norm=0.1, trials=2)
        telemetry.event("custom_event", detail="x")
        report = telemetry.finish_run()

        disk = telemetry.load_report(path)
        assert disk["complete"]
        assert disk["name"] == "rt"
        assert disk["counters"] == report["counters"]
        assert {s["path"] for s in disk["spans"]} == {"a", "a/b"}
        assert disk["iterations"] == report["iterations"]
        assert disk["iterations"][0]["loss"] == 1.5
        assert [e["type"] for e in disk["events"]] == ["custom_event"]
        assert disk["duration_s"] == pytest.approx(report["duration_s"])

    def test_truncated_tail_tolerated(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        telemetry.start_run("rt", jsonl_path=path)
        telemetry.iteration("s", 0, 1.0)
        telemetry.finish_run()
        with open(path, "a") as fh:
            fh.write('{"type": "iteration", "solver": "s", "it')  # cut off
        disk = telemetry.load_report(path)
        assert len(disk["iterations"]) == 1  # prefix still served

    def test_reopen_after_kill_appends_past_torn_tail(self, tmp_path):
        """Elastic-runs satellite: a run killed mid-write leaves a torn
        FINAL record; a resumed run reopening the SAME file with
        append=True must first truncate that tail (otherwise its first
        record fuses onto the torn line and every later event vanishes
        from read_jsonl), then append — all complete records from both
        generations are served."""
        path = str(tmp_path / "run.jsonl")
        telemetry.start_run("gen1", jsonl_path=path)
        telemetry.iteration("s", 0, 1.0)
        telemetry.iteration("s", 1, 0.5)
        telemetry.finish_run()
        with open(path, "a") as fh:  # the kill: a torn final record
            fh.write('{"type": "iteration", "solver": "s", "it')

        telemetry.start_run("gen2", jsonl_path=path, append=True)
        telemetry.iteration("s", 2, 0.25)
        telemetry.finish_run()

        events = list(telemetry.read_jsonl(path))
        assert [e["name"] for e in events
                if e["type"] == "run_start"] == ["gen1", "gen2"]
        iters = [e for e in events if e["type"] == "iteration"]
        assert [e["it"] for e in iters] == [0, 1, 2]  # torn tail dropped
        assert sum(1 for e in events if e["type"] == "run_end") == 2

    def test_repair_tail_noop_on_clean_file(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        telemetry.start_run("rt", jsonl_path=path)
        telemetry.iteration("s", 0, 1.0)
        telemetry.finish_run()
        size = os.path.getsize(path)
        assert telemetry.repair_jsonl_tail(path) == 0
        assert os.path.getsize(path) == size

    def test_every_line_is_json_with_type(self, tmp_path):
        path = str(tmp_path / "run.jsonl")
        telemetry.start_run("rt", jsonl_path=path)
        with telemetry.span("a"):
            pass
        telemetry.finish_run()
        with open(path) as fh:
            kinds = [json.loads(line)["type"] for line in fh]
        assert kinds[0] == "run_start" and kinds[-1] == "run_end"
        assert "span" in kinds


# ------------------------------------------- streamed iteration stream
class TestStreamedIterationStream:
    def _events(self, r, solver):
        evs = sorted((e for e in r.iterations if e["solver"] == solver),
                     key=lambda e: e["it"])
        assert [e["it"] for e in evs] == list(range(len(evs)))
        return evs

    def test_lbfgs_events_match_loss_history(self, rng, tmp_path):
        X, y = _problem(rng)
        cb = chunk_batch(make_batch(X, y), 64)
        path = str(tmp_path / "run.jsonl")
        r = telemetry.start_run("t", jsonl_path=path)
        _, res = train_glm(cb, TaskType.LOGISTIC_REGRESSION, _CFG)
        telemetry.finish_run()
        evs = self._events(r, "lbfgs_streamed")
        hist = res.history()
        assert len(evs) == hist.shape[0] == int(res.iterations) + 1
        np.testing.assert_allclose([e["loss"] for e in evs], hist,
                                   rtol=1e-6)
        ghist = res.grad_history()
        np.testing.assert_allclose([e["grad_norm"] for e in evs], ghist,
                                   rtol=1e-5)
        # per-iteration events carry the accepted step + trial count
        assert all("step" in e and e["trials"] >= 1 for e in evs[1:])
        # the same stream round-trips through the JSONL sink
        disk = [e for e in telemetry.read_jsonl(path, kind="iteration")
                if e["solver"] == "lbfgs_streamed"]
        assert [e["loss"] for e in disk] == [e["loss"] for e in evs]

    def test_owlqn_events_match_loss_history(self, rng):
        X, y = _problem(rng)
        cb = chunk_batch(make_batch(X, y), 64)
        cfg = OptimizerConfig(max_iters=8, tolerance=1e-7, reg=reg.l1(),
                              reg_weight=0.05, history=4)
        r = telemetry.start_run("t")
        _, res = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)
        telemetry.finish_run()
        evs = self._events(r, "owlqn_streamed")
        hist = res.history()
        assert len(evs) == hist.shape[0]
        np.testing.assert_allclose([e["loss"] for e in evs], hist,
                                   rtol=1e-6)

    def test_streamed_mesh_full_report(self, rng, mesh8, tmp_path):
        """The acceptance shape: a streamed-MESH solve with telemetry on
        produces a JSONL report with spans, >=5 distinct counters, and one
        iteration event per solver iteration whose losses equal
        OptResult.loss_history."""
        X, y = _problem(rng, n=400)
        cb = chunk_batch(make_batch(X, y), 100)
        path = str(tmp_path / "mesh_run.jsonl")
        r = telemetry.start_run("mesh", jsonl_path=path)
        _, res = train_glm(cb, TaskType.LOGISTIC_REGRESSION, _CFG,
                           mesh=mesh8)
        telemetry.finish_run()

        evs = self._events(r, "lbfgs_streamed")
        hist = res.history()
        assert len(evs) == hist.shape[0]
        np.testing.assert_allclose([e["loss"] for e in evs], hist,
                                   rtol=1e-6)

        disk = telemetry.load_report(path)
        assert disk["complete"]
        assert len(disk["spans"]) >= 1
        assert any(s["path"].startswith("solve.lbfgs_streamed")
                   for s in disk["spans"])
        assert len(disk["counters"]) >= 5
        for key in ("stream.chunk_uploads", "stream.stall_seconds",
                    "solver.evaluations", "solver.linesearch_trials",
                    "solver.iterations", "solver.feature_streams"):
            assert key in disk["counters"], key
        # per-pass upload accounting: every feature stream re-uploads all
        # chunks (plus margin-only trial streams never touch features)
        assert disk["counters"]["stream.chunk_uploads"] >= \
            disk["counters"]["solver.feature_streams"] * cb.n_chunks

    def test_counters_off_by_default(self, rng):
        X, y = _problem(rng)
        cb = chunk_batch(make_batch(X, y), 64)
        assert telemetry.current_run() is None
        _, res = train_glm(cb, TaskType.LOGISTIC_REGRESSION, _CFG)
        assert int(res.iterations) > 0  # solve unaffected, nothing raised


# --------------------------------------------------- resident solver tap
class TestResidentTap:
    def test_tap_off_then_on_parity_and_events(self, rng):
        X, y = _problem(rng)
        batch = make_batch(X, y)
        # OFF (default): no run, no events — and the solve works
        res_off = train_glm(batch, TaskType.LOGISTIC_REGRESSION, _CFG)[1]

        r = telemetry.start_run("tap", resident_tap=True)
        res_on = train_glm(batch, TaskType.LOGISTIC_REGRESSION, _CFG)[1]
        jax.effects_barrier()  # debug callbacks drain before asserting
        telemetry.finish_run()

        # parity: the tap must not change results
        np.testing.assert_allclose(np.asarray(res_on.w),
                                   np.asarray(res_off.w), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(res_on.loss_history),
                                   np.asarray(res_off.loss_history),
                                   rtol=1e-6)
        assert int(res_on.iterations) == int(res_off.iterations)

        evs = sorted((e for e in r.iterations
                      if e["solver"] == "lbfgs_margin"),
                     key=lambda e: e["it"])
        hist = res_on.history()
        assert len(evs) == hist.shape[0]
        np.testing.assert_allclose([e["loss"] for e in evs], hist,
                                   rtol=1e-6)
        assert all(e.get("tapped") for e in evs)

        # OFF again: a fresh run without the tap sees no resident events
        r2 = telemetry.start_run("tap-off")
        res_off2 = train_glm(batch, TaskType.LOGISTIC_REGRESSION, _CFG)[1]
        jax.effects_barrier()
        telemetry.finish_run()
        assert not [e for e in r2.iterations
                    if e["solver"] == "lbfgs_margin"]
        np.testing.assert_allclose(np.asarray(res_off2.w),
                                   np.asarray(res_off.w), rtol=1e-6)

    def test_tap_events_owlqn(self, rng):
        X, y = _problem(rng)
        batch = make_batch(X, y)
        cfg = OptimizerConfig(max_iters=6, tolerance=1e-7, reg=reg.l1(),
                              reg_weight=0.05, history=4)
        r = telemetry.start_run("tap", resident_tap=True)
        res = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)[1]
        jax.effects_barrier()
        telemetry.finish_run()
        evs = sorted((e for e in r.iterations if e["solver"] == "owlqn"),
                     key=lambda e: e["it"])
        hist = res.history()
        assert len(evs) == hist.shape[0]
        np.testing.assert_allclose([e["loss"] for e in evs], hist,
                                   rtol=1e-6)


# ----------------------------------------------------------- GAME events
class TestGameStream:
    def test_descent_emits_one_event_per_update(self, rng):
        from photon_tpu.game import (FixedEffectConfig, GameData,
                                     GameEstimator, RandomEffectConfig)

        n, d = 400, 4
        ent = rng.integers(0, 12, size=n)
        Xf = rng.normal(size=(n, d)).astype(np.float32)
        Xr = np.ones((n, 1), np.float32)
        yv = (rng.uniform(size=n) < 0.5).astype(np.float32)
        data = GameData.build(yv, shards={"fixed": Xf, "bias": Xr},
                              entity_ids={"e": ent})
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs={
                "fixed": FixedEffectConfig("fixed", _CFG),
                "per_e": RandomEffectConfig("e", "bias", _CFG),
            },
            n_sweeps=2)
        r = telemetry.start_run("game")
        results = est.fit(data)
        telemetry.finish_run()
        descent = results[0].descent
        evs = [e for e in r.iterations if e["solver"] == "game_descent"]
        assert len(evs) == len(descent.objective_history) == 4
        np.testing.assert_allclose([e["loss"] for e in evs],
                                   descent.objective_history, rtol=1e-6)
        assert [(e["sweep"], e["coordinate"]) for e in evs] == \
            [(0, "fixed"), (0, "per_e"), (1, "fixed"), (1, "per_e")]
        assert r.counters["game.coordinate_updates"] == 4.0
        assert r.counters["game.sweeps"] == 2.0
        assert r.counters["game.grid_points"] == 1.0

    def test_re_pipeline_counters_and_spans(self, rng):
        """The round-8 game_re.* spine: per-block upload/solve/readback
        spans + the pipeline/straggler counters, surfaced by
        report_compact() (the piece BENCH_*.json embeds)."""
        from photon_tpu.game import GameData, RandomEffectCoordinate, \
            RandomEffectDataset

        n_entities, d = 10, 3
        ent = np.repeat(np.arange(n_entities), 20)
        n = ent.shape[0]
        X = rng.normal(size=(n, d)).astype(np.float32)
        yv = (rng.uniform(size=n) < 0.5).astype(np.float32)
        data = GameData.build(yv, {"s": X}, {"e": ent})
        ds = RandomEffectDataset.build(data, "e", "s")
        cfg = OptimizerConfig(max_iters=30, tolerance=1e-7, reg=reg.l2(),
                              reg_weight=1e-2, history=4)
        coord = RandomEffectCoordinate(
            ds, TaskType.LOGISTIC_REGRESSION, cfg,
            pipeline_depth=1, straggler_budget=1)
        r = telemetry.start_run("game_re")
        coord.train(np.zeros(n, np.float32))
        telemetry.finish_run()
        assert r.counters["game_re.blocks"] >= 1.0
        assert "game_re.readback_wait_ns" in r.counters
        assert r.gauges["game_re.blocks_in_flight"] >= 1
        # budget=1 guarantees a straggler tail on this problem
        assert r.counters["game_re.straggler_entities"] >= 1.0
        assert r.counters["game_re.tail_resolves"] >= 1.0
        assert "game_re.iters_saved" in r.counters
        totals = r.span_totals()
        for name in ("game_re.upload", "game_re.solve",
                     "game_re.readback", "game_re.tail_solve"):
            assert name in totals, name
        compact = r.report_compact()
        assert "game_re.blocks" in compact["counters"]
        assert "game_re.readback_wait_ns" in compact["counters"]


# ------------------------------------------------------- photon_logger fix
class TestPhotonLoggerLevels:
    def test_explicit_level_survives_reconfiguration(self):
        from photon_tpu.utils.logging import photon_logger

        log = photon_logger("t_lvl_a", level=logging.DEBUG)
        assert log.level == logging.DEBUG
        # a later default-level call (e.g. a second driver phase adding a
        # file handler) must NOT silently reset the effective level
        log = photon_logger("t_lvl_a")
        assert log.level == logging.DEBUG
        # an explicit new level still wins
        log = photon_logger("t_lvl_a", level=logging.WARNING)
        assert log.level == logging.WARNING

    def test_first_call_defaults_to_info(self):
        from photon_tpu.utils.logging import photon_logger

        assert photon_logger("t_lvl_b").level == logging.INFO

    def test_env_override_wins(self, monkeypatch):
        from photon_tpu.utils.logging import photon_logger

        monkeypatch.setenv("PHOTON_TPU_LOG_LEVEL", "warning")
        assert photon_logger("t_lvl_c",
                             level=logging.DEBUG).level == logging.WARNING
        monkeypatch.setenv("PHOTON_TPU_LOG_LEVEL", "15")
        assert photon_logger("t_lvl_d").level == 15
        monkeypatch.setenv("PHOTON_TPU_LOG_LEVEL", "not-a-level")
        assert photon_logger("t_lvl_e").level == logging.INFO

    def test_handlers_stay_notset(self, tmp_path):
        from photon_tpu.utils.logging import photon_logger

        log = photon_logger("t_lvl_f", output_dir=str(tmp_path),
                            level=logging.DEBUG)
        assert log.handlers and all(h.level == logging.NOTSET
                                    for h in log.handlers)

    def test_stall_log_still_fires_with_stable_text(self, caplog):
        from photon_tpu.data.dataset import _log_stream_stall

        r = telemetry.start_run("t")
        with caplog.at_level(logging.INFO, logger="photon_tpu.streamed"):
            _log_stream_stall(stall=1.0, compute=0.2, n_chunks=4,
                              prefetch=2)
        telemetry.finish_run()
        assert any("deeper prefetch or bigger chunks" in rec.message
                   for rec in caplog.records)
        assert r.counters["stream.stalled_passes"] == 1.0


# ----------------------------------------------------- off-is-free contract
class TestOffIsFreeContract:
    def test_registered_and_clean(self):
        from photon_tpu.analysis.contracts import check_contract
        from photon_tpu.analysis.registry import load_registry

        specs = load_registry()
        assert "telemetry_off_is_free" in specs
        spec = specs["telemetry_off_is_free"]
        assert "telemetry" in spec.tags
        violations = check_contract(spec)
        assert violations == [], "\n".join(str(v) for v in violations)

    def test_tap_on_trace_contains_callback_off_does_not(self, rng):
        """The mechanism itself: armed -> debug_callback in the jaxpr;
        disarmed -> absent (what the contract pins at registry level)."""
        import jax.numpy as jnp

        from photon_tpu.analysis import count_primitives
        from photon_tpu.optim.lbfgs import minimize_lbfgs_margin
        from photon_tpu.models.training import make_objective

        X, y = _problem(rng, n=64, d=5)
        batch = make_batch(X, y)
        obj = make_objective(TaskType.LOGISTIC_REGRESSION, _CFG, 5)
        w0 = jnp.zeros((5,), jnp.float32)

        def fn(b, w):
            return minimize_lbfgs_margin(obj, b, w, max_iters=3)

        closed_off = jax.make_jaxpr(fn)(batch, w0)
        assert count_primitives(closed_off,
                                {"debug_callback"}) == {}
        telemetry.set_resident_tap(True)
        try:
            closed_on = jax.make_jaxpr(fn)(batch, w0)
            n_cb = count_primitives(closed_on, {"debug_callback"})
            assert n_cb.get("debug_callback", 0) >= 2  # init + loop body
        finally:
            telemetry.set_resident_tap(False)


# ------------------------------------------------------- serving stream
class TestServingStream:
    def test_dispatcher_emits_serving_events_and_counters(self, rng,
                                                          tmp_path):
        """The round-9 serving.* spine: per-flush spans, request/batch/
        cold-miss counters, the serving_batch JSONL event stream, and
        the close-time latency gauges."""
        from photon_tpu import serving
        from photon_tpu.serving.__main__ import build_demo_model

        model, _ = build_demo_model(seed=3)
        store = serving.CoefficientStore.from_game_model(model)
        ladder = serving.ProgramLadder(store, ladder=(4,),
                                       sparse_k={"member": 3})
        d_f = int(model["fixed"].model.coefficients.dim)
        jsonl = str(tmp_path / "serve.jsonl")
        r = telemetry.start_run("serve", jsonl_path=jsonl)
        disp = serving.MicroBatchDispatcher(ladder, max_batch=4,
                                            max_delay_us=1000)
        try:
            futs = [disp.submit(serving.ScoreRequest(
                features={"global": rng.normal(size=d_f).astype(np.float32),
                          "member": (np.asarray([0, 1], np.int32),
                                     np.asarray([1.0, -1.0], np.float32))},
                entities={"memberId": "e000" if i % 2 else "cold"}))
                for i in range(6)]
            [f.result(timeout=30) for f in futs]
        finally:
            disp.close()
            telemetry.finish_run()
        assert r.counters["serving.requests"] == 6.0
        assert r.counters["serving.batches"] >= 2.0
        assert r.counters["serving.cold_misses"] == 3.0
        assert "serving.batch_fill" in r.gauges
        assert r.gauges["serving.latency_p50_ms"] <= \
            r.gauges["serving.latency_p99_ms"]
        assert any(s.name == "serving.flush" for s in r.spans)
        batches = list(telemetry.read_jsonl(jsonl, kind="serving_batch"))
        assert sum(e["rows"] for e in batches) == 6
        assert all(e["bucket"] == 4 for e in batches)

    def test_docstring_is_single_source_of_truth_for_names(self, rng):
        """Every serving.* counter/gauge a live dispatcher emits must be
        listed in photon_tpu/telemetry/__init__'s docstring — the
        documented registry of counter names."""
        import photon_tpu.telemetry as t
        from photon_tpu import serving
        from photon_tpu.serving.__main__ import build_demo_model

        model, _ = build_demo_model(seed=4)
        store = serving.CoefficientStore.from_game_model(model)
        ladder = serving.ProgramLadder(store, ladder=(4,),
                                       sparse_k={"member": 3})
        d_f = int(model["fixed"].model.coefficients.dim)
        r = telemetry.start_run("doc")
        disp = serving.MicroBatchDispatcher(ladder, max_batch=4,
                                            max_delay_us=500)
        try:
            disp.score(serving.ScoreRequest(
                features={"global": rng.normal(size=d_f).astype(np.float32),
                          "member": (np.asarray([0], np.int32),
                                     np.asarray([1.0], np.float32))},
                entities={"memberId": "nope"}), timeout=30)
        finally:
            disp.close()
            telemetry.finish_run()
        doc = t.__doc__
        emitted = [k for k in list(r.counters) + list(r.gauges)
                   if k.startswith("serving.")]
        assert emitted, "dispatcher emitted no serving.* telemetry"
        for name in emitted:
            short = name.split(".", 1)[1]
            assert short in doc, (
                f"{name} is not listed in telemetry/__init__'s docstring "
                "— the single source of truth for counter names")


# --------------------------------------- round 19: request tracing
class TestRequestTracing:
    def test_disarmed_is_free(self):
        """The off state: begin returns None, every other entry point is
        None-safe, no reservoir exists."""
        assert not trace.armed()
        assert trace.begin("queue_wait") is None
        trace.hop(None, "device_flush")
        trace.finish(None)
        with trace.attach(None):
            assert trace.current() is None
        assert trace.reservoir() is None

    def test_slow_hop_is_named_and_breakdown_sums(self):
        """The acceptance pin's trace-level half: a deterministically
        slow hop must be NAMED by the slowest exemplar, and the hop
        breakdown must sum to the trace total (switch closes the previous
        hop — no gap, no double count)."""
        with trace.tracing(k=4) as res:
            tc = trace.begin("queue_wait")
            trace.hop(tc, "device_flush")
            time.sleep(0.03)  # the injected slow hop
            trace.hop(tc, "retire_wait")
            trace.finish(tc)
        ex = res.slowest()
        assert ex["slowest_hop"] == "device_flush"
        assert [h["name"] for h in ex["hops"]] == \
            ["queue_wait", "device_flush", "retire_wait"]
        assert sum(ex["breakdown_ms"].values()) == \
            pytest.approx(ex["total_ms"], abs=5.0)
        assert ex["breakdown_ms"]["device_flush"] >= 25.0

    def test_reservoir_keeps_k_slowest(self):
        res = trace.ExemplarReservoir(k=3)
        for i in range(10):
            tc = trace.TraceContext()
            tc.switch("h")
            tc.finish()
            tc.start_ns = 0  # pin a deterministic total
            tc.end_ns = (i + 1) * 1_000_000
            res.offer(tc)
        assert res.n_offered == 10
        assert [e["total_ms"] for e in res.snapshot()] == [10.0, 9.0, 8.0]

    def test_finish_is_one_shot(self):
        """A timed-out failover attempt's late retire must not deposit a
        second exemplar or reopen the hop list."""
        with trace.tracing(k=8) as res:
            tc = trace.begin("queue_wait")
            trace.finish(tc)
            trace.finish(tc)  # the straggler thread's late finish
            n_hops = len(tc.hops)
            tc.switch("late_hop")  # mutation after finish: no-op
            assert len(tc.hops) == n_hops
        assert res.n_offered == 1

    def test_contextvar_propagation(self):
        """attach() binds the fleet's trace as the thread's current one;
        begin() inside the block CONTINUES it (how one trace crosses
        fleet → dispatcher.submit), and a fresh one starts outside."""
        with trace.tracing(k=2):
            tc = trace.begin("fleet_route")
            with trace.attach(tc):
                assert trace.current() is tc
                assert trace.begin("queue_wait") is tc
            assert trace.current() is None
            assert trace.begin("queue_wait") is not tc

    def test_tracing_restores_surrounding_state(self):
        outer = trace.arm_tracing()
        try:
            with trace.tracing(k=2) as inner:
                assert trace.reservoir() is inner
            assert trace.reservoir() is outer and trace.armed()
        finally:
            trace.disarm_tracing()

    def test_trace_disabled_scopes_an_armed_session(self):
        with trace.tracing(k=2):
            with trace.trace_disabled():
                assert trace.begin("queue_wait") is None
            assert trace.begin("queue_wait") is not None


# --------------------------------------- round 19: quantile digest
class TestQuantileDigest:
    def test_quantiles_within_1pct_of_exact_on_1e5(self):
        """The dispatcher-regression satellite pin: digest p50/p95/p99 vs
        exact on a 1e5-sample synthetic latency distribution, relative
        error <= 1% (the default 0.5% bucketing leaves headroom)."""
        rng = np.random.default_rng(7)
        lat_ns = rng.lognormal(mean=15.0, sigma=1.0, size=100_000)
        d = QuantileDigest()
        d.add_many(lat_ns)
        for q in (0.50, 0.95, 0.99):
            exact = float(np.quantile(lat_ns, q))
            got = d.quantile(q)
            assert abs(got - exact) / exact <= 0.01, q

    def test_merge_is_exact(self):
        """Same bucketing -> merged counts are bit-identical to a single
        digest over the concatenation (how ReplicaFleet pools replicas)."""
        rng = np.random.default_rng(11)
        a = rng.lognormal(14.0, 1.0, 5_000)
        b = rng.lognormal(16.0, 0.5, 5_000)
        d1, d2, dall = QuantileDigest(), QuantileDigest(), QuantileDigest()
        d1.add_many(a)
        d2.add_many(b)
        d1.merge(d2)
        dall.add_many(np.concatenate([a, b]))
        assert np.array_equal(d1.counts, dall.counts)
        assert d1.n == dall.n
        assert d1.quantile(0.99) == dall.quantile(0.99)

    def test_merge_refuses_different_bucketing(self):
        with pytest.raises(ValueError, match="bucketing"):
            QuantileDigest().merge(QuantileDigest(rel_error=0.01))

    def test_memory_is_fixed(self):
        """O(1) memory forever — the reason the dispatcher's append-only
        latency list is gone."""
        d = QuantileDigest()
        n_buckets = d.counts.size
        assert n_buckets < 3_000  # ~16 KB of int64
        d.add_many(np.random.default_rng(0).lognormal(15, 1, 50_000))
        assert d.counts.size == n_buckets

    def test_stats_ms_shape(self):
        d = QuantileDigest()
        assert d.stats_ms() == {"n": 0, "p50_ms": None, "p95_ms": None,
                                "p99_ms": None, "mean_ms": None}
        d.add(2_000_000.0)  # 2 ms in ns
        s = d.stats_ms()
        assert s["n"] == 1
        assert s["p50_ms"] == pytest.approx(2.0, rel=0.02)
        assert s["mean_ms"] == pytest.approx(2.0, rel=1e-6)


# --------------------------------------- round 19: health plane
class TestHealthPlane:
    def test_watch_rule_thresholds_are_inclusive(self):
        r = WatchRule("shed", "s", 0.05, 0.25, kind="ratio",
                      denominator="a")
        assert r.evaluate({"s": 0, "a": 100})["verdict"] == OK
        assert r.evaluate({"s": 5, "a": 100})["verdict"] == DEGRADED
        assert r.evaluate({"s": 25, "a": 100})["verdict"] == CRITICAL
        d = WatchRule("deaths", "d", 1, 4, kind="delta")
        assert d.evaluate({})["verdict"] == OK
        assert d.evaluate({"d": 1})["verdict"] == DEGRADED
        assert d.evaluate({"d": 4})["verdict"] == CRITICAL

    def test_monitor_windows_diff_counters(self):
        """Each snapshot's rules see ONLY the inter-snapshot delta: a
        healthy first window then a shed storm flips OK -> CRITICAL."""
        run = telemetry.start_run("health_mon")
        try:
            mon = HealthMonitor()
            telemetry.count("serving.admitted", 100)
            rep1 = mon.snapshot(run)
            assert rep1.verdict == OK
            telemetry.count("serving.admitted", 100)
            telemetry.count("serving.shed", 60)
            rep2 = mon.snapshot(run)
            shed = next(r for r in rep2.rules if r["rule"] == "shed_rate")
            assert shed["value"] == pytest.approx(0.6)
            assert rep2.verdict == CRITICAL
        finally:
            telemetry.finish_run()

    def test_staleness_rides_the_gauge(self):
        run = telemetry.start_run("health_stale")
        try:
            telemetry.gauge("continual.staleness_s", 12.5)
            rep = snapshot(run)
            assert rep.staleness_s == 12.5
            assert "photon_tpu_serving_staleness_seconds 12.5" in \
                rep.prometheus()
        finally:
            telemetry.finish_run()

    def test_no_run_snapshot_is_ok_and_empty(self):
        rep = HealthMonitor().snapshot(run=None)
        assert rep.verdict == OK
        assert rep.name == "(no run)"
        assert rep.rates == {} and rep.staleness_s is None

    def test_report_from_jsonl_and_torn_file(self, tmp_path):
        path = str(tmp_path / "r.jsonl")
        telemetry.start_run("offline", jsonl_path=path)
        telemetry.count("serving.admitted", 10)
        telemetry.gauge("continual.staleness_s", 3.0)
        telemetry.finish_run()
        rep = report_from_jsonl(path)
        assert rep.name == "offline"
        assert rep.staleness_s == 3.0
        assert rep.counters["serving.admitted"] == 10
        prom = rep.prometheus()
        assert "photon_tpu_serving_admitted_total 10" in prom
        assert "photon_tpu_health_verdict 0" in prom

        # torn: run_end never landed + a mid-record tear — still a
        # report, never a crash
        with open(path) as fh:
            lines = [ln for ln in fh.read().splitlines()
                     if '"run_end"' not in ln]
        torn = str(tmp_path / "torn.jsonl")
        with open(torn, "w") as fh:
            fh.write("\n".join(lines) + "\n" + '{"type": "co')
        rep2 = report_from_jsonl(torn)
        assert rep2.verdict == OK
        assert rep2.counters == {} and rep2.window_s == 0.0


# --------------------------------------- round 19: cross-rank aggregation
class TestCrossRankAggregation:
    def _write_rank(self, path, name, started_unix, spans, counters,
                    complete=True):
        """Hand-crafted rank JSONL (same record shapes run.Run emits) —
        full control over wall clocks and tears."""
        with open(path, "w") as fh:
            fh.write(json.dumps({"type": "run_start", "name": name,
                                 "started_unix": started_unix}) + "\n")
            for p, secs, t_s in spans:
                fh.write(json.dumps({"type": "span", "name": p, "path": p,
                                     "seconds": secs, "depth": 0,
                                     "t_s": t_s}) + "\n")
            if complete:
                fh.write(json.dumps({"type": "run_end", "duration_s": 5.0,
                                     "counters": counters,
                                     "gauges": {}}) + "\n")

    def test_merge_names_straggler_by_min_barrier_wait(self, tmp_path):
        """Under a barrier the straggler arrives last and waits LEAST —
        rank 1 here, corroborated by its larger decode load."""
        self._write_rank(tmp_path / "p0.jsonl", "r0", 100.0,
                         [("parallel.barrier_wait", 2.0, 3.0)],
                         {"ingest.chunks": 4})
        self._write_rank(tmp_path / "p1.jsonl", "r1", 100.0,
                         [("parallel.barrier_wait", 0.1, 4.9)],
                         {"ingest.chunks": 8})
        rep = aggregate_cluster(str(tmp_path))
        assert rep["complete"]
        assert rep["n_ranks"] == 2 == rep["n_expected"]
        assert rep["skew"]["straggler_rank"] == 1
        assert "rank 1 is the straggler" in rep["skew"]["attribution"]
        assert rep["counters_total"]["ingest.chunks"] == 12
        assert rep["skew"]["barrier_wait_s"]["spread"] == \
            pytest.approx(1.9)

    def test_straggler_falls_back_to_decode_work(self, tmp_path):
        self._write_rank(tmp_path / "p0.jsonl", "r0", 100.0, [],
                         {"ingest.chunks": 2})
        self._write_rank(tmp_path / "p1.jsonl", "r1", 100.0, [],
                         {"ingest.chunks": 9})
        rep = aggregate_cluster(str(tmp_path))
        assert rep["skew"]["straggler_rank"] == 1
        assert rep["skew"]["decode_chunks"]["spread"] == 7

    def test_torn_mid_record_rank_keeps_prefix(self, tmp_path):
        """A rank killed mid-write: its torn tail drops, its prefix still
        contributes, the cluster report is marked incomplete."""
        self._write_rank(tmp_path / "p0.jsonl", "r0", 100.0,
                         [("solve", 1.0, 0.5)], {"ingest.chunks": 3})
        with open(tmp_path / "p1.jsonl", "w") as fh:
            fh.write(json.dumps({"type": "run_start", "name": "r1",
                                 "started_unix": 100.2}) + "\n")
            fh.write(json.dumps({"type": "span", "name": "solve",
                                 "path": "solve", "seconds": 0.7,
                                 "depth": 0, "t_s": 0.1}) + "\n")
            fh.write('{"type": "span", "path": "x", "secon')  # the kill
        rep = aggregate_cluster(str(tmp_path), expect_ranks=2)
        assert rep["n_ranks"] == 2
        assert not rep["complete"]  # rank 1 never wrote run_end
        assert rep["missing_ranks"] == []
        assert rep["ranks"]["1"]["complete"] is False
        assert rep["ranks"]["1"]["span_totals"] == {"solve": 0.7}
        assert rep["counters_total"] == {"ingest.chunks": 3.0}

    def test_missing_rank_is_named_not_crashed(self, tmp_path):
        self._write_rank(tmp_path / "p0.jsonl", "r0", 100.0, [], {})
        self._write_rank(tmp_path / "p2.jsonl", "r2", 100.0, [], {})
        rep = aggregate_cluster(str(tmp_path))  # n_expected inferred: 3
        assert rep["n_expected"] == 3
        assert rep["missing_ranks"] == [1]
        assert not rep["complete"]
        rep2 = aggregate_cluster(str(tmp_path), expect_ranks=4)
        assert rep2["missing_ranks"] == [1, 3]

    def test_clock_skewed_timelines_align_on_wall_clock(self, tmp_path):
        """Rank 1 started 50 s later: its EARLY span must land after
        rank 0's late span on the merged wall clock, and the start
        spread is reported as clock skew."""
        self._write_rank(tmp_path / "p0.jsonl", "r0", 1000.0,
                         [("solve", 1.0, 10.0)], {})
        self._write_rank(tmp_path / "p1.jsonl", "r1", 1050.0,
                         [("solve", 1.0, 2.0)], {})
        rep = aggregate_cluster(str(tmp_path))
        assert rep["clock_skew_s"] == pytest.approx(50.0)
        tl = rep["timeline"]
        assert [e["rank"] for e in tl] == [0, 1]
        assert tl[0]["start_unix"] == pytest.approx(1010.0)
        assert tl[1]["start_unix"] == pytest.approx(1052.0)

    def test_rank_files_and_dict_source(self, tmp_path):
        self._write_rank(tmp_path / "p0.jsonl", "r0", 1.0, [], {})
        (tmp_path / "not_a_rank.jsonl").write_text("{}\n")
        files = rank_files(str(tmp_path))
        assert list(files) == [0]
        rep = aggregate_cluster({0: files[0]})
        assert rep["n_ranks"] == 1 and rep["complete"]
