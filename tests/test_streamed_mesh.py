"""Mesh-sharded streamed training (the ISSUE 2 tentpole): an out-of-HBM
ChunkedBatch trains on a whole (virtual 8-device CPU) mesh — every chunk
row-sharded across the mesh, chunk partials device-local under shard_map,
ONE hierarchical psum per evaluation.

The contract under test: streamed-mesh == streamed single-chip == resident
to f32 accumulation tolerance, across L-BFGS and OWL-QN, a row count that
does not divide the mesh (weight-0 padded tail shard), and a hybrid
replica×data mesh; plus the communication-pattern pin (chunk programs
compile to ZERO collectives, the evaluation finish to exactly ONE
all-reduce) and the driver's pooled-budget auto-trip.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.dataset import chunk_batch, make_batch
from photon_tpu.data.matrix import SparseRows
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.ops.objective import Objective
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.regularization import elastic_net, l1, l2
from photon_tpu.parallel.mesh import (
    fetch_local_rows,
    local_row_slots,
    make_hybrid_mesh,
    shard_local_rows,
    shard_rows,
)


def _problem(rng, task, n=2048, d=10, sparse=False):
    if sparse:
        k = 4
        ind = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        X = SparseRows(ind, val, d)
        Xd = np.zeros((n, d), np.float32)
        np.add.at(Xd, (np.arange(n)[:, None], ind), val)
    else:
        X = Xd = rng.normal(size=(n, d)).astype(np.float32)
    w_true = (rng.normal(size=d) * 0.5).astype(np.float32)
    margin = Xd @ w_true
    if task is TaskType.LOGISTIC_REGRESSION:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
            np.float32)
    else:
        y = (margin + rng.normal(size=n) * 0.3).astype(np.float32)
    wt = rng.uniform(0.5, 2.0, n).astype(np.float32)
    off = (rng.normal(size=n) * 0.1).astype(np.float32)
    return make_batch(X, y, wt, off)


@pytest.fixture(scope="module")
def hybrid_mesh():
    return make_hybrid_mesh(n_replicas=2, devices=jax.devices("cpu"))


# Drop this module's compiled 8-device shard_map programs at teardown —
# without this the accumulated executables make the virtual-CPU XLA client
# segfault compiling LATER unrelated programs (test_tuning's GP
# while_loop). The fixture lives in conftest.py now; the marker opts in.
pytestmark = pytest.mark.release_programs


TASKS = [TaskType.LOGISTIC_REGRESSION, TaskType.LINEAR_REGRESSION]


# ---------------------------------------------------------------- helpers
class TestRowSlotHelpers:
    def test_shard_fetch_round_trip(self, rng, mesh8):
        host = rng.normal(size=(300, 3)).astype(np.float32)  # 300 % 8 != 0
        arr = shard_rows(host, mesh8)
        assert arr.shape == (304, 3)  # padded to the device multiple
        np.testing.assert_array_equal(np.asarray(arr)[:300], host)
        np.testing.assert_array_equal(np.asarray(arr)[300:], 0.0)
        local = fetch_local_rows(arr, mesh8)
        assert local.shape == (8, 38, 3)  # one slice per (local) slot
        back = shard_local_rows(local, mesh8)
        np.testing.assert_array_equal(np.asarray(back), np.asarray(arr))

    def test_local_slots_single_process(self, mesh8):
        assert local_row_slots(mesh8) == list(range(8))

    def test_explicit_pad_rows(self, mesh8):
        arr = shard_rows(np.ones(16, np.float32), mesh8, pad_rows=32)
        assert arr.shape == (32,)
        assert float(jnp.sum(arr)) == 16.0


class TestMeshChunkIterator:
    def test_mesh_chunks_shard_and_pad(self, rng, mesh8):
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, n=1000)
        cb = chunk_batch(batch, 300)
        assert cb.mesh_chunk_rows(mesh8) == 304
        seen = []
        for i, b in cb.iter_device(mesh=mesh8):
            seen.append(i)
            assert b.X.shape == (304, 10)
            assert len(b.y.sharding.device_set) == 8
            # pad rows carry weight 0, so no reduction can see them
            assert float(jnp.sum(b.weights[300:])) == 0.0
        assert seen == [0, 1, 2, 3]
        # total real weight survives the per-chunk mesh padding exactly
        total = sum(float(jnp.sum(b.weights))
                    for _, b in cb.iter_device(mesh=mesh8))
        np.testing.assert_allclose(total, float(np.sum(cb.weights)),
                                   rtol=1e-6)

    def test_stall_logging_signal(self, caplog):
        """The upload-vs-compute imbalance logs at INFO exactly when
        transfer stalls exceed compute over a multi-chunk pass."""
        import logging

        from photon_tpu.data.dataset import _log_stream_stall

        with caplog.at_level(logging.INFO, logger="photon_tpu.streamed"):
            _log_stream_stall(stall=0.2, compute=1.0, n_chunks=4,
                              prefetch=2)  # compute-bound: silent
            assert not caplog.records
            _log_stream_stall(stall=1.0, compute=0.2, n_chunks=1,
                              prefetch=2)  # single chunk: nothing to overlap
            assert not caplog.records
            _log_stream_stall(stall=1.0, compute=0.2, n_chunks=4,
                              prefetch=2)  # upload-bound: the signal
        assert any("deeper prefetch or bigger chunks" in r.message
                   for r in caplog.records)

    def test_prefetch_depths_yield_same_chunks(self, rng, mesh8):
        cb = chunk_batch(_problem(rng, TaskType.LOGISTIC_REGRESSION, n=600),
                         200)
        for prefetch in (1, 2, 4, 99):
            ys = [np.asarray(b.y) for _, b in cb.iter_device(
                mesh=mesh8, prefetch=prefetch)]
            assert len(ys) == 3
            np.testing.assert_array_equal(np.concatenate(ys)[:600], cb.y[:600])
        # single-device path honors the knob too
        ys = [np.asarray(b.y) for _, b in cb.iter_device(prefetch=3)]
        np.testing.assert_array_equal(np.concatenate(ys), cb.y)


# ----------------------------------------------------------------- parity
class TestStreamedMeshParity:
    @pytest.mark.parametrize("task", TASKS)
    def test_lbfgs_three_way(self, rng, task, mesh8):
        """resident == streamed single-chip == streamed mesh, on a row
        count that divides neither the chunk size nor the mesh."""
        batch = _problem(rng, task, n=1900)
        cb = chunk_batch(batch, 300)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7, reg=l2(),
                              reg_weight=0.5)
        m_r, r_r = train_glm(batch, task, cfg)
        m_s, r_s = train_glm(cb, task, cfg)
        m_m, r_m = train_glm(cb, task, cfg, mesh=mesh8)
        assert bool(r_m.converged) == bool(r_r.converged)
        np.testing.assert_allclose(float(r_m.value), float(r_r.value),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=2e-5)
        np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                   np.asarray(m_s.coefficients.means),
                                   rtol=2e-3, atol=2e-5)

    @pytest.mark.parametrize("task", TASKS)
    def test_owlqn_three_way(self, rng, task, mesh8):
        """OWL-QN's candidate-lane ladder shards the same way."""
        batch = _problem(rng, task, n=1900)
        cb = chunk_batch(batch, 300)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7,
                              reg=elastic_net(0.5), reg_weight=0.3)
        m_r, r_r = train_glm(batch, task, cfg)
        m_s, _ = train_glm(cb, task, cfg)
        m_m, r_m = train_glm(cb, task, cfg, mesh=mesh8)
        np.testing.assert_allclose(float(r_m.value), float(r_r.value),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=2e-4)
        np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                   np.asarray(m_s.coefficients.means),
                                   rtol=2e-3, atol=2e-4)

    def test_pure_l1_sparsity_preserved(self, rng, mesh8):
        """The orthant projection's exact zeros survive the mesh psum."""
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION)
        cb = chunk_batch(batch, 512)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7, reg=l1(),
                              reg_weight=8.0)
        m_r, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
        m_m, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg,
                           mesh=mesh8)
        zeros_r = np.asarray(m_r.coefficients.means) == 0.0
        zeros_m = np.asarray(m_m.coefficients.means) == 0.0
        assert zeros_m.any()
        np.testing.assert_array_equal(zeros_r, zeros_m)

    def test_sparse_rows_mesh(self, rng, mesh8):
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, sparse=True)
        cb = chunk_batch(batch, 512)
        cfg = OptimizerConfig(max_iters=50, tolerance=1e-7, reg=l2(),
                              reg_weight=0.3)
        m_r, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
        m_m, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg,
                           mesh=mesh8)
        np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=2e-5)

    def test_hybrid_replica_data_mesh(self, rng, hybrid_mesh):
        """2-D replica×data mesh: the per-evaluation psum runs over BOTH
        axes (hierarchical lowering), same answer."""
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, n=1900)
        cb = chunk_batch(batch, 300)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7, reg=l2(),
                              reg_weight=0.5)
        m_r, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
        m_h, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg,
                           mesh=hybrid_mesh)
        np.testing.assert_allclose(np.asarray(m_h.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=2e-5)

    def test_normalization_mesh(self, rng, mesh8):
        """The norm-shifts gsum partial rides the same single psum."""
        from photon_tpu.data.normalization import (
            NormalizationContext,
            NormalizationType,
        )

        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION)
        norm = NormalizationContext.build(
            np.asarray(batch.X),
            NormalizationType.SCALE_WITH_STANDARD_DEVIATION)
        cb = chunk_batch(batch, 512)
        cfg = OptimizerConfig(max_iters=50, tolerance=1e-7, reg=l2(),
                              reg_weight=0.2)
        m_r, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                           normalization=norm)
        m_m, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg,
                           mesh=mesh8, normalization=norm)
        np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=1e-4)

    def test_host_chunks_stay_numpy(self, rng, mesh8):
        """The peak-HBM contract survives the mesh: after a full sharded
        streamed solve the dataset is still host numpy."""
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION)
        cb = chunk_batch(batch, 256)
        cfg = OptimizerConfig(max_iters=15, tolerance=1e-7, reg=l2(),
                              reg_weight=0.5)
        model, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg,
                             mesh=mesh8)
        for c in cb.X.chunks:
            assert isinstance(c, np.ndarray)
        assert isinstance(cb.y, np.ndarray)
        # the returned coefficients are NOT mesh-committed: downstream
        # scoring runs on the default device
        w = model.coefficients.means
        assert len(w.sharding.device_set) == 1


# -------------------------------------------------- communication pattern
class TestCollectivePattern:
    def _example(self, rng, mesh):
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, n=256)
        cb = chunk_batch(batch, 256)
        obj = Objective(TaskType.LOGISTIC_REGRESSION, l2=0.4)
        w = jnp.zeros((10,), jnp.float32)
        from photon_tpu.optim.streamed import _MeshStream

        be = _MeshStream(cb, mesh)
        b = cb.mesh_chunk(0, mesh)
        return be, obj, w, b

    def test_chunk_program_has_no_collective(self, rng, mesh8):
        """The per-chunk partial program is communication-FREE: partials
        stay device-local until the evaluation's single finishing psum.
        Pinned with the shared jaxpr walker (photon_tpu.analysis)."""
        from photon_tpu.analysis import collective_counts

        be, obj, w, b = self._example(rng, mesh8)
        jaxpr = jax.make_jaxpr(
            lambda o, wv, bv: be.ops.chunk_init(o, wv, bv))(obj, w, b)
        assert not collective_counts(jaxpr)
        compiled = be.ops.chunk_init.lower(obj, w, b).compile()
        hlo = compiled.as_text()
        for bad in ("all-reduce(", "all-to-all(", "collective-permute(",
                    "all-gather(", "reduce-scatter("):
            assert bad not in hlo, f"unexpected collective {bad}"

    def test_finish_is_one_psum(self, rng, mesh8):
        """One evaluation = one hierarchical psum: value and gradient
        partials ride the SAME collective (the treeAggregate). Pinned at
        the jaxpr level — whether XLA's combiner then emits the variadic
        all-reduce as one HLO op is a backend concern (the CPU test
        backend splits it; see test_multihost's pre-existing pin)."""
        from photon_tpu.analysis import collective_counts

        be, obj, w, b = self._example(rng, mesh8)
        _, parts = be.ops.chunk_init(obj, w, b)
        jaxpr = jax.make_jaxpr(
            lambda o, wv, pv: be.ops.finish(o, wv, pv))(obj, w, parts)
        counts = collective_counts(jaxpr)
        assert counts == {"psum": 1}, \
            f"expected 1 psum per evaluation, traced {dict(counts)}"

    def test_trial_totals_are_one_psum(self, rng, mesh8):
        """A line-search trial's (φ, φ') totals also close with a single
        psum — trials never multiply the collective count."""
        from photon_tpu.analysis import collective_counts

        be, obj, w, b = self._example(rng, mesh8)
        _, (wl, wd) = be.ops.chunk_dz_phi(obj, jnp.ones(10), b.offsets,
                                          np.float32(1.0), b)
        jaxpr = jax.make_jaxpr(
            lambda t: be.ops.psum_tree(t))((wl, wd))
        counts = collective_counts(jaxpr)
        assert counts == {"psum": 1}, \
            f"expected 1 psum per trial, traced {dict(counts)}"

    def test_finish_matches_resident_value_grad(self, rng, mesh8):
        """Accumulated sharded chunk partials + the single psum == the
        resident value_and_grad, exactly the treeAggregate contract."""
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, n=1024)
        cb = chunk_batch(batch, 300)
        obj = Objective(TaskType.LOGISTIC_REGRESSION, l2=0.4)
        w = jnp.asarray(rng.normal(size=10).astype(np.float32) * 0.3)
        from photon_tpu.optim.streamed import _MeshStream, _acc

        be = _MeshStream(cb, mesh8)
        acc = None
        for _, b in be.iter_chunks():
            _, parts = be.ops.chunk_init(obj, w, b)
            acc = parts if acc is None else _acc(acc, parts)
        f_m, g_m = be.finish(obj, w, acc)
        f_r, g_r = obj.value_and_grad(w, batch)
        np.testing.assert_allclose(float(f_m), float(f_r), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g_m), np.asarray(g_r),
                                   rtol=1e-4, atol=1e-4)


# ------------------------------------------------------------------ driver
class TestPooledBudget:
    def test_detect_budget_uses_mesh_devices(self, mesh8):
        from photon_tpu.drivers.train import _detect_hbm_budget

        per_chip = _detect_hbm_budget(mesh8)
        assert per_chip > 0
        # CPU test devices either report a limit or fall back to 16 GiB;
        # either way the mesh path must agree with itself
        assert per_chip == _detect_hbm_budget(mesh8)

    def test_resolution_pools_budget_and_logs(self, rng, mesh8, caplog):
        """A dataset over the per-chip budget but under the pooled budget
        stays resident under the mesh; over the pooled budget it streams —
        and both verdicts are logged at INFO."""
        import logging

        from photon_tpu.data.index_map import IndexMap
        from photon_tpu.drivers.train import (TrainingParams,
                                              _resolve_streamed_objective)

        imap = IndexMap({f"f{i}\x01": i for i in range(64)}, frozen=True)
        params = TrainingParams(
            train_path="unused", output_dir="unused",
            feature_shards={"fx": {"bags": ["b"], "has_intercept": False}},
            coordinates={"fixed": {"feature_shard": "fx"}},
        )
        log = logging.getLogger("test_streamed_mesh")
        n_rows = 10_000
        # estimate = 12*n + 64*4*n = 268 B/row ≈ 2.68 MB
        per_chip = 1 << 20  # 1 MiB per chip: over per-chip, under 8x pool
        object.__setattr__(params, "hbm_budget_bytes", per_chip)
        with caplog.at_level(logging.INFO, logger="test_streamed_mesh"):
            assert _resolve_streamed_objective(
                params, {"fx": imap}, n_rows, mesh8, log) is False
            assert _resolve_streamed_objective(
                params, {"fx": imap}, n_rows, None, log) is True
        msgs = [r.message for r in caplog.records]
        assert any("verdict resident" in m and "8 device(s)" in m
                   for m in msgs)
        assert any("verdict STREAM" in m for m in msgs)

    def test_forced_streamed_with_mesh_allowed(self, rng, mesh8):
        """streamed_objective=True + mesh no longer raises — it shards."""
        import logging

        from photon_tpu.data.index_map import IndexMap
        from photon_tpu.drivers.train import (TrainingParams,
                                              _resolve_streamed_objective)

        imap = IndexMap({"a\x01": 0}, frozen=True)
        params = TrainingParams(
            train_path="unused", output_dir="unused",
            feature_shards={"fx": {"bags": ["b"], "has_intercept": False}},
            coordinates={"fixed": {"feature_shard": "fx"}},
            streamed_objective=True,
        )
        log = logging.getLogger("test_streamed_mesh")
        assert _resolve_streamed_objective(
            params, {"fx": imap}, 100, mesh8, log) is True
