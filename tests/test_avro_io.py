"""Avro container IO, record ingestion, and model save/load round trips
(SURVEY.md §4 'Avro reader vs hand-built fixtures')."""
import io
import struct

import numpy as np
import pytest

from photon_tpu.data.avro_io import (
    AvroContainerReader,
    read_avro,
    read_datum,
    parse_schema,
    write_avro,
)
from photon_tpu.data.feature_bags import FeatureShardConfig
from photon_tpu.data.ingest import (
    GameDataConfig,
    read_game_data,
    records_to_game_data,
    training_example_schema,
)
from photon_tpu.data.model_io import (
    load_game_model,
    load_glm_avro,
    save_game_model,
    save_glm_avro,
)
from photon_tpu.data.index_map import IndexMap, feature_key


RICH_SCHEMA = {
    "type": "record",
    "name": "Rich",
    "fields": [
        {"name": "id", "type": "long"},
        {"name": "score", "type": "double"},
        {"name": "tag", "type": ["null", "string"], "default": None},
        {"name": "nested", "type": {
            "type": "record", "name": "Inner",
            "fields": [{"name": "v", "type": "float"}],
        }},
        {"name": "arr", "type": {"type": "array", "items": "Inner"}},
        {"name": "m", "type": {"type": "map", "values": "int"}},
        {"name": "flag", "type": "boolean"},
    ],
}


def _rich_records(n=500):
    return [
        {
            "id": i,
            "score": i * 0.5,
            "tag": None if i % 3 else f"tag{i}",
            "nested": {"v": float(i)},
            "arr": [{"v": float(j)} for j in range(i % 4)],
            "m": {f"k{j}": j for j in range(i % 3)},
            "flag": bool(i % 2),
        }
        for i in range(n)
    ]


class TestContainerRoundTrip:
    @pytest.mark.parametrize("codec", ["null", "deflate", "snappy"])
    def test_round_trip(self, tmp_path, codec):
        p = tmp_path / "t.avro"
        recs = _rich_records()
        write_avro(p, recs, RICH_SCHEMA, codec=codec, block_records=128)
        out = read_avro(p)
        assert len(out) == len(recs)
        for a, b in zip(out, recs):
            assert a["id"] == b["id"]
            assert a["score"] == pytest.approx(b["score"])
            assert a["tag"] == b["tag"]
            assert a["nested"]["v"] == pytest.approx(b["nested"]["v"])
            assert len(a["arr"]) == len(b["arr"])
            assert a["m"] == b["m"]
            assert a["flag"] == b["flag"]

    def test_directory_read(self, tmp_path):
        recs = _rich_records(100)
        write_avro(tmp_path / "part-0.avro", recs[:50], RICH_SCHEMA)
        write_avro(tmp_path / "part-1.avro", recs[50:], RICH_SCHEMA)
        (tmp_path / "ignore.txt").write_text("x")
        out = read_avro(tmp_path)
        assert [r["id"] for r in out] == list(range(100))

    def test_codec_reported(self, tmp_path):
        p = tmp_path / "t.avro"
        write_avro(p, _rich_records(5), RICH_SCHEMA, codec="deflate")
        assert AvroContainerReader(p).codec == "deflate"

    def test_cross_codec_equality(self, tmp_path):
        """The same records under every codec decode to identical dicts."""
        recs = _rich_records(200)
        outs = {}
        for codec in ("null", "deflate", "snappy"):
            p = tmp_path / f"{codec}.avro"
            write_avro(p, recs, RICH_SCHEMA, codec=codec, block_records=64)
            outs[codec] = read_avro(p)
        assert outs["snappy"] == outs["null"] == outs["deflate"]

    def test_snappy_crc_mismatch_raises(self, tmp_path):
        p = tmp_path / "t.avro"
        write_avro(p, _rich_records(50), RICH_SCHEMA, codec="snappy",
                   block_records=50)
        raw = bytearray(p.read_bytes())
        raw[-18] ^= 0xFF  # flip a CRC byte (last block: ... crc4 sync16)
        p.write_bytes(bytes(raw))
        with pytest.raises(ValueError, match="CRC|snappy"):
            read_avro(p)

    def test_snappy_native_matches_python(self):
        """The C++ decompressor is byte-for-byte the pure-Python one."""
        from photon_tpu import native
        from photon_tpu.data import snappy

        if not native.available():
            pytest.skip("native toolchain unavailable")
        rng = np.random.default_rng(0)
        cases = [
            b"", b"x", b"abcd" * 1000,
            rng.integers(0, 256, 100_000, dtype=np.uint8).tobytes(),
            b"the quick brown fox " * 5000,
            rng.integers(0, 4, 200_000, dtype=np.uint8).tobytes(),
        ]
        for raw in cases:
            z = snappy.compress(raw)
            assert snappy.uncompress(z) == raw
            assert native.snappy_uncompress(z) == raw
        for bad in (b"", b"\xff\xff\xff\xff\xff\xff",
                    snappy.compress(b"y" * 500)[:-3],
                    snappy.compress(b"y" * 500)[:-1],
                    snappy.compress(rng.integers(0, 4, 10_000,
                                    dtype=np.uint8).tobytes())[:-1]):
            with pytest.raises(ValueError):
                native.snappy_uncompress(bad)
            with pytest.raises(ValueError):  # python twin: same verdict
                snappy.uncompress(bad)

    def test_snappy_native_ingest(self, tmp_path):
        """The native columnar ingest path reads snappy containers (blocks
        decompress before the C++ record decoder runs)."""
        from photon_tpu import native

        if not native.available():
            pytest.skip("native toolchain unavailable")
        schema = training_example_schema(feature_bags=("features",))
        recs = [{
            "response": float(i % 2), "offset": None, "weight": None,
            "uid": str(i),
            "features": [{"name": f"f{i % 7}", "term": "", "value": 1.0}],
        } for i in range(300)]
        p = tmp_path / "s.avro"
        write_avro(p, recs, schema, codec="snappy", block_records=64)
        cfg = GameDataConfig(
            shards={"all": FeatureShardConfig(bags=("features",))})
        d_nat, m_nat = read_game_data(str(p), cfg, use_native=True)
        d_py, m_py = read_game_data(str(p), cfg, use_native=False)
        np.testing.assert_array_equal(d_nat.y, d_py.y)
        np.testing.assert_array_equal(np.asarray(d_nat.shards["all"]),
                                      np.asarray(d_py.shards["all"]))
        assert m_nat["all"].keys_in_order() == m_py["all"].keys_in_order()

    def test_writer_does_not_mutate_schema(self, tmp_path):
        """parse_schema must not expand named-type references inside the
        caller's dict — the serialized schema would redefine the named type
        (rejected by standard Avro readers) and the shared constant would be
        corrupted for later calls."""
        import copy
        import json

        schema = training_example_schema(feature_bags=("f1", "f2"))
        before = copy.deepcopy(schema)
        p = tmp_path / "t.avro"
        write_avro(p, [], schema)
        assert schema == before  # caller's dict untouched
        written = AvroContainerReader(p).metadata["avro.schema"].decode()
        assert written.count('"NameTermValueAvro"') == 2  # def once + ref once
        assert json.loads(written) == before


class TestHandBuiltFixture:
    """Reader vs bytes encoded by hand from the Avro spec (not our writer)."""

    @staticmethod
    def _zigzag(n: int) -> bytes:
        n = (n << 1) ^ (n >> 63)
        out = b""
        while True:
            b7 = n & 0x7F
            n >>= 7
            if n:
                out += bytes((b7 | 0x80,))
            else:
                return out + bytes((b7,))

    def test_known_bytes(self, tmp_path):
        z = self._zigzag
        schema = (b'{"type":"record","name":"R","fields":['
                  b'{"name":"a","type":"long"},'
                  b'{"name":"s","type":"string"},'
                  b'{"name":"d","type":"double"}]}')
        sync = bytes(range(16))
        # record (a=-3, s="hi", d=1.5): zigzag(-3)=5 -> b"\x05"
        body = z(-3) + z(2) + b"hi" + struct.pack("<d", 1.5)
        blob = (
            b"Obj\x01"
            + z(2)  # 2 metadata entries
            + z(len(b"avro.schema")) + b"avro.schema" + z(len(schema)) + schema
            + z(len(b"avro.codec")) + b"avro.codec" + z(4) + b"null"
            + z(0)  # end metadata map
            + sync
            + z(1) + z(len(body)) + body + sync  # one block, one record
        )
        p = tmp_path / "hand.avro"
        p.write_bytes(blob)
        (rec,) = read_avro(p)
        assert rec == {"a": -3, "s": "hi", "d": 1.5}

    def test_negative_array_block_count(self):
        """Writers may emit (-count, bytesize) array blocks; spec-required."""
        schema = parse_schema(
            {"type": "array", "items": "long"})
        z = self._zigzag
        items = z(7) + z(9)
        payload = z(-2) + z(len(items)) + items + z(0)
        assert read_datum(io.BytesIO(payload), schema) == [7, 9]


class TestIngest:
    def _write_fixture(self, tmp_path, n=40):
        rng = np.random.default_rng(5)
        schema = training_example_schema(
            feature_bags=("global", "per_user"), entity_fields=("userId",))
        records = []
        for i in range(n):
            records.append({
                "response": float(i % 2),
                "offset": 0.25 if i == 0 else None,
                "weight": 2.0 if i == 1 else None,
                "uid": str(i),
                "userId": f"u{i % 5}",
                "global": [
                    {"name": "age", "term": "", "value": float(20 + i % 30)},
                    {"name": "ctr", "term": "7d", "value": float(rng.uniform())},
                ],
                "per_user": [
                    {"name": "hist", "term": "", "value": float(rng.uniform())},
                ],
            })
        p = tmp_path / "train.avro"
        write_avro(p, records, schema)
        return p

    def test_read_game_data(self, tmp_path):
        p = self._write_fixture(tmp_path)
        cfg = GameDataConfig(
            shards={
                "fixed": FeatureShardConfig(bags=("global",)),
                "user": FeatureShardConfig(bags=("per_user",)),
            },
            entity_fields=("userId",),
        )
        data, imaps = read_game_data(p, cfg)
        assert data.n == 40
        assert data.offsets[0] == pytest.approx(0.25)
        assert data.weights[1] == pytest.approx(2.0)
        assert data.weights[0] == pytest.approx(1.0)
        assert set(np.unique(data.entity_ids["userId"])) == {f"u{i}" for i in range(5)}
        assert data.shards["fixed"].shape == (40, 3)  # age, ctr#7d, intercept
        assert data.shards["user"].shape == (40, 2)  # hist, intercept
        # frozen maps reused on a second read (scoring path): same columns
        data2, _ = read_game_data(p, cfg, index_maps=imaps)
        np.testing.assert_allclose(
            np.asarray(data2.shards["fixed"]), np.asarray(data.shards["fixed"]))


class TestModelIO:
    def test_glm_avro_round_trip(self, tmp_path):
        imap = IndexMap()
        imap.build([feature_key("a", ""), feature_key("b", "x"),
                    feature_key("c", ""), "(INTERCEPT)"]).freeze()
        w = np.array([0.5, 0.0, -1.25, 2.0], np.float32)  # b#x is zero
        var = np.array([0.1, 0.0, 0.2, 0.3], np.float32)
        p = tmp_path / "glm.avro"
        save_glm_avro(p, w, imap, var)
        w2, var2 = load_glm_avro(p, imap)
        np.testing.assert_allclose(w2, w)
        np.testing.assert_allclose(var2, var)

    def test_game_model_round_trip(self, tmp_path):
        """Save + load a trained GAME model; scores must be identical."""
        import jax.numpy as jnp

        from photon_tpu.game.dataset import GameData
        from photon_tpu.game.estimator import (
            FixedEffectConfig, GameEstimator, RandomEffectConfig)
        from photon_tpu.game.scoring import score_game
        from photon_tpu.ops.losses import TaskType
        from photon_tpu.optim.config import OptimizerConfig
        from photon_tpu.optim.regularization import l2

        rng = np.random.default_rng(11)
        n, dF, dR, E = 160, 4, 2, 6
        Xf = rng.normal(size=(n, dF)).astype(np.float32)
        Xf[:, -1] = 1.0
        Xr = rng.normal(size=(n, dR)).astype(np.float32)
        ids = np.asarray([f"e{int(i)}" for i in rng.integers(0, E, n)])
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        data = GameData.build(y, shards={"f": Xf, "r": Xr},
                              entity_ids={"user": ids})
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs={
                "fixed": FixedEffectConfig("f", OptimizerConfig(
                    max_iters=15, reg=l2(), reg_weight=0.1)),
                "per_user": RandomEffectConfig("user", "r", OptimizerConfig(
                    max_iters=10, reg=l2(), reg_weight=1.0)),
            },
            n_sweeps=1,
        )
        model = est.fit(data)[0].model

        imF = IndexMap()
        imF.build([f"f{j}" for j in range(dF - 1)] + ["(INTERCEPT)"]).freeze()
        imR = IndexMap()
        imR.build([f"r{j}" for j in range(dR)]).freeze()
        out = tmp_path / "game_model"
        save_game_model(out, model, {"fixed": imF, "per_user": imR})
        loaded, imaps = load_game_model(out)

        assert loaded.task == model.task
        assert loaded.names() == model.names()
        s0 = np.asarray(score_game(model, data))
        s1 = np.asarray(score_game(loaded, data))
        np.testing.assert_allclose(s1, s0, rtol=1e-5, atol=1e-6)
        assert imaps["fixed"].n_features == dF
