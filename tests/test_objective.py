"""Objective value/grad/HVP vs autodiff; sparse vs dense; sharded vs local.

Mirrors the reference's DistributedGLMLossFunctionTest /
SingleNodeGLMLossFunctionTest (gradient checked against finite differences,
distributed result against local).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from photon_tpu.parallel.mesh import shard_map

from photon_tpu.data.dataset import make_batch
from photon_tpu.data.matrix import SparseRows, from_scipy_csr, matvec, rmatvec
from photon_tpu.ops.losses import TaskType
from photon_tpu.ops.objective import Objective

TASKS = list(TaskType)


def _mk(rng, task, n=64, d=7):
    X = rng.normal(size=(n, d)).astype(np.float32)
    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        y = (rng.random(n) < 0.5).astype(np.float32)
    elif task is TaskType.POISSON_REGRESSION:
        y = rng.poisson(2.0, n).astype(np.float32)
    else:
        y = rng.normal(size=n).astype(np.float32)
    w = rng.normal(size=n).astype(np.float32) ** 2 + 0.1
    off = rng.normal(size=n).astype(np.float32) * 0.1
    return make_batch(X, y, w, off)


@pytest.mark.parametrize("task", TASKS)
def test_grad_matches_autodiff(rng, task):
    batch = _mk(rng, task)
    obj = Objective(task, l2=0.3)
    w = jnp.asarray(rng.normal(size=7).astype(np.float32) * 0.3)
    f, g = obj.value_and_grad(w, batch)
    auto = jax.grad(lambda ww: obj.value(ww, batch))(w)
    np.testing.assert_allclose(g, auto, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("task", [TaskType.LOGISTIC_REGRESSION, TaskType.POISSON_REGRESSION])
def test_hvp_matches_autodiff(rng, task):
    batch = _mk(rng, task)
    obj = Objective(task, l2=0.5)
    w = jnp.asarray(rng.normal(size=7).astype(np.float32) * 0.2)
    v = jnp.asarray(rng.normal(size=7).astype(np.float32))
    hv = obj.hvp(w, batch, v)
    auto = jax.jvp(lambda ww: jax.grad(lambda x: obj.value(x, batch))(ww), (w,), (v,))[1]
    np.testing.assert_allclose(hv, auto, rtol=2e-3, atol=2e-3)


def test_hess_diag_and_full(rng):
    batch = _mk(rng, TaskType.LOGISTIC_REGRESSION)
    obj = Objective(TaskType.LOGISTIC_REGRESSION, l2=0.2)
    w = jnp.asarray(rng.normal(size=7).astype(np.float32) * 0.2)
    H = obj.full_hessian(w, batch)
    hd = obj.hess_diag(w, batch)
    np.testing.assert_allclose(jnp.diag(H), hd, rtol=1e-4, atol=1e-4)
    Hauto = jax.hessian(lambda ww: obj.value(ww, batch))(w)
    np.testing.assert_allclose(H, Hauto, rtol=2e-3, atol=2e-3)


def test_sparse_matches_dense(rng):
    import scipy.sparse as sp

    n, d = 48, 20
    Xd = rng.normal(size=(n, d)).astype(np.float32)
    Xd[rng.random((n, d)) < 0.7] = 0.0
    csr = sp.csr_matrix(Xd)
    Xs = from_scipy_csr(csr)
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    r = jnp.asarray(rng.normal(size=n).astype(np.float32))
    np.testing.assert_allclose(matvec(Xs, w), Xd @ np.asarray(w), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(rmatvec(Xs, r), Xd.T @ np.asarray(r), rtol=1e-4, atol=1e-4)

    y = (rng.random(n) < 0.5).astype(np.float32)
    bd = make_batch(Xd, y)
    bs = make_batch(Xs, y)
    obj = Objective(TaskType.LOGISTIC_REGRESSION, l2=0.1)
    fd, gd = obj.value_and_grad(w, bd)
    fs, gs = obj.value_and_grad(w, bs)
    np.testing.assert_allclose(fd, fs, rtol=1e-5)
    np.testing.assert_allclose(gd, gs, rtol=1e-4, atol=1e-4)


def test_sharded_psum_matches_local(rng, mesh8):
    """shard_map + psum over the data axis == single-device computation:
    the treeAggregate-parity test."""
    n, d = 64, 5
    batch = _mk(rng, TaskType.LOGISTIC_REGRESSION, n=n, d=d)
    w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.5)

    local_obj = Objective(TaskType.LOGISTIC_REGRESSION, l2=0.7)
    f_local, g_local = local_obj.value_and_grad(w, batch)

    sharded_obj = Objective(TaskType.LOGISTIC_REGRESSION, l2=0.7, axis_name="data")
    fn = shard_map(
        lambda b, ww: sharded_obj.value_and_grad(ww, b),
        mesh=mesh8,
        in_specs=(P("data"), P()),
        out_specs=(P(), P()),
    )
    f_sh, g_sh = jax.jit(fn)(batch, w)
    np.testing.assert_allclose(f_local, f_sh, rtol=1e-5)
    np.testing.assert_allclose(g_local, g_sh, rtol=1e-4, atol=1e-4)
