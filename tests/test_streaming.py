"""Streaming ingestion: bounded-memory chunks == one-shot read; direct
per-device placement (SURVEY/VERDICT: the reference never holds the dataset
on one host — Spark streams partitions; these tests pin our analog)."""
import numpy as np
import pytest

from photon_tpu.data.avro_io import write_avro
from photon_tpu.data.ingest import (
    GameDataConfig,
    read_game_data,
    training_example_schema,
)
from photon_tpu.data.matrix import SparseRows
from photon_tpu.data.streaming import (
    build_index_maps_streaming,
    iter_game_chunks,
    scan_row_counts,
    stream_to_device,
)
from photon_tpu.data.feature_bags import FeatureShardConfig


def _write_files(root, n_files=3, rows_per_file=400, seed=0, wide=False):
    """Multi-file GAME dataset; `wide` adds a high-cardinality bag so the
    shard goes down the SparseRows path."""
    rng = np.random.default_rng(seed)
    schema = training_example_schema(feature_bags=("f", "g"),
                                     entity_fields=("member",))
    paths = []
    for fi in range(n_files):
        records = []
        for i in range(rows_per_file):
            f_bag = [{"name": "age", "term": "", "value": float(rng.normal())},
                     {"name": "ctr", "term": "", "value": float(rng.normal())}]
            if wide:
                g_bag = [{"name": f"id{int(v)}", "term": "t",
                          "value": float(rng.normal())}
                         for v in rng.integers(0, 500, size=3)]
            else:
                g_bag = [{"name": "bias", "term": "", "value": 1.0}]
            records.append({
                "response": float(rng.integers(0, 2)),
                "offset": float(rng.normal()) if i % 3 == 0 else None,
                "weight": 2.0 if i % 5 == 0 else None,
                "uid": f"r{fi}_{i}",
                "member": f"m{int(rng.integers(0, 37))}",
                "f": f_bag, "g": g_bag,
            })
        p = root / f"part-{fi:03d}.avro"
        write_avro(p, records, schema, block_records=130)
        paths.append(p)
    return root


def _config(wide=False):
    return GameDataConfig(
        shards={
            "dense": FeatureShardConfig(bags=("f",), has_intercept=True),
            "other": FeatureShardConfig(
                bags=("g",), has_intercept=not wide,
                dense_threshold=4 if wide else 1024),
        },
        entity_fields=("member",),
    )


def _assert_chunks_equal_one_shot(root, config, use_native, sparse_k=None,
                                  chunk_rows=300):
    one_shot, maps = read_game_data(str(root), config, sparse_k=sparse_k,
                                    use_native=use_native)
    maps2 = build_index_maps_streaming(str(root), config)
    for s in config.shards:
        assert maps2[s].keys_in_order() == maps[s].keys_in_order()
    stream, chunks = iter_game_chunks(str(root), config, maps2,
                                      chunk_rows=chunk_rows,
                                      sparse_k=sparse_k,
                                      use_native=use_native)
    parts = list(chunks)
    assert len(parts) >= 2  # actually streamed in pieces
    assert sum(p.n for p in parts) == one_shot.n
    np.testing.assert_array_equal(
        np.concatenate([p.y for p in parts]), one_shot.y)
    np.testing.assert_array_equal(
        np.concatenate([p.weights for p in parts]), one_shot.weights)
    np.testing.assert_array_equal(
        np.concatenate([p.offsets for p in parts]), one_shot.offsets)
    np.testing.assert_array_equal(
        np.concatenate([p.entity_ids["member"] for p in parts]),
        one_shot.entity_ids["member"])
    for s in config.shards:
        X1 = one_shot.shards[s]
        if isinstance(X1, SparseRows):
            ind = np.concatenate([np.asarray(p.shards[s].indices)
                                  for p in parts])
            val = np.concatenate([np.asarray(p.shards[s].values)
                                  for p in parts])
            np.testing.assert_array_equal(ind, np.asarray(X1.indices))
            np.testing.assert_array_equal(val, np.asarray(X1.values))
        else:
            np.testing.assert_array_equal(
                np.concatenate([np.asarray(p.shards[s]) for p in parts]),
                np.asarray(X1))
    return stream, parts


class TestChunkStream:
    def test_python_chunks_match_one_shot(self, tmp_path):
        root = _write_files(tmp_path)
        _assert_chunks_equal_one_shot(root, _config(), use_native=False)

    def test_native_chunks_match_one_shot(self, tmp_path):
        from photon_tpu import native

        if not native.available():
            pytest.skip("native toolchain unavailable")
        root = _write_files(tmp_path)
        _assert_chunks_equal_one_shot(root, _config(), use_native=True)

    def test_sparse_chunks_match_one_shot(self, tmp_path):
        root = _write_files(tmp_path, wide=True)
        _assert_chunks_equal_one_shot(root, _config(wide=True),
                                      use_native=False, sparse_k=4)

    def test_bounded_arena(self, tmp_path):
        """Peak assembler arena ≤ 2× the largest chunk, however many files
        and rows stream through (the VERDICT 'bounded peak RSS' contract)."""
        from photon_tpu.data.streaming import _chunk_nbytes

        root = _write_files(tmp_path, n_files=6, rows_per_file=500)
        config = _config()
        maps = build_index_maps_streaming(str(root), config)
        for use_native in (False, None):
            stream, chunks = iter_game_chunks(str(root), config, maps,
                                              chunk_rows=250,
                                              use_native=use_native)
            biggest = 0
            n_chunks = 0
            for chunk in chunks:
                biggest = max(biggest, _chunk_nbytes(chunk))
                n_chunks += 1
            assert n_chunks >= 6
            assert stream.peak_arena_bytes <= 2 * biggest + (1 << 16)

    @pytest.mark.parametrize("use_native", [False, None])
    def test_ragged_chunk_widths_quantize_pow2(self, tmp_path, use_native):
        """uniform_sparse_k=False (the scoring stream): each chunk's own
        nnz width quantizes up to a power of two, so the per-chunk device
        programs compile a handful of shapes instead of one per distinct
        raggedness (each XLA compile is tens of seconds over a remote
        link). Padding slots are (0, 0.0) no-ops: totals must still match
        the one-shot read."""
        from photon_tpu.data.matrix import next_pow2

        root = _write_files(tmp_path, wide=True)
        config = _config(wide=True)
        maps = build_index_maps_streaming(str(root), config)
        one_shot, _ = read_game_data(str(root), config, use_native=use_native)
        stream, chunks = iter_game_chunks(str(root), config, maps,
                                          chunk_rows=300, sparse_k=None,
                                          use_native=use_native,
                                          uniform_sparse_k=False)
        got = 0
        for chunk in chunks:
            X = chunk.shards["other"]
            assert isinstance(X, SparseRows)
            k = X.indices.shape[1]
            assert k == next_pow2(k), k  # quantized
            np.testing.assert_allclose(
                np.asarray(X.values).sum(axis=1),
                np.asarray(one_shot.shards["other"].values)[
                    got:got + chunk.n].sum(axis=1), rtol=1e-5)
            got += chunk.n
        assert got == one_shot.n

    def test_scan_row_counts(self, tmp_path):
        root = _write_files(tmp_path, n_files=4, rows_per_file=123)
        assert scan_row_counts(str(root)) == [123] * 4

    def test_requires_frozen_maps(self, tmp_path):
        root = _write_files(tmp_path)
        with pytest.raises(ValueError, match="frozen index maps"):
            iter_game_chunks(str(root), _config(), {})


class TestStreamToDevice:
    def test_single_device_matches_one_shot(self, tmp_path):
        root = _write_files(tmp_path)
        config = _config()
        one_shot, maps = read_game_data(str(root), config)
        data, n_real = stream_to_device(str(root), config, maps,
                                        chunk_rows=300)
        assert n_real == one_shot.n
        np.testing.assert_array_equal(np.asarray(data.y), one_shot.y)
        np.testing.assert_array_equal(np.asarray(data.weights),
                                      one_shot.weights)
        np.testing.assert_array_equal(
            np.asarray(data.shards["dense"]),
            np.asarray(one_shot.shards["dense"]))
        np.testing.assert_array_equal(data.entity_ids["member"],
                                      one_shot.entity_ids["member"])

    def test_mesh_sharded_matches_one_shot(self, tmp_path, mesh8):
        """Chunks land on their devices directly; the assembled global
        array equals the one-shot host read padded to the mesh."""
        root = _write_files(tmp_path, n_files=3, rows_per_file=333)
        config = _config()
        one_shot, maps = read_game_data(str(root), config)
        data, n_real = stream_to_device(str(root), config, maps, mesh=mesh8,
                                        chunk_rows=250)
        assert n_real == one_shot.n == 999
        n_pad = data.y.shape[0]
        assert n_pad % 8 == 0
        got_y = np.asarray(data.y)
        np.testing.assert_array_equal(got_y[:n_real], one_shot.y)
        assert (np.asarray(data.weights)[n_real:] == 0.0).all()  # padding
        np.testing.assert_array_equal(
            np.asarray(data.shards["dense"])[:n_real],
            np.asarray(one_shot.shards["dense"]))
        # really sharded: one addressable shard per device, rows split
        shards = data.y.addressable_shards
        assert len(shards) == 8
        assert all(s.data.shape[0] == n_pad // 8 for s in shards)

    def test_mesh_sparse_and_bf16(self, tmp_path, mesh8):
        import jax.numpy as jnp

        root = _write_files(tmp_path, wide=True)
        config = _config(wide=True)
        one_shot, maps = read_game_data(str(root), config, sparse_k=4)
        data, n_real = stream_to_device(
            str(root), config, maps, mesh=mesh8, chunk_rows=400,
            sparse_k=4, feature_dtype=jnp.bfloat16)
        X = data.shards["other"]
        assert isinstance(X, SparseRows)
        assert X.values.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(X.indices)[:n_real],
            np.asarray(one_shot.shards["other"].indices))
        np.testing.assert_allclose(
            np.asarray(X.values, dtype=np.float32)[:n_real],
            np.asarray(one_shot.shards["other"].values),
            rtol=0.01, atol=1e-3)  # bf16 rounding

    def test_sparse_without_k_raises(self, tmp_path):
        root = _write_files(tmp_path, wide=True)
        config = _config(wide=True)
        maps = build_index_maps_streaming(str(root), config)
        with pytest.raises(ValueError, match="sparse_k"):
            stream_to_device(str(root), config, maps)

    def test_streamed_data_trains(self, tmp_path):
        """End to end: streamed device-resident data fits a GLM."""
        from photon_tpu.data.dataset import make_batch
        from photon_tpu.models.training import train_glm
        from photon_tpu.ops.losses import TaskType
        from photon_tpu.optim import regularization as reg
        from photon_tpu.optim.config import OptimizerConfig

        root = _write_files(tmp_path)
        config = _config()
        maps = build_index_maps_streaming(str(root), config)
        data, n_real = stream_to_device(str(root), config, maps,
                                        chunk_rows=300)
        batch = make_batch(data.shards["dense"], data.y,
                           weights=data.weights, offsets=data.offsets)
        model, res = train_glm(
            batch, TaskType.LOGISTIC_REGRESSION,
            OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=1.0))
        assert np.isfinite(np.asarray(model.coefficients.means)).all()


class TestMultiHostShardMath:
    """Multi-host-safe stream_to_device (VERDICT r3 item 6): only the
    process's addressable slots fill + device_put; the global assembly
    gets exactly the local shards. Simulated single-process through the
    documented `_local_mask` seam (CPU tests cannot make real devices
    non-addressable)."""

    def test_only_local_slots_materialize(self, tmp_path, mesh8,
                                          monkeypatch):
        import jax

        root = _write_files(tmp_path, n_files=2, rows_per_file=400)
        config = _config()
        one_shot, maps = read_game_data(str(root), config)
        n_real = one_shot.n  # 800 -> n_local = 100 on 8 devices
        mask = [True, False, True, False, True, False, True, False]

        captured = {}

        def fake_assemble(shape, sharding, parts):
            captured.setdefault("calls", []).append((shape, len(parts)))
            return np.concatenate([np.asarray(p) for p in parts])

        monkeypatch.setattr(jax, "make_array_from_single_device_arrays",
                            fake_assemble)
        data, got_real = stream_to_device(
            str(root), config, maps, mesh=mesh8, chunk_rows=250,
            _local_mask=mask)
        assert got_real == n_real
        n_local = 100
        # every assembled column got exactly the 4 LOCAL shards
        assert all(n_parts == 4 for _, n_parts in captured["calls"])
        # and they hold exactly this process's slots' rows: 0-99, 200-299,
        # 400-499, 600-699 of the global padded layout
        want_rows = np.concatenate(
            [np.arange(s * n_local, (s + 1) * n_local)
             for s in range(8) if mask[s]])
        np.testing.assert_array_equal(np.asarray(data.y),
                                      one_shot.y[want_rows])
        np.testing.assert_array_equal(
            np.asarray(data.shards["dense"]),
            np.asarray(one_shot.shards["dense"])[want_rows])
        # entity ids stay GLOBAL on every process
        assert data.entity_ids["member"].shape[0] == 800

    def test_no_addressable_device_gate(self, tmp_path, mesh8):
        root = _write_files(tmp_path, n_files=1, rows_per_file=50)
        config = _config()
        maps = build_index_maps_streaming(str(root), config)
        with pytest.raises(ValueError, match="addressable"):
            stream_to_device(str(root), config, maps, mesh=mesh8,
                             _local_mask=[False] * 8)

    def test_local_only_skips_decode_and_matches(self, tmp_path, mesh8,
                                                 monkeypatch):
        """Round 17: ``local_only=True`` decodes ONLY the chunk tasks
        overlapping this process's slots (the skip counter proves blocks
        were bypassed) and the local shards stay bit-identical to the
        full decode — dense AND sparse columns."""
        import jax

        from photon_tpu import telemetry
        from photon_tpu.data.streaming import scan_ingest

        root = _write_files(tmp_path, n_files=3, rows_per_file=400,
                            wide=True)
        config = _config(wide=True)
        one_shot, maps = read_game_data(str(root), config, sparse_k=4)
        n_real = one_shot.n  # 1200 -> n_local = 150 on 8 devices
        mask = [True, False, True, False, True, False, True, False]

        def fake_assemble(shape, sharding, parts):
            return np.concatenate([np.asarray(p) for p in parts])

        monkeypatch.setattr(jax, "make_array_from_single_device_arrays",
                            fake_assemble)
        scan = scan_ingest(str(root), config, maps)
        telemetry.start_run(name="local_only_parity")
        data, got_real = stream_to_device(
            str(root), config, maps, mesh=mesh8, chunk_rows=250,
            sparse_k=4, _local_mask=mask,
            block_index=scan.block_index, local_only=True)
        counters = (telemetry.finish_run() or {}).get("counters", {})
        assert got_real == n_real
        assert counters.get("ingest.chunks_skipped", 0) >= 1
        n_local = n_real // 8
        want = np.concatenate(
            [np.arange(s * n_local, (s + 1) * n_local)
             for s in range(8) if mask[s]])
        np.testing.assert_array_equal(np.asarray(data.y), one_shot.y[want])
        np.testing.assert_array_equal(np.asarray(data.weights),
                                      one_shot.weights[want])
        np.testing.assert_array_equal(
            np.asarray(data.shards["dense"]),
            np.asarray(one_shot.shards["dense"])[want])
        np.testing.assert_array_equal(
            np.asarray(data.shards["other"].indices),
            np.asarray(one_shot.shards["other"].indices)[want])
        np.testing.assert_array_equal(
            np.asarray(data.shards["other"].values),
            np.asarray(one_shot.shards["other"].values)[want])
        # entity ids stay host-global in SHAPE; skipped chunks fill ""
        assert data.entity_ids["member"].shape[0] == n_real

    def test_local_only_refuses_cache_dir(self, tmp_path, mesh8):
        root = _write_files(tmp_path, n_files=1, rows_per_file=50)
        config = _config()
        maps = build_index_maps_streaming(str(root), config)
        with pytest.raises(ValueError, match="cache"):
            stream_to_device(str(root), config, maps, mesh=mesh8,
                             cache_dir=str(tmp_path / "cache"),
                             local_only=True)

    def test_full_mask_matches_default(self, tmp_path, mesh8):
        """All-local mask (the single-process case) is the existing
        behavior bit for bit."""
        root = _write_files(tmp_path, n_files=1, rows_per_file=160)
        config = _config()
        one_shot, maps = read_game_data(str(root), config)
        a, _ = stream_to_device(str(root), config, maps, mesh=mesh8,
                                chunk_rows=100)
        b, _ = stream_to_device(str(root), config, maps, mesh=mesh8,
                                chunk_rows=100, _local_mask=[True] * 8)
        np.testing.assert_array_equal(np.asarray(a.y), np.asarray(b.y))
        np.testing.assert_array_equal(np.asarray(a.shards["dense"]),
                                      np.asarray(b.shards["dense"]))


@pytest.mark.tier2
class TestRealTwoProcess:
    """VERDICT r4 item 3, rebuilt on the round-17 spine: the multi-host
    story executed across REAL process boundaries, not just the
    `_local_mask` arithmetic seam. Two spawned cluster members
    (`parallel.launch` -> `initialize_distributed` -> gloo CPU
    collectives, 4 virtual devices each) run the full per-process
    pipeline — scan, ``local_only=True`` ingest, the mesh GLM psum
    program — over one 8-device global mesh; every rank must return the
    same replicated model, BIT-identical to a 1-process launch of the
    same program (gloo's reduction tree depends only on the global rank
    count, so splitting the mesh across processes must not move a
    single mantissa bit — docs/MULTIHOST.md). Skips (with the reason)
    when the sandbox blocks the localhost gRPC coordinator the
    distributed runtime needs. Tier-2: spawning + initializing three
    jax runtimes is seconds, not ms."""

    def test_two_processes_match_single(self, tmp_path):
        from photon_tpu.parallel import selfcheck as sc
        from photon_tpu.parallel.launch import ClusterUnavailable, launch

        sc.write_e2e_dataset(tmp_path)  # 1200 rows; 150 per device slot
        try:
            ref = launch(sc.target_stream_solve, 1,
                         args=(str(tmp_path),), timeout_s=420)[0]
            res = launch(sc.target_stream_solve, 2, args=(str(tmp_path),),
                         timeout_s=420)
        except ClusterUnavailable as e:
            pytest.skip("jax.distributed could not form the cluster in "
                        f"this sandbox: {e}")
        assert [r["rank"] for r in res] == [0, 1]
        assert all(r["n_real"] == ref["n_real"] == 1200 for r in res)
        # the ingest plane genuinely split: the 1-process run decoded
        # every chunk itself; at 2 processes both ranks skipped some
        assert ref["chunks_skipped"] == 0 and ref["chunks_decoded"] >= 2
        assert all(r["chunks_skipped"] >= 1 for r in res)
        assert all(r["chunks_decoded"] >= 1 for r in res)
        w0, w1 = res[0]["w"], res[1]["w"]
        # every process computes the same replicated model, and the
        # 2-process split is bit-identical to the 1-process launch
        np.testing.assert_array_equal(w0, w1)
        np.testing.assert_array_equal(w0, ref["w"])
        assert res[0]["digest"] == res[1]["digest"] == ref["digest"]
