"""Pod-scale GAME end-to-end: the composed streamed + mesh regime.

THE acceptance matrix of the round-13 composition: a 2-coordinate GAME
fit (fixed effect + per-entity random effect, 2 sweeps) whose
fixed-effect shard lives as a HOST chunk ladder and solves on the
mesh-streamed backend (mesh 8) — random-effect buckets entity-sharded
over the same mesh, inter-coordinate scores exchanged through host
margin caches — against the resident single-chip fit, across
{L-BFGS, OWL-QN} fixed effects x {dense, blocked-ELL} features, compared
in f64. Chunked f32 accumulation reorders sums (the documented
streamed==resident tolerance of tests/test_streamed.py), so cross-REGIME
parity is pinned at that tolerance; bit-level f64 identity is asserted
where the execution regime is identical — the checkpoint kill/restore
case, whose resumed run must match the uninterrupted one EXACTLY.

Also pinned here: the PR-9 `optim.streamed._backend` mesh + blocked-ELL
rejection is LIFTED for mesh chunk ladders (`chunk_blocked_ell(
n_shards=D)`) and raises precise, actionable errors for every
mismatched layout; the fused-update straggler gate logs + counts; and
the streamed coordinate's scores stay host-resident with the
`game_e2e.*` telemetry spine.
"""
import logging

import numpy as np
import pytest

from photon_tpu import telemetry
from photon_tpu.data.dataset import (chunk_blocked_ell, chunk_matrix,
                                     make_batch)
from photon_tpu.data.matrix import SparseRows
from photon_tpu.game.dataset import GameData
from photon_tpu.game.estimator import (FixedEffectConfig, GameEstimator,
                                       RandomEffectConfig)
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.parallel.mesh import make_mesh

pytestmark = pytest.mark.release_programs

TASK = TaskType.LOGISTIC_REGRESSION
N, E, D_FIXED, D_RE = 384, 24, 8, 5
D_SPARSE, K, D_DENSE = 40, 4, 16
CHUNK_ROWS = 96  # 4 chunks; 96 % 8 == 0 -> 12 rows per device slot

CFG_RE = OptimizerConfig(max_iters=6, tolerance=1e-6, reg=reg.l2(),
                         reg_weight=1.0, history=4)
CFG_F = {
    "lbfgs": OptimizerConfig(max_iters=8, tolerance=1e-6, reg=reg.l2(),
                             reg_weight=0.5, history=4),
    "owlqn": OptimizerConfig(max_iters=8, tolerance=1e-6, reg=reg.l1(),
                             reg_weight=1e-3, history=4),
}


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(11)
    ent = rng.integers(0, E, size=N)
    Xf = rng.normal(size=(N, D_FIXED)).astype(np.float32)
    Xr = rng.normal(size=(N, D_RE)).astype(np.float32)
    ind = rng.integers(0, D_SPARSE, size=(N, K)).astype(np.int32)
    val = rng.normal(size=(N, K)).astype(np.float32)
    w_true = rng.normal(size=D_FIXED).astype(np.float32) * 0.5
    u_true = rng.normal(size=(E, D_RE)).astype(np.float32)
    margin = Xf @ w_true + np.einsum("nd,nd->n", Xr, u_true[ent])
    y = (rng.uniform(size=N) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    return {"y": y, "ent": ent, "dense": Xf, "re": Xr,
            "sparse": SparseRows(ind, val, D_SPARSE)}


def _fixed_shard(problem, layout: str, streamed: bool, n_shards: int = 8):
    if layout == "dense":
        return (chunk_matrix(problem["dense"], CHUNK_ROWS) if streamed
                else problem["dense"])
    sp = problem["sparse"]
    if not streamed:
        return sp
    return chunk_blocked_ell(make_batch(sp, problem["y"]), CHUNK_ROWS,
                             d_dense=D_DENSE, n_shards=n_shards).X


def _fit(problem, shard, opt: str, mesh=None, cfg_re=CFG_RE):
    data = GameData.build(problem["y"], {"fx": shard, "rs": problem["re"]},
                          {"e": problem["ent"]})
    est = GameEstimator(
        task=TASK,
        coordinate_configs={
            "fixed": FixedEffectConfig("fx", CFG_F[opt]),
            "re": RandomEffectConfig("e", "rs", cfg_re)},
        n_sweeps=2, mesh=mesh)
    return est.fit(data)[0]


def _coeffs(result):
    return (np.asarray(result.model.coordinates["fixed"]
                       .model.coefficients.means, np.float64),
            np.asarray(result.model.coordinates["re"].coefficients,
                       np.float64))


# --------------------------------------------------------- parity matrix
class TestStreamedMeshGameParity:
    """streamed(mesh 8) GAME == resident single-chip GAME, f64-compared
    at the streamed==resident tolerance, for every (optimizer, layout)
    face — 2 coordinates, 2 sweeps, warm starts, host score exchange."""

    @pytest.mark.parametrize("opt,layout", [
        ("lbfgs", "dense"), ("lbfgs", "ell"),
        ("owlqn", "dense"), ("owlqn", "ell")])
    def test_streamed_mesh_equals_resident(self, problem, mesh8, opt,
                                           layout):
        r_res = _fit(problem, _fixed_shard(problem, layout, False), opt)
        r_str = _fit(problem, _fixed_shard(problem, layout, True), opt,
                     mesh=mesh8)
        wf_r, wr_r = _coeffs(r_res)
        wf_s, wr_s = _coeffs(r_str)
        np.testing.assert_allclose(wf_s, wf_r, rtol=5e-3, atol=1e-3)
        np.testing.assert_allclose(wr_s, wr_r, rtol=5e-3, atol=1e-3)
        # the objective trajectories track each other update for update
        o_r = r_res.descent.objective_history
        o_s = r_str.descent.objective_history
        assert len(o_r) == len(o_s) == 4  # 2 sweeps x 2 coordinates
        np.testing.assert_allclose(o_s, o_r, rtol=1e-4)

    def test_streamed_scores_stay_host(self, problem, mesh8):
        """The margin exchange is host-resident: the streamed coordinate
        scores into numpy caches, offsets sum on host, and the
        game_e2e.* telemetry spine records the exchange."""
        run = telemetry.start_run("game_e2e_test")
        try:
            r = _fit(problem, _fixed_shard(problem, "dense", True),
                     "lbfgs", mesh=mesh8)
        finally:
            telemetry.finish_run()
        assert r.descent.objective_history
        c = run.counters
        assert c["game_e2e.streamed_fixed_updates"] == 2  # 2 sweeps
        assert c["game_e2e.host_offset_sums"] == 4  # every update
        assert c["game_e2e.score_stream_chunks"] >= 8
        assert c["game_e2e.objective_chunks"] >= 8
        assert c["game_e2e.chunked_fit_points"] == 1

    def test_streamed_fixed_score_is_host_numpy(self, problem, mesh8):
        from photon_tpu.game.dataset import FixedEffectDataset
        from photon_tpu.game.fixed_effect import FixedEffectCoordinate
        from photon_tpu.game.model import FixedEffectModel
        from photon_tpu.models.glm import logistic_regression

        data = GameData.build(problem["y"],
                              {"fx": chunk_matrix(problem["dense"],
                                                  CHUNK_ROWS)},
                              {})
        ds = FixedEffectDataset.build(data, "fx")
        coord = FixedEffectCoordinate(ds, TASK, CFG_F["lbfgs"], mesh=mesh8)
        w = np.linspace(-1, 1, D_FIXED).astype(np.float32)
        score = coord.score(FixedEffectModel(logistic_regression(w), "fx"))
        assert isinstance(score, np.ndarray)
        np.testing.assert_allclose(score, problem["dense"] @ w,
                                   rtol=1e-5, atol=1e-5)


# -------------------------------------------- backend layout pins (PR 9)
class TestBlockedEllMeshBackend:
    """The PR-9 limitation, resolved: mesh + blocked-ELL streams on the
    MESH chunk ladder; every mismatched layout raises an actionable
    error naming the rebuild recipe."""

    def _glm(self, cb, mesh=None):
        from photon_tpu.models.training import train_glm

        cfg = OptimizerConfig(max_iters=6, tolerance=1e-6, reg=reg.l2(),
                              reg_weight=0.3, history=4)
        return train_glm(cb, TASK, cfg, mesh=mesh)

    def test_single_device_ladder_under_mesh_raises_actionable(
            self, problem, mesh8):
        cb = chunk_blocked_ell(make_batch(problem["sparse"],
                                          problem["y"]),
                               CHUNK_ROWS, d_dense=D_DENSE)
        with pytest.raises(ValueError,
                           match=r"n_shards=8.*|chunk_blocked_ell"):
            self._glm(cb, mesh=mesh8)

    def test_mesh_ladder_without_mesh_raises_actionable(self, problem):
        cb = chunk_blocked_ell(make_batch(problem["sparse"],
                                          problem["y"]),
                               CHUNK_ROWS, d_dense=D_DENSE, n_shards=8)
        with pytest.raises(ValueError, match="8-device mesh"):
            self._glm(cb)

    def test_shard_count_mismatch_raises(self, problem, mesh8):
        cb = chunk_blocked_ell(make_batch(problem["sparse"],
                                          problem["y"]),
                               CHUNK_ROWS, d_dense=D_DENSE, n_shards=4)
        with pytest.raises(ValueError, match="4 device shard"):
            self._glm(cb, mesh=mesh8)

    def test_chunk_rows_must_divide_shards(self, problem):
        with pytest.raises(ValueError, match="multiple of"):
            chunk_blocked_ell(make_batch(problem["sparse"], problem["y"]),
                              100, d_dense=D_DENSE, n_shards=8)

    def test_mesh_ladder_glm_parity(self, problem, mesh8):
        """The lifted path at the train_glm level: the mesh chunk ladder
        solves to the resident optimum."""
        m_r, _ = self._glm(make_batch(problem["sparse"], problem["y"]))
        cb = chunk_blocked_ell(make_batch(problem["sparse"],
                                          problem["y"]),
                               CHUNK_ROWS, d_dense=D_DENSE, n_shards=8)
        m_m, _ = self._glm(cb, mesh=mesh8)
        np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=5e-3, atol=5e-4)

    def test_sharded_ladder_matvec_parity(self, problem, mesh8):
        """Layout-level correctness of the mesh ladder: every chunk's
        sharded matvec reproduces the flat SparseRows margins."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from photon_tpu.data.dataset import mesh_chunk_matrix
        from photon_tpu.data.matrix import matvec
        from photon_tpu.models.training import _hybrid_specs
        from photon_tpu.parallel.mesh import shard_map

        sp = problem["sparse"]
        cb = chunk_blocked_ell(make_batch(sp, problem["y"]), CHUNK_ROWS,
                               d_dense=D_DENSE, n_shards=8)
        rng = np.random.default_rng(0)
        w = rng.normal(size=D_SPARSE).astype(np.float32)
        wp = w[np.asarray(cb.X.perm_cols)]
        ref = np.einsum("nk,nk->n", np.asarray(sp.values),
                        w[np.asarray(sp.indices)])
        axes = tuple(mesh8.axis_names)
        cache: dict = {}
        outs = []
        for i in range(cb.n_chunks):
            Xs = mesh_chunk_matrix(cb.X.chunks[i], mesh8, cache)
            fn = shard_map(lambda Xl, wv: matvec(Xl.local(), wv),
                           mesh=mesh8,
                           in_specs=(_hybrid_specs(Xs, axes).X, P()),
                           out_specs=P(axes))
            outs.append(np.asarray(jax.jit(fn)(Xs, jnp.asarray(wp))))
        np.testing.assert_allclose(np.concatenate(outs)[:N], ref,
                                   rtol=2e-5, atol=2e-5)


# ------------------------------------------------- fused gate (satellite)
class TestFusedGateTelemetry:
    def test_straggler_gate_logs_once_and_counts(self, problem, caplog):
        """straggler_budget disabling the fused one-dispatch update is no
        longer a silent call-site comment: INFO log once per coordinate,
        game_re.fused_gate_offs counted per gated call."""
        from photon_tpu.game.dataset import RandomEffectDataset
        from photon_tpu.game.random_effect import RandomEffectCoordinate

        data = GameData.build(problem["y"], {"rs": problem["re"]},
                              {"e": problem["ent"]})
        ds = RandomEffectDataset.build(data, "e", "rs")
        coord = RandomEffectCoordinate(ds, TASK, CFG_RE,
                                       straggler_budget=2)
        run = telemetry.start_run("fused_gate")
        try:
            with caplog.at_level(logging.INFO, logger="photon_tpu.game"):
                assert coord.fused_update_program() is None
                assert coord.fused_update_program() is None
        finally:
            telemetry.finish_run()
        assert run.counters["game_re.fused_gate_offs"] == 2
        gate_lines = [r for r in caplog.records
                      if "straggler_budget" in r.getMessage()]
        assert len(gate_lines) == 1  # once per coordinate, not per call
        assert "pipelined block loop" in gate_lines[0].getMessage()

    def test_unbudgeted_coordinate_still_fuses(self, problem):
        from photon_tpu.game.dataset import RandomEffectDataset
        from photon_tpu.game.random_effect import RandomEffectCoordinate

        data = GameData.build(problem["y"], {"rs": problem["re"]},
                              {"e": problem["ent"]})
        ds = RandomEffectDataset.build(data, "e", "rs")
        coord = RandomEffectCoordinate(ds, TASK, CFG_RE)
        assert coord.fused_update_program() is not None


# --------------------------------------------- checkpoint (coordinate cut)
class TestStreamedGameCheckpoint:
    def test_kill_restore_at_coordinate_boundary_bit_identical(
            self, problem, tmp_path):
        """Kill the streamed GAME descent mid-sweep-2 (inside the SECOND
        coordinate pass — past a coordinate-boundary progress cut of the
        new streamed path), restore, and finish with coefficients AND
        objective history EXACTLY equal (f64) to the uninterrupted
        run's: the host-score progress payload round-trips bit-clean."""
        from photon_tpu import checkpoint

        cfg_re = OptimizerConfig(max_iters=5, tolerance=1e-6,
                                 reg=reg.l2(), reg_weight=1.0, history=4)

        def run():
            return _fit(problem, _fixed_shard(problem, "dense", True),
                        "lbfgs", cfg_re=cfg_re)

        ref = run()
        wf_ref, wr_ref = _coeffs(ref)

        with checkpoint.session(str(tmp_path / "rec"), every_evals=1,
                                every_s=None, async_writer=False):
            with checkpoint.record_sites() as rec:
                armed = run()
        wf_a, wr_a = _coeffs(armed)
        np.testing.assert_array_equal(wf_ref, wf_a)  # observe, not perturb
        np.testing.assert_array_equal(wr_ref, wr_a)
        n_evals = dict(rec.hits)["evaluation"]
        assert n_evals >= 8

        # kill inside the LAST fixed-effect solve: updates 0..2 restore
        # from the descent progress payload (host scores included), the
        # in-flight streamed solve resumes from its own iteration cut
        killed = False
        ckdir = tmp_path / "kill"
        try:
            with checkpoint.session(str(ckdir), every_evals=1,
                                    every_s=None, async_writer=False):
                with checkpoint.fault_plan(
                        checkpoint.FaultPlan.kill_at("evaluation",
                                                     n_evals - 2)):
                    run()
        except checkpoint.InjectedFault:
            killed = True
        assert killed
        with checkpoint.session(str(ckdir), every_evals=1, every_s=None,
                                async_writer=False):
            out2 = run()
        wf2, wr2 = _coeffs(out2)
        np.testing.assert_array_equal(wf_ref, wf2)
        np.testing.assert_array_equal(wr_ref, wr2)
        assert [float(v) for v in ref.descent.objective_history] == \
            [float(v) for v in out2.descent.objective_history]


# -------------------------------------------------------------- contracts
def test_game_e2e_contract_specs_registered():
    """The pod-scale GAME collective budget as registered law: ONE psum
    per streamed fixed-effect evaluation, collective-free RE bucket
    solves on the mesh, scatter-free f32-accumulating streamed chunk and
    score programs."""
    from photon_tpu.analysis.registry import load_registry
    from photon_tpu.analysis.walker import SCATTER_PRIMITIVES

    registry = load_registry()
    assert dict(registry["game_streamed_fixed_evaluation"].collectives) \
        == {"psum": 1}
    assert dict(registry["game_re_mesh_bucket_solve"].collectives or {}) \
        == {}
    for name in ("streamed_mesh_blocked_ell_chunk_partials",
                 "game_score_stream_chunk"):
        spec = registry[name]
        assert dict(spec.collectives or {}) == {}
        assert SCATTER_PRIMITIVES <= spec.forbid, name
        assert spec.require_f32_accum, name
