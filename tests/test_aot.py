"""AOT export round-trips (utils/aot.py): an exported lane-grid program
must replay from bytes — no retracing — with identical results, through
photon-tpu's registered pytree types (GLMBatch in, OptResult out)."""
import os

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import make_batch
from photon_tpu.data.matrix import SparseRows, to_permuted_hybrid
from photon_tpu.models.training import (_lane_solve, lane_weight_arrays,
                                        make_objective)
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.regularization import l2
from photon_tpu.utils.aot import AotStore, export_program, load_program


def _problem(rng, n=400, d=120, k=6):
    ind = rng.integers(0, d - 1, size=(n, k)).astype(np.int32)
    ind[:, -1] = d - 1
    val = rng.normal(size=(n, k)).astype(np.float32)
    val[:, -1] = 1.0
    wt = rng.normal(size=d).astype(np.float32) * 0.5
    z = np.einsum("nk,nk->n", val, wt[ind])
    y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)
    X = to_permuted_hybrid(SparseRows(jnp.asarray(ind), jnp.asarray(val), d),
                           16)
    return make_batch(X, y)


def _fn_and_args(rng):
    batch = _problem(rng)
    cfg = OptimizerConfig(max_iters=30, tolerance=1e-7, reg=l2(),
                          reg_weight=0.0, history=5)
    l2s, l1s, static_cfg = lane_weight_arrays(cfg, [1e-2, 1.0])
    obj = make_objective(TaskType.LOGISTIC_REGRESSION, cfg,
                         batch.X.n_features)
    w0 = jnp.zeros((batch.X.n_features,), jnp.float32)

    def fn(batch, w0, obj, l2s):
        return _lane_solve(obj, batch, w0, l2s, None, static_cfg)

    return fn, (batch, w0, obj, l2s)


def test_export_replay_bitwise(rng, tmp_path):
    fn, args = _fn_and_args(rng)
    direct = jax.jit(fn)(*args)
    data = export_program(fn, *args)
    replay = load_program(data)(*args)
    for a, b in zip(jax.tree_util.tree_leaves(direct),
                    jax.tree_util.tree_leaves(replay)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_store_kill_mid_write_leaves_no_corrupt_export(rng, tmp_path):
    """Kill-mid-write regression (elastic-runs round): exports commit via
    temp+fsync+rename, so a preemption during the write leaves either NO
    export (fresh store) or the OLD bytes (overwrite) — never a truncated
    .jaxexp that fails at the next load. The next call simply re-exports
    and succeeds."""
    from photon_tpu import checkpoint

    fn, args = _fn_and_args(rng)
    store = AotStore(str(tmp_path))
    with np.testing.assert_raises(checkpoint.InjectedFault):
        with checkpoint.fault_plan(
                checkpoint.FaultPlan.kill_at("commit", 1)):
            store.call("lane", fn, *args)
    # the final path never appeared — only an abandoned temp file
    assert [f for f in os.listdir(tmp_path) if f.endswith(".jaxexp")] == []
    # a fresh process re-exports cleanly and the replay works
    fresh = AotStore(str(tmp_path))
    r = fresh.call("lane", fn, *args)
    assert np.asarray(r.w).ndim == 2  # (d, lanes)
    assert len([f for f in os.listdir(tmp_path)
                if f.endswith(".jaxexp")]) == 1


def test_store_hits_and_aval_guard(rng, tmp_path):
    fn, args = _fn_and_args(rng)
    store = AotStore(str(tmp_path))
    r1 = store.call("lane", fn, *args)
    files = [f for f in os.listdir(tmp_path) if f.endswith(".jaxexp")]
    assert len(files) == 1
    # Fresh store object (new process analog): replays from disk.
    store2 = AotStore(str(tmp_path))
    r2 = store2.call("lane", fn, *args)
    np.testing.assert_array_equal(np.asarray(r1.w), np.asarray(r2.w))
    # Different avals under the same key re-export instead of misfiring.
    bigger = _problem(np.random.default_rng(2), n=512)
    r3 = store2.call("lane", fn, bigger, args[1], args[2], args[3])
    assert np.asarray(r3.w).shape == np.asarray(r1.w).shape
    assert len([f for f in os.listdir(tmp_path)
                if f.endswith(".jaxexp")]) == 2


def test_sharded_permuted_batch_registered(rng):
    """ADVICE r5 #1: a program whose arguments carry the sharded-permuted
    batch must export (the pytree type is registered with jax.export)."""
    from photon_tpu.data.dataset import shard_permuted_batch

    n, d, k = 64, 40, 4
    ind = rng.integers(0, d, size=(n, k)).astype(np.int32)
    val = rng.normal(size=(n, k)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    batch = shard_permuted_batch(
        make_batch(SparseRows(jnp.asarray(ind), jnp.asarray(val), d), y),
        1, d_dense=8)
    fn = jax.jit(lambda b: jnp.sum(b.X.local().dense))
    data = export_program(fn, batch)
    np.testing.assert_allclose(np.asarray(load_program(data)(batch)),
                               np.asarray(fn(batch)), rtol=1e-6)


def test_store_reraises_genuine_value_error(rng, tmp_path):
    """ADVICE r5 #2: only jax.export's platform-mismatch ValueError may
    trigger the silent re-export; any other ValueError must surface."""
    import pytest

    store = AotStore(str(tmp_path))
    fn = jax.jit(lambda x: x * 2)
    x = jnp.ones(3)
    store.call("k", fn, x)

    def boom(*a):
        raise ValueError("boom: genuine error from the replayed program")

    for path in list(store._loaded):
        store._loaded[path] = boom
    with pytest.raises(ValueError, match="boom"):
        store.call("k", fn, x)


def test_auxdata_is_json_not_pickle(rng, tmp_path):
    """ADVICE r5 #3: exported files must not depend on pickle for auxdata
    (arbitrary-code-execution hazard on shared cache dirs) — the enum-
    carrying Objective round-trips through the JSON codec."""
    from photon_tpu.utils.aot import _deserialize_auxdata, _serialize_auxdata

    aux = (TaskType.LOGISTIC_REGRESSION, ("data", None), False, 3, "s")
    blob = _serialize_auxdata(aux)
    assert b"photon_tpu" in blob or b"{" in blob  # JSON, readable
    assert _deserialize_auxdata(blob) == aux
    # a pickle-only payload type fails loudly at EXPORT time
    import pytest

    with pytest.raises(TypeError, match="auxdata"):
        _serialize_auxdata(object())


def test_store_key_covers_jax_version_and_schema(tmp_path, monkeypatch):
    """A jax upgrade or a ladder-schema bump must MISS (re-export fresh),
    never attempt to replay a blob a different jax serialized."""
    store = AotStore(str(tmp_path))
    fp = "ab" * 8
    p_now = store._path("k", fp)
    monkeypatch.setattr(jax, "__version__", "999.999.999")
    assert store._path("k", fp) != p_now
    monkeypatch.undo()
    assert store._path("k", fp) == p_now  # deterministic within a version
    assert AotStore(str(tmp_path), schema="v2")._path("k", fp) != p_now


def test_store_warmup_preloads_entries(tmp_path, monkeypatch):
    """warmup(entries) exports+compiles everything up front: afterwards a
    call must replay from the warmed store, never export again."""
    import photon_tpu.utils.aot as aot_mod

    @jax.jit
    def double(x):
        return x * 2.0 + 1.0

    @jax.jit
    def triple(x):
        return x * 3.0

    x = jnp.arange(8, dtype=jnp.float32)
    store = AotStore(str(tmp_path))
    entries = [("double", double, (x,)), ("triple", triple, (x,))]
    assert store.warmup(entries) == 2
    assert len([f for f in os.listdir(tmp_path)
                if f.endswith(".jaxexp")]) == 2

    def boom(*a, **kw):
        raise AssertionError("warm store re-exported")

    monkeypatch.setattr(aot_mod, "export_program", boom)
    np.testing.assert_array_equal(store.call("double", double, x),
                                  np.arange(8, dtype=np.float32) * 2 + 1)
    # a COLD process (fresh store over the same dir) also replays
    fresh = AotStore(str(tmp_path))
    np.testing.assert_array_equal(fresh.call("triple", triple, x),
                                  np.arange(8, dtype=np.float32) * 3)
