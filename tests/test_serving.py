"""Online serving tier (photon_tpu/serving): coefficient-store lookups +
mmap persistence, the pow2 AOT program ladder's never-retrace guarantee,
micro-batching dispatcher semantics, and THE acceptance parity —
dispatcher-batched scores bit-identical to the offline drivers/score.py
path for the same model and rows, including the cold-miss
fixed-effect-only fallback.

Marked `release_programs`: the ladder compiles one program per rung per
configuration; teardown drops them (tests/conftest.py).
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax.numpy as jnp

from photon_tpu import serving, telemetry
from photon_tpu.telemetry import trace
from photon_tpu.data.matrix import SparseRows
from photon_tpu.game.dataset import GameData
from photon_tpu.game.model import (FixedEffectModel, GameModel,
                                   RandomEffectModel)
from photon_tpu.game.scoring import score_game
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.ops.losses import TaskType
from photon_tpu.serving.__main__ import build_demo_model

pytestmark = pytest.mark.release_programs

SPARSE_K = 3


@pytest.fixture(autouse=True)
def _detached():
    yield
    telemetry.finish_run()


@pytest.fixture(scope="module")
def demo():
    """(model, store, ladder): one ladder for the whole module — shared
    shapes keep the compile count at one program per rung."""
    model, _ = build_demo_model(seed=7)
    store = serving.CoefficientStore.from_game_model(model)
    ladder = serving.ProgramLadder(store, ladder=(4, 8),
                                   sparse_k={"member": SPARSE_K},
                                   output_mean=True)
    return model, store, ladder


def _requests(rng, model, n, unseen_every=5):
    """n ragged requests over the demo model's shards; every
    ``unseen_every``-th entity key is unknown to the store."""
    d_f = int(model["fixed"].model.coefficients.dim)
    d_r = model["perEntity"].dim
    E = model["perEntity"].n_entities
    xg = rng.normal(size=(n, d_f)).astype(np.float32)
    ind = rng.integers(0, d_r, size=(n, SPARSE_K)).astype(np.int32)
    val = rng.normal(size=(n, SPARSE_K)).astype(np.float32)
    offs = rng.normal(size=n).astype(np.float32)
    ents = [f"zz{i}" if i % unseen_every == 0 else f"e{i % E:03d}"
            for i in range(n)]
    reqs = [serving.ScoreRequest(
        features={"global": xg[i], "member": (ind[i], val[i])},
        entities={"memberId": ents[i]}, offset=float(offs[i]))
        for i in range(n)]
    data = GameData.build(np.zeros(n, np.float32),
                          {"global": xg, "member": SparseRows(ind, val, d_r)},
                          {"memberId": np.asarray(ents)}, offsets=offs)
    return reqs, data, ents


# ----------------------------------------------------------------- the store
class TestCoefficientStore:
    def test_lookup_seen_unseen_and_zero_row(self, demo):
        model, store, _ = demo
        re = model["perEntity"]
        ids, miss = store.lookup("perEntity", ["e003", "nope", "e000"])
        assert miss == 1
        assert ids.tolist() == [3, re.n_entities, 0]
        # the cold-miss row is all-zero: the graceful-degradation row
        assert (store.random["perEntity"].coefficients[-1] == 0).all()
        # matches the offline model's own unseen-entity convention
        np.testing.assert_array_equal(
            ids, re.dense_ids(np.asarray(["e003", "nope", "e000"])))

    def test_save_open_roundtrip_mmap(self, demo, tmp_path):
        _, store, _ = demo
        store.save(tmp_path / "s")
        back = serving.CoefficientStore.open(tmp_path / "s", mmap=True)
        assert back.order == store.order and back.task == store.task
        np.testing.assert_array_equal(back.fixed["fixed"].weights,
                                      store.fixed["fixed"].weights)
        np.testing.assert_array_equal(
            back.random["perEntity"].coefficients,
            store.random["perEntity"].coefficients)
        # mmap=True really maps (no heap copy of a multi-GB store)
        assert isinstance(back.random["perEntity"].coefficients, np.memmap)
        ids_a, _ = store.lookup("perEntity", ["e001", "x"])
        ids_b, _ = back.lookup("perEntity", ["e001", "x"])
        np.testing.assert_array_equal(ids_a, ids_b)

    def test_save_kill_mid_write_is_crash_consistent(self, demo, tmp_path):
        """Kill-mid-write regression (elastic-runs round): `save` commits
        payload files temp+fsync+rename-first and the manifest LAST, so a
        preemption during the write leaves (a) a fresh directory with NO
        manifest — `open` fails cleanly instead of reading a torn .npy —
        and (b) a re-save over the old store either the complete old or
        complete new manifest, with every referenced block loadable."""
        from photon_tpu import checkpoint

        _, store, _ = demo
        out = tmp_path / "s"
        # (a) fresh save killed in the write phase (before any rename)
        with pytest.raises(checkpoint.InjectedFault):
            with checkpoint.fault_plan(
                    checkpoint.FaultPlan.kill_at("commit", 1)):
                store.save(out)
        assert not (out / "serving_store.json").exists()
        with pytest.raises(FileNotFoundError):
            serving.CoefficientStore.open(out)
        # (b) retry completes; then a killed RE-save (mid manifest
        # commit — the LAST commit point of a save) leaves the previous
        # committed store fully loadable
        with checkpoint.record_sites() as rec:
            store.save(out)
        with pytest.raises(checkpoint.InjectedFault):
            with checkpoint.fault_plan(
                    checkpoint.FaultPlan.kill_at("commit",
                                                 rec.hits["commit"])):
                store.save(out)
        back = serving.CoefficientStore.open(out, mmap=False)
        np.testing.assert_array_equal(
            back.random["perEntity"].coefficients,
            store.random["perEntity"].coefficients)

    def test_open_rejects_foreign_dir(self, tmp_path):
        (tmp_path / "serving_store.json").write_text('{"format": "nope"}')
        with pytest.raises(ValueError, match="not a"):
            serving.CoefficientStore.open(tmp_path)

    def test_reload_requires_identical_shapes(self, demo):
        model, store, _ = demo
        other = serving.CoefficientStore.from_game_model(model)
        store.reload_coefficients(other)  # identical shapes: fine
        small, _ = build_demo_model(seed=1, n_entities=4)
        with pytest.raises(ValueError, match="identically-shaped"):
            store.reload_coefficients(
                serving.CoefficientStore.from_game_model(small))

    def test_paldb_directory_equivalence(self, demo, tmp_path):
        from photon_tpu import native

        if not native.available():
            pytest.skip("native toolchain unavailable")
        model, store, _ = demo
        pstore = serving.CoefficientStore.from_game_model(model, paldb=True)
        keys = ["e000", "e007", "absent", "e015"]
        np.testing.assert_array_equal(store.lookup("perEntity", keys)[0],
                                      pstore.lookup("perEntity", keys)[0])
        pstore.save(tmp_path / "p")
        back = serving.CoefficientStore.open(tmp_path / "p")
        np.testing.assert_array_equal(back.lookup("perEntity", keys)[0],
                                      store.lookup("perEntity", keys)[0])


# -------------------------------------------------------------- the programs
class TestProgramLadder:
    def test_bucket_selection(self, demo):
        _, _, ladder = demo
        assert [ladder.bucket_for(n) for n in (1, 4, 5, 8)] == [4, 4, 8, 8]
        with pytest.raises(ValueError, match="exceeds ladder top"):
            ladder.bucket_for(9)

    def test_non_pow2_ladder_rejected(self, demo):
        _, store, _ = demo
        with pytest.raises(ValueError, match="pow2"):
            serving.ProgramLadder(store, ladder=(4, 6))

    def test_mixed_sizes_never_retrace(self, demo):
        """THE steady-state law: any mix of request sizes compiles at
        most one program per rung (TraceSignatureLog-asserted)."""
        _, _, ladder = demo
        before = len(ladder.signature_log.signatures("serving.score"))
        for B in (4, 8, 4, 8, 4):
            args = ladder.example_args(B)
            ladder.score_padded(args[0], args[1], args[2])
        n_sigs = ladder.assert_no_retrace()
        assert n_sigs <= len(ladder.ladder)
        assert n_sigs >= max(before, 2)  # both rungs actually dispatched

    def test_aot_export_replay_bitwise(self, demo, tmp_path):
        """The AOT plane: warmup exports one program per rung; a FRESH
        ladder over the same store replays (no export) bit-identically."""
        model, store, _ = demo
        aot = str(tmp_path / "aot")
        ladder = serving.ProgramLadder(store, ladder=(4,),
                                       sparse_k={"member": SPARSE_K},
                                       aot_dir=aot, model_tag="demo")
        assert ladder.warmup() == 1
        files = [f for f in os.listdir(aot) if f.endswith(".jaxexp")]
        assert len(files) == 1  # one export per (model, rung)
        rng = np.random.default_rng(3)
        reqs, data, _ = _requests(rng, model, 4)
        replay = serving.ProgramLadder(store, ladder=(4,),
                                       sparse_k={"member": SPARSE_K},
                                       aot_dir=aot, model_tag="demo")
        d = serving.MicroBatchDispatcher(replay, max_batch=4,
                                         max_delay_us=100)
        try:
            got = np.asarray([f.result(timeout=30)
                              for f in [d.submit(q) for q in reqs]],
                             np.float32)
        finally:
            d.close()
        want = np.asarray(model.mean(score_game(model, data)), np.float32)
        assert got.tobytes() == want.tobytes()
        # the replay ladder REPLAYED — it exported nothing new
        assert sorted(os.listdir(aot)) == sorted(files)

    def test_schema_tag_isolates_exports(self, demo, tmp_path):
        """A ladder-schema redesign (different AotStore schema tag) must
        MISS the old files, never replay them."""
        from photon_tpu.utils.aot import AotStore

        store_a = AotStore(str(tmp_path), schema="serving-ladder-v1")
        store_b = AotStore(str(tmp_path), schema="serving-ladder-v2")
        fp = "00" * 8
        assert store_a._path("k", fp) != store_b._path("k", fp)


# ---------------------------------------------------- dispatcher + acceptance
class TestDispatcherParity:
    def test_bitwise_parity_with_offline_driver(self, demo, tmp_path):
        """ACCEPTANCE: the full offline path — save_game_model → Avro
        scoring data → drivers/score.py run_scoring — against the same
        rows dispatched through the micro-batcher: bit-identical scores,
        including the cold-miss fixed-effect-only rows."""
        from photon_tpu.data.avro_io import write_avro
        from photon_tpu.data.index_map import INTERCEPT_KEY, IndexMap
        from photon_tpu.data.ingest import training_example_schema
        from photon_tpu.data.model_io import load_game_model, save_game_model
        from photon_tpu.drivers.score import ScoringParams, run_scoring

        rng = np.random.default_rng(11)
        n, E = 53, 7
        task = TaskType.LOGISTIC_REGRESSION
        # feature shards: "fs" = bag g features a, c + intercept (d=3);
        # "us" = bag pu feature b, no intercept (d=1)
        imap_f = IndexMap().build(["a", "c", INTERCEPT_KEY]).freeze()
        imap_u = IndexMap().build(["b"]).freeze()
        keys = np.asarray(sorted(f"u{i}" for i in range(E)))
        model = GameModel({
            "fixed": FixedEffectModel(GeneralizedLinearModel(
                Coefficients(jnp.asarray(
                    rng.normal(size=3).astype(np.float32))), task), "fs"),
            "perUser": RandomEffectModel(
                entity_name="userId", feature_shard="us", task=task,
                coefficients=jnp.asarray(
                    rng.normal(size=(E, 1)).astype(np.float32)),
                entity_keys=keys,
                key_to_index={k: i for i, k in enumerate(keys.tolist())}),
        }, task)
        model_dir = tmp_path / "model"
        save_game_model(str(model_dir), model,
                        {"fixed": imap_f, "perUser": imap_u})

        a = rng.normal(size=n).astype(np.float32)
        c = rng.normal(size=n).astype(np.float32)
        b = rng.normal(size=n).astype(np.float32)
        offs = rng.normal(size=n).astype(np.float32)
        # u7/u8 never trained: the driver maps them to the zero row, the
        # dispatcher counts them as cold misses — SAME score either way
        users = [f"u{i % (E + 2)}" for i in range(n)]
        schema = training_example_schema(feature_bags=("g", "pu"),
                                         entity_fields=("userId",))
        recs = [{"response": float(i % 2), "offset": float(offs[i]),
                 "weight": None, "uid": f"r{i}", "userId": users[i],
                 "g": [{"name": "a", "term": "", "value": float(a[i])},
                       {"name": "c", "term": "", "value": float(c[i])}],
                 "pu": [{"name": "b", "term": "", "value": float(b[i])}]}
                for i in range(n)]
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        write_avro(data_dir / "part-0.avro", recs, schema, block_records=16)

        out = run_scoring(ScoringParams(
            model_dir=str(model_dir), data_path=str(data_dir),
            output_dir=str(tmp_path / "out"),
            feature_shards={"fs": {"bags": ["g"], "has_intercept": True},
                            "us": {"bags": ["pu"], "has_intercept": False}},
            entity_fields=["userId"]))
        assert out.scores.shape == (n,)

        # the serving side, built from the SAME saved artifacts
        loaded, _ = load_game_model(str(model_dir))
        store = serving.CoefficientStore.from_game_model(loaded)
        # rungs ≥ 8: bit-parity-safe vs the driver's 4096-row chunks
        # (sub-8 CPU matvec kernels drift ULPs — ProgramLadder docstring)
        ladder = serving.ProgramLadder(store, ladder=(8, 16),
                                       output_mean=True)
        d = serving.MicroBatchDispatcher(ladder, max_batch=16,
                                         max_delay_us=500)
        r = telemetry.start_run("parity")
        try:
            futs = [d.submit(serving.ScoreRequest(
                features={"fs": np.asarray([a[i], c[i], 1.0], np.float32),
                          "us": np.asarray([b[i]], np.float32)},
                entities={"userId": users[i]}, offset=float(offs[i])))
                for i in range(n)]
            got = np.asarray([f.result(timeout=30) for f in futs])
        finally:
            d.close()
            telemetry.finish_run()
        # driver scores are the f32 device result widened to f64 — exact,
        # so bitwise f64 comparison is the honest equality
        np.testing.assert_array_equal(got.astype(np.float64), out.scores)
        ladder.assert_no_retrace()
        n_cold = sum(1 for u in users if u in ("u7", "u8"))
        assert r.counters["serving.cold_misses"] == float(n_cold) > 0

    def test_margin_head_matches_score_game(self, demo):
        """output_mean=False serves the raw margin — score_game verbatim."""
        model, store, _ = demo
        ladder = serving.ProgramLadder(store, ladder=(8,),
                                       sparse_k={"member": SPARSE_K},
                                       output_mean=False)
        rng = np.random.default_rng(5)
        reqs, data, _ = _requests(rng, model, 8)
        d = serving.MicroBatchDispatcher(ladder, max_batch=8,
                                         max_delay_us=200)
        try:
            got = np.asarray([f.result(timeout=30)
                              for f in [d.submit(q) for q in reqs]],
                             np.float32)
        finally:
            d.close()
        want = np.asarray(score_game(model, data), np.float32)
        assert got.tobytes() == want.tobytes()


class TestDispatcherBehavior:
    def test_single_request_flushes_on_deadline(self, demo):
        _, _, ladder = demo
        d = serving.MicroBatchDispatcher(ladder, max_delay_us=1000)
        rng = np.random.default_rng(0)
        model = demo[0]
        reqs, _, _ = _requests(rng, model, 1)
        try:
            assert isinstance(d.score(reqs[0], timeout=30), float)
        finally:
            d.close()

    def test_counters_events_and_latency(self, demo, tmp_path):
        model, _, ladder = demo
        rng = np.random.default_rng(2)
        n = 11
        reqs, _, ents = _requests(rng, model, n)
        jsonl = str(tmp_path / "serving.jsonl")
        r = telemetry.start_run("disp", jsonl_path=jsonl)
        d = serving.MicroBatchDispatcher(ladder, max_batch=8,
                                         max_delay_us=2000)
        try:
            futs = [d.submit(q) for q in reqs]
            [f.result(timeout=30) for f in futs]
        finally:
            d.close()
            telemetry.finish_run()
        assert r.counters["serving.requests"] == float(n)
        assert r.counters["serving.batches"] >= 2  # 11 > max_batch=8
        n_unseen = sum(1 for e in ents if e.startswith("zz"))
        assert r.counters["serving.cold_misses"] == float(n_unseen)
        assert "serving.pad_waste" in r.counters
        assert "serving.batch_fill" in r.gauges
        batches = list(telemetry.read_jsonl(jsonl, kind="serving_batch"))
        assert sum(e["rows"] for e in batches) == n
        assert all(e["bucket"] in ladder.ladder for e in batches)
        # close() gauged the percentile summary into the run
        assert r.gauges["serving.latency_p50_ms"] <= \
            r.gauges["serving.latency_p99_ms"]
        st = d.latency_stats()
        assert st["n"] == n and st["p50_ms"] <= st["p95_ms"] <= st["p99_ms"]

    def test_close_flushes_queue_and_rejects_after(self, demo):
        model, _, ladder = demo
        rng = np.random.default_rng(4)
        reqs, _, _ = _requests(rng, model, 6)
        d = serving.MicroBatchDispatcher(ladder, max_batch=8,
                                         max_delay_us=10_000_000)
        futs = [d.submit(q) for q in reqs[:3]]
        d.close()  # must flush the queued 3, not abort them
        assert all(isinstance(f.result(timeout=5), float) for f in futs)
        with pytest.raises(RuntimeError, match="closed"):
            d.submit(reqs[3])

    def test_bad_request_fails_its_future_only(self, demo):
        model, _, ladder = demo
        rng = np.random.default_rng(6)
        reqs, _, _ = _requests(rng, model, 2)
        d = serving.MicroBatchDispatcher(ladder, max_delay_us=500)
        try:
            bad = serving.ScoreRequest(features={}, entities={})
            fb = d.submit(bad)
            with pytest.raises(Exception):
                fb.result(timeout=30)
            # the dispatcher survives and serves the next request
            assert isinstance(d.score(reqs[0], timeout=30), float)
        finally:
            d.close()


# ---------------------------------------------------------- request tracing
class TestDispatcherTracing:
    """telemetry/trace.py riding the real dispatcher: a deterministically
    slow hop must be NAMED by the slowest exemplar, arming tracing must
    not mint new rung signatures, and the disarmed path stays free."""

    def test_slow_device_flush_names_the_hop(self, demo):
        """THE acceptance: inject a deterministic slow hop (a sleeping
        executor) and the slowest-trace exemplar names it."""
        model, _, ladder = demo
        rng = np.random.default_rng(11)
        reqs, _, _ = _requests(rng, model, 4)
        d = serving.MicroBatchDispatcher(ladder, max_batch=8,
                                         max_delay_us=500)
        real_execute = d._executor.execute

        def slow_execute(batch):
            time.sleep(0.05)
            return real_execute(batch)

        d._executor.execute = slow_execute
        try:
            with trace.tracing(k=2) as res:
                futs = [d.submit(q) for q in reqs]
                [f.result(timeout=30) for f in futs]
                slow = res.slowest()
        finally:
            d.close()
        assert slow is not None and slow["slowest_hop"] == "device_flush"
        assert slow["breakdown_ms"]["device_flush"] >= 40.0
        assert res.n_offered == len(reqs)
        # the full hop chain survives the three thread crossings
        names = [h["name"] for h in slow["hops"]]
        assert names == ["queue_wait", "device_flush", "retire_wait"]

    def test_slow_queue_wait_names_the_hop(self, demo):
        """Same acceptance from the other side: a long batching delay on
        a lone request makes queue_wait the dominant hop."""
        model, _, ladder = demo
        rng = np.random.default_rng(12)
        reqs, _, _ = _requests(rng, model, 1)
        d = serving.MicroBatchDispatcher(ladder, max_batch=8,
                                         max_delay_us=80_000)
        try:
            with trace.tracing(k=1) as res:
                assert isinstance(d.score(reqs[0], timeout=30), float)
                slow = res.slowest()
        finally:
            d.close()
        assert slow is not None and slow["slowest_hop"] == "queue_wait"
        assert slow["breakdown_ms"]["queue_wait"] >= 60.0

    def test_armed_tracing_never_retraces(self, demo):
        model, _, ladder = demo
        rng = np.random.default_rng(13)
        reqs, _, _ = _requests(rng, model, 18)
        d = serving.MicroBatchDispatcher(ladder, max_batch=8,
                                         max_delay_us=2000)
        try:
            # untraced warm drive populates both rungs' signatures...
            futs = [d.submit(q) for q in reqs[:9]]
            [f.result(timeout=30) for f in futs]
            before = ladder.assert_no_retrace()
            # ...then the armed drive must not mint a single new one
            with trace.tracing(k=4):
                futs = [d.submit(q) for q in reqs[9:]]
                [f.result(timeout=30) for f in futs]
        finally:
            d.close()
        assert ladder.assert_no_retrace() == before

    def test_disarmed_requests_carry_no_trace(self, demo):
        from photon_tpu.serving.dispatcher import _Pending
        model, _, ladder = demo
        rng = np.random.default_rng(14)
        reqs, _, _ = _requests(rng, model, 2)
        # the request object is where the trace rides; disarmed it is None
        assert _Pending(reqs[0]).trace is None
        with trace.tracing(k=2):
            assert _Pending(reqs[0]).trace is not None
        assert trace.reservoir() is None


# ------------------------------------------------------------ overload policy
class TestAdmission:
    """serving/admission.py: deadlines, watermark shedding, bounded
    submit. The invariants: every dropped request resolves to a typed
    `Shed` (futures never leak, callers never block forever), the
    counters add up, and the policy layer never changes the device
    programs (the live half of the registered
    `serving_admission_program_invariance` contract)."""

    def test_default_policy_is_off(self):
        p = serving.AdmissionPolicy()
        assert not p.active
        ctrl = serving.AdmissionController(p)
        assert ctrl.submit_shed_reason(10**9) is None
        assert ctrl.deadline_ns(serving.ScoreRequest(features={}), 0) is None
        assert ctrl.submit_timeout_s(None) is None  # legacy: block forever

    def test_shed_is_typed_and_falsy(self):
        s = serving.Shed("watermark", queue_depth=3)
        assert not s and s.reason == "watermark"

    def test_deadline_expired_resolves_shed(self, demo):
        """deadline_ms=0.0 expires every request at its first batch-slot
        check: the future resolves to Shed("deadline_expired"), counted,
        and the batch dispatches WITHOUT them."""
        model, _, ladder = demo
        rng = np.random.default_rng(8)
        reqs, _, _ = _requests(rng, model, 5)
        r = telemetry.start_run("admission_deadline")
        d = serving.MicroBatchDispatcher(
            ladder, max_batch=8, max_delay_us=500,
            policy=serving.AdmissionPolicy(deadline_ms=0.0))
        try:
            res = [d.submit(q).result(timeout=30) for q in reqs]
        finally:
            d.close()
            telemetry.finish_run()
        assert all(isinstance(v, serving.Shed)
                   and v.reason == "deadline_expired" for v in res)
        assert r.counters["serving.deadline_expired"] == 5.0
        assert r.counters["serving.admitted"] == 5.0
        assert "serving.requests" not in r.counters  # nothing dispatched

    def test_request_deadline_overrides_policy(self, demo):
        """A per-request deadline_ms wins over the policy default: the
        doomed request sheds, its batch-mates score."""
        model, _, ladder = demo
        rng = np.random.default_rng(9)
        reqs, data, _ = _requests(rng, model, 8)
        reqs[2] = serving.ScoreRequest(
            features=reqs[2].features, entities=reqs[2].entities,
            offset=reqs[2].offset, deadline_ms=0.0)
        d = serving.MicroBatchDispatcher(
            ladder, max_batch=8, max_delay_us=50_000,
            policy=serving.AdmissionPolicy(deadline_ms=10_000.0))
        try:
            res = [d.submit(q).result(timeout=30) for q in reqs]
        finally:
            d.close()
        assert isinstance(res[2], serving.Shed)
        assert res[2].reason == "deadline_expired"
        alive = [i for i in range(8) if i != 2]
        assert all(isinstance(res[i], float) for i in alive)
        want = np.asarray(model.mean(score_game(model, data)), np.float32)
        for i in alive:  # survivors land on rung 8: bit-parity territory
            assert np.float32(res[i]) == want[i]

    def test_watermark_sheds_at_submit(self, demo):
        model, _, ladder = demo
        rng = np.random.default_rng(10)
        reqs, _, _ = _requests(rng, model, 6)
        r = telemetry.start_run("admission_watermark")
        d = serving.MicroBatchDispatcher(
            ladder, max_batch=8, max_delay_us=500,
            policy=serving.AdmissionPolicy(shed_watermark=0))
        try:
            res = [d.submit(q).result(timeout=30) for q in reqs]
        finally:
            d.close()
            telemetry.finish_run()
        assert all(isinstance(v, serving.Shed) and v.reason == "watermark"
                   for v in res)
        assert r.counters["serving.shed"] == 6.0
        assert "serving.admitted" not in r.counters  # never enqueued

    def test_bounded_submit_never_blocks_forever(self, demo):
        """queue_depth=1 + submit(timeout=0): a full queue sheds
        ("queue_full") instead of blocking; every future resolves to a
        float or a typed Shed and the accounting closes."""
        model, _, ladder = demo
        rng = np.random.default_rng(12)
        reqs, _, _ = _requests(rng, model, 200)
        r = telemetry.start_run("admission_bounded")
        d = serving.MicroBatchDispatcher(ladder, max_batch=8,
                                         max_delay_us=100, queue_depth=1)
        try:
            futs = [d.submit(q, timeout=0.0) for q in reqs]
            res = [f.result(timeout=60) for f in futs]
        finally:
            d.close()
            telemetry.finish_run()
        sheds = [v for v in res if isinstance(v, serving.Shed)]
        scored = [v for v in res if isinstance(v, float)]
        assert len(sheds) + len(scored) == 200
        assert sheds and all(s.reason == "queue_full" for s in sheds)
        assert r.counters["serving.shed"] == float(len(sheds))
        assert r.counters["serving.admitted"] == float(len(scored))

    def test_close_resolves_expired_inflight_futures(self, demo):
        """THE close() guarantee with overload policy armed: requests
        whose deadline expired while batched-but-undispatched resolve at
        close (shed, never leaked) — the dispatcher holds them in its
        assembly loop (max_delay 10 s, batch unfilled) until close
        flushes, and the flush-time deadline check sheds them all."""
        model, _, ladder = demo
        rng = np.random.default_rng(13)
        reqs, _, _ = _requests(rng, model, 6)
        r = telemetry.start_run("admission_close")
        d = serving.MicroBatchDispatcher(
            ladder, max_batch=8, max_delay_us=10_000_000,
            policy=serving.AdmissionPolicy(deadline_ms=100.0))
        futs = [d.submit(q) for q in reqs]
        import time as _time

        _time.sleep(0.15)  # all six expire while awaiting batch-mates
        d.close()
        telemetry.finish_run()
        assert all(f.done() for f in futs)  # nothing leaked
        res = [f.result(timeout=1) for f in futs]
        assert all(isinstance(v, serving.Shed)
                   and v.reason == "deadline_expired" for v in res)
        assert r.counters["serving.deadline_expired"] == 6.0

    def test_admission_on_off_never_retraces(self, demo):
        """The same ladder serves admission-off and admission-on traffic
        with zero new signatures — the live face of the registered
        program-invariance contract."""
        model, _, ladder = demo
        rng = np.random.default_rng(14)
        reqs, _, _ = _requests(rng, model, 8)
        before = len(ladder.signature_log.signatures("serving.score"))
        d_off = serving.MicroBatchDispatcher(ladder, max_batch=8,
                                             max_delay_us=50_000)
        try:
            off = [d_off.submit(q).result(timeout=30) for q in reqs]
        finally:
            d_off.close()
        d_on = serving.MicroBatchDispatcher(
            ladder, max_batch=8, max_delay_us=50_000,
            policy=serving.AdmissionPolicy(deadline_ms=10_000.0,
                                           shed_watermark=1 << 20,
                                           submit_timeout_s=5.0))
        try:
            on = [d_on.submit(q).result(timeout=30) for q in reqs]
        finally:
            d_on.close()
        assert off == on  # same model, same rows, same programs
        assert ladder.assert_no_retrace() >= before


# ------------------------------------------------------- hot-swap concurrency
class TestHotSwapConcurrency:
    """`CoefficientStore.reload_coefficients` under an in-flight
    dispatcher flush: every request scores bit-identically under EITHER
    the old or the new model — one coefficient generation per dispatch,
    never a torn fixed-from-A/random-from-B mix — and each swap counts
    on `serving.hot_swaps`."""

    def _scores(self, store, reqs) -> np.ndarray:
        ladder = serving.ProgramLadder(store, ladder=(8, 16),
                                       sparse_k={"member": SPARSE_K},
                                       output_mean=True)
        d = serving.MicroBatchDispatcher(ladder, max_delay_us=200)
        try:
            futs = [d.submit(r) for r in reqs]
            return np.asarray([f.result(timeout=60) for f in futs])
        finally:
            d.close()

    def test_requests_see_old_or_new_never_torn(self):
        import threading

        model_a, _ = build_demo_model(seed=7)
        model_b, _ = build_demo_model(seed=21)  # same structure, new values
        store_a = serving.CoefficientStore.from_game_model(model_a)
        store_b = serving.CoefficientStore.from_game_model(model_b)
        rng = np.random.default_rng(11)
        reqs, _, _ = _requests(rng, model_a, 48)
        # reference scores under each pure generation (rungs ≥ 8 are
        # row-stable across batch compositions — docs/SERVING.md)
        ref_a = self._scores(serving.CoefficientStore.from_game_model(
            model_a), reqs)
        ref_b = self._scores(serving.CoefficientStore.from_game_model(
            model_b), reqs)
        assert (ref_a != ref_b).any()

        run = telemetry.start_run("hot_swap_test")
        live = serving.CoefficientStore.from_game_model(model_a)
        ladder = serving.ProgramLadder(live, ladder=(8, 16),
                                       sparse_k={"member": SPARSE_K},
                                       output_mean=True)
        d = serving.MicroBatchDispatcher(ladder, max_delay_us=100)
        results: dict = {}
        stop = threading.Event()
        n_swaps = 0

        def swapper():
            nonlocal n_swaps
            import time as _time

            flip = [store_b, store_a]
            while not stop.is_set():
                live.reload_coefficients(flip[n_swaps % 2])
                n_swaps += 1
                _time.sleep(0.002)  # don't starve the 1-core CI box

        t = threading.Thread(target=swapper)
        t.start()
        try:
            for rep in range(6):
                futs = [(i, d.submit(r)) for i, r in enumerate(reqs)]
                for i, f in futs:
                    results.setdefault(i, []).append(f.result(timeout=60))
        finally:
            stop.set()
            t.join()
            d.close()
        assert n_swaps >= 2
        for i, got in results.items():
            for v in got:
                assert v == ref_a[i] or v == ref_b[i], (
                    f"request {i} scored {v!r}: neither the old model's "
                    f"{ref_a[i]!r} nor the new model's {ref_b[i]!r} — "
                    "a torn coefficient generation")
        assert run.counters.get("serving.hot_swaps") == n_swaps
        ladder.assert_no_retrace()  # swaps never retrace the rungs

    def test_mid_swap_kill_under_load_keeps_old_model(self, tmp_path):
        """The continual flywheel's crash story under LIVE dispatcher
        load: a kill at the ``swap_publish`` fault site (after the new
        version directory is written, before the CURRENT pointer commits)
        aborts the hot swap with every in-flight request still resolving
        — all on the OLD model, bit-identically — and nothing published.
        The next clean swap then cuts the same traffic over to the new
        model."""
        from photon_tpu import checkpoint, continual

        model_a, _ = build_demo_model(seed=7)
        model_b, _ = build_demo_model(seed=21)
        store_b = serving.CoefficientStore.from_game_model(model_b)
        rng = np.random.default_rng(17)
        reqs, _, _ = _requests(rng, model_a, 32)
        ref_a = self._scores(serving.CoefficientStore.from_game_model(
            model_a), reqs)
        ref_b = self._scores(store_b, reqs)
        assert (ref_a != ref_b).any()

        root = str(tmp_path / "pub")
        live = serving.CoefficientStore.from_game_model(model_a)
        ladder = serving.ProgramLadder(live, ladder=(8, 16),
                                       sparse_k={"member": SPARSE_K},
                                       output_mean=True)
        d = serving.MicroBatchDispatcher(ladder, max_delay_us=100)
        try:
            futs = [d.submit(r) for r in reqs]  # sustained in-flight load
            with pytest.raises(checkpoint.InjectedFault):
                with checkpoint.fault_plan(
                        checkpoint.FaultPlan.kill_at("swap_publish", 1)):
                    continual.hot_swap(live, store_b, root=root,
                                       probe=continual.ParityProbe(
                                           bound=1e9))
            got = np.asarray([f.result(timeout=60) for f in futs])
            # the killed swap never reloaded: everything served OLD
            np.testing.assert_array_equal(got, ref_a)
            from photon_tpu.continual.swap import current_version

            assert current_version(root) is None  # nothing published
            # the half-written version directory from the kill is swept
            # by the next successful publish, which also cuts over
            continual.hot_swap(live, store_b, root=root,
                               probe=continual.ParityProbe(bound=1e9))
            assert current_version(root) is not None
            futs2 = [d.submit(r) for r in reqs]
            got2 = np.asarray([f.result(timeout=60) for f in futs2])
            np.testing.assert_array_equal(got2, ref_b)
        finally:
            d.close()
        ladder.assert_no_retrace()  # neither kill nor swap retraced

    def test_reload_still_rejects_mismatched_shapes(self):
        model, _ = build_demo_model(seed=7)
        small, _ = build_demo_model(seed=7, n_entities=8)
        store = serving.CoefficientStore.from_game_model(model)
        with pytest.raises(ValueError, match="identically-shaped"):
            store.reload_coefficients(
                serving.CoefficientStore.from_game_model(small))


def test_selftest_cli_end_to_end():
    """`python -m photon_tpu.serving --selftest --json` — the CI smoke
    face of this whole module — exits 0 with every check ok."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the CLI must self-provision its platform
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_tpu.serving", "--selftest", "--json"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["ok"] is True
    assert all(v == "ok" for v in report["checks"].values())
