"""data/index_map.py persistence + lookup semantics: the serving
coefficient store keys its entity→row directories on this machinery, so
the save/load round-trip (ordering, intercept id, unknown-key behavior,
delimiter escaping) and the PalDBIndexMap.build equivalence are tier-1
law, not incidental behavior."""
import numpy as np
import pytest

from photon_tpu.data.index_map import (DELIMITER, INTERCEPT_KEY, IndexMap,
                                       PalDBIndexMap, feature_key)


class TestIndexMap:
    def test_build_assigns_in_first_sight_order(self):
        m = IndexMap().build(["b", "a", "c", "a"])
        assert [m.index_of(k) for k in ("b", "a", "c")] == [0, 1, 2]
        assert len(m) == 3 and m.intercept_id is None

    def test_frozen_unknown_returns_null_id(self):
        m = IndexMap().build(["x"]).freeze()
        assert m.index_of("y") == IndexMap.NULL_ID
        assert m.get("y") == IndexMap.NULL_ID
        assert m.index_of("x") == 0  # frozen lookups still resolve

    def test_intercept_is_always_last(self):
        m = IndexMap().build(["a", INTERCEPT_KEY, "b"])
        assert m.has_intercept and m.intercept_id == len(m) - 1 == 2
        assert m.keys_in_order() == ["a", "b", INTERCEPT_KEY]
        # an unfrozen map re-asks: intercept stays last as keys grow
        m.index_of("z")
        assert m.intercept_id == 3 and m.index_of(INTERCEPT_KEY) == 3

    def test_save_load_roundtrip(self, tmp_path):
        m = IndexMap().build(
            [feature_key("age", "decade"), "plain", INTERCEPT_KEY])
        p = tmp_path / "imap.tsv"
        m.save(p)
        back = IndexMap.load(p)
        assert back.frozen and back.has_intercept
        assert back.keys_in_order() == m.keys_in_order()
        for k in m.keys_in_order():
            assert back.get(k) == m.get(k)
        assert back.get("unseen") == IndexMap.NULL_ID
        assert back.intercept_id == m.intercept_id

    def test_roundtrip_escapes_delimiter(self, tmp_path):
        key = feature_key("name", "term")  # embeds \x01
        assert DELIMITER in key
        m = IndexMap().build([key, "other"])
        m.save(tmp_path / "d.tsv")
        back = IndexMap.load(tmp_path / "d.tsv")
        assert back.get(key) == 0
        assert back.keys_in_order()[0] == key

    def test_roundtrip_without_intercept(self, tmp_path):
        m = IndexMap().build(["only"])
        m.save(tmp_path / "n.tsv")
        back = IndexMap.load(tmp_path / "n.tsv")
        assert not back.has_intercept and back.intercept_id is None
        assert back.get(INTERCEPT_KEY) == IndexMap.NULL_ID

    def test_load_rejects_foreign_file(self, tmp_path):
        (tmp_path / "x.tsv").write_text("not\tan\tindexmap\n")
        with pytest.raises(ValueError, match="not a photon_tpu index map"):
            IndexMap.load(tmp_path / "x.tsv")

    def test_key_of_reverse_lookup(self):
        m = IndexMap().build(["a", "b", INTERCEPT_KEY])
        assert m.key_of(0) == "a" and m.key_of(2) == INTERCEPT_KEY
        with pytest.raises(KeyError):
            m.key_of(99)


class TestPalDBIndexMap:
    @pytest.fixture(autouse=True)
    def _native(self):
        from photon_tpu import native

        if not native.available():
            pytest.skip("native toolchain unavailable")

    def test_build_equivalence_with_index_map(self):
        keys = [feature_key("f", str(i)) for i in range(40)]
        m = IndexMap().build(keys + [INTERCEPT_KEY]).freeze()
        p = PalDBIndexMap.build(m)
        assert len(p) == len(m) and p.intercept_id == m.intercept_id
        assert p.keys_in_order() == m.keys_in_order()
        for k in keys + [INTERCEPT_KEY, "unseen"]:
            assert p.get(k) == m.get(k)
        np.testing.assert_array_equal(
            p.lookup_batch(keys + ["unseen", INTERCEPT_KEY]),
            np.asarray([m.get(k)
                        for k in keys + ["unseen", INTERCEPT_KEY]]))

    def test_save_open_roundtrip(self, tmp_path):
        m = IndexMap().build(["a", "b", INTERCEPT_KEY]).freeze()
        p = PalDBIndexMap.build(m)
        path = str(tmp_path / "store.paldb")
        p.save(path)
        back = PalDBIndexMap.open(path)
        assert back.has_intercept and back.keys_in_order() == \
            m.keys_in_order()
        assert back.get("b") == 1 and back.get("zz") == IndexMap.NULL_ID

    def test_to_index_map_inverse(self):
        m = IndexMap().build(["x", "y"]).freeze()
        assert PalDBIndexMap.build(m).to_index_map().key_to_id == \
            m.key_to_id
