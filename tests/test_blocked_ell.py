"""BlockedEllRows: the blocked-ELL scatter-free sparse hot path
(data/matrix.py, round 12). Parity contract: every op and every solve
must agree with the SparseRows representation of the same matrix, with
all user-facing vectors in ORIGINAL column order — across resident,
lane-grid, streamed, and mesh paths.

Mirrors tests/test_permuted.py's representation-invariance suite for the
round-5 layout (reference: com.linkedin.photon.ml.data — LabeledPoint
math is identical whatever the underlying vector type).
"""
import dataclasses
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from photon_tpu.data.dataset import (cast_features, chunk_batch,
                                     chunk_blocked_ell, make_batch,
                                     pad_batch, shard_blocked_ell_batch)
from photon_tpu.data.matrix import (BlockedEllRows, ShardedBlockedEllRows,
                                    SparseRows, blocked_ell_from_scipy_csr,
                                    from_scipy_csr, last_column_is_intercept,
                                    matvec, matvec_lanes, rmatvec,
                                    rmatvec_lanes, shard_blocked_ell,
                                    sorted_segment_sum, sq_rmatvec,
                                    to_blocked_ell, weighted_gram)
from photon_tpu.models.training import train_glm, train_glm_grid
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.regularization import l2

# The mesh/grid/streamed cases compile multi-device solver programs; drop
# them at module teardown so the suite stays inside the live-executable
# envelope (see conftest).
pytestmark = pytest.mark.release_programs


def _power_law_sparse(rng, n=500, d=800, k=10, d_dense=32):
    """Zipf-ish column frequencies so the hot block, several ELL widths,
    and the occurrence buckets all fill. Duplicate (row, col) slots get
    value 0 (the padding convention)."""
    col = (rng.zipf(1.5, size=(n, k)).astype(np.int64) - 1) % (d - 1)
    val = rng.normal(size=(n, k)).astype(np.float32)
    order = np.argsort(col, axis=1, kind="stable")
    sorted_col = np.take_along_axis(col, order, axis=1)
    dup = sorted_col[:, 1:] == sorted_col[:, :-1]
    dupmask = np.zeros_like(col, bool)
    np.put_along_axis(dupmask, order[:, 1:], dup, axis=1)
    val[dupmask] = 0.0
    ind = np.concatenate([col, np.full((n, 1), d - 1)], axis=1).astype(
        np.int32)
    va = np.concatenate([val, np.ones((n, 1), np.float32)], axis=1)
    X = SparseRows(jnp.asarray(ind), jnp.asarray(va), d)
    B = to_blocked_ell(X, d_dense)
    return X, B


def _labels(rng, X):
    wt = rng.normal(size=X.n_features).astype(np.float32) * 0.5
    z = np.asarray(matvec(X, jnp.asarray(wt)))
    return jnp.asarray((rng.random(X.shape[0]) < 1 / (1 + np.exp(-z)))
                       .astype(np.float32))


# ------------------------------------------------------------ layout facts
def test_bell_roundtrip_and_layout(rng):
    X, B = _power_law_sparse(rng)
    d = X.n_features
    perm = np.asarray(B.perm_cols)
    inv = np.asarray(B.inv_perm)
    assert sorted(perm.tolist()) == list(range(d))
    np.testing.assert_array_equal(perm[inv], np.arange(d))
    v = rng.normal(size=d).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(B.to_model_space(B.from_model_space(v))), v)
    # intercept (original last column, in every row) must be hot
    assert B.last_col_pos < B.d_sel
    assert np.asarray(B.dense)[:, B.last_col_pos].min() == 1.0
    # ELL widths are a pow2 ladder, ascending, and every real tail nnz is
    # laid exactly once: padded slots carry value 0 at column 0
    widths = [v.shape[1] for v in B.ell_vals]
    assert widths == sorted(widths)
    assert all(w & (w - 1) == 0 for w in widths)
    laid = sum(int((np.asarray(v) != 0.0).sum()) for v in B.ell_vals)
    total = int((np.asarray(X.values) != 0.0).sum())
    # every tail nnz is laid exactly once (tail values are nonzero by
    # construction, padding slots are zero), and the tail is a subset of
    # the matrix's real nnz
    assert laid == B.tail_nnz <= total
    assert B.ell_slots >= B.tail_nnz
    assert B.tail_pad_waste >= 0.0
    # row_pos: every row maps into [0, B_total] (B_total = the zero slot)
    B_total = sum(v.shape[0] for v in B.ell_vals)
    rp = np.asarray(B.row_pos)
    assert rp.min() >= 0 and rp.max() <= B_total


def test_bell_matvec_rmatvec_parity(rng):
    X, B = _power_law_sparse(rng)
    n, d = X.shape
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    r = jnp.asarray(rng.normal(size=n).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(matvec(B, B.from_model_space(w))),
        np.asarray(matvec(X, w)), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(B.to_model_space(rmatvec(B, r))),
        np.asarray(rmatvec(X, r)), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(B.to_model_space(sq_rmatvec(B, r))),
        np.asarray(sq_rmatvec(X, r)), rtol=2e-4, atol=2e-4)


def test_bell_lane_ops_parity(rng):
    X, B = _power_law_sparse(rng)
    n, d = X.shape
    G = 4
    W = jnp.asarray(rng.normal(size=(d, G)).astype(np.float32))
    R = jnp.asarray(rng.normal(size=(n, G)).astype(np.float32))
    perm = jnp.asarray(B.perm_cols)
    inv = np.asarray(B.inv_perm)
    np.testing.assert_allclose(
        np.asarray(matvec_lanes(B, W[perm])),
        np.asarray(matvec_lanes(X, W)), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(rmatvec_lanes(B, R))[inv],
        np.asarray(rmatvec_lanes(X, R)), rtol=2e-4, atol=2e-4)


def test_bell_weighted_gram_parity(rng):
    X, B = _power_law_sparse(rng, n=200, d=120, k=6, d_dense=16)
    r = jnp.asarray(np.abs(rng.normal(size=200)).astype(np.float32))
    inv = np.asarray(B.inv_perm)
    g_ref = np.asarray(weighted_gram(X, r))
    g = np.asarray(weighted_gram(B, r))[inv][:, inv]
    np.testing.assert_allclose(g, g_ref, rtol=1e-4, atol=1e-4)


def test_bell_empty_tail(rng):
    # d_dense >= d: everything is hot, no ELL buckets at all
    X, B = _power_law_sparse(rng, n=100, d=40, k=5, d_dense=64)
    assert B.ell_vals == () and B.tail_nnz == 0
    assert B.tail_pad_waste == 0.0
    w = jnp.asarray(rng.normal(size=40).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(matvec(B, B.from_model_space(w))),
        np.asarray(matvec(X, w)), rtol=2e-4, atol=2e-4)
    r = jnp.asarray(rng.normal(size=100).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(B.to_model_space(rmatvec(B, r))),
        np.asarray(rmatvec(X, r)), rtol=2e-4, atol=2e-4)


def test_bell_pad_and_cast(rng):
    X, B = _power_law_sparse(rng, n=100, d=300, k=6)
    y = jnp.asarray(rng.normal(size=100).astype(np.float32))
    b = pad_batch(make_batch(B, y), 128)
    assert b.n == 128
    w = jnp.asarray(rng.normal(size=300).astype(np.float32))
    z = np.asarray(matvec(b.X, b.X.from_model_space(w)))
    np.testing.assert_allclose(z[:100], np.asarray(matvec(X, w)), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(z[100:], 0.0, atol=1e-6)
    bc = cast_features(b)
    assert bc.X.dense.dtype == jnp.bfloat16
    assert all(v.dtype == jnp.bfloat16 for v in bc.X.ell_vals)
    assert all(v.dtype == jnp.bfloat16 for v in bc.X.bucket_vals)
    # bf16 multiply / f32 accumulate stays within bf16 quantization noise
    zb = np.asarray(matvec(bc.X, bc.X.from_model_space(w)))
    assert zb.dtype == np.float32
    np.testing.assert_allclose(zb[:100], np.asarray(matvec(X, w)),
                               rtol=2e-2, atol=2e-2)


def test_bell_intercept_detection(rng):
    X, B = _power_law_sparse(rng)
    assert last_column_is_intercept(B)
    # break the intercept: scale one row's intercept value
    va = np.asarray(X.values).copy()
    va[0, -1] = 2.0
    B2 = to_blocked_ell(SparseRows(np.asarray(X.indices), va,
                                   X.n_features), 32)
    assert not last_column_is_intercept(B2)


# ------------------------------------------------------- scipy CSR builder
def test_bell_from_scipy_csr(rng):
    n, d = 120, 90
    M = sp.random(n, d, density=0.08, format="csr", dtype=np.float32,
                  random_state=np.random.RandomState(0))
    B = blocked_ell_from_scipy_csr(M, d_dense=12)
    w = rng.normal(size=d).astype(np.float32)
    ref = M @ w
    got = np.asarray(matvec(B, B.from_model_space(jnp.asarray(w))))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    r = rng.normal(size=n).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(B.to_model_space(rmatvec(B, jnp.asarray(r)))),
        M.T @ r, rtol=2e-4, atol=2e-4)


def test_from_scipy_csr_warning_reports_mass_fraction():
    M = sp.csr_matrix(np.array([[1.0, 2.0, 3.0, 4.0],
                                [0.0, 0.0, 5.0, 0.0]], np.float32))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        S = from_scipy_csr(M, k=2)
    msgs = [str(w.message) for w in caught
            if "from_scipy_csr" in str(w.message)]
    assert len(msgs) == 1
    # row 0 drops its 2 smallest-|value| entries (1, 2) of total mass 15
    assert "2 smallest-|value| entries" in msgs[0]
    assert "20.0000%" in msgs[0]
    # kept entries are the largest-|value| ones
    kept = np.sort(np.asarray(S.values)[0])
    np.testing.assert_array_equal(kept[-2:], [3.0, 4.0])


def test_from_scipy_csr_strict_raises():
    M = sp.csr_matrix(np.array([[1.0, 2.0, 3.0]], np.float32))
    with pytest.raises(ValueError, match="strict=True.*mass"):
        from_scipy_csr(M, k=2, strict=True)
    # strict with no truncation is a no-op
    S = from_scipy_csr(M, k=3, strict=True)
    assert S.values.shape == (1, 3)


# ---------------------------------------------------------- solver parity
@pytest.mark.parametrize("task", [TaskType.LOGISTIC_REGRESSION,
                                  TaskType.LINEAR_REGRESSION,
                                  TaskType.POISSON_REGRESSION])
def test_bell_train_glm_parity(rng, task):
    X, B = _power_law_sparse(rng, n=400, d=400, k=8, d_dense=24)
    if task is TaskType.LOGISTIC_REGRESSION:
        y = _labels(rng, X)
        rtol, atol = 1e-5, 5e-3
    else:
        # abs-normal responses: a harder-conditioned fit whose two solves
        # stop at slightly different points of the same flat optimum —
        # value parity is the tight pin, coefficients follow looser
        y = jnp.asarray(np.abs(rng.normal(size=400)).astype(np.float32))
        rtol, atol = 5e-4, 5e-2
    cfg = OptimizerConfig(max_iters=60, tolerance=1e-6, reg=l2(),
                          reg_weight=0.1, history=5)
    m_b, r_b = train_glm(make_batch(B, y), task, cfg)
    m_s, r_s = train_glm(make_batch(X, y), task, cfg)
    np.testing.assert_allclose(float(r_b.value), float(r_s.value), rtol=rtol)
    np.testing.assert_allclose(np.asarray(m_b.coefficients.means),
                               np.asarray(m_s.coefficients.means), atol=atol)
    # model scoring translates to permuted space internally
    np.testing.assert_allclose(np.asarray(m_b.score(B)),
                               np.asarray(m_b.score(X)), rtol=2e-4,
                               atol=2e-4)


def test_bell_grid_lanes_parity(rng):
    X, B = _power_law_sparse(rng)
    y = _labels(rng, X)
    cfg = OptimizerConfig(max_iters=60, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    weights = [1e-1, 1.0, 30.0]
    grid_b = train_glm_grid(make_batch(B, y), TaskType.LOGISTIC_REGRESSION,
                            cfg, weights)
    grid_s = train_glm_grid(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                            cfg, weights)
    for (m_b, r_b), (m_s, r_s) in zip(grid_b, grid_s):
        np.testing.assert_allclose(float(r_b.value), float(r_s.value),
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(m_b.coefficients.means),
                                   np.asarray(m_s.coefficients.means),
                                   atol=3e-2)


def test_bell_streamed_parity(rng):
    """chunk_blocked_ell: the streamed solve over a blocked-ELL chunk
    ladder matches the resident SparseRows solve (one global permutation
    across chunks, translation at the train_glm boundary)."""
    X, _ = _power_law_sparse(rng, n=384, d=150, k=6, d_dense=16)
    y = _labels(rng, X)
    batch = make_batch(X, y)
    cb = chunk_blocked_ell(batch, 128, d_dense=16)
    assert cb.X.permuted and cb.n_chunks == 3
    # uniform chunk shapes: ONE compiled per-chunk program
    shapes = {tuple(v.shape for v in c.ell_vals) for c in cb.X.chunks}
    assert len(shapes) == 1
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-7, reg=l2(),
                          reg_weight=0.3, history=5)
    m_c, r_c = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)
    m_s, r_s = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
    np.testing.assert_allclose(float(r_c.value), float(r_s.value), rtol=5e-5)
    # the streamed and resident L-BFGS paths diverge on near-flat sparse
    # directions (chunked accumulation order); value parity is the tight
    # pin, coefficients agree to ~1e-2 absolute
    np.testing.assert_allclose(np.asarray(m_c.coefficients.means),
                               np.asarray(m_s.coefficients.means),
                               rtol=2e-3, atol=1e-2)


@pytest.mark.slow
def test_bell_streamed_owlqn_and_bf16_chunks(rng):
    X, _ = _power_law_sparse(rng, n=256, d=200, k=6, d_dense=16)
    y = _labels(rng, X)
    batch = make_batch(X, y)
    from photon_tpu.optim.config import OptimizerType
    from photon_tpu.optim.regularization import elastic_net

    cfg = OptimizerConfig(max_iters=30, tolerance=1e-7,
                          reg=elastic_net(0.5), reg_weight=1e-2, history=5,
                          optimizer=OptimizerType.OWLQN)
    cb = chunk_blocked_ell(batch, 128, d_dense=16,
                           feature_dtype=jnp.bfloat16)
    assert all(c.dense.dtype == jnp.bfloat16 for c in cb.X.chunks)
    m_c, r_c = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)
    m_s, r_s = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
    # bf16 feature storage: value parity within quantization noise
    np.testing.assert_allclose(float(r_c.value), float(r_s.value), rtol=5e-3)


def test_bell_streamed_mesh_rejected(rng, mesh8):
    X, _ = _power_law_sparse(rng, n=160, d=120, k=5, d_dense=8)
    y = _labels(rng, X)
    cb = chunk_blocked_ell(make_batch(X, y), 80, d_dense=8)
    cfg = OptimizerConfig(max_iters=5, tolerance=1e-7, reg=l2(),
                          reg_weight=0.1, history=4)
    with pytest.raises(ValueError, match="mesh"):
        train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg, mesh=mesh8)


def test_bell_single_device_mesh_rejected(rng, mesh8):
    X, B = _power_law_sparse(rng, n=160, d=120, k=5, d_dense=8)
    y = _labels(rng, X)
    cfg = OptimizerConfig(max_iters=5, tolerance=1e-7, reg=l2(),
                          reg_weight=0.1, history=4)
    with pytest.raises(ValueError, match="single-device"):
        train_glm(make_batch(B, y), TaskType.LOGISTIC_REGRESSION, cfg,
                  mesh=mesh8)


# ----------------------------------------------------------- mesh parity
class TestShardedBlockedEll:
    def test_ops_match_single_device(self, rng):
        X, B = _power_law_sparse(rng, n=256, d=300, k=8, d_dense=16)
        S = shard_blocked_ell(SparseRows(np.asarray(X.indices),
                                         np.asarray(X.values),
                                         X.n_features), 8, d_dense=16)
        assert isinstance(S, ShardedBlockedEllRows)
        assert S.n_shards == 8 and S.n_local == 32
        n, d = X.shape
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        r = jnp.asarray(rng.normal(size=n).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(matvec(S, S.from_model_space(w))),
            np.asarray(matvec(X, w)), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(S.to_model_space(rmatvec(S, r))),
            np.asarray(rmatvec(X, r)), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(S.to_model_space(sq_rmatvec(S, r))),
            np.asarray(sq_rmatvec(X, r)), rtol=2e-4, atol=2e-4)
        G = 3
        W = jnp.asarray(rng.normal(size=(d, G)).astype(np.float32))
        R = jnp.asarray(rng.normal(size=(n, G)).astype(np.float32))
        perm = jnp.asarray(S.perm_cols)
        inv = np.asarray(S.inv_perm)
        np.testing.assert_allclose(
            np.asarray(matvec_lanes(S, W[perm])),
            np.asarray(matvec_lanes(X, W)), rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(
            np.asarray(rmatvec_lanes(S, R))[inv],
            np.asarray(rmatvec_lanes(X, R)), rtol=2e-4, atol=2e-4)
        # the local views compose to the global op
        chunk0 = S.chunk(0)
        np.testing.assert_allclose(
            np.asarray(matvec(chunk0, S.from_model_space(w))),
            np.asarray(matvec(X, w))[:32], rtol=2e-4, atol=2e-4)

    def test_train_glm_mesh_matches_single_device(self, rng, mesh8):
        X, _ = _power_law_sparse(rng, n=320, d=300, k=8, d_dense=16)
        y = _labels(rng, X)
        batch = shard_blocked_ell_batch(
            make_batch(SparseRows(np.asarray(X.indices),
                                  np.asarray(X.values), X.n_features),
                       np.asarray(y)), 8, d_dense=16)
        cfg = OptimizerConfig(max_iters=40, tolerance=1e-6, reg=l2(),
                              reg_weight=0.1, history=5)
        m_m, r_m = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                             mesh=mesh8)
        m_s, r_s = train_glm(make_batch(X, y),
                             TaskType.LOGISTIC_REGRESSION, cfg)
        np.testing.assert_allclose(float(r_m.value), float(r_s.value),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                   np.asarray(m_s.coefficients.means),
                                   atol=5e-3)

    @pytest.mark.slow
    def test_train_glm_grid_lanes_mesh(self, rng, mesh8):
        X, _ = _power_law_sparse(rng, n=320, d=300, k=8, d_dense=16)
        y = _labels(rng, X)
        batch = shard_blocked_ell_batch(
            make_batch(SparseRows(np.asarray(X.indices),
                                  np.asarray(X.values), X.n_features),
                       np.asarray(y)), 8, d_dense=16)
        cfg = OptimizerConfig(max_iters=40, tolerance=1e-6, reg=l2(),
                              reg_weight=0.0, history=5)
        weights = [0.5, 5.0]
        grid_m = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                                weights, mesh=mesh8)
        grid_s = train_glm_grid(make_batch(X, y),
                                TaskType.LOGISTIC_REGRESSION, cfg, weights)
        for (m_m, r_m), (m_s, r_s) in zip(grid_m, grid_s):
            np.testing.assert_allclose(float(r_m.value), float(r_s.value),
                                       rtol=1e-4)
            np.testing.assert_allclose(np.asarray(m_m.coefficients.means),
                                       np.asarray(m_s.coefficients.means),
                                       atol=2e-2)

    def test_cast_features_bf16(self, rng):
        X, _ = _power_law_sparse(rng, n=64, d=80, k=5, d_dense=8)
        batch = shard_blocked_ell_batch(
            make_batch(SparseRows(np.asarray(X.indices),
                                  np.asarray(X.values), X.n_features),
                       np.zeros(64, np.float32)), 8, d_dense=8)
        bc = cast_features(batch)
        assert bc.X.dense.dtype == jnp.bfloat16
        assert all(v.dtype == jnp.bfloat16 for v in bc.X.ell_vals)
        assert all(v.dtype == jnp.bfloat16 for v in bc.X.bucket_vals)


# ------------------------------------------------- sorted-segment helper
def test_sorted_segment_sum_matches_segment_sum(rng):
    ids = np.sort(rng.integers(0, 17, size=200)).astype(np.int32)
    dat = rng.normal(size=200).astype(np.float32)
    ref = np.asarray(jax.ops.segment_sum(jnp.asarray(dat),
                                         jnp.asarray(ids),
                                         num_segments=17))
    got = np.asarray(sorted_segment_sum(jnp.asarray(dat),
                                        jnp.asarray(ids), 17))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    # lane-stacked form
    dat2 = rng.normal(size=(200, 3)).astype(np.float32)
    ref2 = np.asarray(jax.ops.segment_sum(jnp.asarray(dat2),
                                          jnp.asarray(ids),
                                          num_segments=17))
    got2 = np.asarray(sorted_segment_sum(jnp.asarray(dat2),
                                         jnp.asarray(ids), 17))
    np.testing.assert_allclose(got2, ref2, rtol=1e-5, atol=1e-5)


def test_bell_chunked_margins_permuted(rng):
    """models.glm.chunked_margins translates the ladder's global
    permutation once for the whole stream."""
    from photon_tpu.models.glm import chunked_margins

    X, _ = _power_law_sparse(rng, n=200, d=150, k=6, d_dense=8)
    y = np.zeros(200, np.float32)
    cb = chunk_blocked_ell(make_batch(X, y), 64, d_dense=8)
    w = rng.normal(size=150).astype(np.float32)
    got = np.asarray(chunked_margins(cb.X, w))
    ref = np.asarray(matvec(X, jnp.asarray(w)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
