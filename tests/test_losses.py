"""Loss functions vs closed forms and autodiff.

Mirrors the reference's pointwise loss unit tests
(photon-ml: LogisticLossFunctionTest etc., which check loss/derivative
values at hand-picked margins).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.ops.losses import TaskType, loss_fns, mean_fn

TASKS = list(TaskType)


@pytest.mark.parametrize("task", TASKS)
def test_d1_matches_autodiff(task):
    loss, d1, _ = loss_fns(task)
    z = jnp.linspace(-3.0, 3.0, 13)
    y = jnp.array([0.0, 1.0] * 6 + [1.0])
    if task is TaskType.POISSON_REGRESSION:
        y = jnp.abs(y * 3.0)
    auto = jax.vmap(jax.grad(lambda zz, yy: loss(zz, yy)))(z, y)
    np.testing.assert_allclose(d1(z, y), auto, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("task", TASKS)
def test_d2_matches_autodiff(task):
    loss, _, d2 = loss_fns(task)
    # stay off the hinge's kink points where the 2nd derivative jumps
    z = jnp.linspace(-2.7, 2.7, 11)
    y = jnp.array([0.0, 1.0] * 5 + [1.0])
    auto = jax.vmap(jax.grad(jax.grad(lambda zz, yy: loss(zz, yy))))(z, y)
    np.testing.assert_allclose(d2(z, y), auto, rtol=1e-4, atol=1e-5)


def test_logistic_closed_form():
    loss, _, _ = loss_fns(TaskType.LOGISTIC_REGRESSION)
    # loss(z, y) = log(1 + e^z) - y z
    np.testing.assert_allclose(loss(0.0, 0.0), np.log(2.0), rtol=1e-6)
    np.testing.assert_allclose(loss(0.0, 1.0), np.log(2.0), rtol=1e-6)
    # stable at extreme margins (no overflow)
    assert np.isfinite(float(loss(80.0, 0.0)))
    assert float(loss(80.0, 1.0)) < 1e-6


def test_smoothed_hinge_regions():
    loss, _, _ = loss_fns(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM)
    # y=1: margin m=z. m>=1 → 0; m<=0 → 0.5-m; else quadratic
    np.testing.assert_allclose(loss(2.0, 1.0), 0.0, atol=1e-7)
    np.testing.assert_allclose(loss(-1.0, 1.0), 1.5, rtol=1e-6)
    np.testing.assert_allclose(loss(0.5, 1.0), 0.125, rtol=1e-6)


def test_poisson_mean_is_exp():
    assert np.isclose(float(mean_fn(TaskType.POISSON_REGRESSION)(1.0)), np.e)
    assert np.isclose(float(mean_fn(TaskType.LOGISTIC_REGRESSION)(0.0)), 0.5)
