"""Replica fleet (photon_tpu/serving/fleet.py): entity-range sharding
over the index-map machinery, hashed range routing, retry/backoff
failover — and THE robustness acceptance: the kill matrix over the new
serving fault sites (``replica_dispatch``, ``rung_execute``,
``store_open``) × first/middle/last occurrence leaves zero hung futures,
zero torn responses, and degraded-but-correct answers (the cold-miss
fixed-effect-only fallback).

Marked `release_programs`: each fleet replica compiles its rung once;
teardown drops them (tests/conftest.py).
"""
import os

import numpy as np
import pytest

from photon_tpu import checkpoint, serving, telemetry
from photon_tpu.serving.__main__ import build_demo_model

pytestmark = pytest.mark.release_programs

SPARSE_K = 3

# one fast-failover policy for the whole module (backoff in the ms range:
# the injected faults are deterministic, the waits pure overhead)
FAST = serving.FleetPolicy(attempt_timeout_s=30.0, failover_retries=2,
                           base_delay_s=0.001, max_delay_s=0.01)
LK = dict(ladder=(8,), sparse_k={"member": SPARSE_K}, output_mean=True)
DK = dict(max_batch=8, max_delay_us=200)


@pytest.fixture(autouse=True)
def _detached():
    yield
    telemetry.finish_run()


@pytest.fixture(scope="module")
def rig():
    """(model, full store, fleet, requests, clean refs, fixed-only refs):
    one 2-replica fleet for the whole module — two rung-8 programs total.

    The reference scores come through the fleet itself on a clean run;
    `fixed_only` re-scores the same feature rows under an unseen entity
    (the degraded answer a non-owning replica must produce)."""
    model, _ = build_demo_model(seed=7)
    store = serving.CoefficientStore.from_game_model(model)
    fleet = serving.ReplicaFleet.build(store, 2, policy=FAST,
                                       ladder_kwargs=LK,
                                       dispatcher_kwargs=DK)
    rng = np.random.default_rng(3)
    xg = rng.normal(size=(8, 6)).astype(np.float32)
    ind = rng.integers(0, 4, size=(8, SPARSE_K)).astype(np.int32)
    val = rng.normal(size=(8, SPARSE_K)).astype(np.float32)

    def req(i, ent):
        return serving.ScoreRequest(
            features={"global": xg[i], "member": (ind[i], val[i])},
            entities={"memberId": ent})

    reqs = [req(i, f"e{(2 * i) % 16:03d}") for i in range(8)]  # both ranges
    clean = [fleet.score(q) for q in reqs]
    fixed_only = [fleet.score(req(i, "zz-unseen")) for i in range(8)]
    assert any(c != f for c, f in zip(clean, fixed_only))
    yield model, store, fleet, reqs, clean, fixed_only
    fleet.close()


# ------------------------------------------------------------------ sharding
class TestShardStore:
    def test_ranges_partition_the_entity_space(self, rig):
        model, store, _, _, _, _ = rig
        shards = serving.shard_store(store, 3)
        E = store.random["perEntity"].n_entities
        seen: dict = {}
        for j, s in enumerate(shards):
            blk = s.random["perEntity"]
            for k in blk.directory.keys_in_order():
                assert k not in seen, f"{k} owned by shards {seen[k]},{j}"
                seen[k] = j
        assert len(seen) == E  # the union covers everything exactly once
        # shard coefficient rows match the full store's, row for row
        full = np.asarray(store.random["perEntity"].coefficients)
        for s in shards:
            blk = s.random["perEntity"]
            for k in blk.directory.keys_in_order():
                i_local = blk.directory.get(k)
                i_full, miss = store.random["perEntity"].lookup([k])
                assert not miss
                np.testing.assert_array_equal(
                    np.asarray(blk.coefficients)[i_local],
                    full[int(i_full[0])])
            # the local cold-miss row stays all-zero
            assert (np.asarray(blk.coefficients)[-1] == 0).all()

    def test_out_of_range_entity_degrades_to_zero_row(self, rig):
        _, store, _, _, _, _ = rig
        shards = serving.shard_store(store, 2)
        # e015 lives in the upper range: shard 0 must cold-miss it
        ids, miss = shards[0].random["perEntity"].lookup(["e015"])
        assert miss == 1
        assert ids[0] == shards[0].random["perEntity"].n_entities
        ids1, miss1 = shards[1].random["perEntity"].lookup(["e015"])
        assert miss1 == 0

    def test_more_shards_than_entities_is_fine(self, rig):
        _, store, _, _, _, _ = rig
        tiny, _ = build_demo_model(seed=1, n_entities=2)
        tstore = serving.CoefficientStore.from_game_model(tiny)
        shards = serving.shard_store(tstore, 4)
        owned = sum(s.random["perEntity"].n_entities for s in shards)
        assert owned == 2  # empty shards carry just the zero row

    def test_rejects_bad_shard_count(self, rig):
        _, store, _, _, _, _ = rig
        with pytest.raises(ValueError, match="n_shards"):
            serving.shard_store(store, 0)


# ------------------------------------------------------------------- routing
class TestRouting:
    def test_entities_route_to_their_owning_range(self, rig):
        model, store, fleet, _, _, _ = rig
        bounds = serving.fleet.shard_bounds(16, 2)
        for i in range(16):
            q = serving.ScoreRequest(features={},
                                     entities={"memberId": f"e{i:03d}"})
            want = 0 if i < bounds[1] else 1
            assert fleet.replica_for(q) == want
            # ... and the routed replica actually OWNS the entity
            rep = fleet.replicas[fleet.replica_for(q)]
            _, miss = rep.store.random["perEntity"].lookup([f"e{i:03d}"])
            assert miss == 0

    def test_unseen_and_keyless_requests_hash_deterministically(self, rig):
        _, _, fleet, _, _, _ = rig
        q1 = serving.ScoreRequest(features={},
                                  entities={"memberId": "never-seen"})
        q2 = serving.ScoreRequest(features={}, entities={})
        assert fleet.replica_for(q1) == fleet.replica_for(q1)
        assert fleet.replica_for(q2) == fleet.replica_for(q2)
        assert 0 <= fleet.replica_for(q1) < 2
        assert 0 <= fleet.replica_for(q2) < 2


# ------------------------------------------------- failover + the kill matrix
class TestFleetServing:
    def test_clean_scores_are_exact(self, rig):
        """Routing sends every entity to its owning shard, so a healthy
        fleet is bit-identical to the unsharded dispatcher path (the
        demo-model parity the single-replica tests already pin)."""
        model, store, fleet, reqs, clean, _ = rig
        ladder = serving.ProgramLadder(store, **LK)
        d = serving.MicroBatchDispatcher(ladder, **DK)
        try:
            want = [d.submit(q).result(timeout=30) for q in reqs]
        finally:
            d.close()
        assert clean == want

    def test_async_submit_resolves(self, rig):
        _, _, fleet, reqs, clean, _ = rig
        futs = [fleet.submit(q) for q in reqs]
        got = [f.result(timeout=60) for f in futs]
        assert got == clean

    def test_kill_matrix_no_hangs_no_torn_responses(self, rig):
        """THE acceptance: for every new serving fault site ×
        first/middle/last occurrence, every request resolves (zero hung
        futures) to either its exact score or the degraded-but-correct
        fixed-effect-only fallback (zero torn responses), and the fleet
        keeps serving afterwards."""
        _, _, fleet, reqs, clean, fixed_only = rig
        with checkpoint.record_sites() as rec:
            dry = [fleet.score(q) for q in reqs]
        assert dry == clean  # the recorder injects nothing
        for site in ("replica_dispatch", "rung_execute"):
            total = rec.hits[site]
            assert total >= len(reqs)
            for occ in sorted({1, total // 2, total}):
                with checkpoint.fault_plan(
                        checkpoint.FaultPlan.kill_at(site, occ)):
                    got = [fleet.score(q, timeout=30) for q in reqs]
                for i, (g, c, f) in enumerate(zip(got, clean, fixed_only)):
                    assert g == c or g == f, (
                        f"kill {site}@{occ}: request {i} scored {g!r} — "
                        f"neither exact {c!r} nor degraded {f!r} (torn)")
        # disarmed again: back to exact
        assert [fleet.score(q) for q in reqs] == clean

    def test_rung_execute_kill_serves_degraded_and_counts(self, rig):
        """A replica dying mid-execution fails over to a NON-owning
        replica: the answer is the cold-miss fixed-effect-only score —
        degraded, correct, counted on serving.fleet_degraded/failovers."""
        _, _, fleet, reqs, clean, fixed_only = rig
        r = telemetry.start_run("fleet_kill")
        with checkpoint.fault_plan(
                checkpoint.FaultPlan.kill_at("rung_execute", 1)):
            got = fleet.score(reqs[0], timeout=30)
        telemetry.finish_run()
        assert got == fixed_only[0] and got != clean[0]
        assert r.counters["serving.fleet_failovers"] == 1.0
        assert r.counters["serving.fleet_degraded"] == 1.0

    def test_transient_errors_retry_with_backoff(self, rig):
        """errors at the replica_dispatch site: the first two attempts
        fail, the third answers — io_retries/backoff counted, the answer
        still exact-or-degraded."""
        _, _, fleet, reqs, clean, fixed_only = rig
        r = telemetry.start_run("fleet_retry")
        with checkpoint.fault_plan(
                checkpoint.FaultPlan(errors={"replica_dispatch": 2})):
            got = fleet.score(reqs[0], timeout=30)
        telemetry.finish_run()
        assert got == clean[0] or got == fixed_only[0]
        assert r.counters["faults.io_retries"] == 2.0
        assert r.counters["faults.io_retries.replica_dispatch"] == 2.0
        assert r.counters["faults.backoff_seconds"] > 0

    def test_failover_hops_ride_one_trace(self, rig):
        """Request tracing across failover: a replica dying mid-dispatch
        puts ``failover_backoff`` between its ``replica_dispatch`` hop
        and the winning replica's, all on ONE trace that the winner's
        retire thread closes — so the exemplar's breakdown charges the
        backoff wait by name."""
        from photon_tpu.telemetry import trace
        _, _, fleet, reqs, clean, fixed_only = rig
        primary = fleet.replica_for(reqs[0])
        rep = fleet.replicas[primary]
        real_dispatch = rep.dispatch
        calls = {"n": 0}

        def dying_dispatch(req, timeout):
            calls["n"] += 1
            raise OSError("replica died mid-flight")

        rep.dispatch = dying_dispatch
        try:
            with trace.tracing(k=2) as res:
                got = fleet.score(reqs[0], timeout=30)
                slow = res.slowest()
        finally:
            rep.dispatch = real_dispatch
        assert calls["n"] == 1  # failover went to the OTHER replica
        assert got == clean[0] or got == fixed_only[0]
        assert slow is not None and res.n_offered == 1
        names = [h["name"] for h in slow["hops"]]
        assert names[:4] == ["fleet_route", "replica_dispatch",
                             "failover_backoff", "replica_dispatch"]
        assert names[-1] == "retire_wait"  # the retire thread closed it
        # the backoff sleep (>=1ms under FAST) accrues to its own hop
        assert slow["breakdown_ms"]["failover_backoff"] >= 0.9

    def test_injected_retry_errors_keep_one_trace(self, rig):
        """The fault plan's injected replica_dispatch errors raise
        BEFORE the attempt runs, so the retry sleeps accrue on the
        still-open ``fleet_route`` hop and the single winning attempt
        carries the full dispatcher hop chain — one exemplar, no
        phantom attempts."""
        from photon_tpu.telemetry import trace
        _, _, fleet, reqs, clean, fixed_only = rig
        with trace.tracing(k=2) as res:
            with checkpoint.fault_plan(
                    checkpoint.FaultPlan(errors={"replica_dispatch": 2})):
                got = fleet.score(reqs[0], timeout=30)
            slow = res.slowest()
        assert got == clean[0] or got == fixed_only[0]
        assert slow is not None and res.n_offered == 1
        names = [h["name"] for h in slow["hops"]]
        assert names == ["fleet_route", "replica_dispatch", "queue_wait",
                         "device_flush", "retire_wait"]
        # two backoffs (1ms + 2ms) landed on the route hop
        assert slow["breakdown_ms"]["fleet_route"] >= 2.5

    def test_clean_fleet_trace_has_no_failover_hops(self, rig):
        from photon_tpu.telemetry import trace
        _, _, fleet, reqs, clean, fixed_only = rig
        with trace.tracing(k=1) as res:
            got = fleet.score(reqs[1], timeout=30)
            slow = res.slowest()
        assert got == clean[1] or got == fixed_only[1]
        assert "failover_backoff" not in slow["breakdown_ms"]

    def test_exhausted_failover_reraises(self, rig):
        """More consecutive kills than the retry budget: the final
        failure surfaces (bounded retry, never an infinite loop) and the
        fleet still serves afterwards."""
        _, _, fleet, reqs, clean, _ = rig
        n_kill = FAST.failover_retries + 1
        with checkpoint.fault_plan(checkpoint.FaultPlan(
                errors={"replica_dispatch": 10_000})):
            with pytest.raises(OSError):
                fleet.score(reqs[0], timeout=30)
        assert n_kill >= 1
        assert fleet.score(reqs[0]) == clean[0]

    def test_no_retrace_across_the_whole_module(self, rig):
        """Kills, failovers, and retries never retrace a replica rung."""
        _, _, fleet, _, _, _ = rig
        assert fleet.assert_no_retrace() <= sum(
            len(rep.ladder.ladder) for rep in fleet.replicas)

    def test_shed_is_an_answer_not_a_failover(self, rig):
        """A replica shedding under overload policy must NOT cascade the
        request onto other replicas — shedding is load control."""
        model, store, _, reqs, _, _ = rig
        fleet = serving.ReplicaFleet.build(
            store, 2, policy=FAST, ladder_kwargs=LK, dispatcher_kwargs=DK,
            admission=serving.AdmissionPolicy(shed_watermark=0))
        r = telemetry.start_run("fleet_shed")
        try:
            got = fleet.score(reqs[0], timeout=30)
        finally:
            fleet.close()
            telemetry.finish_run()
        assert isinstance(got, serving.Shed)
        assert "serving.fleet_failovers" not in r.counters

    def test_closed_fleet_rejects(self, rig):
        model, store, _, reqs, _, _ = rig
        fleet = serving.ReplicaFleet.build(store, 2, policy=FAST,
                                           ladder_kwargs=LK,
                                           dispatcher_kwargs=DK)
        fleet.close()
        fleet.close()  # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            fleet.score(reqs[0])
        with pytest.raises(RuntimeError, match="closed"):
            fleet.submit(reqs[0])


# ------------------------------------------------------- store_open fault site
class TestStoreOpenFaults:
    def test_transient_open_errors_retry(self, rig, tmp_path):
        _, store, _, _, _, _ = rig
        sdir = tmp_path / "shard0"
        serving.shard_store(store, 2)[0].save(sdir)
        r = telemetry.start_run("store_open_retry")
        with checkpoint.fault_plan(
                checkpoint.FaultPlan(errors={"store_open": 2})):
            back = serving.CoefficientStore.open(sdir, mmap=False)
        telemetry.finish_run()
        assert back.order == store.order
        assert r.counters["faults.io_retries.store_open"] == 2.0

    def test_kill_at_every_occurrence_dies_clean_reopens_clean(
            self, rig, tmp_path):
        """Kills at the store_open site (fleet startup from saved shard
        dirs): first/middle/last occurrence each aborts the open with
        nothing half-built, and an immediate clean retry serves."""
        _, store, _, _, _, _ = rig
        dirs = []
        for j, s in enumerate(serving.shard_store(store, 2)):
            d = tmp_path / f"s{j}"
            s.save(d)
            dirs.append(str(d))
        with checkpoint.record_sites() as rec:
            fleet = serving.ReplicaFleet.open(
                dirs, mmap=False, routing_store=store, policy=FAST,
                ladder_kwargs=LK, dispatcher_kwargs=DK)
            fleet.close()
        total = rec.hits["store_open"]
        assert total == 2  # one per shard dir
        for occ in sorted({1, max(total // 2, 1), total}):
            with pytest.raises(checkpoint.InjectedFault):
                with checkpoint.fault_plan(
                        checkpoint.FaultPlan.kill_at("store_open", occ)):
                    serving.ReplicaFleet.open(
                        dirs, mmap=False, routing_store=store, policy=FAST,
                        ladder_kwargs=LK, dispatcher_kwargs=DK)
        fleet = serving.ReplicaFleet.open(
            dirs, mmap=False, routing_store=store, policy=FAST,
            ladder_kwargs=LK, dispatcher_kwargs=DK)
        try:
            q = serving.ScoreRequest(
                features={"global": np.ones(6, np.float32),
                          "member": (np.zeros(1, np.int32),
                                     np.zeros(1, np.float32))},
                entities={"memberId": "e003"})
            assert isinstance(fleet.score(q, timeout=30), float)
        finally:
            fleet.close()

    def test_missing_manifest_fails_fast_without_retry_burn(self, tmp_path):
        """No manifest = permanent, not transient: FileNotFoundError
        surfaces immediately instead of spending the backoff budget."""
        import time as _time

        t0 = _time.perf_counter()
        with pytest.raises(FileNotFoundError, match="manifest"):
            serving.CoefficientStore.open(tmp_path / "nothing")
        assert _time.perf_counter() - t0 < 0.2
