"""bf16-storage / f32-accumulation mixed-precision path.

SURVEY.md §1 promises "dense bf16/f32 matmuls on MXU"; these tests pin the
semantics: feature storage may be bfloat16, every contraction accumulates in
f32, and every public output (margins, gradients, fitted coefficients) is f32
and close to the pure-f32 result.
"""
import numpy as np
import jax
import jax.numpy as jnp
import scipy.sparse as sp

from photon_tpu.data.dataset import cast_features, make_batch, pad_batch
from photon_tpu.data.matrix import (
    SparseRows,
    from_scipy_csr,
    matvec,
    rmatvec,
    sq_rmatvec,
)
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig


class TestMixedPrecisionOps:
    def test_dense_matvec_accumulates_f32(self, rng):
        import ml_dtypes

        X = rng.normal(size=(512, 64)).astype(np.float32)
        w = rng.normal(size=64).astype(np.float32)
        out = matvec(jnp.asarray(X, jnp.bfloat16), jnp.asarray(w))
        assert out.dtype == jnp.float32
        # Against the f64 product of bf16-ROUNDED operands: any deviation is
        # accumulation error, which f32 accumulation keeps at ~1e-6 relative —
        # bf16 accumulation would sit at ~1e-2.
        X16 = X.astype(ml_dtypes.bfloat16).astype(np.float64)
        w16 = w.astype(ml_dtypes.bfloat16).astype(np.float64)
        exact_rounded = X16 @ w16
        np.testing.assert_allclose(np.asarray(out), exact_rounded,
                                   rtol=1e-5, atol=1e-4)
        # And the end-to-end error vs the unrounded product is operand-level.
        np.testing.assert_allclose(np.asarray(out), X @ w, atol=0.2)

    def test_dense_rmatvec_and_sq(self, rng):
        X = rng.normal(size=(256, 32)).astype(np.float32)
        r = rng.normal(size=256).astype(np.float32)
        Xb = jnp.asarray(X, jnp.bfloat16)
        out = rmatvec(Xb, jnp.asarray(r))
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), X.T @ r, rtol=0.05,
                                   atol=0.05)
        out2 = sq_rmatvec(Xb, jnp.asarray(r))
        assert out2.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out2), (X * X).T @ r, rtol=0.05,
                                   atol=0.08)

    def test_sparse_bf16_matches_f32(self, rng):
        M = sp.random(200, 50, density=0.15, random_state=0, format="csr",
                      dtype=np.float32)
        X = from_scipy_csr(M)
        Xb = SparseRows(X.indices, X.values.astype(jnp.bfloat16), X.n_features)
        w = rng.normal(size=50).astype(np.float32)
        r = rng.normal(size=200).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(matvec(Xb, jnp.asarray(w))),
            np.asarray(matvec(X, jnp.asarray(w))), rtol=0.05, atol=0.02)
        np.testing.assert_allclose(
            np.asarray(rmatvec(Xb, jnp.asarray(r))),
            np.asarray(rmatvec(X, jnp.asarray(r))), rtol=0.05, atol=0.02)
        assert matvec(Xb, jnp.asarray(w)).dtype == jnp.float32

    def test_f32_path_unchanged(self, rng):
        X = rng.normal(size=(128, 16)).astype(np.float32)
        w = rng.normal(size=16).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(matvec(jnp.asarray(X), jnp.asarray(w))), X @ w,
            rtol=1e-5, atol=1e-5)


class TestMixedPrecisionTraining:
    def _problem(self, rng, n=4000, d=24):
        X = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
        p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
        y = (rng.uniform(size=n) < p).astype(np.float32)
        return X, y

    def test_bf16_training_matches_f32(self, rng):
        X, y = self._problem(rng)
        cfg = OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=1.0,
                              regularize_intercept=True)
        m32, r32 = train_glm(make_batch(X, y),
                             TaskType.LOGISTIC_REGRESSION, cfg)
        m16, r16 = train_glm(cast_features(make_batch(X, y)),
                             TaskType.LOGISTIC_REGRESSION, cfg)
        assert bool(r16.converged) and not bool(r16.failed)
        w32 = np.asarray(m32.coefficients.means)
        w16 = np.asarray(m16.coefficients.means)
        assert w16.dtype == np.float32
        # bf16 data rounding perturbs the optimum slightly; agreement well
        # inside statistical noise.
        np.testing.assert_allclose(w16, w32, atol=0.02)

    def test_bf16_on_mesh(self, rng, mesh8):
        X, y = self._problem(rng, n=1024, d=8)
        # tolerance sits above the bf16 operand-rounding noise floor; the
        # default 1e-7-ish tolerance is unreachable with rounded features.
        cfg = OptimizerConfig(max_iters=40, tolerance=1e-4, reg=reg.l2(),
                              reg_weight=1.0, regularize_intercept=True)
        batch = cast_features(make_batch(X, y))
        m_mesh, res = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                                mesh=mesh8)
        m_one, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(m_mesh.coefficients.means),
                                   np.asarray(m_one.coefficients.means),
                                   atol=2e-3)

    def test_pad_batch_preserves_bf16(self, rng):
        X, y = self._problem(rng, n=100, d=4)
        b = cast_features(make_batch(X, y))
        padded = pad_batch(b, 128)
        assert padded.X.dtype == jnp.bfloat16
        M = sp.random(100, 16, density=0.2, random_state=0, format="csr",
                      dtype=np.float32)
        bs = cast_features(make_batch(from_scipy_csr(M), y))
        ps = pad_batch(bs, 128)
        assert ps.X.values.dtype == jnp.bfloat16


class TestDeviceStorageDtypePreserved:
    """Round 4: already-device FLOATING shards keep their storage dtype
    (a bf16 shard must not double its HBM via an f32 upcast); integer
    device arrays still normalize to f32 (matvec would truncate w to the
    feature dtype otherwise)."""

    def test_make_batch(self):
        import jax
        import jax.numpy as jnp

        from photon_tpu.data.dataset import make_batch

        Xb = jax.device_put(np.ones((8, 3), np.float32).astype(jnp.bfloat16))
        y = np.zeros(8, np.float32)
        assert make_batch(Xb, y).X.dtype == jnp.bfloat16
        Xi = jax.device_put(np.ones((8, 3), np.int32))
        assert make_batch(Xi, y).X.dtype == jnp.float32
        assert make_batch(np.ones((8, 3), np.float64), y).X.dtype \
            == jnp.float32

    def test_fixed_effect_dataset(self):
        import jax
        import jax.numpy as jnp

        from photon_tpu.game.dataset import FixedEffectDataset, GameData

        y = np.zeros(8, np.float32)
        for arr, want in (
                (jax.device_put(np.ones((8, 3), np.float32
                                        ).astype(jnp.bfloat16)),
                 jnp.bfloat16),
                (jax.device_put(np.ones((8, 3), np.int32)), jnp.float32),
                (np.ones((8, 3), np.float32), jnp.float32)):
            data = GameData.build(y, {"s": arr}, {})
            assert FixedEffectDataset.build(data, "s").X.dtype == want
