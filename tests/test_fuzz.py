"""Randomized cross-product sanity: every (task × optimizer) on random
problems with random weights/offsets must reach (or beat, modulo f32) the
objective scipy's f64 L-BFGS-B finds on the IDENTICAL objective function.

This is the breadth counterpart to the targeted parity tests: it sweeps the
loss × solver matrix the reference exercises across its *FunctionTest and
*OptimizerTest suites with fresh random draws each seed.
"""
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from photon_tpu.data.dataset import make_batch
from photon_tpu.models.training import make_objective, solve
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig, OptimizerType

TASKS = [
    TaskType.LOGISTIC_REGRESSION,
    TaskType.LINEAR_REGRESSION,
    TaskType.POISSON_REGRESSION,
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
]
OPTS = [OptimizerType.LBFGS, OptimizerType.TRON]


def _random_problem(task, seed, n=300, d=8):
    rng = np.random.default_rng(seed)
    X = (rng.normal(size=(n, d)) * 0.5).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32) * 0.6
    z = X @ w_true
    if task is TaskType.LINEAR_REGRESSION:
        y = (z + 0.2 * rng.normal(size=n)).astype(np.float32)
    elif task is TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(z, -4, 4))).astype(np.float32)
    else:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(np.float32)
        if task is TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
            y = y  # hinge losses take {0,1} labels like the reference
    weights = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    offsets = (rng.normal(size=n) * 0.3).astype(np.float32)
    return make_batch(X, y, weights=weights, offsets=offsets)


def _scipy_optimum(obj, batch, d):
    def fun(w):
        return float(obj.value(jnp.asarray(w, jnp.float32), batch))

    def jac(w):
        return np.asarray(obj.grad(jnp.asarray(w, jnp.float32), batch),
                          np.float64)

    r = scipy.optimize.minimize(fun, np.zeros(d), jac=jac, method="L-BFGS-B",
                                options={"maxiter": 500, "ftol": 1e-12})
    return float(r.fun)


@pytest.mark.parametrize("task", TASKS, ids=lambda t: t.name)
@pytest.mark.parametrize("opt", OPTS, ids=lambda o: o.name)
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_reaches_scipy_objective(task, opt, seed):
    batch = _random_problem(task, seed)
    d = batch.X.shape[1]
    config = OptimizerConfig(optimizer=opt, max_iters=200, tolerance=1e-9,
                             reg=reg.l2(), reg_weight=0.3,
                             regularize_intercept=True)
    obj = make_objective(task, config, d)
    res = solve(obj, batch, jnp.zeros((d,), jnp.float32), config)
    ours = float(res.value)
    ref = _scipy_optimum(obj, batch, d)
    # f32 solver vs f64 scipy on the same objective: equal to f32 slack.
    assert ours <= ref * (1 + 1e-3) + 1e-3, (task, opt, seed, ours, ref)
    assert np.isfinite(np.asarray(res.w)).all()


@pytest.mark.parametrize("task", TASKS, ids=lambda t: t.name)
@pytest.mark.parametrize("opt", [OptimizerType.LBFGS, OptimizerType.TRON,
                                 OptimizerType.OWLQN],
                         ids=lambda o: o.name)
@pytest.mark.parametrize("seed", [11])
def test_lane_grid_reaches_scipy_objective(task, opt, seed):
    """The same scipy bar, per LANE of one lock-step lane-minor sweep —
    the randomized breadth for all three lane solvers (L2 sweeps on
    L-BFGS/TRON lanes, elastic-net sweeps on OWL-QN lanes)."""
    from photon_tpu.models.training import train_glm_grid

    batch = _random_problem(task, seed)
    d = batch.X.shape[1]
    l1 = opt is OptimizerType.OWLQN
    config = OptimizerConfig(optimizer=opt, max_iters=200, tolerance=1e-9,
                             reg=reg.elastic_net(0.5) if l1 else reg.l2(),
                             reg_weight=0.0, regularize_intercept=True)
    weights = [0.03, 0.3, 3.0]
    grid = train_glm_grid(batch, task, config, weights)
    for wt, (_, res) in zip(weights, grid):
        ours = float(res.value)
        obj = make_objective(
            task, OptimizerConfig(reg=config.reg, reg_weight=wt), d)
        if l1:
            # scipy minimizes the smooth part only; add the L1 term at the
            # solution via a subgradient-aware comparison: minimize the
            # smooth+L1 composite with L-BFGS-B on a split-positive
            # formulation (w = u - v, u, v >= 0 turns |w| linear).
            lam = config.reg.l1_weight(wt)

            def fun(uv):
                w = jnp.asarray(uv[:d] - uv[d:], jnp.float32)
                return (float(obj.value(w, batch))
                        + lam * float(np.sum(uv)))

            def jac(uv):
                w = jnp.asarray(uv[:d] - uv[d:], jnp.float32)
                g = np.asarray(obj.grad(w, batch), np.float64)
                return np.concatenate([g + lam, -g + lam])

            r = scipy.optimize.minimize(
                fun, np.zeros(2 * d), jac=jac, method="L-BFGS-B",
                bounds=[(0, None)] * (2 * d),
                options={"maxiter": 1000, "ftol": 1e-12})
            ref = float(r.fun)
        else:
            ref = _scipy_optimum(obj, batch, d)
        assert ours <= ref * (1 + 1e-3) + 1e-3, (task, opt, wt, ours, ref)
        assert np.isfinite(np.asarray(res.w)).all()


@pytest.mark.parametrize("task", TASKS, ids=lambda t: t.name)
def test_owlqn_zero_l1_equals_lbfgs(task):
    """OWL-QN with λ=0 must coincide with plain L-BFGS (the pseudo-gradient
    reduces to the gradient, the orthant projection to a no-op)."""
    batch = _random_problem(task, seed=7)
    d = batch.X.shape[1]
    cfg_l = OptimizerConfig(max_iters=150, tolerance=1e-9, reg=reg.l2(),
                            reg_weight=0.5)
    obj = make_objective(task, cfg_l, d)
    res_l = solve(obj, batch, jnp.zeros((d,), jnp.float32), cfg_l)
    cfg_o = OptimizerConfig(optimizer=OptimizerType.OWLQN, max_iters=150,
                            tolerance=1e-9, reg=reg.l2(), reg_weight=0.5)
    res_o = solve(obj, batch, jnp.zeros((d,), jnp.float32), cfg_o,
                  l1_weight=0.0)
    np.testing.assert_allclose(float(res_o.value), float(res_l.value),
                               rtol=1e-4)
    np.testing.assert_allclose(np.asarray(res_o.w), np.asarray(res_l.w),
                               atol=2e-3)
