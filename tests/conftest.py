"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of unit-testing Spark code on a
local[*] SparkContext (photon-ml SparkTestUtils): we force a fake
8-device CPU platform so every sharding/`psum` path is exercised
without TPU hardware.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# f32 matmuls on CPU for numeric comparisons against scipy/sklearn.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)
# Persistent XLA compilation cache for the suite (the round-5 driver
# feature, applied to CI): the tier-1 wall is compile-dominated — the GP
# tuner alone retraces its fit across ~100 growing training-set shapes —
# and the 870 s budget is thin on a contended box, so repeat runs load
# executables from disk instead of recompiling. Artifacts are keyed by
# jax on program+flags, so numerics are identical to a cold compile;
# only programs over the min-compile-time threshold are stored (tiny
# jits stay out of the cache). Override the location with
# PHOTON_TPU_TEST_CACHE_DIR; set it empty to disable.
_cache_dir = os.environ.get("PHOTON_TPU_TEST_CACHE_DIR",
                            "/tmp/photon_tpu_xla_test_cache")
if _cache_dir:
    try:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.9)
    except Exception:  # older/newer jax without the flags: run uncached
        pass
# The axon TPU plugin overrides JAX_PLATFORMS env filtering with its own
# jax_platforms='axon,cpu'; force plain CPU *before* any backend init so the
# suite never touches (or blocks on) the TPU tunnel.
jax.config.update("jax_platforms", "cpu")
_cpu_devices = jax.devices("cpu")
jax.config.update("jax_default_device", _cpu_devices[0])

import sys  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "cpu_parity_drift: one of the 6 triaged grid/lane/permuted parity "
        "assertions that fail ONLY on this container's jax 0.4.37 CPU "
        "backend (reduction-order drift between compilation paths — see "
        "ADVICE.md round-8 triage). NOT a skip/xfail: pass/fail behavior "
        "is unchanged; the marker exists so reports and -m selections "
        "can name the set (verify on a real TPU backend before loosening "
        "any tolerance).")
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 suite (-m 'not slow'); "
        "long-running end-to-end checks like the umbrella selfcheck.")
    config.addinivalue_line(
        "markers",
        "tier2: acceptance tests promoted OUT of the tier-1 wall (round-16 "
        "suite-time relief) — statistical end-to-end properties (GP-beats-"
        "random, q-EI-vs-constant-liar, mesh game grids) that each burn "
        "15-60 s re-proving claims the faster unit tests already pin. "
        "Run them with -m tier2 (they implicitly carry `slow`, so the "
        "tier-1 selection -m 'not slow' keeps excluding them).")
    config.addinivalue_line(
        "markers",
        "release_programs: drop this module's compiled XLA programs at "
        "module teardown (jax.clear_caches + photon_tpu program caches). "
        "Apply (pytestmark = pytest.mark.release_programs) to any module "
        "that compiles many multi-device programs: the virtual-CPU XLA "
        "client segfaults compiling LATER unrelated programs once too "
        "many live executables have accumulated in the process "
        "(~460; first seen from test_streamed_mesh's 8-device shard_map "
        "programs breaking test_tuning's GP while_loop compile).")


def pytest_collection_modifyitems(config, items):
    """Every `tier2` item implicitly carries `slow`: tier-2 promotion is
    one marker at the test site, and the long-standing tier-1 selection
    (-m 'not slow') needs no change to exclude the promoted set."""
    for item in items:
        if item.get_closest_marker("tier2") is not None:
            item.add_marker(pytest.mark.slow)


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_programs(request):
    """Module teardown for `release_programs`-marked modules: clear the
    photon_tpu module-level jitted-program caches that pin executables
    alive, then jax.clear_caches() — keeping the rest of the suite inside
    the executable-count envelope it had before the marked module ran."""
    yield
    if request.node.get_closest_marker("release_programs") is None:
        return
    streamed = sys.modules.get("photon_tpu.optim.streamed")
    if streamed is not None:
        streamed._MESH_OPS_CACHE.clear()
    random_effect = sys.modules.get("photon_tpu.game.random_effect")
    if random_effect is not None:
        random_effect._SCAN_DISPATCH.clear()
        random_effect._RE_SOLVERS.clear()
        random_effect._FUSED_RE.clear()
    jax.clear_caches()


# Tier-1 budget guard (round-20 suite-time relief): the driver runs the
# tier-1 selection under `timeout -k 10 870`, and a pass that lands
# within a minute of the cap is one contended box away from a wall-clock
# kill that reads as a regression. The guard asserts the MEASURED
# headroom stays >= 60 s whenever the canonical tier-1 selection runs
# (full tests/ tree, -m 'not slow', no -k filter) — a breach fails the
# session teardown loudly TODAY, instead of the timeout failing it
# nondeterministically next round. Partial selections (single modules,
# -k filters) never trip it.
TIER1_BUDGET_S = 870.0
TIER1_MIN_HEADROOM_S = 60.0


@pytest.fixture(scope="session", autouse=True)
def _tier1_budget_guard(request):
    import time as _time

    t0 = _time.time()
    yield
    config = request.config
    if config.option.markexpr != "not slow" or config.option.keyword:
        return
    if getattr(request.session, "testscollected", 0) < 500:
        return  # partial selection: not the tier-1 wall
    wall = _time.time() - t0
    headroom = TIER1_BUDGET_S - wall
    reporter = config.pluginmanager.get_plugin("terminalreporter")
    capman = config.pluginmanager.get_plugin("capturemanager")
    if reporter is not None and capman is not None:
        # fd-level capture is still armed during session-fixture
        # teardown (the output would silently attach to the last item);
        # suspend it so the headroom line lands on the real terminal
        with capman.global_and_fixture_disabled():
            reporter.write_line(
                f"tier-1 wall {wall:.0f}s — {headroom:.0f}s headroom "
                f"against the {TIER1_BUDGET_S:.0f}s budget")
    assert headroom >= TIER1_MIN_HEADROOM_S, (
        f"tier-1 suite burned {wall:.0f}s of the {TIER1_BUDGET_S:.0f}s "
        f"budget — headroom {headroom:.0f}s < {TIER1_MIN_HEADROOM_S:.0f}s "
        f"floor; promote the slowest acceptance tests to tier2 "
        f"(see `--durations=25`) before the timeout kills a round")


@pytest.fixture(scope="session")
def mesh8():
    from photon_tpu.parallel.mesh import make_mesh

    return make_mesh(data_axis="data", devices=_cpu_devices)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
