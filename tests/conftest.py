"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of unit-testing Spark code on a
local[*] SparkContext (photon-ml SparkTestUtils): we force a fake
8-device CPU platform so every sharding/`psum` path is exercised
without TPU hardware.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# f32 matmuls on CPU for numeric comparisons against scipy/sklearn.
os.environ.setdefault("JAX_DEFAULT_MATMUL_PRECISION", "highest")

import jax  # noqa: E402

jax.config.update("jax_enable_x64", False)
# The axon TPU plugin overrides JAX_PLATFORMS env filtering with its own
# jax_platforms='axon,cpu'; force plain CPU *before* any backend init so the
# suite never touches (or blocks on) the TPU tunnel.
jax.config.update("jax_platforms", "cpu")
_cpu_devices = jax.devices("cpu")
jax.config.update("jax_default_device", _cpu_devices[0])

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from photon_tpu.parallel.mesh import make_mesh

    return make_mesh(data_axis="data", devices=_cpu_devices)


@pytest.fixture
def rng():
    return np.random.default_rng(0)
