"""Data-layer tests: index map, feature bags, normalization, validators,
down-sampling (SURVEY.md §4 'normalization round-trips; index-map
round-trips')."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.dataset import make_batch
from photon_tpu.data.feature_bags import (
    FeatureShardConfig,
    NameTermValue,
    build_design_matrix,
    build_shard,
)
from photon_tpu.data.index_map import (
    DELIMITER,
    INTERCEPT_KEY,
    IndexMap,
    feature_key,
)
from photon_tpu.data.matrix import SparseRows, from_scipy_csr
from photon_tpu.data.normalization import (
    NormalizationContext,
    NormalizationType,
)
from photon_tpu.data.sampling import binary_down_sample, default_down_sample
from photon_tpu.data.validators import (
    DataValidationType,
    validate_glm_data,
)
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.ops.objective import Objective
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig


# --------------------------------------------------------------- index map
class TestIndexMap:
    def test_build_freeze_lookup(self):
        m = IndexMap()
        a = m.index_of(feature_key("age", ""))
        b = m.index_of(feature_key("clicks", "7d"))
        assert (a, b) == (0, 1)
        assert m.index_of(feature_key("age", "")) == 0  # idempotent
        icpt = m.index_of(INTERCEPT_KEY)
        assert icpt == m.intercept_id == len(m) - 1  # intercept last
        m.freeze()
        assert m.index_of("never-seen") == IndexMap.NULL_ID
        assert m.get(feature_key("clicks", "7d")) == 1

    def test_intercept_stays_last_after_growth(self):
        m = IndexMap()
        m.index_of("f0")
        m.index_of(INTERCEPT_KEY)
        m.index_of("f1")  # grows past the intercept
        assert m.intercept_id == 2
        assert m.keys_in_order() == ["f0", "f1", INTERCEPT_KEY]

    def test_save_load_round_trip(self, tmp_path):
        m = IndexMap()
        m.build([feature_key("a", "x"), feature_key("b", ""), INTERCEPT_KEY])
        p = tmp_path / "imap.tsv"
        m.save(p)
        m2 = IndexMap.load(p)
        assert m2.frozen and m2.has_intercept
        assert len(m2) == len(m)
        for k in m.keys_in_order():
            assert m2.get(k) == m.get(k)
        assert DELIMITER in m.keys_in_order()[0]  # delimiter survived escaping


# ------------------------------------------------------------ feature bags
def _records():
    return [
        {"global": [NameTermValue("age", "", 30.0), NameTermValue("ctr", "7d", 0.1)]},
        {"global": [NameTermValue("age", "", 40.0)],
         "extra": [NameTermValue("dev", "ios", 1.0)]},
        {"global": [NameTermValue("ctr", "7d", 0.2),
                    NameTermValue("ctr", "7d", 0.3)]},  # duplicate sums
    ]


class TestFeatureBags:
    def test_dense_shard_with_intercept(self):
        cfg = FeatureShardConfig(bags=("global",))
        X, imap = build_shard(_records(), cfg)
        assert X.shape == (3, 3)  # age, ctr#7d, intercept
        icpt = imap.intercept_id
        np.testing.assert_allclose(np.asarray(X)[:, icpt], 1.0)
        age = imap.get(feature_key("age", ""))
        ctr = imap.get(feature_key("ctr", "7d"))
        np.testing.assert_allclose(np.asarray(X)[:, age], [30.0, 40.0, 0.0])
        np.testing.assert_allclose(np.asarray(X)[:, ctr], [0.1, 0.0, 0.5])

    def test_multi_bag_merge(self):
        cfg = FeatureShardConfig(bags=("global", "extra"), has_intercept=False)
        X, imap = build_shard(_records(), cfg)
        assert X.shape == (3, 3)
        dev = imap.get(feature_key("dev", "ios"))
        np.testing.assert_allclose(np.asarray(X)[:, dev], [0.0, 1.0, 0.0])

    def test_sparse_path_matches_dense(self):
        cfg_d = FeatureShardConfig(bags=("global", "extra"), dense_threshold=1024)
        cfg_s = FeatureShardConfig(bags=("global", "extra"), dense_threshold=1)
        Xd, imap = build_shard(_records(), cfg_d)
        Xs = build_design_matrix(_records(), cfg_s, imap)
        assert isinstance(Xs, SparseRows)
        dense_from_sparse = np.zeros(Xd.shape, np.float32)
        idx, val = np.asarray(Xs.indices), np.asarray(Xs.values)
        for i in range(Xd.shape[0]):
            np.add.at(dense_from_sparse[i], idx[i], val[i])
        np.testing.assert_allclose(dense_from_sparse, np.asarray(Xd))

    def test_frozen_map_drops_unseen(self):
        cfg = FeatureShardConfig(bags=("global",), has_intercept=False)
        _, imap = build_shard(_records()[:1], cfg)  # only age, ctr
        X = build_design_matrix(
            [{"global": [NameTermValue("brand-new", "", 5.0),
                         NameTermValue("age", "", 25.0)]}], cfg, imap)
        row = np.asarray(X)[0]
        assert row[imap.get(feature_key("age", ""))] == 25.0
        assert np.count_nonzero(row) == 1  # unseen feature dropped


# ---------------------------------------------------------- normalization
def _logit_problem(rng, n=2000, d=8, scale=None):
    X = rng.normal(size=(n, d)).astype(np.float32)
    if scale is not None:
        X *= scale  # wildly different column scales
    X[:, -1] = 1.0  # intercept last
    w = rng.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y


class TestNormalization:
    def test_stats_modes(self, rng):
        X = rng.normal(size=(500, 4)).astype(np.float32) * [1, 10, 100, 1]
        X[:, -1] = 1.0
        ctx = NormalizationContext.build(
            jnp.asarray(X), NormalizationType.SCALE_WITH_STANDARD_DEVIATION)
        np.testing.assert_allclose(
            ctx.factors[:-1], 1.0 / X[:, :-1].std(0), rtol=1e-5)
        assert ctx.factors[-1] == 1.0  # intercept untouched
        ctx2 = NormalizationContext.build(
            jnp.asarray(X), NormalizationType.SCALE_WITH_MAX_MAGNITUDE)
        np.testing.assert_allclose(
            ctx2.factors[:-1], 1.0 / np.abs(X[:, :-1]).max(0), rtol=1e-5)
        ctx3 = NormalizationContext.build(
            jnp.asarray(X), NormalizationType.STANDARDIZATION)
        np.testing.assert_allclose(ctx3.shifts[:-1], X[:, :-1].mean(0), rtol=1e-4,
                                   atol=1e-6)
        assert ctx3.shifts[-1] == 0.0

    def test_sparse_stats_match_dense(self, rng):
        import scipy.sparse as sp

        Xd = rng.normal(size=(200, 6)).astype(np.float32)
        Xd[Xd < 0.3] = 0.0  # sparsify; implicit zeros must count in stats
        Xs = from_scipy_csr(sp.csr_matrix(Xd))
        for t in (NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
                  NormalizationType.SCALE_WITH_MAX_MAGNITUDE):
            cd = NormalizationContext.build(jnp.asarray(Xd), t,
                                            intercept_index=None)
            cs = NormalizationContext.build(Xs, t, intercept_index=None)
            np.testing.assert_allclose(cs.factors, cd.factors, rtol=1e-4)

    def test_normalized_objective_grad_matches_autodiff(self, rng):
        X, y = _logit_problem(rng, n=300, d=6, scale=np.float32([1, 5, 50, 0.1, 2, 1]))
        ctx = NormalizationContext.build(
            jnp.asarray(X), NormalizationType.STANDARDIZATION)
        obj = Objective(
            task=TaskType.LOGISTIC_REGRESSION, l2=0.5,
            norm_factors=jnp.asarray(ctx.factors),
            norm_shifts=jnp.asarray(ctx.shifts),
        )
        batch = make_batch(X, y)
        w = jnp.asarray(rng.normal(size=6), jnp.float32)
        v, g = obj.value_and_grad(w, batch)
        g_auto = jax.grad(lambda w: obj.value_and_grad(w, batch)[0])(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_auto),
                                   rtol=1e-4, atol=1e-4)
        # HVP against autodiff too (shift + factor chain rule)
        vdir = jnp.asarray(rng.normal(size=6), jnp.float32)
        hv = obj.hvp(w, batch, vdir)
        hv_auto = jax.jvp(
            lambda w: jax.grad(lambda u: obj.value_and_grad(u, batch)[0])(w),
            (w,), (vdir,))[1]
        np.testing.assert_allclose(np.asarray(hv), np.asarray(hv_auto),
                                   rtol=1e-3, atol=1e-3)
        # Hessian diagonal matches the dense Hessian's diagonal
        H = obj.full_hessian(w, batch)
        hd = obj.hess_diag(w, batch)
        np.testing.assert_allclose(np.asarray(hd), np.asarray(jnp.diag(H)),
                                   rtol=1e-3, atol=1e-3)

    def test_training_under_standardization_matches_materialized(self, rng):
        """train_glm(normalization=...) on raw X == train_glm on explicitly
        standardized X with coefficients mapped back (no regularization, so
        the two parameterizations have identical optima)."""
        scale = np.float32([100.0, 0.01, 1.0, 10.0, 1.0, 1.0])
        X, y = _logit_problem(rng, n=2000, d=6, scale=scale)
        ctx = NormalizationContext.build(
            jnp.asarray(X), NormalizationType.STANDARDIZATION)
        cfg = OptimizerConfig(max_iters=200, tolerance=1e-12)
        m_norm, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                              cfg, normalization=ctx)
        Xstd = X.copy()
        Xstd[:, :-1] = (X[:, :-1] - X[:, :-1].mean(0)) / X[:, :-1].std(0)
        m_mat, _ = train_glm(make_batch(Xstd, y), TaskType.LOGISTIC_REGRESSION,
                             cfg)
        w_mat_orig = ctx.to_original_space(np.asarray(m_mat.weights))
        np.testing.assert_allclose(np.asarray(m_norm.weights), w_mat_orig,
                                   rtol=2e-2, atol=2e-3)
        # and the normalized solve beats the raw solve's conditioning:
        # same data, badly scaled — raw solve needs far more iterations.

    def test_shifts_without_intercept_rejected(self):
        with pytest.raises(ValueError, match="intercept_index"):
            NormalizationContext(
                NormalizationType.STANDARDIZATION,
                factors=np.ones(3, np.float32),
                shifts=np.zeros(3, np.float32),
            )

    def test_coefficient_space_round_trip(self, rng):
        X, _ = _logit_problem(rng, n=100, d=5)
        ctx = NormalizationContext.build(
            jnp.asarray(X), NormalizationType.STANDARDIZATION)
        w = rng.normal(size=5).astype(np.float32)
        np.testing.assert_allclose(
            ctx.to_normalized_space(ctx.to_original_space(w)), w,
            rtol=1e-4, atol=1e-5)


# -------------------------------------------------------------- validators
class TestValidators:
    def test_passes_clean_data(self, rng):
        X = rng.normal(size=(50, 3))
        y = (rng.uniform(size=50) < 0.5).astype(np.float32)
        validate_glm_data(y, X=X, task=TaskType.LOGISTIC_REGRESSION)

    def test_catches_all_failures_at_once(self):
        y = np.array([0.0, 1.0, 2.0, np.nan])
        X = np.array([[1.0], [np.inf], [0.0], [0.0]])
        w = np.array([1.0, -1.0, 1.0, 1.0])
        with pytest.raises(ValueError) as e:
            validate_glm_data(y, X=X, weights=w,
                              task=TaskType.LOGISTIC_REGRESSION)
        msg = str(e.value)
        assert "non-finite labels" in msg
        assert "non-binary labels" in msg
        assert "non-finite feature" in msg
        assert "negative or non-finite weights" in msg

    def test_poisson_negative_labels(self):
        with pytest.raises(ValueError, match="negative labels"):
            validate_glm_data(np.array([1.0, -2.0]),
                              task=TaskType.POISSON_REGRESSION)

    def test_disabled_skips(self):
        validate_glm_data(np.array([np.nan]), mode=DataValidationType.DISABLED)


# ---------------------------------------------------------------- sampling
class TestDownSampling:
    def test_default_preserves_total_weight_in_expectation(self, rng):
        n, rate = 20000, 0.3
        idx, w = default_down_sample(n, rate, seed=1)
        assert abs(w.sum() - n) / n < 0.05  # unbiased: E[sum w] = n
        assert len(idx) < n * 0.4

    def test_binary_keeps_all_positives(self, rng):
        y = (rng.uniform(size=10000) < 0.1).astype(np.float32)
        idx, w = binary_down_sample(y, 0.2, seed=2)
        kept_y = y[idx]
        assert kept_y.sum() == y.sum()  # every positive survives
        np.testing.assert_allclose(w[kept_y > 0], 1.0)  # positive weights untouched
        np.testing.assert_allclose(w[kept_y == 0], 1.0 / 0.2)
        # negative effective mass preserved in expectation
        neg_mass = w[kept_y == 0].sum()
        assert abs(neg_mass - (y == 0).sum()) / (y == 0).sum() < 0.05

    def test_rate_validation(self):
        with pytest.raises(ValueError):
            default_down_sample(10, 0.0)
        with pytest.raises(ValueError):
            binary_down_sample(np.zeros(4), 1.5)
