"""Hybrid ICI×DCN mesh path (multi-host story), on the virtual 8-CPU mesh.

Mirrors the reference's cluster semantics (Spark executors over Ethernet)
with a 2-D (replica × data) mesh: examples shard over both axes, the
gradient all-reduce psums over both, and results must match the 1-D mesh
and single-device solves to f32 reduction noise.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from photon_tpu.parallel.mesh import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_tpu.data.dataset import make_batch
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.ops.objective import Objective
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.parallel.mesh import (
    data_sharding,
    initialize_distributed,
    make_hybrid_mesh,
    pad_to_multiple,
)


@pytest.fixture(scope="module")
def hybrid_mesh():
    return make_hybrid_mesh(n_replicas=2, devices=jax.devices("cpu"))


def _logistic(rng, n=2048, d=10):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
    return X, y


def test_hybrid_mesh_shape(hybrid_mesh):
    assert hybrid_mesh.axis_names == ("replica", "data")
    assert hybrid_mesh.devices.shape == (2, 4)
    spec = data_sharding(hybrid_mesh).spec
    assert spec == P(("replica", "data"))


def test_train_glm_on_hybrid_mesh(rng, hybrid_mesh):
    X, y = _logistic(rng)
    cfg = OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=1.0,
                          regularize_intercept=True)
    m_single, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                            cfg)
    m_hybrid, res = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                              cfg, mesh=hybrid_mesh)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(m_hybrid.coefficients.means),
                               np.asarray(m_single.coefficients.means),
                               atol=2e-3)


def test_hierarchical_psum_gradient(rng, hybrid_mesh):
    """Explicit shard_map over BOTH axes: psum(("replica","data")) equals the
    single-device gradient — pins the hierarchical collective pattern."""
    X, y = _logistic(rng, n=1024, d=6)
    batch = make_batch(X, y)
    w = jnp.asarray(rng.normal(size=6), jnp.float32) * 0.2

    obj_local = Objective(task=TaskType.LOGISTIC_REGRESSION, l2=0.3)
    v_ref, g_ref = obj_local.value_and_grad(w, batch)

    obj = Objective(task=TaskType.LOGISTIC_REGRESSION, l2=0.3,
                    axis_name=("replica", "data"))

    @jax.jit
    def sharded(batch, w):
        return shard_map(
            lambda b, w: obj.value_and_grad(w, b),
            mesh=hybrid_mesh,
            in_specs=(P(("replica", "data")), P()),
            out_specs=(P(), P()),
        )(batch, w)

    f, g = sharded(
        jax.device_put(batch, data_sharding(hybrid_mesh)),
        jax.device_put(w, NamedSharding(hybrid_mesh, P())))
    np.testing.assert_allclose(float(f), float(v_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)


def test_single_all_reduce_per_evaluation(rng, mesh8):
    """Pins the communication pattern: one value_and_grad under shard_map
    traces to exactly ONE psum equation (value and gradient partial sums
    ride the same variadic collective — the reference's single
    treeAggregate). Counted at the JAXPR level with the shared
    photon_tpu.analysis walker: backend-independent, where the old
    compiled-HLO `all-reduce(` text count broke on the CPU test backend's
    missing all-reduce combiner (it legally splits the variadic psum)."""
    from photon_tpu.analysis import collective_counts

    X, y = _logistic(rng, n=512, d=6)
    batch = make_batch(X, y)
    obj = Objective(task=TaskType.LOGISTIC_REGRESSION, l2=0.5,
                    axis_name="data")

    @jax.jit
    def vg(batch, w):
        return shard_map(
            lambda b, w: obj.value_and_grad(w, b), mesh=mesh8,
            in_specs=(P("data"), P()), out_specs=(P(), P()))(batch, w)

    counts = collective_counts(jax.make_jaxpr(vg)(batch, jnp.zeros(6)))
    assert counts == {"psum": 1}, \
        f"expected exactly 1 psum and no other collective, " \
        f"traced {dict(counts)}"


def test_padding_divides_hybrid_mesh(hybrid_mesh):
    n_dev = hybrid_mesh.devices.size
    assert pad_to_multiple(1000, n_dev) % n_dev == 0


def test_initialize_distributed_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
    assert initialize_distributed() is False


def test_bad_replica_count(rng):
    with pytest.raises(ValueError):
        make_hybrid_mesh(n_replicas=3, devices=jax.devices("cpu"))


def test_sharded_hybrid_solve_collectives(rng, mesh8):
    """The ShardedHybridRows shard_map solve: its value_and_grad traces to
    exactly ONE psum and NO other collective — the per-shard tail
    gather/scatter provably never crosses devices (the point of the
    per-shard-tail layout; a global segment_sum under SPMD inference gives
    XLA no such guarantee). Jaxpr-level via photon_tpu.analysis:
    backend-independent, unlike the old HLO `all-reduce(` text count."""
    import scipy.sparse as sp

    from photon_tpu.analysis import collective_counts
    from photon_tpu.data.dataset import shard_hybrid_batch
    from photon_tpu.models.training import _hybrid_specs

    n, d, k = 512, 64, 8
    cols = rng.integers(0, d, size=(n, k))
    rows = np.repeat(np.arange(n), k)
    M = sp.csr_matrix((rng.normal(size=n * k).astype(np.float32),
                       (rows, cols.ravel())), shape=(n, d))
    M.sum_duplicates()
    from photon_tpu.data.matrix import from_scipy_csr

    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    batch = shard_hybrid_batch(make_batch(from_scipy_csr(M), y), 8,
                               d_dense=16)
    obj = Objective(task=TaskType.LOGISTIC_REGRESSION, l2=0.5,
                    axis_name="data")

    @jax.jit
    def vg(batch, w):
        def body(b, w):
            return obj.value_and_grad(w, b._replace(X=b.X.local()))

        return shard_map(
            body, mesh=mesh8,
            in_specs=(_hybrid_specs(batch.X, ("data",)), P()),
            out_specs=(P(), P()))(batch, w)

    counts = collective_counts(jax.make_jaxpr(vg)(batch, jnp.zeros(d)))
    assert counts == {"psum": 1}, \
        f"expected exactly 1 psum and no other collective in the hybrid " \
        f"solve, traced {dict(counts)}"


def test_sharded_permuted_solve_collectives_and_no_scatter(rng, mesh8):
    """The ShardedPermutedHybridRows shard_map solve — the multi-chip form
    of the scatter-free layout — traces to exactly ONE psum, NO other
    collectives, and ZERO scatter ops: the round-5 measured wall (TPU
    scatter-adds at ~12 ns/element vs ~7 ns/gather-index, docs/PERF.md) is
    eliminated by construction on the mesh path too, where
    ShardedHybridRows still pays a per-shard tail segment_sum. The pin
    covers one value_and_grad (scatter-free outright) and the FULL
    lane-grid solver program, whose only scatter eqns are `.at[i].set`
    L-BFGS history writes — plain `scatter`, lowered to
    dynamic-update-slice, never a combining scatter-add. Jaxpr-level via
    photon_tpu.analysis: backend-independent, unlike the old HLO text
    counts."""
    from photon_tpu.analysis import (SCATTER_ADD_PRIMITIVES,
                                     SCATTER_PRIMITIVES, collective_counts,
                                     count_primitives)
    from photon_tpu.data.dataset import shard_permuted_batch
    from photon_tpu.models.training import (_hybrid_specs,
                                            _train_run_sharded_grid_lanes,
                                            lane_weight_arrays,
                                            make_objective)
    from photon_tpu.optim.config import OptimizerConfig as OC

    n, d, k = 512, 300, 6
    cols = (rng.zipf(1.5, size=(n, k)).astype(np.int64) - 1) % d
    vals = rng.normal(size=(n, k)).astype(np.float32)
    from photon_tpu.data.matrix import SparseRows

    X = SparseRows(jnp.asarray(cols.astype(np.int32)), jnp.asarray(vals), d)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    batch = shard_permuted_batch(make_batch(X, y), 8, d_dense=16)
    obj = Objective(task=TaskType.LOGISTIC_REGRESSION, l2=0.5,
                    axis_name="data")

    @jax.jit
    def vg(batch, w):
        def body(b, w):
            return obj.value_and_grad(w, b._replace(X=b.X.local()))

        return shard_map(
            body, mesh=mesh8,
            in_specs=(_hybrid_specs(batch.X, ("data",)), P()),
            out_specs=(P(), P()))(batch, w)

    jaxpr = jax.make_jaxpr(vg)(batch, jnp.zeros(d))
    counts = collective_counts(jaxpr)
    assert counts == {"psum": 1}, \
        f"expected exactly 1 psum and no other collective, " \
        f"traced {dict(counts)}"
    scatters = count_primitives(jaxpr, SCATTER_PRIMITIVES)
    assert not scatters, \
        f"unexpected scatter in sharded permuted solve: {dict(scatters)}"

    # The whole lane-grid solver program: no combining scatter anywhere.
    cfg = OC(max_iters=10, tolerance=1e-7, reg=reg.l2(), reg_weight=0.0,
             history=5)
    l2s, l1s, static_cfg = lane_weight_arrays(cfg, [0.1, 1.0])
    obj_g = make_objective(TaskType.LOGISTIC_REGRESSION, cfg, d,
                           axis_name="data",
                           intercept_index=batch.X.last_col_pos)
    jaxpr_g = jax.make_jaxpr(
        lambda b, w, o, l2v: _train_run_sharded_grid_lanes(
            b, w, o, l2v, None, static_cfg, mesh8))(
        batch, jnp.zeros(d), obj_g, l2s)
    adds = count_primitives(jaxpr_g, SCATTER_ADD_PRIMITIVES)
    assert not adds, \
        f"combining scatter in the sharded permuted lane-grid program: " \
        f"{dict(adds)}"


def test_sharded_hybrid_on_hybrid_mesh(rng, hybrid_mesh):
    """ShardedHybridRows solves on a 2-D (replica × data) mesh: tails shard
    over BOTH axes, psums lower hierarchically, results match single-device."""
    import scipy.sparse as sp

    from photon_tpu.data.dataset import shard_hybrid_batch
    from photon_tpu.data.matrix import from_scipy_csr
    from photon_tpu.optim.config import OptimizerConfig as OC

    n, d, k = 640, 48, 6
    cols = rng.integers(0, d, size=(n, k))
    M = sp.csr_matrix((rng.normal(size=n * k).astype(np.float32),
                       (np.repeat(np.arange(n), k), cols.ravel())),
                      shape=(n, d))
    M.sum_duplicates()
    X = from_scipy_csr(M)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    cfg = OC(max_iters=30, reg=reg.l2(), reg_weight=1.0,
             regularize_intercept=True)
    m_ref, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg)
    b = shard_hybrid_batch(make_batch(X, y), hybrid_mesh.devices.size,
                           d_dense=16)
    m_sh, res = train_glm(b, TaskType.LOGISTIC_REGRESSION, cfg,
                          mesh=hybrid_mesh)
    assert not bool(res.failed)
    np.testing.assert_allclose(np.asarray(m_sh.coefficients.means),
                               np.asarray(m_ref.coefficients.means),
                               atol=5e-3)


def test_sharded_hybrid_grid_on_hybrid_mesh(rng, hybrid_mesh):
    """Reg-weight grid over ShardedHybridRows on the 2-D (replica × data)
    mesh: lanes vmapped inside shard_map, psums over both axes."""
    import scipy.sparse as sp

    from photon_tpu.data.dataset import shard_hybrid_batch
    from photon_tpu.data.matrix import from_scipy_csr
    from photon_tpu.models.training import train_glm_grid
    from photon_tpu.optim.config import OptimizerConfig as OC

    n, d, k = 512, 32, 6
    cols = rng.integers(0, d, size=(n, k))
    M = sp.csr_matrix((rng.normal(size=n * k).astype(np.float32),
                       (np.repeat(np.arange(n), k), cols.ravel())),
                      shape=(n, d))
    M.sum_duplicates()
    X = from_scipy_csr(M)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    cfg = OC(max_iters=25, reg=reg.l2(), reg_weight=0.0,
             regularize_intercept=True)
    ref = train_glm_grid(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                         cfg, [0.5, 5.0])
    b = shard_hybrid_batch(make_batch(X, y), hybrid_mesh.devices.size,
                           d_dense=8)
    got = train_glm_grid(b, TaskType.LOGISTIC_REGRESSION, cfg, [0.5, 5.0],
                         mesh=hybrid_mesh)
    for (m_r, _), (m_g, r_g) in zip(ref, got):
        assert not bool(r_g.failed)
        np.testing.assert_allclose(np.asarray(m_g.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   atol=5e-3)


# ------------------------------------------------------- round 17: the spine
class TestShardChunkRange:
    """The canonical per-process chunk split (data/chunk_cache.py) that
    the distributed cache AND the local_only ingest convention lean on:
    contiguous, in process order, an EXACT partition of [0, n_chunks)."""

    def test_union_is_exact_partition(self):
        from photon_tpu.data.chunk_cache import shard_chunk_range

        for n_chunks in (0, 1, 7, 8, 9, 64, 1000):
            for n_proc in (1, 2, 3, 4, 8):
                spans = [shard_chunk_range(n_chunks, k, n_proc)
                         for k in range(n_proc)]
                # contiguous in process order, starting at 0, ending at n
                assert spans[0][0] == 0
                assert spans[-1][1] == n_chunks
                for (a_lo, a_hi), (b_lo, b_hi) in zip(spans, spans[1:]):
                    assert a_hi == b_lo, (n_chunks, n_proc, spans)
                # balanced: sizes differ by at most one, big ones first
                sizes = [hi - lo for lo, hi in spans]
                assert max(sizes) - min(sizes) <= 1
                assert sizes == sorted(sizes, reverse=True)

    def test_fewer_chunks_than_processes(self):
        """n_chunks < n_processes: the tail processes get VALID empty
        ranges (lo == hi) — a zero-row cluster member is legal and must
        not crash the split."""
        from photon_tpu.data.chunk_cache import shard_chunk_range

        spans = [shard_chunk_range(2, k, 4) for k in range(4)]
        assert spans == [(0, 1), (1, 2), (2, 2), (2, 2)]
        assert all(lo <= hi for lo, hi in spans)

    def test_non_dividing_counts(self):
        from photon_tpu.data.chunk_cache import shard_chunk_range

        assert [shard_chunk_range(10, k, 4) for k in range(4)] == \
            [(0, 3), (3, 6), (6, 8), (8, 10)]

    def test_process_out_of_range(self):
        from photon_tpu.data.chunk_cache import shard_chunk_range

        with pytest.raises(ValueError, match="out of range"):
            shard_chunk_range(10, 4, 4)
        with pytest.raises(ValueError, match="out of range"):
            shard_chunk_range(10, -1, 4)


class TestInitializeDistributedValidation:
    """Round-17 satellite: loud validation BEFORE any network traffic,
    and the PHOTON_TPU_* knob plumbing the launcher rides."""

    def test_process_id_out_of_range(self):
        with pytest.raises(ValueError, match=r"ranks are 0\.\.3"):
            initialize_distributed("127.0.0.1:9", num_processes=4,
                                   process_id=4)
        with pytest.raises(ValueError, match="out of range"):
            initialize_distributed("127.0.0.1:9", num_processes=2,
                                   process_id=-1)

    def test_process_id_without_num_processes(self):
        with pytest.raises(ValueError, match="without num_processes"):
            initialize_distributed("127.0.0.1:9", process_id=0)

    def test_bad_num_processes(self):
        with pytest.raises(ValueError, match="num_processes"):
            initialize_distributed("127.0.0.1:9", num_processes=0)

    def test_knobs_feed_validation(self, monkeypatch):
        """The PHOTON_TPU_* env knobs land in the same validation path
        as explicit arguments."""
        monkeypatch.setenv("PHOTON_TPU_NUM_PROCESSES", "2")
        monkeypatch.setenv("PHOTON_TPU_PROCESS_ID", "5")
        with pytest.raises(ValueError, match="out of range"):
            initialize_distributed()

    def test_double_initialize_refused(self, monkeypatch):
        """A live distributed client means a second initialize must be
        refused with the fix spelled out, not forwarded to jax's opaque
        failure."""
        from photon_tpu.parallel import mesh as mesh_mod

        monkeypatch.setattr(mesh_mod, "distributed_client",
                            lambda: object())
        with pytest.raises(RuntimeError, match="already initialized"):
            mesh_mod.initialize_distributed("127.0.0.1:9",
                                            num_processes=2, process_id=0)

    def test_knobs_are_registered(self):
        from photon_tpu.utils.env import KNOB_DOCS

        for knob in ("PHOTON_TPU_COORDINATOR", "PHOTON_TPU_NUM_PROCESSES",
                     "PHOTON_TPU_PROCESS_ID",
                     "PHOTON_TPU_BARRIER_TIMEOUT_S"):
            assert knob in KNOB_DOCS, knob


class TestGradOnlyDcnContract:
    """The round-17 wire bill, priced: the one psum closing a sharded
    evaluation carries O(d) bytes — the features (O(n*d)) never ride a
    collective. (The contract itself — exactly one psum — is checked
    with the whole registry; here the BYTES are pinned.)"""

    def test_collective_bytes_are_gradient_sized(self):
        from photon_tpu.analysis.contracts import REGISTRY
        from photon_tpu.analysis import trace_contract
        from photon_tpu.profiling.model import estimate_jaxpr

        spec = REGISTRY["multihost_grad_only_dcn"]
        traced = trace_contract(spec)
        cost = estimate_jaxpr(traced.closed_jaxpr)
        d = 48
        # per-shard psum payload: the (d,) gradient partial + the scalar
        # value partial, f32
        assert cost.collective_bytes == (d + 1) * 4
        batch = traced.example_args[0]
        feature_bytes = int(np.asarray(batch.X).nbytes)
        per_shard_features = feature_bytes // len(jax.devices())
        assert per_shard_features >= 100 * cost.collective_bytes


class TestLaunchValidation:
    """parallel.launch argument validation — no processes are spawned."""

    def test_non_dividing_device_count(self):
        from photon_tpu.parallel.launch import launch

        with pytest.raises(ValueError, match="does not divide"):
            launch(len, 3, total_devices=8)

    def test_bad_process_count(self):
        from photon_tpu.parallel.launch import launch

        with pytest.raises(ValueError, match="n_processes"):
            launch(len, 0)


def _launch_or_skip(target, n, **kwargs):
    from photon_tpu.parallel.launch import ClusterUnavailable, launch

    try:
        return launch(target, n, **kwargs)
    except ClusterUnavailable as e:
        pytest.skip(f"jax.distributed cluster unavailable in this "
                    f"sandbox: {e}")


@pytest.mark.tier2
class TestMultiProcessSpine:
    """The round-17 acceptance matrix across REAL process boundaries:
    1/2/4 spawned cluster members over the SAME 8-device global mesh.
    Promoted straight to tier-2 (each case spawns + initializes several
    jax runtimes); the umbrella `python -m photon_tpu.parallel
    --selftest` keeps a bounded smoke of the same targets."""

    def test_psum_bit_identical_across_process_counts(self):
        from photon_tpu.parallel import selfcheck as sc

        digests = set()
        for n in (1, 2, 4):
            res = _launch_or_skip(sc.target_psum_signature, n,
                                  timeout_s=180)
            assert [r["rank"] for r in res] == list(range(n))
            assert all(r["n_devices"] == 8 for r in res)
            digests.update(r["digest"] for r in res)
        assert len(digests) == 1, digests

    def test_e2e_solve_bit_identical_and_ingest_split(self, tmp_path):
        """The tentpole bar: scan -> local_only ingest -> mesh GLM solve
        at 1, 2 and 4 processes — f64 coefficients BIT-identical, and
        each multi-process rank provably decoded only a strict subset of
        the chunks."""
        from photon_tpu.parallel import selfcheck as sc

        sc.write_e2e_dataset(tmp_path)
        w_by_n = {}
        for n in (1, 2, 4):
            res = _launch_or_skip(sc.target_stream_solve, n,
                                  args=(str(tmp_path),), timeout_s=420)
            assert all(r["n_real"] == 1200 for r in res)
            if n == 1:
                assert res[0]["chunks_skipped"] == 0
            else:
                # every rank decoded SOME chunks and skipped SOME —
                # the disk/decode work is genuinely partitioned
                assert all(r["chunks_decoded"] >= 1 for r in res)
                assert all(r["chunks_skipped"] >= 1 for r in res)
            w_by_n[n] = np.stack([r["w"] for r in res])
            # replicated model: every rank returns the same bits
            assert all(np.array_equal(w_by_n[n][0], w) for w in w_by_n[n])
        np.testing.assert_array_equal(w_by_n[1][0], w_by_n[2][0])
        np.testing.assert_array_equal(w_by_n[1][0], w_by_n[4][0])

    def test_two_proc_snapshot_restores_at_1_and_4_procs(self, tmp_path):
        """Elastic restore across process counts: a 2-process mesh-
        streamed solve killed mid-run leaves per-process p<k>_ payloads
        with per-slot row-cache entries; 1- and 4-process clusters must
        both finish BIT-identical to the uninterrupted run (the global
        8-slot mesh is the same at every count)."""
        from photon_tpu.parallel import selfcheck as sc

        ref = _launch_or_skip(sc.target_resume_solve, 1,
                              args=(str(tmp_path / "ref"),),
                              timeout_s=300)[0]
        for resume_n in (1, 4):
            ck = tmp_path / f"snap_{resume_n}"
            killed = _launch_or_skip(sc.target_snapshot_kill, 2,
                                     args=(str(ck), "evaluation", 7),
                                     timeout_s=300)
            assert all(r["killed"] for r in killed), killed
            assert all(r["latest_seq"] >= 0 for r in killed), killed
            res = _launch_or_skip(sc.target_resume_solve, resume_n,
                                  args=(str(ck),), timeout_s=300)
            for r in res:
                np.testing.assert_array_equal(ref["w"], r["w"])

    def test_commit_kill_fails_loudly_previous_manifest_intact(
            self, tmp_path):
        """Satellite 1: rank 1 dies BETWEEN its durable payload write
        and the commit barrier. The surviving rank's commit must fail
        within PHOTON_TPU_BARRIER_TIMEOUT_S (loud, not hung), the
        manifest must still point at the last fully-committed snapshot,
        and every payload it references must exist."""
        import os

        from photon_tpu.checkpoint import SnapshotStore
        from photon_tpu.parallel import selfcheck as sc

        ck = tmp_path / "ck"
        res = _launch_or_skip(
            sc.target_commit_kill, 2, args=(str(ck), 1, 2),
            timeout_s=300, env={"PHOTON_TPU_BARRIER_TIMEOUT_S": "8"})
        by_rank = {r["rank"]: r for r in res}
        assert by_rank[1]["outcome"] == "killed"
        assert by_rank[0]["outcome"] == "commit_failed", by_rank[0]
        store = SnapshotStore(str(ck))
        manifest = store.read_manifest()
        assert manifest is not None and manifest["seq"] == 0
        # the committed snapshot fully resolves: no referenced payload
        # is missing even though a LATER snapshot attempt died half-way
        state, _ = store.load_latest()
        assert state
        snap_dir = os.path.join(str(ck), manifest["latest"])
        assert os.path.isdir(snap_dir)
