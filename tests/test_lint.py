"""photon_tpu.lint: the source-level convention auditor.

Every rule is proven to FIRE on a violating in-memory fixture repo (a
tmp_path tree with just the registries the rules read), the suppression
comment is honored with a reason and rejected without one, the --json
CLI round-trips as a subprocess, and — the tier-1 acceptance — the
repo-wide run exits 0 at HEAD with an EMPTY baseline.

Deliberately jax-free fixtures: the whole module runs in well under a
second, which is what lets the auditor ride tier-1 without budget cost.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from photon_tpu.lint import (Finding, load_baseline, repo_root, run_lint)
from photon_tpu.lint.rules import RULES

REPO = repo_root()


# --------------------------------------------------------------- fixture

_REGISTRIES = {
    "photon_tpu/__init__.py": "",
    "photon_tpu/checkpoint/__init__.py": "",
    "photon_tpu/checkpoint/faults.py": '''
"""sites"""
FAULT_SITES = {"commit": "the commit site", "evaluation": "eval tick"}

def kill_point(site):
    pass
''',
    "photon_tpu/telemetry/__init__.py": '''
"""Counters: the stream family chunk_uploads counter; latency_ gauges;
solve spans."""
TELEMETRY_REGISTRY = {
    "counters": ("stream.chunk_uploads",),
    "gauges": ("serving.latency_*",),
    "span_families": ("solve",),
}
''',
    "photon_tpu/utils/__init__.py": "",
    "photon_tpu/utils/env.py": '''
"""knobs"""
KNOB_DOCS = {"PHOTON_TPU_DEMO": "a demo knob. Owner: demo.py."}

def get_raw(name, default=None):
    import os
    return os.environ.get(name, default)
''',
    "photon_tpu/analysis/__init__.py": "",
    "photon_tpu/analysis/registry.py": '''
HOT_PATH_MODULES = ("photon_tpu.hot",)
''',
    "photon_tpu/profiling/__init__.py": "",
    "photon_tpu/profiling/sentinel.py": '''
_LOWER_BETTER_PATTERNS = ("_ms", "stall")
_EXCLUDE_PATTERNS = ("_n_chips",)
''',
    # a clean module exercising the registries so the clean fixture has
    # no orphan findings
    "photon_tpu/hot.py": '''
from photon_tpu.analysis.contracts import register_contract
from photon_tpu import telemetry
from photon_tpu.checkpoint.faults import kill_point, retry_io
from photon_tpu.utils import env as env_knobs

def touch():
    kill_point("commit")
    retry_io(lambda: 0, site="evaluation")
    telemetry.count("stream.chunk_uploads")
    telemetry.gauge(f"serving.latency_{0}")
    with telemetry.span("solve.demo"):
        pass
    return env_knobs.get_raw("PHOTON_TPU_DEMO")

register_contract(None)
''',
    "bench.py": '''
def main():
    doc = {"legs": {"demo_rate_rows_per_sec": 1.0,
                    "demo_wall_ms": 2.0,
                    "demo_shards_n_chips": 8}}
    return doc

if __name__ == "__main__":
    main()
''',
}


def write_repo(tmp_path, extra=None, replace=None):
    files = dict(_REGISTRIES)
    files.update(replace or {})
    files.update(extra or {})
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


def findings_of(report, rule):
    return [f for f in report["findings"] if f.rule == rule]


def run_rules(root, only=None):
    return run_lint(root=root, only=only, baseline=set())


# ---------------------------------------------------------- clean fixture

def test_clean_fixture_has_no_findings(tmp_path):
    report = run_rules(write_repo(tmp_path))
    assert [f.text for f in report["findings"]] == []
    assert report["ok"] and report["n_rules"] == len(RULES) + 1


# ------------------------------------------------------- 1. durable write

class TestDurableWrite:
    def test_fires_on_raw_write(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
import json

def save(path, doc):
    with open(path, "w") as fh:
        json.dump(doc, fh)
'''})
        f, = findings_of(run_rules(root, ["durable_write"]),
                         "durable_write")
        assert f.path == "photon_tpu/bad.py" and "commit_bytes" in f.message

    def test_mode_kw_and_exclusive_create_fire(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
def save(path):
    open(path, mode="xb").write(b"")
'''})
        assert findings_of(run_rules(root, ["durable_write"]),
                           "durable_write")

    def test_append_and_read_are_legal(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/ok.py": '''
def log(path):
    open(path, "a").write("event\\n")
    return open(path).read()
'''})
        assert not findings_of(run_rules(root, ["durable_write"]),
                               "durable_write")

    def test_commit_primitive_file_is_exempt(self, tmp_path):
        root = write_repo(tmp_path, extra={
            "photon_tpu/checkpoint/store.py": '''
def commit_bytes(path, data):
    with open(path + ".tmp", "wb") as f:
        f.write(data)
'''})
        assert not findings_of(run_rules(root, ["durable_write"]),
                               "durable_write")

    def test_suppression_with_reason_honored(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
def save(path):
    # lint: rawwrite(scratch artifact, nothing resumes from it)
    with open(path, "w") as fh:
        fh.write("x")
'''})
        report = run_rules(root, ["durable_write"])
        assert not findings_of(report, "durable_write")
        assert len(report["suppressed"]) == 1

    def test_suppression_without_reason_rejected(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
def save(path):
    # lint: rawwrite()
    with open(path, "w") as fh:
        fh.write("x")
'''})
        report = run_rules(root)
        assert findings_of(report, "durable_write"), \
            "reasonless suppression must not suppress"
        sup, = findings_of(report, "suppression")
        assert "no reason" in sup.message

    def test_wrong_tag_does_not_suppress(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
def save(path):
    # lint: unlocked(wrong tag for this rule)
    with open(path, "w") as fh:
        fh.write("x")
'''})
        assert findings_of(run_rules(root, ["durable_write"]),
                           "durable_write")


# -------------------------------------------------- 2. fault-site registry

class TestFaultSiteRegistry:
    def test_undeclared_site_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
from photon_tpu.checkpoint.faults import kill_point

def f():
    kill_point("mystery_site")
'''})
        f, = findings_of(run_rules(root, ["fault_site_registry"]),
                         "fault_site_registry")
        assert "mystery_site" in f.message and f.path == "photon_tpu/bad.py"

    def test_retry_io_site_kw_checked(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
from photon_tpu.checkpoint.faults import retry_io

def f():
    return retry_io(lambda: 0, site="mystery_io")
'''})
        assert findings_of(run_rules(root, ["fault_site_registry"]),
                           "fault_site_registry")

    def test_orphan_declared_site_fires(self, tmp_path):
        root = write_repo(tmp_path, replace={
            "photon_tpu/checkpoint/faults.py": '''
FAULT_SITES = {"commit": "doc", "evaluation": "doc",
               "ghost_site": "never hit"}

def kill_point(site):
    pass
'''})
        f, = findings_of(run_rules(root, ["fault_site_registry"]),
                         "fault_site_registry")
        assert "ghost_site" in f.message
        assert f.path == "photon_tpu/checkpoint/faults.py"


# ------------------------------------------------------ 3. telemetry sync

class TestTelemetrySync:
    def test_unregistered_counter_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
from photon_tpu import telemetry

def f():
    telemetry.count("rogue.counter_nobody_registered")
'''})
        f, = findings_of(run_rules(root, ["telemetry_sync"]),
                         "telemetry_sync")
        assert "rogue.counter_nobody_registered" in f.message

    def test_dynamic_prefix_must_match_glob(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
from photon_tpu import telemetry

def f(site):
    telemetry.count(f"rogue.dyn.{site}")
'''})
        f, = findings_of(run_rules(root, ["telemetry_sync"]),
                         "telemetry_sync")
        assert "rogue.dyn." in f.message

    def test_orphan_registry_entry_fires(self, tmp_path):
        root = write_repo(tmp_path, replace={
            "photon_tpu/telemetry/__init__.py": '''
"""chunk_uploads latency_ orphan_counter solve"""
TELEMETRY_REGISTRY = {
    "counters": ("stream.chunk_uploads", "stream.orphan_counter"),
    "gauges": ("serving.latency_*",),
    "span_families": ("solve",),
}
'''})
        f, = findings_of(run_rules(root, ["telemetry_sync"]),
                         "telemetry_sync")
        assert "orphan_counter" in f.message and "nowhere" in f.message

    def test_registry_name_missing_from_docstring_fires(self, tmp_path):
        root = write_repo(tmp_path, replace={
            "photon_tpu/telemetry/__init__.py": '''
"""latency_ solve (chunk uploads described only in prose)"""
TELEMETRY_REGISTRY = {
    "counters": ("stream.chunk_uploads",),
    "gauges": ("serving.latency_*",),
    "span_families": ("solve",),
}
'''})
        f, = findings_of(run_rules(root, ["telemetry_sync"]),
                         "telemetry_sync")
        assert "docstring" in f.message and "chunk_uploads" in f.message

    def test_unknown_span_family_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
from photon_tpu import telemetry

def f():
    with telemetry.span("rogue_family.phase"):
        pass
'''})
        f, = findings_of(run_rules(root, ["telemetry_sync"]),
                         "telemetry_sync")
        assert "rogue_family" in f.message

    def test_selftest_mains_are_exempt(self, tmp_path):
        root = write_repo(tmp_path, extra={
            "photon_tpu/demo/__init__.py": "",
            "photon_tpu/demo/__main__.py": '''
from photon_tpu import telemetry

def run_selftest():
    telemetry.count("selftest.scratch_counter")
'''})
        assert not findings_of(run_rules(root, ["telemetry_sync"]),
                               "telemetry_sync")


# ----------------------------------------------------- 4. lock discipline

_LOCKED_CLASS = '''
import threading

class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0
        self.generation = 0

    def bump(self, v):
        with self._lock:
            self.total += v

    def unsafe_reset(self):{marker}
        self.total = 0
'''


class TestLockDiscipline:
    def test_mixed_locked_unlocked_write_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={
            "photon_tpu/rec.py": _LOCKED_CLASS.format(marker="")})
        f, = findings_of(run_rules(root, ["lock_discipline"]),
                         "lock_discipline")
        assert "Recorder.total" in f.message and "unsafe_reset" in f.message

    def test_init_writes_do_not_count(self, tmp_path):
        # generation is written only in __init__ + nowhere else: clean
        root = write_repo(tmp_path, extra={"photon_tpu/rec.py": '''
import threading

class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.total = 0

    def bump(self, v):
        with self._lock:
            self.total += v
'''})
        assert not findings_of(run_rules(root, ["lock_discipline"]),
                               "lock_discipline")

    def test_suppression_with_reason_honored(self, tmp_path):
        body = _LOCKED_CLASS.format(
            marker="\n        # lint: unlocked(reset runs pre-start, "
                   "single-threaded by construction)")
        root = write_repo(tmp_path, extra={"photon_tpu/rec.py": body})
        report = run_rules(root, ["lock_discipline"])
        assert not findings_of(report, "lock_discipline")
        assert report["suppressed"]


# --------------------------------------------------- 5. env-knob registry

class TestEnvKnobRegistry:
    def test_adhoc_environ_read_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
import os

def f():
    return os.environ.get("PHOTON_TPU_DEMO", "auto")
'''})
        f, = findings_of(run_rules(root, ["env_knob_registry"]),
                         "env_knob_registry")
        assert "ad-hoc" in f.message and "get_raw" in f.message

    def test_undeclared_knob_literal_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
from photon_tpu.utils import env as env_knobs

KNOB = "PHOTON_TPU_BRAND_NEW_KNOB"
'''})
        f, = findings_of(run_rules(root, ["env_knob_registry"]),
                         "env_knob_registry")
        assert "PHOTON_TPU_BRAND_NEW_KNOB" in f.message

    def test_orphan_declared_knob_fires(self, tmp_path):
        root = write_repo(tmp_path, replace={"photon_tpu/utils/env.py": '''
"""knobs"""
KNOB_DOCS = {"PHOTON_TPU_DEMO": "read by hot.py",
             "PHOTON_TPU_GHOST": "read by nobody"}

def get_raw(name, default=None):
    import os
    return os.environ.get(name, default)
'''})
        f, = findings_of(run_rules(root, ["env_knob_registry"]),
                         "env_knob_registry")
        assert "PHOTON_TPU_GHOST" in f.message

    def test_environ_subscript_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
import os

def f():
    os.environ["PHOTON_TPU_DEMO"] = "on"
'''})
        assert findings_of(run_rules(root, ["env_knob_registry"]),
                           "env_knob_registry")


# -------------------------------------------------- 6. contract coverage

class TestContractCoverage:
    def test_specless_listed_module_fires(self, tmp_path):
        root = write_repo(tmp_path, replace={"photon_tpu/hot.py": '''
from photon_tpu import telemetry
from photon_tpu.checkpoint.faults import kill_point, retry_io
from photon_tpu.utils import env as env_knobs

def touch():
    kill_point("commit")
    retry_io(lambda: 0, site="evaluation")
    telemetry.count("stream.chunk_uploads")
    telemetry.gauge(f"serving.latency_{0}")
    with telemetry.span("solve.demo"):
        pass
    return env_knobs.get_raw("PHOTON_TPU_DEMO")
'''})
        f, = findings_of(run_rules(root, ["contract_coverage"]),
                         "contract_coverage")
        assert "photon_tpu.hot" in f.message and "no ContractSpec" \
            in f.message

    def test_unlisted_registering_module_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/rogue.py": '''
from photon_tpu.analysis.contracts import register_contract

register_contract(None)
'''})
        f, = findings_of(run_rules(root, ["contract_coverage"]),
                         "contract_coverage")
        assert "photon_tpu.rogue" in f.message \
            and "HOT_PATH_MODULES" in f.message


# -------------------------------------------------- 7. sentinel coverage

class TestSentinelCoverage:
    def test_cost_leg_gated_higher_better_fires(self, tmp_path):
        root = write_repo(tmp_path, replace={"bench.py": '''
def main():
    doc = {"legs": {"demo_commit_latency_us": 3.0}}
    return doc

if __name__ == "__main__":
    main()
'''})
        f, = findings_of(run_rules(root, ["sentinel_coverage"]),
                         "sentinel_coverage")
        assert "demo_commit_latency_us" in f.message \
            and "lower-better" in f.message

    def test_config_leg_gated_fires(self, tmp_path):
        root = write_repo(tmp_path, replace={"bench.py": '''
def main():
    doc = {"legs": {"demo_mesh_n_chips_used": 8}}
    return doc

if __name__ == "__main__":
    main()
'''})
        # "_n_chips" excluded in the fixture sentinel only as exact
        # substring: "demo_mesh_n_chips_used" contains it -> excluded,
        # so use a count leg the exclude list misses
        root = write_repo(tmp_path, replace={"bench.py": '''
def main():
    doc = {"legs": {"demo_run_snapshots": 8}}
    return doc

if __name__ == "__main__":
    main()
'''})
        f, = findings_of(run_rules(root, ["sentinel_coverage"]),
                         "sentinel_coverage")
        assert "demo_run_snapshots" in f.message

    def test_spread_stats_dict_is_resolved(self, tmp_path):
        root = write_repo(tmp_path, replace={"bench.py": '''
def demo_problem():
    stats = {"demo_layout_pad_stall_pct": 0.5}
    return object(), stats

def main():
    batch, demo_stats = demo_problem()
    doc = {"legs": {"demo_rate_rows_per_sec": 1.0, **demo_stats}}
    return doc

if __name__ == "__main__":
    main()
'''})
        # "stall" IS lower-better in the fixture patterns: clean…
        assert not findings_of(run_rules(root, ["sentinel_coverage"]),
                               "sentinel_coverage")
        # …but a cost-shaped spread leg the patterns miss fires
        root = write_repo(tmp_path, replace={"bench.py": '''
def demo_problem():
    stats = {"demo_layout_pad_overhead_us": 0.5}
    return object(), stats

def main():
    batch, demo_stats = demo_problem()
    doc = {"legs": {"demo_rate_rows_per_sec": 1.0, **demo_stats}}
    return doc

if __name__ == "__main__":
    main()
'''})
        f, = findings_of(run_rules(root, ["sentinel_coverage"]),
                         "sentinel_coverage")
        assert "demo_layout_pad_overhead_us" in f.message


# ----------------------------------------------------- 8. spawn hygiene

class TestSpawnHygiene:
    def test_unguarded_spawn_script_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"benches/pool_script.py": '''
import multiprocessing
from concurrent.futures import ProcessPoolExecutor

def work():
    with ProcessPoolExecutor(
            mp_context=multiprocessing.get_context("spawn")) as pool:
        return pool

work()
'''})
        f, = findings_of(run_rules(root, ["spawn_hygiene"]),
                         "spawn_hygiene")
        assert "__main__" in f.message

    def test_guarded_spawn_script_clean(self, tmp_path):
        root = write_repo(tmp_path, extra={"benches/pool_script.py": '''
import multiprocessing
from concurrent.futures import ProcessPoolExecutor

def work():
    with ProcessPoolExecutor(
            mp_context=multiprocessing.get_context("spawn")) as pool:
        return pool

if __name__ == "__main__":
    work()
'''})
        assert not findings_of(run_rules(root, ["spawn_hygiene"]),
                               "spawn_hygiene")

    def test_daemon_thread_without_join_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bg.py": '''
import threading

class Loop:
    def start(self):
        self._t = threading.Thread(target=self._run, daemon=True)
        self._t.start()

    def _run(self):
        pass
'''})
        f, = findings_of(run_rules(root, ["spawn_hygiene"]),
                         "spawn_hygiene")
        assert "daemon thread" in f.message

    def test_nondaemon_thread_unjoined_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bg.py": '''
import threading

def fan_out(fn):
    ts = [threading.Thread(target=fn) for _ in range(4)]
    for t in ts:
        t.start()
'''})
        f, = findings_of(run_rules(root, ["spawn_hygiene"]),
                         "spawn_hygiene")
        assert "not joined" in f.message

    def test_joined_threads_clean(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bg.py": '''
import threading

def fan_out(fn):
    ts = [threading.Thread(target=fn) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
'''})
        assert not findings_of(run_rules(root, ["spawn_hygiene"]),
                               "spawn_hygiene")

    def test_executor_without_shutdown_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bg.py": '''
from concurrent.futures import ThreadPoolExecutor

class Fleet:
    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=2)
'''})
        f, = findings_of(run_rules(root, ["spawn_hygiene"]),
                         "spawn_hygiene")
        assert "shutdown" in f.message


# -------------------------------------------------- 9. exception hygiene

class TestExceptionHygiene:
    def test_broad_swallow_around_fault_site_fires(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
from photon_tpu.checkpoint.faults import kill_point

def f():
    try:
        kill_point("commit")
    except Exception:
        return None
'''})
        f, = findings_of(run_rules(root, ["exception_hygiene"]),
                         "exception_hygiene")
        assert "InjectedFault" in f.message

    def test_injectedfault_reraise_first_is_clean(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/ok.py": '''
from photon_tpu.checkpoint.faults import InjectedFault, kill_point

def f():
    try:
        kill_point("commit")
    except InjectedFault:
        raise
    except Exception:
        return None
'''})
        assert not findings_of(run_rules(root, ["exception_hygiene"]),
                               "exception_hygiene")

    def test_delivering_handler_is_clean(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/ok.py": '''
from photon_tpu.checkpoint.faults import kill_point

def f(fut):
    try:
        kill_point("commit")
    except BaseException as e:
        fut.set_exception(e)
'''})
        assert not findings_of(run_rules(root, ["exception_hygiene"]),
                               "exception_hygiene")

    def test_narrow_handler_is_clean(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/ok.py": '''
from photon_tpu.checkpoint.faults import retry_io

def f():
    try:
        return retry_io(lambda: 0, site="evaluation")
    except OSError:
        return None
'''})
        assert not findings_of(run_rules(root, ["exception_hygiene"]),
                               "exception_hygiene")

    def test_suppression_with_reason_honored(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/ok.py": '''
from photon_tpu.checkpoint.faults import kill_point

def f():
    try:
        kill_point("commit")
    # lint: swallow(the injected death IS the degrade path under test)
    except BaseException:
        return None
'''})
        report = run_rules(root, ["exception_hygiene"])
        assert not findings_of(report, "exception_hygiene")
        assert report["suppressed"]


# ----------------------------------------------------- engine mechanics

class TestEngine:
    def test_baseline_subtracts_by_fingerprint(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
def save(path):
    with open(path, "w") as fh:
        fh.write("x")
'''})
        f, = findings_of(run_lint(root=root, baseline=set()),
                         "durable_write")
        report = run_lint(root=root, baseline={f.fingerprint})
        assert not findings_of(report, "durable_write")

    def test_shipped_baseline_is_empty(self):
        assert load_baseline() == set()

    def test_only_filters_rules(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
import os

def f():
    with open("x", "w") as fh:
        fh.write(os.environ.get("PHOTON_TPU_DEMO", ""))
'''})
        report = run_rules(root, ["env_knob_registry"])
        assert findings_of(report, "env_knob_registry")
        assert not findings_of(report, "durable_write")

    def test_syntax_error_reported_not_crashed(self, tmp_path):
        root = write_repo(tmp_path, extra={
            "photon_tpu/broken.py": "def f(:\n"})
        report = run_rules(root)
        f, = findings_of(report, "parse")
        assert f.path == "photon_tpu/broken.py"

    def test_finding_roundtrip(self):
        f = Finding("durable_write", "a.py", 3, "msg", key="k")
        assert f.to_json()["key"] == "k"
        assert "a.py:3" in f.text


# ------------------------------------------------ the repo itself + CLI

@pytest.mark.filterwarnings("ignore")
class TestRepoIsClean:
    def test_repo_wide_run_exits_clean_at_head(self):
        """THE acceptance pin: the auditor finds nothing at HEAD with an
        empty baseline — drift from any registered convention turns
        tier-1 red in milliseconds."""
        report = run_lint(root=REPO, baseline=set())
        assert [f.text for f in report["findings"]] == []
        assert report["n_rules"] == len(RULES) + 1
        assert report["n_files"] > 100

    def test_every_suppression_in_repo_carries_a_reason(self):
        from photon_tpu.lint import load_context

        ctx = load_context(REPO)
        n = 0
        for rel, src in ctx.files.items():
            assert not src.bad_suppressions, (rel, src.bad_suppressions)
            n += len(src.suppressions)
        assert n >= 5  # the documented deliberate sites

    def test_json_cli_subprocess(self):
        """--json CLI e2e: one machine-readable object, exit 0 at HEAD."""
        proc = subprocess.run(
            [sys.executable, "-m", "photon_tpu.lint", "--json"],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert proc.returncode == 0, proc.stderr
        doc = json.loads(proc.stdout)
        assert doc["ok"] is True and doc["n_findings"] == 0
        assert doc["n_rules"] == len(RULES) + 1

    def test_cli_exit_1_on_findings(self, tmp_path):
        root = write_repo(tmp_path, extra={"photon_tpu/bad.py": '''
def save(path):
    with open(path, "w") as fh:
        fh.write("x")
'''})
        proc = subprocess.run(
            [sys.executable, "-m", "photon_tpu.lint", "--json",
             "--root", root],
            capture_output=True, text=True, cwd=REPO, timeout=120)
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert doc["n_findings"] == 1
        assert doc["findings"][0]["rule"] == "durable_write"

    def test_bench_guard_matches_registry_counts(self):
        """bench.py --check-lint is wired before the jax imports (the
        --check-contracts precedent) — prove by text, not subprocess
        (the full bench import would cost minutes)."""
        with open(os.path.join(REPO, "bench.py")) as fh:
            src = fh.read()
        guard = src.index('"--check-lint" in sys.argv')
        assert guard < src.index("import jax")

    def test_lint_is_a_selfcheck_suite(self):
        from photon_tpu.__main__ import SUITES

        names = [n for n, _ in SUITES]
        # round 18: + the whole-program concurrency auditor (threads)
        assert "lint" in names and "threads" in names and len(names) == 13
