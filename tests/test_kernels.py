"""Roofline-closure round (15): the Pallas kernel dispatch seam, the
donated upload ring, and the quantized serving rungs.

The load-bearing facts, each pinned bitwise where the design claims
bitwise:

- Pallas INTERPRET mode on this CPU backend reproduces the XLA
  blocked-ELL X passes bit for bit — across every nnz width bucket the
  pow2 ladder produces, empty buckets, non-dividing row counts, f32 and
  bf16 storage, single-vector and lane-minor forms, and the squared
  (Hessian-diagonal) rmatvec.
- The dispatch seam (PHOTON_TPU_KERNELS / OptimizerConfig.kernels) is
  pure routing: kernels-on solves equal kernels-off solves bitwise on
  the resident AND streamed-chunk paths, fallbacks (no tail, VMEM
  budget) never error, and mode flips never change call signatures.
- The DeviceChunkRing rotates across passes in order, pre-arms the next
  pass at exhaustion, and resets cleanly when a pass is abandoned — the
  crash/kill path of the donated double-buffer round.
- Quantized rungs: the warmup accuracy gate REFUSES a breach
  (`QuantizationRefused`, counted), the cold-miss row dequantizes to
  exact zeros (fixed-effect-only degradation is bit-identical to the
  f32 ladder), and mixed-size quantized dispatch never retraces.
"""
import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_tpu import kernels as K
from photon_tpu.data import matrix as M
from photon_tpu.data.dataset import (chunk_batch, chunk_blocked_ell,
                                     make_batch)
from photon_tpu.data.matrix import SparseRows, to_blocked_ell
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.regularization import l2

pytestmark = pytest.mark.release_programs


def _wide_bucket_problem(n=51, d=160, d_dense=8, seed=0, bf16=False):
    """A blocked-ELL layout exercising MANY width buckets: row i carries
    (i % 18) + 1 tail nnz on top of 2 hot columns, so the pow2 width
    ladder spans 1/2/4/8/16/32 and n=51 divides nothing."""
    rng = np.random.default_rng(seed)
    rows_ind, rows_val = [], []
    kmax = 21
    for i in range(n):
        tail = (i % 18) + 1
        cols = rng.permutation(np.arange(2, d - 1))[:tail]  # distinct
        ind = np.concatenate([[0, 1], cols, np.zeros(kmax - 2 - tail,
                                                     np.int64)])
        val = np.concatenate([rng.normal(size=2 + tail),
                              np.zeros(kmax - 2 - tail)])
        rows_ind.append(ind)
        rows_val.append(val)
    sp = SparseRows(np.asarray(rows_ind, np.int32),
                    np.asarray(rows_val, np.float32), d)
    X = to_blocked_ell(sp, d_dense)
    if bf16:
        bf = jnp.bfloat16
        X = dataclasses.replace(
            X, dense=jnp.asarray(X.dense).astype(bf),
            ell_vals=tuple(jnp.asarray(v).astype(bf) for v in X.ell_vals),
            bucket_vals=tuple(jnp.asarray(v).astype(bf)
                              for v in X.bucket_vals))
    return X


class TestKernelParity:
    @pytest.mark.parametrize("bf16", [False, True])
    def test_full_bucket_matrix_bitwise(self, bf16):
        """Every op, every width bucket, non-dividing rows: kernel == XLA
        bit for bit."""
        X = _wide_bucket_problem(bf16=bf16)
        assert len(X.ell_vals) >= 4  # widths 1/2/4/8/16…: real coverage
        n, d = X.shape
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        r = jnp.asarray(rng.normal(size=n).astype(np.float32))
        W = jnp.asarray(rng.normal(size=(d, 3)).astype(np.float32))
        R = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
        cases = ((M.matvec, w), (M.rmatvec, r), (M.matvec_lanes, W),
                 (M.rmatvec_lanes, R), (M.sq_rmatvec, r))
        with K.scope("off"):
            ref = [np.asarray(f(X, v)) for f, v in cases]
        with K.scope("on"):
            assert K.active()
            got = [np.asarray(f(X, v)) for f, v in cases]
        for (f, _), a, b in zip(cases, ref, got):
            np.testing.assert_array_equal(a, b, err_msg=f.__name__)

    def test_empty_bucket_fallback(self):
        """A layout with no tail routes to the XLA path (nothing to
        fuse) — same answer, no error."""
        sp = SparseRows(np.zeros((8, 2), np.int32),
                        np.ones((8, 2), np.float32), 16)
        X = to_blocked_ell(sp, 16)
        assert X.ell_vals == ()
        w = jnp.ones((16,), jnp.float32)
        with K.scope("on"):
            assert not M._use_kernel(X, w)
            out = np.asarray(M.matvec(X, w))
        with K.scope("off"):
            np.testing.assert_array_equal(out, np.asarray(M.matvec(X, w)))

    def test_vmem_budget_fallback(self):
        """Past the VMEM budget the seam steps aside per call — never an
        error, same bits."""
        X = _wide_bucket_problem()
        w = jnp.ones((X.shape[1],), jnp.float32)
        with K.scope("on"):
            ref = np.asarray(M.matvec(X, w))
            os.environ[K.ENV_VMEM] = "1"
            try:
                assert not M._use_kernel(X, w)
                np.testing.assert_array_equal(ref, np.asarray(M.matvec(X, w)))
            finally:
                del os.environ[K.ENV_VMEM]

    def test_jit_solve_parity_resident(self):
        """A resident blocked-ELL train_glm with kernels on equals the
        XLA solve bitwise (the seam dispatches inside jit)."""
        rng = np.random.default_rng(3)
        ind = rng.integers(0, 96, size=(128, 5)).astype(np.int32)
        val = rng.normal(size=(128, 5)).astype(np.float32)
        y = (rng.uniform(size=128) < 0.5).astype(np.float32)
        batch = jax.device_put(make_batch(SparseRows(ind, val, 96), y))
        batch = batch._replace(X=jax.device_put(
            to_blocked_ell(SparseRows(ind, val, 96), 16)))
        cfg = OptimizerConfig(max_iters=6, tolerance=0.0, reg=l2(),
                              reg_weight=1e-3, history=4)
        w_off = np.asarray(train_glm(
            batch, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, kernels="off"))[1].w)
        w_on = np.asarray(train_glm(
            batch, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, kernels="on"))[1].w)
        np.testing.assert_array_equal(w_off, w_on)

    def test_streamed_chunk_path_parity(self):
        """The streamed blocked-ELL chunk ladder with kernels on equals
        kernels off bit for bit (the chunk programs carry the seam)."""
        rng = np.random.default_rng(4)
        ind = rng.integers(0, 64, size=(96, 4)).astype(np.int32)
        val = rng.normal(size=(96, 4)).astype(np.float32)
        y = (rng.uniform(size=96) < 0.5).astype(np.float32)
        cb = chunk_blocked_ell(make_batch(SparseRows(ind, val, 64), y),
                               32, d_dense=16)
        cfg = OptimizerConfig(max_iters=5, tolerance=0.0, reg=l2(),
                              reg_weight=1e-3, history=4)
        w_off = np.asarray(train_glm(
            cb, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, kernels="off"))[1].w)
        w_on = np.asarray(train_glm(
            cb, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, kernels="on"))[1].w)
        np.testing.assert_array_equal(w_off, w_on)


class TestDispatchSeam:
    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv(K.ENV_KNOB, "on")
        assert K.mode() == "on" and K.active()
        monkeypatch.setenv(K.ENV_KNOB, "off")
        assert not K.active()
        monkeypatch.setenv(K.ENV_KNOB, "auto")
        assert K.active() == (jax.default_backend() == "tpu")
        monkeypatch.setenv(K.ENV_KNOB, "bogus")
        with pytest.raises(ValueError, match="PHOTON_TPU_KERNELS"):
            K.mode()

    def test_scope_nesting_and_restore(self):
        base = K.active()
        with K.scope("on"):
            assert K.active()
            with K.scope("off"):
                assert not K.active()
            assert K.active()
        assert K.active() == base

    def test_signature_invariance_across_modes(self):
        from photon_tpu.analysis.rules import TraceSignatureLog

        X = _wide_bucket_problem()
        w = jnp.zeros((X.shape[1],), jnp.float32)
        log = TraceSignatureLog()
        for m in ("off", "on", "off", "on"):
            with K.scope(m):
                log.record("seam", (X, w))
        assert len(log.signatures("seam")) == 1
        assert log.hazards() == []


class TestDeviceChunkRing:
    def test_rotation_order_and_prearm(self):
        rng = np.random.default_rng(5)
        Xd = rng.normal(size=(64, 8)).astype(np.float32)
        cb = chunk_batch(make_batch(
            Xd, (rng.uniform(size=64) < 0.5).astype(np.float32)), 16)
        ring = cb.device_ring(prefetch=2)
        for p in range(3):
            seen = [(i, np.asarray(b.y)) for i, b in ring.stream_pass()]
            assert [i for i, _ in seen] == [0, 1, 2, 3]
            for i, yb in seen:
                np.testing.assert_array_equal(yb, cb.y[i * 16:(i + 1) * 16])
            # pre-arm: the next pass's first uploads are already issued
            assert len(ring._window) == 2

    def test_abandoned_pass_resets(self):
        rng = np.random.default_rng(6)
        Xd = rng.normal(size=(48, 4)).astype(np.float32)
        cb = chunk_batch(make_batch(
            Xd, np.zeros(48, np.float32)), 16)
        ring = cb.device_ring(prefetch=2)
        it = ring.stream_pass()
        next(it)  # consume chunk 0, abandon mid-pass
        it.close()
        assert len(ring._window) == 0 and ring._next == 0
        order = [i for i, _ in ring.stream_pass()]
        assert order == [0, 1, 2]  # restarts at chunk 0, nothing stale

    def test_streamed_solve_unchanged_by_ring(self):
        """The ring + donated programs are pure overlap: streamed ==
        resident at the documented tolerance, twice in a row (ring state
        carries across solves of the same backend instance only)."""
        rng = np.random.default_rng(7)
        Xd = rng.normal(size=(256, 12)).astype(np.float32)
        y = (rng.uniform(size=256) < 0.5).astype(np.float32)
        cfg = OptimizerConfig(max_iters=8, tolerance=0.0, reg=l2(),
                              reg_weight=1e-3, history=4)
        res = train_glm(make_batch(Xd, y), TaskType.LOGISTIC_REGRESSION,
                        cfg)[1]
        cb = chunk_batch(make_batch(Xd, y), 64)
        s1 = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)[1]
        s2 = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)[1]
        np.testing.assert_array_equal(np.asarray(s1.w), np.asarray(s2.w))
        np.testing.assert_allclose(np.asarray(res.w), np.asarray(s1.w),
                                   atol=2e-4, rtol=2e-4)


class TestQuantizedRungs:
    def _ladder(self, quantize=None, eps=0.5, E=32, df=12, dr=6, k=3):
        from photon_tpu import serving
        from photon_tpu.game.model import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
        from photon_tpu.models.glm import (Coefficients,
                                           GeneralizedLinearModel)

        rng = np.random.default_rng(8)
        task = TaskType.LOGISTIC_REGRESSION
        keys = np.asarray(sorted(str(i) for i in range(E)))
        model = GameModel({
            "fixed": FixedEffectModel(GeneralizedLinearModel(
                Coefficients(jnp.asarray(
                    rng.normal(size=df).astype(np.float32))), task),
                "global"),
            "perMember": RandomEffectModel(
                entity_name="memberId", feature_shard="member", task=task,
                coefficients=jnp.asarray(
                    rng.normal(size=(E, dr)).astype(np.float32)),
                entity_keys=keys,
                key_to_index={kk: i for i, kk in enumerate(keys.tolist())}),
        }, task)
        store = serving.CoefficientStore.from_game_model(model)
        return serving.ProgramLadder(
            store, floor=8, max_batch=16, sparse_k={"member": k},
            quantize=quantize, quant_epsilon=eps), (df, dr, k, E)

    def test_epsilon_refusal_and_counter(self):
        from photon_tpu import telemetry
        from photon_tpu.serving.programs import QuantizationRefused

        ladder, _ = self._ladder(quantize="int8", eps=1e-9)
        run = telemetry.start_run("quant_refusal_test")
        try:
            with pytest.raises(QuantizationRefused, match="exceeds"):
                ladder.warmup()
            assert run.counters.get("serving.quant_refusals", 0) == 1
        finally:
            telemetry.finish_run()
        assert ladder.quant_report["max_abs_diff"] > 0.0

    def test_gate_passes_and_reports(self):
        ladder, _ = self._ladder(quantize="int8", eps=0.5)
        assert ladder.warmup() >= 1
        rep = ladder.quant_report
        assert rep["mode"] == "int8"
        assert 0.0 < rep["max_abs_diff"] <= 0.5

    def test_cold_miss_row_bitwise(self):
        """An unseen entity's quantized score equals the f32 ladder's bit
        for bit: the all-zero cold-miss row quantizes at scale 1.0 and
        dequantizes to exact zeros."""
        ladder, (df, dr, k, E) = self._ladder(quantize="int8")
        f32, _ = self._ladder(quantize=None)
        ladder.warmup()
        f32.warmup()
        rng = np.random.default_rng(9)
        off = np.zeros(8, np.float32)
        shards = {"global": np.zeros((8, df), np.float32),
                  "member": SparseRows(
                      rng.integers(0, dr, size=(8, k)).astype(np.int32),
                      rng.normal(size=(8, k)).astype(np.float32), dr)}
        ids = {"perMember": np.full(8, E, np.int32)}  # the cold row
        np.testing.assert_array_equal(
            np.asarray(f32.score_padded(off, shards, ids)),
            np.asarray(ladder.score_padded(off, shards, ids)))

    @pytest.mark.parametrize("mode", ["int8", "bf16"])
    def test_mixed_sizes_never_retrace(self, mode):
        ladder, (df, dr, k, _E) = self._ladder(quantize=mode)
        ladder.warmup()
        rng = np.random.default_rng(10)
        for B in (8, 16, 8, 16, 8):
            shards = {"global": rng.normal(size=(B, df)).astype(np.float32),
                      "member": SparseRows(
                          rng.integers(0, dr, size=(B, k)).astype(np.int32),
                          rng.normal(size=(B, k)).astype(np.float32), dr)}
            ids = {"perMember": np.zeros(B, np.int32)}
            ladder.score_padded(np.zeros(B, np.float32), shards, ids)
        assert ladder.assert_no_retrace() <= len(ladder.ladder)

    def test_hot_swap_requantizes(self):
        """A reload_coefficients swap invalidates the quantized-block
        cache: the next dispatch scores the NEW model (tracked via a
        margin that flips sign when every coefficient is negated)."""
        ladder, (df, dr, k, _E) = self._ladder(quantize="int8")
        ladder.warmup()
        rng = np.random.default_rng(11)
        shards = {"global": rng.normal(size=(8, df)).astype(np.float32),
                  "member": SparseRows(
                      np.zeros((8, k), np.int32),
                      np.zeros((8, k), np.float32), dr)}
        ids = {"perMember": np.zeros(8, np.int32)}
        before = np.asarray(ladder.score_padded(
            np.zeros(8, np.float32), shards, ids))
        import copy

        other = copy.copy(ladder.store)
        neg_fixed = {n: dataclasses.replace(
            b, weights=-np.asarray(b.weights)) for n, b in
            ladder.store.fixed.items()}
        neg_rand = {n: dataclasses.replace(
            b, coefficients=-np.asarray(b.coefficients)) for n, b in
            ladder.store.random.items()}
        other.fixed, other.random = neg_fixed, neg_rand
        other._device = None
        ladder.store.reload_coefficients(other)
        after = np.asarray(ladder.score_padded(
            np.zeros(8, np.float32), shards, ids))
        # logistic mean head: negated margins mirror around 0.5
        np.testing.assert_allclose(np.asarray(before) + np.asarray(after),
                                   1.0, atol=1e-6)


class TestStaticCostNarrowing:
    def test_quantized_dot_charges_storage_width(self):
        from photon_tpu.profiling.model import estimate_fn

        q = np.zeros((256,), np.int8)
        s = np.float32(0.5)
        x = np.zeros((64, 256), np.float32)

        def quant_dot(q, s, x):
            return x @ (q.astype(jnp.float32) * s)

        c = estimate_fn(quant_dot, (q, s, x))
        assert c.narrowed_bytes == 256 * 3  # int8 charged 1 B, not 4

        def f32_dot(w, x):
            return x @ w

        c2 = estimate_fn(f32_dot, (np.zeros(256, np.float32), x))
        assert c2.narrowed_bytes == 0
        # the row-wise serving-rung pattern narrows through the gather +
        # per-row scale multiply too
        def rung(qm, sc, ids, xr):
            rows = qm[ids].astype(jnp.float32) * sc[ids][:, None]
            return jnp.einsum("nd,nd->n", xr, rows)

        c3 = estimate_fn(rung, (np.zeros((100, 8), np.int8),
                                np.zeros(100, np.float32),
                                np.zeros(16, np.int32),
                                np.zeros((16, 8), np.float32)))
        assert c3.narrowed_bytes == 16 * 8 * 3
