"""Roofline-closure round (15): the Pallas kernel dispatch seam, the
donated upload ring, and the quantized serving rungs.

The load-bearing facts, each pinned bitwise where the design claims
bitwise:

- Pallas INTERPRET mode on this CPU backend reproduces the XLA
  blocked-ELL X passes bit for bit — across every nnz width bucket the
  pow2 ladder produces, empty buckets, non-dividing row counts, f32 and
  bf16 storage, single-vector and lane-minor forms, and the squared
  (Hessian-diagonal) rmatvec.
- The dispatch seam (PHOTON_TPU_KERNELS / OptimizerConfig.kernels) is
  pure routing: kernels-on solves equal kernels-off solves bitwise on
  the resident AND streamed-chunk paths, fallbacks (no tail, VMEM
  budget) never error, and mode flips never change call signatures.
- The DeviceChunkRing rotates across passes in order, pre-arms the next
  pass at exhaustion, and resets cleanly when a pass is abandoned — the
  crash/kill path of the donated double-buffer round.
- Quantized rungs: the warmup accuracy gate REFUSES a breach
  (`QuantizationRefused`, counted), the cold-miss row dequantizes to
  exact zeros (fixed-effect-only degradation is bit-identical to the
  f32 ladder), and mixed-size quantized dispatch never retraces.
"""
import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from photon_tpu import kernels as K
from photon_tpu.data import matrix as M
from photon_tpu.data.dataset import (chunk_batch, chunk_blocked_ell,
                                     make_batch)
from photon_tpu.data.matrix import SparseRows, to_blocked_ell
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.regularization import l2

pytestmark = pytest.mark.release_programs


def _wide_bucket_problem(n=51, d=160, d_dense=8, seed=0, bf16=False):
    """A blocked-ELL layout exercising MANY width buckets: row i carries
    (i % 18) + 1 tail nnz on top of 2 hot columns, so the pow2 width
    ladder spans 1/2/4/8/16/32 and n=51 divides nothing."""
    rng = np.random.default_rng(seed)
    rows_ind, rows_val = [], []
    kmax = 21
    for i in range(n):
        tail = (i % 18) + 1
        cols = rng.permutation(np.arange(2, d - 1))[:tail]  # distinct
        ind = np.concatenate([[0, 1], cols, np.zeros(kmax - 2 - tail,
                                                     np.int64)])
        val = np.concatenate([rng.normal(size=2 + tail),
                              np.zeros(kmax - 2 - tail)])
        rows_ind.append(ind)
        rows_val.append(val)
    sp = SparseRows(np.asarray(rows_ind, np.int32),
                    np.asarray(rows_val, np.float32), d)
    X = to_blocked_ell(sp, d_dense)
    if bf16:
        bf = jnp.bfloat16
        X = dataclasses.replace(
            X, dense=jnp.asarray(X.dense).astype(bf),
            ell_vals=tuple(jnp.asarray(v).astype(bf) for v in X.ell_vals),
            bucket_vals=tuple(jnp.asarray(v).astype(bf)
                              for v in X.bucket_vals))
    return X


def _fused_nbytes(X, v):
    """The fused form's whole operand set in bytes — one byte past this
    the route ladder's middle (grid-tiled) rung takes over."""
    from photon_tpu.kernels import blocked_ell as BE

    total = BE._nbytes(v) + BE._nbytes(X.row_pos)
    for t in (X.ell_pcols, X.ell_vals, X.bucket_rows, X.bucket_vals):
        total += sum(BE._nbytes(b) for b in t)
    return total


class TestKernelParity:
    @pytest.mark.parametrize("bf16", [False, True])
    def test_full_bucket_matrix_bitwise(self, bf16):
        """Every op, every width bucket, non-dividing rows: kernel == XLA
        bit for bit."""
        X = _wide_bucket_problem(bf16=bf16)
        assert len(X.ell_vals) >= 4  # widths 1/2/4/8/16…: real coverage
        n, d = X.shape
        rng = np.random.default_rng(1)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        r = jnp.asarray(rng.normal(size=n).astype(np.float32))
        W = jnp.asarray(rng.normal(size=(d, 3)).astype(np.float32))
        R = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
        cases = ((M.matvec, w), (M.rmatvec, r), (M.matvec_lanes, W),
                 (M.rmatvec_lanes, R), (M.sq_rmatvec, r))
        with K.scope("off"):
            ref = [np.asarray(f(X, v)) for f, v in cases]
        with K.scope("on"):
            assert K.active()
            got = [np.asarray(f(X, v)) for f, v in cases]
        for (f, _), a, b in zip(cases, ref, got):
            np.testing.assert_array_equal(a, b, err_msg=f.__name__)

    def test_empty_bucket_fallback(self):
        """A layout with no tail routes to the XLA path (nothing to
        fuse) — same answer, no error."""
        sp = SparseRows(np.zeros((8, 2), np.int32),
                        np.ones((8, 2), np.float32), 16)
        X = to_blocked_ell(sp, 16)
        assert X.ell_vals == ()
        w = jnp.ones((16,), jnp.float32)
        with K.scope("on"):
            assert M._kernel_route(X, w) is None
            out = np.asarray(M.matvec(X, w))
        with K.scope("off"):
            np.testing.assert_array_equal(out, np.asarray(M.matvec(X, w)))

    def test_vmem_budget_fallback(self):
        """The route ladder walks down under pressure: past the fused
        budget the grid-tiled rung serves (same bits), and at one byte —
        below even one tile — the seam steps aside to XLA entirely.
        Never an error, never different bits."""
        X = _wide_bucket_problem()
        w = jnp.ones((X.shape[1],), jnp.float32)
        total = _fused_nbytes(X, w)
        with K.scope("on"):
            assert M._kernel_route(X, w) == "fused"
            ref = np.asarray(M.matvec(X, w))
        os.environ[K.ENV_VMEM] = str(total - 1)
        try:
            with K.scope("on"):
                assert M._kernel_route(X, w) == "tiled"
                np.testing.assert_array_equal(ref, np.asarray(M.matvec(X, w)))
        finally:
            del os.environ[K.ENV_VMEM]
        os.environ[K.ENV_VMEM] = "1"
        try:
            with K.scope("on"):
                assert M._kernel_route(X, w) is None
                np.testing.assert_array_equal(ref, np.asarray(M.matvec(X, w)))
        finally:
            del os.environ[K.ENV_VMEM]

    def test_jit_solve_parity_resident(self):
        """A resident blocked-ELL train_glm with kernels on equals the
        XLA solve bitwise (the seam dispatches inside jit)."""
        rng = np.random.default_rng(3)
        ind = rng.integers(0, 96, size=(128, 5)).astype(np.int32)
        val = rng.normal(size=(128, 5)).astype(np.float32)
        y = (rng.uniform(size=128) < 0.5).astype(np.float32)
        batch = jax.device_put(make_batch(SparseRows(ind, val, 96), y))
        batch = batch._replace(X=jax.device_put(
            to_blocked_ell(SparseRows(ind, val, 96), 16)))
        cfg = OptimizerConfig(max_iters=6, tolerance=0.0, reg=l2(),
                              reg_weight=1e-3, history=4)
        w_off = np.asarray(train_glm(
            batch, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, kernels="off"))[1].w)
        w_on = np.asarray(train_glm(
            batch, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, kernels="on"))[1].w)
        np.testing.assert_array_equal(w_off, w_on)

    def test_streamed_chunk_path_parity(self):
        """The streamed blocked-ELL chunk ladder with kernels on equals
        kernels off bit for bit (the chunk programs carry the seam)."""
        rng = np.random.default_rng(4)
        ind = rng.integers(0, 64, size=(96, 4)).astype(np.int32)
        val = rng.normal(size=(96, 4)).astype(np.float32)
        y = (rng.uniform(size=96) < 0.5).astype(np.float32)
        cb = chunk_blocked_ell(make_batch(SparseRows(ind, val, 64), y),
                               32, d_dense=16)
        cfg = OptimizerConfig(max_iters=5, tolerance=0.0, reg=l2(),
                              reg_weight=1e-3, history=4)
        w_off = np.asarray(train_glm(
            cb, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, kernels="off"))[1].w)
        w_on = np.asarray(train_glm(
            cb, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, kernels="on"))[1].w)
        np.testing.assert_array_equal(w_off, w_on)


class TestDispatchSeam:
    def test_env_knob(self, monkeypatch):
        monkeypatch.setenv(K.ENV_KNOB, "on")
        assert K.mode() == "on" and K.active()
        monkeypatch.setenv(K.ENV_KNOB, "off")
        assert not K.active()
        monkeypatch.setenv(K.ENV_KNOB, "auto")
        assert K.active() == (jax.default_backend() == "tpu")
        monkeypatch.setenv(K.ENV_KNOB, "bogus")
        with pytest.raises(ValueError, match="PHOTON_TPU_KERNELS"):
            K.mode()

    def test_scope_nesting_and_restore(self):
        base = K.active()
        with K.scope("on"):
            assert K.active()
            with K.scope("off"):
                assert not K.active()
            assert K.active()
        assert K.active() == base

    def test_signature_invariance_across_modes(self):
        from photon_tpu.analysis.rules import TraceSignatureLog

        X = _wide_bucket_problem()
        w = jnp.zeros((X.shape[1],), jnp.float32)
        log = TraceSignatureLog()
        for m in ("off", "on", "off", "on"):
            with K.scope(m):
                log.record("seam", (X, w))
        assert len(log.signatures("seam")) == 1
        assert log.hazards() == []


class TestDeviceChunkRing:
    def test_rotation_order_and_prearm(self):
        rng = np.random.default_rng(5)
        Xd = rng.normal(size=(64, 8)).astype(np.float32)
        cb = chunk_batch(make_batch(
            Xd, (rng.uniform(size=64) < 0.5).astype(np.float32)), 16)
        ring = cb.device_ring(prefetch=2)
        for p in range(3):
            seen = [(i, np.asarray(b.y)) for i, b in ring.stream_pass()]
            assert [i for i, _ in seen] == [0, 1, 2, 3]
            for i, yb in seen:
                np.testing.assert_array_equal(yb, cb.y[i * 16:(i + 1) * 16])
            # pre-arm: the next pass's first uploads are already issued
            assert len(ring._window) == 2

    def test_abandoned_pass_resets(self):
        rng = np.random.default_rng(6)
        Xd = rng.normal(size=(48, 4)).astype(np.float32)
        cb = chunk_batch(make_batch(
            Xd, np.zeros(48, np.float32)), 16)
        ring = cb.device_ring(prefetch=2)
        it = ring.stream_pass()
        next(it)  # consume chunk 0, abandon mid-pass
        it.close()
        assert len(ring._window) == 0 and ring._next == 0
        order = [i for i, _ in ring.stream_pass()]
        assert order == [0, 1, 2]  # restarts at chunk 0, nothing stale

    def test_streamed_solve_unchanged_by_ring(self):
        """The ring + donated programs are pure overlap: streamed ==
        resident at the documented tolerance, twice in a row (ring state
        carries across solves of the same backend instance only)."""
        rng = np.random.default_rng(7)
        Xd = rng.normal(size=(256, 12)).astype(np.float32)
        y = (rng.uniform(size=256) < 0.5).astype(np.float32)
        cfg = OptimizerConfig(max_iters=8, tolerance=0.0, reg=l2(),
                              reg_weight=1e-3, history=4)
        res = train_glm(make_batch(Xd, y), TaskType.LOGISTIC_REGRESSION,
                        cfg)[1]
        cb = chunk_batch(make_batch(Xd, y), 64)
        s1 = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)[1]
        s2 = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)[1]
        np.testing.assert_array_equal(np.asarray(s1.w), np.asarray(s2.w))
        np.testing.assert_allclose(np.asarray(res.w), np.asarray(s1.w),
                                   atol=2e-4, rtol=2e-4)


class TestQuantizedRungs:
    def _ladder(self, quantize=None, eps=0.5, E=32, df=12, dr=6, k=3):
        from photon_tpu import serving
        from photon_tpu.game.model import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
        from photon_tpu.models.glm import (Coefficients,
                                           GeneralizedLinearModel)

        rng = np.random.default_rng(8)
        task = TaskType.LOGISTIC_REGRESSION
        keys = np.asarray(sorted(str(i) for i in range(E)))
        model = GameModel({
            "fixed": FixedEffectModel(GeneralizedLinearModel(
                Coefficients(jnp.asarray(
                    rng.normal(size=df).astype(np.float32))), task),
                "global"),
            "perMember": RandomEffectModel(
                entity_name="memberId", feature_shard="member", task=task,
                coefficients=jnp.asarray(
                    rng.normal(size=(E, dr)).astype(np.float32)),
                entity_keys=keys,
                key_to_index={kk: i for i, kk in enumerate(keys.tolist())}),
        }, task)
        store = serving.CoefficientStore.from_game_model(model)
        return serving.ProgramLadder(
            store, floor=8, max_batch=16, sparse_k={"member": k},
            quantize=quantize, quant_epsilon=eps), (df, dr, k, E)

    def test_epsilon_refusal_and_counter(self):
        from photon_tpu import telemetry
        from photon_tpu.serving.programs import QuantizationRefused

        ladder, _ = self._ladder(quantize="int8", eps=1e-9)
        run = telemetry.start_run("quant_refusal_test")
        try:
            with pytest.raises(QuantizationRefused, match="exceeds"):
                ladder.warmup()
            assert run.counters.get("serving.quant_refusals", 0) == 1
        finally:
            telemetry.finish_run()
        assert ladder.quant_report["max_abs_diff"] > 0.0

    def test_gate_passes_and_reports(self):
        ladder, _ = self._ladder(quantize="int8", eps=0.5)
        assert ladder.warmup() >= 1
        rep = ladder.quant_report
        assert rep["mode"] == "int8"
        assert 0.0 < rep["max_abs_diff"] <= 0.5

    def test_cold_miss_row_bitwise(self):
        """An unseen entity's quantized score equals the f32 ladder's bit
        for bit: the all-zero cold-miss row quantizes at scale 1.0 and
        dequantizes to exact zeros."""
        ladder, (df, dr, k, E) = self._ladder(quantize="int8")
        f32, _ = self._ladder(quantize=None)
        ladder.warmup()
        f32.warmup()
        rng = np.random.default_rng(9)
        off = np.zeros(8, np.float32)
        shards = {"global": np.zeros((8, df), np.float32),
                  "member": SparseRows(
                      rng.integers(0, dr, size=(8, k)).astype(np.int32),
                      rng.normal(size=(8, k)).astype(np.float32), dr)}
        ids = {"perMember": np.full(8, E, np.int32)}  # the cold row
        np.testing.assert_array_equal(
            np.asarray(f32.score_padded(off, shards, ids)),
            np.asarray(ladder.score_padded(off, shards, ids)))

    @pytest.mark.parametrize("mode", ["int8", "bf16"])
    def test_mixed_sizes_never_retrace(self, mode):
        ladder, (df, dr, k, _E) = self._ladder(quantize=mode)
        ladder.warmup()
        rng = np.random.default_rng(10)
        for B in (8, 16, 8, 16, 8):
            shards = {"global": rng.normal(size=(B, df)).astype(np.float32),
                      "member": SparseRows(
                          rng.integers(0, dr, size=(B, k)).astype(np.int32),
                          rng.normal(size=(B, k)).astype(np.float32), dr)}
            ids = {"perMember": np.zeros(B, np.int32)}
            ladder.score_padded(np.zeros(B, np.float32), shards, ids)
        assert ladder.assert_no_retrace() <= len(ladder.ladder)

    def test_hot_swap_requantizes(self):
        """A reload_coefficients swap invalidates the quantized-block
        cache: the next dispatch scores the NEW model (tracked via a
        margin that flips sign when every coefficient is negated)."""
        ladder, (df, dr, k, _E) = self._ladder(quantize="int8")
        ladder.warmup()
        rng = np.random.default_rng(11)
        shards = {"global": rng.normal(size=(8, df)).astype(np.float32),
                  "member": SparseRows(
                      np.zeros((8, k), np.int32),
                      np.zeros((8, k), np.float32), dr)}
        ids = {"perMember": np.zeros(8, np.int32)}
        before = np.asarray(ladder.score_padded(
            np.zeros(8, np.float32), shards, ids))
        import copy

        other = copy.copy(ladder.store)
        neg_fixed = {n: dataclasses.replace(
            b, weights=-np.asarray(b.weights)) for n, b in
            ladder.store.fixed.items()}
        neg_rand = {n: dataclasses.replace(
            b, coefficients=-np.asarray(b.coefficients)) for n, b in
            ladder.store.random.items()}
        other.fixed, other.random = neg_fixed, neg_rand
        other._device = None
        ladder.store.reload_coefficients(other)
        after = np.asarray(ladder.score_padded(
            np.zeros(8, np.float32), shards, ids))
        # logistic mean head: negated margins mirror around 0.5
        np.testing.assert_allclose(np.asarray(before) + np.asarray(after),
                                   1.0, atol=1e-6)


class TestStaticCostNarrowing:
    def test_quantized_dot_charges_storage_width(self):
        from photon_tpu.profiling.model import estimate_fn

        q = np.zeros((256,), np.int8)
        s = np.float32(0.5)
        x = np.zeros((64, 256), np.float32)

        def quant_dot(q, s, x):
            return x @ (q.astype(jnp.float32) * s)

        c = estimate_fn(quant_dot, (q, s, x))
        assert c.narrowed_bytes == 256 * 3  # int8 charged 1 B, not 4

        def f32_dot(w, x):
            return x @ w

        c2 = estimate_fn(f32_dot, (np.zeros(256, np.float32), x))
        assert c2.narrowed_bytes == 0
        # the row-wise serving-rung pattern narrows through the gather +
        # per-row scale multiply too
        def rung(qm, sc, ids, xr):
            rows = qm[ids].astype(jnp.float32) * sc[ids][:, None]
            return jnp.einsum("nd,nd->n", xr, rows)

        c3 = estimate_fn(rung, (np.zeros((100, 8), np.int8),
                                np.zeros(100, np.float32),
                                np.zeros(16, np.int32),
                                np.zeros((16, 8), np.float32)))
        assert c3.narrowed_bytes == 16 * 8 * 3


class TestTiledForms:
    """Round 20: the grid-tiled middle rung of the route ladder — bitwise
    vs the XLA path across tile choices, including a tail bucket SMALLER
    than one tile (which must run at its exact shape: padding a tiny
    einsum changes XLA CPU's per-row reduction strategy and the bits)."""

    def _refs(self, X, w, r, W, R):
        cases = ((M.matvec, w), (M.rmatvec, r), (M.matvec_lanes, W),
                 (M.rmatvec_lanes, R), (M.sq_rmatvec, r))
        with K.scope("off"):
            return cases, [np.asarray(f(X, v)) for f, v in cases]

    @pytest.mark.parametrize("bf16", [False, True])
    @pytest.mark.parametrize("tile", [None, "8"])
    def test_tiled_route_full_surface_bitwise(self, monkeypatch, bf16,
                                              tile):
        """Every op through the seam with the route pinned to "tiled"
        (one byte past the fused budget): kernel == XLA bit for bit, at
        the default tile AND at the minimum tile where sub-tile buckets
        take the exact-shape path."""
        X = _wide_bucket_problem(bf16=bf16)
        # the sub-tile regime is real: some bucket has fewer rows than
        # even the minimum 8-row tile (it must run at its exact shape)
        assert min(int(b.shape[0])
                   for t in (X.ell_vals, X.bucket_rows) for b in t) < 8
        n, d = X.shape
        rng = np.random.default_rng(20)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        r = jnp.asarray(rng.normal(size=n).astype(np.float32))
        W = jnp.asarray(rng.normal(size=(d, 3)).astype(np.float32))
        R = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
        cases, ref = self._refs(X, w, r, W, R)
        if tile is not None:
            monkeypatch.setenv(K.ENV_TILE, tile)
        monkeypatch.setenv(K.ENV_VMEM, str(_fused_nbytes(X, w) - 1))
        with K.scope("on"):
            assert M._kernel_route(X, w) == "tiled"
            got = [np.asarray(f(X, v)) for f, v in cases]
        for (f, _), a, b in zip(cases, ref, got):
            np.testing.assert_array_equal(a, b, err_msg=f.__name__)

    def test_tiled_direct_forms_bitwise(self):
        """The tiled forms called directly equal the fused forms bit for
        bit — same inputs, same outputs, only the VMEM schedule moves."""
        X = _wide_bucket_problem()
        n, d = X.shape
        rng = np.random.default_rng(21)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        r = jnp.asarray(rng.normal(size=n).astype(np.float32))
        with K.scope("on"):
            np.testing.assert_array_equal(
                np.asarray(K.tail_matvec(X, w)),
                np.asarray(K.tail_matvec_tiled(X, w)))
            np.testing.assert_array_equal(
                np.asarray(K.bucket_rmatvec(X, r)),
                np.asarray(K.bucket_rmatvec_tiled(X, r)))
            np.testing.assert_array_equal(
                np.asarray(K.bucket_rmatvec(X, r, square=True)),
                np.asarray(K.bucket_rmatvec_tiled(X, r, square=True)))

    def test_vmem_knob_validation(self, monkeypatch):
        """Satellite 1: a malformed PHOTON_TPU_KERNELS_VMEM raises a
        ValueError NAMING the knob — not a bare int() parse error from
        deep inside a jitted X pass."""
        monkeypatch.setenv(K.ENV_VMEM, "lots")
        with pytest.raises(ValueError, match="PHOTON_TPU_KERNELS_VMEM"):
            K.vmem_budget()
        monkeypatch.setenv(K.ENV_VMEM, "-4096")
        with pytest.raises(ValueError, match="PHOTON_TPU_KERNELS_VMEM"):
            K.vmem_budget()
        monkeypatch.setenv(K.ENV_VMEM, "4096")
        assert K.vmem_budget() == 4096
        monkeypatch.delenv(K.ENV_VMEM)
        assert K.vmem_budget() is None  # interpret mode: unbounded

    def test_tile_knob_validation(self, monkeypatch):
        for bad in ("wide", "12", "4", "-8", "0"):
            monkeypatch.setenv(K.ENV_TILE, bad)
            with pytest.raises(ValueError,
                               match="PHOTON_TPU_KERNELS_TILE"):
                K.tile_override()
        monkeypatch.setenv(K.ENV_TILE, "64")
        assert K.tile_override() == 64
        monkeypatch.delenv(K.ENV_TILE)
        assert K.tile_override() is None


class TestTileTuner:
    """Round 20: the ledger-driven tile autotuner — measures once per
    (backend, kind, width), persists beside the AOT store, and a warm
    run reuses the cached winner WITHOUT re-measuring."""

    def _problem(self):
        X = M._contract_blocked_ell(n=24, d=48, k=3, d_dense=8)
        n, d = X.shape
        rng = np.random.default_rng(22)
        return (X, jnp.asarray(rng.normal(size=d).astype(np.float32)),
                jnp.asarray(rng.normal(size=n).astype(np.float32)))

    def test_cold_measures_warm_reuses(self, tmp_path):
        from photon_tpu import telemetry
        from photon_tpu.tuning import tile_tuner as TT

        X, w, r = self._problem()
        TT.reset_memo()
        try:
            run = telemetry.start_run("tile_tuner_cold")
            try:
                cold = TT.autotune_tiles(X, w, r, cache_dir=str(tmp_path),
                                         candidates=(64, 128), repeats=1)
                assert cold  # layout exercises at least one key
                assert run.counters.get("kernels.tile_measures", 0) \
                    == 2 * len(cold)
                assert run.counters.get("kernels.tile_cache_hits", 0) == 0
            finally:
                telemetry.finish_run()
            assert os.path.exists(TT.tile_cache_path(str(tmp_path)))
            TT.reset_memo()  # simulate a fresh process, same cache_dir
            run = telemetry.start_run("tile_tuner_warm")
            try:
                warm = TT.autotune_tiles(X, w, r, cache_dir=str(tmp_path),
                                         candidates=(64, 128), repeats=1)
                assert warm == cold  # the cached choice, verbatim
                assert run.counters.get("kernels.tile_measures", 0) == 0
                assert run.counters.get("kernels.tile_cache_hits", 0) \
                    == len(cold)
            finally:
                telemetry.finish_run()
            # the warm winners drive dispatch: tile_for resolves them
            kind, width = next(iter(warm)).split(":")
            assert TT.tile_for(kind, int(width)) == warm[f"{kind}:{width}"]
        finally:
            TT.reset_memo()

    def test_untuned_process_runs_default(self):
        from photon_tpu.tuning import tile_tuner as TT

        TT.reset_memo()
        assert TT.tile_for("tail_matvec", 16) == TT.DEFAULT_TILE

    def test_corrupt_cache_is_cold_cache(self, tmp_path):
        from photon_tpu.tuning import tile_tuner as TT

        path = TT.tile_cache_path(str(tmp_path))
        with open(path, "w") as f:
            f.write("{not json")
        X, w, r = self._problem()
        TT.reset_memo()
        try:
            out = TT.autotune_tiles(X, w, r, cache_dir=str(tmp_path),
                                    candidates=(64,), repeats=1)
            assert out  # re-measured, no crash
            import json

            with open(path) as f:
                doc = json.load(f)  # rewritten well-formed
            assert doc["format"] == TT._FORMAT
        finally:
            TT.reset_memo()
