"""Two-process multi-controller worker (tests/test_streaming.py::
TestRealTwoProcess): each OS process owns 4 virtual CPU devices of a
shared 8-device mesh, streams the same Avro files through
stream_to_device, and trains the same psum GLM program — the REAL
process-boundary run behind the `_local_mask` shard-math tests.

Not collected by pytest (underscore name); invoked as
    python tests/_multihost_worker.py <pid> <port> <data_root> <out.npy>
Prints INIT_FAILED when jax.distributed cannot form the cluster (the
parent test skips: some sandboxes block even localhost gRPC).
"""
import os
import sys

pid, port, root, out = (int(sys.argv[1]), int(sys.argv[2]), sys.argv[3],
                        sys.argv[4])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax  # noqa: E402

# the axon plugin ignores JAX_PLATFORMS env filtering; pin before init
jax.config.update("jax_platforms", "cpu")
try:
    jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2,
                               process_id=pid,
                               initialization_timeout=60)
except Exception as e:  # noqa: BLE001 — any init failure → documented skip
    print(f"INIT_FAILED: {type(e).__name__}: {e}", flush=True)
    sys.exit(42)

assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

import numpy as np  # noqa: E402

from photon_tpu.data.dataset import make_batch  # noqa: E402
from photon_tpu.data.feature_bags import FeatureShardConfig  # noqa: E402
from photon_tpu.data.ingest import GameDataConfig  # noqa: E402
from photon_tpu.data.streaming import (build_index_maps_streaming,  # noqa: E402
                                       stream_to_device)
from photon_tpu.models.training import train_glm  # noqa: E402
from photon_tpu.ops.losses import TaskType  # noqa: E402
from photon_tpu.optim import regularization as reg  # noqa: E402
from photon_tpu.optim.config import OptimizerConfig  # noqa: E402
from photon_tpu.parallel.mesh import make_mesh  # noqa: E402

config = GameDataConfig(
    shards={"dense": FeatureShardConfig(bags=("f",), has_intercept=True)},
    entity_fields=("member",),
)
maps = build_index_maps_streaming(root, config)
mesh = make_mesh(devices=np.asarray(jax.devices()))
data, n_real = stream_to_device(root, config, maps, mesh=mesh,
                                chunk_rows=300)
batch = make_batch(data.shards["dense"], data.y, weights=data.weights,
                   offsets=data.offsets)
try:
    model, res = train_glm(
        batch, TaskType.LOGISTIC_REGRESSION,
        OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=1.0),
        mesh=mesh)
except Exception as e:  # noqa: BLE001
    if "aren't implemented on the CPU backend" in str(e):
        # This jax build cannot EXECUTE multi-process computations on the
        # CPU backend at all (cluster formation succeeded; the runtime
        # refuses the launch) — the same "this sandbox can't run the
        # 2-process program" condition as a failed handshake.
        print(f"INIT_FAILED: {type(e).__name__}: {e}", flush=True)
        sys.exit(42)
    raise
w = np.asarray(model.coefficients.means)
np.save(out, w)
print(f"OK process {pid}: n_real={n_real} iters={int(res.iterations)} "
      f"|w|={float(np.linalg.norm(w)):.6f}", flush=True)
