"""Continual-flywheel tier-1 coverage: delta ingestion, prior
warm-started partial re-solves, and the parity-probed atomic hot-swap.

The acceptance matrix:

- manifest: weight-carrying row counts persist beside the model
  (`save_game_model(manifest=...)` / `load_training_manifest`) and a
  refresh built from the SAVED model directory alone — coefficients,
  variances, manifest — reproduces the in-memory refresh bit-for-bit.
- delta: delta drops touch every present entity, full drops touch only
  changed ones, unseen entities defer, newer manifest versions refuse.
- priors (`PriorDistribution.from_variances` end-to-end): precision is
  1/variance with non-positive variances meaning NO prior; the
  prior-weighted objective matches the hand-built 0.5·(w−μ)ᵀΛ(w−μ)
  term bitwise; a variance→prior→warm-started solve converges in
  measurably fewer iterations than a cold start; and the lane-grid's
  prior rejection (`ops.lane_objective.supports_lanes`) routes to the
  single-lane vmapped path with an actionable INFO message.
- refresh: untouched entities BIT-identical, touched entities re-solve
  with refreshed variances, repeated refreshes with different touched
  sets add ZERO compacted-solve program signatures.
- swap: versioned publish + CURRENT pointer, kill injected mid-swap
  leaves the OLD model serving bit-identically, a blown-up model is
  refused by the parity probe (counted), a clean swap reloads the live
  store (counted on serving.hot_swaps).
"""
import dataclasses
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from photon_tpu import continual, telemetry
from photon_tpu.continual.swap import current_version, open_current
from photon_tpu.data.model_io import (load_game_model,
                                      load_training_manifest,
                                      save_game_model)
from photon_tpu.game.dataset import GameData
from photon_tpu.game.estimator import (FixedEffectConfig, GameEstimator,
                                       RandomEffectConfig)
from photon_tpu.models.variance import VarianceComputationType
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.prior import PriorDistribution
from photon_tpu.optim.regularization import l2
from photon_tpu.serving.store import CoefficientStore

pytestmark = pytest.mark.release_programs

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG_F = OptimizerConfig(max_iters=8, tolerance=1e-6, reg=l2(),
                        reg_weight=0.5, history=4)
CFG_R = OptimizerConfig(max_iters=25, tolerance=1e-7, reg=l2(),
                        reg_weight=0.5, history=4)

N, E, DF, DR = 600, 24, 6, 4
TOUCHED = np.asarray([3, 7, 11, 19])


def _labels(rng, Xf, Xr, ent, w_true, u_true):
    m = Xf @ w_true + np.einsum("nd,nd->n", Xr, u_true[ent])
    return (rng.uniform(size=m.shape[0])
            < 1 / (1 + np.exp(-m))).astype(np.float32)


@pytest.fixture(scope="module")
def world():
    """One trained GAME model (with SIMPLE variances) + its manifest +
    a delta drop touching TOUCHED entities (plus one brand-new entity),
    shared by the refresh/swap tests to amortize solver compiles."""
    rng = np.random.default_rng(0)
    ent = rng.integers(0, E, size=N)
    Xf = rng.normal(size=(N, DF)).astype(np.float32)
    Xr = rng.normal(size=(N, DR)).astype(np.float32)
    w_true = rng.normal(size=DF).astype(np.float32) * 0.5
    u_true = rng.normal(size=(E, DR)).astype(np.float32) * 0.5
    y = _labels(rng, Xf, Xr, ent, w_true, u_true)
    data = GameData.build(y, {"fx": Xf, "rs": Xr}, {"e": ent})
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configs={"fixed": FixedEffectConfig("fx", CFG_F),
                            "re": RandomEffectConfig("e", "rs", CFG_R)},
        n_sweeps=2, variance=VarianceComputationType.SIMPLE)
    prev = est.fit(data)[0].model
    manifest = continual.build_manifest(data)

    n2 = 144
    ent2 = np.concatenate([
        rng.permutation(np.repeat(TOUCHED, (n2 - 16) // TOUCHED.size)),
        np.full(16, E + 3)])  # 16 rows of a brand-new entity
    Xf2 = rng.normal(size=(ent2.shape[0], DF)).astype(np.float32)
    Xr2 = rng.normal(size=(ent2.shape[0], DR)).astype(np.float32)
    u_shift = np.vstack([u_true + 0.8, np.zeros((E + 4 - E, DR),
                                                np.float32)])
    y2 = _labels(rng, Xf2, Xr2, ent2, w_true, u_shift)
    drop = GameData.build(y2, {"fx": Xf2, "rs": Xr2}, {"e": ent2})
    plan = continual.diff_manifest(manifest, drop, prev)
    return {"data": data, "prev": prev, "manifest": manifest,
            "drop": drop, "plan": plan, "rng_seed": 1}


# ------------------------------------------------------------------ manifest
class TestManifest:
    def test_counts_weight_carrying_rows_only(self):
        ids = np.asarray([0, 0, 1, 1, 2])
        w = np.asarray([1.0, 0.0, 1.0, 1.0, 0.0], np.float32)
        data = GameData.build(np.zeros(5, np.float32),
                              {"x": np.zeros((5, 2), np.float32)},
                              {"e": ids}, weights=w)
        m = continual.build_manifest(data)
        assert m["entities"]["e"] == {"0": 1, "1": 2}
        assert m["n_rows"] == 5

    def test_round_trip_beside_model(self, world, tmp_path):
        from photon_tpu.data.index_map import IndexMap, feature_key

        imaps = {
            "fixed": IndexMap({feature_key(f"f{j}"): j
                               for j in range(DF)}, frozen=True),
            "re": IndexMap({feature_key(f"r{j}"): j
                            for j in range(DR)}, frozen=True)}
        out = str(tmp_path / "model")
        save_game_model(out, world["prev"], imaps,
                        manifest=world["manifest"])
        assert load_training_manifest(out) == json.loads(
            json.dumps(world["manifest"]))
        assert load_training_manifest(str(tmp_path)) is None
        # variances persist too — the other half of "a refresh can build
        # its priors from a saved model alone". The loader re-sorts
        # entity rows by STRING key, so compare aligned by key.
        loaded, _ = load_game_model(out)
        lre = loaded.coordinates["re"]
        assert lre.variances is not None
        pid = world["prev"].coordinates["re"].dense_ids(
            np.asarray(lre.entity_keys))
        assert np.allclose(
            np.asarray(lre.variances),
            np.asarray(world["prev"].coordinates["re"].variances)[pid])


# --------------------------------------------------------------------- delta
class TestDelta:
    def test_delta_drop_touches_present_entities(self, world):
        cp = world["plan"].coordinates["re"]
        assert set(np.asarray(cp.touched_keys).astype(np.str_).tolist()) \
            == {str(k) for k in TOUCHED.tolist()}
        assert int(cp.new_keys.shape[0]) == 1  # E + 3, unseen → deferred
        assert cp.n_touched_rows == 128

    def test_new_key_deferral_is_counted_and_logged(self, world, caplog):
        """new_keys deferral is no longer silent: the diff counts
        ``continual.deferred_new_keys`` and says so at INFO with the
        deferred-entity count (the ROADMAP new-entity-admission
        breadcrumb starts from this signal)."""
        import logging

        from photon_tpu import telemetry

        r = telemetry.start_run("deferral")
        try:
            with caplog.at_level(logging.INFO, logger="photon_tpu.continual"):
                plan = continual.diff_manifest(world["manifest"],
                                               world["drop"], world["prev"])
        finally:
            telemetry.finish_run()
        assert r.counters["continual.deferred_new_keys"] == 1.0
        msgs = [rec.getMessage() for rec in caplog.records
                if rec.name == "photon_tpu.continual"]
        assert any("deferring 1 new" in m and "'re'" in m for m in msgs), \
            msgs
        # a drop with NO new keys stays silent and uncounted
        caplog.clear()
        r2 = telemetry.start_run("no_deferral")
        try:
            with caplog.at_level(logging.INFO, logger="photon_tpu.continual"):
                continual.diff_manifest(world["manifest"], world["data"],
                                        world["prev"], full=True)
        finally:
            telemetry.finish_run()
        assert "continual.deferred_new_keys" not in r2.counters
        assert not [rec for rec in caplog.records
                    if rec.name == "photon_tpu.continual"]
        assert plan.coordinates["re"].n_touched > 0

    def test_full_drop_touches_changed_only(self, world):
        data = world["data"]
        # the full refreshed dataset = the original rows + 8 extra rows
        # for entity 5 — only entity 5's count changed
        rng = np.random.default_rng(9)
        extra = 8
        ent_f = np.concatenate([np.asarray(data.entity_ids["e"]),
                                np.full(extra, 5)])
        full = GameData.build(
            np.concatenate([data.y, np.zeros(extra, np.float32)]),
            {"fx": np.vstack([data.shards["fx"],
                              rng.normal(size=(extra, DF)).astype(
                                  np.float32)]),
             "rs": np.vstack([data.shards["rs"],
                              rng.normal(size=(extra, DR)).astype(
                                  np.float32)])},
            {"e": ent_f})
        plan = continual.diff_manifest(world["manifest"], full,
                                       world["prev"], full=True)
        cp = plan.coordinates["re"]
        assert np.asarray(cp.touched_keys).astype(np.str_).tolist() == ["5"]

    def test_newer_manifest_version_refused(self, world):
        bad = dict(world["manifest"], version=99)
        with pytest.raises(ValueError, match="newer"):
            continual.diff_manifest(bad, world["drop"], world["prev"])

    def test_missing_entity_column_refused(self, world):
        bad = {"version": 1, "n_rows": 1, "entities": {}}
        with pytest.raises(KeyError, match="retrain fully"):
            continual.diff_manifest(bad, world["drop"], world["prev"])


# -------------------------------------------------------------------- priors
class TestFromVariances:
    def test_precision_is_inverse_variance(self):
        means = np.asarray([1.0, -2.0, 0.5], np.float32)
        var = np.asarray([0.25, 4.0, 0.0], np.float32)
        p = PriorDistribution.from_variances(means, var)
        assert np.allclose(p.precision_diag[:2], [4.0, 0.25])
        # variance ≤ 0: the dim was never estimated → NO prior there
        assert p.precision_diag[2] == 0.0
        assert p.precision_full is None

    def test_variances_required_and_shape_checked(self):
        with pytest.raises(ValueError, match="variances"):
            PriorDistribution.from_variances(np.zeros(3), None)
        with pytest.raises(ValueError, match="shape"):
            PriorDistribution.from_variances(np.zeros(3), np.ones(4))

    def test_prior_objective_matches_hand_built_bitwise(self):
        from photon_tpu.data.dataset import make_batch
        from photon_tpu.models.training import make_objective

        rng = np.random.default_rng(4)
        d = 6
        mu = rng.normal(size=d).astype(np.float32)
        var = rng.uniform(0.1, 2.0, size=d).astype(np.float32)
        prior = PriorDistribution.from_variances(mu, var)
        cfg = OptimizerConfig(reg=l2(), reg_weight=0.7,
                              regularize_intercept=True)
        obj = make_objective(TaskType.LOGISTIC_REGRESSION, cfg, d,
                             intercept_index=None,
                             prior_mean=jnp.asarray(prior.mean),
                             prior_precision=jnp.asarray(
                                 prior.precision_diag))
        w = jnp.asarray(rng.normal(size=d).astype(np.float32))
        # weight-0 rows: the data term vanishes EXACTLY, leaving only the
        # regularizer — the hand-built 0.5·(w−μ)ᵀΛ(w−μ) with Λ = l2 + τ
        batch = make_batch(rng.normal(size=(8, d)).astype(np.float32),
                           np.zeros(8, np.float32),
                           weights=np.zeros(8, np.float32))
        dw = w - jnp.asarray(mu)
        lam = obj.l2 + jnp.asarray(prior.precision_diag)
        hand = 0.5 * jnp.sum(lam * dw * dw)
        assert float(obj.value(w, batch)) == float(hand)

    def test_warm_started_solve_beats_cold_start(self):
        from photon_tpu.data.dataset import make_batch
        from photon_tpu.models.training import make_objective, train_glm
        from photon_tpu.models.variance import compute_variances

        rng = np.random.default_rng(7)
        n, d = 512, 8
        w_true = rng.normal(size=d).astype(np.float32)
        X1 = rng.normal(size=(n, d)).astype(np.float32)
        y1 = (rng.uniform(size=n)
              < 1 / (1 + np.exp(-(X1 @ w_true)))).astype(np.float32)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7, reg=l2(),
                              reg_weight=0.5, history=5)
        b1 = make_batch(X1, y1)
        model1, _ = train_glm(b1, TaskType.LOGISTIC_REGRESSION, cfg)
        w1 = jnp.asarray(model1.coefficients.means)
        var1 = compute_variances(
            make_objective(TaskType.LOGISTIC_REGRESSION, cfg, d), w1, b1,
            VarianceComputationType.SIMPLE)
        # a fresh (smaller) drop from the SAME world: the flywheel step
        X2 = rng.normal(size=(128, d)).astype(np.float32)
        y2 = (rng.uniform(size=128)
              < 1 / (1 + np.exp(-(X2 @ w_true)))).astype(np.float32)
        b2 = make_batch(X2, y2)
        prior = PriorDistribution.from_variances(np.asarray(w1),
                                                 np.asarray(var1))
        _, warm = train_glm(b2, TaskType.LOGISTIC_REGRESSION, cfg,
                            w0=w1, prior=prior)
        _, cold = train_glm(b2, TaskType.LOGISTIC_REGRESSION, cfg)
        assert int(warm.iterations) < int(cold.iterations), \
            (int(warm.iterations), int(cold.iterations))
        assert bool(warm.converged)

    def test_grid_prior_rejection_routes_single_lane(self, caplog):
        import logging

        from photon_tpu.data.dataset import make_batch
        from photon_tpu.models.training import train_glm, train_glm_grid
        from photon_tpu.ops.lane_objective import supports_lanes
        from photon_tpu.ops.objective import Objective

        assert not supports_lanes(Objective(
            task=TaskType.LOGISTIC_REGRESSION,
            prior_precision=jnp.ones(3, jnp.float32)))
        rng = np.random.default_rng(5)
        n, d = 128, 5
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        batch = make_batch(X, y)
        mu = rng.normal(size=d).astype(np.float32)
        prior = PriorDistribution.from_variances(
            mu, np.full(d, 0.5, np.float32))
        cfg = OptimizerConfig(max_iters=40, tolerance=1e-7, reg=l2(),
                              reg_weight=0.1, history=5)
        weights = [0.05, 0.5]
        with caplog.at_level(logging.INFO, logger="photon_tpu.models"):
            grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION,
                                  cfg, weights, prior=prior)
        assert any("lane-minor" in r.message and "prior" in r.message
                   for r in caplog.records), caplog.text
        # the fallback is a ROUTE, not a different answer: each lane
        # matches the sequential single-lane prior solve
        for wt, (model, _) in zip(weights, grid):
            seq, _ = train_glm(
                batch, TaskType.LOGISTIC_REGRESSION,
                dataclasses.replace(cfg, reg_weight=wt), prior=prior)
            np.testing.assert_allclose(
                np.asarray(model.coefficients.means),
                np.asarray(seq.coefficients.means), rtol=1e-3, atol=5e-4)


# ------------------------------------------------------------------- refresh
class TestRefresh:
    def test_untouched_bit_identical_touched_resolve(self, world):
        res = continual.refresh_game_model(
            world["prev"], world["drop"], world["plan"], {"re": CFG_R})
        prev_c = np.asarray(world["prev"].coordinates["re"].coefficients)
        new_c = np.asarray(res.model.coordinates["re"].coefficients)
        untouched = np.setdiff1d(np.arange(E), TOUCHED)
        assert (prev_c[untouched] == new_c[untouched]).all()
        assert (prev_c[TOUCHED] != new_c[TOUCHED]).any()
        st = res.stats["re"]
        assert st.n_touched == TOUCHED.size and st.n_failed == 0
        assert st.n_converged == TOUCHED.size
        assert st.n_deferred_new == 1
        # refreshed variances feed the NEXT turn of the flywheel
        new_v = np.asarray(res.model.coordinates["re"].variances)
        prev_v = np.asarray(world["prev"].coordinates["re"].variances)
        assert (new_v[untouched] == prev_v[untouched]).all()
        assert (new_v[TOUCHED] != prev_v[TOUCHED]).any()
        # the fixed effect is FROZEN by design
        assert (np.asarray(res.model.coordinates["fixed"]
                           .model.coefficients.means)
                == np.asarray(world["prev"].coordinates["fixed"]
                              .model.coefficients.means)).all()

    def test_refresh_from_saved_model_alone(self, world, tmp_path):
        """THE satellite claim: coefficients + variances + manifest all
        round-trip through disk, and the refresh built from the saved
        directory matches the in-memory refresh bit-for-bit."""
        from photon_tpu.data.index_map import IndexMap, feature_key

        imaps = {
            "fixed": IndexMap({feature_key(f"f{j}"): j
                               for j in range(DF)}, frozen=True),
            "re": IndexMap({feature_key(f"r{j}"): j
                            for j in range(DR)}, frozen=True)}
        out = str(tmp_path / "saved")
        save_game_model(out, world["prev"], imaps,
                        manifest=world["manifest"])
        loaded, _ = load_game_model(out)
        manifest = load_training_manifest(out)
        plan = continual.diff_manifest(manifest, world["drop"], loaded)
        got = continual.refresh_game_model(
            loaded, world["drop"], plan, {"re": CFG_R})
        want = continual.refresh_game_model(
            world["prev"], world["drop"], world["plan"], {"re": CFG_R})
        # the loader re-sorts entity rows by string key: align by key
        # before the bitwise comparison
        got_re = got.model.coordinates["re"]
        want_re = want.model.coordinates["re"]
        pid = want_re.dense_ids(np.asarray(got_re.entity_keys))
        np.testing.assert_array_equal(
            np.asarray(got_re.coefficients),
            np.asarray(want_re.coefficients)[pid])

    def test_repeat_refresh_adds_no_signatures(self, world):
        continual.refresh_game_model(world["prev"], world["drop"],
                                     world["plan"], {"re": CFG_R})
        baseline = len(continual.RefreshResult.signatures())
        # a DIFFERENT touched set and row count — but the same pow2
        # bucket shape (24 rows → the m=32 ladder rung, like the first
        # drop's 32): the hourly cadence produces a small closed set of
        # bucket shapes, and within it the delta path never compiles
        rng = np.random.default_rng(13)
        sub = TOUCHED[:2]
        ent3 = np.repeat(sub, 24)
        drop3 = GameData.build(
            np.zeros(ent3.shape[0], np.float32),
            {"fx": rng.normal(size=(ent3.shape[0], DF)).astype(np.float32),
             "rs": rng.normal(size=(ent3.shape[0], DR)).astype(np.float32)},
            {"e": ent3})
        plan3 = continual.diff_manifest(world["manifest"], drop3,
                                        world["prev"])
        continual.refresh_game_model(world["prev"], drop3, plan3,
                                     {"re": CFG_R})
        assert continual.RefreshResult.assert_no_retrace(baseline) \
            == baseline

    def test_refresh_requires_config(self, world):
        with pytest.raises(KeyError, match="OptimizerConfig"):
            continual.refresh_game_model(world["prev"], world["drop"],
                                         world["plan"], {})


# ---------------------------------------------------------------------- swap
class TestSwap:
    def _stores(self, world):
        live = CoefficientStore.from_game_model(world["prev"])
        res = continual.refresh_game_model(
            world["prev"], world["drop"], world["plan"], {"re": CFG_R})
        return live, CoefficientStore.from_game_model(res.model)

    def test_publish_open_and_sweep(self, world, tmp_path):
        root = str(tmp_path / "serve")
        live, new = self._stores(world)
        assert current_version(root) is None
        v0 = continual.publish_store(root, live)
        v1 = continual.publish_store(root, new)
        store, v = open_current(root)
        assert (v0, v1, v) == (0, 1, 1)
        np.testing.assert_array_equal(
            np.asarray(store.random["re"].coefficients),
            np.asarray(new.random["re"].coefficients))
        v2 = continual.publish_store(root, live)
        assert v2 == 2 and not os.path.isdir(
            os.path.join(root, "v00000000"))  # swept: older than live-1

    def test_kill_mid_swap_leaves_old_model_serving(self, world, tmp_path):
        from photon_tpu.checkpoint.faults import (FaultPlan, InjectedFault,
                                                  fault_plan)

        root = str(tmp_path / "serve")
        live, new = self._stores(world)
        continual.publish_store(root, live)
        before = np.asarray(open_current(root)[0]
                            .random["re"].coefficients).copy()
        for site, occ in (("swap_publish", 1), ("commit", 1),
                          ("commit", 2)):
            with pytest.raises(InjectedFault):
                with fault_plan(FaultPlan.kill_at(site, occ)):
                    continual.hot_swap(None, new, root=root, probe=None)
            after, v = open_current(root)
            assert v == 0, (site, occ)
            np.testing.assert_array_equal(
                np.asarray(after.random["re"].coefficients), before,
                err_msg=f"torn swap at {site}#{occ}")
        # and the un-killed publish completes from the same state (the
        # killed attempts' orphan version dirs only advance numbering)
        continual.hot_swap(None, new, root=root, probe=None)
        store, v = open_current(root)
        assert v > 0
        np.testing.assert_array_equal(
            np.asarray(store.random["re"].coefficients),
            np.asarray(new.random["re"].coefficients))

    def test_probe_refuses_blown_up_model(self, world):
        live, new = self._stores(world)
        broken = CoefficientStore.from_game_model(world["prev"])
        broken.random["re"] = dataclasses.replace(
            broken.random["re"],
            coefficients=broken.random["re"].coefficients + 1e6)
        run = telemetry.start_run("swap_test")
        try:
            with pytest.raises(continual.SwapRefused):
                continual.hot_swap(live, broken,
                                   probe=continual.ParityProbe(bound=1.0))
            assert run.counters.get("continual.swap_refusals") == 1
            assert "serving.hot_swaps" not in run.counters
            # the live store is untouched by a refusal
            np.testing.assert_array_equal(
                np.asarray(live.random["re"].coefficients)[:-1],
                np.asarray(world["prev"].coordinates["re"].coefficients))
            # ... and the honest refresh passes the same probe + counts
            out = continual.hot_swap(live, new,
                                     probe=continual.ParityProbe(
                                         bound=1e3))
            assert out["report"].ok
            assert run.counters.get("serving.hot_swaps") == 1
        finally:
            telemetry.finish_run()

    def test_staleness_gauge_rides_the_swap(self, world):
        """`rows_changed_unix` arms the freshness clock: the swap gauges
        ``continual.staleness_s`` (rows-changed -> servable seconds) at
        the moment the new coefficients become servable, and returns the
        same number. Without the timestamp nothing is gauged."""
        import time as _time
        live, new = self._stores(world)
        changed = _time.time() - 5.0  # the delta's rows changed 5s ago
        run = telemetry.start_run("swap_staleness")
        try:
            out = continual.hot_swap(live, new,
                                     probe=continual.ParityProbe(bound=1e3),
                                     rows_changed_unix=changed)
            assert out["staleness_s"] is not None
            assert 5.0 <= out["staleness_s"] < 60.0
            assert run.gauges.get("continual.staleness_s") == pytest.approx(
                out["staleness_s"])
            # disarmed: no timestamp, no gauge, None in the return
            live2, new2 = self._stores(world)
            out2 = continual.hot_swap(live2, new2,
                                      probe=continual.ParityProbe(bound=1e3))
            assert out2["staleness_s"] is None
            assert run.gauges.get("continual.staleness_s") == pytest.approx(
                out["staleness_s"])  # untouched by the disarmed swap
        finally:
            telemetry.finish_run()


def test_selftest_cli_end_to_end():
    """`python -m photon_tpu.continual --selftest --json` — the CI smoke
    face of the whole flywheel — exits 0 with every check ok."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the CLI must self-provision its platform
    env["JAX_PLATFORMS"] = "cpu"
    # share the suite's persistent XLA compile cache so repeat CI runs
    # replay executables instead of recompiling the selftest's solvers
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.environ.get("PHOTON_TPU_TEST_CACHE_DIR",
                                  "/tmp/photon_tpu_xla_test_cache"))
    proc = subprocess.run(
        [sys.executable, "-m", "photon_tpu.continual", "--selftest",
         "--json"],
        capture_output=True, text=True, timeout=420, env=env, cwd=_REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["ok"] is True
    assert set(report["checks"]) == {"delta_plan", "refresh_parity",
                                     "refresh_no_retrace", "swap",
                                     "contracts"}
