"""Diagnostics: bootstrap CIs, Hosmer-Lemeshow calibration, feature importance.

Mirrors the reference's diagnostics.* unit tests: statistics checked against
plain-numpy reimplementations and against planted ground truth.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from photon_tpu.data.dataset import make_batch
from photon_tpu.data.matrix import SparseRows, from_scipy_csr
from photon_tpu.diagnostics import (
    bootstrap_glm,
    expected_magnitude_importance,
    hosmer_lemeshow,
    variance_importance,
)
from photon_tpu.evaluation.metrics import logistic_loss
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig


def _logistic_problem(rng, n=3000, d=6):
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = np.linspace(-1.0, 1.0, d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return X, y, w_true


class TestBootstrap:
    def test_ci_covers_truth(self, rng):
        X, y, w_true = _logistic_problem(rng)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7,
                              regularize_intercept=True)
        rep = bootstrap_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                            cfg, n_replicates=24, intercept_index=None)
        assert rep.coefficients.shape == (24, 6)
        assert rep.converged.all()
        # Every replicate differs (Poisson weights actually vary the fit).
        assert np.std(rep.coefficients, axis=0).min() > 1e-4
        # 95% CI covers the planted coefficients in nearly all coords.
        assert rep.contains(w_true).sum() >= 5
        # Bootstrap mean lands near the truth too.
        np.testing.assert_allclose(rep.mean, w_true, atol=0.25)

    def test_metric_distribution(self, rng):
        X, y, _ = _logistic_problem(rng, n=800, d=4)
        cfg = OptimizerConfig(max_iters=40, regularize_intercept=True)
        rep = bootstrap_glm(
            make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg,
            n_replicates=8, intercept_index=None,
            metric_fn=lambda w, b: logistic_loss(
                b.X @ w + b.offsets, b.y, b.weights),
        )
        assert rep.metrics.shape == (8,)
        assert np.isfinite(rep.metrics).all()
        # Training log-loss on a separable-ish fit stays below chance.
        assert rep.metrics.mean() < np.log(2.0)

    def test_padding_rows_stay_dead(self, rng):
        X, y, _ = _logistic_problem(rng, n=200, d=4)
        w = np.ones(200, np.float32)
        w[150:] = 0.0  # padding
        y2 = y.copy()
        y2[150:] = 99.0  # poison: must never be touched
        cfg = OptimizerConfig(max_iters=30, regularize_intercept=True)
        rep = bootstrap_glm(make_batch(X, y2, weights=w),
                            TaskType.LOGISTIC_REGRESSION, cfg,
                            n_replicates=4, intercept_index=None)
        assert np.isfinite(rep.coefficients).all()


def _hl_numpy(probs, labels, weights, n_bins=10):
    order = np.argsort(probs)
    p, y, w = probs[order], labels[order], weights[order]
    cumw = np.cumsum(w) - 0.5 * w
    bins = np.clip((cumw / w.sum() * n_bins).astype(int), 0, n_bins - 1)
    chi2 = 0.0
    for g in range(n_bins):
        m = (bins == g) & (w > 0)
        if not m.any():
            continue
        obs, exp, mass = (w[m] * y[m]).sum(), (w[m] * p[m]).sum(), w[m].sum()
        chi2 += (obs - exp) ** 2 / max(exp * (1 - exp / mass), 1e-12)
    return chi2


class TestHosmerLemeshow:
    def test_matches_numpy(self, rng):
        n = 2000
        p = rng.uniform(0.05, 0.95, size=n).astype(np.float32)
        y = (rng.uniform(size=n) < p).astype(np.float32)
        w = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
        res = hosmer_lemeshow(p, y, w)
        np.testing.assert_allclose(float(res.chi2), _hl_numpy(p, y, w),
                                   rtol=2e-4)
        assert res.observed_pos.shape == (10,)
        np.testing.assert_allclose(float(res.bin_weight.sum()), w.sum(),
                                   rtol=1e-5)

    def test_calibrated_vs_miscalibrated(self, rng):
        n = 5000
        p = rng.uniform(0.05, 0.95, size=n).astype(np.float32)
        y_good = (rng.uniform(size=n) < p).astype(np.float32)
        good = hosmer_lemeshow(p, y_good)
        assert float(good.p_value) > 0.05
        assert bool(good.well_calibrated)
        # Systematically over-predicted labels → reject calibration.
        y_bad = (rng.uniform(size=n) < np.clip(p + 0.2, 0, 1)).astype(np.float32)
        bad = hosmer_lemeshow(p, y_bad)
        assert float(bad.p_value) < 1e-4
        assert float(bad.chi2) > float(good.chi2)

    def test_padding_ignored(self, rng):
        n = 1000
        p = rng.uniform(0.1, 0.9, size=n).astype(np.float32)
        y = (rng.uniform(size=n) < p).astype(np.float32)
        w = np.ones(n, np.float32)
        base = hosmer_lemeshow(p, y, w)
        p2 = np.concatenate([p, np.full(100, 0.5, np.float32)])
        y2 = np.concatenate([y, np.ones(100, np.float32)])
        w2 = np.concatenate([w, np.zeros(100, np.float32)])
        padded = hosmer_lemeshow(p2, y2, w2)
        np.testing.assert_allclose(float(padded.chi2), float(base.chi2),
                                   rtol=1e-5)


class TestFeatureImportance:
    def test_dense_matches_numpy(self, rng):
        n, d = 500, 7
        X = rng.normal(size=(n, d)).astype(np.float32) * rng.uniform(0.5, 3, d)
        w = rng.normal(size=d).astype(np.float32)
        wt = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
        wn = wt / wt.sum()
        rep = expected_magnitude_importance(w, jnp.asarray(X), wt)
        np.testing.assert_allclose(
            rep.importance, np.abs(w) * (wn @ np.abs(X)), rtol=1e-4)
        repv = variance_importance(w, jnp.asarray(X), wt)
        mu = wn @ X
        var = wn @ (X * X) - mu * mu
        np.testing.assert_allclose(
            repv.importance, np.abs(w) * np.sqrt(np.maximum(var, 0)),
            rtol=1e-3, atol=1e-5)
        assert rep.importance[rep.order[0]] == rep.importance.max()

    def test_sparse_matches_dense(self, rng):
        import scipy.sparse as sp
        n, d = 300, 20
        M = sp.random(n, d, density=0.2, random_state=1, format="csr",
                      dtype=np.float32)
        X = from_scipy_csr(M)
        w = rng.normal(size=d).astype(np.float32)
        dense = expected_magnitude_importance(w, jnp.asarray(M.toarray()))
        sparse = expected_magnitude_importance(w, X)
        np.testing.assert_allclose(sparse.importance, dense.importance,
                                   rtol=1e-4, atol=1e-6)
        densev = variance_importance(w, jnp.asarray(M.toarray()))
        sparsev = variance_importance(w, X)
        np.testing.assert_allclose(sparsev.importance, densev.importance,
                                   rtol=1e-3, atol=1e-5)

    def test_names_and_top(self, rng):
        X = rng.normal(size=(100, 3)).astype(np.float32)
        rep = expected_magnitude_importance(
            np.array([0.1, 5.0, 1.0], np.float32), jnp.asarray(X),
            names=["a", "b", "c"])
        top = rep.top(2)
        assert top[0][0] == "b"
        assert len(top) == 2
