"""PermutedHybridRows: the scatter-free permuted-space hybrid
(data/matrix.py). Parity contract: every op and every solve must agree
with the SparseRows representation of the same matrix, with all
user-facing vectors in ORIGINAL column order.

Mirrors the reference's representation-invariance expectation
(com.linkedin.photon.ml.data: LabeledPoint math is identical whatever the
underlying vector type).
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.dataset import GLMBatch, cast_features, make_batch, pad_batch
from photon_tpu.data.matrix import (PermutedHybridRows, SparseRows, matvec,
                                    matvec_lanes, rmatvec, rmatvec_lanes,
                                    sq_rmatvec, to_permuted_hybrid,
                                    weighted_gram)
from photon_tpu.models.training import (evaluate_glm_grid, train_glm,
                                        train_glm_grid)
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.regularization import l2


def _power_law_sparse(rng, n=500, d=800, k=10, d_dense=32):
    """Zipf-ish column frequencies so hot/bucket/deep-tail paths all fill.

    Duplicate (row, col) slots get value 0 (the padding convention): real
    feature-bag rows never repeat a feature, and duplicate cells are where
    per-entry and per-cell quadratic semantics (sq_rmatvec) diverge."""
    col = (rng.zipf(1.5, size=(n, k)).astype(np.int64) - 1) % (d - 1)
    val = rng.normal(size=(n, k)).astype(np.float32)
    order = np.argsort(col, axis=1, kind="stable")
    sorted_col = np.take_along_axis(col, order, axis=1)
    dup = sorted_col[:, 1:] == sorted_col[:, :-1]
    dupmask = np.zeros_like(col, bool)
    np.put_along_axis(dupmask, order[:, 1:], dup, axis=1)
    val[dupmask] = 0.0
    ind = np.concatenate([col, np.full((n, 1), d - 1)], axis=1).astype(
        np.int32)
    va = np.concatenate([val, np.ones((n, 1), np.float32)], axis=1)
    X = SparseRows(jnp.asarray(ind), jnp.asarray(va), d)
    P = to_permuted_hybrid(X, d_dense)
    return X, P


def test_perm_roundtrip_and_layout(rng):
    X, P = _power_law_sparse(rng)
    d = X.n_features
    perm = np.asarray(P.perm_cols)
    inv = np.asarray(P.inv_perm)
    assert sorted(perm.tolist()) == list(range(d))
    np.testing.assert_array_equal(perm[inv], np.arange(d))
    v = rng.normal(size=d).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(P.to_model_space(P.from_model_space(v))), v)
    # intercept (original last column, in every row) must be hot
    assert P.last_col_pos < P.d_sel
    assert np.asarray(P.dense)[:, P.last_col_pos].min() == 1.0


def test_perm_matvec_rmatvec_parity(rng):
    X, P = _power_law_sparse(rng)
    n, d = X.shape
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    r = jnp.asarray(rng.normal(size=n).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(matvec(P, P.from_model_space(w))),
        np.asarray(matvec(X, w)), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(P.to_model_space(rmatvec(P, r))),
        np.asarray(rmatvec(X, r)), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(
        np.asarray(P.to_model_space(sq_rmatvec(P, r))),
        np.asarray(sq_rmatvec(X, r)), rtol=2e-4, atol=2e-4)


def test_perm_lane_ops_parity(rng):
    X, P = _power_law_sparse(rng)
    n, d = X.shape
    G = 5
    W = jnp.asarray(rng.normal(size=(d, G)).astype(np.float32))
    R = jnp.asarray(rng.normal(size=(n, G)).astype(np.float32))
    Wp = P.from_model_space(W)
    mv = np.asarray(matvec_lanes(P, Wp))
    rv = np.asarray(P.to_model_space(rmatvec_lanes(P, R)))
    for g in range(G):
        np.testing.assert_allclose(mv[:, g], np.asarray(matvec(X, W[:, g])),
                                   rtol=2e-4, atol=2e-4)
        np.testing.assert_allclose(rv[:, g], np.asarray(rmatvec(X, R[:, g])),
                                   rtol=2e-4, atol=2e-4)


def test_perm_weighted_gram_parity(rng):
    X, P = _power_law_sparse(rng, n=200, d=60, k=6, d_dense=8)
    r = jnp.asarray(rng.uniform(0.1, 1.0, size=200).astype(np.float32))
    Gp = np.asarray(weighted_gram(P, r))          # permuted space
    Gs = np.asarray(weighted_gram(X, r))
    perm = np.asarray(P.perm_cols)
    np.testing.assert_allclose(Gp, Gs[np.ix_(perm, perm)], rtol=1e-4,
                               atol=1e-4)


def test_perm_empty_tail(rng):
    # every column hot → tail empty; ops must still be exact
    ind = rng.integers(0, 16, size=(50, 4)).astype(np.int32)
    val = rng.normal(size=(50, 4)).astype(np.float32)
    X = SparseRows(jnp.asarray(ind), jnp.asarray(val), 16)
    P = to_permuted_hybrid(X, 16)
    assert P.bucket_rows == ()
    w = jnp.asarray(rng.normal(size=16).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(matvec(P, P.from_model_space(w))),
        np.asarray(matvec(X, w)), rtol=1e-5, atol=1e-5)
    r = jnp.asarray(rng.normal(size=50).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(P.to_model_space(rmatvec(P, r))),
        np.asarray(rmatvec(X, r)), rtol=1e-5, atol=1e-5)


@pytest.mark.cpu_parity_drift
def test_perm_train_glm_parity(rng):
    X, P = _power_law_sparse(rng)
    wt = rng.normal(size=X.n_features).astype(np.float32) * 0.5
    z = np.asarray(matvec(X, jnp.asarray(wt)))
    y = jnp.asarray((rng.random(X.shape[0]) < 1 / (1 + np.exp(-z))).astype(
        np.float32))
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=l2(),
                          reg_weight=0.1, history=5)
    m_p, r_p = train_glm(make_batch(P, y), TaskType.LOGISTIC_REGRESSION, cfg)
    m_s, r_s = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg)
    np.testing.assert_allclose(float(r_p.value), float(r_s.value), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m_p.coefficients.means),
                               np.asarray(m_s.coefficients.means), atol=5e-3)
    # model scoring translates to permuted space internally
    np.testing.assert_allclose(np.asarray(m_p.score(P)),
                               np.asarray(m_p.score(X)), rtol=2e-4, atol=2e-4)


def test_perm_train_glm_regularize_intercept_off(rng):
    X, P = _power_law_sparse(rng)
    y = jnp.asarray((rng.random(X.shape[0]) < 0.5).astype(np.float32))
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=l2(),
                          reg_weight=10.0, history=5,
                          regularize_intercept=False)
    m_p, r_p = train_glm(make_batch(P, y), TaskType.LOGISTIC_REGRESSION, cfg)
    m_s, r_s = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg)
    np.testing.assert_allclose(float(r_p.value), float(r_s.value), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m_p.coefficients.means),
                               np.asarray(m_s.coefficients.means), atol=5e-3)


def test_perm_train_glm_w0_and_normalization(rng):
    from photon_tpu.data.normalization import (NormalizationContext,
                                               NormalizationType)

    X, P = _power_law_sparse(rng, n=400, d=200, k=8, d_dense=16)
    d = X.n_features
    y = jnp.asarray((rng.random(400) < 0.5).astype(np.float32))
    w0 = rng.normal(size=d).astype(np.float32) * 0.1
    norm = NormalizationContext.build(X, NormalizationType.STANDARDIZATION,
                                      intercept_index=d - 1)
    # standardization of rare sparse columns gives huge factors and flat
    # optimum directions; strong L2 keeps the parity check conditioned
    # (the objective VALUE is the tight assertion either way)
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=l2(),
                          reg_weight=5.0, history=5)
    m_p, r_p = train_glm(make_batch(P, y), TaskType.LOGISTIC_REGRESSION,
                         cfg, w0=w0, normalization=norm)
    m_s, r_s = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                         cfg, w0=w0, normalization=norm)
    np.testing.assert_allclose(float(r_p.value), float(r_s.value), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(m_p.coefficients.means),
                               np.asarray(m_s.coefficients.means), atol=5e-3)


def test_perm_grid_parity_and_eval(rng):
    X, P = _power_law_sparse(rng)
    wt = rng.normal(size=X.n_features).astype(np.float32) * 0.5
    z = np.asarray(matvec(X, jnp.asarray(wt)))
    y = jnp.asarray((rng.random(X.shape[0]) < 1 / (1 + np.exp(-z))).astype(
        np.float32))
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    weights = [1e-1, 1.0, 30.0]
    bp, bs = make_batch(P, y), make_batch(X, y)
    grid_p = train_glm_grid(bp, TaskType.LOGISTIC_REGRESSION, cfg, weights)
    grid_s = train_glm_grid(bs, TaskType.LOGISTIC_REGRESSION, cfg, weights)
    for (m_p, r_p), (m_s, r_s) in zip(grid_p, grid_s):
        np.testing.assert_allclose(float(r_p.value), float(r_s.value),
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(m_p.coefficients.means),
                                   np.asarray(m_s.coefficients.means),
                                   atol=2e-2)
    best_p, scores_p = evaluate_glm_grid(grid_p, bp)
    best_s, scores_s = evaluate_glm_grid(grid_s, bs)
    assert best_p == best_s
    np.testing.assert_allclose(scores_p, scores_s, rtol=1e-3)


def test_perm_grid_device_results_original_order(rng):
    X, P = _power_law_sparse(rng, n=200, d=100, k=6, d_dense=8)
    y = jnp.asarray((rng.random(200) < 0.5).astype(np.float32))
    cfg = OptimizerConfig(max_iters=30, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    res_p, _ = train_glm_grid(make_batch(P, y), TaskType.LOGISTIC_REGRESSION,
                              cfg, [0.5, 2.0], device_results=True)
    grid_s = train_glm_grid(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                            cfg, [0.5, 2.0])
    for i, (m_s, _) in enumerate(grid_s):
        np.testing.assert_allclose(np.asarray(res_p.w)[i],
                                   np.asarray(m_s.coefficients.means),
                                   atol=2e-2)


def test_perm_pad_and_cast(rng):
    X, P = _power_law_sparse(rng, n=100, d=300, k=6)
    y = jnp.asarray(rng.normal(size=100).astype(np.float32))
    b = pad_batch(make_batch(P, y), 128)
    assert b.n == 128
    w = jnp.asarray(rng.normal(size=300).astype(np.float32))
    z = np.asarray(matvec(b.X, b.X.from_model_space(w)))
    np.testing.assert_allclose(z[:100], np.asarray(matvec(X, w)), rtol=2e-4,
                               atol=2e-4)
    np.testing.assert_allclose(z[100:], 0.0, atol=1e-6)
    bc = cast_features(b)
    assert bc.X.dense.dtype == jnp.bfloat16
    assert all(v.dtype == jnp.bfloat16 for v in bc.X.bucket_vals)


def test_perm_intercept_in_tail_detected(rng):
    """Hot-selection tie-break can leave an every-row intercept column in
    the tail (other columns with duplicate entries out-count it); the
    bucket scan must still recognize it — and reject a near-intercept
    missing one row."""
    from photon_tpu.data.matrix import last_column_is_intercept

    n, d = 16, 6
    ind = np.tile(np.array([[0, 0, 1, 1, 2, 5]], np.int32), (n, 1))
    val = np.ones((n, 6), np.float32)
    P = to_permuted_hybrid(SparseRows(jnp.asarray(ind), jnp.asarray(val), d),
                           d_dense=2)
    assert P.last_col_pos >= P.d_sel  # forced into the tail
    assert last_column_is_intercept(P)
    val2 = val.copy()
    val2[3, 5] = 0.0  # intercept missing from one row
    P2 = to_permuted_hybrid(
        SparseRows(jnp.asarray(ind), jnp.asarray(val2), d), d_dense=2)
    assert not last_column_is_intercept(P2)


def test_perm_game_fixed_effect_falls_back_correctly(rng):
    """A GAME fit whose fixed shard is PermutedHybridRows must route
    through train_glm (which owns the coefficient-space translation), not
    the fused update or the lane grid — and match the SparseRows fit."""
    from photon_tpu.game.coordinate_descent import _fixed_fusable
    from photon_tpu.game.dataset import GameData
    from photon_tpu.game.estimator import FixedEffectConfig, GameEstimator

    X, P = _power_law_sparse(rng, n=300, d=150, k=6, d_dense=16)
    y = (rng.random(300) < 0.5).astype(np.float32)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-6, reg=l2(),
                          reg_weight=1.0)

    def fit(shard):
        data = GameData.build(y, {"f": shard}, {})
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs={"fixed": FixedEffectConfig("f", cfg)},
            warm_start=False)
        assert not est._grid_data_supported(data) or shard is X
        return est.fit(data)[0]

    r_p, r_s = fit(P), fit(X)
    np.testing.assert_allclose(
        np.asarray(r_p.model["fixed"].model.coefficients.means),
        np.asarray(r_s.model["fixed"].model.coefficients.means), atol=5e-3)


def test_perm_mesh_rejected(rng, mesh8):
    X, P = _power_law_sparse(rng, n=64, d=100, k=4)
    y = jnp.asarray(rng.normal(size=64).astype(np.float32))
    cfg = OptimizerConfig(max_iters=5, reg=l2(), reg_weight=0.1)
    with pytest.raises(ValueError, match="single-device"):
        train_glm(make_batch(P, y), TaskType.LINEAR_REGRESSION, cfg,
                  mesh=mesh8)


class TestShardedPermuted:
    """ShardedPermutedHybridRows (the mesh form of the scatter-free
    layout): op + solve parity vs the single-device permuted build, with
    user-facing vectors in original column order."""

    def _problem(self, rng, n=640, d=500, k=9):
        col = (rng.zipf(1.5, size=(n, k - 1)).astype(np.int64) - 1) % (d - 1)
        val = rng.normal(size=(n, k - 1)).astype(np.float32)
        order = np.argsort(col, axis=1, kind="stable")
        sorted_col = np.take_along_axis(col, order, axis=1)
        dup = sorted_col[:, 1:] == sorted_col[:, :-1]
        dupmask = np.zeros_like(col, bool)
        np.put_along_axis(dupmask, order[:, 1:], dup, axis=1)
        val[dupmask] = 0.0
        ind = np.concatenate([col, np.full((n, 1), d - 1)], axis=1).astype(
            np.int32)
        va = np.concatenate([val, np.ones((n, 1), np.float32)], axis=1)
        X = SparseRows(jnp.asarray(ind), jnp.asarray(va), d)
        wt = rng.normal(size=d).astype(np.float32) * 0.5
        z = np.einsum("nk,nk->n", va, wt[ind])
        y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)
        return X, y

    def test_ops_match_single_device_permuted(self, rng):
        from photon_tpu.data.matrix import shard_permuted_hybrid

        X, _ = self._problem(rng)
        n, d = X.shape
        P1 = to_permuted_hybrid(X, 64)
        SP = shard_permuted_hybrid(X, 8, 64)
        assert SP.n_shards == 8 and SP.shape == (n, d)
        w = rng.normal(size=d).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(matvec(SP, SP.from_model_space(w))),
            np.asarray(matvec(P1, P1.from_model_space(w))),
            rtol=2e-5, atol=1e-5)
        r = rng.normal(size=n).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(SP.to_model_space(rmatvec(SP, r))),
            np.asarray(P1.to_model_space(rmatvec(P1, r))),
            rtol=2e-5, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(SP.to_model_space(sq_rmatvec(SP, r))),
            np.asarray(P1.to_model_space(sq_rmatvec(P1, r))),
            rtol=2e-5, atol=1e-4)
        W = rng.normal(size=(d, 4)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(matvec_lanes(SP, SP.from_model_space(W))),
            np.asarray(matvec_lanes(P1, P1.from_model_space(W))),
            rtol=2e-5, atol=1e-4)
        R = rng.normal(size=(n, 4)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(SP.to_model_space(rmatvec_lanes(SP, R))),
            np.asarray(P1.to_model_space(rmatvec_lanes(P1, R))),
            rtol=2e-5, atol=1e-4)

    def test_local_view_composes_to_global(self, rng):
        """Slicing shard s's leaves + local() must equal the global op on
        that shard's row range — the shard_map contract, checked without a
        mesh."""
        from photon_tpu.data.matrix import shard_permuted_hybrid

        X, _ = self._problem(rng)
        n, d = X.shape
        SP = shard_permuted_hybrid(X, 4, 64)
        n_local = SP.n_local
        w = rng.normal(size=d).astype(np.float32)
        wp = SP.from_model_space(w)
        full = np.asarray(matvec(SP, wp))
        grads = []
        for s in range(SP.n_shards):
            sliced = dataclasses.replace(
                SP,
                dense=SP.dense[s * n_local:(s + 1) * n_local],
                tail_pcols=SP.tail_pcols[s:s + 1],
                tail_vals=SP.tail_vals[s:s + 1],
                row_bounds=SP.row_bounds[s:s + 1],
                bucket_rows=tuple(b[s:s + 1] for b in SP.bucket_rows),
                bucket_vals=tuple(b[s:s + 1] for b in SP.bucket_vals))
            loc = sliced.local()
            np.testing.assert_allclose(
                np.asarray(matvec(loc, wp)),
                full[s * n_local:(s + 1) * n_local], rtol=2e-5, atol=1e-5)
            r = rng.normal(size=n_local).astype(np.float32)
            grads.append((loc, r))
        # per-shard rmatvec partials sum to the global rmatvec
        r_full = np.concatenate([np.asarray(r) for _, r in grads])
        total = sum(np.asarray(rmatvec(loc, jnp.asarray(r)))
                    for loc, r in grads)
        np.testing.assert_allclose(
            total, np.asarray(rmatvec(SP, jnp.asarray(r_full))),
            rtol=2e-5, atol=1e-4)

    @pytest.mark.cpu_parity_drift
    def test_train_glm_mesh_matches_single_device(self, rng, mesh8):
        from photon_tpu.data.dataset import shard_permuted_batch

        X, y = self._problem(rng)
        sb = shard_permuted_batch(make_batch(X, y), mesh8.devices.size, 64)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7, reg=l2(),
                              reg_weight=1.0)
        m_s, r_s = train_glm(sb, TaskType.LOGISTIC_REGRESSION, cfg,
                             mesh=mesh8)
        m_1, r_1 = train_glm(make_batch(to_permuted_hybrid(X, 64), y),
                             TaskType.LOGISTIC_REGRESSION, cfg)
        np.testing.assert_allclose(float(r_s.value), float(r_1.value),
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(m_s.coefficients.means),
                                   np.asarray(m_1.coefficients.means),
                                   atol=2e-3)

    def test_train_glm_grid_lanes_mesh(self, rng, mesh8):
        from photon_tpu.data.dataset import shard_permuted_batch

        X, y = self._problem(rng)
        sb = shard_permuted_batch(make_batch(X, y), mesh8.devices.size, 64)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7, reg=l2(),
                              reg_weight=0.0, history=5)
        weights = [1e-1, 1.0, 10.0]
        grid = train_glm_grid(sb, TaskType.LOGISTIC_REGRESSION, cfg,
                              weights, mesh=mesh8)
        ref = train_glm_grid(make_batch(to_permuted_hybrid(X, 64), y),
                             TaskType.LOGISTIC_REGRESSION, cfg, weights)
        for (ms, rs), (m1, r1) in zip(grid, ref):
            np.testing.assert_allclose(float(rs.value), float(r1.value),
                                       rtol=1e-4)
            np.testing.assert_allclose(np.asarray(ms.coefficients.means),
                                       np.asarray(m1.coefficients.means),
                                       atol=2e-2)

    def test_cast_features_bf16(self, rng):
        from photon_tpu.data.matrix import shard_permuted_hybrid

        X, y = self._problem(rng)
        SP = shard_permuted_hybrid(X, 4, 64)
        b = cast_features(make_batch(SP, y))
        assert b.X.dense.dtype == jnp.bfloat16
        assert all(v.dtype == jnp.bfloat16 for v in b.X.bucket_vals)
        w = rng.normal(size=X.n_features).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(matvec(b.X, b.X.from_model_space(w))),
            np.asarray(matvec(SP, SP.from_model_space(w))),
            rtol=2e-2, atol=2e-2)
