"""Streamed (out-of-HBM) objective mode: chunk partials, the host-driven
L-BFGS/OWL-QN solvers, and the training driver's HBM-budget auto-trip.

The contract under test is the ISSUE's acceptance line: a streamed fit's
value/gradient and FINAL COEFFICIENTS match the resident path to f32
accumulation tolerance, across logistic + linear and L-BFGS + OWL-QN, and
the dataset itself never becomes device-resident (host chunks stay numpy).
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.dataset import (
    ChunkedBatch,
    ChunkedMatrix,
    chunk_batch,
    make_batch,
)
from photon_tpu.data.matrix import SparseRows
from photon_tpu.models.training import train_glm, train_glm_grid
from photon_tpu.ops.losses import TaskType
from photon_tpu.ops.objective import Objective
from photon_tpu.optim.config import OptimizerConfig, OptimizerType
from photon_tpu.optim.regularization import elastic_net, l1, l2


def _problem(rng, task, n=2048, d=10, sparse=False):
    if sparse:
        k = 4
        ind = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        X = SparseRows(ind, val, d)
        Xd = np.zeros((n, d), np.float32)
        np.add.at(Xd, (np.arange(n)[:, None], ind), val)
    else:
        X = Xd = rng.normal(size=(n, d)).astype(np.float32)
    w_true = (rng.normal(size=d) * 0.5).astype(np.float32)
    margin = Xd @ w_true
    if task is TaskType.LOGISTIC_REGRESSION:
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(
            np.float32)
    else:
        y = (margin + rng.normal(size=n) * 0.3).astype(np.float32)
    wt = rng.uniform(0.5, 2.0, n).astype(np.float32)
    off = (rng.normal(size=n) * 0.1).astype(np.float32)
    return make_batch(X, y, wt, off)


TASKS = [TaskType.LOGISTIC_REGRESSION, TaskType.LINEAR_REGRESSION]


class TestChunkedContainers:
    def test_chunk_batch_shapes_and_padding(self, rng):
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, n=1000)
        cb = chunk_batch(batch, 256)
        assert cb.n == 1000
        assert cb.n_chunks == 4  # ceil(1000/256)
        assert cb.chunk_rows == 256
        assert cb.X.n_padded == 1024
        # padding rows are weight-0, so no reduction can see them
        assert (cb.weights[1000:] == 0.0).all()
        assert (cb.y[1000:] == 0.0).all()
        # chunks are HOST numpy — the whole point of the regime
        for c in cb.X.chunks:
            assert isinstance(c, np.ndarray)
        # concatenating the chunks reproduces the dataset
        np.testing.assert_array_equal(
            np.concatenate(cb.X.chunks)[:1000], np.asarray(batch.X))

    def test_iter_device_yields_device_chunks(self, rng):
        cb = chunk_batch(_problem(rng, TaskType.LOGISTIC_REGRESSION, n=600),
                         200)
        seen = []
        for i, b in cb.iter_device():
            seen.append(i)
            assert isinstance(b.X, jax.Array)
            assert b.X.shape == (200, 10)
        assert seen == [0, 1, 2]

    def test_sparse_chunking(self, rng):
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, n=700,
                         sparse=True)
        cb = chunk_batch(batch, 256)
        assert all(isinstance(c, SparseRows) for c in cb.X.chunks)
        assert all(isinstance(c.indices, np.ndarray) for c in cb.X.chunks)
        assert cb.X.n_features == 10

    def test_hybrid_rejected(self, rng):
        from photon_tpu.data.dataset import chunk_matrix
        from photon_tpu.data.matrix import to_hybrid

        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, n=128,
                         sparse=True)
        H = to_hybrid(jax.device_get(batch.X), d_dense=4)
        with pytest.raises(TypeError, match="host-chunked"):
            chunk_matrix(H, 64)


class TestChunkPartials:
    @pytest.mark.parametrize("task", TASKS)
    def test_partials_match_value_and_grad(self, rng, task):
        """Accumulated chunk partials == the resident single-pass (f, g):
        the treeAggregate leaf is exact, not approximate."""
        batch = _problem(rng, task, n=1024)
        cb = chunk_batch(batch, 256)
        obj = Objective(task, l2=0.4)
        w = jnp.asarray(rng.normal(size=10).astype(np.float32) * 0.3)
        f_r, g_r = obj.value_and_grad(w, batch)
        acc = None
        for i, b in cb.iter_device():
            _, parts = (obj.chunk_value_grad_partials(w, b))
            acc = parts if acc is None else obj.add_partials(acc, parts)
        f_s, g_s = obj.finish_value_grad(w, acc)
        np.testing.assert_allclose(f_r, f_s, rtol=1e-5)
        np.testing.assert_allclose(g_r, g_s, rtol=1e-4, atol=1e-4)

    def test_phi_partials_match_margin_api(self, rng):
        """chunk_phi_partials over chunks + ray coefficients ==
        Objective.phi_at on the full batch."""
        task = TaskType.LOGISTIC_REGRESSION
        batch = _problem(rng, task, n=1024)
        cb = chunk_batch(batch, 256)
        obj = Objective(task, l2=0.2)
        w = jnp.asarray(rng.normal(size=10).astype(np.float32) * 0.3)
        p = jnp.asarray(rng.normal(size=10).astype(np.float32))
        z = obj.margin(w, batch)
        dz = obj.direction_margin(p, batch)
        a = 0.37
        f_r, d_r = obj.phi_at(z, dz, a, w, p, batch)
        wl = wd = 0.0
        for i, b in cb.iter_device():
            zc = obj.margin(w, b)
            dzc = obj.direction_margin(p, b)
            wl_i, wd_i = obj.chunk_phi_partials(zc, dzc, a, b.y, b.weights)
            wl, wd = wl + wl_i, wd + wd_i
        c0, c1, c2 = obj.ray_reg_coeffs(w, p)
        f_s = wl + c0 + a * (c1 + 0.5 * a * c2)
        d_s = wd + c1 + a * c2
        np.testing.assert_allclose(f_r, f_s, rtol=1e-5)
        np.testing.assert_allclose(d_r, d_s, rtol=1e-4, atol=1e-5)


class TestStreamedSolvers:
    @pytest.mark.parametrize("task", TASKS)
    def test_lbfgs_matches_resident(self, rng, task):
        batch = _problem(rng, task)
        cb = chunk_batch(batch, 300)  # uneven tail chunk on purpose
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7, reg=l2(),
                              reg_weight=0.5)
        m_r, r_r = train_glm(batch, task, cfg)
        m_s, r_s = train_glm(cb, task, cfg)
        assert bool(r_s.converged) == bool(r_r.converged)
        np.testing.assert_allclose(float(r_s.value), float(r_r.value),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m_s.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=2e-5)

    @pytest.mark.parametrize("task", TASKS)
    def test_owlqn_matches_resident(self, rng, task):
        batch = _problem(rng, task)
        cb = chunk_batch(batch, 300)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7,
                              reg=elastic_net(0.5), reg_weight=0.3)
        m_r, r_r = train_glm(batch, task, cfg)
        m_s, r_s = train_glm(cb, task, cfg)
        np.testing.assert_allclose(float(r_s.value), float(r_r.value),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m_s.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=2e-4)

    def test_pure_l1_sparsity_preserved(self, rng):
        """Streamed OWL-QN keeps the orthant projection's exact zeros."""
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION)
        cb = chunk_batch(batch, 512)
        cfg = OptimizerConfig(max_iters=60, tolerance=1e-7, reg=l1(),
                              reg_weight=8.0)
        m_r, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
        m_s, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)
        zeros_r = np.asarray(m_r.coefficients.means) == 0.0
        zeros_s = np.asarray(m_s.coefficients.means) == 0.0
        assert zeros_s.any()  # the weight is strong enough to zero coords
        np.testing.assert_array_equal(zeros_r, zeros_s)

    def test_sparse_rows_streamed(self, rng):
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION, sparse=True)
        cb = chunk_batch(batch, 512)
        cfg = OptimizerConfig(max_iters=50, tolerance=1e-7, reg=l2(),
                              reg_weight=0.3)
        m_r, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
        m_s, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)
        np.testing.assert_allclose(np.asarray(m_s.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=2e-5)

    def test_single_chunk_degenerates_to_resident(self, rng):
        """chunk_rows >= n: one chunk, still the streamed code path."""
        batch = _problem(rng, TaskType.LINEAR_REGRESSION, n=500)
        cb = chunk_batch(batch, 4096)
        assert cb.n_chunks == 1
        cfg = OptimizerConfig(max_iters=40, tolerance=1e-7, reg=l2(),
                              reg_weight=0.2)
        m_r, _ = train_glm(batch, TaskType.LINEAR_REGRESSION, cfg)
        m_s, _ = train_glm(cb, TaskType.LINEAR_REGRESSION, cfg)
        np.testing.assert_allclose(np.asarray(m_s.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=1e-3, atol=1e-5)

    def test_normalization_round_trip(self, rng):
        from photon_tpu.data.normalization import (
            NormalizationContext,
            NormalizationType,
        )

        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION)
        Xh = np.asarray(batch.X)
        norm = NormalizationContext.build(
            Xh, NormalizationType.SCALE_WITH_STANDARD_DEVIATION)
        cb = chunk_batch(batch, 512)
        cfg = OptimizerConfig(max_iters=50, tolerance=1e-7, reg=l2(),
                              reg_weight=0.2)
        m_r, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                           normalization=norm)
        m_s, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg,
                           normalization=norm)
        # atol covers near-zero coordinates, where the normalization
        # unfold amplifies f32 accumulation-order noise
        np.testing.assert_allclose(np.asarray(m_s.coefficients.means),
                                   np.asarray(m_r.coefficients.means),
                                   rtol=2e-3, atol=1e-4)

    def test_host_chunks_stay_numpy(self, rng):
        """The peak-device-memory contract's observable: after a full
        streamed solve the dataset is still host numpy — nothing pinned
        it to the device."""
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION)
        cb = chunk_batch(batch, 256)
        cfg = OptimizerConfig(max_iters=20, tolerance=1e-7, reg=l2(),
                              reg_weight=0.5)
        train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)
        for c in cb.X.chunks:
            assert isinstance(c, np.ndarray)
        assert isinstance(cb.y, np.ndarray)

    def test_chunked_scoring_matches_resident(self, rng):
        batch = _problem(rng, TaskType.LOGISTIC_REGRESSION)
        cb = chunk_batch(batch, 300)
        cfg = OptimizerConfig(max_iters=30, tolerance=1e-7, reg=l2(),
                              reg_weight=0.5)
        m_s, _ = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)
        scores_chunked = np.asarray(m_s.score(cb.X))
        scores_resident = np.asarray(m_s.score(batch.X))
        assert scores_chunked.shape == (batch.n,)
        np.testing.assert_allclose(scores_chunked, scores_resident,
                                   rtol=1e-5, atol=1e-5)

    def test_tron_rejected(self, rng):
        cb = chunk_batch(_problem(rng, TaskType.LOGISTIC_REGRESSION, n=256),
                         128)
        cfg = OptimizerConfig(optimizer=OptimizerType.TRON, reg=l2(),
                              reg_weight=0.1)
        with pytest.raises(ValueError, match="TRON"):
            train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg)

    def test_grid_rejected_mesh_dispatches(self, rng, mesh8):
        """The lane grid still refuses ChunkedBatch (every lane would
        multiply the host stream), but a mesh now DISPATCHES to the
        sharded streamed solve (tests/test_streamed_mesh.py pins its
        parity) instead of raising."""
        cb = chunk_batch(_problem(rng, TaskType.LOGISTIC_REGRESSION, n=256),
                         128)
        cfg = OptimizerConfig(max_iters=10, reg=l2(), reg_weight=0.1)
        with pytest.raises(ValueError, match="sequential"):
            train_glm_grid(cb, TaskType.LOGISTIC_REGRESSION, cfg,
                           [0.1, 1.0])
        model, res = train_glm(cb, TaskType.LOGISTIC_REGRESSION, cfg,
                               mesh=mesh8)
        assert np.isfinite(np.asarray(model.coefficients.means)).all()


# ------------------------------------------------------------------ driver
def _write_game_parts(root, n_files=2, rows_per_file=260, seed=0):
    from photon_tpu.data.avro_io import write_avro
    from photon_tpu.data.ingest import training_example_schema

    rng = np.random.default_rng(seed)
    schema = training_example_schema(feature_bags=("global", "puser"),
                                     entity_fields=("userId",))
    os.makedirs(root, exist_ok=True)
    for fi in range(n_files):
        records = []
        for i in range(rows_per_file):
            age = float(rng.normal())
            ctr = float(rng.normal(2.0, 3.0))
            u = int(rng.integers(0, 9))
            margin = 1.1 * age - 0.3 * (ctr - 2.0) + 0.2 * (u - 4)
            y = float(rng.uniform() < 1 / (1 + np.exp(-margin)))
            records.append({
                "response": y, "offset": None, "weight": None,
                "uid": f"r{fi}_{i}", "userId": f"u{u}",
                "global": [
                    {"name": "age", "term": "", "value": age},
                    {"name": "ctr", "term": "", "value": ctr},
                ],
                "puser": [{"name": "bias", "term": "", "value": 1.0}],
            })
        write_avro(root / f"part-{fi:03d}.avro", records, schema,
                   block_records=64)
    return root


_SHARDS = {
    "fixedShard": {"bags": ["global"], "has_intercept": True},
    "userShard": {"bags": ["puser"], "has_intercept": False},
}
_COORDS = {
    "fixed": {"feature_shard": "fixedShard", "reg_type": "l2",
              "reg_weight": 0.5, "max_iters": 40},
    "perUser": {"feature_shard": "userShard", "entity_name": "userId",
                "reg_type": "l2", "reg_weight": 2.0, "max_iters": 20},
}


@pytest.fixture(scope="module")
def streamed_job(tmp_path_factory):
    root = tmp_path_factory.mktemp("streamed_job")
    _write_game_parts(root / "train", seed=1)
    _write_game_parts(root / "val", n_files=1, rows_per_file=150, seed=2)
    return root


def _params(root, out, **kw):
    from photon_tpu.drivers import TrainingParams

    base = dict(
        train_path=str(root / "train"),
        validation_path=str(root / "val"),
        output_dir=str(out),
        feature_shards=_SHARDS,
        coordinates=_COORDS,
        entity_fields=["userId"],
        n_sweeps=2,
    )
    base.update(kw)
    return TrainingParams(**base)


class TestStreamedDriver:
    def test_forced_streamed_matches_resident(self, streamed_job, tmp_path):
        """The mixed-residency GAME fit (fixed shard host-chunked, RE shard
        resident) converges to the resident driver's model."""
        from photon_tpu.drivers import run_training

        a = run_training(_params(streamed_job, tmp_path / "resident",
                                 streaming=False, streamed_objective=False))
        b = run_training(_params(streamed_job, tmp_path / "streamed",
                                 streamed_objective=True,
                                 objective_chunk_rows=128,
                                 streaming_chunk_rows=128))
        assert b.best.validation_score == pytest.approx(
            a.best.validation_score, abs=5e-3)
        wa = np.asarray(
            a.best.model.coordinates["fixed"].model.coefficients.means)
        wb = np.asarray(
            b.best.model.coordinates["fixed"].model.coefficients.means)
        np.testing.assert_allclose(wb, wa, rtol=5e-3, atol=5e-4)
        np.testing.assert_allclose(
            np.asarray(b.best.model.coordinates["perUser"].coefficients),
            np.asarray(a.best.model.coordinates["perUser"].coefficients),
            rtol=5e-3, atol=5e-4)

    def test_forced_streamed_with_mesh_matches_resident(
            self, streamed_job, tmp_path, mesh8):
        """The whole driver pipeline with a mesh + streamed objective: the
        fixed shard's chunks row-shard across the mesh (the pod-scale
        treeAggregate), RE shards stay resident, and the fit matches the
        resident single-device driver."""
        from photon_tpu.drivers import run_training

        a = run_training(_params(streamed_job, tmp_path / "resident",
                                 streaming=False, streamed_objective=False))
        b = run_training(_params(streamed_job, tmp_path / "mesh_streamed",
                                 streamed_objective=True,
                                 objective_chunk_rows=100,
                                 streaming_chunk_rows=128), mesh=mesh8)
        assert b.best.validation_score == pytest.approx(
            a.best.validation_score, abs=5e-3)
        wa = np.asarray(
            a.best.model.coordinates["fixed"].model.coefficients.means)
        wb = np.asarray(
            b.best.model.coordinates["fixed"].model.coefficients.means)
        np.testing.assert_allclose(wb, wa, rtol=5e-3, atol=5e-4)

    def test_auto_trip_on_tiny_budget(self, streamed_job, tmp_path,
                                      monkeypatch):
        """streamed_objective=None + an HBM budget smaller than the data
        estimate engages the out-of-HBM read (and the fixed shard really is
        host-chunked inside the fit)."""
        import photon_tpu.data.streaming as streaming_mod
        from photon_tpu.drivers import run_training

        captured = {}
        real = streaming_mod.stream_to_host

        def spy(*a, **kw):
            data, n_real = real(*a, **kw)
            captured["shards"] = data.shards
            return data, n_real

        monkeypatch.setattr(streaming_mod, "stream_to_host", spy)
        out = run_training(_params(
            streamed_job, tmp_path / "auto", streamed_objective=None,
            hbm_budget_bytes=1024,  # far below the ~520-row dataset
            streaming=True, objective_chunk_rows=100))
        assert np.isfinite(out.best.validation_score)
        assert isinstance(captured["shards"]["fixedShard"], ChunkedMatrix)
        assert captured["shards"]["fixedShard"].n_chunks >= 2
        # the RE shard must stay resident (bucketing gathers rows)
        assert not isinstance(captured["shards"]["userShard"], ChunkedMatrix)

    def test_big_budget_stays_resident(self, streamed_job, tmp_path,
                                       monkeypatch):
        import photon_tpu.data.streaming as streaming_mod
        from photon_tpu.drivers import run_training

        calls = []
        real = streaming_mod.stream_to_host

        def spy(*a, **kw):
            calls.append(1)
            return real(*a, **kw)

        monkeypatch.setattr(streaming_mod, "stream_to_host", spy)
        run_training(_params(streamed_job, tmp_path / "big",
                             streamed_objective=None,
                             hbm_budget_bytes=1 << 40, streaming=True))
        assert not calls
