"""Native C++ layer: hash store, PalDB index map, Avro block decoder.

Every test asserts exact agreement with the pure-Python implementations —
the native layer is a fast path, never a semantic fork. Skipped wholesale
when the toolchain is unavailable (callers fall back the same way).
"""
import numpy as np
import pytest

from photon_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")

from photon_tpu.data.avro_io import read_avro, write_avro  # noqa: E402
from photon_tpu.data.feature_bags import FeatureShardConfig  # noqa: E402
from photon_tpu.data.index_map import (  # noqa: E402
    INTERCEPT_KEY,
    IndexMap,
    PalDBIndexMap,
    feature_key,
)
from photon_tpu.data.ingest import (  # noqa: E402
    GameDataConfig,
    read_game_data,
    records_to_game_data,
    training_example_schema,
)
from photon_tpu.data.matrix import SparseRows  # noqa: E402


class TestNativeStore:
    def test_insert_get_roundtrip(self, rng):
        s = native.NativeIndexStore(capacity_hint=8)
        keys = [f"f{i}\x01t{i % 7}" for i in range(500)]
        ids = s.insert_batch(keys)
        np.testing.assert_array_equal(ids, np.arange(500))
        assert len(s) == 500
        np.testing.assert_array_equal(s.lookup_batch(keys), np.arange(500))
        assert s.get("missing") == -1
        # re-insert returns existing ids
        np.testing.assert_array_equal(s.insert_batch(keys[:10]),
                                      np.arange(10))

    def test_save_open_mmap(self, tmp_path):
        s = native.NativeIndexStore.from_keys(["a", "b\x01t", "c"])
        p = tmp_path / "store.phidx"
        s.save(p)
        s2 = native.NativeIndexStore.open(p)
        assert len(s2) == 3
        assert s2.get("b\x01t") == 1
        assert s2.keys_in_order() == ["a", "b\x01t", "c"]
        # mapped stores are frozen: insert degrades to lookup
        assert s2.insert("nope") == -1

    def test_paldb_matches_index_map(self, tmp_path):
        imap = IndexMap()
        for i in range(100):
            imap.index_of(feature_key(f"f{i}", f"t{i % 3}"))
        imap.index_of(INTERCEPT_KEY)
        imap.freeze()
        pal = PalDBIndexMap.build(imap)
        assert pal.n_features == imap.n_features
        assert pal.intercept_id == imap.intercept_id
        for k in imap.keys_in_order():
            assert pal.get(k) == imap.get(k)
        assert pal.get("absent") == IndexMap.NULL_ID
        p = tmp_path / "pal.bin"
        pal.save(p)
        pal2 = PalDBIndexMap.open(p)
        assert pal2.keys_in_order() == imap.keys_in_order()
        assert pal2.to_index_map().key_to_id == imap.key_to_id


def _fixture_records(rng, n=200):
    recs = []
    for i in range(n):
        feats = [{"name": f"f{j}", "term": ("" if j % 3 == 0 else f"t{j % 5}"),
                  "value": float(rng.normal())}
                 for j in rng.choice(30, size=rng.integers(1, 8),
                                     replace=False)]
        recs.append({
            "response": float(i % 2),
            "offset": None if i % 4 else 0.25,
            "weight": None if i % 3 else 2.0,
            "uid": f"u{i}",
            "userId": f"user{i % 11}",
            "features": feats,
            "ctx": [{"name": "c", "term": "", "value": 1.0 + i}],
        })
    return recs


@pytest.fixture
def avro_file(tmp_path, rng):
    schema = training_example_schema(feature_bags=("features", "ctx"),
                                     entity_fields=("userId",))
    recs = _fixture_records(rng)
    path = tmp_path / "train.avro"
    write_avro(path, recs, schema, codec="deflate", block_records=64)
    return path


@pytest.fixture
def gd_config():
    return GameDataConfig(
        shards={"global": FeatureShardConfig(bags=("features", "ctx")),
                # bag order REVERSED vs the schema's field order: id
                # assignment must still follow config order, like the
                # Python build_index_map loop.
                "rev": FeatureShardConfig(bags=("ctx", "features")),
                "per_user": FeatureShardConfig(bags=("ctx",),
                                               has_intercept=False)},
        entity_fields=("userId",),
    )


def _assert_same(gd_n, maps_n, gd_p, maps_p):
    np.testing.assert_array_equal(gd_n.y, gd_p.y)
    np.testing.assert_array_equal(gd_n.weights, gd_p.weights)
    np.testing.assert_array_equal(gd_n.offsets, gd_p.offsets)
    assert set(gd_n.shards) == set(gd_p.shards)
    for s in gd_p.shards:
        assert maps_n[s].keys_in_order() == maps_p[s].keys_in_order()
        Xn, Xp = gd_n.shards[s], gd_p.shards[s]
        if isinstance(Xp, SparseRows):
            np.testing.assert_array_equal(np.asarray(Xn.indices),
                                          np.asarray(Xp.indices))
            np.testing.assert_allclose(np.asarray(Xn.values),
                                       np.asarray(Xp.values), rtol=1e-6)
        else:
            np.testing.assert_allclose(np.asarray(Xn), np.asarray(Xp),
                                       rtol=1e-6)
    for e in gd_p.entity_ids:
        np.testing.assert_array_equal(gd_n.entity_ids[e], gd_p.entity_ids[e])


class TestNativeIngest:
    def test_matches_python_build_mode(self, avro_file, gd_config):
        gd_p, maps_p = read_game_data(avro_file, gd_config, use_native=False)
        gd_n, maps_n = read_game_data(avro_file, gd_config, use_native=True)
        _assert_same(gd_n, maps_n, gd_p, maps_p)

    def test_matches_python_frozen_mode(self, avro_file, gd_config):
        _, maps = read_game_data(avro_file, gd_config, use_native=False)
        gd_p, _ = read_game_data(avro_file, gd_config, index_maps=maps,
                                 use_native=False)
        gd_n, _ = read_game_data(avro_file, gd_config, index_maps=maps,
                                 use_native=True)
        _assert_same(gd_n, maps, gd_p, maps)

    def test_unplannable_schema_falls_back(self, tmp_path, gd_config, rng):
        # Unconsumed fields of any shape skip natively, and consumed
        # scalars accept wide unions with ONE numeric branch (round 5);
        # what remains unplannable is an AMBIGUOUS consumed union — two
        # numeric branches — where picking one would silently drop the
        # other's values. Native returns None and
        # read_game_data(use_native=True) raises.
        schema = training_example_schema(feature_bags=("features", "ctx"),
                                         entity_fields=("userId",))
        for f in schema["fields"]:
            if f["name"] == "response":
                f["type"] = ["null", "double", "float"]
        recs = _fixture_records(rng, 10)
        path = tmp_path / "odd.avro"
        write_avro(path, recs, schema)
        with pytest.raises(RuntimeError):
            read_game_data(path, gd_config, use_native=True)
        gd, _ = read_game_data(path, gd_config)  # auto-fallback works
        assert gd.y.shape == (10,)

    def test_corrupt_block_raises_not_crashes(self, tmp_path, gd_config, rng):
        """Bit-flipped/truncated payloads must surface as ValueError from the
        C++ decoder's bounds checks — never an out-of-bounds read (the
        varint length guard in photon_native.cc read_str/read_long)."""
        from photon_tpu.data.avro_io import AvroContainerReader
        from photon_tpu.data.native_ingest import read_game_data_native

        schema = training_example_schema(feature_bags=("features", "ctx"),
                                         entity_fields=("userId",))
        recs = _fixture_records(rng, 50)
        path = tmp_path / "ok.avro"
        write_avro(path, recs, schema, codec="null", block_records=50)
        raw = bytearray(path.read_bytes())
        rd = AvroContainerReader(path)
        # Corrupt bytes inside the data block (after header+sync): flip a
        # spread of payload bytes so varint string lengths go haywire.
        start = rd._data_offset + 8
        for off in range(start, min(start + 2000, len(raw) - 20), 37):
            raw[off] ^= 0xFF
        bad = tmp_path / "bad.avro"
        bad.write_bytes(bytes(raw))
        with pytest.raises((ValueError, EOFError)):
            read_game_data_native(bad, gd_config)

    def test_truncated_block_raises(self, tmp_path, gd_config, rng):
        schema = training_example_schema(feature_bags=("features", "ctx"),
                                         entity_fields=("userId",))
        recs = _fixture_records(rng, 50)
        path = tmp_path / "ok.avro"
        write_avro(path, recs, schema, codec="null", block_records=50)
        raw = path.read_bytes()
        bad = tmp_path / "trunc.avro"
        bad.write_bytes(raw[:len(raw) - len(raw) // 3])
        from photon_tpu.data.native_ingest import read_game_data_native

        with pytest.raises((ValueError, EOFError)):
            read_game_data_native(bad, gd_config)

    def test_null_codec_and_dir_input(self, tmp_path, gd_config, rng):
        schema = training_example_schema(feature_bags=("features", "ctx"),
                                         entity_fields=("userId",))
        recs = _fixture_records(rng, 120)
        d = tmp_path / "data"
        d.mkdir()
        write_avro(d / "part-0.avro", recs[:50], schema, codec="null")
        write_avro(d / "part-1.avro", recs[50:], schema, codec="null")
        gd_p, maps_p = read_game_data(d, gd_config, use_native=False)
        gd_n, maps_n = read_game_data(d, gd_config, use_native=True)
        _assert_same(gd_n, maps_n, gd_p, maps_p)


class TestWidenedPlanner:
    """Round-4 planner widening: unconsumed fields of ANY shape skip
    natively (generic skip programs), scalars/entities accept more union
    shapes, and map-typed feature bags decode natively. Each case pins
    native == pure-Python exactly."""

    def _parity(self, tmp_path, schema, recs, config):
        path = tmp_path / "wide.avro"
        write_avro(path, recs, schema, block_records=64)
        gd_n, maps_n = read_game_data(path, config, use_native=True)
        gd_p, maps_p = read_game_data(path, config, use_native=False)
        _assert_same(gd_n, maps_n, gd_p, maps_p)
        return gd_n

    def test_exotic_unconsumed_fields_stay_native(self, tmp_path, rng,
                                                  gd_config):
        """Nested records, wide unions, enums, fixed, maps, arrays of
        records — all UNCONSUMED — no longer knock the job off the native
        road (the round-3 ~10-20x cliff)."""
        schema = training_example_schema(feature_bags=("features", "ctx"),
                                         entity_fields=("userId",))
        schema["fields"] += [
            {"name": "meta", "type": {
                "type": "record", "name": "Meta", "fields": [
                    {"name": "a", "type": "long"},
                    {"name": "b", "type": ["null", "string", "double"]},
                    {"name": "inner", "type": {
                        "type": "record", "name": "Inner", "fields": [
                            {"name": "xs", "type": {"type": "array",
                                                    "items": "double"}},
                        ]}},
                ]}},
            {"name": "tags", "type": {"type": "map", "values": "string"}},
            {"name": "kind", "type": {"type": "enum", "name": "Kind",
                                      "symbols": ["A", "B", "C"]}},
            {"name": "blob", "type": {"type": "fixed", "name": "Blob",
                                      "size": 6}},
            {"name": "flag", "type": "boolean"},
        ]
        recs = [dict(r,
                     meta={"a": i, "b": ("s" if i % 3 == 0 else
                                         (None if i % 3 == 1 else 2.5)),
                           "inner": {"xs": [1.0] * (i % 4)}},
                     tags={f"t{j}": "v" for j in range(i % 3)},
                     kind="ABC"[i % 3],
                     blob=b"\x01\x02\x03\x04\x05\x06",
                     flag=bool(i % 2))
                for i, r in enumerate(_fixture_records(rng, 120))]
        # _parity forces use_native=True, which raises if the plan is
        # refused — native engagement is asserted by construction
        self._parity(tmp_path, schema, recs, gd_config)

    def test_map_typed_feature_bag(self, tmp_path, rng):
        """map<string,double> feature bags decode natively; map key =
        feature name, empty term (reference: makeFeatures handles both
        bag field shapes)."""
        schema = training_example_schema(feature_bags=("features",),
                                         entity_fields=("userId",))
        for f in schema["fields"]:
            if f["name"] == "features":
                f["type"] = {"type": "map", "values": "double"}
        rng2 = np.random.default_rng(5)
        recs = [{
            "response": float(i % 2), "offset": None, "weight": None,
            "uid": f"u{i}", "userId": f"user{i % 7}",
            "features": {f"m{int(j)}": float(rng2.normal())
                         for j in rng2.choice(25, size=4, replace=False)},
        } for i in range(150)]
        config = GameDataConfig(
            shards={"all": FeatureShardConfig(bags=("features",))},
            entity_fields=("userId",))
        gd = self._parity(tmp_path, schema, recs, config)
        assert gd.y.shape == (150,)

    def test_widened_scalar_and_entity_shapes(self, tmp_path, rng):
        """float response, [long,null] weight, plain-string entity — all
        consumed natively now."""
        schema = training_example_schema(feature_bags=("features",),
                                         entity_fields=("userId",))
        for f in schema["fields"]:
            if f["name"] == "response":
                f["type"] = "float"
            elif f["name"] == "weight":
                f["type"] = ["long", "null"]
            elif f["name"] == "userId":
                f["type"] = "string"
        recs = []
        for i, r in enumerate(_fixture_records(rng, 100)):
            r = dict(r, response=float(i % 2), weight=(i % 5) or None)
            del r["ctx"]
            recs.append(r)
        config = GameDataConfig(
            shards={"all": FeatureShardConfig(bags=("features",))},
            entity_fields=("userId",))
        self._parity(tmp_path, schema, recs, config)


def test_deeply_nested_skip_refuses_at_plan_time():
    """Schemas nested past the C++ VM's recursion guard must refuse at
    PLAN time (Python fallback), never mid-decode on valid data."""
    from photon_tpu.data.native_ingest import compile_plan

    t = "double"
    for i in range(70):
        t = {"type": "record", "name": f"N{i}",
             "fields": [{"name": "x", "type": t}]}
    schema = training_example_schema(feature_bags=("features",))
    schema["fields"].append({"name": "deep", "type": t})
    cfg = GameDataConfig(
        shards={"all": FeatureShardConfig(bags=("features",))})
    assert compile_plan(schema, cfg) is None
    # one level inside the guard still plans
    t2 = "double"
    for i in range(30):
        t2 = {"type": "record", "name": f"M{i}",
              "fields": [{"name": "x", "type": t2}]}
    schema2 = training_example_schema(feature_bags=("features",))
    schema2["fields"].append({"name": "deep", "type": t2})
    assert compile_plan(schema2, cfg) is not None


class TestExoticConsumedShapes:
    """Round-5 planner widening: CONSUMED fields in exotic shapes decode
    natively — union-wrapped bags, 3+-branch scalar/entity unions,
    long/int bag values — each pinned native == pure-Python (the last
    ~10x ingest cliff: one odd consumed column used to drop the whole job
    to the Python record decoder)."""

    def _schema(self):
        ntv_int = {"type": "record", "name": "NTVInt", "fields": [
            {"name": "name", "type": "string"},
            {"name": "term", "type": "string"},
            {"name": "value", "type": "int"}]}
        return {"type": "record", "name": "Exotic", "fields": [
            # 3-branch scalar union: one numeric branch + null + skippable
            {"name": "response", "type": "double"},
            {"name": "offset", "type": ["null", "double"], "default": None},
            {"name": "weight",
             "type": ["null", "long", "string"], "default": None},
            # entity behind a wide union (data only uses string/null)
            {"name": "userId",
             "type": ["null", "string", {"type": "array", "items": "int"}],
             "default": None},
            # [null, array<NTV-with-int-values>]
            {"name": "features", "type": ["null", {"type": "array",
                                                   "items": ntv_int}],
             "default": None},
            # [map<string, long>, null] — reversed branch order
            {"name": "ctx",
             "type": [{"type": "map", "values": "long"}, "null"]},
        ]}

    def _records(self, rng, n=120):
        recs = []
        for i in range(n):
            feats = (None if i % 7 == 0 else
                     [{"name": f"f{int(j)}", "term": "t" if j % 2 else "",
                       "value": int(rng.integers(-5, 6))}
                      for j in rng.choice(20, size=rng.integers(1, 5),
                                          replace=False)])
            ctx = (None if i % 5 == 3 else
                   {f"c{int(v)}": int(v) * 2 for v in
                    rng.integers(0, 8, size=2)})
            # populate the NON-consumed union branches too: a string
            # weight and an array userId must read as ABSENT on both
            # decoders (the shared wide-union semantic)
            weight = ("heavy" if i % 17 == 4
                      else None if i % 3 else int(2 + i % 4))
            user = ([1, 2, 3] if i % 19 == 6
                    else None if i % 11 == 5 else f"user{i % 9}")
            recs.append({
                "response": float(i % 2),
                "offset": None if i % 4 else 0.5,
                "weight": weight,
                "userId": user,
                "features": feats, "ctx": ctx,
            })
        return recs

    def test_parity_and_cliff_closed(self, tmp_path, rng):
        from photon_tpu.data.native_ingest import compile_plan

        config = GameDataConfig(
            shards={"all": FeatureShardConfig(bags=("features", "ctx"))},
            entity_fields=("userId",),
            optional_entity_fields=("userId",),
        )
        schema = self._schema()
        assert compile_plan(schema, config) is not None  # stays native
        recs = self._records(np.random.default_rng(3))
        path = tmp_path / "exotic.avro"
        write_avro(path, recs, schema, block_records=32)
        gd_n, maps_n = read_game_data(path, config, use_native=True)
        gd_p, maps_p = read_game_data(path, config, use_native=False)
        _assert_same(gd_n, maps_n, gd_p, maps_p)
        # spot-check semantics beyond parity: weight long consumed, null
        # weight defaults to 1, absent uid folded to ""
        w = np.asarray(gd_n.weights)
        assert set(np.unique(w)).issubset({1.0, 2.0, 3.0, 4.0, 5.0})
        assert (np.asarray(gd_n.entity_ids["userId"]) == "").any()

    def test_streaming_matches_one_shot(self, tmp_path, rng):
        from photon_tpu.data.streaming import (build_index_maps_streaming,
                                               iter_game_chunks)

        config = GameDataConfig(
            shards={"all": FeatureShardConfig(bags=("features", "ctx"))},
            entity_fields=("userId",),
            optional_entity_fields=("userId",),
        )
        schema = self._schema()
        recs = self._records(np.random.default_rng(4), n=200)
        path = tmp_path / "exotic_stream.avro"
        write_avro(path, recs, schema, block_records=32)
        one, _ = read_game_data(path, config, use_native=True)
        maps = build_index_maps_streaming(str(path), config)
        stream, chunks = iter_game_chunks(str(path), config, maps,
                                          chunk_rows=64, use_native=True)
        parts = list(chunks)
        assert len(parts) >= 2
        np.testing.assert_array_equal(
            np.concatenate([p.y for p in parts]), one.y)
        np.testing.assert_array_equal(
            np.concatenate([p.weights for p in parts]), one.weights)
        np.testing.assert_array_equal(
            np.concatenate([p.entity_ids["userId"] for p in parts]),
            one.entity_ids["userId"])

    def test_entity_union_numeric_branch_stays_python(self, tmp_path, rng):
        """An entity union with a NUMERIC branch is not natively
        consumable: Python stringifies numbers, so skipping that branch
        natively would diverge — compile_plan must refuse (the schema
        falls back whole) while plain long entity ids keep working on the
        Python path."""
        from photon_tpu.data.native_ingest import compile_plan

        config = GameDataConfig(
            shards={"all": FeatureShardConfig(bags=("features", "ctx"))},
            entity_fields=("userId",),
        )
        schema = self._schema()
        for f in schema["fields"]:
            if f["name"] == "userId":
                f["type"] = ["null", "string", "long"]
        assert compile_plan(schema, config) is None

        # plain long id column: Python-path behavior, numbers stringify
        schema2 = self._schema()
        for f in schema2["fields"]:
            if f["name"] == "userId":
                f["type"] = "long"
        assert compile_plan(schema2, config) is None
        recs = self._records(np.random.default_rng(5), n=40)
        for i, r in enumerate(recs):
            r["userId"] = i % 7
        path = tmp_path / "longid.avro"
        write_avro(path, recs, schema2, block_records=16)
        gd, _ = read_game_data(path, config, use_native=False)
        assert set(gd.entity_ids["userId"]) == {str(i) for i in range(7)}
