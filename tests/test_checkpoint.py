"""Elastic runs (photon_tpu/checkpoint): crash-consistent snapshot/restore
with deterministic fault injection.

THE acceptance property, in PR-5's bit-parity discipline: kill a streamed
(and streamed-mesh) GLM solve and a GAME run (straggler budgeting on, so
the pipelined block loop runs) at EVERY registered fault-injection site —
chunk upload, evaluation close, bucket retire, mid-snapshot-write, and
the commit rename itself — restore from the last committed snapshot, and
finish with coefficients EXACTLY equal (f64-compared) to the
uninterrupted run's. Plus the restore edge cases: mesh-8 snapshots onto
mesh-4/single-device, a NEWER snapshot schema refused with a clear error,
empty-history resume at iteration 0 == cold start, and the store-level
retention/async-writer/retry machinery.
"""
import json
import os

import numpy as np
import pytest

from photon_tpu import checkpoint
from photon_tpu.data.dataset import chunk_batch, make_batch
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig

pytestmark = pytest.mark.release_programs

TASK = TaskType.LOGISTIC_REGRESSION
# tolerance=0 forces the full iteration budget: the kill/restore matrix
# then exercises mid-run cuts, not an early-converged triviality
CFG = OptimizerConfig(max_iters=10, tolerance=0.0, reg=reg.l2(),
                      reg_weight=1e-2, history=4)
# the registered KILL sites (snapshot_io is a retry site, not a kill site)
KILL_SITES = ("chunk_upload", "evaluation", "snapshot_write", "commit")


def _stream_data(chunk_rows=32):
    rng = np.random.default_rng(0)
    n, d = 96, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return chunk_batch(make_batch(X, y), chunk_rows)


@pytest.fixture(scope="module")
def cb():
    return _stream_data()


def _solve(cb, mesh=None, cfg=CFG):
    _, res = train_glm(cb, TASK, cfg, mesh=mesh)
    return np.asarray(res.w, np.float64)


def _kill_then_resume(ckdir, run_fn, site, occ, async_writer=False):
    """Arm (site, occ), run; on the injected kill, resume from the last
    committed snapshot. Returns (final_w, was_killed)."""
    try:
        with checkpoint.session(str(ckdir), every_evals=1, every_s=None,
                                async_writer=async_writer):
            with checkpoint.fault_plan(
                    checkpoint.FaultPlan.kill_at(site, occ)):
                return run_fn(), False
    except checkpoint.InjectedFault:
        pass
    with checkpoint.session(str(ckdir), every_evals=1, every_s=None,
                            async_writer=async_writer):
        return run_fn(), True


def _occurrences(n):
    """First / middle / last — the spread each site is killed at."""
    return sorted({1, (n + 1) // 2, n})


# ------------------------------------------------------------- streamed GLM
class TestStreamedBitParity:
    def test_armed_but_unkilled_run_is_bit_identical(self, cb, tmp_path):
        """Checkpointing must observe, never perturb: a fully-armed run
        (snapshots every evaluation) equals the unarmed run bitwise."""
        w_ref = _solve(cb)
        with checkpoint.session(str(tmp_path / "ck"), every_evals=1,
                                every_s=None, async_writer=False):
            w_armed = _solve(cb)
        np.testing.assert_array_equal(w_ref, w_armed)

    def test_kill_every_site_resume_bit_identical(self, cb, tmp_path):
        """THE acceptance matrix (single chip): every kill site, killed at
        first/middle/last occurrence, restores and finishes bit-identical
        — including kills DURING a snapshot write and during the commit
        rename (restore falls back to the previous committed manifest)."""
        w_ref = _solve(cb)
        with checkpoint.session(str(tmp_path / "rec"), every_evals=1,
                                every_s=None, async_writer=False):
            with checkpoint.record_sites() as rec:
                _solve(cb)
        counts = dict(rec.hits)
        for site in KILL_SITES:
            assert counts.get(site, 0) > 0, f"site {site} never hit"
        for site in KILL_SITES:
            for occ in _occurrences(counts[site]):
                w, killed = _kill_then_resume(
                    tmp_path / f"{site}_{occ}", lambda: _solve(cb),
                    site, occ)
                assert killed, (site, occ)
                np.testing.assert_array_equal(
                    w_ref, w, err_msg=f"drift after kill at {site}#{occ}")

    def test_empty_history_resume_at_it0_equals_cold_start(self, cb,
                                                           tmp_path):
        """Kill right after the it=0 snapshot (before iteration 1
        completes): the restored state has an EMPTY curvature history and
        must replay the whole solve bit-identically to a cold start."""
        w_ref = _solve(cb)
        ckdir = tmp_path / "it0"
        # evaluation #1 is the initial pass (snapshotted at it=0);
        # evaluation #2 is iteration 1's direction pass — kill there
        w, killed = _kill_then_resume(ckdir, lambda: _solve(cb),
                                      "evaluation", 2)
        assert killed
        assert checkpoint.SnapshotStore(str(ckdir)).latest_seq() >= 0
        np.testing.assert_array_equal(w_ref, w)

    def test_async_writer_kill_resume(self, cb, tmp_path):
        """The production shape: snapshots committed on the writer
        thread. A kill mid-run still restores bit-identically, and the
        session close drains the queue."""
        w_ref = _solve(cb)
        w, killed = _kill_then_resume(tmp_path / "async",
                                      lambda: _solve(cb),
                                      "evaluation", 9, async_writer=True)
        assert killed
        np.testing.assert_array_equal(w_ref, w)

    def test_owlqn_streamed_kill_resume(self, cb, tmp_path):
        cfg = OptimizerConfig(max_iters=8, tolerance=0.0, reg=reg.l1(),
                              reg_weight=1e-3, history=4)
        w_ref = _solve(cb, cfg=cfg)
        w, killed = _kill_then_resume(tmp_path / "owlqn",
                                      lambda: _solve(cb, cfg=cfg),
                                      "evaluation", 5)
        assert killed
        np.testing.assert_array_equal(w_ref, w)


# ------------------------------------------------------------ streamed mesh
class TestStreamedMeshBitParity:
    def test_mesh_kill_every_site_resume_bit_identical(self, cb, tmp_path,
                                                       mesh8):
        """The mesh half of the acceptance matrix: every kill site —
        including mid-snapshot-write and mid-commit — restores onto the
        SAME mesh bit-identically."""
        w_ref = _solve(cb, mesh=mesh8)
        for site, occ in (("evaluation", 8), ("chunk_upload", 7),
                          ("snapshot_write", 3), ("commit", 3)):
            w, killed = _kill_then_resume(
                tmp_path / f"mesh_{site}", lambda: _solve(cb, mesh=mesh8),
                site, occ)
            assert killed, site
            np.testing.assert_array_equal(w_ref, w, err_msg=site)

    def test_mesh8_snapshot_restores_on_mesh4_and_single(self, cb,
                                                         tmp_path, mesh8):
        """Topology-changing restore: the margin caches re-shard through
        the canonical global row layout. Cross-topology f32 reduction
        order differs, so the guarantee is the same OPTIMUM, not the same
        bits (bit-parity is same-topology)."""
        from photon_tpu.parallel.mesh import make_mesh

        w_ref = _solve(cb, mesh=mesh8)
        for target, label in ((make_mesh(n_devices=4), "mesh4"),
                              (None, "single")):
            ckdir = tmp_path / f"reshard_{label}"
            try:
                with checkpoint.session(str(ckdir), every_evals=1,
                                        every_s=None, async_writer=False):
                    with checkpoint.fault_plan(
                            checkpoint.FaultPlan.kill_at("evaluation", 9)):
                        _solve(cb, mesh=mesh8)
            except checkpoint.InjectedFault:
                pass
            with checkpoint.session(str(ckdir), every_evals=1,
                                    every_s=None, async_writer=False):
                w = _solve(cb, mesh=target)
            assert checkpoint.SnapshotStore(str(ckdir)).latest_seq() >= 0
            np.testing.assert_allclose(w_ref, w, atol=5e-3, err_msg=label)


# -------------------------------------------------------------------- GAME
def _game_problem():
    from photon_tpu.game import (GameData, RandomEffectCoordinate,
                                 RandomEffectDataset)
    from photon_tpu.game.dataset import FixedEffectDataset
    from photon_tpu.game.fixed_effect import FixedEffectCoordinate

    rng = np.random.default_rng(3)
    E, d = 13, 4
    rows = rng.integers(3, 28, size=E)
    ent = np.repeat(np.arange(E), rows)
    rng.shuffle(ent)
    n = ent.shape[0]
    Xr = rng.normal(size=(n, d)).astype(np.float32)
    Xf = rng.normal(size=(n, 3)).astype(np.float32)
    w_re = rng.normal(size=(E, d)) * 1.5
    logit = np.einsum("nd,nd->n", Xr, w_re[ent]) + \
        Xf @ np.array([0.5, -0.3, 0.2])
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    data = GameData.build(y, {"s": Xr, "fx": Xf},
                          {"e": ent.astype(np.int64)})
    ds = RandomEffectDataset.build(data, "e", "s", max_blocks=2)
    cfg = OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=0.5,
                          history=4)

    def build_coords():
        fe_ds = FixedEffectDataset(X=data.shards["fx"], y=data.y,
                                   weights=data.weights, shard_name="fx")
        return {
            "fixed": FixedEffectCoordinate(fe_ds, TASK, cfg),
            # straggler budgeting ON: the fused one-dispatch path gates
            # itself off, so the pipelined train() block loop (the
            # checkpointed path) runs
            "re": RandomEffectCoordinate(ds, TASK, cfg, pipeline_depth=1,
                                         straggler_budget=8),
        }

    def run():
        from photon_tpu.game.coordinate_descent import coordinate_descent

        return coordinate_descent(build_coords(), data.y, data.weights,
                                  np.zeros(n, np.float32), TASK,
                                  n_sweeps=2)

    return run


def _game_w(out):
    return (np.asarray(out.model.coordinates["fixed"]
                       .model.coefficients.means, np.float64),
            np.asarray(out.model.coordinates["re"].coefficients,
                       np.float64))


class TestGameBitParity:
    def test_kill_every_site_resume_bit_identical(self, tmp_path):
        """The GAME acceptance matrix: straggler-budgeted random-effect
        training + a fused fixed coordinate, 2 sweeps; killed at EVERY
        bucket retirement plus mid-snapshot-write and mid-commit, each
        resume finishing bit-identically (coefficients AND objective
        history)."""
        run = _game_problem()
        ref = run()
        wf_ref, wr_ref = _game_w(ref)

        with checkpoint.session(str(tmp_path / "rec"), every_evals=1,
                                every_s=None, async_writer=False):
            with checkpoint.record_sites() as rec:
                armed = run()
        wf_a, wr_a = _game_w(armed)
        np.testing.assert_array_equal(wf_ref, wf_a)
        np.testing.assert_array_equal(wr_ref, wr_a)
        counts = dict(rec.hits)
        assert counts.get("bucket_retire", 0) >= 4  # 2 blocks x 2 sweeps

        matrix = [("bucket_retire", occ)
                  for occ in range(1, counts["bucket_retire"] + 1)]
        matrix += [("snapshot_write", _occurrences(
            counts["snapshot_write"])[1]),
            ("commit", _occurrences(counts["commit"])[1])]
        for site, occ in matrix:
            ckdir = tmp_path / f"{site}_{occ}"
            try:
                with checkpoint.session(str(ckdir), every_evals=1,
                                        every_s=None, async_writer=False):
                    with checkpoint.fault_plan(
                            checkpoint.FaultPlan.kill_at(site, occ)):
                        run()
                killed = False
            except checkpoint.InjectedFault:
                killed = True
            assert killed, (site, occ)
            with checkpoint.session(str(ckdir), every_evals=1,
                                    every_s=None, async_writer=False):
                out2 = run()
            wf2, wr2 = _game_w(out2)
            np.testing.assert_array_equal(
                wf_ref, wf2, err_msg=f"fixed drift at {site}#{occ}")
            np.testing.assert_array_equal(
                wr_ref, wr2, err_msg=f"re drift at {site}#{occ}")
            assert [float(v) for v in ref.objective_history] == \
                [float(v) for v in out2.objective_history], (site, occ)


# ----------------------------------------------------- store / state layer
class TestStoreAndState:
    def test_newer_schema_rejected_with_clear_error(self, cb, tmp_path):
        ckdir = tmp_path / "newer"
        with checkpoint.session(str(ckdir), every_evals=1, every_s=None,
                                async_writer=False):
            _solve(cb)
        mpath = ckdir / "MANIFEST.json"
        manifest = json.loads(mpath.read_text())
        manifest["schema"] = checkpoint.SCHEMA_VERSION + 1
        mpath.write_text(json.dumps(manifest))
        with pytest.raises(checkpoint.SnapshotSchemaError,
                           match="newer"):
            checkpoint.CheckpointSession(str(ckdir), async_writer=False)

    def test_state_shape_mismatch_rejected(self, cb, tmp_path):
        """A snapshot only fits the program that wrote it: re-chunking
        the dataset must be refused with the mismatch spelled out, not
        resumed into silent drift."""
        ckdir = tmp_path / "mismatch"
        try:
            with checkpoint.session(str(ckdir), every_evals=1,
                                    every_s=None, async_writer=False):
                with checkpoint.fault_plan(
                        checkpoint.FaultPlan.kill_at("evaluation", 5)):
                    _solve(cb)
        except checkpoint.InjectedFault:
            pass
        rechunked = _stream_data(chunk_rows=16)
        with checkpoint.session(str(ckdir), every_evals=1, every_s=None,
                                async_writer=False):
            with pytest.raises(checkpoint.SnapshotStateError,
                               match="chunk"):
                _solve(rechunked)

    def test_retention_keeps_newest(self, cb, tmp_path):
        ckdir = tmp_path / "gc"
        with checkpoint.session(str(ckdir), every_evals=1, every_s=None,
                                async_writer=False, keep=2):
            _solve(cb)
        snaps = sorted(d for d in os.listdir(ckdir)
                       if d.startswith("snap_"))
        assert 1 <= len(snaps) <= 2
        store = checkpoint.SnapshotStore(str(ckdir))
        assert f"snap_{store.latest_seq():08d}" == snaps[-1]

    def test_commit_bytes_kill_leaves_old_content(self, tmp_path):
        path = tmp_path / "blob"
        checkpoint.commit_bytes(str(path), b"generation-1")
        with pytest.raises(checkpoint.InjectedFault):
            with checkpoint.fault_plan(
                    checkpoint.FaultPlan.kill_at("commit", 1)):
                checkpoint.commit_bytes(str(path), b"generation-2")
        assert path.read_bytes() == b"generation-1"
        checkpoint.commit_bytes(str(path), b"generation-2")
        assert path.read_bytes() == b"generation-2"

    def test_retry_io_backoff_and_counters(self):
        from photon_tpu import telemetry

        delays = []
        run = telemetry.start_run("retry_test")
        try:
            with checkpoint.fault_plan(
                    checkpoint.FaultPlan(errors={"s": 3})):
                out = checkpoint.retry_io(lambda: 42, site="s",
                                          base_delay=0.01,
                                          sleep=delays.append)
        finally:
            telemetry.finish_run()
        assert out == 42
        assert delays == [0.01, 0.02, 0.04]  # exponential, deterministic
        assert run.counters["faults.io_retries"] == 3
        assert run.counters["faults.injected_errors"] == 3

    def test_retry_io_exhaustion_reraises(self):
        with checkpoint.fault_plan(
                checkpoint.FaultPlan(errors={"s": 99})):
            with pytest.raises(checkpoint.TransientIOError):
                checkpoint.retry_io(lambda: 42, site="s", retries=2,
                                    base_delay=0.0, sleep=lambda _d: None)

    def test_avro_open_rides_retry(self, tmp_path):
        """The ingest choke point: a transiently-failing container open
        backs off and succeeds (satellite: Avro ingest IO retry)."""
        from photon_tpu.data.avro_io import write_avro
        from photon_tpu.data.streaming import _open_reader

        path = tmp_path / "t.avro"
        write_avro(str(path), [{"x": 1}], json.dumps({
            "type": "record", "name": "R",
            "fields": [{"name": "x", "type": "int"}]}))
        with checkpoint.fault_plan(
                checkpoint.FaultPlan(errors={"avro_open": 2})):
            rd = _open_reader(str(path))
        assert sum(c for c, _ in rd.blocks(skip_payload=True)) == 1

    def test_seeded_fault_plan_is_deterministic(self):
        counts = {"evaluation": 12, "chunk_upload": 30}
        a = checkpoint.FaultPlan.seeded(5, counts)
        b = checkpoint.FaultPlan.seeded(5, counts)
        assert a.kills == b.kills and len(a.kills) == 1


# ------------------------------------------------------------ resident tap
class TestResidentTap:
    def test_tap_captures_last_iterate_and_restores(self, tmp_path):
        rng = np.random.default_rng(1)
        n, d = 48, 5
        X = rng.normal(size=(n, d)).astype(np.float32)
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        batch = make_batch(X, y)
        cfg = OptimizerConfig(max_iters=4, reg=reg.l2(), reg_weight=0.3,
                              history=3)
        ckdir = tmp_path / "resident"
        with checkpoint.session(str(ckdir), every_evals=None,
                                every_s=None, async_writer=False,
                                resident_tap=True) as sess:
            _, res = train_glm(batch, TASK, cfg)
            np.asarray(res.w)  # force the callback stream
            assert "resident/lbfgs_margin" in sess._state
            sess.snapshot(block=True)
        assert not checkpoint.snapshot_tap_enabled()  # disarmed at close
        with checkpoint.session(str(ckdir), async_writer=False):
            cap = checkpoint.resident_restore("lbfgs_margin")
        assert cap is not None
        assert np.asarray(cap["w"]).shape == (d,)
        assert int(np.asarray(cap["it"])) >= 1

    def test_disarmed_tap_stays_out_of_the_jaxpr(self):
        """Dynamic twin of the checkpoint_off_is_free ContractSpec."""
        import jax

        from photon_tpu.models.training import make_objective
        from photon_tpu.optim.lbfgs import minimize_lbfgs_margin

        cfg = OptimizerConfig(max_iters=3, reg=reg.l2(), reg_weight=0.3,
                              history=3)
        obj = make_objective(TASK, cfg, 4)
        batch = make_batch(np.zeros((8, 4), np.float32),
                           np.zeros(8, np.float32))
        jaxpr = str(jax.make_jaxpr(
            lambda b, w: minimize_lbfgs_margin(obj, b, w, max_iters=3))(
                batch, np.zeros(4, np.float32)))
        assert "callback" not in jaxpr


# ------------------------------------------------------------- session API
class TestSessionScoping:
    def test_scope_paths_and_consumed_once_restore(self, tmp_path):
        s = checkpoint.CheckpointSession(str(tmp_path / "s"),
                                         async_writer=False)
        with s.scope("a"):
            with s.scope("b"):
                s.update("leaf", {"v": 1})
        assert "a/b/leaf" in s._state
        s.snapshot()
        s2 = checkpoint.CheckpointSession(str(tmp_path / "s"),
                                          async_writer=False)
        with s2.scope("a"), s2.scope("b"):
            assert s2.restore("leaf") == {"v": 1}
            assert s2.restore("leaf") is None  # consumed once
        s.close()
        s2.close()

    def test_clear_prefix_drops_subtree(self, tmp_path):
        s = checkpoint.CheckpointSession(str(tmp_path / "s"),
                                         async_writer=False)
        with s.scope("u0"):
            s.update("re", {"v": 1})
            s.update("other", {"v": 2})
        s.update("progress", {"v": 3})
        s.clear("u0", prefix=True)
        assert set(s._state) == {"progress"}
        s.close()
