"""End-to-end GLM training: mesh == single device, parity with sklearn /
closed forms, variances.

Mirrors the reference's DistributedOptimizationProblemTest and the
supervised-model integration tests.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.dataset import make_batch
from photon_tpu.models.training import train_glm
from photon_tpu.models.variance import VarianceComputationType
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig, OptimizerType


def _logistic_data(rng, n=2000, d=12):
    X = rng.normal(size=(n, d)).astype(np.float32)
    wt = (rng.normal(size=d) * 0.5).astype(np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-X @ wt))).astype(np.float32)
    return X, y


def test_mesh_matches_single_device(rng, mesh8):
    X, y = _logistic_data(rng)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=150, reg=reg.l2(), reg_weight=1.0)
    m_mesh, r_mesh = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg, mesh=mesh8)
    m_one, r_one = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
    # sharded reductions reorder f32 sums; the line search then stops at a
    # slightly different iterate — ~1e-4 coefficient drift is expected
    np.testing.assert_allclose(m_mesh.weights, m_one.weights, atol=1e-4)
    np.testing.assert_allclose(r_mesh.value, r_one.value, rtol=1e-5)


def test_mesh_with_padding(rng, mesh8):
    """n not divisible by 8: zero-weight padding must not change the result."""
    X, y = _logistic_data(rng, n=1001)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=150, reg=reg.l2(), reg_weight=1.0)
    m_mesh, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg, mesh=mesh8)
    m_one, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
    # f32 reduction order differs once padding reshapes the shards, so the
    # iterates drift by ~1 ulp per step; equality holds to optimizer tolerance.
    np.testing.assert_allclose(m_mesh.weights, m_one.weights, atol=5e-4)


def test_linear_regression_closed_form(rng):
    n, d = 500, 8
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) + 0.1 * rng.normal(size=n)).astype(np.float32)
    lam = 2.0
    cfg = OptimizerConfig(max_iters=300, reg=reg.l2(), reg_weight=lam, tolerance=1e-9)
    model, _ = train_glm(make_batch(X, y), TaskType.LINEAR_REGRESSION, cfg)
    exact = np.linalg.solve(X.T @ X + lam * np.eye(d), X.T @ y)
    np.testing.assert_allclose(model.weights, exact, atol=2e-3)


def test_poisson_regression_recovers_truth(rng):
    n, d = 4000, 5
    X = (rng.normal(size=(n, d)) * 0.3).astype(np.float32)
    wt = np.array([0.5, -0.3, 0.2, 0.0, 0.4], np.float32)
    y = rng.poisson(np.exp(X @ wt)).astype(np.float32)
    cfg = OptimizerConfig(max_iters=200, reg=reg.l2(), reg_weight=1e-3)
    model, res = train_glm(make_batch(X, y), TaskType.POISSON_REGRESSION, cfg)
    assert bool(res.converged)
    np.testing.assert_allclose(model.weights, wt, atol=0.1)


def test_tron_optimizer_path(rng, mesh8):
    X, y = _logistic_data(rng, n=800)
    cfg_t = OptimizerConfig(optimizer=OptimizerType.TRON, max_iters=80,
                            reg=reg.l2(), reg_weight=1.0)
    cfg_l = OptimizerConfig(max_iters=200, reg=reg.l2(), reg_weight=1.0)
    mt, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg_t, mesh=mesh8)
    ml, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg_l)
    np.testing.assert_allclose(mt.weights, ml.weights, atol=3e-3)


def test_l1_auto_selects_owlqn(rng):
    X, y = _logistic_data(rng, n=400, d=20)
    cfg = OptimizerConfig(max_iters=200, reg=reg.l1(), reg_weight=8.0)
    assert cfg.effective_optimizer() is OptimizerType.OWLQN
    model, res = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg)
    assert int((np.asarray(model.weights) != 0).sum()) < 20


def test_elastic_net(rng):
    X, y = _logistic_data(rng, n=400, d=15)
    cfg = OptimizerConfig(max_iters=200, reg=reg.elastic_net(alpha=0.5),
                          reg_weight=4.0)
    model, res = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg)
    assert bool(res.converged)
    # elastic net at alpha=0.5 still induces some sparsity
    assert int((np.asarray(model.weights) == 0).sum()) > 0


def test_simple_variances_match_inverse_hessian_diag(rng):
    """For linear regression with lam=0, SIMPLE variance = 1/diag(X^T X)."""
    n, d = 300, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ np.ones(d)).astype(np.float32)
    cfg = OptimizerConfig(max_iters=100)
    model, _ = train_glm(make_batch(X, y), TaskType.LINEAR_REGRESSION, cfg,
                         variance=VarianceComputationType.SIMPLE)
    expected = 1.0 / np.diag(X.T @ X)
    np.testing.assert_allclose(model.coefficients.variances, expected, rtol=1e-3)


def test_full_variances(rng):
    n, d = 300, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ np.ones(d)).astype(np.float32)
    cfg = OptimizerConfig(max_iters=100)
    model, _ = train_glm(make_batch(X, y), TaskType.LINEAR_REGRESSION, cfg,
                         variance=VarianceComputationType.FULL)
    expected = np.diag(np.linalg.inv(X.T @ X))
    np.testing.assert_allclose(model.coefficients.variances, expected, rtol=2e-3)


def test_weights_and_offsets(rng):
    """Duplicating a row == weighting it 2x; offsets shift the margin."""
    X, y = _logistic_data(rng, n=200, d=6)
    cfg = OptimizerConfig(max_iters=200, reg=reg.l2(), reg_weight=0.5)

    Xdup = np.concatenate([X, X[:50]])
    ydup = np.concatenate([y, y[:50]])
    w = np.ones(200, np.float32)
    w[:50] = 2.0
    m_dup, _ = train_glm(make_batch(Xdup, ydup), TaskType.LOGISTIC_REGRESSION, cfg)
    m_wt, _ = train_glm(make_batch(X, y, weights=w), TaskType.LOGISTIC_REGRESSION, cfg)
    np.testing.assert_allclose(m_dup.weights, m_wt.weights, atol=2e-3)


def test_prior_incremental_training(rng):
    """Strong prior pins coefficients at the prior mean; weak prior doesn't."""
    X, y = _logistic_data(rng, n=300, d=5)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=200)
    mu = jnp.asarray(np.full(5, 0.37, np.float32))
    strong, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                          prior_mean=mu, prior_precision=jnp.full((5,), 1e6))
    np.testing.assert_allclose(strong.weights, mu, atol=1e-2)


class TestTrainGlmGrid:
    """train_glm_grid: one compiled program per reg-weight sweep."""

    def _problem(self, rng, n=512, d=12):
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w)))).astype(
            np.float32)
        return make_batch(X, y)

    def test_matches_sequential_l2(self, rng):
        from photon_tpu.models.training import train_glm_grid

        batch = self._problem(rng)
        cfg = OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=0.0,
                              regularize_intercept=True)
        weights = [0.1, 1.0, 10.0]
        grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                              weights)
        assert len(grid) == 3
        for wt, (m_g, r_g) in zip(weights, grid):
            import dataclasses

            m_s, r_s = train_glm(
                batch, TaskType.LOGISTIC_REGRESSION,
                dataclasses.replace(cfg, reg_weight=wt))
            assert bool(r_g.converged)
            np.testing.assert_allclose(
                np.asarray(m_g.coefficients.means),
                np.asarray(m_s.coefficients.means), atol=2e-4)

    def test_matches_sequential_owlqn(self, rng):
        """Grid lanes must equal the same-route single solve bit-for-bit-ish
        (train_glm's single-device OWLQN takes the pallas fused route, whose
        f32 rounding diverges the iterate path — so compare against the jnp
        objective the grid itself uses)."""
        from photon_tpu.models.training import (
            make_objective, solve, train_glm_grid)
        from photon_tpu.optim.config import OptimizerType

        batch = self._problem(rng)
        cfg = OptimizerConfig(optimizer=OptimizerType.OWLQN, max_iters=60,
                              reg=reg.l1(), reg_weight=0.0,
                              regularize_intercept=True)
        weights = [0.5, 5.0]
        grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                              weights)
        d = batch.X.shape[1]
        w0 = np.zeros(d, np.float32)
        for wt, (m_g, r_g) in zip(weights, grid):
            import dataclasses

            c = dataclasses.replace(cfg, reg_weight=wt)
            obj = make_objective(TaskType.LOGISTIC_REGRESSION, c, d)
            r_s = solve(obj, batch, w0, c)
            np.testing.assert_allclose(np.asarray(m_g.coefficients.means),
                                       np.asarray(r_s.w), atol=1e-5)
        # stronger L1 → sparser lane
        nnz = [int((np.abs(np.asarray(m.coefficients.means)) > 1e-6).sum())
               for m, _ in grid]
        assert nnz[1] <= nnz[0]

    def test_l1_grid_routes_owlqn_without_config_weight(self, rng):
        """An L1 grid whose config carries reg_weight=0.0 (the natural grid
        idiom) must still run OWL-QN lanes with the grid's weights —
        regression: effective_optimizer() saw l1_weight(0.0)==0 and silently
        dropped ALL regularization, every lane returning the same
        unpenalized solution."""
        from photon_tpu.models.training import train_glm_grid

        batch = self._problem(rng)
        cfg = OptimizerConfig(max_iters=60, reg=reg.l1(), reg_weight=0.0,
                              regularize_intercept=True)
        grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                              [0.5, 20.0])
        w_weak = np.asarray(grid[0][0].coefficients.means)
        w_strong = np.asarray(grid[1][0].coefficients.means)
        assert not np.allclose(w_weak, w_strong)  # weights actually applied
        nnz_weak = int((np.abs(w_weak) > 1e-6).sum())
        nnz_strong = int((np.abs(w_strong) > 1e-6).sum())
        assert nnz_strong < nnz_weak  # strong L1 produces genuine sparsity

    def test_grid_on_mesh(self, rng, mesh8):
        from photon_tpu.models.training import train_glm_grid

        batch = self._problem(rng, n=1024)
        cfg = OptimizerConfig(max_iters=40, reg=reg.l2(), reg_weight=0.0,
                              regularize_intercept=True)
        grid_m = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                                [0.5, 5.0], mesh=mesh8)
        grid_s = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                                [0.5, 5.0])
        for (m_m, _), (m_s, _) in zip(grid_m, grid_s):
            np.testing.assert_allclose(
                np.asarray(m_m.coefficients.means),
                np.asarray(m_s.coefficients.means), atol=2e-3)

    def test_grid_with_variances_and_normalization(self, rng):
        from photon_tpu.data.normalization import (
            NormalizationContext, NormalizationType)
        from photon_tpu.models.training import train_glm_grid
        from photon_tpu.models.variance import VarianceComputationType

        rng2 = np.random.default_rng(3)
        n, d = 400, 6
        X = np.concatenate([rng2.normal(2.0, 5.0, size=(n, d - 1)),
                            np.ones((n, 1))], 1).astype(np.float32)
        y = (rng2.uniform(size=n) < 0.4).astype(np.float32)
        batch = make_batch(X, y)
        norm = NormalizationContext.build(X, NormalizationType.STANDARDIZATION)
        cfg = OptimizerConfig(max_iters=50, reg=reg.l2(), reg_weight=0.0,
                              regularize_intercept=True)
        grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                              [1.0, 3.0], normalization=norm,
                              variance=VarianceComputationType.SIMPLE)
        for wt, (m_g, _) in zip([1.0, 3.0], grid):
            import dataclasses

            m_s, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION,
                               dataclasses.replace(cfg, reg_weight=wt),
                               normalization=norm,
                               variance=VarianceComputationType.SIMPLE)
            np.testing.assert_allclose(
                np.asarray(m_g.coefficients.means),
                np.asarray(m_s.coefficients.means), atol=2e-3)
            np.testing.assert_allclose(
                np.asarray(m_g.coefficients.variances),
                np.asarray(m_s.coefficients.variances), rtol=2e-2)

    def test_score_models_and_grid_selection(self, rng):
        from photon_tpu.models.glm import score_models
        from photon_tpu.models.training import (
            evaluate_glm_grid, train_glm_grid)

        batch = self._problem(rng, n=800)
        Xv = np.asarray(batch.X)[600:]
        val = make_batch(Xv, np.asarray(batch.y)[600:])
        tr = make_batch(np.asarray(batch.X)[:600], np.asarray(batch.y)[:600])
        cfg = OptimizerConfig(max_iters=50, reg=reg.l2(), reg_weight=0.0,
                              regularize_intercept=True)
        weights = [0.1, 1.0, 1000.0]
        grid = train_glm_grid(tr, TaskType.LOGISTIC_REGRESSION, cfg, weights)
        # batched margins == per-model margins
        M = np.asarray(score_models([m for m, _ in grid], val.X))
        for i, (m, _) in enumerate(grid):
            np.testing.assert_allclose(M[i], np.asarray(m.score(val.X)),
                                       rtol=1e-5, atol=1e-5)
        best, scores = evaluate_glm_grid(grid, val)
        assert len(scores) == 3
        # default logistic evaluator is AUC; the absurdly over-regularized
        # lane must not win
        assert best != 2
