"""End-to-end GLM training: mesh == single device, parity with sklearn /
closed forms, variances.

Mirrors the reference's DistributedOptimizationProblemTest and the
supervised-model integration tests.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.dataset import make_batch
from photon_tpu.models.training import train_glm
from photon_tpu.models.variance import VarianceComputationType
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig, OptimizerType


def _logistic_data(rng, n=2000, d=12):
    X = rng.normal(size=(n, d)).astype(np.float32)
    wt = (rng.normal(size=d) * 0.5).astype(np.float32)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-X @ wt))).astype(np.float32)
    return X, y


def test_mesh_matches_single_device(rng, mesh8):
    X, y = _logistic_data(rng)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=150, reg=reg.l2(), reg_weight=1.0)
    m_mesh, r_mesh = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg, mesh=mesh8)
    m_one, r_one = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
    np.testing.assert_allclose(m_mesh.weights, m_one.weights, atol=1e-5)
    np.testing.assert_allclose(r_mesh.value, r_one.value, rtol=1e-5)


def test_mesh_with_padding(rng, mesh8):
    """n not divisible by 8: zero-weight padding must not change the result."""
    X, y = _logistic_data(rng, n=1001)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=150, reg=reg.l2(), reg_weight=1.0)
    m_mesh, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg, mesh=mesh8)
    m_one, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg)
    # f32 reduction order differs once padding reshapes the shards, so the
    # iterates drift by ~1 ulp per step; equality holds to optimizer tolerance.
    np.testing.assert_allclose(m_mesh.weights, m_one.weights, atol=5e-4)


def test_linear_regression_closed_form(rng):
    n, d = 500, 8
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) + 0.1 * rng.normal(size=n)).astype(np.float32)
    lam = 2.0
    cfg = OptimizerConfig(max_iters=300, reg=reg.l2(), reg_weight=lam, tolerance=1e-9)
    model, _ = train_glm(make_batch(X, y), TaskType.LINEAR_REGRESSION, cfg)
    exact = np.linalg.solve(X.T @ X + lam * np.eye(d), X.T @ y)
    np.testing.assert_allclose(model.weights, exact, atol=2e-3)


def test_poisson_regression_recovers_truth(rng):
    n, d = 4000, 5
    X = (rng.normal(size=(n, d)) * 0.3).astype(np.float32)
    wt = np.array([0.5, -0.3, 0.2, 0.0, 0.4], np.float32)
    y = rng.poisson(np.exp(X @ wt)).astype(np.float32)
    cfg = OptimizerConfig(max_iters=200, reg=reg.l2(), reg_weight=1e-3)
    model, res = train_glm(make_batch(X, y), TaskType.POISSON_REGRESSION, cfg)
    assert bool(res.converged)
    np.testing.assert_allclose(model.weights, wt, atol=0.1)


def test_tron_optimizer_path(rng, mesh8):
    X, y = _logistic_data(rng, n=800)
    cfg_t = OptimizerConfig(optimizer=OptimizerType.TRON, max_iters=80,
                            reg=reg.l2(), reg_weight=1.0)
    cfg_l = OptimizerConfig(max_iters=200, reg=reg.l2(), reg_weight=1.0)
    mt, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg_t, mesh=mesh8)
    ml, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg_l)
    np.testing.assert_allclose(mt.weights, ml.weights, atol=3e-3)


def test_l1_auto_selects_owlqn(rng):
    X, y = _logistic_data(rng, n=400, d=20)
    cfg = OptimizerConfig(max_iters=200, reg=reg.l1(), reg_weight=8.0)
    assert cfg.effective_optimizer() is OptimizerType.OWLQN
    model, res = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg)
    assert int((np.asarray(model.weights) != 0).sum()) < 20


def test_elastic_net(rng):
    X, y = _logistic_data(rng, n=400, d=15)
    cfg = OptimizerConfig(max_iters=200, reg=reg.elastic_net(alpha=0.5),
                          reg_weight=4.0)
    model, res = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION, cfg)
    assert bool(res.converged)
    # elastic net at alpha=0.5 still induces some sparsity
    assert int((np.asarray(model.weights) == 0).sum()) > 0


def test_simple_variances_match_inverse_hessian_diag(rng):
    """For linear regression with lam=0, SIMPLE variance = 1/diag(X^T X)."""
    n, d = 300, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ np.ones(d)).astype(np.float32)
    cfg = OptimizerConfig(max_iters=100)
    model, _ = train_glm(make_batch(X, y), TaskType.LINEAR_REGRESSION, cfg,
                         variance=VarianceComputationType.SIMPLE)
    expected = 1.0 / np.diag(X.T @ X)
    np.testing.assert_allclose(model.coefficients.variances, expected, rtol=1e-3)


def test_full_variances(rng):
    n, d = 300, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ np.ones(d)).astype(np.float32)
    cfg = OptimizerConfig(max_iters=100)
    model, _ = train_glm(make_batch(X, y), TaskType.LINEAR_REGRESSION, cfg,
                         variance=VarianceComputationType.FULL)
    expected = np.diag(np.linalg.inv(X.T @ X))
    np.testing.assert_allclose(model.coefficients.variances, expected, rtol=2e-3)


def test_weights_and_offsets(rng):
    """Duplicating a row == weighting it 2x; offsets shift the margin."""
    X, y = _logistic_data(rng, n=200, d=6)
    cfg = OptimizerConfig(max_iters=200, reg=reg.l2(), reg_weight=0.5)

    Xdup = np.concatenate([X, X[:50]])
    ydup = np.concatenate([y, y[:50]])
    w = np.ones(200, np.float32)
    w[:50] = 2.0
    m_dup, _ = train_glm(make_batch(Xdup, ydup), TaskType.LOGISTIC_REGRESSION, cfg)
    m_wt, _ = train_glm(make_batch(X, y, weights=w), TaskType.LOGISTIC_REGRESSION, cfg)
    np.testing.assert_allclose(m_dup.weights, m_wt.weights, atol=2e-3)


def test_prior_incremental_training(rng):
    """Strong prior pins coefficients at the prior mean; weak prior doesn't."""
    X, y = _logistic_data(rng, n=300, d=5)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=200)
    mu = jnp.asarray(np.full(5, 0.37, np.float32))
    strong, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                          prior_mean=mu, prior_precision=jnp.full((5,), 1e6))
    np.testing.assert_allclose(strong.weights, mu, atol=1e-2)
