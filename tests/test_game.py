"""GAME end-to-end tests (SURVEY.md §4 integration strategy): synthetic
mixed-effect data must recover planted coefficients, GAME must beat a
fixed-effect-only model, and everything must run on the 8-device mesh."""
import dataclasses
import numpy as np
import pytest
import jax.numpy as jnp
from sklearn.metrics import roc_auc_score

from photon_tpu.data.matrix import SparseRows, from_scipy_csr
from photon_tpu.game import (
    FixedEffectConfig,
    FixedEffectCoordinate,
    FixedEffectDataset,
    GameData,
    GameEstimator,
    RandomEffectConfig,
    RandomEffectCoordinate,
    RandomEffectDataset,
    coordinate_descent,
    predict_mean,
    score_game,
)
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.models.training import train_glm
from photon_tpu.data.dataset import make_batch


def _mixed_effect_logistic(rng, n_entities=30, d_fixed=8, d_re=3, rows_lo=5,
                           rows_hi=60, noise=1.0):
    """Rows: y ~ Bernoulli(sigmoid(x_f·w_fixed + x_r·w_entity))."""
    w_fixed = rng.normal(size=d_fixed)
    w_re = rng.normal(size=(n_entities, d_re)) * 1.5
    rows = rng.integers(rows_lo, rows_hi, size=n_entities)
    ent = np.repeat(np.arange(n_entities), rows)
    n = ent.shape[0]
    perm = rng.permutation(n)
    ent = ent[perm]
    Xf = rng.normal(size=(n, d_fixed)).astype(np.float32)
    Xr = rng.normal(size=(n, d_re)).astype(np.float32)
    logit = Xf @ w_fixed + np.einsum("nd,nd->n", Xr, w_re[ent]) + noise * 0
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
    data = GameData.build(
        y,
        shards={"fixed": Xf, "per_entity": Xr},
        entity_ids={"entity": ent.astype(np.int64)},
    )
    return data, w_fixed, w_re, ent


@pytest.mark.tier2
def test_movielens_style_two_random_effects(rng):
    """BASELINE config 3 shape: fixed effect + per-USER + per-ITEM random
    effects (MovieLens-style), coordinate descent alternating over three
    coordinates with residual offsets. Each additional coordinate must add
    held-out AUC, and the full model must recover the planted structure."""
    n_users, n_items, d_f = 60, 40, 6
    n = 6000
    users = rng.integers(0, n_users, size=n)
    items = rng.integers(0, n_items, size=n)
    w_f = rng.normal(size=d_f)
    u_eff = rng.normal(size=n_users) * 1.3   # per-user intercepts
    i_eff = rng.normal(size=n_items) * 1.3   # per-item intercepts
    Xf = rng.normal(size=(n, d_f)).astype(np.float32)
    ones = np.ones((n, 1), np.float32)       # RE shard: intercept feature
    logit = Xf @ w_f + u_eff[users] + i_eff[items]
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)

    tr = np.arange(n) < n - 1500
    te = ~tr

    def build(idx):
        return GameData.build(
            y[idx], shards={"fixed": Xf[idx], "bias": ones[idx]},
            entity_ids={"user": users[idx], "item": items[idx]})

    data, test = build(tr), build(te)
    cfg = OptimizerConfig(max_iters=40, reg=reg.l2(), reg_weight=1.0)
    configs_full = {
        "fixed": FixedEffectConfig("fixed", cfg),
        "per_user": RandomEffectConfig("user", "bias", cfg),
        "per_item": RandomEffectConfig("item", "bias", cfg),
    }
    aucs = {}
    for name, keys in [("fixed", ("fixed",)),
                       ("user", ("fixed", "per_user")),
                       ("full", ("fixed", "per_user", "per_item"))]:
        est = GameEstimator(TaskType.LOGISTIC_REGRESSION,
                            {k: configs_full[k] for k in keys}, n_sweeps=2)
        model = est.fit(data)[0].model
        aucs[name] = roc_auc_score(y[te], np.asarray(score_game(model, test)))
    assert aucs["user"] > aucs["fixed"] + 0.01
    assert aucs["full"] > aucs["user"] + 0.01
    assert aucs["full"] > 0.8
    # Planted per-user effects recovered (up to shared-intercept shift);
    # align by the model's own entity keys — robust to users unseen in
    # training (dense_ids would return the out-of-range sentinel there).
    u_hat = np.asarray(model["per_user"].coefficients)[:, 0]
    keys = np.asarray(model["per_user"].entity_keys).astype(int)
    corr = np.corrcoef(u_hat, u_eff[keys])[0, 1]
    assert corr > 0.8


def test_re_dataset_bucketing(rng):
    n_entities = 17
    rows = rng.integers(1, 40, size=n_entities)
    ent = np.repeat(np.arange(n_entities), rows)
    rng.shuffle(ent)
    n = ent.shape[0]
    X = rng.normal(size=(n, 2)).astype(np.float32)
    data = GameData.build(np.zeros(n), {"s": X}, {"e": ent})
    ds = RandomEffectDataset.build(data, "e", "s")
    assert ds.n_entities == n_entities
    assert ds.n_active == n and ds.n_passive == 0
    # every real row appears exactly once across blocks, padding is weight-0
    seen = np.zeros(n, np.int32)
    total_entities = 0
    for b in ds.blocks:
        assert b.m & (b.m - 1) == 0  # power of two
        total_entities += b.n_entities
        w = np.asarray(b.weights)
        ri = np.asarray(b.row_index)
        for i in range(b.n_entities):
            real = w[i] > 0
            np.testing.assert_array_equal(
                np.sort(ent[ri[i][real]]), np.full(real.sum(), ent[ri[i][real]][0])
            )
            seen[ri[i][real]] += 1
    assert total_entities == n_entities
    np.testing.assert_array_equal(seen, 1)


def test_random_effect_recovers_per_entity_coefficients(rng):
    n_entities, d = 12, 3
    w_true = rng.normal(size=(n_entities, d)).astype(np.float32)
    rows = rng.integers(30, 80, size=n_entities)
    ent = np.repeat(np.arange(n_entities), rows)
    n = ent.shape[0]
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = np.einsum("nd,nd->n", X, w_true[ent]) + 0.01 * rng.normal(size=n)
    data = GameData.build(y, {"s": X}, {"e": ent})
    ds = RandomEffectDataset.build(data, "e", "s")
    coord = RandomEffectCoordinate(
        ds, TaskType.LINEAR_REGRESSION,
        OptimizerConfig(max_iters=50, reg=reg.l2(), reg_weight=1e-4),
    )
    model, stats = coord.train(np.zeros(n, np.float32))
    assert stats.n_converged == n_entities
    got = np.asarray(model.coefficients)[
        np.asarray([model.key_to_index[k] for k in range(n_entities)])
    ]
    np.testing.assert_allclose(got, w_true, atol=0.05)


def test_game_beats_fixed_only_and_recovers_coefficients(rng):
    data, w_fixed, w_re, ent = _mixed_effect_logistic(rng)
    n = data.n
    tr = np.arange(n) % 5 != 0
    te = ~tr

    def subset(mask):
        return GameData.build(
            data.y[mask],
            {k: np.asarray(v)[mask] for k, v in data.shards.items()},
            {k: v[mask] for k, v in data.entity_ids.items()},
        )

    train, test = subset(tr), subset(te)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinate_configs={
            "fixed": FixedEffectConfig(
                "fixed", OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=0.1)
            ),
            "per_entity": RandomEffectConfig(
                "entity", "per_entity",
                OptimizerConfig(max_iters=40, reg=reg.l2(), reg_weight=1.0),
            ),
        },
        n_sweeps=2,
    )
    results = est.fit(train, validation=test)
    model = results[0].model
    # objective decreases monotonically-ish across coordinate updates
    hist = results[0].descent.objective_history
    assert hist[-1] < hist[0]

    # fixed coefficients recovered up to noise
    got_fixed = np.asarray(model["fixed"].model.weights)
    corr = np.corrcoef(got_fixed, w_fixed)[0, 1]
    assert corr > 0.95

    # GAME beats fixed-effect-only on held-out AUC
    game_scores = np.asarray(score_game(model, test))
    game_auc = roc_auc_score(test.y, game_scores)
    fe_only, _ = train_glm(
        make_batch(train.shards["fixed"], train.y),
        TaskType.LOGISTIC_REGRESSION,
        OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=0.1),
    )
    fe_auc = roc_auc_score(
        test.y, np.asarray(fe_only.predict_mean(jnp.asarray(test.shards["fixed"])))
    )
    assert game_auc > fe_auc + 0.02
    assert results[0].validation_score == pytest.approx(game_auc, abs=1e-5)


def test_game_mesh_matches_single_device(rng, mesh8):
    data, *_ = _mixed_effect_logistic(rng, n_entities=10, rows_lo=8, rows_hi=24)
    configs = {
        "fixed": FixedEffectConfig(
            "fixed", OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=0.5)
        ),
        "per_entity": RandomEffectConfig(
            "entity", "per_entity",
            OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=1.0),
        ),
    }
    single = GameEstimator(TaskType.LOGISTIC_REGRESSION, configs, n_sweeps=1)
    meshy = GameEstimator(TaskType.LOGISTIC_REGRESSION, configs, n_sweeps=1, mesh=mesh8)
    m1 = single.fit(data)[0].model
    m2 = meshy.fit(data)[0].model
    # Single-device fixed-effect solves run the fused pallas objective while
    # mesh solves use the jnp path: different f32 reduction orders, drift
    # amplified across coordinate-descent iterations. ~1e-3 is the expected
    # noise floor, not a semantic difference.
    np.testing.assert_allclose(
        np.asarray(m1["fixed"].model.weights),
        np.asarray(m2["fixed"].model.weights),
        atol=2e-3,
    )
    np.testing.assert_allclose(
        np.asarray(m1["per_entity"].coefficients),
        np.asarray(m2["per_entity"].coefficients),
        atol=2e-3,
    )


def test_locked_coordinate_not_retrained(rng):
    data, *_ = _mixed_effect_logistic(rng, n_entities=8, rows_lo=8, rows_hi=20)
    fe_ds = FixedEffectDataset.build(data, "fixed")
    cfg = OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=0.5)
    fe_coord = FixedEffectCoordinate(fe_ds, TaskType.LOGISTIC_REGRESSION, cfg)
    pretrained, _ = fe_coord.train(np.zeros(data.n, np.float32))

    re_ds = RandomEffectDataset.build(data, "entity", "per_entity")
    re_coord = RandomEffectCoordinate(
        re_ds, TaskType.LOGISTIC_REGRESSION,
        OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=1.0),
    )
    result = coordinate_descent(
        {"fixed": fe_coord, "per_entity": re_coord},
        data.y, data.weights, data.offsets,
        TaskType.LOGISTIC_REGRESSION,
        n_sweeps=2,
        locked=frozenset({"fixed"}),
        initial_models={"fixed": pretrained},
    )
    np.testing.assert_array_equal(
        np.asarray(result.model["fixed"].model.weights),
        np.asarray(pretrained.model.weights),
    )
    # the random effect actually trained
    assert np.abs(np.asarray(result.model["per_entity"].coefficients)).max() > 0


def test_config_grid_warm_start_and_selection(rng):
    data, *_ = _mixed_effect_logistic(rng, n_entities=10, rows_lo=10, rows_hi=30)
    base = {
        "fixed": FixedEffectConfig(
            "fixed", OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=1.0)
        ),
    }
    est = GameEstimator(TaskType.LOGISTIC_REGRESSION, base, n_sweeps=1)
    grid = [
        {"fixed": FixedEffectConfig(
            "fixed", OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=w))}
        for w in (10.0, 0.1)
    ]
    results = est.fit(data, validation=data, config_grid=grid)
    assert len(results) == 2
    assert all(r.validation_score is not None for r in results)
    best = est.best_model(results)
    assert best is results[int(np.argmax([r.validation_score for r in results]))]


def test_scoring_unseen_entity_contributes_zero(rng):
    data, *_ = _mixed_effect_logistic(rng, n_entities=6, rows_lo=10, rows_hi=20)
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {
            "fixed": FixedEffectConfig(
                "fixed", OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=1.0)
            ),
            "per_entity": RandomEffectConfig(
                "entity", "per_entity",
                OptimizerConfig(max_iters=15, reg=reg.l2(), reg_weight=1.0),
            ),
        },
        n_sweeps=1,
    )
    model = est.fit(data)[0].model
    new = GameData.build(
        data.y[:3],
        {k: np.asarray(v)[:3] for k, v in data.shards.items()},
        {"entity": np.array([999, 998, 997], np.int64)},  # all unseen
    )
    scores = np.asarray(score_game(model, new))
    fe_scores = np.asarray(model["fixed"].score(new.shards["fixed"]))
    np.testing.assert_allclose(scores, fe_scores, atol=1e-6)
    mean = np.asarray(predict_mean(model, new))
    assert ((mean > 0) & (mean < 1)).all()
    # Device-resident shards score identically (drivers use to_device()).
    np.testing.assert_allclose(np.asarray(score_game(model, new.to_device())),
                               scores, atol=1e-6)


def test_sparse_re_matches_dense(rng):
    import scipy.sparse as sp

    n_entities, d = 6, 5
    rows = rng.integers(10, 25, size=n_entities)
    ent = np.repeat(np.arange(n_entities), rows)
    n = ent.shape[0]
    Xd = rng.normal(size=(n, d)).astype(np.float32)
    Xd[rng.random(size=(n, d)) < 0.5] = 0.0
    y = rng.normal(size=n).astype(np.float32)
    cfg = OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=0.1)

    def fit(X):
        data = GameData.build(y, {"s": X}, {"e": ent})
        ds = RandomEffectDataset.build(data, "e", "s")
        coord = RandomEffectCoordinate(ds, TaskType.LINEAR_REGRESSION, cfg)
        model, _ = coord.train(np.zeros(n, np.float32))
        return np.asarray(model.coefficients), np.asarray(coord.score(model))

    cd, sd = fit(Xd)
    cs, ss = fit(from_scipy_csr(sp.csr_matrix(Xd)))
    # f32 reduction-order drift between segment_sum and dense matmul paths
    # compounds over solver iterations; ~1e-4 is expected, not a bug.
    np.testing.assert_allclose(cd, cs, atol=5e-4)
    np.testing.assert_allclose(sd, ss, atol=5e-4)


def test_active_cap_passive_rows_scored(rng):
    n_entities = 5
    rows = np.full(n_entities, 40)
    ent = np.repeat(np.arange(n_entities), rows)
    n = ent.shape[0]
    X = rng.normal(size=(n, 2)).astype(np.float32)
    y = rng.normal(size=n).astype(np.float32)
    data = GameData.build(y, {"s": X}, {"e": ent})
    ds = RandomEffectDataset.build(data, "e", "s", active_cap=16)
    assert ds.n_active == n_entities * 16
    assert ds.n_passive == n - n_entities * 16
    coord = RandomEffectCoordinate(
        ds, TaskType.LINEAR_REGRESSION,
        OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=0.1),
    )
    model, _ = coord.train(np.zeros(n, np.float32))
    scores = np.asarray(coord.score(model))
    assert scores.shape == (n,)
    expected = np.einsum(
        "nd,nd->n", X, np.asarray(model.coefficients)[ds.entity_dense]
    )
    np.testing.assert_allclose(scores, expected, atol=1e-5)


def test_sharded_evaluator_in_fit(rng):
    from photon_tpu.evaluation import Evaluator, EvaluatorType

    data, *_ = _mixed_effect_logistic(rng, n_entities=8, rows_lo=10, rows_hi=25)
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {
            "fixed": FixedEffectConfig(
                "fixed", OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=1.0)
            ),
            "per_entity": RandomEffectConfig(
                "entity", "per_entity",
                OptimizerConfig(max_iters=15, reg=reg.l2(), reg_weight=1.0),
            ),
        },
        n_sweeps=1,
        evaluator=Evaluator(EvaluatorType.SHARDED_AUC),
    )
    results = est.fit(data, validation=data)
    assert results[0].validation_score is not None
    assert 0.5 < results[0].validation_score <= 1.0


def test_config_grid_dataset_override_takes_effect(rng):
    data, *_ = _mixed_effect_logistic(rng, n_entities=5, rows_lo=30, rows_hi=40)
    base = {
        "per_entity": RandomEffectConfig(
            "entity", "per_entity",
            OptimizerConfig(max_iters=10, reg=reg.l2(), reg_weight=1.0),
        ),
    }
    est = GameEstimator(TaskType.LOGISTIC_REGRESSION, base, n_sweeps=1,
                        warm_start=False)
    grid = [
        {"per_entity": RandomEffectConfig(
            "entity", "per_entity",
            OptimizerConfig(max_iters=10, reg=reg.l2(), reg_weight=1.0),
            active_cap=8)},
        {"per_entity": base["per_entity"]},
    ]
    r_capped, r_full = est.fit(data, config_grid=grid)
    # the capped fit trained on fewer rows, so coefficients must differ
    assert not np.allclose(
        np.asarray(r_capped.model["per_entity"].coefficients),
        np.asarray(r_full.model["per_entity"].coefficients),
    )


def test_initial_models_honored_without_warm_start(rng):
    data, *_ = _mixed_effect_logistic(rng, n_entities=6, rows_lo=10, rows_hi=20)
    cfg = {
        "fixed": FixedEffectConfig(
            "fixed", OptimizerConfig(max_iters=25, reg=reg.l2(), reg_weight=0.5)
        ),
    }
    est = GameEstimator(TaskType.LOGISTIC_REGRESSION, cfg, n_sweeps=1)
    pretrained = est.fit(data)[0].model.coordinates
    est2 = GameEstimator(TaskType.LOGISTIC_REGRESSION, cfg, n_sweeps=1,
                         warm_start=False)
    r = est2.fit(data, initial_models=dict(pretrained))[0]
    # warm-started solve converges almost immediately from the optimum
    assert r.descent.coordinate_stats["fixed"][0].iterations <= 3


def test_unseen_longer_entity_id_maps_to_zero_row():
    """Unseen ids longer than every training key must NOT truncate into a
    real entity's row (fixed-width unicode cast bug)."""
    from photon_tpu.game.model import RandomEffectModel

    keys = np.asarray(["abc", "xyz"])  # dtype <U3
    m = RandomEffectModel(
        entity_name="e", feature_shard="s", task=TaskType.LOGISTIC_REGRESSION,
        coefficients=jnp.ones((2, 2)), entity_keys=keys,
        key_to_index={"abc": 0, "xyz": 1},
    )
    ids = m.dense_ids(np.asarray(["abcde", "abc", "zzz", "xyz"]))
    np.testing.assert_array_equal(ids, [2, 0, 2, 1])
    # integer raw ids against string keys still resolve by string value
    m2 = RandomEffectModel(
        entity_name="e", feature_shard="s", task=TaskType.LOGISTIC_REGRESSION,
        coefficients=jnp.ones((2, 2)), entity_keys=np.asarray(["1", "2"]),
        key_to_index={"1": 0, "2": 1},
    )
    np.testing.assert_array_equal(m2.dense_ids(np.asarray([2, 7, 1])), [1, 2, 0])


def test_estimator_normalization_detects_intercept():
    """Estimator-level normalization must not treat a real feature column as
    the intercept on shards built without one."""
    from photon_tpu.data.normalization import NormalizationType
    from photon_tpu.game.estimator import _last_column_is_intercept

    rng = np.random.default_rng(0)
    X_no = rng.normal(size=(50, 3)).astype(np.float32)  # no intercept
    X_yes = X_no.copy(); X_yes[:, -1] = 1.0
    assert not _last_column_is_intercept(X_no)
    assert _last_column_is_intercept(jnp.asarray(X_yes))

    y = (rng.uniform(size=50) < 0.5).astype(np.float32)
    data = GameData.build(y, shards={"s": X_no}, entity_ids={})
    est = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {"fixed": FixedEffectConfig("s", OptimizerConfig(max_iters=5))},
        n_sweeps=1,
        normalization={"fixed": NormalizationType.STANDARDIZATION},
    )
    with pytest.raises(ValueError, match="intercept"):
        est.fit(data)
    # scale-only mode works without an intercept, and normalizes EVERY column
    est2 = GameEstimator(
        TaskType.LOGISTIC_REGRESSION,
        {"fixed": FixedEffectConfig("s", OptimizerConfig(max_iters=20))},
        n_sweeps=1,
        normalization={"fixed": NormalizationType.SCALE_WITH_STANDARD_DEVIATION},
    )
    r = est2.fit(data)[0]
    assert np.isfinite(np.asarray(r.model["fixed"].model.weights)).all()


class TestVectorizedFixedGrid:
    """Fixed-effect-only reg-weight grids run as one compiled program."""

    def _data(self, rng, n=600, d=10):
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = rng.normal(size=d).astype(np.float32) * 0.7
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w)))).astype(
            np.float32)
        return GameData.build(y, shards={"fixed": X}, entity_ids={})

    @pytest.mark.cpu_parity_drift
    def test_matches_sequential_path(self, rng):
        data = self._data(rng)
        val = self._data(rng, n=300)
        cfg = OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=1.0,
                              regularize_intercept=True)
        grid = [{"fixed": FixedEffectConfig(
            "fixed", dataclasses.replace(cfg, reg_weight=wt))}
            for wt in (0.1, 1.0, 10.0)]

        def run(vectorized, warm):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs={"fixed": FixedEffectConfig("fixed", cfg)},
                n_sweeps=1, vectorized_grid=vectorized, warm_start=warm)
            return est.fit(data, validation=val, config_grid=grid)

        fast = run(True, False)
        slow = run(False, False)
        assert len(fast) == len(slow) == 3
        for rf, rs in zip(fast, slow):
            wf = np.asarray(
                rf.model.coordinates["fixed"].model.coefficients.means)
            ws = np.asarray(
                rs.model.coordinates["fixed"].model.coefficients.means)
            np.testing.assert_allclose(wf, ws, atol=2e-4)
            assert abs(rf.validation_score - rs.validation_score) < 1e-3
            np.testing.assert_allclose(rf.descent.objective_history[-1],
                                       rs.descent.objective_history[-1],
                                       rtol=1e-4)
            assert rf.configs["fixed"].optimizer.reg_weight == \
                rs.configs["fixed"].optimizer.reg_weight

    def test_matches_sequential_path_elastic_net(self, rng):
        """Fixed-only L1 grids through the estimator ride the OWL-QN lane
        road inside train_glm_grid and must still match the sequential
        estimator path point for point (incl. exact-zero sparsity)."""
        data = self._data(rng)
        cfg = OptimizerConfig(max_iters=80, reg=reg.elastic_net(0.5),
                              reg_weight=1.0, regularize_intercept=True)
        grid = [{"fixed": FixedEffectConfig(
            "fixed", dataclasses.replace(cfg, reg_weight=wt))}
            for wt in (0.05, 0.5, 5.0)]

        def run(vectorized):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs={"fixed": FixedEffectConfig("fixed", cfg)},
                n_sweeps=1, vectorized_grid=vectorized, warm_start=False)
            return est.fit(data, config_grid=grid)

        fast = run(True)
        slow = run(False)
        for rf, rs in zip(fast, slow):
            wf = np.asarray(
                rf.model.coordinates["fixed"].model.coefficients.means)
            ws = np.asarray(
                rs.model.coordinates["fixed"].model.coefficients.means)
            np.testing.assert_allclose(wf, ws, atol=2e-3)
            np.testing.assert_array_equal(wf == 0.0, ws == 0.0)

    def test_fast_path_not_taken_with_random_effects(self, rng):
        """Mixed-effect grids must keep the sequential path (probe None)."""
        data = self._data(rng)
        ids = np.arange(data.n) % 5
        data = GameData.build(np.asarray(data.y),
                              shards={"fixed": np.asarray(data.shards["fixed"]),
                                      "re": np.asarray(data.shards["fixed"])},
                              entity_ids={"e": ids})
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs={
                "fixed": FixedEffectConfig("fixed"),
                "per_e": RandomEffectConfig("e", "re"),
            }, n_sweeps=1)
        assert est._fixed_only_reg_grid([est.coordinate_configs]) is None

    def test_best_model_selection_through_fast_path(self, rng):
        data = self._data(rng)
        val = self._data(rng, n=300)
        cfg = OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=1.0,
                              regularize_intercept=True)
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs={"fixed": FixedEffectConfig("fixed", cfg)},
            n_sweeps=1, vectorized_grid=True)
        grid = [{"fixed": FixedEffectConfig(
            "fixed", dataclasses.replace(cfg, reg_weight=wt))}
            for wt in (0.1, 1e5)]
        results = est.fit(data, validation=val, config_grid=grid)
        best = est.best_model(results)
        assert best.configs["fixed"].optimizer.reg_weight == 0.1

    def test_sweeps_route_to_lane_path_with_full_semantics(self, rng):
        """n_sweeps>1 no longer disengages vectorization: it routes to the
        lane-axis grid (game.grid), whose lanes run BOTH warm-started
        sweeps — the original regression (the one-solve fast path silently
        replacing the second sweep) must stay fixed, now by semantics
        rather than by falling back."""
        data = self._data(rng)
        cfg = OptimizerConfig(max_iters=15, reg=reg.l2(), reg_weight=1.0,
                              regularize_intercept=True)
        grid = [{"fixed": FixedEffectConfig(
            "fixed", dataclasses.replace(cfg, reg_weight=wt))}
            for wt in (0.5, 5.0)]

        def run(vectorized):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs={"fixed": FixedEffectConfig("fixed", cfg)},
                n_sweeps=2, warm_start=True, vectorized_grid=vectorized)
            return est.fit(data, config_grid=grid)

        fast_flag, slow = run(True), run(False)
        for rf, rs in zip(fast_flag, slow):
            # two objective entries per point: the second sweep really ran
            assert len(rf.descent.objective_history) == 2
            np.testing.assert_allclose(
                np.asarray(rf.model.coordinates["fixed"].model.coefficients.means),
                np.asarray(rs.model.coordinates["fixed"].model.coefficients.means),
                atol=5e-3)
            np.testing.assert_allclose(rf.descent.objective_history,
                                       rs.descent.objective_history,
                                       rtol=2e-3)
        # plain fit() (no config_grid) stays sequential: two sweeps
        # progress further than one solve from zeros would.
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs={"fixed": FixedEffectConfig("fixed", cfg)},
            n_sweeps=2)
        (r,) = est.fit(data)
        assert len(r.descent.objective_history) == 2

    def test_default_respects_warm_start(self, rng):
        """vectorized_grid=None + warm_start=True (the defaults) must keep
        the sequential warm-started sweep — warm starts the user asked for
        are never silently dropped."""
        data = self._data(rng)
        cfg = OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=1.0,
                              regularize_intercept=True)
        grid = [{"fixed": FixedEffectConfig(
            "fixed", dataclasses.replace(cfg, reg_weight=wt))}
            for wt in (0.5, 5.0)]

        def run(**kw):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs={"fixed": FixedEffectConfig("fixed", cfg)},
                n_sweeps=1, **kw)
            return est.fit(data, config_grid=grid)

        default = run()                                  # warm_start=True
        sequential = run(vectorized_grid=False)
        for rd, rs in zip(default, sequential):
            np.testing.assert_array_equal(
                np.asarray(rd.model.coordinates["fixed"].model.coefficients.means),
                np.asarray(rs.model.coordinates["fixed"].model.coefficients.means))
        # warm_start=False defaults into the vectorized path
        auto = run(warm_start=False)
        forced = run(warm_start=False, vectorized_grid=True)
        for ra, rf in zip(auto, forced):
            np.testing.assert_array_equal(
                np.asarray(ra.model.coordinates["fixed"].model.coefficients.means),
                np.asarray(rf.model.coordinates["fixed"].model.coefficients.means))


class TestVectorizedGameGrid:
    """Mixed (fixed + random effect) reg-weight grids run as lanes of one
    vectorized coordinate descent (game.grid.fit_game_grid)."""

    def _mixed(self, rng, n_entities=25):
        data, w_fixed, w_re, ent = _mixed_effect_logistic(
            rng, n_entities=n_entities, d_fixed=6, d_re=3, rows_lo=5,
            rows_hi=40)
        val, *_ = _mixed_effect_logistic(
            rng, n_entities=n_entities, d_fixed=6, d_re=3, rows_lo=3,
            rows_hi=20)
        return data, val

    def _configs(self, cfg_f, cfg_r):
        return {"fixed": FixedEffectConfig("fixed", cfg_f),
                "per_e": RandomEffectConfig("entity", "per_entity", cfg_r)}

    def _grid(self, cfg_f, cfg_r, pairs):
        return [{"fixed": FixedEffectConfig(
                     "fixed", dataclasses.replace(cfg_f, reg_weight=wf)),
                 "per_e": RandomEffectConfig(
                     "entity", "per_entity",
                     dataclasses.replace(cfg_r, reg_weight=wr))}
                for wf, wr in pairs]

    @pytest.mark.tier2
    def test_mixed_grid_matches_sequential(self, rng):
        """The top round-3 deliverable: lane-axis GAME grid == sequential
        per point (mirroring the fixed-only pin above), with per-lane
        sweeps, validation scores, histories, and RE stats."""
        data, val = self._mixed(rng)
        cfg_f = OptimizerConfig(max_iters=25, reg=reg.l2(), reg_weight=0.1)
        cfg_r = OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=1.0)
        grid = self._grid(cfg_f, cfg_r,
                          [(0.05, 0.5), (0.05, 5.0), (0.5, 0.5), (0.5, 5.0)])

        def run(vectorized):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs=self._configs(cfg_f, cfg_r),
                n_sweeps=2, warm_start=False, vectorized_grid=vectorized)
            if vectorized:
                assert est.would_vectorize(grid)
            return est.fit(data, validation=val, config_grid=grid)

        fast, slow = run(True), run(False)
        assert len(fast) == len(slow) == 4
        for rf, rs in zip(fast, slow):
            np.testing.assert_allclose(
                np.asarray(rf.model["fixed"].model.coefficients.means),
                np.asarray(rs.model["fixed"].model.coefficients.means),
                atol=5e-3)
            np.testing.assert_allclose(
                np.asarray(rf.model["per_e"].coefficients),
                np.asarray(rs.model["per_e"].coefficients), atol=2e-2)
            assert abs(rf.validation_score - rs.validation_score) < 5e-3
            # 2 sweeps × 2 coordinates = 4 objective entries, same curve
            assert len(rf.descent.objective_history) == 4
            np.testing.assert_allclose(rf.descent.objective_history,
                                       rs.descent.objective_history,
                                       rtol=2e-3)
            assert (rf.configs["per_e"].optimizer.reg_weight
                    == rs.configs["per_e"].optimizer.reg_weight)
            stats = rf.descent.coordinate_stats["per_e"][0]
            assert stats.n_entities == 25
            assert stats.n_converged + stats.n_failed <= 25
        # stronger RE regularization must shrink the per-entity coefficients
        norm_small = np.linalg.norm(np.asarray(fast[0].model["per_e"].coefficients))
        norm_big = np.linalg.norm(np.asarray(fast[1].model["per_e"].coefficients))
        assert norm_big < norm_small

    @pytest.mark.tier2
    def test_l1_grid_runs_owlqn_lanes(self, rng):
        """An elastic-net sweep routes the lane solves through OWL-QN and
        matches the sequential path (sparsity included)."""
        data, val = self._mixed(rng)
        cfg_f = OptimizerConfig(max_iters=30, reg=reg.l1(), reg_weight=0.1)
        cfg_r = OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=1.0)
        grid = self._grid(cfg_f, cfg_r, [(0.5, 1.0), (8.0, 1.0)])

        def run(vectorized):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs=self._configs(cfg_f, cfg_r),
                n_sweeps=1, warm_start=False, vectorized_grid=vectorized)
            return est.fit(data, config_grid=grid)

        fast, slow = run(True), run(False)
        for rf, rs in zip(fast, slow):
            wf = np.asarray(rf.model["fixed"].model.coefficients.means)
            ws = np.asarray(rs.model["fixed"].model.coefficients.means)
            np.testing.assert_allclose(wf, ws, atol=5e-3)
            np.testing.assert_array_equal(wf == 0.0, ws == 0.0)
        # the strong-L1 lane is genuinely sparser
        w_hi = np.asarray(fast[1].model["fixed"].model.coefficients.means)
        assert (w_hi == 0.0).sum() > 0

    @pytest.mark.tier2
    def test_runs_on_mesh(self, rng, mesh8):
        """The lane path under a mesh (entity-axis sharded RE chunks,
        row-sharded fixed batch) matches the single-device lane path."""
        data, val = self._mixed(rng)
        cfg_f = OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=0.1)
        cfg_r = OptimizerConfig(max_iters=15, reg=reg.l2(), reg_weight=1.0)
        grid = self._grid(cfg_f, cfg_r, [(0.05, 0.5), (0.5, 5.0)])

        def run(mesh):
            est = GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs=self._configs(cfg_f, cfg_r),
                n_sweeps=1, warm_start=False, vectorized_grid=True,
                mesh=mesh)
            return est.fit(data, validation=val, config_grid=grid)

        on_mesh, single = run(mesh8), run(None)
        for rm, r1 in zip(on_mesh, single):
            np.testing.assert_allclose(
                np.asarray(rm.model["fixed"].model.coefficients.means),
                np.asarray(r1.model["fixed"].model.coefficients.means),
                atol=5e-3)
            np.testing.assert_allclose(
                np.asarray(rm.model["per_e"].coefficients),
                np.asarray(r1.model["per_e"].coefficients), atol=2e-2)

    def test_gate_probes(self, rng):
        """_game_grid_probe accepts reg-only mixed grids and rejects
        anything the lane path cannot replicate."""
        from photon_tpu.game.projector import ProjectionConfig, ProjectorType

        cfg_f = OptimizerConfig(max_iters=10, reg=reg.l2(), reg_weight=0.1)
        cfg_r = OptimizerConfig(max_iters=10, reg=reg.l2(), reg_weight=1.0)
        grid = self._grid(cfg_f, cfg_r, [(0.1, 1.0), (1.0, 2.0)])

        def make(**kw):
            return GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs=self._configs(cfg_f, cfg_r),
                warm_start=False, **kw)

        est = make()
        lanes = est._game_grid_probe(grid)
        assert lanes == {"fixed": [0.1, 1.0], "per_e": [1.0, 2.0]}
        assert est.would_vectorize(grid)
        # n_sweeps > 1 is supported by the mixed path
        assert make(n_sweeps=3).would_vectorize(grid)
        # grid varying a non-reg knob → sequential
        bad = [dict(g) for g in grid]
        bad[1]["fixed"] = FixedEffectConfig(
            "fixed", dataclasses.replace(cfg_f, reg_weight=1.0, max_iters=11))
        assert est._game_grid_probe(bad) is None
        # projection on the RE coordinate → sequential
        proj = make()
        proj.coordinate_configs["per_e"] = RandomEffectConfig(
            "entity", "per_entity", cfg_r,
            projection=ProjectionConfig(ProjectorType.RANDOM, 2))
        assert proj._game_grid_probe(grid) is None
        # normalization → sequential
        from photon_tpu.data.normalization import NormalizationType

        normed = make(
            normalization={"fixed": NormalizationType.STANDARDIZATION})
        assert normed._game_grid_probe(grid) is None
        # warm_start=True default → sequential (never silently dropped)
        warm = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs=self._configs(cfg_f, cfg_r))
        assert not warm.would_vectorize(grid)

    def test_skew_aware_auto_policy(self):
        """Auto mode (vectorized_grid=None) must fall back to sequential on
        strongly skewed reg grids — docs/PERF.md's masking A/B measured the
        lane-axis path 3.7× WORSE at spread 1e5 (lock-step runs every chunk
        to its slowest lane) — while mild geomspace sweeps keep the lane
        path and the explicit tri-state always wins."""
        cfg_f = OptimizerConfig(max_iters=25, reg=reg.l2(), reg_weight=0.1)
        cfg_r = OptimizerConfig(max_iters=20, reg=reg.l2(), reg_weight=1.0)

        def make(**kw):
            return GameEstimator(
                task=TaskType.LOGISTIC_REGRESSION,
                coordinate_configs=self._configs(cfg_f, cfg_r),
                warm_start=False, **kw)

        skewed = self._grid(cfg_f, cfg_r,
                            [(100.0, 1.0), (10.0, 1.0), (1.0, 1.0),
                             (1e-3, 1.0)])   # the A/B's skewed profile
        mild = self._grid(cfg_f, cfg_r,
                          [(w, 1.0) for w in np.geomspace(1e-4, 1e-2, 4)])
        auto = make()
        assert auto._grid_reg_skew(skewed) > 1e4
        assert not auto.would_vectorize(skewed)
        assert auto.would_vectorize(mild)
        # explicit tri-state overrides the heuristic in both directions
        assert make(vectorized_grid=True).would_vectorize(skewed)
        assert not make(vectorized_grid=False).would_vectorize(mild)
        # a zero-reg lane among heavy ones counts as unconditioned (slow)
        mixed_zero = self._grid(cfg_f, cfg_r,
                                [(0.0, 1.0), (500.0, 1.0), (50.0, 1.0)])
        assert not auto.would_vectorize(mixed_zero)


def test_poisson_game_end_to_end(rng):
    """GAME with a second GLM family: per-entity Poisson rates recovered
    through coordinate descent (the machinery is task-generic; this pins it
    beyond logistic/linear)."""
    n_entities, d_f = 25, 4
    rows = rng.integers(40, 80, size=n_entities)
    ent = np.repeat(np.arange(n_entities), rows)
    rng.shuffle(ent)
    n = ent.shape[0]
    Xf = (rng.normal(size=(n, d_f)) * 0.3).astype(np.float32)
    ones = np.ones((n, 1), np.float32)
    w_f = rng.normal(size=d_f) * 0.4
    u = rng.normal(size=n_entities) * 0.8  # per-entity log-rate intercepts
    lam = np.exp(np.clip(Xf @ w_f + u[ent], -4, 4))
    y = rng.poisson(lam).astype(np.float32)
    data = GameData.build(y, {"fixed": Xf, "bias": ones}, {"e": ent})
    est = GameEstimator(
        task=TaskType.POISSON_REGRESSION,
        coordinate_configs={
            "fixed": FixedEffectConfig(
                "fixed", OptimizerConfig(max_iters=60, reg=reg.l2(),
                                         reg_weight=1e-2)),
            "per_e": RandomEffectConfig(
                "e", "bias", OptimizerConfig(max_iters=40, reg=reg.l2(),
                                             reg_weight=0.5)),
        },
        n_sweeps=2,
    )
    model = est.fit(data)[0].model
    got_w = np.asarray(model["fixed"].model.weights)
    np.testing.assert_allclose(got_w, w_f, atol=0.15)
    u_hat = np.asarray(model["per_e"].coefficients)[:, 0]
    keys = np.asarray(model["per_e"].entity_keys).astype(int)
    corr = np.corrcoef(u_hat, u[keys])[0, 1]
    assert corr > 0.85
    # predicted rates correlate with true rates
    mean = np.asarray(predict_mean(model, data))
    assert np.corrcoef(mean, lam)[0, 1] > 0.9
