"""Hyperparameter-tuning tests (SURVEY.md §4 'GP tuner improves over random
on a synthetic bowl')."""
import numpy as np
import pytest

from photon_tpu.tuning import (
    SearchRange,
    SearchSpace,
    candidates,
    expected_improvement,
    fit_gp,
    tune,
)


class TestSearchSpace:
    def test_linear_and_log_mapping(self):
        space = SearchSpace([
            SearchRange(0.0, 10.0),
            SearchRange(1e-4, 1e2, log_scale=True),
        ])
        U = np.array([[0.0, 0.0], [1.0, 1.0], [0.5, 0.5]])
        X = space.from_unit(U)
        np.testing.assert_allclose(X[0], [0.0, 1e-4], rtol=1e-6)
        np.testing.assert_allclose(X[1], [10.0, 1e2], rtol=1e-6)
        np.testing.assert_allclose(X[2, 1], 1e-1, rtol=1e-6)  # log midpoint
        np.testing.assert_allclose(space.to_unit(X), U, atol=1e-9)

    def test_invalid_ranges(self):
        with pytest.raises(ValueError):
            SearchRange(1.0, 1.0)
        with pytest.raises(ValueError):
            SearchRange(0.0, 1.0, log_scale=True)

    def test_candidate_methods(self):
        space = SearchSpace([SearchRange(0, 1), SearchRange(0, 1)])
        for method in ("sobol", "random"):
            C = candidates(space, 64, method, seed=3)
            assert C.shape == (64, 2)
            assert (C >= 0).all() and (C <= 1).all()
        G = candidates(space, 0, "grid", points_per_dim=4)
        assert G.shape == (16, 2)

    def test_sobol_better_spread_than_random(self):
        """Sobol's low-discrepancy property: max nearest-neighbor gap is
        smaller than iid uniform's on the same budget."""
        space = SearchSpace([SearchRange(0, 1)] * 2)
        S = candidates(space, 128, "sobol", seed=0)
        R = candidates(space, 128, "random", seed=0)

        def max_nn_gap(P):
            d = np.linalg.norm(P[:, None] - P[None, :], axis=-1)
            np.fill_diagonal(d, np.inf)
            return d.min(1).max()

        assert max_nn_gap(S) < max_nn_gap(R)


class TestGP:
    def test_posterior_interpolates_noiseless_data(self, rng):
        X = rng.uniform(size=(30, 2)).astype(np.float32)
        y = np.sin(3 * X[:, 0]) + X[:, 1] ** 2
        gp = fit_gp(X, y)
        mean, std = gp.predict(X)
        assert float(np.abs(np.asarray(mean) - y).max()) < 0.05
        # predictive uncertainty grows away from the data
        far = np.full((1, 2), 5.0, np.float32)
        _, std_far = gp.predict(far)
        assert float(std_far[0]) > float(np.asarray(std).mean()) * 2

    @pytest.mark.parametrize("kernel", ["rbf", "matern52"])
    def test_kernels_predict_held_out(self, rng, kernel):
        X = rng.uniform(size=(60, 1)).astype(np.float32)
        y = np.sin(6 * X[:, 0])
        gp = fit_gp(X, y, kernel=kernel)
        Xq = np.linspace(0.05, 0.95, 17, dtype=np.float32)[:, None]
        mean, _ = gp.predict(Xq)
        np.testing.assert_allclose(
            np.asarray(mean), np.sin(6 * Xq[:, 0]), atol=0.1)

    def test_expected_improvement_prefers_promising_region(self, rng):
        X = np.array([[0.1], [0.5], [0.9]], np.float32)
        y = np.array([1.0, 0.2, 1.0], np.float32)  # minimum near 0.5
        gp = fit_gp(X, y)
        Xq = np.linspace(0, 1, 101, dtype=np.float32)[:, None]
        ei = np.asarray(expected_improvement(gp, Xq, float(y.min())))
        assert (ei >= -1e-9).all()
        assert 0.25 < Xq[int(np.argmax(ei)), 0] < 0.75


class TestTuner:
    @staticmethod
    def _bowl(x):
        """Minimum 0.0 at (0.3, 1.0-in-log-space)."""
        return float((x[0] - 0.3) ** 2 + (np.log10(x[1]) - 0.0) ** 2)

    def _space(self):
        return SearchSpace([
            SearchRange(0.0, 1.0),
            SearchRange(1e-3, 1e3, log_scale=True),
        ])

    @pytest.mark.tier2
    def test_gp_beats_random_on_bowl(self):
        budget = 18
        space = self._space()
        gp_best = [
            tune(self._bowl, space, n_iters=budget, method="gp", seed=s).best_y
            for s in range(3)
        ]
        rnd_best = [
            tune(self._bowl, space, n_iters=budget, method="random", seed=s).best_y
            for s in range(3)
        ]
        assert np.mean(gp_best) < np.mean(rnd_best)
        assert np.mean(gp_best) < 0.05  # actually found the basin

    def test_history_monotone_and_shapes(self):
        space = self._space()
        r = tune(self._bowl, space, n_iters=8, method="sobol", seed=1)
        assert r.xs.shape == (8, 2) and r.ys.shape == (8,)
        h = r.history()
        assert (np.diff(h) <= 1e-12).all()
        assert r.best_y == pytest.approx(h[-1])

    def test_warm_start_observations(self):
        space = self._space()
        # seed the GP with the near-optimum; it must not get worse
        r = tune(self._bowl, space, n_iters=6, method="gp",
                 initial_observations=[(np.array([0.3, 1.0]), 0.0)])
        assert r.best_y <= 1e-9


class TestBatchedTuning:
    def test_batched_gp_beats_random_on_bowl(self, rng):
        from photon_tpu.tuning import SearchRange, SearchSpace, tune

        space = SearchSpace([SearchRange(-4.0, 4.0), SearchRange(-4.0, 4.0)])
        calls = []

        def evaluate_batch(X):
            calls.append(len(X))
            return [float(np.sum((x - 1.2) ** 2)) for x in X]

        out = tune(None, space, n_iters=21, n_seed=5, batch_size=4, seed=3,
                   evaluate_batch=evaluate_batch)
        assert len(out.ys) == 21
        # one call for the seeds, then ceil(16/4) batched rounds
        assert calls == [5, 4, 4, 4, 4]
        rnd = tune(None, space, n_iters=21, method="random", seed=3,
                   evaluate_batch=lambda X: [float(np.sum((x - 1.2) ** 2))
                                             for x in X])
        assert out.best_y <= rnd.best_y + 1e-6

    def test_qei_single_point_matches_closed_form(self, rng):
        """The fantasy math: MC q-EI of a single point converges to the
        analytic expected improvement (the brute-force pin of the joint
        sampling path)."""
        from photon_tpu.tuning.acquisition import qei

        X = rng.uniform(size=(12, 2)).astype(np.float32)
        y_clean = np.sum((X - 0.4) ** 2, axis=1)
        # both a near-noiseless fit and a NOISY one (regression: the joint
        # sampler drew latent values without the fitted observation noise,
        # so qei collapsed to ~0 under noisy fits while EI did not)
        for y in (y_clean, y_clean + 0.3 * rng.normal(size=12)):
            gp = fit_gp(X, y)
            best = float(y.min())
            pts = rng.uniform(size=(5, 2)).astype(np.float32)
            ei = np.asarray(expected_improvement(gp, pts, best))
            for i in range(5):
                mc = qei(gp, pts[i:i + 1], best, n_samples=40000, seed=7)
                # MC std error ~ sigma/sqrt(S); tolerance sized generously
                assert abs(mc - float(ei[i])) < 0.07 * max(float(ei[i]),
                                                           0.02), \
                    (i, mc, float(ei[i]))

    def test_qei_greedy_near_exhaustive(self, rng):
        """Greedy q-EI picks a batch whose joint value is close to the
        exhaustively-best pair from the pool (submodular greedy bound)."""
        from photon_tpu.tuning.acquisition import qei, qei_greedy

        X = rng.uniform(size=(10, 1)).astype(np.float32)
        y = np.sum((X - 0.3) ** 2, axis=1)
        gp = fit_gp(X, y)
        best = float(y.min())
        pool = np.linspace(0, 1, 24, dtype=np.float32)[:, None]
        picked = qei_greedy(gp, pool, best, q=2, n_samples=4096, seed=0)
        assert len(set(picked)) == 2  # distinct points
        v_greedy = qei(gp, pool[picked], best, n_samples=20000, seed=1)
        v_best = max(
            qei(gp, pool[[i, j]], best, n_samples=4096, seed=1)
            for i in range(24) for j in range(i + 1, 24))
        assert v_greedy >= 0.63 * v_best  # (1 − 1/e) up to MC noise

    @pytest.mark.tier2
    def test_qei_batches_match_or_beat_constant_liar_on_bowl(self):
        """Same budget, same seeds: true-q-EI batches end at least as close
        to the bowl optimum as the constant-liar heuristic (the VERDICT
        acceptance bar). Deterministic given the fixed seeds."""
        from photon_tpu.tuning import SearchRange, SearchSpace, tune

        space = SearchSpace([SearchRange(-4.0, 4.0), SearchRange(-4.0, 4.0)])

        def f(X):
            return [float(np.sum((x - 1.2) ** 2)) for x in X]

        results = {}
        for bm in ("qei", "liar"):
            best = []
            for seed in (0, 1, 2):
                out = tune(None, space, n_iters=21, n_seed=5, batch_size=4,
                           seed=seed, evaluate_batch=f, batch_method=bm)
                best.append(out.best_y)
            results[bm] = float(np.mean(best))
        assert results["qei"] <= results["liar"] + 1e-6
        assert results["qei"] < 0.2  # actually near the optimum

    def test_batch_requires_some_evaluator(self):
        from photon_tpu.tuning import SearchRange, SearchSpace, tune

        space = SearchSpace([SearchRange(0.0, 1.0)])
        import pytest as _pytest

        with _pytest.raises(ValueError, match="evaluate or evaluate_batch"):
            tune(None, space, n_iters=3)

    def test_tune_glm_reg_end_to_end(self, rng):
        from photon_tpu.data.dataset import make_batch
        from photon_tpu.ops.losses import TaskType
        from photon_tpu.optim import regularization as reg
        from photon_tpu.optim.config import OptimizerConfig
        from photon_tpu.tuning.tuner import tune_glm_reg

        n, d = 900, 20
        X = rng.normal(size=(n, d)).astype(np.float32)
        w = (rng.normal(size=d) * (rng.uniform(size=d) < 0.4)).astype(
            np.float32) * 1.5
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w)))).astype(
            np.float32)
        tr = make_batch(X[:700], y[:700])
        va = make_batch(X[700:], y[700:])
        cfg = OptimizerConfig(max_iters=40, reg=reg.l2(), reg_weight=0.0,
                              regularize_intercept=True)
        model, best_wt, result = tune_glm_reg(
            tr, TaskType.LOGISTIC_REGRESSION, cfg, va,
            n_iters=12, batch_size=4, reg_range=(1e-3, 1e3), seed=1)
        assert 1e-3 <= best_wt <= 1e3
        assert len(result.ys) == 12
        # the tuner's pick must beat the WORST candidate it saw by a margin
        assert result.best_y <= np.max(result.ys) - 1e-4
        # and the returned model actually scores well
        from sklearn.metrics import roc_auc_score

        p = np.asarray(model.predict_mean(va.X))
        assert roc_auc_score(np.asarray(va.y), p) > 0.8
