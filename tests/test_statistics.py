"""FeatureSummary (reference: stat.BasicStatisticalSummary) vs numpy."""
import numpy as np
import pytest
import scipy.sparse as sp

from photon_tpu.data.matrix import from_scipy_csr, to_hybrid
from photon_tpu.data.normalization import NormalizationContext, NormalizationType
from photon_tpu.data.statistics import FeatureSummary, summarize_features


@pytest.fixture
def sparse_with_zeros(rng):
    """Sparse matrix with implicit zeros, an all-zero column, and negatives."""
    n, d = 240, 40
    M = sp.random(n, d, density=0.15, random_state=7,
                  data_rvs=lambda k: rng.normal(size=k)).tocsr()
    M[:, 11] = 0.0  # all-zero column
    M.eliminate_zeros()
    return M


def _dense_ref(Xd):
    n = Xd.shape[0]
    return dict(
        mean=Xd.mean(0), variance=Xd.var(0), minimum=Xd.min(0),
        maximum=Xd.max(0), abs_max=np.abs(Xd).max(0),
        norm_l1=np.abs(Xd).sum(0), norm_l2=np.sqrt((Xd * Xd).sum(0)),
        num_nonzeros=(Xd != 0).sum(0).astype(float), count=n)


def _check(s: FeatureSummary, Xd):
    ref = _dense_ref(np.asarray(Xd, np.float64))
    assert s.count == ref["count"]
    for k, v in ref.items():
        if k == "count":
            continue
        np.testing.assert_allclose(getattr(s, k), v, rtol=1e-4, atol=1e-5,
                                   err_msg=k)


def test_dense_matches_numpy(rng):
    Xd = rng.normal(size=(300, 17)).astype(np.float32)
    Xd[:, 4] = 0.0
    _check(FeatureSummary.compute(Xd), Xd)


def test_sparse_matches_dense(sparse_with_zeros):
    X = from_scipy_csr(sparse_with_zeros)
    _check(FeatureSummary.compute(X), sparse_with_zeros.toarray())


def test_sparse_implicit_zero_extrema(rng):
    # A column whose stored values are all positive still has min 0 when
    # some rows miss it (full-vector semantics).
    M = sp.csr_matrix(np.array([[2.0, -3.0], [5.0, -1.0], [0.0, -2.0]]))
    s = FeatureSummary.compute(from_scipy_csr(M))
    assert s.minimum[0] == 0.0 and s.maximum[0] == 5.0
    # Column 1 is fully stored: min stays negative, max is max(stored, 0)?
    # no — no implicit zero, so extrema are the stored ones.
    assert s.minimum[1] == -3.0 and s.maximum[1] == -1.0


def test_mesh_matches_single(sparse_with_zeros, mesh8):
    X = from_scipy_csr(sparse_with_zeros)
    s1 = FeatureSummary.compute(X)
    s2 = FeatureSummary.compute(X, mesh=mesh8)
    for f in ("mean", "variance", "minimum", "maximum", "num_nonzeros"):
        np.testing.assert_allclose(getattr(s2, f), getattr(s1, f),
                                   rtol=1e-5, atol=1e-6, err_msg=f)


def test_mesh_requires_aligned_rows(rng, mesh8):
    with pytest.raises(ValueError, match="divide"):
        FeatureSummary.compute(rng.normal(size=(101, 4)).astype(np.float32),
                               mesh=mesh8)


def test_hybrid_rejected(sparse_with_zeros):
    X = to_hybrid(from_scipy_csr(sparse_with_zeros), d_dense=8)
    with pytest.raises(TypeError, match="before to_hybrid"):
        FeatureSummary.compute(X)


def test_save_load_roundtrip(tmp_path, rng):
    s = FeatureSummary.compute(rng.normal(size=(64, 5)).astype(np.float32))
    p = str(tmp_path / "summary.json")
    s.save(p)
    s2 = FeatureSummary.load(p)
    assert s2.count == s.count
    np.testing.assert_allclose(s2.variance, s.variance, rtol=1e-6)
    np.testing.assert_allclose(s2.num_nonzeros, s.num_nonzeros)


def test_normalization_from_summary_matches_build(rng):
    Xd = np.concatenate(
        [rng.normal(size=(128, 6)).astype(np.float32) * 3.0 + 1.0,
         np.ones((128, 1), np.float32)], axis=1)
    s = FeatureSummary.compute(Xd)
    for nt in (NormalizationType.STANDARDIZATION,
               NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
               NormalizationType.SCALE_WITH_MAX_MAGNITUDE):
        a = NormalizationContext.build(Xd, nt)
        b = NormalizationContext.from_summary(s, nt)
        np.testing.assert_allclose(b.factors, a.factors, rtol=1e-4,
                                   err_msg=str(nt))
        if a.shifts is not None:
            np.testing.assert_allclose(b.shifts, a.shifts, rtol=1e-4,
                                       atol=1e-5)


def test_summarize_features_table(rng):
    Xd = rng.normal(size=(32, 3)).astype(np.float32)
    tab = summarize_features(Xd, names=["a", "b", "c"])
    assert set(tab) == {"a", "b", "c"}
    np.testing.assert_allclose(tab["b"]["mean"], Xd[:, 1].mean(), atol=1e-5)


def test_large_mean_variance_no_cancellation(rng):
    # E[x^2]-E[x]^2 in f32 would report ~0 variance here; the mean-shifted
    # second pass must recover it (regression: from_summary silently
    # disabling standardization on large-offset features).
    col = rng.normal(5000.0, 0.1, size=4096).astype(np.float32)
    Xd = col[:, None]
    s = FeatureSummary.compute(Xd)
    true_var = np.asarray(col, np.float64).var()
    np.testing.assert_allclose(s.variance[0], true_var, rtol=0.05)
    a = NormalizationContext.build(Xd, NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
                                   intercept_index=None)
    b = NormalizationContext.from_summary(
        s, NormalizationType.SCALE_WITH_STANDARD_DEVIATION, intercept_index=None)
    np.testing.assert_allclose(b.factors, a.factors, rtol=0.05)


def test_large_mean_variance_sparse(rng):
    # Same cancellation check through the sparse path (stored entries +
    # implicit-zero term).
    col = rng.normal(3000.0, 0.5, size=512)
    M = sp.csr_matrix(np.stack([col, np.zeros(512)], 1))
    M[::2, 1] = 1.0
    M.eliminate_zeros()
    s = FeatureSummary.compute(from_scipy_csr(M.tocsr()))
    np.testing.assert_allclose(s.variance[0], col.var(), rtol=0.05)
    np.testing.assert_allclose(s.variance[1], 0.25, rtol=1e-3)


def test_roundtrip_precision_large_counts():
    # num_nonzeros must survive save/load exactly above 2^24.
    s = FeatureSummary(
        count=30_000_000, mean=np.array([1.0]), variance=np.array([2.0]),
        minimum=np.array([0.0]), maximum=np.array([9.0]),
        abs_max=np.array([9.0]), norm_l1=np.array([3.0]),
        norm_l2=np.array([4.0]), num_nonzeros=np.array([20_000_001]))
    import tempfile, os
    p = os.path.join(tempfile.mkdtemp(), "s.json")
    s.save(p)
    s2 = FeatureSummary.load(p)
    assert int(s2.num_nonzeros[0]) == 20_000_001
    assert s2.num_nonzeros.dtype == np.int64


def test_make_batch_accepts_sharded_hybrid(sparse_with_zeros, rng):
    from photon_tpu.data.dataset import make_batch
    from photon_tpu.data.matrix import from_scipy_csr as f, shard_hybrid

    X = shard_hybrid(f(sparse_with_zeros), 4, d_dense=8)
    b = make_batch(X, rng.uniform(size=X.shape[0]).astype(np.float32))
    assert b.X is X and b.n == X.shape[0]
