"""HybridRows (hot-dense / cold-sparse split) vs plain SparseRows parity."""
import numpy as np
import jax.numpy as jnp
import pytest
import scipy.sparse as sp

from photon_tpu.data.dataset import cast_features, make_batch, pad_batch
from photon_tpu.data.matrix import (
    HybridRows,
    SparseRows,
    from_scipy_csr,
    matvec,
    rmatvec,
    sq_rmatvec,
    to_hybrid,
    weighted_gram,
)
from photon_tpu.models.training import train_glm
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig


@pytest.fixture
def power_law(rng):
    """Power-law sparse matrix: a few hot columns, long cold tail."""
    n, d, k = 400, 500, 12
    cols = np.minimum((rng.pareto(1.0, size=(n, k)) * 20).astype(np.int64),
                      d - 1)
    vals = rng.normal(size=(n, k)).astype(np.float32)
    rows = np.repeat(np.arange(n), k)
    M = sp.csr_matrix((vals.ravel(), (rows, cols.ravel())), shape=(n, d))
    M.sum_duplicates()
    return from_scipy_csr(M)


class TestHybridParity:
    def test_ops_match_sparse(self, power_law, rng):
        X = power_law
        H = to_hybrid(X, d_dense=32)
        assert H.shape == X.shape
        w = jnp.asarray(rng.normal(size=X.n_features), jnp.float32)
        r = jnp.asarray(rng.normal(size=X.shape[0]), jnp.float32)
        np.testing.assert_allclose(np.asarray(matvec(H, w)),
                                   np.asarray(matvec(X, w)),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(rmatvec(H, r)),
                                   np.asarray(rmatvec(X, r)),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(sq_rmatvec(H, r)),
                                   np.asarray(sq_rmatvec(X, r)),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(weighted_gram(H, r)),
                                   np.asarray(weighted_gram(X, r)),
                                   rtol=1e-3, atol=1e-3)

    def test_hot_columns_really_dense(self, power_law):
        H = to_hybrid(power_law, d_dense=32)
        # The selected columns carry no tail nnz.
        tail_cols = set(np.asarray(H.tail_cols)[
            np.asarray(H.tail_vals) != 0].ravel())
        assert tail_cols.isdisjoint(set(np.asarray(H.dense_cols)))
        # Power-law data: 32 of 500 columns should cover most nnz.
        nnz_dense = int((np.asarray(H.dense) != 0).sum())
        nnz_tail = int((np.asarray(H.tail_vals) != 0).sum())
        assert nnz_dense > nnz_tail
        # Flat tail is exact-size (no per-row padding) and row-sorted.
        rows = np.asarray(H.tail_rows)
        assert (np.diff(rows) >= 0).all()

    def test_train_glm_hybrid(self, power_law, rng):
        X = power_law
        n = X.shape[0]
        w_true = rng.normal(size=X.n_features).astype(np.float32)
        z = np.asarray(matvec(X, jnp.asarray(w_true)))
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(np.float32)
        cfg = OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=1.0,
                              regularize_intercept=True)
        m_s, r_s = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                             cfg)
        m_h, r_h = train_glm(make_batch(to_hybrid(X, 32), y),
                             TaskType.LOGISTIC_REGRESSION, cfg)
        assert bool(r_h.converged)
        np.testing.assert_allclose(np.asarray(m_h.coefficients.means),
                                   np.asarray(m_s.coefficients.means),
                                   atol=2e-3)

    def test_pad_and_cast(self, power_law, rng):
        n = power_law.shape[0]
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        b = make_batch(to_hybrid(power_law, 16), y)
        padded = pad_batch(b, n + 24)
        assert padded.X.dense.shape[0] == n + 24
        w = jnp.asarray(rng.normal(size=power_law.n_features), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(matvec(padded.X, w))[:n],
            np.asarray(matvec(b.X, w)), rtol=1e-5, atol=1e-5)
        b16 = cast_features(b)
        assert b16.X.dense.dtype == jnp.bfloat16
        assert b16.X.tail_vals.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(matvec(b16.X, w)),
                                   np.asarray(matvec(b.X, w)),
                                   rtol=0.05, atol=0.1)


class TestShardedHybrid:
    """ShardedHybridRows: the mesh-ready per-shard-tail layout."""

    def test_global_ops_match_sparse(self, power_law, rng):
        from photon_tpu.data.matrix import shard_hybrid

        X = power_law
        S = shard_hybrid(X, n_shards=8, d_dense=32)
        assert S.n_shards == 8 and S.shape == X.shape
        w = jnp.asarray(rng.normal(size=X.n_features), jnp.float32)
        r = jnp.asarray(rng.normal(size=X.shape[0]), jnp.float32)
        np.testing.assert_allclose(np.asarray(matvec(S, w)),
                                   np.asarray(matvec(X, w)),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(rmatvec(S, r)),
                                   np.asarray(rmatvec(X, r)),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(sq_rmatvec(S, r)),
                                   np.asarray(sq_rmatvec(X, r)),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(weighted_gram(S, r)),
                                   np.asarray(weighted_gram(X, r)),
                                   rtol=1e-3, atol=1e-3)

    def test_local_views_tile_the_matrix(self, power_law, rng):
        """Concatenating each shard's local() matvec == the global matvec."""
        import dataclasses

        from photon_tpu.data.matrix import shard_hybrid

        X = shard_hybrid(power_law, n_shards=8, d_dense=32)
        w = jnp.asarray(rng.normal(size=X.n_features), jnp.float32)
        n_local = X.n_local
        pieces = []
        for s in range(X.n_shards):
            local = dataclasses.replace(
                X, dense=X.dense[s * n_local:(s + 1) * n_local],
                tail_rows=X.tail_rows[s:s + 1],
                tail_cols=X.tail_cols[s:s + 1],
                tail_vals=X.tail_vals[s:s + 1]).local()
            # per-shard rows ascending (sorted segment_sum contract)
            assert (np.diff(np.asarray(local.tail_rows)) >= 0).all()
            pieces.append(np.asarray(matvec(local, w)))
        np.testing.assert_allclose(np.concatenate(pieces),
                                   np.asarray(matvec(X, w)),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("opt", ["LBFGS", "TRON", "OWLQN"])
    def test_train_glm_sharded_matches_single(self, power_law, rng, mesh8,
                                              opt):
        from photon_tpu.data.dataset import shard_hybrid_batch
        from photon_tpu.optim.config import OptimizerType

        X = power_law
        n = X.shape[0]
        w_true = rng.normal(size=X.n_features).astype(np.float32) * 0.5
        z = np.asarray(matvec(X, jnp.asarray(w_true)))
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(np.float32)
        is_l1 = opt == "OWLQN"
        cfg = OptimizerConfig(
            optimizer=OptimizerType[opt], max_iters=40,
            reg=reg.l1() if is_l1 else reg.l2(), reg_weight=1.0,
            regularize_intercept=True)
        m_ref, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                             cfg)
        b = shard_hybrid_batch(make_batch(X, y), mesh8.devices.size,
                               d_dense=32)
        m_sh, res = train_glm(b, TaskType.LOGISTIC_REGRESSION, cfg,
                              mesh=mesh8)
        assert not bool(res.failed)
        np.testing.assert_allclose(np.asarray(m_sh.coefficients.means),
                                   np.asarray(m_ref.coefficients.means),
                                   atol=5e-3)

    def test_sharded_variances_match_single(self, power_law, rng, mesh8):
        from photon_tpu.data.dataset import shard_hybrid_batch
        from photon_tpu.models.variance import VarianceComputationType

        X = power_law
        n = X.shape[0]
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        cfg = OptimizerConfig(max_iters=25, reg=reg.l2(), reg_weight=2.0,
                              regularize_intercept=True)
        m_ref, _ = train_glm(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                             cfg, variance=VarianceComputationType.SIMPLE)
        b = shard_hybrid_batch(make_batch(X, y), mesh8.devices.size,
                               d_dense=32)
        m_sh, _ = train_glm(b, TaskType.LOGISTIC_REGRESSION, cfg, mesh=mesh8,
                            variance=VarianceComputationType.SIMPLE)
        np.testing.assert_allclose(np.asarray(m_sh.coefficients.variances),
                                   np.asarray(m_ref.coefficients.variances),
                                   rtol=1e-3, atol=1e-3)

    def test_mismatched_shards_raise(self, power_law, rng, mesh8):
        from photon_tpu.data.dataset import shard_hybrid_batch

        n = power_law.shape[0]
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        b = shard_hybrid_batch(make_batch(power_law, y), 4, d_dense=16)
        with pytest.raises(ValueError, match="4 shards"):
            train_glm(b, TaskType.LOGISTIC_REGRESSION,
                      OptimizerConfig(max_iters=2), mesh=mesh8)

    def test_plain_hybrid_under_mesh_points_at_sharded(self, power_law, rng,
                                                       mesh8):
        n = power_law.shape[0]
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        b = make_batch(to_hybrid(power_law, 16), y)
        with pytest.raises(ValueError, match="shard_hybrid_batch"):
            train_glm(b, TaskType.LOGISTIC_REGRESSION,
                      OptimizerConfig(max_iters=2), mesh=mesh8)

    def test_single_device_global_view_owlqn(self, power_law, rng):
        """A ShardedHybridRows batch also works WITHOUT a mesh (global view),
        including the OWLQN route whose fused-padding branch must not try to
        pad the laid-out shards (regression: pad_batch ValueError)."""
        from photon_tpu.data.dataset import shard_hybrid_batch

        n = power_law.shape[0]
        y = (rng.uniform(size=n) < 0.5).astype(np.float32)
        b = shard_hybrid_batch(make_batch(power_law, y), 8, d_dense=16)
        cfg = OptimizerConfig(max_iters=25, reg=reg.l1(), reg_weight=2.0,
                              regularize_intercept=True)
        m_sh, res = train_glm(b, TaskType.LOGISTIC_REGRESSION, cfg)
        m_ref, _ = train_glm(make_batch(power_law, y),
                             TaskType.LOGISTIC_REGRESSION, cfg)
        assert not bool(res.failed)
        np.testing.assert_allclose(np.asarray(m_sh.coefficients.means),
                                   np.asarray(m_ref.coefficients.means),
                                   atol=5e-3)

    @pytest.mark.parametrize(
        "l1",
        [pytest.param(False, marks=pytest.mark.cpu_parity_drift), True])
    def test_grid_on_sharded_hybrid(self, power_law, rng, mesh8, l1):
        """train_glm_grid over a ShardedHybridRows batch: vmapped lanes
        inside the shard_map solver, parity with single-device grid lanes."""
        from photon_tpu.data.dataset import shard_hybrid_batch
        from photon_tpu.models.training import train_glm_grid

        X = power_law
        n = X.shape[0]
        z = np.asarray(matvec(X, jnp.asarray(
            rng.normal(size=X.n_features).astype(np.float32) * 0.5)))
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-z))).astype(np.float32)
        cfg = OptimizerConfig(max_iters=30,
                              reg=reg.l1() if l1 else reg.l2(),
                              reg_weight=0.0, regularize_intercept=True)
        weights = [0.5, 5.0]
        ref = train_glm_grid(make_batch(X, y), TaskType.LOGISTIC_REGRESSION,
                             cfg, weights)
        b = shard_hybrid_batch(make_batch(X, y), mesh8.devices.size,
                               d_dense=32)
        got = train_glm_grid(b, TaskType.LOGISTIC_REGRESSION, cfg, weights,
                             mesh=mesh8)
        for (m_r, _), (m_g, r_g) in zip(ref, got):
            assert not bool(r_g.failed)
            np.testing.assert_allclose(np.asarray(m_g.coefficients.means),
                                       np.asarray(m_r.coefficients.means),
                                       atol=5e-3)


class TestDeviceDenseBuild:
    """to_hybrid(device_dense_dtype=...) scatters the hot block on device
    from the compact COO (the ~10x-fewer-tunnel-bytes bench load path) —
    it must match the host bincount build exactly up to the storage cast."""

    def test_matches_host_build(self, rng=np.random.default_rng(3)):
        n, k, d = 400, 6, 5000
        ind = rng.integers(0, d, (n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        val[rng.uniform(size=(n, k)) < 0.2] = 0.0  # padding slots
        # force duplicate (row, col) entries: summed on both paths
        ind[:, 1] = ind[:, 0]
        X = SparseRows(ind, val, d)
        host = to_hybrid(X, 64)
        dev = to_hybrid(X, 64, device_dense_dtype=jnp.float32)
        np.testing.assert_array_equal(host.dense_cols, dev.dense_cols)
        np.testing.assert_allclose(np.asarray(dev.dense), host.dense,
                                   rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(host.tail_rows, dev.tail_rows)
        np.testing.assert_array_equal(host.tail_cols, dev.tail_cols)
        np.testing.assert_array_equal(host.tail_vals, dev.tail_vals)

    def test_bf16_storage_matches_cast_host(self):
        rng = np.random.default_rng(4)
        n, k, d = 300, 5, 3000
        ind = rng.integers(0, d, (n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        X = SparseRows(ind, val, d)
        host = cast_features(make_batch(to_hybrid(X, 32), np.zeros(n)))
        dev = to_hybrid(X, 32, device_dense_dtype=jnp.bfloat16)
        assert dev.dense.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(dev.dense, np.float32),
            np.asarray(host.X.dense, np.float32))

    def test_chunked_scatter_matches(self, monkeypatch):
        """The row-chunked device scatter (bounded f32 intermediate) is
        identical to the one-shot scatter."""
        import photon_tpu.data.matrix as matrix_mod

        rng = np.random.default_rng(5)
        n, k, d = 700, 6, 4000
        ind = rng.integers(0, d, (n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        X = SparseRows(ind, val, d)
        one_shot = to_hybrid(X, 48, device_dense_dtype=jnp.float32)
        monkeypatch.setattr(matrix_mod, "_SCATTER_CHUNK_ELEMS", 48 * 128)
        chunked = to_hybrid(X, 48, device_dense_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(one_shot.dense),
                                      np.asarray(chunked.dense))
