"""Round 20: the fused int8 serving-rung Pallas kernel.

The serving-side roofline claims, pinned bitwise where the design says
bitwise:

- One rung's whole quantized score — dequant + fixed-effect matvec +
  per-entity gather-dot, coordinate order — fused into a single
  `pallas_call` reproduces the XLA rung BIT FOR BIT in interpret mode,
  cold-miss row (all-entities-unseen) included.
- The fallback ladder never errors and never changes bits: past the
  VMEM budget the rung stays on XLA; mode flips never move a rung's
  dispatch signature (only its executable).
- The AOT key carries the kernel route (``:pk``), because a stored
  export replays WITHOUT tracing — the trace-time verdict must be part
  of the file identity.
- A `continual.hot_swap` invalidates the ladder's quantized-block cache
  (`_qdev`): the next kernel-path dispatch re-quantizes and scores the
  NEW model through the SAME executables.
"""
import copy
import dataclasses

import numpy as np
import pytest

import jax.numpy as jnp

from photon_tpu import kernels as K
from photon_tpu import serving
from photon_tpu.data.matrix import SparseRows
from photon_tpu.ops.losses import TaskType

pytestmark = pytest.mark.release_programs


def _ladder(quantize="int8", eps=0.5, E=32, df=12, dr=6, k=3):
    from photon_tpu.game.model import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
    from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel

    rng = np.random.default_rng(8)
    task = TaskType.LOGISTIC_REGRESSION
    keys = np.asarray(sorted(str(i) for i in range(E)))
    model = GameModel({
        "fixed": FixedEffectModel(GeneralizedLinearModel(
            Coefficients(jnp.asarray(
                rng.normal(size=df).astype(np.float32))), task),
            "global"),
        "perMember": RandomEffectModel(
            entity_name="memberId", feature_shard="member", task=task,
            coefficients=jnp.asarray(
                rng.normal(size=(E, dr)).astype(np.float32)),
            entity_keys=keys,
            key_to_index={kk: i for i, kk in enumerate(keys.tolist())}),
    }, task)
    store = serving.CoefficientStore.from_game_model(model)
    return serving.ProgramLadder(
        store, floor=8, max_batch=16, sparse_k={"member": k},
        quantize=quantize, quant_epsilon=eps), (df, dr, k, E)


def _batch(df, dr, k, B=8, seed=30, entity=0):
    rng = np.random.default_rng(seed)
    shards = {"global": rng.normal(size=(B, df)).astype(np.float32),
              "member": SparseRows(
                  rng.integers(0, dr, size=(B, k)).astype(np.int32),
                  rng.normal(size=(B, k)).astype(np.float32), dr)}
    ids = {"perMember": np.full(B, entity, np.int32)}
    return np.zeros(B, np.float32), shards, ids


class TestFusedRungParity:
    def test_fused_vs_xla_bitwise(self):
        """The fused kernel rung equals the XLA rung bit for bit — and
        the kernel path really engaged (it recorded its dispatch)."""
        ladder, (df, dr, k, _E) = _ladder()
        ladder.warmup()
        off, shards, ids = _batch(df, dr, k, entity=3)
        with K.scope("off"):
            ref = np.asarray(ladder.score_padded(off, shards, ids))
        with K.scope("on"):
            from photon_tpu.kernels import serving as KS

            assert KS.fused_feasible(*ladder.example_args(8))
            got = np.asarray(ladder.score_padded(off, shards, ids))
            assert K.KERNEL_SIGNATURES.signatures("kernels.serving_int8")
        np.testing.assert_array_equal(ref, got)

    def test_cold_miss_row_bitwise(self):
        """An all-unseen-entity batch through the FUSED rung equals the
        f32 ladder bit for bit: row E dequantizes to exact zeros inside
        the kernel too."""
        ladder, (df, dr, k, E) = _ladder()
        f32, _ = _ladder(quantize=None)
        ladder.warmup()
        f32.warmup()
        off, shards, ids = _batch(df, dr, k, entity=E)  # the cold row
        # kernel == XLA on the quantized rung itself, cold row included
        with K.scope("off"):
            ref = np.asarray(ladder.score_padded(off, shards, ids))
        with K.scope("on"):
            got = np.asarray(ladder.score_padded(off, shards, ids))
        np.testing.assert_array_equal(ref, got)
        # and with no fixed contribution the fused int8 rung equals the
        # f32 ladder outright: the cold row is EXACT zeros in-kernel
        shards["global"] = np.zeros_like(shards["global"])
        with K.scope("off"):
            ref32 = np.asarray(f32.score_padded(off, shards, ids))
        with K.scope("on"):
            got8 = np.asarray(ladder.score_padded(off, shards, ids))
        np.testing.assert_array_equal(ref32, got8)

    def test_budget_infeasible_stays_xla(self, monkeypatch):
        """Past the VMEM budget the rung stays on the XLA path — no
        error, same bits (it IS the XLA program)."""
        ladder, (df, dr, k, _E) = _ladder()
        ladder.warmup()
        off, shards, ids = _batch(df, dr, k)
        with K.scope("off"):
            ref = np.asarray(ladder.score_padded(off, shards, ids))
        monkeypatch.setenv(K.ENV_VMEM, "1")
        with K.scope("on"):
            from photon_tpu.kernels import serving as KS

            assert not KS.fused_feasible(*ladder.example_args(8))
            got = np.asarray(ladder.score_padded(off, shards, ids))
        np.testing.assert_array_equal(ref, got)

    def test_mode_flips_never_move_signatures(self):
        """Mixed batch sizes driven kernels-off AND kernels-on: the
        rung dispatch signatures stay one-per-bucket (the route is an
        executable fact, never a call-signature fact)."""
        ladder, (df, dr, k, _E) = _ladder()
        ladder.warmup()
        for m in ("off", "on", "off"):
            with K.scope(m):
                for B, seed in ((8, 1), (16, 2), (8, 3)):
                    off, shards, ids = _batch(df, dr, k, B=B, seed=seed)
                    ladder.score_padded(off, shards, ids)
        assert ladder.assert_no_retrace() <= len(ladder.ladder)

    def test_aot_key_carries_route(self, monkeypatch):
        """A stored export replays without tracing, so the kernel route
        must be part of the AOT file identity: kernels-on feasible rungs
        key with the ``:pk`` marker, everything else without."""
        ladder, _ = _ladder()
        with K.scope("off"):
            key_off = ladder._key(8)
        with K.scope("on"):
            key_on = ladder._key(8)
        assert key_on.endswith(":pk") and not key_off.endswith(":pk")
        assert key_on[: -len(":pk")] == key_off
        monkeypatch.setenv(K.ENV_VMEM, "1")
        with K.scope("on"):
            assert ladder._key(8) == key_off  # infeasible: XLA identity


class TestHotSwapQuantCache:
    def test_hot_swap_invalidates_qdev(self):
        """Satellite 2: a `continual.hot_swap` swings `device_blocks()`
        to a new generation, which invalidates the ladder's `_qdev`
        quantized-block cache — the next KERNEL-path dispatch
        re-quantizes and scores the new model (negated coefficients
        mirror the logistic mean around 0.5), through the same
        executables (no retrace)."""
        from photon_tpu.continual import hot_swap

        ladder, (df, dr, k, _E) = _ladder()
        ladder.warmup()
        off, shards, ids = _batch(df, dr, k, seed=31)
        with K.scope("on"):
            before = np.asarray(ladder.score_padded(off, shards, ids))
        token_before = ladder._qdev[0]
        other = copy.copy(ladder.store)
        other.fixed = {n: dataclasses.replace(
            b, weights=-np.asarray(b.weights))
            for n, b in ladder.store.fixed.items()}
        other.random = {n: dataclasses.replace(
            b, coefficients=-np.asarray(b.coefficients))
            for n, b in ladder.store.random.items()}
        other._device = None
        hot_swap(ladder.store, other, probe=None, root=None)
        with K.scope("on"):
            after = np.asarray(ladder.score_padded(off, shards, ids))
        assert ladder._qdev[0] is not token_before  # cache turned over
        np.testing.assert_allclose(before + after, 1.0, atol=1e-6)
        assert ladder.assert_no_retrace() <= len(ladder.ladder)
