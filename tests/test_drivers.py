"""Driver end-to-end tests: Avro files → trained model dir → scored output
(SURVEY.md §4 'driver end-to-end from Avro files to scored output')."""
import json

import numpy as np
import pytest

from photon_tpu.data.avro_io import read_avro, write_avro
from photon_tpu.data.ingest import training_example_schema
from photon_tpu.drivers import (
    CoordinateSpec,
    ScoringParams,
    TrainingParams,
    run_scoring,
    run_training,
)
from photon_tpu.utils.timing import PhaseTimers, Timer


def _write_game_avro(path, n, seed=0, n_users=8):
    rng = np.random.default_rng(seed)
    user = rng.integers(0, n_users, n)
    age = rng.normal(0, 1, n)
    ctr = rng.normal(0, 1, n)
    u_eff = np.linspace(-1.5, 1.5, n_users)[np.argsort(rng.uniform(size=n_users))]
    margin = 1.2 * age - 0.8 * ctr + u_eff[user]
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-margin))).astype(np.float32)
    schema = training_example_schema(
        feature_bags=("global", "puser"), entity_fields=("userId",))
    records = [{
        "response": float(y[i]),
        "offset": None, "weight": None, "uid": f"row{i}",
        "userId": f"u{user[i]}",
        "global": [
            {"name": "age", "term": "", "value": float(age[i])},
            {"name": "ctr", "term": "", "value": float(ctr[i])},
        ],
        "puser": [{"name": "bias", "term": "", "value": 1.0}],
    } for i in range(n)]
    write_avro(path, records, schema)
    return y


FEATURE_SHARDS = {
    "fixedShard": {"bags": ["global"], "has_intercept": True},
    "userShard": {"bags": ["puser"], "has_intercept": False},
}
from photon_tpu.data.feature_bags import FeatureShardConfig

FEATURE_SHARDS_TYPED = {
    k: FeatureShardConfig(bags=tuple(v["bags"]),
                          has_intercept=v["has_intercept"])
    for k, v in FEATURE_SHARDS.items()
}
COORDINATES = {
    "fixed": {"feature_shard": "fixedShard", "reg_type": "l2",
              "reg_weight": 0.5, "max_iters": 40},
    "perUser": {"feature_shard": "userShard", "entity_name": "userId",
                "reg_type": "l2", "reg_weight": 2.0, "max_iters": 20},
}


@pytest.fixture(scope="module")
def job_dirs(tmp_path_factory):
    root = tmp_path_factory.mktemp("game_job")
    y_train = _write_game_avro(root / "train.avro", 600, seed=1)
    y_val = _write_game_avro(root / "validation.avro", 300, seed=2)
    return root, y_train, y_val


class TestTrainingDriver:
    def test_end_to_end_with_grid(self, job_dirs):
        root, *_ = job_dirs
        params = TrainingParams(
            train_path=str(root / "train.avro"),
            validation_path=str(root / "validation.avro"),
            output_dir=str(root / "out"),
            feature_shards=FEATURE_SHARDS,
            coordinates={
                **COORDINATES,
                "fixed": {**COORDINATES["fixed"], "reg_weights": [0.1, 10.0]},
            },
            entity_fields=["userId"],
            n_sweeps=2,
        )
        out = run_training(params)
        assert len(out.results) == 2  # one model per grid point
        assert out.best.validation_score is not None
        assert out.best.validation_score > 0.7  # AUC on planted signal
        # model dir is loadable and complete
        from photon_tpu.data.model_io import load_game_model

        model, imaps = load_game_model(out.model_dir)
        assert set(model.names()) == {"fixed", "perUser"}
        assert "read" in out.timings and "train" in out.timings

    def test_compilation_cache_knob(self, job_dirs, tmp_path):
        """Default: persistent XLA cache lands under output_dir; "" turns
        it off; an explicit relative path lands under output_dir too."""
        import jax

        from photon_tpu.utils.compile_cache import resolve_cache_dir

        assert resolve_cache_dir(None, "/o") == "/o/xla_cache"
        assert resolve_cache_dir("", "/o") is None
        assert resolve_cache_dir("cc", "/o") == "/o/cc"
        assert resolve_cache_dir("/abs/cc", "/o") == "/abs/cc"

        root, *_ = job_dirs
        out_dir = tmp_path / "cache_job"
        params = TrainingParams(
            train_path=str(root / "train.avro"),
            output_dir=str(out_dir),
            feature_shards=FEATURE_SHARDS,
            coordinates={"fixed": COORDINATES["fixed"]},
            entity_fields=["userId"],
            n_sweeps=1,
        )
        prev = jax.config.jax_compilation_cache_dir
        prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
        try:
            run_training(params)
            assert jax.config.jax_compilation_cache_dir == str(
                out_dir / "xla_cache")
            assert (out_dir / "xla_cache").is_dir()
        finally:  # both knobs: the rest of the session must not keep
            # persisting every compile into a deleted tmpdir
            jax.config.update("jax_compilation_cache_dir", prev)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              prev_min)

    def test_scoring_driver_round_trip(self, job_dirs):
        root, _, y_val = job_dirs
        params = TrainingParams(
            train_path=str(root / "train.avro"),
            validation_path=str(root / "validation.avro"),
            output_dir=str(root / "out2"),
            feature_shards=FEATURE_SHARDS,
            coordinates=COORDINATES,
            entity_fields=["userId"],
            n_sweeps=1,
        )
        tr = run_training(params)
        sc = run_scoring(ScoringParams(
            model_dir=tr.model_dir,
            data_path=str(root / "validation.avro"),
            output_dir=str(root / "scored"),
            feature_shards=FEATURE_SHARDS,
            entity_fields=["userId"],
        ))
        assert sc.metric == pytest.approx(tr.best.validation_score, abs=1e-6)
        written = read_avro(sc.output_path)
        assert len(written) == 300
        assert written[0]["uid"] == "row0"
        probs = np.asarray([r["predictionScore"] for r in written])
        assert ((probs > 0) & (probs < 1)).all()  # sigmoid applied
        np.testing.assert_allclose(
            [r["label"] for r in written], y_val, atol=1e-6)

    def test_normalization_and_downsampling_modes(self, job_dirs, tmp_path):
        root, *_ = job_dirs
        params = TrainingParams(
            train_path=str(root / "train.avro"),
            validation_path=str(root / "validation.avro"),
            output_dir=str(tmp_path / "out_norm"),
            feature_shards=FEATURE_SHARDS,
            coordinates=COORDINATES,
            entity_fields=["userId"],
            n_sweeps=1,
            normalization="scale_with_standard_deviation",
            down_sampling_rate=0.5,
        )
        out = run_training(params)
        assert out.best.validation_score > 0.65

    def test_cli_json_config(self, job_dirs, tmp_path, capsys):
        root, *_ = job_dirs
        cfg = {
            "train_path": str(root / "train.avro"),
            "validation_path": str(root / "validation.avro"),
            "output_dir": str(tmp_path / "cli_out"),
            "feature_shards": FEATURE_SHARDS,
            "coordinates": COORDINATES,
            "entity_fields": ["userId"],
            "n_sweeps": 1,
        }
        cfg_path = tmp_path / "job.json"
        cfg_path.write_text(json.dumps(cfg))
        from photon_tpu.drivers.train import main

        main(["--config", str(cfg_path)])
        printed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert printed["n_models"] == 1
        assert printed["validation_score"] > 0.65

    def test_gp_tuning_mode(self, job_dirs, tmp_path):
        root, *_ = job_dirs
        params = TrainingParams(
            train_path=str(root / "train.avro"),
            validation_path=str(root / "validation.avro"),
            output_dir=str(tmp_path / "out_tune"),
            feature_shards=FEATURE_SHARDS,
            coordinates=COORDINATES,
            entity_fields=["userId"],
            n_sweeps=1,
            tuning_iters=4,
            tuning_range=(1e-3, 1e3),
        )
        out = run_training(params)
        assert len(out.results) == 4  # one fit per tuner evaluation
        assert out.best.validation_score == pytest.approx(
            max(r.validation_score for r in out.results))


class TestTimers:
    def test_timer_accumulates(self):
        t = Timer()
        with t:
            pass
        first = t.seconds
        with t:
            pass
        assert t.seconds >= first
        with pytest.raises(RuntimeError):
            t.stop()

    def test_phase_timers(self):
        timers = PhaseTimers()
        with timers("a"):
            pass
        with timers("a"):
            pass
        with timers("b"):
            pass
        s = timers.summary()
        assert set(s) == {"a", "b"} and s["a"] >= 0


class TestSummarization:
    def test_driver_writes_feature_summaries(self, job_dirs):
        from photon_tpu.data.statistics import FeatureSummary

        root, *_ = job_dirs
        params = TrainingParams(
            train_path=str(root / "train.avro"),
            output_dir=str(root / "out_summ"),
            feature_shards=FEATURE_SHARDS,
            coordinates=COORDINATES,
            entity_fields=["userId"],
            n_sweeps=1,
            normalization="scale_with_standard_deviation",
            summarization_output_dir="summaries",
        )
        out = run_training(params)
        assert out.best is not None
        for shard in FEATURE_SHARDS:
            s = FeatureSummary.load(
                str(root / "out_summ" / "summaries" / f"{shard}.json"))
            assert s.count == 600
        s_fixed = FeatureSummary.load(
            str(root / "out_summ" / "summaries" / "fixedShard.json"))
        # age/ctr are standard normal draws; intercept column is constant 1
        assert abs(float(s_fixed.mean[-1]) - 1.0) < 1e-6
        assert float(s_fixed.variance[-1]) < 1e-8
        assert 0.7 < float(s_fixed.std[0]) < 1.3


class TestOutputModeAll:
    def test_all_models_saved_with_manifest(self, job_dirs):
        import json as _json

        from photon_tpu.data.model_io import load_game_model

        root, *_ = job_dirs
        params = TrainingParams(
            train_path=str(root / "train.avro"),
            validation_path=str(root / "validation.avro"),
            output_dir=str(root / "out_all"),
            feature_shards=FEATURE_SHARDS,
            coordinates={
                **COORDINATES,
                "fixed": {**COORDINATES["fixed"],
                          "reg_weights": [0.1, 10.0]},
            },
            entity_fields=["userId"],
            n_sweeps=1,
            output_mode="ALL",
        )
        out = run_training(params)
        with open(root / "out_all" / "models" / "models.json") as fh:
            manifest = _json.load(fh)
        assert len(manifest) == 2
        assert sum(1 for m in manifest if m["best"]) == 1
        regs = [m["reg_weights"]["fixed"] for m in manifest]
        assert sorted(regs) == [0.1, 10.0]
        for m in manifest:
            gm, _ = load_game_model(m["dir"])
            assert set(gm.names()) == {"fixed", "perUser"}
            assert m["validation_score"] is not None

    def test_bad_output_mode_rejected(self, job_dirs):
        # fails fast at construction, before any training runs
        root, *_ = job_dirs
        with pytest.raises(ValueError, match="BEST or ALL"):
            TrainingParams(
                train_path=str(root / "train.avro"),
                output_dir=str(root / "out_bad"),
                feature_shards=FEATURE_SHARDS,
                coordinates=COORDINATES,
                entity_fields=["userId"],
                n_sweeps=1,
                output_mode="SOME",
            )


class TestMultipleEvaluators:
    def test_selection_and_reporting(self, job_dirs):
        root, *_ = job_dirs
        params = TrainingParams(
            train_path=str(root / "train.avro"),
            validation_path=str(root / "validation.avro"),
            output_dir=str(root / "out_ev"),
            feature_shards=FEATURE_SHARDS,
            coordinates={
                **COORDINATES,
                "fixed": {**COORDINATES["fixed"], "reg_weights": [0.1, 100.0]},
            },
            entity_fields=["userId"],
            n_sweeps=1,
            evaluators=["logistic_loss", "AUC", "precision@5",
                        "sharded_auc"],
            evaluator_entity="userId",
        )
        out = run_training(params)
        # selection ran on LOGISTIC_LOSS (lower is better)
        losses = [r.validation_score for r in out.results]
        assert out.best.validation_score == min(losses)
        m = out.validation_metrics
        assert set(m) == {"LOGISTIC_LOSS", "AUC", "PRECISION_AT_K@5",
                          "SHARDED_AUC"}
        assert m["LOGISTIC_LOSS"] == pytest.approx(out.best.validation_score)
        assert 0.5 < m["AUC"] <= 1.0
        assert 0.0 <= m["PRECISION_AT_K@5"] <= 1.0

    def test_parse_evaluator_specs(self):
        from photon_tpu.evaluation.evaluator import (
            EvaluatorType, evaluator_name, parse_evaluator)

        ev = parse_evaluator("precision@3")
        assert ev.kind is EvaluatorType.PRECISION_AT_K and ev.k == 3
        assert evaluator_name(ev) == "PRECISION_AT_K@3"
        assert parse_evaluator("rmse").kind is EvaluatorType.RMSE
        with pytest.raises(ValueError, match="unknown evaluator"):
            parse_evaluator("nope")

    def test_scoring_driver_multiple_evaluators(self, job_dirs):
        root, *_ = job_dirs
        tr = run_training(TrainingParams(
            train_path=str(root / "train.avro"),
            output_dir=str(root / "out_sc_ev"),
            feature_shards=FEATURE_SHARDS,
            coordinates=COORDINATES,
            entity_fields=["userId"],
            n_sweeps=1,
        ))
        sc = run_scoring(ScoringParams(
            model_dir=tr.model_dir,
            data_path=str(root / "validation.avro"),
            output_dir=str(root / "scored_ev"),
            feature_shards=FEATURE_SHARDS,
            entity_fields=["userId"],
            evaluators=["AUC", "logistic_loss", "sharded_auc"],
        ))
        assert set(sc.metrics) == {"AUC", "LOGISTIC_LOSS", "SHARDED_AUC"}
        assert sc.metric == pytest.approx(sc.metrics["AUC"])
        assert 0.5 < sc.metrics["AUC"] <= 1.0

    def test_metric_none_when_first_evaluator_skipped(self, job_dirs,
                                                      tmp_path):
        """ScoringOutput.metric must honor the FIRST evaluator, not fall
        back to a different metric's value (regression)."""
        root, *_ = job_dirs
        tr = run_training(TrainingParams(
            train_path=str(root / "train.avro"),
            output_dir=str(tmp_path / "o"),
            feature_shards=FEATURE_SHARDS,
            coordinates=COORDINATES,
            entity_fields=["userId"],
            n_sweeps=1,
        ))
        sc = run_scoring(ScoringParams(
            model_dir=tr.model_dir,
            data_path=str(root / "validation.avro"),
            output_dir=str(tmp_path / "s"),
            feature_shards=FEATURE_SHARDS,
            entity_fields=["userId"],
            evaluators=["sharded_auc", "AUC"],
            evaluator_entity="missingEntity",
        ))
        assert sc.metric is None  # first evaluator was skipped
        assert set(sc.metrics) == {"AUC"}

    def test_bad_evaluator_k_suffix_rejected(self):
        from photon_tpu.evaluation.evaluator import parse_evaluator

        with pytest.raises(ValueError, match="only applies to the precision"):
            parse_evaluator("AUC@5")

    def test_sharded_extra_metric_never_destroys_run(self, job_dirs,
                                                     tmp_path):
        """A sharded EXTRA evaluator with no usable entity must be skipped
        with a warning after training, not crash before the save
        (regression)."""
        import os

        root, *_ = job_dirs
        out = run_training(TrainingParams(
            train_path=str(root / "train.avro"),
            validation_path=str(root / "validation.avro"),
            output_dir=str(tmp_path / "o"),
            feature_shards={"fixedShard": FEATURE_SHARDS["fixedShard"]},
            coordinates={"fixed": COORDINATES["fixed"]},  # no random effect
            entity_fields=[],
            n_sweeps=1,
            evaluators=["AUC", "sharded_auc"],
        ))
        assert os.path.isdir(out.model_dir)  # model was saved
        assert set(out.validation_metrics) == {"AUC"}  # sharded skipped


class TestIndexingDriver:
    def test_build_save_and_reuse(self, job_dirs, tmp_path):
        from photon_tpu.data.ingest import GameDataConfig, read_game_data
        from photon_tpu.drivers import (IndexingParams, load_index_maps,
                                        run_indexing)

        root, *_ = job_dirs
        out = run_indexing(IndexingParams(
            data_path=str(root / "train.avro"),
            output_dir=str(tmp_path / "maps"),
            feature_shards=FEATURE_SHARDS,
        ))
        assert out.n_records == 600
        # fixedShard: age + ctr + intercept
        assert out.sizes["fixedShard"] == 3
        maps = load_index_maps(out.map_paths)
        assert maps["fixedShard"].frozen
        assert maps["fixedShard"].intercept_id == 2  # intercept LAST
        # ingestion with the prebuilt maps matches implicit ingestion
        cfg = GameDataConfig(shards=FEATURE_SHARDS_TYPED,
                             entity_fields=("userId",))
        d1, implicit = read_game_data(str(root / "train.avro"), cfg)
        d2, _ = read_game_data(str(root / "train.avro"), cfg,
                               index_maps=maps)
        np.testing.assert_array_equal(
            np.asarray(d1.shards["fixedShard"]),
            np.asarray(d2.shards["fixedShard"]))

    def test_min_count_prunes_rare_features(self, tmp_path):
        from photon_tpu.data.ingest import training_example_schema
        from photon_tpu.drivers import IndexingParams, run_indexing

        schema = training_example_schema(feature_bags=("g",),
                                         entity_fields=())
        recs = []
        for i in range(20):
            feats = [{"name": "common", "term": "", "value": 1.0}]
            if i == 0:
                feats.append({"name": "rare", "term": "", "value": 1.0})
            recs.append({"response": 1.0, "offset": None, "weight": None,
                         "uid": str(i), "g": feats})
        write_avro(str(tmp_path / "d.avro"), recs, schema)
        out = run_indexing(IndexingParams(
            data_path=str(tmp_path / "d.avro"),
            output_dir=str(tmp_path / "maps"),
            feature_shards={"s": {"bags": ["g"], "has_intercept": False}},
            min_count=2,
        ))
        assert out.sizes["s"] == 1  # only "common" survives

    def test_cli(self, job_dirs, tmp_path, capsys):
        cfg = {
            "data_path": str(job_dirs[0] / "train.avro"),
            "output_dir": str(tmp_path / "m"),
            "feature_shards": FEATURE_SHARDS,
        }
        p = tmp_path / "job.json"
        p.write_text(json.dumps(cfg))
        from photon_tpu.drivers.index import main

        main(["--config", str(p)])
        printed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert printed["sizes"]["fixedShard"] == 3

    def test_training_driver_consumes_prebuilt_maps(self, tmp_path):
        """index_map_dir: min_count pruning must carry through to the
        trained model's feature space (the offline job's purpose)."""
        from photon_tpu.data.ingest import training_example_schema
        from photon_tpu.drivers import IndexingParams, run_indexing

        schema = training_example_schema(feature_bags=("g",),
                                         entity_fields=())
        rng = np.random.default_rng(0)
        recs = []
        for i in range(120):
            feats = [{"name": "a", "term": "", "value": float(rng.normal())},
                     {"name": "b", "term": "", "value": float(rng.normal())}]
            if i == 0:
                feats.append({"name": "rare", "term": "", "value": 1.0})
            recs.append({"response": float(rng.integers(0, 2)),
                         "offset": None, "weight": None, "uid": str(i),
                         "g": feats})
        write_avro(str(tmp_path / "d.avro"), recs, schema)
        shards = {"s": {"bags": ["g"], "has_intercept": True}}
        idx = run_indexing(IndexingParams(
            data_path=str(tmp_path / "d.avro"),
            output_dir=str(tmp_path / "maps"),
            feature_shards=shards, min_count=2))
        assert idx.sizes["s"] == 3  # a, b, intercept — rare pruned
        out = run_training(TrainingParams(
            train_path=str(tmp_path / "d.avro"),
            output_dir=str(tmp_path / "out"),
            feature_shards=shards,
            coordinates={"fixed": {"feature_shard": "s", "reg_type": "l2",
                                   "reg_weight": 1.0, "max_iters": 15}},
            n_sweeps=1,
            index_map_dir=str(tmp_path / "maps")))
        w = np.asarray(out.best.model.coordinates["fixed"]
                       .model.coefficients.means)
        assert w.shape == (3,)  # pruned width, not 4
        with pytest.raises(FileNotFoundError, match="no map for shard"):
            run_training(TrainingParams(
                train_path=str(tmp_path / "d.avro"),
                output_dir=str(tmp_path / "out2"),
                feature_shards={"other": {"bags": ["g"]}},
                coordinates={"fixed": {"feature_shard": "other",
                                       "max_iters": 2}},
                n_sweeps=1,
                index_map_dir=str(tmp_path / "maps")))


class TestProfiling:
    def test_trace_writes_profile(self, tmp_path):
        import os

        import jax.numpy as jnp

        from photon_tpu.utils.profiling import annotate, trace

        with trace(str(tmp_path)):
            with annotate("tiny-matmul"):
                x = jnp.ones((64, 64))
                (x @ x).block_until_ready()
        found = []
        for base, _, files in os.walk(tmp_path):
            found += [f for f in files if f.endswith((".pb", ".json.gz",
                                                      ".xplane.pb"))]
        assert found, "profiler trace produced no files"


class TestResume:
    def test_resume_skips_completed_points(self, job_dirs, tmp_path):
        root, *_ = job_dirs

        def make(resume):
            return TrainingParams(
                train_path=str(root / "train.avro"),
                validation_path=str(root / "validation.avro"),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": [0.1, 10.0]},
                },
                entity_fields=["userId"],
                n_sweeps=1,
                output_mode="ALL",
                resume=resume,
            )

        first = run_training(make(resume=False))
        assert first.n_resumed == 0
        second = run_training(make(resume=True))
        assert second.n_resumed == 2  # both points loaded, nothing retrained
        for a, b in zip(first.results, second.results):
            assert b.validation_score == pytest.approx(a.validation_score)
            wa = np.asarray(
                a.model.coordinates["fixed"].model.coefficients.means)
            wb = np.asarray(
                b.model.coordinates["fixed"].model.coefficients.means)
            np.testing.assert_allclose(wb, wa, atol=1e-6)
        assert (second.best.configs["fixed"].optimizer.reg_weight
                == first.best.configs["fixed"].optimizer.reg_weight)

    def test_resume_trains_only_missing_points(self, job_dirs, tmp_path):
        import shutil

        root, *_ = job_dirs

        def make(weights, resume):
            return TrainingParams(
                train_path=str(root / "train.avro"),
                validation_path=str(root / "validation.avro"),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": weights},
                },
                entity_fields=["userId"],
                n_sweeps=1,
                output_mode="ALL",
                resume=resume,
            )

        run_training(make([0.1], resume=False))
        # widen the grid; the 0.1 point must load, 10.0 must train fresh
        out = run_training(make([0.1, 10.0], resume=True))
        assert out.n_resumed == 1
        assert len(out.results) == 2
        regs = [r.configs["fixed"].optimizer.reg_weight for r in out.results]
        assert regs == [0.1, 10.0]

    def test_resume_requires_all_mode(self, job_dirs):
        root, *_ = job_dirs
        with pytest.raises(ValueError, match="output_mode=ALL"):
            TrainingParams(
                train_path=str(root / "train.avro"),
                output_dir="x",
                feature_shards=FEATURE_SHARDS,
                coordinates=COORDINATES,
                resume=True,
            )

    def test_died_job_resumes_from_checkpoints(self, job_dirs, tmp_path,
                                               monkeypatch):
        """Crash mid-grid: completed points were checkpointed as they
        finished, so the rerun retrains only the rest (regression: nothing
        was persisted until the whole grid succeeded)."""
        from photon_tpu.game.estimator import GameEstimator

        root, *_ = job_dirs

        def make():
            return TrainingParams(
                train_path=str(root / "train.avro"),
                validation_path=str(root / "validation.avro"),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": [0.1, 1.0, 10.0]},
                },
                entity_fields=["userId"],
                n_sweeps=1, output_mode="ALL", resume=True,
            )

        real_fit = GameEstimator.fit
        calls = {"n": 0}

        def dying_fit(self, *a, **kw):
            calls["n"] += 1
            if calls["n"] == 3:  # die while training the third point
                raise RuntimeError("simulated preemption")
            return real_fit(self, *a, **kw)

        monkeypatch.setattr(GameEstimator, "fit", dying_fit)
        with pytest.raises(RuntimeError, match="preemption"):
            run_training(make())
        monkeypatch.setattr(GameEstimator, "fit", real_fit)
        out = run_training(make())
        assert out.n_resumed == 2  # the two checkpointed points loaded
        assert len(out.results) == 3

    def test_changed_config_is_not_resumed(self, job_dirs, tmp_path):
        """Any hyperparameter change invalidates the checkpoint (regression:
        matching on reg weights alone reloaded stale models)."""
        root, *_ = job_dirs

        def make(max_iters):
            return TrainingParams(
                train_path=str(root / "train.avro"),
                validation_path=str(root / "validation.avro"),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "max_iters": max_iters,
                              "reg_weights": [0.1, 10.0]},
                },
                entity_fields=["userId"],
                n_sweeps=1, output_mode="ALL", resume=True,
            )

        run_training(make(max_iters=40))
        out = run_training(make(max_iters=41))
        assert out.n_resumed == 0  # different config signature → retrain

    def test_resume_objective_selection_without_validation(self, job_dirs,
                                                           tmp_path):
        """Loaded points carry their recorded training objective, so
        best-by-objective selection survives a resume (regression: empty
        history compared as +inf)."""
        root, *_ = job_dirs

        def make():
            return TrainingParams(
                train_path=str(root / "train.avro"),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": [0.1, 1000.0]},
                },
                entity_fields=["userId"],
                n_sweeps=1, output_mode="ALL", resume=True,
            )

        first = run_training(make())
        second = run_training(make())
        assert second.n_resumed == 2
        assert (second.best.configs["fixed"].optimizer.reg_weight
                == first.best.configs["fixed"].optimizer.reg_weight)

    def test_resume_rejects_incremental(self, job_dirs):
        root, *_ = job_dirs
        with pytest.raises(ValueError, match="incremental"):
            TrainingParams(
                train_path=str(root / "train.avro"),
                output_dir="x", feature_shards=FEATURE_SHARDS,
                coordinates=COORDINATES, output_mode="ALL", resume=True,
                incremental_coordinates=["fixed"],
                initial_model_dir="y")

    def test_global_config_change_is_not_resumed(self, job_dirs, tmp_path):
        """Changing a training-wide knob (n_sweeps here) must invalidate
        every checkpoint (regression: signature covered only per-coordinate
        settings, so stale models were silently reloaded)."""
        root, *_ = job_dirs

        def make(n_sweeps):
            return TrainingParams(
                train_path=str(root / "train.avro"),
                validation_path=str(root / "validation.avro"),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": [0.1, 10.0]},
                },
                entity_fields=["userId"],
                n_sweeps=n_sweeps, output_mode="ALL", resume=True,
            )

        run_training(make(n_sweeps=1))
        out = run_training(make(n_sweeps=2))
        assert out.n_resumed == 0
        # and same-config rerun still resumes fully
        out2 = run_training(make(n_sweeps=2))
        assert out2.n_resumed == 2

    def test_changed_validation_is_not_resumed(self, job_dirs, tmp_path):
        """Resume must not reuse stored validation_scores when the
        validation data or selection metric changed — the scores would be
        incomparable to freshly trained points' scores and silently
        corrupt best-model selection (regression: signature omitted
        validation_path/evaluators)."""
        root, *_ = job_dirs
        other_val = tmp_path / "validation2.avro"
        _write_game_avro(other_val, 300, seed=7)

        def make(validation_path, evaluators=()):
            return TrainingParams(
                train_path=str(root / "train.avro"),
                validation_path=str(validation_path),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": [0.1, 10.0]},
                },
                entity_fields=["userId"],
                n_sweeps=1, output_mode="ALL", resume=True,
                evaluators=evaluators,
            )

        run_training(make(root / "validation.avro"))
        out = run_training(make(other_val))
        assert out.n_resumed == 0  # different validation data → retrain
        # changing the selection metric also invalidates the checkpoints
        out2 = run_training(make(other_val, evaluators=("RMSE",)))
        assert out2.n_resumed == 0
        # unchanged rerun still resumes fully
        out3 = run_training(make(other_val, evaluators=("RMSE",)))
        assert out3.n_resumed == 2

    def test_all_mode_overwrites_stale_point_dirs(self, job_dirs, tmp_path):
        """A non-resume ALL run into a reused output_dir must overwrite
        existing signature-keyed dirs: the signature keys on train_path,
        not file content, so an existing dir may hold a stale model
        (regression: the save phase skipped any dir that existed)."""
        import shutil

        from photon_tpu.data.model_io import load_game_model

        root, *_ = job_dirs

        def make():
            return TrainingParams(
                train_path=str(root / "train.avro"),
                validation_path=str(root / "validation.avro"),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": [0.1, 10.0]},
                },
                entity_fields=["userId"],
                n_sweeps=1, output_mode="ALL",
            )

        run_training(make())
        models_dir = tmp_path / "out" / "models"
        with open(models_dir / "models.json") as fh:
            manifest = json.load(fh)
        # tamper: swap one point's on-disk model for the other's, the
        # observable effect of train_path's content having changed
        a, b = (m["dir"] for m in manifest[:2])
        shutil.rmtree(a)
        shutil.copytree(b, a)
        out = run_training(make())
        for r, m in zip(out.results, manifest):
            on_disk, _ = load_game_model(m["dir"])
            want = np.asarray(
                r.model.coordinates["fixed"].model.coefficients.means)
            got = np.asarray(
                on_disk.coordinates["fixed"].model.coefficients.means)
            np.testing.assert_allclose(got, want, atol=1e-6)

    def test_duplicate_grid_points_get_distinct_dirs(self, job_dirs,
                                                     tmp_path):
        """Two identical grid points train different models under warm
        starts (different warm-start chains); their signatures must not
        collide on one models/m_<hash>/ dir (regression: the second save
        overwrote the first, and resume handed both points one model)."""
        from photon_tpu.data.model_io import load_game_model

        root, *_ = job_dirs

        def make(resume):
            return TrainingParams(
                train_path=str(root / "train.avro"),
                validation_path=str(root / "validation.avro"),
                output_dir=str(tmp_path / "out"),
                feature_shards=FEATURE_SHARDS,
                coordinates={
                    **COORDINATES,
                    "fixed": {**COORDINATES["fixed"],
                              "reg_weights": [0.1, 0.1]},
                },
                entity_fields=["userId"],
                n_sweeps=1, output_mode="ALL", resume=resume,
            )

        first = run_training(make(resume=False))
        models_dir = tmp_path / "out" / "models"
        with open(models_dir / "models.json") as fh:
            manifest = json.load(fh)
        assert len({m["dir"] for m in manifest}) == 2
        for r, m in zip(first.results, manifest):
            on_disk, _ = load_game_model(m["dir"])
            np.testing.assert_allclose(
                np.asarray(
                    on_disk.coordinates["fixed"].model.coefficients.means),
                np.asarray(
                    r.model.coordinates["fixed"].model.coefficients.means),
                atol=1e-6)
        # and a resumed rerun recovers BOTH points
        second = run_training(make(resume=True))
        assert second.n_resumed == 2
