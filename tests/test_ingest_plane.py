"""Round-14 ingest data plane: sharded worker-pool decode parity (ordered,
bit-identical, fault-degrading), the decode-once columnar chunk cache
(cold==cached bitwise, torn-commit fallback, CRC, key invalidation), the
blocked-ELL ladder cache, stall-driven prefetch, and plane-on/off solver
bit parity through the streamed GLM and the GAME training driver."""
import json
import os

import numpy as np
import pytest

from photon_tpu.checkpoint.faults import (FaultPlan, InjectedFault,
                                          fault_plan, record_sites)
from photon_tpu.data import chunk_cache as cc
from photon_tpu.data.avro_io import write_avro
from photon_tpu.data.feature_bags import FeatureShardConfig
from photon_tpu.data.ingest import GameDataConfig, training_example_schema
from photon_tpu.data.ingest_plane import (AdaptivePrefetch,
                                          chunk_blocked_ell_from_avro,
                                          iter_game_chunks_parallel,
                                          open_chunk_source,
                                          plan_chunk_tasks)
from photon_tpu.data.matrix import SparseRows
from photon_tpu.data.streaming import (iter_game_chunks, scan_ingest,
                                       scan_row_counts, stream_to_host)


def _write_files(root, n_files=3, rows_per_file=400, seed=0):
    """Multi-file GAME dataset: a dense bag, a wide (sparse) bag, an
    entity column, optional offset/weight — block_records=130 leaves a
    NON-DIVIDING tail block per file (400 = 130+130+130+10)."""
    rng = np.random.default_rng(seed)
    schema = training_example_schema(feature_bags=("f", "g"),
                                     entity_fields=("member",))
    os.makedirs(root, exist_ok=True)
    for fi in range(n_files):
        records = []
        for i in range(rows_per_file):
            f_bag = [{"name": "age", "term": "",
                      "value": float(rng.normal())},
                     {"name": "ctr", "term": "",
                      "value": float(rng.normal())}]
            g_bag = [{"name": f"id{int(v)}", "term": "t",
                      "value": float(rng.normal())}
                     for v in rng.integers(0, 500, size=3)]
            records.append({
                "response": float(rng.integers(0, 2)),
                "offset": float(rng.normal()) if i % 3 == 0 else None,
                "weight": 2.0 if i % 5 == 0 else None,
                "uid": f"r{fi}_{i}",
                "member": f"m{int(rng.integers(0, 37))}",
                "f": f_bag, "g": g_bag,
            })
        write_avro(root / f"part-{fi:03d}.avro", records, schema,
                   block_records=130)
    return root


def _config():
    return GameDataConfig(
        shards={
            "dense": FeatureShardConfig(bags=("f",), has_intercept=True),
            "wide": FeatureShardConfig(bags=("g",), has_intercept=False,
                                       dense_threshold=4),
        },
        entity_fields=("member",),
    )


def _chunks_equal(a, b):
    np.testing.assert_array_equal(a.y, b.y)
    np.testing.assert_array_equal(a.weights, b.weights)
    np.testing.assert_array_equal(a.offsets, b.offsets)
    for s, X in a.shards.items():
        Y = b.shards[s]
        if isinstance(X, SparseRows):
            np.testing.assert_array_equal(np.asarray(X.indices),
                                          np.asarray(Y.indices))
            np.testing.assert_array_equal(np.asarray(X.values),
                                          np.asarray(Y.values))
        else:
            np.testing.assert_array_equal(np.asarray(X), np.asarray(Y))
    for e, col in a.entity_ids.items():
        np.testing.assert_array_equal(col, b.entity_ids[e])


@pytest.fixture(scope="module")
def dataset(tmp_path_factory):
    root = _write_files(tmp_path_factory.mktemp("ingest_plane"))
    config = _config()
    scan = scan_ingest(str(root), config)
    _, chunks = iter_game_chunks(str(root), config, scan.index_maps,
                                 chunk_rows=300, sparse_k=4)
    return root, config, scan, list(chunks)


class TestScanIngest:
    def test_one_pass_scan_matches_two_pass(self, dataset):
        """scan_ingest's maps == build_index_maps_streaming's, its block
        index answers scan_row_counts without reopening, and its row
        count matches the header scan."""
        root, config, scan, _ = dataset
        from photon_tpu.data.streaming import build_index_maps_streaming

        maps2 = build_index_maps_streaming(str(root), config)
        for s in config.shards:
            assert scan.index_maps[s].keys_in_order() == \
                maps2[s].keys_in_order()
        assert scan.n_rows == 1200
        assert scan_row_counts(str(root)) == scan.row_counts
        assert scan_row_counts(str(root),
                               block_index=scan.block_index) == \
            scan.row_counts

    def test_task_plan_matches_serial_chunk_boundaries(self, dataset):
        """plan_chunk_tasks closes tasks at exactly the block boundaries
        the serial chunker closes chunks on — including the non-dividing
        tail blocks."""
        _, _, scan, ref = dataset
        tasks = plan_chunk_tasks(scan.block_index, 300)
        assert len(tasks) == len(ref)
        assert [t.n_rows for t in tasks] == [c.n for c in ref]
        assert sum(t.n_rows for t in tasks) == 1200


class TestParallelDecode:
    @pytest.mark.parametrize("chunk_rows", [250, 300, 1000])
    def test_thread_pool_parity_matrix(self, dataset, chunk_rows):
        """Worker-pool chunks == in-process chunks bit-for-bit, in order,
        across chunk sizes that do and do not divide the block counts."""
        root, config, scan, _ = dataset
        _, c0 = iter_game_chunks(str(root), config, scan.index_maps,
                                 chunk_rows=chunk_rows, sparse_k=4)
        ref = list(c0)
        _, c1 = iter_game_chunks_parallel(
            str(root), config, scan.index_maps, chunk_rows=chunk_rows,
            sparse_k=4, workers=2, mode="thread",
            block_index=scan.block_index)
        got = list(c1)
        assert len(got) == len(ref) >= 2
        for a, b in zip(ref, got):
            _chunks_equal(a, b)

    def test_process_pool_parity(self, dataset):
        """The real plane: spawn-context worker processes decode the
        blocks; chunks come back bit-identical and in order."""
        root, config, scan, ref = dataset
        _, c = iter_game_chunks_parallel(
            str(root), config, scan.index_maps, chunk_rows=300,
            sparse_k=4, workers=2, mode="process")
        got = list(c)
        assert len(got) == len(ref)
        for a, b in zip(ref, got):
            _chunks_equal(a, b)

    def test_worker_kill_matrix(self, dataset):
        """An injected ingest_worker kill at the FIRST / a MIDDLE / the
        LAST retired task degrades that chunk to in-process decode: no
        hung iterator, chunk order and content unchanged, the death
        counted."""
        from photon_tpu import telemetry

        root, config, scan, ref = dataset
        n = len(ref)
        for occ in (1, max(n // 2, 1), n):
            run = telemetry.start_run("kill")
            try:
                with fault_plan(FaultPlan.kill_at("ingest_worker", occ)):
                    _, c = iter_game_chunks_parallel(
                        str(root), config, scan.index_maps, chunk_rows=300,
                        sparse_k=4, workers=2, mode="thread",
                        block_index=scan.block_index)
                    got = list(c)
            finally:
                telemetry.finish_run()
            assert len(got) == n
            for a, b in zip(ref, got):
                _chunks_equal(a, b)
            assert run.counters.get("ingest.worker_deaths", 0) >= 1

    def test_python_decoder_parity(self, dataset):
        """use_native=False in the workers matches the forced-Python
        serial stream (decoder choice is parity-pinned either way)."""
        root, config, scan, _ = dataset
        _, c0 = iter_game_chunks(str(root), config, scan.index_maps,
                                 chunk_rows=300, sparse_k=4,
                                 use_native=False)
        ref = list(c0)
        _, c1 = iter_game_chunks_parallel(
            str(root), config, scan.index_maps, chunk_rows=300,
            sparse_k=4, workers=2, mode="thread", use_native=False,
            block_index=scan.block_index)
        for a, b in zip(ref, list(c1)):
            _chunks_equal(a, b)


class TestChunkCache:
    def test_cached_equals_cold_bitwise(self, dataset, tmp_path):
        """Cold decode == cache-building pass == cached epoch, bitwise,
        across dense + sparse shards and the GAME entity columns; the
        cached epoch is counted as a hit."""
        from photon_tpu import telemetry

        root, config, scan, ref = dataset
        cache = tmp_path / "cache"
        _, c = open_chunk_source(str(root), config, scan.index_maps,
                                 chunk_rows=300, sparse_k=4,
                                 cache_dir=str(cache))
        cold = list(c)
        run = telemetry.start_run("hit")
        try:
            _, c = open_chunk_source(str(root), config, scan.index_maps,
                                     chunk_rows=300, sparse_k=4,
                                     cache_dir=str(cache))
            warm = list(c)
        finally:
            telemetry.finish_run()
        assert run.counters.get("ingest.cache_hits", 0) == 1
        assert len(cold) == len(warm) == len(ref)
        for a, b, w in zip(ref, cold, warm):
            _chunks_equal(a, b)
            _chunks_equal(a, w)

    def test_kill_mid_commit_matrix_falls_back(self, dataset, tmp_path):
        """Kills at the first / a middle / the LAST cache_commit
        occurrence (the manifest commit itself) leave a TORN entry that
        reads as a MISS — the next run falls back to Avro decode, serves
        bit-identical chunks, and rebuilds a good entry. No partial chunk
        is ever served."""
        root, config, scan, ref = dataset
        key = cc.cache_key(str(root), config, scan.index_maps, 300, 4)
        with record_sites() as rec:
            _, c = open_chunk_source(str(root), config, scan.index_maps,
                                     chunk_rows=300, sparse_k=4,
                                     cache_dir=str(tmp_path / "dry"))
            list(c)
        n_hits = rec.hits["cache_commit"]
        for occ in (1, max(n_hits // 2, 1), n_hits):
            cache = tmp_path / f"kill_{occ}"
            with pytest.raises(InjectedFault):
                with fault_plan(FaultPlan.kill_at("cache_commit", occ)):
                    _, c = open_chunk_source(
                        str(root), config, scan.index_maps, chunk_rows=300,
                        sparse_k=4, cache_dir=str(cache))
                    list(c)
            assert cc.open_cache(str(cache), key, "game_chunks") is None
            _, c = open_chunk_source(str(root), config, scan.index_maps,
                                     chunk_rows=300, sparse_k=4,
                                     cache_dir=str(cache))
            rebuilt = list(c)
            for a, b in zip(ref, rebuilt):
                _chunks_equal(a, b)
            assert cc.open_cache(str(cache), key,
                                 "game_chunks") is not None

    def test_schema_hash_invalidation(self, dataset):
        """The key moves with every layout/config/map input: chunk_rows,
        sparse_k, GameDataConfig, index maps, entry kind."""
        root, config, scan, _ = dataset
        maps = scan.index_maps
        base = cc.cache_key(str(root), config, maps, 300, 4)
        assert cc.cache_key(str(root), config, maps, 256, 4) != base
        assert cc.cache_key(str(root), config, maps, 300, 8) != base
        import dataclasses

        cfg2 = dataclasses.replace(config, entity_fields=())
        assert cc.cache_key(str(root), cfg2, maps, 300, 4) != base
        cfg3 = dataclasses.replace(config, shards={
            **config.shards,
            "wide": FeatureShardConfig(bags=("g",), has_intercept=False,
                                       dense_threshold=8)})
        assert cc.cache_key(str(root), cfg3, maps, 300, 4) != base
        from photon_tpu.data.index_map import IndexMap

        maps2 = dict(maps)
        maps2["wide"] = IndexMap({"only": 0}, frozen=True)
        assert cc.cache_key(str(root), config, maps2, 300, 4) != base
        assert cc.cache_key(str(root), config, maps, 300, 4,
                            kind="ladder") != base
        # and the key is STABLE when nothing changed
        assert cc.cache_key(str(root), config, maps, 300, 4) == base

    def test_newer_schema_refused(self, dataset, tmp_path):
        root, config, scan, _ = dataset
        cache = tmp_path / "cache"
        _, c = open_chunk_source(str(root), config, scan.index_maps,
                                 chunk_rows=300, sparse_k=4,
                                 cache_dir=str(cache))
        list(c)
        key = cc.cache_key(str(root), config, scan.index_maps, 300, 4)
        mpath = os.path.join(cc.entry_dir(str(cache), key),
                             "MANIFEST.json")
        doc = json.load(open(mpath))
        doc["schema"] = cc.CACHE_SCHEMA_VERSION + 1
        with open(mpath, "w") as f:
            json.dump(doc, f)
        with pytest.raises(cc.ChunkCacheSchemaError):
            open_chunk_source(str(root), config, scan.index_maps,
                              chunk_rows=300, sparse_k=4,
                              cache_dir=str(cache))

    def test_corrupted_payload_detected(self, dataset, tmp_path):
        root, config, scan, _ = dataset
        cache = tmp_path / "cache"
        _, c = open_chunk_source(str(root), config, scan.index_maps,
                                 chunk_rows=300, sparse_k=4,
                                 cache_dir=str(cache))
        list(c)
        key = cc.cache_key(str(root), config, scan.index_maps, 300, 4)
        bag = cc.open_cache(str(cache), key, "game_chunks")
        victim = os.path.join(bag.dir, bag.manifest["entries"][0]["file"])
        raw = open(victim, "rb").read()
        with open(victim, "wb") as f:
            f.write(raw[:-4] + b"\x00\x01\x02\x03")
        with pytest.raises(cc.ChunkCacheCorrupt):
            _, c = open_chunk_source(str(root), config, scan.index_maps,
                                     chunk_rows=300, sparse_k=4,
                                     cache_dir=str(cache))
            list(c)

    def test_response_mask_and_presence_round_trip(self, tmp_path):
        """allow_missing_response masks and optional-entity presence ride
        the cache: the cached stream restores them onto the handle
        exactly as a live decode."""
        rng = np.random.default_rng(3)
        schema = training_example_schema(feature_bags=("f",),
                                         entity_fields=("member",))
        # nullable response: the allow_missing_response regime
        schema["fields"][0]["type"] = ["null", "double"]
        records = []
        for i in range(60):
            records.append({
                "response": float(i) if i % 4 else None,
                "offset": None, "weight": None, "uid": f"r{i}",
                "member": f"m{i % 5}" if i % 3 else None,
                "f": [{"name": "x", "term": "",
                       "value": float(rng.normal())}]})
        root = tmp_path / "data"
        os.makedirs(root)
        write_avro(root / "a.avro", records, schema, block_records=16)
        config = GameDataConfig(
            shards={"s": FeatureShardConfig(bags=("f",),
                                            has_intercept=True)},
            entity_fields=("member",),
            optional_entity_fields=("member",),
            allow_missing_response=True)
        scan = scan_ingest(str(root), config)
        cache = tmp_path / "cache"

        def collect(cache_dir):
            stream, chunks = open_chunk_source(
                str(root), config, scan.index_maps, chunk_rows=25,
                cache_dir=cache_dir)
            out = []
            for ch in chunks:
                out.append((np.asarray(stream.last_response_mask),
                            np.asarray(
                                stream.last_entity_presence["member"])))
            return stream, out

        s_cold, cold = collect(str(cache))
        s_warm, warm = collect(str(cache))
        assert s_cold.saw_missing_response and s_warm.saw_missing_response
        assert len(cold) == len(warm) >= 2
        for (ma, pa), (mb, pb) in zip(cold, warm):
            np.testing.assert_array_equal(ma, mb)
            np.testing.assert_array_equal(pa, pb)

    def test_distributed_writer_convention(self, tmp_path):
        """The multi-host cache directory convention (docs/INGEST.md):
        p<k>_ payload prefixes, k>0 sidecars instead of manifests,
        process 0 merging entries + metas and committing the ONE shared
        manifest LAST; a missing sidecar fails loudly instead of
        publishing a partial entry."""
        from photon_tpu.data.chunk_cache import (ChunkCacheWriter,
                                                 open_cache,
                                                 shard_chunk_range)

        key = "d" * 64
        w1 = ChunkCacheWriter(tmp_path, key, "game_chunks",
                              meta={"n_chunks": 1, "n_rows": 7},
                              process=1, n_processes=2)
        w1.add_array("c00001.y", np.arange(3.0))
        w1.commit()
        # no manifest yet: the entry is a MISS everywhere until process 0
        assert open_cache(tmp_path, key, "game_chunks") is None
        w0 = ChunkCacheWriter(tmp_path, key, "game_chunks",
                              meta={"n_chunks": 1, "n_rows": 5},
                              process=0, n_processes=2)
        w0.add_array("c00000.y", np.arange(2.0))
        w0.commit(sidecar_timeout_s=5)
        bag = open_cache(tmp_path, key, "game_chunks")
        assert sorted(bag.names()) == ["c00000.y", "c00001.y"]
        assert bag.meta["n_chunks"] == 2 and bag.meta["n_rows"] == 12
        np.testing.assert_array_equal(
            np.asarray(bag.array("c00001.y")), np.arange(3.0))
        files = sorted(os.listdir(w0.dir))
        assert any(f.startswith("p0_") for f in files)
        assert any(f.startswith("p1_") for f in files)
        # process 0 with a never-arriving sidecar refuses to publish
        key2 = "e" * 64
        lone = ChunkCacheWriter(tmp_path, key2, "game_chunks",
                                meta={}, process=0, n_processes=2)
        lone.add_array("c00000.y", np.arange(2.0))
        with pytest.raises(TimeoutError, match="sidecar"):
            lone.commit(sidecar_timeout_s=0.2)
        assert open_cache(tmp_path, key2, "game_chunks") is None
        # the canonical split covers [0, n) contiguously in order
        spans = [shard_chunk_range(10, k, 3) for k in range(3)]
        assert spans == [(0, 4), (4, 7), (7, 10)]


class TestLadderCache:
    @pytest.mark.parametrize("n_shards", [1, 2])
    def test_ladder_cache_round_trips_bitwise(self, dataset, tmp_path,
                                              n_shards):
        """The direct-to-blocked-ELL build == its cached reopen,
        leaf-for-leaf, for both the single-device and the mesh
        (ShardedBlockedEllRows) ladders."""
        import jax

        root, config, scan, _ = dataset
        cache = tmp_path / f"ladder{n_shards}"
        kw = dict(d_dense=64, sparse_k=4, n_shards=n_shards,
                  cache_dir=str(cache))
        cb1 = chunk_blocked_ell_from_avro(str(root), config,
                                          scan.index_maps, "wide", 256,
                                          **kw)
        cb2 = chunk_blocked_ell_from_avro(str(root), config,
                                          scan.index_maps, "wide", 256,
                                          **kw)
        assert cb1.X.n_chunks == cb2.X.n_chunks
        assert cb1.X.chunk_shards == cb2.X.chunk_shards == n_shards
        l1 = jax.tree_util.tree_leaves(cb1.X.chunks)
        l2 = jax.tree_util.tree_leaves(cb2.X.chunks)
        assert len(l1) == len(l2)
        for a, b in zip(l1, l2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for a, b in ((cb1.y, cb2.y), (cb1.weights, cb2.weights),
                     (cb1.offsets, cb2.offsets),
                     (cb1.X.perm_cols, cb2.X.perm_cols),
                     (cb1.X.inv_perm, cb2.X.inv_perm)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert cb1.X.last_col_pos == cb2.X.last_col_pos


class TestAdaptivePrefetch:
    def test_widen_narrow_and_budget(self):
        ap = AdaptivePrefetch(depth=2, max_depth=8, byte_budget=1000)
        ap.observe(stall_s=1.0, compute_s=0.1, n_items=4, item_bytes=100)
        assert ap.depth == 4  # stall > compute: +2
        ap.observe(stall_s=0.2, compute_s=1.0, n_items=4, item_bytes=100)
        assert ap.depth == 5  # stalled (>5% of compute): +1
        ap.observe(stall_s=0.0, compute_s=1.0, n_items=4, item_bytes=100)
        assert ap.depth == 4  # stall-free: -1
        ap.observe(stall_s=9.0, compute_s=0.1, n_items=4, item_bytes=200)
        assert ap.depth == 5  # byte budget: 1000 // 200
        ap.observe_wait(0.5, 200)
        assert ap.depth == 5  # still capped
        ap.observe_wait(0.5, 50)
        assert ap.depth == 6  # wider budget at smaller items
        assert [d["why"] for d in ap.decisions] == [
            "stalled", "stalled", "stall-free", "stalled", "upload-wait"]

    def test_iter_device_feeds_controller_and_telemetry(self, tmp_path):
        """A streamed pass under the controller records its decision
        (controller trace + a prefetch_decision JSONL event) and yields
        chunks identical to a fixed window — depth is an overlap knob,
        never a results knob."""
        from photon_tpu import telemetry
        from photon_tpu.data.dataset import chunk_batch, make_batch
        from photon_tpu.telemetry import read_jsonl

        rng = np.random.default_rng(0)
        X = rng.normal(size=(64, 4)).astype(np.float32)
        cb = chunk_batch(make_batch(X, np.zeros(64, np.float32)), 16)
        ctl = AdaptivePrefetch()
        jsonl = str(tmp_path / "run.jsonl")
        telemetry.start_run("prefetch", jsonl_path=jsonl)
        try:
            fixed = [np.asarray(b.y) for _, b in cb.iter_device(prefetch=2)]
            ctl_out = [np.asarray(b.y)
                       for _, b in cb.iter_device(prefetch=ctl)]
        finally:
            telemetry.finish_run()
        for a, b in zip(fixed, ctl_out):
            np.testing.assert_array_equal(a, b)
        assert len(ctl.decisions) == 1
        events = [e for e in read_jsonl(jsonl)
                  if e.get("type") == "prefetch_decision"]
        assert len(events) == 1
        assert events[0]["depth"] >= 1


class TestPlaneSolverParity:
    def test_streamed_glm_plane_on_off_bit_identical(self, dataset,
                                                     tmp_path):
        """THE acceptance parity, streamed-GLM face: the host-chunked
        dataset assembled through the plane (worker pool + cache, then
        the cached epoch) is bit-identical to the serial read, the chunk
        program sees ONE dispatch signature across all three sources, and
        the streamed L-BFGS solve lands f64-bit-identical coefficients."""
        from photon_tpu.analysis.rules import TraceSignatureLog
        from photon_tpu.data.dataset import make_chunked_batch
        from photon_tpu.models.training import train_glm
        from photon_tpu.ops.losses import TaskType
        from photon_tpu.optim.config import OptimizerConfig
        from photon_tpu.optim.regularization import l2

        root, config, scan, _ = dataset
        cache = tmp_path / "cache"

        def read(**kw):
            data, n_real = stream_to_host(
                str(root), config, scan.index_maps,
                chunked_shards={"dense"}, chunk_rows=300,
                objective_chunk_rows=256, sparse_k=4, **kw)
            assert n_real == 1200
            return data

        plain = read()
        plane = read(workers=2, cache_dir=str(cache),
                     block_index=scan.block_index)
        cached = read(workers=2, cache_dir=str(cache))
        log = TraceSignatureLog()
        batches = []
        for data in (plain, plane, cached):
            cb = make_chunked_batch(data.shards["dense"], data.y,
                                    data.weights, data.offsets)
            if batches:
                ref = batches[0]
                assert cb.n_chunks == ref.n_chunks
                for i in range(cb.n_chunks):
                    a, b = ref.chunk(i), cb.chunk(i)
                    np.testing.assert_array_equal(np.asarray(a.X),
                                                  np.asarray(b.X))
                    np.testing.assert_array_equal(a.y, b.y)
                    np.testing.assert_array_equal(a.weights, b.weights)
            log.record("ingest.chunk0", tuple(cb.chunk(0)))
            batches.append(cb)
        assert len(log.signatures("ingest.chunk0")) == 1
        assert not log.hazards()
        cfg = OptimizerConfig(max_iters=8, tolerance=0.0, reg=l2(),
                              reg_weight=1e-2, history=4)
        ws = [np.asarray(
            train_glm(b, TaskType.LOGISTIC_REGRESSION,
                      cfg)[0].coefficients.means, dtype=np.float64)
            for b in batches]
        np.testing.assert_array_equal(ws[0], ws[1])
        np.testing.assert_array_equal(ws[0], ws[2])

    def test_game_driver_plane_on_off_bit_identical(self, tmp_path):
        """THE acceptance parity, GAME-e2e face: run_training (fixed +
        per-entity random effect) with the ingest plane on (workers +
        chunk cache, twice — cold build then cached epoch) produces
        models f64-bit-identical to the plane-off driver run."""
        from photon_tpu.drivers import TrainingParams, run_training

        root = _write_files(tmp_path / "train", n_files=2,
                            rows_per_file=220, seed=7)
        shards = {"fixedShard": {"bags": ["f"], "has_intercept": True},
                  "memShard": {"bags": ["g"], "has_intercept": False,
                               "dense_threshold": 4}}
        coords = {"fixed": {"feature_shard": "fixedShard",
                            "reg_type": "l2", "reg_weight": 0.5,
                            "max_iters": 15},
                  "perMember": {"feature_shard": "memShard",
                                "entity_name": "member",
                                "reg_type": "l2", "reg_weight": 2.0,
                                "max_iters": 10}}

        def fit(tag, **kw):
            return run_training(TrainingParams(
                train_path=str(root), output_dir=str(tmp_path / tag),
                feature_shards=shards, coordinates=coords,
                entity_fields=["member"], n_sweeps=1, sparse_k=4,
                streaming=True, streaming_chunk_rows=128, **kw))

        off = fit("off")
        cache = str(tmp_path / "cache")
        on = fit("on", ingest_workers=2, chunk_cache_dir=cache)
        warm = fit("warm", ingest_workers=2, chunk_cache_dir=cache)
        for run_out in (on, warm):
            ca = off.best.model.coordinates
            cb = run_out.best.model.coordinates
            assert set(ca) == set(cb)
            np.testing.assert_array_equal(
                np.asarray(ca["fixed"].model.coefficients.means),
                np.asarray(cb["fixed"].model.coefficients.means))
            np.testing.assert_array_equal(
                np.asarray(ca["perMember"].coefficients),
                np.asarray(cb["perMember"].coefficients))
            np.testing.assert_array_equal(ca["perMember"].entity_keys,
                                          cb["perMember"].entity_keys)


class TestSelftestCLI:
    @pytest.mark.slow
    def test_selftest_cli(self):
        import subprocess
        import sys

        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.run(
            [sys.executable, "-m", "photon_tpu.ingest", "--selftest",
             "--json"], capture_output=True, text=True, timeout=600,
            env=env)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        assert report["ok"]
        assert set(report["checks"]) == {
            "scan", "decode_parity", "cache", "ladder", "prefetch",
            "contract"}
