"""Lane-minor grid solver parity (optim/lane_lbfgs.py, ops/lane_objective.py).

Mirrors the reference's grid-search contract (GameEstimator over a λ grid:
each grid point must train AS IF it were its own job): every lane of the
lock-step lane-minor solver must match an independent single-lane
`train_glm` solve on the same data to f32 reduction noise, across matrix
representations, tasks, weights/offsets, normalization, and skewed grids.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.dataset import GLMBatch, make_batch
from photon_tpu.data.matrix import (SparseRows, matvec, matvec_lanes,
                                    rmatvec, rmatvec_lanes, to_hybrid)
from photon_tpu.models.training import train_glm, train_glm_grid
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig, OptimizerType
from photon_tpu.optim.regularization import elastic_net, l2


def _sparse_problem(rng, n=600, d=120, k=8, task=TaskType.LOGISTIC_REGRESSION):
    ind = rng.integers(0, d - 1, size=(n, k)).astype(np.int32)
    ind[:, -1] = d - 1  # intercept column
    val = rng.normal(size=(n, k)).astype(np.float32)
    val[:, -1] = 1.0
    wt = rng.normal(size=d).astype(np.float32) * 0.5
    z = np.einsum("nk,nk->n", val, wt[ind])
    if task is TaskType.LINEAR_REGRESSION:
        y = (z + 0.1 * rng.normal(size=n)).astype(np.float32)
    elif task is TaskType.POISSON_REGRESSION:
        y = rng.poisson(np.exp(np.clip(z * 0.3, None, 3.0))).astype(np.float32)
    else:
        y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)
    return SparseRows(jnp.asarray(ind), jnp.asarray(val), d), jnp.asarray(y)


def _grid_vs_sequential(batch, task, cfg, weights, atol=2e-2):
    """Each lane must train AS IF it were its own job. Near a tolerance-
    converged optimum the two f32 solver paths (lock-step lanes vs solo)
    take different line-search trial sequences, so coefficients agree to
    the optimum's conditioning (loose atol) while the achieved OBJECTIVE
    values — the quantity convergence actually pins — must match tightly."""
    grid = train_glm_grid(batch, task, cfg, weights)
    assert len(grid) == len(weights)
    for wt, (model, res) in zip(weights, grid):
        m_seq, r_seq = train_glm(
            batch, task, dataclasses.replace(cfg, reg_weight=wt))
        np.testing.assert_allclose(
            float(res.value), float(r_seq.value), rtol=1e-5,
            err_msg=f"objective mismatch at weight {wt}")
        np.testing.assert_allclose(
            np.asarray(model.coefficients.means),
            np.asarray(m_seq.coefficients.means), atol=atol,
            err_msg=f"lane mismatch at weight {wt}")
        assert bool(res.converged) == bool(r_seq.converged)


@pytest.mark.parametrize("task", [TaskType.LOGISTIC_REGRESSION,
                                  TaskType.LINEAR_REGRESSION,
                                  TaskType.POISSON_REGRESSION])
def test_lane_grid_matches_sequential_sparse(rng, task):
    X, y = _sparse_problem(rng, task=task)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    _grid_vs_sequential(batch, task, cfg, [1e-2, 1e-1, 1.0, 10.0])


@pytest.mark.cpu_parity_drift
def test_lane_grid_matches_sequential_hybrid(rng):
    X, y = _sparse_problem(rng, n=600, d=500, k=10)
    H = to_hybrid(X, 64)
    batch = make_batch(H, y)
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    _grid_vs_sequential(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                        [1e-2, 1.0, 30.0])


def test_lane_grid_matches_sequential_dense_weights_offsets(rng):
    n, d = 300, 20
    X = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    wt = rng.normal(size=d).astype(np.float32)
    y = jnp.asarray((rng.random(n) < 1 / (1 + np.exp(-X @ wt))).astype(
        np.float32))
    weights = jnp.asarray(rng.uniform(0.5, 2.0, size=n).astype(np.float32))
    offsets = jnp.asarray(rng.normal(size=n).astype(np.float32) * 0.3)
    batch = GLMBatch(X=X, y=y, weights=weights, offsets=offsets)
    cfg = OptimizerConfig(max_iters=100, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    _grid_vs_sequential(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                        [1e-2, 1.0, 100.0])


def test_lane_grid_normalization(rng):
    from photon_tpu.data.normalization import NormalizationContext, NormalizationType

    n, d = 300, 12
    X = np.asarray(rng.normal(size=(n, d)) * rng.uniform(0.1, 8.0, size=d),
                   dtype=np.float32)
    X[:, -1] = 1.0
    wt = rng.normal(size=d).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-X @ wt))).astype(np.float32)
    norm = NormalizationContext.build(jnp.asarray(X),
                                      NormalizationType.STANDARDIZATION,
                                      intercept_index=d - 1)
    batch = make_batch(jnp.asarray(X), jnp.asarray(y))
    cfg = OptimizerConfig(max_iters=100, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    weights = [1e-2, 1.0]
    grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg, weights,
                          normalization=norm)
    for wt_, (model, _) in zip(weights, grid):
        m_seq, _ = train_glm(batch, TaskType.LOGISTIC_REGRESSION,
                             dataclasses.replace(cfg, reg_weight=wt_),
                             normalization=norm)
        np.testing.assert_allclose(np.asarray(model.coefficients.means),
                                   np.asarray(m_seq.coefficients.means),
                                   atol=3e-3)


def test_lane_grid_skewed_weights_converge_independently(rng):
    """Wildly skewed grids: the heavy-reg lane converges in a handful of
    iterations, the light lane needs many; per-lane freezing must keep
    both correct and report per-lane iteration counts."""
    X, y = _sparse_problem(rng)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=120, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    weights = [1e-4, 1e4]
    grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg, weights)
    iters = [int(r.iterations) for _, r in grid]
    assert iters[1] < iters[0], iters  # heavy reg stops far earlier
    _grid_vs_sequential(batch, TaskType.LOGISTIC_REGRESSION, cfg, weights)


def test_lane_grid_owlqn_matches_sequential(rng):
    """Elastic-net sweeps ride the lane-minor OWL-QN solver
    (optim/lane_owlqn.py): each lane must match its own sequential OWL-QN
    solve — coefficients, achieved objective, AND the L1 sparsity the
    orthant projection is there to produce."""
    X, y = _sparse_problem(rng)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=120, tolerance=1e-6,
                          reg=elastic_net(0.5), reg_weight=0.0, history=5)
    weights = [1e-2, 1e-1, 3.0]
    grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg, weights)
    for wt, (model, res) in zip(weights, grid):
        m_seq, r_seq = train_glm(
            batch, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, reg_weight=wt,
                                optimizer=OptimizerType.OWLQN))
        np.testing.assert_allclose(float(res.value), float(r_seq.value),
                                   rtol=1e-5,
                                   err_msg=f"objective mismatch at {wt}")
        np.testing.assert_allclose(np.asarray(model.coefficients.means),
                                   np.asarray(m_seq.coefficients.means),
                                   atol=2e-3)
    # The heavy-L1 lane must be genuinely sparse — exact zeros, not small
    # (the sequential OWL-QN zeroes the same ~40% at this weight).
    w_heavy = np.asarray(grid[-1][0].coefficients.means)
    assert (w_heavy == 0.0).sum() > w_heavy.size // 3


@pytest.mark.parametrize("task", [TaskType.LOGISTIC_REGRESSION,
                                  TaskType.LINEAR_REGRESSION])
def test_lane_grid_tron_matches_sequential(rng, task):
    """TRON sweeps ride the lane-minor margin-cached TRON
    (optim/lane_tron.py): each lane must match its own sequential TRON
    solve — same trust-region constants, same Steihaug subproblem, same
    stop rules, per lane."""
    X, y = _sparse_problem(rng, task=task)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(optimizer=OptimizerType.TRON, max_iters=80,
                          tolerance=1e-6, reg=l2(), reg_weight=0.0,
                          cg_max_iters=20)
    _grid_vs_sequential(batch, task, cfg, [1e-2, 1.0, 10.0])


def test_lane_grid_tron_sharded_hybrid(rng, mesh8):
    from photon_tpu.data.dataset import shard_hybrid_batch

    X, y = _sparse_problem(rng, n=640, d=400, k=10)
    H = to_hybrid(X, 64)
    batch = shard_hybrid_batch(make_batch(H, y), mesh8.devices.size)
    cfg = OptimizerConfig(optimizer=OptimizerType.TRON, max_iters=80,
                          tolerance=1e-6, reg=l2(), reg_weight=0.0)
    weights = [1e-1, 1.0, 30.0]
    grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg, weights,
                          mesh=mesh8)
    single = make_batch(to_hybrid(X, 64), y)
    for wt, (model, res) in zip(weights, grid):
        m_seq, r_seq = train_glm(single, TaskType.LOGISTIC_REGRESSION,
                                 dataclasses.replace(cfg, reg_weight=wt))
        np.testing.assert_allclose(float(res.value), float(r_seq.value),
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(model.coefficients.means),
                                   np.asarray(m_seq.coefficients.means),
                                   atol=2e-2)


def test_lane_grid_owlqn_variance_fallback_vmap_path(rng):
    """L1 grids that request variances cannot ride the lane road (the
    lane runners skip variance computation) — they must fall back to the
    vmapped runner and still match sequential solves, variances included."""
    from photon_tpu.models.variance import VarianceComputationType

    X, y = _sparse_problem(rng)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=120, tolerance=1e-6,
                          reg=elastic_net(0.5), reg_weight=0.0, history=5)
    weights = [1e-2, 1e-1]
    grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg, weights,
                          variance=VarianceComputationType.SIMPLE)
    for wt, (model, res) in zip(weights, grid):
        m_seq, _ = train_glm(
            batch, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, reg_weight=wt,
                                optimizer=OptimizerType.OWLQN),
            variance=VarianceComputationType.SIMPLE)
        np.testing.assert_allclose(np.asarray(model.coefficients.means),
                                   np.asarray(m_seq.coefficients.means),
                                   atol=2e-3)
        assert model.coefficients.variances is not None
        np.testing.assert_allclose(np.asarray(model.coefficients.variances),
                                   np.asarray(m_seq.coefficients.variances),
                                   rtol=2e-2, atol=1e-4)


@pytest.mark.cpu_parity_drift
def test_lane_grid_owlqn_sharded_hybrid(rng, mesh8):
    from photon_tpu.data.dataset import shard_hybrid_batch

    X, y = _sparse_problem(rng, n=640, d=400, k=10)
    H = to_hybrid(X, 64)
    batch = shard_hybrid_batch(make_batch(H, y), mesh8.devices.size)
    cfg = OptimizerConfig(max_iters=120, tolerance=1e-6,
                          reg=elastic_net(0.5), reg_weight=0.0, history=5)
    weights = [1e-1, 1.0]
    grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg, weights,
                          mesh=mesh8)
    single = make_batch(to_hybrid(X, 64), y)
    for wt, (model, res) in zip(weights, grid):
        m_seq, r_seq = train_glm(
            single, TaskType.LOGISTIC_REGRESSION,
            dataclasses.replace(cfg, reg_weight=wt,
                                optimizer=OptimizerType.OWLQN))
        np.testing.assert_allclose(float(res.value), float(r_seq.value),
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(model.coefficients.means),
                                   np.asarray(m_seq.coefficients.means),
                                   atol=2e-2)


def test_lane_grid_sharded_hybrid(rng, mesh8):
    from photon_tpu.data.dataset import shard_hybrid_batch

    X, y = _sparse_problem(rng, n=640, d=400, k=10)
    H = to_hybrid(X, 64)
    batch = shard_hybrid_batch(make_batch(H, y), mesh8.devices.size)
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    # d≈n: the near-unregularized lane's optimum has flat directions
    # where f32 paths wander ~0.04; keep the lightest weight conditioned.
    weights = [1e-1, 1.0, 30.0]
    grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg, weights,
                          mesh=mesh8)
    single = make_batch(to_hybrid(X, 64), y)
    for wt, (model, res) in zip(weights, grid):
        m_seq, r_seq = train_glm(single, TaskType.LOGISTIC_REGRESSION,
                                 dataclasses.replace(cfg, reg_weight=wt))
        # Two divergence sources vs the single-device sequential run: lane
        # lock-step AND the shard psum's reduction order — same contract as
        # _grid_vs_sequential (tight objective, conditioning-loose coeffs).
        np.testing.assert_allclose(float(res.value), float(r_seq.value),
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(model.coefficients.means),
                                   np.asarray(m_seq.coefficients.means),
                                   atol=2e-2)


def test_lane_grid_bf16_history_quality(rng):
    """lane_history_dtype="bfloat16" stores the (m, d, G) S/Y pairs
    half-width while every steering inner product (rho, gamma, curvature
    acceptance) stays f32 from the unrounded pair. The rounded two-loop
    direction is still vetted by the Wolfe search, so achieved objectives
    must match the f32-history run tightly and coefficients to the
    optimum's conditioning."""
    X, y = _sparse_problem(rng)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=l2(),
                          reg_weight=0.0, history=5)
    weights = [1e-2, 1.0, 10.0]
    grid32 = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                            weights)
    grid16 = train_glm_grid(
        batch, TaskType.LOGISTIC_REGRESSION,
        dataclasses.replace(cfg, lane_history_dtype="bfloat16"), weights)
    for (m32, r32), (m16, r16) in zip(grid32, grid16):
        np.testing.assert_allclose(float(r16.value), float(r32.value),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(m16.coefficients.means),
                                   np.asarray(m32.coefficients.means),
                                   atol=2e-2)
        assert bool(r16.converged)


def test_matvec_lanes_match_single(rng):
    n, d, k, G = 64, 120, 6, 5
    ind = rng.integers(0, d, size=(n, k)).astype(np.int32)
    val = rng.normal(size=(n, k)).astype(np.float32)
    X = SparseRows(jnp.asarray(ind), jnp.asarray(val), d)
    H = to_hybrid(X, 16)
    D = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32))
    W = jnp.asarray(rng.normal(size=(d, G)).astype(np.float32))
    R = jnp.asarray(rng.normal(size=(n, G)).astype(np.float32))
    for M in (X, H, D):
        mv = np.asarray(matvec_lanes(M, W))
        rv = np.asarray(rmatvec_lanes(M, R))
        for g in range(G):
            np.testing.assert_allclose(
                mv[:, g], np.asarray(matvec(M, W[:, g])), rtol=2e-5,
                atol=1e-5)
            np.testing.assert_allclose(
                rv[:, g], np.asarray(rmatvec(M, R[:, g])), rtol=2e-5,
                atol=1e-5)


def test_lane_grid_device_results_layout(rng):
    X, y = _sparse_problem(rng, n=200, d=100, k=6)
    batch = make_batch(X, y)
    cfg = OptimizerConfig(max_iters=40, tolerance=1e-7, reg=l2(),
                          reg_weight=0.0, history=5)
    res, var = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION, cfg,
                              [1e-2, 1.0, 30.0], device_results=True)
    assert res.w.shape == (3, 100)
    assert res.value.shape == (3,)
    assert var is None
