"""Lane-batched cost-aware tuner tests (round 16): fixed-chunk dispatch
with zero retrace across rounds, successive-halving survivor compaction
edges, the cost model's pre-dispatch budget gate, and the GP pow2
observation ladder that keeps the fit from recompiling per round."""
import numpy as np
import pytest

from photon_tpu.data.dataset import make_batch
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.regularization import l2
from photon_tpu.parallel.mesh import compact_rows
from photon_tpu.tuning import (
    LaneBudget,
    LaneTuningResult,
    RoundBudgetError,
    fit_gp,
    tune_glm_reg_lanes,
)
from photon_tpu.tuning import gp as gp_mod
from photon_tpu.tuning.acquisition import qei_greedy


def _logistic_problem(rng, n=384, d=8):
    w_true = rng.normal(size=d)

    def draw(m):
        X = rng.normal(size=(m, d)).astype(np.float32)
        y = (X @ w_true + 0.5 * rng.normal(size=m) > 0).astype(np.float32)
        return make_batch(X, y)

    return draw(n), draw(n // 2)


class TestCompactRowsEdges:
    """The tuner's survivor repack: compact_rows over halving outcomes
    that fall off the happy path (none survive / everyone survives /
    a non-pow2 count padded back up to the lane chunk)."""

    def test_zero_survivors_zero_pad(self):
        x = np.arange(24, dtype=np.float32).reshape(6, 4)
        out = np.asarray(compact_rows(x, np.zeros((0,), np.int32),
                                      pad_rows=4))
        assert out.shape == (4, 4)
        assert (out == 0.0).all()

    def test_zero_survivors_edge_pad_rejected(self):
        # edge mode repeats the LAST gathered row; with nothing gathered
        # there is nothing to repeat — must refuse, not emit garbage
        with pytest.raises(ValueError, match="at least one"):
            compact_rows(np.ones((6, 4), np.float32),
                         np.zeros((0,), np.int32), pad_rows=4,
                         pad_mode="edge")

    def test_all_survivors_identity(self):
        x = np.arange(20, dtype=np.float32).reshape(5, 4)
        out = np.asarray(compact_rows(x, np.arange(5, dtype=np.int32)))
        np.testing.assert_array_equal(out, x)

    def test_non_pow2_survivors_zero_vs_edge_pad(self):
        x = np.arange(32, dtype=np.float32).reshape(8, 4)
        idx = np.asarray([1, 6, 3], np.int32)  # 3 survivors -> chunk 4
        z = np.asarray(compact_rows(x, idx, pad_rows=4))
        e = np.asarray(compact_rows(x, idx, pad_rows=4, pad_mode="edge"))
        np.testing.assert_array_equal(z[:3], x[idx])
        np.testing.assert_array_equal(e[:3], x[idx])
        assert (z[3] == 0.0).all()
        np.testing.assert_array_equal(e[3], x[3])  # last gathered row

    def test_invalid_pad_mode(self):
        with pytest.raises(ValueError, match="pad_mode"):
            compact_rows(np.ones((4, 2), np.float32),
                         np.asarray([0], np.int32), pad_mode="mirror")


class TestQeiGreedyEdges:
    def test_overdraw_returns_whole_pool_without_repeats(self, rng):
        gp = fit_gp(rng.uniform(size=(9, 1)).astype(np.float32),
                    rng.normal(size=9))
        pool = rng.uniform(size=(5, 1)).astype(np.float32)
        picks = qei_greedy(gp, pool, best_y=0.0, q=12, seed=3)
        assert sorted(picks) == [0, 1, 2, 3, 4]

    def test_uniform_costs_match_costless_greedy(self, rng):
        gp = fit_gp(rng.uniform(size=(10, 1)).astype(np.float32),
                    rng.normal(size=10))
        pool = rng.uniform(size=(24, 1)).astype(np.float32)
        plain = qei_greedy(gp, pool, best_y=0.0, q=6, seed=5)
        uniform = qei_greedy(gp, pool, best_y=0.0, q=6, seed=5,
                             costs=np.full(24, 37.5))
        assert plain == uniform

    def test_costs_steer_ties_to_the_cheap_duplicate(self, rng):
        gp = fit_gp(rng.uniform(size=(8, 1)).astype(np.float32),
                    rng.normal(size=8))
        point = rng.uniform(size=(1, 1)).astype(np.float32)
        pool = np.concatenate([point, point])  # identical gains
        costs = np.asarray([50.0, 1.0])
        picks = qei_greedy(gp, pool, best_y=1e3, q=1, seed=0, costs=costs)
        assert picks == [1]


class TestGpObservationLadder:
    def test_growing_history_stays_on_rung_signatures(self, rng):
        # warm the d=2 rung-16 program, then 7 growing counts on the same
        # rung must add ZERO fit signatures (the per-round retrace the
        # ladder exists to kill)
        def fit_at(k):
            Xo = rng.uniform(size=(k, 2)).astype(np.float32)
            fit_gp(Xo, np.sin(3 * Xo[:, 0]) + Xo[:, 1])

        fit_at(16)
        base = len(gp_mod._FIT_SIG_LOG.signatures(gp_mod.FIT_SIG_NAME))
        for k in range(9, 16):
            fit_at(k)
        now = len(gp_mod._FIT_SIG_LOG.signatures(gp_mod.FIT_SIG_NAME))
        assert now == base

    def test_padded_fit_interpolates_real_points_only(self, rng):
        # 5 real observations pad to the rung-8 block; the masked Gram
        # must keep the pad invisible — the posterior still interpolates
        # the real points as if unpadded
        X = rng.uniform(size=(5, 1)).astype(np.float32)
        y = np.sin(4 * X[:, 0])
        gp = fit_gp(X, y)
        assert gp.X.shape[0] == 8 and float(gp.mask.sum()) == 5.0
        mean, _ = gp.predict(X)
        np.testing.assert_allclose(np.asarray(mean), y, atol=0.05)


class TestLaneTuner:
    @pytest.fixture(scope="class")
    def outcome(self):
        rng = np.random.default_rng(16)
        train, val = _logistic_problem(rng)
        cfg = OptimizerConfig(max_iters=24, reg=l2(), history=5)
        base = LaneTuningResult.signature_count()
        model, best_w, res = tune_glm_reg_lanes(
            train, TaskType.LOGISTIC_REGRESSION, cfg, val,
            n_configs=16, lane_chunk=8, seed=0)
        return train, val, cfg, base, model, best_w, res

    def test_recovers_a_strong_config(self, outcome):
        _, _, _, _, model, best_w, res = outcome
        assert len(res.ys) == 16 and len(res.rounds) == 2
        assert 1e-4 <= best_w <= 1e4
        assert res.best_y < -0.75  # negated validation AUC
        hist = res.history()
        assert (np.diff(hist) <= 1e-12).all()  # incumbent only improves
        assert np.asarray(model.coefficients.means).ndim == 1

    def test_round_stats_cost_model(self, outcome):
        *_, res = outcome
        for rs in res.rounds:
            assert rs.modeled_flops > 0 and rs.modeled_bytes > 0
            assert rs.modeled_collective_bytes == 0  # single-device
            assert rs.n_proposed == 8 and rs.n_survivors == 2
            assert rs.flops_per_config > 0

    def test_no_retrace_across_rounds_and_reruns(self, outcome):
        train, val, cfg, base, *_ = outcome
        # the whole multi-round tune dispatched exactly two lane programs
        n_sigs = LaneTuningResult.assert_no_retrace(base + 2)
        # a second tune (different seed, same shapes) adds ZERO
        tune_glm_reg_lanes(train, TaskType.LOGISTIC_REGRESSION, cfg, val,
                           n_configs=16, lane_chunk=8, seed=9)
        LaneTuningResult.assert_no_retrace(n_sigs)

    def test_starved_budget_raises_before_dispatch(self, outcome):
        train, val, cfg, *_ = outcome
        with pytest.raises(RoundBudgetError):
            tune_glm_reg_lanes(train, TaskType.LOGISTIC_REGRESSION, cfg,
                               val, n_configs=8, lane_chunk=8, seed=1,
                               budget=LaneBudget(max_round_flops=10.0))

    def test_rejects_non_pow2_chunk_and_short_budget(self, outcome):
        train, val, cfg, *_ = outcome
        with pytest.raises(ValueError, match="pow2"):
            tune_glm_reg_lanes(train, TaskType.LOGISTIC_REGRESSION, cfg,
                               val, n_configs=12, lane_chunk=6)
        with pytest.raises(ValueError):
            tune_glm_reg_lanes(train, TaskType.LOGISTIC_REGRESSION, cfg,
                               val, n_configs=4, lane_chunk=8)
