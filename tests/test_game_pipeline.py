"""The round-8 random-effect block-loop pipeline (game/random_effect.py):

- pipelined (in-flight ledger) train() must be BIT-identical to the
  sequential loop (depth 0) across dense/sparse/INDEX_MAP/RANDOM
  projection, mesh/no-mesh, variances, and per-entity priors — the
  pipeline is a pure reordering of host readbacks over disjoint entity
  sets;
- difficulty-sorted chunk packing must be a pure permutation: every row
  still lands in exactly one lane, lanes within a block are row-count
  ordered, and scatter-back still addresses the right entity keys;
- the compacted straggler re-solve (budget-capped first pass + dense
  full-depth tail) must reach the same per-entity optima as the uncapped
  solve, including for an adversarial entity whose lane alone needs the
  whole iteration budget.
"""
import dataclasses

import numpy as np
import pytest

from photon_tpu.data.matrix import SparseRows
from photon_tpu.game import (
    GameData,
    RandomEffectCoordinate,
    RandomEffectDataset,
)
from photon_tpu.game.projector import ProjectionConfig, ProjectorType
from photon_tpu.models.variance import VarianceComputationType
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig

# vmapped while_loop solver compiles accumulate fast here; release them at
# module teardown (see tests/conftest.py).
pytestmark = pytest.mark.release_programs

CFG = OptimizerConfig(max_iters=30, reg=reg.l2(), reg_weight=0.5, history=4)


def _mixed_problem(rng, n_entities=13, d=4, sparse=False):
    rows = rng.integers(3, 28, size=n_entities)
    ent = np.repeat(np.arange(n_entities), rows)
    rng.shuffle(ent)
    n = ent.shape[0]
    w_re = rng.normal(size=(n_entities, d)) * 1.5
    if sparse:
        k = 2
        ind = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = rng.normal(size=(n, k)).astype(np.float32)
        Xd = np.zeros((n, d), np.float32)
        np.add.at(Xd, (np.arange(n)[:, None], ind), val)
        X = SparseRows(ind, val, d)
    else:
        Xd = rng.normal(size=(n, d)).astype(np.float32)
        X = Xd
    logit = np.einsum("nd,nd->n", Xd, w_re[ent])
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    return GameData.build(y, {"s": X}, {"e": ent.astype(np.int64)}), n


def _train(ds, n, *, depth, budget=None, mesh=None,
           variance=VarianceComputationType.NONE, prior=None, cfg=CFG):
    coord = RandomEffectCoordinate(
        ds, TaskType.LOGISTIC_REGRESSION, cfg, mesh=mesh, variance=variance,
        pipeline_depth=depth, straggler_budget=budget)
    return coord.train(np.zeros(n, np.float32), prior=prior)


@pytest.mark.parametrize("variant", ["dense", "sparse", "index_map",
                                     "random_proj", "variance"])
def test_pipelined_matches_sequential(rng, variant):
    """depth-2 pipeline == depth-0 sequential loop: bit-identical
    coefficients/variances and identical RETrainStats totals."""
    sparse = variant == "sparse"
    projection = None
    variance = VarianceComputationType.NONE
    if variant == "index_map":
        projection = ProjectionConfig(ProjectorType.INDEX_MAP)
    elif variant == "random_proj":
        projection = ProjectionConfig(ProjectorType.RANDOM, projected_dim=3)
    elif variant == "variance":
        variance = VarianceComputationType.SIMPLE
    data, n = _mixed_problem(rng, sparse=sparse)
    # max_blocks=2 keeps the multi-bucket pipeline real while halving the
    # per-variant vmapped-solver compile count (tier-1 wall budget).
    ds = RandomEffectDataset.build(data, "e", "s", projection=projection,
                                   max_blocks=2)
    m_seq, s_seq = _train(ds, n, depth=0, variance=variance)
    m_pipe, s_pipe = _train(ds, n, depth=2, variance=variance)
    np.testing.assert_array_equal(np.asarray(m_seq.coefficients),
                                  np.asarray(m_pipe.coefficients))
    if variance is not VarianceComputationType.NONE:
        np.testing.assert_array_equal(np.asarray(m_seq.variances),
                                      np.asarray(m_pipe.variances))
    assert (s_seq.n_entities, s_seq.n_converged, s_seq.n_failed,
            s_seq.total_iterations) == \
           (s_pipe.n_entities, s_pipe.n_converged, s_pipe.n_failed,
            s_pipe.total_iterations)
    np.testing.assert_array_equal(s_seq.iterations_per_entity,
                                  s_pipe.iterations_per_entity)


def test_pipelined_matches_sequential_mesh(rng, mesh8):
    data, n = _mixed_problem(rng)
    ds = RandomEffectDataset.build(data, "e", "s", max_blocks=2)
    m_seq, s_seq = _train(ds, n, depth=0, mesh=mesh8)
    m_pipe, s_pipe = _train(ds, n, depth=1, mesh=mesh8)
    np.testing.assert_array_equal(np.asarray(m_seq.coefficients),
                                  np.asarray(m_pipe.coefficients))
    assert s_seq.total_iterations == s_pipe.total_iterations


def test_pipelined_matches_sequential_with_prior(rng):
    """Incremental-training shape: per-entity Gaussian priors ride the
    pipeline unchanged."""
    data, n = _mixed_problem(rng)
    ds = RandomEffectDataset.build(data, "e", "s", max_blocks=2)
    prior_model, _ = _train(ds, n, depth=0,
                            variance=VarianceComputationType.SIMPLE)
    m_seq, s_seq = _train(ds, n, depth=0, prior=prior_model)
    m_pipe, s_pipe = _train(ds, n, depth=2, prior=prior_model)
    np.testing.assert_array_equal(np.asarray(m_seq.coefficients),
                                  np.asarray(m_pipe.coefficients))
    assert s_seq.total_iterations == s_pipe.total_iterations


def test_sorted_packing_permutation_roundtrip(rng):
    """Difficulty-sorted packing is a pure permutation: per-block lanes are
    active-row-count ordered, every real row lands in exactly one lane of
    its own entity, and every entity appears exactly once."""
    n_entities = 23
    rows = rng.integers(1, 50, size=n_entities)
    ent = np.repeat(np.arange(n_entities), rows)
    rng.shuffle(ent)
    n = ent.shape[0]
    X = rng.normal(size=(n, 2)).astype(np.float32)
    data = GameData.build(np.zeros(n), {"s": X}, {"e": ent})
    ds = RandomEffectDataset.build(data, "e", "s")
    seen = np.zeros(n, np.int32)
    total_entities = 0
    for b in ds.blocks:
        w = np.asarray(b.weights)
        ri = np.asarray(b.row_index)
        active = (w > 0).sum(axis=1)
        assert (np.diff(active) >= 0).all(), "lanes not row-count sorted"
        total_entities += b.n_entities
        for i in range(b.n_entities):
            real = w[i] > 0
            assert (ent[ri[i][real]] == b.entity_index[i]).all()
            seen[ri[i][real]] += 1
    assert total_entities == n_entities
    np.testing.assert_array_equal(seen, 1)


def test_sorted_packing_scatter_back_recovers(rng):
    """Planted per-entity coefficients come back under the sorted packing —
    the scatter respects the permutation threaded through entity_index."""
    n_entities, d = 11, 3
    w_true = rng.normal(size=(n_entities, d)).astype(np.float32)
    rows = rng.integers(30, 60, size=n_entities)  # diverse -> real sorting
    ent = np.repeat(np.arange(n_entities), rows)
    n = ent.shape[0]
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = np.einsum("nd,nd->n", X, w_true[ent]) + 0.01 * rng.normal(size=n)
    data = GameData.build(y, {"s": X}, {"e": ent})
    ds = RandomEffectDataset.build(data, "e", "s", max_blocks=1)
    cfg = OptimizerConfig(max_iters=50, reg=reg.l2(), reg_weight=1e-4)
    coord = RandomEffectCoordinate(ds, TaskType.LINEAR_REGRESSION, cfg)
    model, stats = coord.train(np.zeros(n, np.float32))
    got = np.asarray(model.coefficients)[
        np.asarray([model.key_to_index[k] for k in range(n_entities)])]
    np.testing.assert_allclose(got, w_true, atol=0.05)
    assert stats.n_converged == n_entities


class TestStragglerResolve:
    def _adversarial_problem(self, rng, n_entities=9, d=3):
        """Entity 0's lane alone needs (nearly) the whole iteration budget:
        anisotropically scaled features + separable labels converge slowly
        under weak L2; the other entities finish in a handful of steps."""
        rows = np.full(n_entities, 24)
        ent = np.repeat(np.arange(n_entities), rows)
        n = ent.shape[0]
        X = rng.normal(size=(n, d)).astype(np.float32)
        bad = ent == 0
        X[bad] *= np.geomspace(1e-1, 1e1, d).astype(np.float32)[None, :]
        w_re = rng.normal(size=(n_entities, d)) * 1.0
        logit = np.einsum("nd,nd->n", X, w_re[ent])
        y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
        y[bad] = (logit[bad] > 0).astype(np.float32)
        data = GameData.build(y, {"s": X}, {"e": ent})
        return RandomEffectDataset.build(data, "e", "s"), n

    def test_straggler_resolve_parity(self, rng):
        ds, n = self._adversarial_problem(rng)
        cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=reg.l2(),
                              reg_weight=1e-2, history=5)
        m_full, s_full = _train(ds, n, depth=1, cfg=cfg)
        m_comp, s_comp = _train(ds, n, depth=1, budget=4, cfg=cfg)
        # same per-entity optima (convex problems solved to tolerance) —
        # the tail restart changes the path, not the destination
        np.testing.assert_allclose(np.asarray(m_comp.coefficients),
                                   np.asarray(m_full.coefficients),
                                   atol=2e-3)
        assert s_comp.n_converged >= s_full.n_converged
        # the adversarial entity really went through the tail pass and
        # dominates the per-entity iteration counts — the lane the
        # sequential loop would have run the WHOLE chunk for
        adv = ds.key_to_index[0]
        ipe = s_comp.iterations_per_entity
        assert ipe[adv] > 4
        assert ipe[adv] == ipe.max()
        assert ipe[adv] > 1.5 * np.median(ipe)
        # and the cap alone (no tail) would NOT have converged everyone:
        # the compaction did real work
        capped_only = dataclasses.replace(cfg, max_iters=4)
        _, s_capped = _train(ds, n, depth=1, cfg=capped_only)
        assert s_capped.n_converged < s_full.n_entities
        assert s_comp.n_converged == s_full.n_entities

    def test_budget_noop_when_at_or_above_max_iters(self, rng):
        """budget >= max_iters (or <= 0) degrades to the plain path.
        (Same problem/config family as the parity test: the solver
        programs are already compiled.)"""
        ds, n = self._adversarial_problem(rng)
        cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=reg.l2(),
                              reg_weight=1e-2, history=5)
        m_a, s_a = _train(ds, n, depth=1, budget=None, cfg=cfg)
        m_b, s_b = _train(ds, n, depth=1, budget=80, cfg=cfg)
        m_c, s_c = _train(ds, n, depth=1, budget=0, cfg=cfg)
        np.testing.assert_array_equal(np.asarray(m_a.coefficients),
                                      np.asarray(m_b.coefficients))
        np.testing.assert_array_equal(np.asarray(m_a.coefficients),
                                      np.asarray(m_c.coefficients))
        assert s_a.total_iterations == s_b.total_iterations \
            == s_c.total_iterations

    def test_straggler_budget_disables_fused_program(self, rng):
        """The compacted re-solve needs a host repack between passes, so a
        budgeted coordinate must take the pipelined train() path. (Builds
        the fused callable only — jit is lazy, nothing compiles.)"""
        ds, n = self._adversarial_problem(rng)
        cfg = OptimizerConfig(max_iters=80, tolerance=1e-6, reg=reg.l2(),
                              reg_weight=1e-2, history=5)
        plain = RandomEffectCoordinate(ds, TaskType.LOGISTIC_REGRESSION, cfg)
        budgeted = RandomEffectCoordinate(ds, TaskType.LOGISTIC_REGRESSION,
                                          cfg, straggler_budget=4)
        assert plain.fused_update_program() is not None
        assert budgeted.fused_update_program() is None
