"""The whole-program concurrency auditor (round 18).

Three layers, mirroring the auditor itself:

1. Violating fixtures for every rule — deadlock cycle, unguarded
   two-role write, inconsistent guards, blocking-under-lock (direct,
   untimed queue, transitive), broken pinned expectations — each proven
   to FIRE, plus the waiver forms (``photon: unguarded``,
   ``photon: allow``) proven to suppress with a reason and to be
   flagged when reasonless or stale.
2. The clean-repo law: ``run_lint`` over this repo at HEAD with an
   empty baseline returns ZERO findings, and the ``--threads`` CLI
   round-trips the model as JSON/dot.
3. Deterministic interleaving tests wiring the static findings to
   dynamic evidence: the pre-fix ``AsyncSnapshotWriter._err``
   read-then-clear protocol demonstrably LOSES an error under a forced
   preemption schedule; the shipped (locked) writer survives the same
   schedule, plus seeded yielding-lock fuzz and regression tests for
   the other races fixed in this round (telemetry emit-lock split,
   FaultPlan hit counters).

Everything here is jax-free and fast — the tier-1 budget is tight.
"""
from __future__ import annotations

import json
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from photon_tpu.lint import load_context, repo_root, run_lint
from photon_tpu.lint.rules import RULES
from photon_tpu.lint.thread_model import build_thread_model

from test_lint import write_repo  # the registry-complete clean fixture

REPO = repo_root()


def run_rules(root, only=None):
    return run_lint(root=str(root), only=only, baseline=set())


def findings_of(report, rule):
    return [f for f in report["findings"] if f.rule == rule]


def write(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    return str(tmp_path)


CONC = ["lock_order", "blocking_under_lock", "guarded_by",
        "concurrency_model"]


# ------------------------------------------------------------ lock_order

DEADLOCK = """\
    import threading

    _l1 = threading.Lock()
    _l2 = threading.Lock()

    def forward():
        with _l1:
            take_second()

    def take_second():
        with _l2:
            return 1

    def backward():
        with _l2:
            with _l1:
                return 2
"""


class TestLockOrder:
    def test_cross_call_cycle_fires(self, tmp_path):
        root = write(tmp_path, {"photon_tpu/dead.py": DEADLOCK})
        report = run_rules(root, only=["lock_order"])
        f, = findings_of(report, "lock_order")
        assert "deadlock" in f.message
        assert "_l1" in f.key and "_l2" in f.key

    def test_consistent_nesting_is_clean(self, tmp_path):
        clean = DEADLOCK.replace("with _l2:\n            with _l1:",
                                 "with _l1:\n            with _l2:")
        assert clean != DEADLOCK
        root = write(tmp_path, {"photon_tpu/dead.py": clean})
        report = run_rules(root, only=["lock_order"])
        assert findings_of(report, "lock_order") == []


# ------------------------------------------------------------ guarded_by

UNGUARDED = """\
    import threading

    class Worker:
        def __init__(self):
            self.state = 0
            self._t = threading.Thread(target=self._loop,
                                       name="fixture-loop")
            self._t.start()

        def _loop(self):
            self.state = 1

        def poke(self):
            self.state = 2
"""

INCONSISTENT = """\
    import threading

    class Incons:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
            self.val = 0
            self._t = threading.Thread(target=self._loop,
                                       name="incons-loop")

        def _loop(self):
            with self._a:
                self.val = 1

        def set_val(self):
            with self._b:
                self.val = 2
"""


class TestGuardedBy:
    def test_unguarded_two_role_write_fires(self, tmp_path):
        root = write(tmp_path, {"photon_tpu/w.py": UNGUARDED})
        report = run_rules(root, only=["guarded_by"])
        found = findings_of(report, "guarded_by")
        assert len(found) == 2  # one per write site
        msg = found[0].message
        assert "fixture-loop" in msg and "NO lock" in msg

    def test_inconsistent_guards_fire(self, tmp_path):
        root = write(tmp_path, {"photon_tpu/i.py": INCONSISTENT})
        report = run_rules(root, only=["guarded_by"])
        found = findings_of(report, "guarded_by")
        assert found and all("DIFFERENT locks" in f.message
                             for f in found)

    def test_common_lock_is_clean(self, tmp_path):
        fixed = INCONSISTENT.replace("with self._b:", "with self._a:")
        root = write(tmp_path, {"photon_tpu/i.py": fixed})
        report = run_rules(root, only=["guarded_by"])
        assert findings_of(report, "guarded_by") == []

    def test_lock_inherited_through_call_is_clean(self, tmp_path):
        # the write site itself is lockless, but EVERY call path in
        # holds the lock — the meet-over-paths analysis must see it
        src = UNGUARDED.replace(
            "    def poke(self):\n        self.state = 2",
            "    def poke(self):\n"
            "        with self._g:\n"
            "            self._store()\n\n"
            "    def _loop(self2):\n"
            "        pass\n\n"
            "    def _store(self):\n"
            "        self.state = 2",
        ).replace("self.state = 0",
                  "self.state = 0\n        self._g = threading.Lock()")
        root = write(tmp_path, {"photon_tpu/w.py": src})
        report = run_rules(root, only=["guarded_by"])
        # _loop writes unlocked -> still fires there, but the _store
        # site inherits the lock and must NOT fire
        assert all("_store" not in f.key
                   for f in findings_of(report, "guarded_by"))

    def test_process_entries_are_not_shared_memory_roles(self, tmp_path):
        # spawn-context Process targets live in another address space:
        # a global written by the child entry and a public function must
        # NOT count as a two-role shared write
        src = """\
            import multiprocessing

            _COUNT = 0

            def _child_main():
                global _COUNT
                _COUNT = 1

            def bump():
                global _COUNT
                _COUNT = 2

            def launch():
                mp = multiprocessing.get_context("spawn")
                p = mp.Process(target=_child_main)
                p.start()
        """
        root = write(tmp_path, {"photon_tpu/p.py": src})
        report = run_rules(root, only=["guarded_by"])
        assert findings_of(report, "guarded_by") == []


# ---------------------------------------------------- blocking_under_lock

class TestBlockingUnderLock:
    def _root(self, tmp_path, body):
        src = ("import queue\nimport threading\nimport time\n\n"
               "_lk = threading.Lock()\n\n" + textwrap.dedent(body))
        return write(tmp_path, {"photon_tpu/b.py": src})

    def test_direct_sleep_under_lock_fires(self, tmp_path):
        root = self._root(tmp_path, """\
            def hold():
                with _lk:
                    time.sleep(0.5)
        """)
        report = run_rules(root, only=["blocking_under_lock"])
        f, = findings_of(report, "blocking_under_lock")
        assert "time.sleep" in f.message and "_lk" in f.message

    def test_untimed_queue_get_under_lock_fires(self, tmp_path):
        root = self._root(tmp_path, """\
            def hold():
                q = queue.Queue()
                with _lk:
                    return q.get()
        """)
        report = run_rules(root, only=["blocking_under_lock"])
        f, = findings_of(report, "blocking_under_lock")
        assert "queue.Queue.get" in f.message

    def test_timed_queue_get_is_exempt(self, tmp_path):
        root = self._root(tmp_path, """\
            def hold():
                q = queue.Queue()
                with _lk:
                    return q.get(timeout=1.0)
        """)
        report = run_rules(root, only=["blocking_under_lock"])
        assert findings_of(report, "blocking_under_lock") == []

    def test_transitive_file_io_under_lock_fires(self, tmp_path):
        root = self._root(tmp_path, """\
            def _flush(path):
                with open(path, "w") as f:
                    f.write("x")

            def hold(path):
                with _lk:
                    _flush(path)
        """)
        report = run_rules(root, only=["blocking_under_lock"])
        f, = findings_of(report, "blocking_under_lock")
        assert "transitively" in f.message and "_flush" in f.message

    def test_io_outside_lock_is_clean(self, tmp_path):
        root = self._root(tmp_path, """\
            def hold(path):
                with _lk:
                    x = 1
                with open(path, "w") as f:
                    f.write(str(x))
        """)
        report = run_rules(root, only=["blocking_under_lock"])
        assert findings_of(report, "blocking_under_lock") == []


# ------------------------------------------------------ concurrency_model

class TestConcurrencyModel:
    def test_missing_pinned_thread_fires(self, tmp_path):
        # a serving/dispatcher.py EXISTS but its pinned threads are gone
        root = write(tmp_path, {"photon_tpu/serving/dispatcher.py":
                                "class MicroBatchDispatcher:\n"
                                "    pass\n"})
        report = run_rules(root, only=["concurrency_model"])
        keys = {f.key for f in findings_of(report, "concurrency_model")}
        assert "thread:serving-dispatch" in keys
        assert "thread:serving-retire" in keys

    def test_absent_file_skips_expectation(self, tmp_path):
        # fixture repos without the production modules stay clean
        root = write(tmp_path, {"photon_tpu/other.py": "X = 1\n"})
        report = run_rules(root, only=["concurrency_model"])
        assert findings_of(report, "concurrency_model") == []

    def test_broken_guard_binding_fires(self, tmp_path):
        src = """\
            import threading

            class CoefficientStore:
                def __init__(self):
                    self._swap_lock = threading.Lock()
                    self._device = None

                def reload(self):
                    self._device = None
        """
        root = write(tmp_path, {"photon_tpu/serving/store.py": src})
        report = run_rules(root, only=["concurrency_model"])
        f, = [f for f in findings_of(report, "concurrency_model")
              if "CoefficientStore._device" in f.key]
        assert "_swap_lock" in f.message

    def test_guard_binding_holds_when_locked(self, tmp_path):
        src = """\
            import threading

            class CoefficientStore:
                def __init__(self):
                    self._swap_lock = threading.Lock()
                    self._device = None

                def reload(self):
                    with self._swap_lock:
                        self._device = None
        """
        root = write(tmp_path, {"photon_tpu/serving/store.py": src})
        report = run_rules(root, only=["concurrency_model"])
        assert not [f for f in findings_of(report, "concurrency_model")
                    if "CoefficientStore._device" in f.key]


# ------------------------------------------------------------- waivers

class TestWaivers:
    def test_photon_unguarded_tag_waiver_honored(self, tmp_path):
        src = UNGUARDED.replace(
            "            self.state = 1",
            "            # photon: unguarded(fixture says so)\n"
            "            self.state = 1",
        ).replace(
            "        self.state = 2",
            "        self.state = 2  # photon: unguarded(fixture says so)",
        )
        root = write(tmp_path, {"photon_tpu/w.py": src})
        report = run_rules(root, only=["guarded_by"])
        assert findings_of(report, "guarded_by") == []
        assert len(report["suppressed"]) == 2

    def test_photon_allow_rule_waiver_honored(self, tmp_path):
        src = UNGUARDED.replace(
            "            self.state = 1",
            "            # photon: allow(guarded_by, fixture says so)\n"
            "            self.state = 1",
        ).replace(
            "        self.state = 2",
            "        self.state = 2  # photon: allow(guarded_by, ok here)",
        )
        root = write(tmp_path, {"photon_tpu/w.py": src})
        report = run_rules(root, only=["guarded_by"])
        assert findings_of(report, "guarded_by") == []
        assert len(report["suppressed"]) == 2

    def test_allow_for_wrong_rule_does_not_suppress(self, tmp_path):
        src = UNGUARDED.replace(
            "        self.state = 2",
            "        self.state = 2  # photon: allow(lock_order, wrong)",
        )
        root = write(tmp_path, {"photon_tpu/w.py": src})
        report = run_rules(root, only=["guarded_by"])
        assert len(findings_of(report, "guarded_by")) == 2

    def test_reasonless_allow_rejected(self, tmp_path):
        src = UNGUARDED.replace(
            "        self.state = 2",
            "        self.state = 2  # photon: allow(guarded_by)",
        )
        root = write(tmp_path, {"photon_tpu/w.py": src})
        report = run_rules(root, only=["guarded_by", "suppression"])
        # the finding is NOT suppressed and the bad waiver is flagged
        assert len(findings_of(report, "guarded_by")) == 2
        sup, = findings_of(report, "suppression")
        assert "no reason" in sup.message

    def test_stale_waiver_flagged_on_full_run(self, tmp_path):
        root = write_repo(tmp_path, extra={
            "photon_tpu/stale.py":
                "X = 1\n"
                "# photon: allow(guarded_by, nothing fires here anymore)\n"
                "Y = 2\n"})
        report = run_rules(root)  # FULL run: stale check active
        stale = [f for f in findings_of(report, "suppression")
                 if f.key.startswith("stale:")]
        assert len(stale) == 1 and stale[0].path == "photon_tpu/stale.py"
        assert "guarded_by" in stale[0].message

    def test_stale_check_skipped_under_only_filter(self, tmp_path):
        root = write_repo(tmp_path, extra={
            "photon_tpu/stale.py":
                "X = 1\n"
                "# photon: allow(guarded_by, nothing fires here anymore)\n"
                "Y = 2\n"})
        report = run_rules(root, only=["guarded_by", "suppression"])
        assert not [f for f in findings_of(report, "suppression")
                    if f.key.startswith("stale:")]

    def test_legacy_lint_waivers_are_not_stale_checked(self, tmp_path):
        root = write_repo(tmp_path, extra={
            "photon_tpu/old.py":
                "X = 1\n"
                "# lint" ": rawwrite(legacy form, not stale-checked)\n"
                "Y = 2\n"})
        report = run_rules(root)
        assert not [f for f in findings_of(report, "suppression")
                    if f.key.startswith("stale:")]


# ------------------------------------------------------ the thread model

class TestThreadModel:
    def test_inventory_and_reach(self, tmp_path):
        root = write(tmp_path, {"photon_tpu/w.py": UNGUARDED})
        model = build_thread_model(load_context(root))
        entry, = [e for e in model.entries if e.kind == "thread"]
        assert entry.label == "fixture-loop" and entry.shares_memory
        assert entry.targets == ("photon_tpu/w.py::Worker._loop",)
        doc = model.to_doc()
        assert doc["threads"][0]["label"] == "fixture-loop"
        assert "Worker.state" in model.render()

    def test_model_is_memoized_on_context(self, tmp_path):
        ctx = load_context(write(tmp_path, {"photon_tpu/w.py": UNGUARDED}))
        assert build_thread_model(ctx) is build_thread_model(ctx)


# ------------------------------------------------- the clean-repo law

@pytest.fixture(scope="module")
def repo_report():
    return run_lint(root=REPO, baseline=set())


class TestRepoIsClean:
    def test_zero_findings_with_empty_baseline(self, repo_report):
        assert [f.text for f in repo_report["findings"]] == []
        assert repo_report["ok"]
        assert repo_report["n_rules"] == len(RULES) + 1

    def test_concurrency_rules_registered(self):
        for name in CONC:
            assert name in RULES

    def test_repo_thread_inventory_pinned(self):
        from photon_tpu.lint.concurrency import EXPECTED_THREADS

        model = build_thread_model(load_context(REPO))
        have = {(e.rel, e.label) for e in model.entries}
        for rel, label in EXPECTED_THREADS:
            assert (rel, label) in have, (rel, label)
        assert not model.cycles

    def test_threads_cli_json_subprocess(self, tmp_path):
        root = write(tmp_path, {"photon_tpu/w.py": UNGUARDED,
                                "photon_tpu/dead.py": DEADLOCK})
        proc = subprocess.run(
            [sys.executable, "-m", "photon_tpu.lint", "--root", root,
             "--threads", "--json"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert proc.returncode == 1
        doc = json.loads(proc.stdout)
        assert not doc["ok"] and doc["n_findings"] >= 3
        labels = {t["label"] for t in doc["model"]["threads"]}
        assert "fixture-loop" in labels
        assert doc["model"]["lock_cycles"]

    def test_threads_cli_dot_subprocess(self, tmp_path):
        root = write(tmp_path, {"photon_tpu/dead.py": DEADLOCK})
        proc = subprocess.run(
            [sys.executable, "-m", "photon_tpu.lint", "--root", root,
             "--threads", "--dot"],
            capture_output=True, text=True, timeout=120, cwd=REPO)
        assert proc.returncode == 1
        assert proc.stdout.startswith("digraph lock_order")
        assert "->" in proc.stdout


# ------------------------------------- interleaving: static -> dynamic

class _FailingStore:
    """Commit always raises a numbered error — the dying-disk stand-in."""

    def __init__(self):
        self.n = 0

    def commit(self, state, seq, meta=None):
        self.n += 1
        raise RuntimeError(f"boom{self.n}")


class _YieldingLock:
    """A real lock whose acquire() first yields the GIL a seeded number
    of times — widening any unlocked window at the auditor-identified
    acquisition sites without changing semantics."""

    def __init__(self, seed: int):
        self._lock = threading.Lock()
        self._state = seed or 1  # xorshift; no randomness APIs needed

    def _yields(self) -> int:
        s = self._state
        s ^= (s << 13) & 0xFFFFFFFF
        s ^= s >> 17
        s ^= (s << 5) & 0xFFFFFFFF
        self._state = s
        return s % 4

    def acquire(self, *a, **k):
        for _ in range(self._yields()):
            time.sleep(0)
        return self._lock.acquire(*a, **k)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()


def _shutdown(writer) -> None:
    writer._q.put(None)
    writer._thread.join(timeout=10)
    assert not writer._thread.is_alive()


class TestErrLatchInterleaving:
    """The race guarded_by flagged at HEAD: AsyncSnapshotWriter._err was
    read-then-cleared by callers with no lock, so a writer-thread store
    landing between the read and the clear was erased UNRAISED."""

    def test_prefix_protocol_drops_the_last_error(self):
        # the pre-fix `err, self._err = self._err, None` as its two
        # bytecode steps (LOAD_ATTR ... STORE_ATTR), with the writer's
        # store forced into the window between them
        box = {"err": RuntimeError("boom1")}
        in_window = threading.Event()
        stored = threading.Event()
        raised = []

        def check():
            err = box["err"]          # the read
            in_window.set()
            assert stored.wait(5)     # the preemption the lint flagged
            box["err"] = None         # the clear — erases boom2
            if err is not None:
                raised.append(str(err))

        def writer():
            assert in_window.wait(5)
            box["err"] = RuntimeError("boom2")
            stored.set()

        tc = threading.Thread(target=check)
        tw = threading.Thread(target=writer)
        tc.start(); tw.start(); tc.join(5); tw.join(5)
        # boom2 was stored by the writer, never raised, and is now gone:
        assert raised == ["boom1"] and box["err"] is None

    def test_fixed_writer_survives_the_same_schedule(self):
        from photon_tpu.checkpoint.store import AsyncSnapshotWriter

        store = _FailingStore()
        w = AsyncSnapshotWriter(store)
        try:
            raised = []
            w.submit({"x": 1}, seq=1)   # commit -> boom1 stored
            w._q.join()
            w._err_lock = _YieldingLock(7)  # the preemption harness
            # same shape as the red test: a check racing a second store
            def check():
                try:
                    w._check()
                except RuntimeError as e:
                    raised.append(str(e))
            tc = threading.Thread(target=check)
            tc.start()
            try:
                w.submit({"x": 2}, seq=2)  # may itself raise boom1
            except RuntimeError as e:
                raised.append(str(e))
            tc.join(5)
            w._q.join()
            try:
                w._check()
            except RuntimeError as e:
                raised.append(str(e))
            # the LAST error always surfaces — nothing is silently lost
            assert f"boom{store.n}" in raised
        finally:
            _shutdown(w)

    @pytest.mark.parametrize("seed", [3, 11, 29])
    def test_fixed_writer_never_silences_the_final_error(self, seed):
        from photon_tpu.checkpoint.store import AsyncSnapshotWriter

        store = _FailingStore()
        w = AsyncSnapshotWriter(store)
        w._err_lock = _YieldingLock(seed)
        try:
            raised = []
            for i in range(25):
                try:
                    w.submit({"x": i}, seq=i)
                except RuntimeError as e:
                    raised.append(str(e))
                if i % 3 == 0:
                    time.sleep(0)
            w._q.join()
            try:
                w._check()
            except RuntimeError as e:
                raised.append(str(e))
            # after quiescence + a final check the latest injected error
            # must have been raised (the pre-fix tear violates this)
            if store.n:
                assert f"boom{store.n}" in raised
        finally:
            _shutdown(w)


class TestRound18RaceFixRegressions:
    """One regression test per concurrency fix this round."""

    def test_counter_bump_never_waits_on_the_jsonl_sink(self, tmp_path):
        # Run._emit got its own lock: a counter bump must complete even
        # while the JSONL sink lock is held (pre-fix: same lock)
        from photon_tpu.telemetry.run import Run

        r = Run(name="t", jsonl_path=str(tmp_path / "t.jsonl"))
        done = threading.Event()
        with r._emit_lock:
            t = threading.Thread(
                target=lambda: (r.count("k"), done.set()))
            t.start()
            assert done.wait(5), "count() blocked behind the emit lock"
        t.join(5)
        r.close()

    def test_emit_completes_while_stats_lock_held(self, tmp_path):
        from photon_tpu.telemetry.run import Run

        r = Run(name="t", jsonl_path=str(tmp_path / "t.jsonl"))
        done = threading.Event()
        with r._lock:
            t = threading.Thread(
                target=lambda: (r._emit({"type": "x"}), done.set()))
            t.start()
            assert done.wait(5), "_emit blocked behind the stats lock"
        t.join(5)
        r.close()

    def test_faultplan_hits_exact_under_contention(self):
        from photon_tpu.checkpoint.faults import FaultPlan

        plan = FaultPlan()
        n_threads, per = 8, 400
        threads = [threading.Thread(
            target=lambda: [plan.hit("site") for _ in range(per)])
            for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10)
        assert plan.hits["site"] == n_threads * per
