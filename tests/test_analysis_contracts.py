"""Tier-1 contract enforcement: every hot path's registered ContractSpec
must trace clean — THE test that turns the repo's implicit performance
model (one psum per evaluation, communication-free chunk partials,
scatter-free permuted layouts, f32 accumulation, no host exits, no retrace
hazards) into law that fails CI on drift.

Trace-only (jax.make_jaxpr): no compiles, so this module is cheap despite
walking every solver program in the repo. The CLI face of the same
registry is exercised end to end as a subprocess.
"""
import json
import os
import subprocess
import sys

import pytest

from photon_tpu.analysis import check_contract, trace_contract
from photon_tpu.analysis.registry import load_registry

pytestmark = pytest.mark.release_programs

_REGISTRY = load_registry()


def test_registry_is_broad_enough():
    """≥ 25 specs (round 11 added the ledger-off pin) spanning every
    workload family, now including the profiling attribution ledger."""
    assert len(_REGISTRY) >= 25
    tags = {t for spec in _REGISTRY.values() for t in spec.tags}
    for family in ("resident", "streamed", "mesh-streamed", "lane", "game",
                   "serving", "checkpoint", "profiling"):
        assert family in tags, f"no contract covers the {family} family"


def test_checkpoint_off_specs_are_registered():
    """Disarmed checkpointing must add ZERO transfer/callback primitives
    to jitted solver programs: both checkpoint-off specs are strict
    (no transfers, no f64, empty collective budget) and forbid the
    transfer family outright — the acceptance pin of the elastic-runs
    round, mirroring telemetry_off_is_free."""
    from photon_tpu.analysis.walker import TRANSFER_PRIMITIVES

    for name in ("checkpoint_off_is_free", "checkpoint_off_tron_free"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}
        assert not spec.allow_transfers and not spec.allow_f64
        assert TRANSFER_PRIMITIVES <= spec.forbid


def test_ledger_off_spec_is_registered():
    """Disarmed profiling must add ZERO transfer/callback primitives to
    jitted solver programs — the attribution-ledger round's acceptance
    pin, same strictness as the telemetry/checkpoint off-specs."""
    from photon_tpu.analysis.walker import TRANSFER_PRIMITIVES

    spec = _REGISTRY["ledger_off_is_free"]
    assert dict(spec.collectives or {}) == {}
    assert not spec.allow_transfers and not spec.allow_f64
    assert TRANSFER_PRIMITIVES <= spec.forbid
    assert "profiling" in spec.tags


def test_checkpoint_selftest_cli_end_to_end():
    """`python -m photon_tpu.checkpoint --selftest --json` — the
    snapshot → kill → restore → bit-parity smoke — exits 0 with every
    check green (exit 1 on drift is the CI contract)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the CLI must self-provision its platform
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_tpu.checkpoint", "--selftest",
         "--json"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] is True
    assert report["checks"]["resume_bit_identical"]["ok"] is True
    assert report["checks"]["mid_write_resume_bit_identical"]["ok"] is True


def test_serving_request_specs_are_registered():
    """The serving tier's per-request program is pinned: both heads
    (mean + margin), both strict — zero collectives, zero host exits."""
    for name in ("serving_request_program", "serving_request_margin"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}
        assert not spec.allow_transfers and not spec.allow_f64


@pytest.mark.parametrize("name", sorted(_REGISTRY))
def test_contract_holds(name):
    spec = _REGISTRY[name]
    violations = check_contract(spec)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_declared_collective_budgets_are_exact():
    """The budgets are EXACT pins, not ceilings: a spec declaring
    {"psum": 1} must actually trace one psum (drift DOWN — a collective
    disappearing — is also a contract change someone must look at)."""
    from photon_tpu.analysis import collective_counts

    checked = 0
    for spec in _REGISTRY.values():
        if spec.collectives:
            traced = trace_contract(spec)
            assert dict(collective_counts(traced.closed_jaxpr)) == \
                dict(spec.collectives), spec.name
            checked += 1
    assert checked >= 4  # the mesh/streamed psum pins exist


def test_cli_json_end_to_end():
    """`python -m photon_tpu.analysis --json` — the CI entry point —
    exits 0 with zero violations over the full registry."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the CLI must self-provision its platform
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_tpu.analysis", "--json"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] is True
    assert report["n_specs"] >= 8
    assert report["n_violations"] == 0
