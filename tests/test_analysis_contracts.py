"""Tier-1 contract enforcement: every hot path's registered ContractSpec
must trace clean — THE test that turns the repo's implicit performance
model (one psum per evaluation, communication-free chunk partials,
scatter-free permuted layouts, f32 accumulation, no host exits, no retrace
hazards) into law that fails CI on drift.

Trace-only (jax.make_jaxpr): no compiles, so this module is cheap despite
walking every solver program in the repo. The CLI face of the same
registry is exercised end to end as a subprocess.
"""
import json
import os
import subprocess
import sys

import pytest

from photon_tpu.analysis import check_contract, trace_contract
from photon_tpu.analysis.registry import load_registry

pytestmark = pytest.mark.release_programs

_REGISTRY = load_registry()


def test_registry_is_broad_enough():
    """≥ 48 specs (round 19 added the request-tracing off-state pin:
    `serving_trace_off_is_free` — zero extra primitives + zero rung
    signature drift armed vs disarmed) spanning every workload family."""
    assert len(_REGISTRY) >= 51
    tags = {t for spec in _REGISTRY.values() for t in spec.tags}
    for family in ("resident", "streamed", "mesh-streamed", "lane", "game",
                   "serving", "checkpoint", "profiling", "sparse",
                   "evaluation", "continual", "ingest", "kernels",
                   "tuning", "multihost"):
        assert family in tags, f"no contract covers the {family} family"


def test_lane_tuner_specs_are_registered():
    """The round-16 acceptance pins, strict: the tuning lane dispatch
    (pow2 proposal padding never changes the screen program's trace
    signature) and the round budget (modeled cost enforced BEFORE
    dispatch; the halving tail's compact_rows + re-solve traces clean)
    both budget ZERO collectives with no transfer/f64 escape hatch."""
    for name in ("tuning_lane_dispatch", "tuning_round_budget"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}, name
        assert not spec.allow_transfers and not spec.allow_f64, name
        assert "tuning" in spec.tags and "lane" in spec.tags, name
        violations = check_contract(spec)
        assert violations == [], "\n".join(str(v) for v in violations)


def test_serving_trace_off_is_free_spec_is_registered():
    """The round-19 acceptance pin, strict: the serving rung program
    traced with request tracing DISARMED budgets zero collectives and
    forbids transfers (tracing is host bookkeeping around host queues —
    it cannot enter the program), and the builder itself raises if the
    collated rung arguments drift signature between armed and disarmed
    (the zero-retrace half)."""
    spec = _REGISTRY["serving_trace_off_is_free"]
    assert dict(spec.collectives or {}) == {}
    assert not spec.allow_transfers and not spec.allow_f64
    assert "serving" in spec.tags and "telemetry" in spec.tags
    violations = check_contract(spec)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_roofline_closure_specs_are_registered():
    """The round-15 acceptance pins, strict: the kernel-dispatched X
    passes forbid the FULL scatter family and require f32 accumulation
    (the walker descends into the pallas_call body, so the law holds
    INSIDE the kernel); the two no-retrace invariances (kernel seam,
    donated ring) and the quantized rung budget ZERO collectives with no
    transfer/f64 escape hatch."""
    from photon_tpu.analysis.walker import (SCATTER_ADD_PRIMITIVES,
                                            SCATTER_PRIMITIVES)

    spec = _REGISTRY["blocked_ell_kernel_x_passes"]
    assert SCATTER_PRIMITIVES <= spec.forbid
    assert SCATTER_ADD_PRIMITIVES <= spec.forbid
    assert spec.require_f32_accum
    assert not spec.allow_transfers and not spec.allow_f64
    assert "kernels" in spec.tags
    for name in ("blocked_ell_kernel_no_retrace",
                 "mesh_stream_donated_no_retrace",
                 "serving_quantized_rung_invariance"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}, name
        assert not spec.allow_transfers and not spec.allow_f64, name
    assert "serving" in _REGISTRY["serving_quantized_rung_invariance"].tags
    assert "streamed" in _REGISTRY["mesh_stream_donated_no_retrace"].tags


def test_ingest_plane_spec_is_registered():
    """The round-14 acceptance pin: enabling the ingest plane introduces
    zero new trace signatures — the registered contract runs the cache's
    .npy round-trip through TraceSignatureLog against the direct chunk
    and refuses any signature divergence, and the traced streamed chunk
    program stays collective-free with the strict transfer/f64 policy."""
    spec = _REGISTRY["ingest_plane_chunk_invariance"]
    assert dict(spec.collectives or {}) == {}
    assert not spec.allow_transfers and not spec.allow_f64
    assert "ingest" in spec.tags and "streamed" in spec.tags
    violations = check_contract(spec)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_blocked_ell_specs_are_registered():
    """The round-12 acceptance pins: BOTH X passes of the blocked-ELL
    layout forbid the FULL scatter family (not just combining scatters)
    and require f32 accumulation on every sparse dot/einsum, across the
    resident, lane, streamed-chunk, and mesh faces."""
    from photon_tpu.analysis.walker import (SCATTER_ADD_PRIMITIVES,
                                            SCATTER_PRIMITIVES)

    names = ("blocked_ell_x_passes", "blocked_ell_lane_x_passes",
             "streamed_blocked_ell_chunk_partials",
             "lane_blocked_ell_value_and_grad",
             "sharded_blocked_ell_value_and_grad")
    for name in names:
        spec = _REGISTRY[name]
        assert SCATTER_PRIMITIVES <= spec.forbid, name
        assert SCATTER_ADD_PRIMITIVES <= spec.forbid, name
        assert spec.require_f32_accum, name
        assert not spec.allow_transfers and not spec.allow_f64, name
    assert dict(_REGISTRY[
        "sharded_blocked_ell_value_and_grad"].collectives) == {"psum": 1}


def test_blocked_ell_contracts_hold_on_cpu_backend():
    """The ADVICE.md cpu_parity_drift triage forward: the 6 tolerance
    failures are value-level CPU reduction-order drift, but the NEW
    sparse programs' STRUCTURAL contracts (scatter-free, f32 accumulation,
    one psum) must hold on the CPU backend too — the parity-drift escape
    hatch does not widen to the blocked-ELL layout. (This whole module
    runs on the CPU backend; this test makes the blocked-ELL subset's
    zero-violation status an explicit named assertion.)"""
    import jax

    assert jax.default_backend() == "cpu"
    for name in ("blocked_ell_x_passes", "blocked_ell_lane_x_passes",
                 "streamed_blocked_ell_chunk_partials",
                 "lane_blocked_ell_value_and_grad",
                 "sharded_blocked_ell_value_and_grad",
                 "grouped_auc_scatter_free"):
        violations = check_contract(_REGISTRY[name])
        assert violations == [], \
            f"{name} drifted on the CPU backend:\n" + \
            "\n".join(str(v) for v in violations)


def test_game_e2e_specs_are_registered():
    """The round-13 pod-scale GAME acceptance pins: the streamed-mesh
    fixed-effect evaluation budgets EXACTLY one psum, the mesh RE bucket
    solve is collective-free, and the mesh blocked-ELL chunk/score
    programs forbid the full scatter family with f32 accumulation."""
    from photon_tpu.analysis.walker import SCATTER_PRIMITIVES

    assert dict(_REGISTRY["game_streamed_fixed_evaluation"].collectives) \
        == {"psum": 1}
    assert dict(_REGISTRY["game_re_mesh_bucket_solve"].collectives
                or {}) == {}
    for name in ("streamed_mesh_blocked_ell_chunk_partials",
                 "game_score_stream_chunk"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}
        assert SCATTER_PRIMITIVES <= spec.forbid, name
        assert spec.require_f32_accum, name
        assert not spec.allow_transfers and not spec.allow_f64, name


def test_continual_specs_are_registered():
    """The round-14 continual-flywheel acceptance pins: the compacted
    refresh solve (compact_rows gather + prior-threaded vmapped lanes)
    budgets ZERO collectives with no transfer/f64 escape hatch, and the
    no-retrace spec — whose BUILDER asserts signature equality across
    touched sets of different sizes — is registered and strict too."""
    for name in ("continual_re_refresh_solve",
                 "continual_refresh_no_retrace"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}
        assert not spec.allow_transfers and not spec.allow_f64, name
        assert "continual" in spec.tags, name


def test_checkpoint_off_specs_are_registered():
    """Disarmed checkpointing must add ZERO transfer/callback primitives
    to jitted solver programs: both checkpoint-off specs are strict
    (no transfers, no f64, empty collective budget) and forbid the
    transfer family outright — the acceptance pin of the elastic-runs
    round, mirroring telemetry_off_is_free."""
    from photon_tpu.analysis.walker import TRANSFER_PRIMITIVES

    for name in ("checkpoint_off_is_free", "checkpoint_off_tron_free"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}
        assert not spec.allow_transfers and not spec.allow_f64
        assert TRANSFER_PRIMITIVES <= spec.forbid


def test_ledger_off_spec_is_registered():
    """Disarmed profiling must add ZERO transfer/callback primitives to
    jitted solver programs — the attribution-ledger round's acceptance
    pin, same strictness as the telemetry/checkpoint off-specs."""
    from photon_tpu.analysis.walker import TRANSFER_PRIMITIVES

    spec = _REGISTRY["ledger_off_is_free"]
    assert dict(spec.collectives or {}) == {}
    assert not spec.allow_transfers and not spec.allow_f64
    assert TRANSFER_PRIMITIVES <= spec.forbid
    assert "profiling" in spec.tags


def test_checkpoint_selftest_cli_end_to_end():
    """`python -m photon_tpu.checkpoint --selftest --json` — the
    snapshot → kill → restore → bit-parity smoke — exits 0 with every
    check green (exit 1 on drift is the CI contract)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the CLI must self-provision its platform
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_tpu.checkpoint", "--selftest",
         "--json"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] is True
    assert report["checks"]["resume_bit_identical"]["ok"] is True
    assert report["checks"]["mid_write_resume_bit_identical"]["ok"] is True


def test_serving_request_specs_are_registered():
    """The serving tier's per-request program is pinned: both heads
    (mean + margin), both strict — zero collectives, zero host exits."""
    for name in ("serving_request_program", "serving_request_margin"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}
        assert not spec.allow_transfers and not spec.allow_f64


def test_serving_overload_specs_are_registered():
    """The overload-round pins: the admission layer adds ZERO device-
    program changes (its builder raises on any signature divergence
    between admission on and off — traced by test_contract_holds), and
    a fleet replica's per-request path over an entity-range shard stays
    collective-free / host-exit-free / f64-free like the unsharded
    program."""
    for name in ("serving_admission_program_invariance",
                 "serving_fleet_request_path"):
        spec = _REGISTRY[name]
        assert dict(spec.collectives or {}) == {}
        assert not spec.allow_transfers and not spec.allow_f64
        assert "serving" in spec.tags


@pytest.mark.parametrize("name", sorted(_REGISTRY))
def test_contract_holds(name):
    spec = _REGISTRY[name]
    violations = check_contract(spec)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_declared_collective_budgets_are_exact():
    """The budgets are EXACT pins, not ceilings: a spec declaring
    {"psum": 1} must actually trace one psum (drift DOWN — a collective
    disappearing — is also a contract change someone must look at)."""
    from photon_tpu.analysis import collective_counts

    checked = 0
    for spec in _REGISTRY.values():
        if spec.collectives:
            traced = trace_contract(spec)
            assert dict(collective_counts(traced.closed_jaxpr)) == \
                dict(spec.collectives), spec.name
            checked += 1
    assert checked >= 4  # the mesh/streamed psum pins exist


def test_cli_json_end_to_end():
    """`python -m photon_tpu.analysis --json` — the CI entry point —
    exits 0 with zero violations over the full registry."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the CLI must self-provision its platform
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "photon_tpu.analysis", "--json"],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["ok"] is True
    assert report["n_specs"] >= 8
    assert report["n_violations"] == 0
