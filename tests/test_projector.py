"""Random-effect feature-space projectors (reference: projector.*)."""
import numpy as np
import pytest

from photon_tpu.game.dataset import GameData, RandomEffectDataset
from photon_tpu.game.projector import (
    BlockProjection,
    ProjectionConfig,
    ProjectorType,
    RandomProjector,
    build_index_map_projection,
    gather_rows,
    scatter_rows_into,
)
from photon_tpu.game.random_effect import RandomEffectCoordinate
from photon_tpu.models.variance import VarianceComputationType
from photon_tpu.ops.losses import TaskType
from photon_tpu.optim.config import OptimizerConfig
from photon_tpu.optim.regularization import l2


def _mixed_effect_data(seed=0, n=400, E=7, d=24, sparse_per_entity=3,
                       intercept=True, vary_support=False):
    """Each entity only ever touches its own small feature subset (plus the
    intercept), the regime INDEX_MAP projection exists for."""
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, E, size=n)
    # entity e is active on features [e*s, (e+1)*s)
    s = sparse_per_entity
    assert E * s <= d - int(intercept)
    X = np.zeros((n, d), np.float32)
    for i in range(n):
        e = ids[i]
        # vary_support: entity e uses only (e % s) + 1 of its features, so one
        # bucket mixes entities with different active-set sizes
        se = (e % s) + 1 if vary_support else s
        X[i, e * s:e * s + se] = rng.normal(size=se)
    if intercept:
        X[:, -1] = 1.0
    u = rng.normal(size=(E, d)).astype(np.float32) * 0.8
    margin = np.einsum("nd,nd->n", X, u[ids])
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
    return X, y, ids


def _train_re(X, y, ids, projection=None, variance=VarianceComputationType.NONE):
    data = GameData.build(y, shards={"s": X}, entity_ids={"e": ids})
    ds = RandomEffectDataset.build(data, "e", "s", projection=projection)
    coord = RandomEffectCoordinate(
        ds, TaskType.LOGISTIC_REGRESSION,
        OptimizerConfig(max_iters=60, reg=l2(), reg_weight=0.5),
        variance=variance,
    )
    model, stats = coord.train(np.zeros_like(y))
    return ds, coord, model, stats


class TestBlockProjection:
    def test_gather_scatter_roundtrip(self):
        rng = np.random.default_rng(3)
        E, d = 5, 12
        sets = [np.sort(rng.choice(d - 1, size=rng.integers(1, 5), replace=False))
                for _ in range(E)]
        bp = build_index_map_projection(sets, intercept_index=d - 1)
        full = rng.normal(size=(E, d)).astype(np.float32)
        rows = gather_rows(full, bp)
        # round-trip: scatter the gathered rows into zeros == full restricted
        # to each entity's active set + intercept
        out = np.zeros((E, d), np.float32)
        scatter_rows_into(out, rows, np.arange(E), bp)
        for e in range(E):
            keep = np.zeros(d, bool)
            keep[sets[e]] = True
            keep[d - 1] = True
            np.testing.assert_allclose(out[e][keep], full[e][keep], rtol=1e-6)
            assert (out[e][~keep] == 0).all()

    def test_intercept_pinned_last(self):
        bp = build_index_map_projection(
            [np.array([1, 3]), np.array([0])], intercept_index=9)
        assert (bp.proj_idx[:, -1] == 9).all()
        assert (bp.proj_mask[:, -1] == 1.0).all()

    def test_dim_padded_pow2(self):
        bp = build_index_map_projection(
            [np.arange(5), np.arange(2)], intercept_index=None)
        assert bp.dim == 8

    def test_sparse_block_varying_active_sizes(self):
        """Regression: entities whose active count + 1 < padded width p must
        still route intercept values to the intercept column, not feature 0."""
        from photon_tpu.game.projector import project_sparse_block

        # entity 0: features {2, 5} + intercept 9 (p=4 -> nact+1 < p)
        bp = build_index_map_projection(
            [np.array([2, 5]), np.array([1, 3, 7])], intercept_index=9)
        assert bp.dim == 4
        ind = np.array([[[2, 5, 9, 0]], [[1, 3, 9, 0]]])  # (E=2, m=1, k=4)
        val = np.array([[[1.5, -2.0, 1.0, 0.0]], [[4.0, 5.0, 1.0, 0.0]]],
                       np.float32)
        out = project_sparse_block(ind, val, bp)
        np.testing.assert_allclose(out[0, 0], [1.5, -2.0, 0.0, 1.0])
        np.testing.assert_allclose(out[1, 0], [4.0, 5.0, 0.0, 1.0])


class TestIndexMapProjection:
    def test_projected_solve_matches_full_solve(self):
        """INDEX_MAP projection is exact: same coefficients as the
        unprojected per-entity solves."""
        X, y, ids = _mixed_effect_data()
        _, _, m_full, _ = _train_re(X, y, ids, projection=None)
        ds, _, m_proj, stats = _train_re(
            X, y, ids,
            projection=ProjectionConfig(ProjectorType.INDEX_MAP))
        # every bucket solved in a reduced space strictly smaller than d
        assert all(b.dim is not None and b.dim < X.shape[1] for b in ds.blocks)
        np.testing.assert_allclose(
            np.asarray(m_proj.coefficients), np.asarray(m_full.coefficients),
            atol=2e-3,
        )
        assert stats.n_converged == stats.n_entities

    def test_projected_variances_match(self):
        X, y, ids = _mixed_effect_data(seed=1)
        _, _, m_full, _ = _train_re(
            X, y, ids, variance=VarianceComputationType.SIMPLE)
        _, _, m_proj, _ = _train_re(
            X, y, ids,
            projection=ProjectionConfig(ProjectorType.INDEX_MAP),
            variance=VarianceComputationType.SIMPLE,
        )
        vf = np.asarray(m_full.variances)
        vp = np.asarray(m_proj.variances)
        # On each entity's active features the variances agree; off-support
        # projected variances are 0 while the full solve reports the bare
        # 1/(l2) prior curvature there — compare only where both are active.
        active = vp > 0
        assert active.any()
        np.testing.assert_allclose(vp[active], vf[active], rtol=0.05, atol=1e-2)

    def test_sparse_input_matches_dense(self):
        import scipy.sparse as sp

        from photon_tpu.data.matrix import from_scipy_csr

        X, y, ids = _mixed_effect_data(seed=2, vary_support=True)
        _, _, m_dense, _ = _train_re(
            X, y, ids, projection=ProjectionConfig(ProjectorType.INDEX_MAP))
        Xs = from_scipy_csr(sp.csr_matrix(X))
        data = GameData.build(y, shards={"s": Xs}, entity_ids={"e": ids})
        ds = RandomEffectDataset.build(
            data, "e", "s",
            projection=ProjectionConfig(ProjectorType.INDEX_MAP))
        coord = RandomEffectCoordinate(
            ds, TaskType.LOGISTIC_REGRESSION,
            OptimizerConfig(max_iters=60, reg=l2(), reg_weight=0.5),
        )
        m_sparse, _ = coord.train(np.zeros_like(y))
        np.testing.assert_allclose(
            np.asarray(m_sparse.coefficients), np.asarray(m_dense.coefficients),
            atol=1e-4,
        )


class TestRandomProjection:
    def test_back_projected_scoring_is_exact(self):
        """x·back_project(w) == project_rows(x)·w — the identity scoring
        correctness rests on."""
        rng = np.random.default_rng(5)
        d, p = 40, 12
        proj = RandomProjector.build(d, p, keep_intercept=True, seed=0)
        X = rng.normal(size=(50, d)).astype(np.float32)
        X[:, -1] = 1.0
        w = rng.normal(size=p).astype(np.float32)
        lhs = X @ proj.back_project(w)
        rhs = proj.project_rows(X) @ w
        np.testing.assert_allclose(lhs, rhs, rtol=1e-4, atol=1e-4)

    def test_sparse_rows_projection_matches_dense(self):
        import scipy.sparse as sp

        from photon_tpu.data.matrix import from_scipy_csr

        rng = np.random.default_rng(6)
        d, p = 60, 16
        dense = np.zeros((30, d), np.float32)
        for i in range(30):
            cols = rng.choice(d - 1, size=4, replace=False)
            dense[i, cols] = rng.normal(size=4)
        dense[:, -1] = 1.0
        proj = RandomProjector.build(d, p, keep_intercept=True, seed=1)
        Xs = from_scipy_csr(sp.csr_matrix(dense))
        out_sparse = proj.project_sparse_rows(
            np.asarray(Xs.indices), np.asarray(Xs.values))
        np.testing.assert_allclose(
            out_sparse, proj.project_rows(dense), rtol=1e-4, atol=1e-4)

    def test_random_projected_training_learns(self):
        """Training per-entity models in a random-projected space still beats
        chance, and the model lives in full space for scoring."""
        X, y, ids = _mixed_effect_data(seed=7, n=800, E=4, d=32,
                                       sparse_per_entity=6)
        ds, coord, model, _ = _train_re(
            X, y, ids,
            projection=ProjectionConfig(ProjectorType.RANDOM, projected_dim=16))
        assert np.asarray(model.coefficients).shape == (4, X.shape[1])
        scores = np.asarray(coord.score(model))
        from sklearn.metrics import roc_auc_score

        assert roc_auc_score(y, scores) > 0.6

    def test_coeff_roundtrip_is_unbiased(self):
        """Regression: project_coeffs∘back_project must be ≈ identity, not a
        (d/p)-fold blow-up — warm starts cross this round trip every sweep."""
        rng = np.random.default_rng(11)
        d, p = 512, 64
        proj = RandomProjector.build(d, p, keep_intercept=True, seed=2)
        w = rng.normal(size=p).astype(np.float32)
        w2 = proj.project_coeffs(proj.back_project(w))
        ratio = np.linalg.norm(w2) / np.linalg.norm(w)
        assert 0.5 < ratio < 2.0

    def test_variance_with_random_projection_raises(self):
        X, y, ids = _mixed_effect_data(seed=8)
        with pytest.raises(ValueError, match="RANDOM"):
            _train_re(
                X, y, ids,
                projection=ProjectionConfig(ProjectorType.RANDOM, projected_dim=8),
                variance=VarianceComputationType.SIMPLE,
            )

    def test_projected_dim_required(self):
        with pytest.raises(ValueError, match="projected_dim"):
            ProjectionConfig(ProjectorType.RANDOM)


class TestEstimatorIntegration:
    def test_game_fit_with_projection(self):
        from photon_tpu.game.estimator import (
            FixedEffectConfig,
            GameEstimator,
            RandomEffectConfig,
        )
        from photon_tpu.game.scoring import score_game

        X, y, ids = _mixed_effect_data(seed=9, n=600, E=6, d=20)
        rng = np.random.default_rng(10)
        Xf = rng.normal(size=(len(y), 5)).astype(np.float32)
        data = GameData.build(
            y, shards={"fixed": Xf, "per": X}, entity_ids={"e": ids})
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_configs={
                "fixed": FixedEffectConfig(
                    "fixed", OptimizerConfig(max_iters=20, reg=l2(), reg_weight=0.1)),
                "per_e": RandomEffectConfig(
                    "e", "per",
                    OptimizerConfig(max_iters=30, reg=l2(), reg_weight=0.5),
                    projection=ProjectionConfig(ProjectorType.INDEX_MAP)),
            },
            n_sweeps=2,
        )
        results = est.fit(data)
        scores = np.asarray(score_game(results[0].model, data))
        from sklearn.metrics import roc_auc_score

        assert roc_auc_score(y, scores) > 0.75
