"""Chunked scoring driver (VERDICT r3 item 2): native-decode streaming,
vectorized ScoredItemAvro block encoding, bounded memory over multi-file
inputs, per-row nullable uid/label handling."""
import numpy as np
import pytest

from photon_tpu.data.avro_io import read_avro, write_avro
from photon_tpu.data.ingest import training_example_schema
from photon_tpu.drivers import (
    ScoringParams,
    TrainingParams,
    run_scoring,
    run_training,
)
from photon_tpu.drivers.score import SCORED_ITEM_SCHEMA, encode_scored_block


class TestEncodeScoredBlock:
    def _roundtrip(self, uids, scores, labels, lmask, umask, tmp_path):
        from photon_tpu.data.avro_io import AvroBlockWriter

        p = tmp_path / "b.avro"
        payload = encode_scored_block(
            np.asarray(uids), np.asarray(scores, np.float64),
            np.asarray(labels, np.float64), np.asarray(lmask),
            np.asarray(umask))
        with AvroBlockWriter(str(p), SCORED_ITEM_SCHEMA, codec="null") as w:
            w.write_block(len(uids), payload)
        return read_avro(str(p))

    def test_matches_per_record_writer(self, tmp_path):
        rng = np.random.default_rng(0)
        n = 500
        uids = np.asarray(
            [f"user_{i}" * (1 + i % 23) if i % 7 else "" for i in range(n)])
        scores = rng.normal(size=n)
        labels = rng.integers(0, 2, n).astype(np.float64)
        lmask = rng.uniform(size=n) < 0.8
        umask = uids != ""
        got = self._roundtrip(uids, scores, labels, lmask, umask, tmp_path)
        assert len(got) == n
        for i, r in enumerate(got):
            if umask[i]:
                assert r["uid"] == uids[i]
            else:
                assert r["uid"] is None
            assert r["predictionScore"] == pytest.approx(scores[i], abs=0)
            if lmask[i]:
                assert r["label"] == labels[i]
            else:
                assert r["label"] is None

    def test_long_uids_multibyte_varint(self, tmp_path):
        n = 3
        uids = np.asarray(["x" * 5, "y" * 200, "z" * 20000])
        got = self._roundtrip(uids, np.arange(n, dtype=float),
                              np.zeros(n), np.zeros(n, bool),
                              np.ones(n, bool), tmp_path)
        assert [len(r["uid"]) for r in got] == [5, 200, 20000]

    def test_unicode_uids(self, tmp_path):
        uids = np.asarray(["héllo", "模型", "a"])
        got = self._roundtrip(uids, np.zeros(3), np.zeros(3),
                              np.ones(3, bool), np.ones(3, bool), tmp_path)
        assert [r["uid"] for r in got] == ["héllo", "模型", "a"]


def _write_scoring_parts(root, n_files=3, rows=150, seed=0, labeled=True,
                         null_uid_every=0, empty_uid_every=0):
    rng = np.random.default_rng(seed)
    schema = training_example_schema(feature_bags=("g", "pu"),
                                     entity_fields=("userId",))
    if not labeled:  # unlabeled data has NO response field at all
        schema = dict(schema, fields=[f for f in schema["fields"]
                                      if f["name"] != "response"])
    root.mkdir(parents=True, exist_ok=True)
    truth = []
    for fi in range(n_files):
        recs = []
        for i in range(rows):
            a, c = float(rng.normal()), float(rng.normal())
            u = int(rng.integers(0, 7))
            m = 1.2 * a - 0.5 * c + 0.3 * (u - 3)
            y = float(rng.uniform() < 1 / (1 + np.exp(-m)))
            uid = (None if null_uid_every and i % null_uid_every == 0
                   else "" if empty_uid_every and i % empty_uid_every == 1
                   else f"r{fi}_{i}")
            rec_y = {"response": y} if labeled else {}
            recs.append({
                **rec_y,
                "offset": None, "weight": None, "uid": uid,
                "userId": f"u{u}",
                "g": [{"name": "a", "term": "", "value": a},
                      {"name": "c", "term": "", "value": c}],
                "pu": [{"name": "b", "term": "", "value": 1.0}],
            })
            truth.append((uid, y))
        write_avro(root / f"part-{fi}.avro", recs, schema, block_records=64)
    return truth


FEATURE_SHARDS = {"fs": {"bags": ["g"], "has_intercept": True},
                  "us": {"bags": ["pu"], "has_intercept": False}}


@pytest.fixture(scope="module")
def trained_model(tmp_path_factory):
    root = tmp_path_factory.mktemp("score_stream")
    _write_scoring_parts(root / "train", n_files=2, rows=300, seed=1)
    out = run_training(TrainingParams(
        train_path=str(root / "train"),
        output_dir=str(root / "model_out"),
        feature_shards=FEATURE_SHARDS,
        coordinates={
            "fixed": {"feature_shard": "fs", "reg_type": "l2",
                      "reg_weight": 0.5, "max_iters": 40},
            "perUser": {"feature_shard": "us", "entity_name": "userId",
                        "reg_type": "l2", "reg_weight": 2.0,
                        "max_iters": 20},
        },
        entity_fields=["userId"], n_sweeps=2))
    return root, out.model_dir


def _score(root, model_dir, data, out, **kw):
    base = dict(model_dir=model_dir, data_path=str(data),
                output_dir=str(out), feature_shards=FEATURE_SHARDS,
                entity_fields=["userId"], evaluators=["AUC"])
    base.update(kw)
    return run_scoring(ScoringParams(**base))


class TestStreamedScoringDriver:
    def test_multi_file_chunked_scores_and_metric(self, trained_model,
                                                  tmp_path):
        root, model_dir = trained_model
        truth = _write_scoring_parts(root / "test", n_files=3, rows=150,
                                     seed=2)
        out = _score(root, model_dir, root / "test", tmp_path / "sc",
                     chunk_rows=128)  # many chunks over 3 files
        assert out.scores.shape[0] == len(truth)
        assert out.metric is not None and out.metric > 0.65
        rows = read_avro(str(tmp_path / "sc" / "scores.avro"))
        assert len(rows) == len(truth)
        # order preserved across files and chunks; labels round-trip
        for r, (uid, y) in zip(rows, truth):
            assert r["uid"] == uid
            assert r["label"] == y
        p = np.asarray([r["predictionScore"] for r in rows])
        np.testing.assert_allclose(p, out.scores, rtol=0, atol=0)
        assert np.all((p > 0) & (p < 1))  # output_mean through sigmoid

    def test_unlabeled_data_scores_without_metric(self, trained_model,
                                                  tmp_path):
        root, model_dir = trained_model
        _write_scoring_parts(root / "unlab", n_files=1, rows=120, seed=3,
                             labeled=False)
        out = _score(root, model_dir, root / "unlab", tmp_path / "un")
        assert out.metric is None and out.metrics == {}
        rows = read_avro(str(tmp_path / "un" / "scores.avro"))
        assert len(rows) == 120
        assert all(r["label"] is None for r in rows)

    def test_null_uids_pass_through(self, trained_model, tmp_path):
        root, model_dir = trained_model
        truth = _write_scoring_parts(root / "nuid", n_files=1, rows=90,
                                     seed=4, null_uid_every=5)
        out = _score(root, model_dir, root / "nuid", tmp_path / "nu")
        rows = read_avro(str(tmp_path / "nu" / "scores.avro"))
        assert [r["uid"] for r in rows] == [u for u, _ in truth]
        assert out.metric is not None

    def test_empty_string_uid_distinct_from_null(self, trained_model,
                                                 tmp_path):
        """ADVICE r4: a legitimately EMPTY-STRING uid must come back as ""
        (string branch), not be conflated with a truly missing uid (null
        branch) — the decoder's presence mask, not the folded "" sentinel,
        decides the output union branch."""
        root, model_dir = trained_model
        truth = _write_scoring_parts(root / "euid", n_files=1, rows=60,
                                     seed=6, null_uid_every=5,
                                     empty_uid_every=7)
        assert any(u == "" for u, _ in truth)     # both cases present
        assert any(u is None for u, _ in truth)
        out = _score(root, model_dir, root / "euid", tmp_path / "eu")
        rows = read_avro(str(tmp_path / "eu" / "scores.avro"))
        assert [r["uid"] for r in rows] == [u for u, _ in truth]
        assert out.scores.shape[0] == 60

    def test_python_and_native_paths_agree(self, trained_model, tmp_path):
        root, model_dir = trained_model
        _write_scoring_parts(root / "par", n_files=2, rows=100, seed=5)
        a = _score(root, model_dir, root / "par", tmp_path / "pyp",
                   use_native=False)
        from photon_tpu import native

        if not native.available():
            pytest.skip("native toolchain unavailable")
        b = _score(root, model_dir, root / "par", tmp_path / "nat",
                   use_native=True)
        np.testing.assert_array_equal(a.scores, b.scores)
        assert a.metric == b.metric

    def test_bounded_chunk_arena(self, trained_model, tmp_path,
                                 monkeypatch):
        import photon_tpu.data.streaming as streaming_mod

        root, model_dir = trained_model
        _write_scoring_parts(root / "big", n_files=4, rows=200, seed=6)
        captured = []
        real = streaming_mod.iter_game_chunks

        def spy(*a, **kw):
            stream, it = real(*a, **kw)
            captured.append(stream)
            return stream, it

        monkeypatch.setattr(streaming_mod, "iter_game_chunks", spy)
        # the scoring driver imports iter_game_chunks at module level
        import photon_tpu.drivers.score as score_mod

        monkeypatch.setattr(score_mod, "iter_game_chunks", spy)
        _score(root, model_dir, root / "big", tmp_path / "bg",
               chunk_rows=128)
        assert captured
        st = captured[-1]
        assert 0 < st.peak_arena_bytes < 4096 * 2 * 191  # ~2 chunks max


class TestScoringEdgeCases:
    def test_uid_listed_in_entity_fields_with_nulls(self, trained_model,
                                                    tmp_path):
        """uid is nullable even when the caller lists it among
        entity_fields (it is always an optional column)."""
        root, model_dir = trained_model
        truth = _write_scoring_parts(root / "uid_ent", n_files=1, rows=60,
                                     seed=7, null_uid_every=4)
        out = _score(root, model_dir, root / "uid_ent", tmp_path / "ue",
                     entity_fields=["userId", "uid"])
        rows = read_avro(str(tmp_path / "ue" / "scores.avro"))
        assert [r["uid"] for r in rows] == [u for u, _ in truth]
        assert out.metric is not None

    def test_sparse_shard_scores_without_sparse_k(self, tmp_path):
        """Sparse shards score with per-chunk nnz widths — no sparse_k
        required (chunks are independent; the old reader's behavior)."""
        rng = np.random.default_rng(8)
        root = tmp_path / "sparse_job"
        schema = training_example_schema(feature_bags=("wide",))
        root.mkdir()

        def gen(path, rows, seed):
            r = np.random.default_rng(seed)
            recs = []
            for i in range(rows):
                feats = [{"name": f"w{int(v)}", "term": "",
                          "value": float(r.normal())}
                         for v in r.integers(0, 30, size=2 + i % 4)]
                m = sum(f["value"] for f in feats) * 0.4
                y = float(r.uniform() < 1 / (1 + np.exp(-m)))
                recs.append({"response": y, "offset": None, "weight": None,
                             "uid": f"s{seed}_{i}", "wide": feats})
            write_avro(path, recs, schema, block_records=32)

        gen(root / "train.avro", 200, 1)
        shards = {"wide": {"bags": ["wide"], "dense_threshold": 4}}
        t = run_training(TrainingParams(
            train_path=str(root / "train.avro"),
            output_dir=str(root / "model"),
            feature_shards=shards,
            coordinates={"fixed": {"feature_shard": "wide",
                                   "reg_type": "l2", "reg_weight": 1.0,
                                   "max_iters": 20}},
            sparse_k=8))
        data_dir = root / "score_data"
        data_dir.mkdir()
        gen(data_dir / "p0.avro", 100, 2)
        gen(data_dir / "p1.avro", 100, 3)
        out = run_scoring(ScoringParams(
            model_dir=t.model_dir, data_path=str(data_dir),
            output_dir=str(root / "scored"), feature_shards=shards,
            chunk_rows=64))  # no sparse_k: ragged per-chunk widths
        assert out.scores.shape[0] == 200
        assert np.isfinite(out.scores).all()
        assert out.metric is not None


class TestMidStreamFailure:
    def test_partial_output_keeps_scored_chunks(self, trained_model,
                                                tmp_path):
        """A malformed block mid-stream raises, but every chunk scored
        BEFORE the failure — including the pipeline's in-flight one — is
        in the partial scores.avro (the file users debug/resume from)."""
        root, model_dir = trained_model
        d = root / "corrupt_job"
        _write_scoring_parts(d, n_files=2, rows=150, seed=9)
        # keep file 2's header valid but trash its block payloads
        p2 = d / "part-1.avro"
        raw = bytearray(p2.read_bytes())
        for i in range(len(raw) // 2, len(raw) - 64, 7):
            raw[i] ^= 0xFF
        p2.write_bytes(bytes(raw))

        with pytest.raises(ValueError):
            _score(root, model_dir, d, tmp_path / "partial",
                   chunk_rows=64)
        rows = read_avro(str(tmp_path / "partial" / "scores.avro"))
        # file 1 yields two complete 64-row chunks before the third chunk
        # (file 1's 22-row tail + file 2's blocks) hits the corruption.
        # WITHOUT the unwind flush the in-flight second chunk would be
        # dropped and only 64 rows would survive.
        assert len(rows) >= 128
        assert rows[0]["uid"] == "r0_0"
        assert rows[127]["uid"] == "r0_127"
