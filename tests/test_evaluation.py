"""Evaluation metrics vs sklearn / per-group Python loops (SURVEY.md §4)."""
import numpy as np
import pytest
from sklearn.metrics import mean_squared_error, roc_auc_score

from photon_tpu.evaluation import (
    Evaluator,
    EvaluatorType,
    auc,
    default_evaluator,
    grouped_auc,
    grouped_precision_at_k,
    logistic_loss,
    precision_at_k,
    rmse,
)
from photon_tpu.ops.losses import TaskType

rng = np.random.default_rng(0)


def test_auc_matches_sklearn():
    n = 500
    y = (rng.random(n) < 0.4).astype(np.float32)
    s = rng.normal(size=n).astype(np.float32) + y
    np.testing.assert_allclose(float(auc(s, y)), roc_auc_score(y, s), atol=1e-6)


def test_auc_weighted_with_ties():
    n = 400
    y = (rng.random(n) < 0.5).astype(np.float32)
    s = np.round(rng.normal(size=n) + 0.8 * y, 1).astype(np.float32)  # many ties
    w = rng.integers(1, 5, size=n).astype(np.float32)
    expected = roc_auc_score(y, s, sample_weight=w)
    np.testing.assert_allclose(float(auc(s, y, w)), expected, atol=1e-6)


def test_auc_ignores_padding():
    y = np.array([1, 0, 1, 0, 1], np.float32)
    s = np.array([0.9, 0.1, 0.8, 0.4, 0.2], np.float32)
    w = np.array([1, 1, 1, 1, 0], np.float32)  # last row is padding
    np.testing.assert_allclose(
        float(auc(s, y, w)), roc_auc_score(y[:4], s[:4]), atol=1e-6
    )


def test_rmse_matches_sklearn():
    n = 300
    y = rng.normal(size=n).astype(np.float32)
    s = y + 0.3 * rng.normal(size=n).astype(np.float32)
    w = rng.random(n).astype(np.float32) + 0.5
    expected = np.sqrt(mean_squared_error(y, s, sample_weight=w))
    np.testing.assert_allclose(float(rmse(s, y, w)), expected, rtol=1e-5)


def test_logistic_loss_closed_form():
    s = np.array([0.0, 2.0, -1.0], np.float32)
    y = np.array([1.0, 0.0, 1.0], np.float32)
    expected = np.mean(np.log1p(np.exp(s)) - y * s)
    np.testing.assert_allclose(float(logistic_loss(s, y)), expected, rtol=1e-5)


def test_precision_at_k():
    s = np.array([0.9, 0.8, 0.7, 0.6, 0.5], np.float32)
    y = np.array([1, 0, 1, 1, 0], np.float32)
    np.testing.assert_allclose(float(precision_at_k(s, y, 3)), 2.0 / 3.0, atol=1e-6)
    # padding rows excluded even when high-scoring
    w = np.array([0, 1, 1, 1, 1], np.float32)
    np.testing.assert_allclose(
        float(precision_at_k(s, y, 3, w)), 2.0 / 3.0, atol=1e-6
    )


def _random_groups(n, num_groups):
    g = rng.integers(0, num_groups, size=n).astype(np.int32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    s = np.round(rng.normal(size=n) + 0.7 * y, 1).astype(np.float32)
    w = rng.integers(1, 4, size=n).astype(np.float32)
    return s, y, w, g


def test_grouped_auc_matches_python_loop():
    num_groups = 12
    s, y, w, g = _random_groups(600, num_groups)
    per_group, valid, mean = grouped_auc(s, y, w, g, num_groups)
    per_group, valid = np.asarray(per_group), np.asarray(valid)
    expected = []
    for gid in range(num_groups):
        m = g == gid
        if m.sum() == 0 or len(np.unique(y[m])) < 2:
            assert not valid[gid]
            continue
        ref = roc_auc_score(y[m], s[m], sample_weight=w[m])
        assert valid[gid]
        np.testing.assert_allclose(per_group[gid], ref, atol=1e-5)
        expected.append(ref)
    np.testing.assert_allclose(float(mean), np.mean(expected), atol=1e-5)


def test_grouped_precision_at_k_matches_python_loop():
    num_groups, k = 10, 3
    s, y, w, g = _random_groups(200, num_groups)
    w[rng.random(len(w)) < 0.1] = 0.0  # some padding
    per_group, valid, mean = grouped_precision_at_k(s, y, w, g, num_groups, k)
    per_group, valid = np.asarray(per_group), np.asarray(valid)
    expected = []
    for gid in range(num_groups):
        m = (g == gid) & (w > 0)
        if m.sum() == 0:
            assert not valid[gid]
            continue
        order = np.argsort(-s[m], kind="stable")[:k]
        ref = y[m][order].mean()
        np.testing.assert_allclose(per_group[gid], ref, atol=1e-6)
        expected.append(ref)
    np.testing.assert_allclose(float(mean), np.mean(expected), atol=1e-6)


def test_evaluator_better_than_direction():
    assert Evaluator(EvaluatorType.AUC).better_than(0.9, 0.8)
    assert not Evaluator(EvaluatorType.AUC).better_than(0.7, 0.8)
    assert Evaluator(EvaluatorType.RMSE).better_than(0.1, 0.2)
    assert Evaluator(EvaluatorType.RMSE).better_than(0.1, None)


def test_default_evaluator_per_task():
    assert default_evaluator(TaskType.LOGISTIC_REGRESSION).kind is EvaluatorType.AUC
    assert default_evaluator(TaskType.LINEAR_REGRESSION).kind is EvaluatorType.RMSE
    assert (
        default_evaluator(TaskType.POISSON_REGRESSION).kind
        is EvaluatorType.POISSON_LOSS
    )


def test_sharded_evaluator_object():
    num_groups = 8
    s, y, w, g = _random_groups(300, num_groups)
    ev = Evaluator(EvaluatorType.SHARDED_AUC, num_groups=num_groups)
    _, _, mean = grouped_auc(s, y, w, g, num_groups)
    np.testing.assert_allclose(ev.evaluate(s, y, w, g), float(mean), atol=1e-6)
    with pytest.raises(ValueError):
        ev.evaluate(s, y, w)  # missing groups


def test_grouped_mean_nan_when_no_valid_group():
    # every group single-class ⇒ metric undefined, not 0.0
    s = np.array([0.5, 0.6, 0.2, 0.3], np.float32)
    y = np.array([1, 1, 0, 0], np.float32)
    w = np.ones(4, np.float32)
    g = np.array([0, 0, 1, 1], np.int32)
    _, valid, mean = grouped_auc(s, y, w, g, 2)
    assert not np.asarray(valid).any()
    assert np.isnan(float(mean))


# ------------------------------------------------------------------------ AUPR
def test_aupr_matches_sklearn():
    from sklearn.metrics import average_precision_score

    from photon_tpu.evaluation import aupr

    n = 500
    y = (rng.random(n) < 0.3).astype(np.float32)
    s = rng.normal(size=n).astype(np.float32) + 1.2 * y
    np.testing.assert_allclose(
        float(aupr(s, y)), average_precision_score(y, s), atol=1e-6)


def test_aupr_weighted_with_ties_matches_sklearn():
    from sklearn.metrics import average_precision_score

    from photon_tpu.evaluation import aupr

    n = 400
    y = (rng.random(n) < 0.4).astype(np.float32)
    s = np.round(rng.normal(size=n) + 0.7 * y, 1).astype(np.float32)
    w = rng.integers(1, 5, size=n).astype(np.float32)
    expected = average_precision_score(y, s, sample_weight=w)
    np.testing.assert_allclose(float(aupr(s, y, w)), expected, atol=1e-6)


def test_aupr_padding_and_degenerate_groups():
    from photon_tpu.evaluation import aupr

    y = np.array([1, 0, 1, 0, 1], np.float32)
    s = np.array([0.9, 0.1, 0.8, 0.4, 0.2], np.float32)
    w = np.ones(5, np.float32)
    base = float(aupr(s, y, w))
    # weight-0 padding rows change nothing
    yp = np.concatenate([y, [1, 0]]).astype(np.float32)
    sp = np.concatenate([s, [5.0, -5.0]]).astype(np.float32)
    wp = np.concatenate([w, [0.0, 0.0]]).astype(np.float32)
    np.testing.assert_allclose(float(aupr(sp, yp, wp)), base, atol=1e-6)
    # no positives -> undefined
    assert np.isnan(float(aupr(s, np.zeros(5, np.float32))))
    # all positives -> 1.0
    np.testing.assert_allclose(
        float(aupr(s, np.ones(5, np.float32))), 1.0, atol=1e-6)


def test_grouped_aupr_matches_per_group_loop():
    from sklearn.metrics import average_precision_score

    from photon_tpu.evaluation import grouped_aupr

    num_groups = 7
    s, y, w, g = _random_groups(350, num_groups)
    per_group, valid, mean = grouped_aupr(s, y, w, g, num_groups)
    per_group = np.asarray(per_group)
    expected = []
    for gi in range(num_groups):
        m = g == gi
        if y[m].sum() == 0:
            assert not valid[gi]
            continue
        ref = average_precision_score(y[m], s[m], sample_weight=w[m])
        np.testing.assert_allclose(per_group[gi], ref, atol=1e-5)
        expected.append(ref)
    np.testing.assert_allclose(float(mean), np.mean(expected), atol=1e-5)


def test_aupr_evaluator_wiring():
    from photon_tpu.evaluation.evaluator import evaluator_name, parse_evaluator

    ev = parse_evaluator("AUPR")
    assert ev.kind is EvaluatorType.AUPR
    assert ev.higher_is_better and not ev.needs_groups
    assert evaluator_name(ev) == "AUPR"
    sv = parse_evaluator("sharded_aupr")
    assert sv.kind is EvaluatorType.SHARDED_AUPR
    assert sv.higher_is_better and sv.needs_groups

    num_groups = 6
    s, y, w, g = _random_groups(240, num_groups)
    from photon_tpu.evaluation import grouped_aupr

    ev2 = Evaluator(EvaluatorType.SHARDED_AUPR, num_groups=num_groups)
    _, _, mean = grouped_aupr(s, y, w, g, num_groups)
    np.testing.assert_allclose(ev2.evaluate(s, y, w, g), float(mean),
                               atol=1e-6)


def test_grouped_metrics_are_scatter_free_and_counted():
    """Round 12: the grouped metrics ride the sorted-segment machinery —
    the traced program contains NO scatter of any kind, and each call
    books the scatter elements it saved on the telemetry counter."""
    import jax

    from photon_tpu import telemetry
    from photon_tpu.analysis.walker import SCATTER_PRIMITIVES, sites
    from photon_tpu.evaluation.grouped import _grouped_auc

    num_groups = 12
    s, y, w, g = _random_groups(600, num_groups)
    jaxpr = jax.make_jaxpr(
        lambda *a: _grouped_auc(*a, num_groups=num_groups))(s, y, w, g)
    scatters = [st.name for st in sites(jaxpr)
                if st.name in SCATTER_PRIMITIVES]
    assert scatters == []

    run = telemetry.start_run("eval-test")
    try:
        grouped_auc(s, y, w, g, num_groups)
        saved = run.counters.get("eval.scatter_elems_saved", 0)
        # 6 segment reductions × 600 rows under the old formulation
        assert saved == 6 * 600
    finally:
        telemetry.finish_run()
