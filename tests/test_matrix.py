"""SparseRows construction / matvec tests (data.matrix)."""
import numpy as np
import pytest
import scipy.sparse as sp

from photon_tpu.data.matrix import from_scipy_csr, matvec, rmatvec, weighted_gram


def _random_csr(rng, n=50, d=30, density=0.2):
    return sp.random(n, d, density=density, format="csr",
                     random_state=np.random.RandomState(0), dtype=np.float32)


def test_from_scipy_csr_matches_dense(rng):
    csr = _random_csr(rng)
    S = from_scipy_csr(csr)
    w = rng.normal(size=csr.shape[1]).astype(np.float32)
    np.testing.assert_allclose(matvec(S, w), csr @ w, rtol=1e-5, atol=1e-5)
    r = rng.normal(size=csr.shape[0]).astype(np.float32)
    np.testing.assert_allclose(rmatvec(S, r), csr.T @ r, rtol=1e-5, atol=1e-5)


def test_from_scipy_csr_empty_rows(rng):
    csr = sp.csr_matrix(
        np.array([[0, 0, 3], [0, 0, 0], [1, 0, 0]], np.float32)
    )
    S = from_scipy_csr(csr)
    w = np.array([1.0, 2.0, 4.0], np.float32)
    np.testing.assert_allclose(matvec(S, w), [12.0, 0.0, 1.0])


def test_from_scipy_csr_truncation_keeps_largest(rng):
    dense = np.array([[5.0, -9.0, 1.0, 0.0],
                      [0.0, 2.0, 0.0, 0.0]], np.float32)
    csr = sp.csr_matrix(dense)
    with pytest.warns(UserWarning, match="1 rows exceed k=2"):
        S = from_scipy_csr(csr, k=2)
    # Row 0 keeps its two largest-|value| entries (-9 at col 1, 5 at col 0).
    got = np.zeros(4, np.float32)
    idx = np.asarray(S.indices[0])
    val = np.asarray(S.values[0])
    got[idx[val != 0]] = val[val != 0]
    np.testing.assert_allclose(got, [5.0, -9.0, 0.0, 0.0])


def test_weighted_gram_guard():
    S = from_scipy_csr(sp.identity(3, format="csr", dtype=np.float32))
    big = S.__class__(S.indices, S.values, 10_000_000)
    with pytest.raises(ValueError, match="MAX_GRAM_FEATURES"):
        weighted_gram(big, np.ones(3, np.float32))
