"""Pallas fused value+grad kernel vs the jnp objective (interpreter mode).

The kernel's compiled path runs on real TPU only; these tests pin the math
via the interpreter lowering, which shares _chunk_math with the compiled
DMA kernel.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from photon_tpu.data.dataset import cast_features, make_batch
from photon_tpu.data.matrix import from_scipy_csr
from photon_tpu.models.training import train_glm
from photon_tpu.ops.fused import can_fuse, fused_value_and_grad, pick_chunk
from photon_tpu.ops.losses import TaskType
from photon_tpu.ops.objective import Objective
from photon_tpu.optim import regularization as reg
from photon_tpu.optim.config import OptimizerConfig


@pytest.fixture
def batch(rng):
    n, d = 1024, 40
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    wt = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    off = (rng.normal(size=n) * 0.1).astype(np.float32)
    return make_batch(X, y, weights=wt, offsets=off)


class TestFusedKernel:
    @pytest.mark.parametrize("task", list(TaskType))
    def test_matches_jnp_objective(self, task, batch, rng):
        w = jnp.asarray(rng.normal(size=40), jnp.float32) * 0.3
        v_ref, g_ref = Objective(task=task).value_and_grad(w, batch)
        v, g = fused_value_and_grad(task, batch.X, w, batch.y,
                                    batch.weights, batch.offsets)
        np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-4, atol=1e-3)

    def test_bf16_storage(self, batch, rng):
        w = jnp.asarray(rng.normal(size=40), jnp.float32) * 0.3
        b16 = cast_features(batch)
        v_ref, g_ref = Objective(task=TaskType.LOGISTIC_REGRESSION
                                 ).value_and_grad(w, b16)
        v, g = fused_value_and_grad(TaskType.LOGISTIC_REGRESSION, b16.X, w,
                                    b16.y, b16.weights, b16.offsets)
        np.testing.assert_allclose(float(v), float(v_ref), rtol=5e-3)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=0.05, atol=0.05)

    def test_objective_fused_flag_dispatch(self, batch, rng):
        w = jnp.asarray(rng.normal(size=40), jnp.float32) * 0.3
        obj_f = Objective(task=TaskType.LOGISTIC_REGRESSION, l2=0.5, fused=True)
        obj_j = Objective(task=TaskType.LOGISTIC_REGRESSION, l2=0.5)
        vf, gf = obj_f.value_and_grad(w, batch)
        vj, gj = obj_j.value_and_grad(w, batch)
        np.testing.assert_allclose(float(vf), float(vj), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gj),
                                   rtol=1e-4, atol=1e-3)

    def test_can_fuse_gates(self, rng):
        import scipy.sparse as sp
        assert can_fuse(jnp.zeros((1024, 16)))
        assert not can_fuse(jnp.zeros((100, 16)))  # no 128-divisible chunk
        M = sp.random(256, 16, density=0.3, format="csr", dtype=np.float32)
        assert not can_fuse(from_scipy_csr(M))  # sparse never fuses
        assert pick_chunk(1 << 20, 256, 4) is not None

    def test_fused_inside_solver_loop(self, rng):
        """train_glm(mesh=None) engages the fused objective end-to-end."""
        n, d = 2048, 12
        X = rng.normal(size=(n, d)).astype(np.float32)
        w_true = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
        y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w_true)))).astype(
            np.float32)
        cfg = OptimizerConfig(max_iters=60, reg=reg.l2(), reg_weight=1.0,
                              regularize_intercept=True)
        m_fused, r = train_glm(make_batch(X, y),
                               TaskType.LOGISTIC_REGRESSION, cfg)
        assert bool(r.converged)
        # Same solve through the never-fused objective route.
        from photon_tpu.models.training import make_objective, solve

        obj = make_objective(TaskType.LOGISTIC_REGRESSION, cfg, d,
                             intercept_index=None)
        r_ref = jax.jit(lambda b, w0: solve(obj, b, w0, cfg))(
            make_batch(X, y), jnp.zeros((d,), jnp.float32))
        np.testing.assert_allclose(np.asarray(m_fused.coefficients.means),
                                   np.asarray(r_ref.w), atol=2e-4)
