"""Source-level convention auditor: the host-side law the jaxpr checker
can't see.

`photon_tpu.analysis` pins the DEVICE-side performance model (collective
budgets, dtype policy, retrace hazards) by tracing jaxprs. This package
is its host-side twin: an AST walk over the repo's own source that
enforces the operational conventions fourteen PRs of growth wrote down
in docstrings and then maintained by hand — the commit-bytes-only rule
for durable writes, the fault-site registry, the telemetry name
registry, lock discipline in the threaded spines, the central
``PHOTON_TPU_*`` knob table, contract/sentinel coverage, spawn/thread
hygiene, and InjectedFault-swallowing ``except`` clauses. One unaudited
``open(..., "w")`` breaks the crash-consistency story of Graepel et
al.'s flywheel without any jaxpr changing; this is the auditor that
catches it on the PR that introduces it.

Deliberately **jax-free**: rules read the registries they pin
(`checkpoint.faults.FAULT_SITES`, `telemetry.TELEMETRY_REGISTRY`,
`utils.env.KNOB_DOCS`, `analysis.registry.HOT_PATH_MODULES`, the
sentinel's direction/exclude patterns, bench.py's legs dict) as AST
literals, so ``python -m photon_tpu.lint`` costs milliseconds and runs
before anything heavyweight imports — the same guard economics as
``bench.py --gate``.

Waiver syntax (docs/ANALYSIS.md "Source-level lint"): a finding is
waived by a trailing comment on its line (or the line above) — the
reason string is MANDATORY in every form; an empty or missing reason is
itself a finding:

- ``photon: allow(<rule>, <reason>)`` — keyed by RULE NAME, works for
  every rule (the shared form new code should use);
- ``photon: <tag>(<reason>)`` — keyed by the rule's suppression tag
  (e.g. ``photon: unguarded(...)`` for ``guarded_by``);
- ``lint: <tag>(<reason>)`` — the legacy tag form, still honored.

``photon:``-form waivers are STALE-CHECKED: on a full run (no ``--only``
filter), a waiver on a line where its rule no longer fires is itself a
finding — waivers can't outlive the hazard they excuse.

The shipped ``baseline.json`` is EMPTY and stays empty: every true
violation gets fixed, not baselined — the file exists so a future
emergency has a documented escape hatch with a visible diff.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import subprocess
from typing import Iterable, Optional

__all__ = [
    "Finding", "SourceFile", "Context", "load_context", "run_lint",
    "repo_root", "load_baseline",
]

# trailing waiver comments; the marker strings are split so these
# regexes (and this comment) never read as live waivers themselves
_SUPPRESS_RE = re.compile(
    r"#\s*lint" r":\s*([a-z_]+)\s*\(\s*(.*?)\s*\)\s*$")
_SUPPRESS_BARE_RE = re.compile(r"#\s*lint" r":\s*([a-z_]+)\s*$")
_PHOTON_ALLOW_RE = re.compile(
    r"#\s*photon" r":\s*allow\s*\(\s*([a-z_]+)\s*"
    r"(?:,\s*(.*?))?\s*\)\s*$")
_PHOTON_TAG_RE = re.compile(
    r"#\s*photon" r":\s*([a-z_]+)\s*\(\s*(.*?)\s*\)\s*$")
_PHOTON_BARE_RE = re.compile(r"#\s*photon" r":\s*([a-z_]+)\s*$")


@dataclasses.dataclass
class Finding:
    """One convention violation. ``key`` is the stable fingerprint piece
    (rule + path + key identifies the finding across line drift — the
    baseline format)."""

    rule: str
    path: str
    line: int
    message: str
    key: str

    @property
    def fingerprint(self) -> tuple:
        return (self.rule, self.path, self.key)

    @property
    def text(self) -> str:
        return f"{self.rule}: {self.path}:{self.line}: {self.message}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "key": self.key, "message": self.message}


class SourceFile:
    """One parsed source file: AST + raw lines + suppression comments."""

    def __init__(self, rel: str, text: str):
        self.rel = rel
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)
        # lineno -> (kind, name, reason) where kind is "rule" (photon
        # allow form, name = rule name), "tag" (photon tag form), or
        # "legacy" (lint: tag form, exempt from stale checking); bad
        # entries (empty/missing reason) kept apart
        self.suppressions: dict = {}
        self.bad_suppressions: list = []
        for i, ln in enumerate(self.lines, start=1):
            if "#" not in ln:
                continue
            m = _PHOTON_ALLOW_RE.search(ln)
            if m:
                name, reason = m.group(1), m.group(2)
                if reason:
                    self.suppressions[i] = ("rule", name, reason)
                else:
                    self.bad_suppressions.append((i, name))
                continue
            m = _PHOTON_TAG_RE.search(ln)
            if m:
                tag, reason = m.group(1), m.group(2)
                if reason:
                    self.suppressions[i] = ("tag", tag, reason)
                else:
                    self.bad_suppressions.append((i, tag))
                continue
            m = _PHOTON_BARE_RE.search(ln)
            if m:
                self.bad_suppressions.append((i, m.group(1)))
                continue
            m = _SUPPRESS_RE.search(ln)
            if m:
                tag, reason = m.group(1), m.group(2)
                if reason:
                    self.suppressions[i] = ("legacy", tag, reason)
                else:
                    self.bad_suppressions.append((i, tag))
                continue
            m = _SUPPRESS_BARE_RE.search(ln)
            if m:
                self.bad_suppressions.append((i, m.group(1)))

    def match_waiver(self, line: int, tag: str,
                     rule: Optional[str] = None) -> Optional[int]:
        """The lineno of the waiver covering a finding at ``line`` (same
        line or the line directly above), or None. Tag forms match the
        rule's suppression tag; the ``allow`` form matches the rule
        name."""
        for at in (line, line - 1):
            got = self.suppressions.get(at)
            if not got:
                continue
            kind, name, _reason = got
            if kind == "rule":
                if rule is not None and name == rule:
                    return at
            elif name == tag:
                return at
        return None

    def suppressed(self, line: int, tag: str,
                   rule: Optional[str] = None) -> bool:
        """A finding at ``line`` is waived by a reasoned comment on the
        same line or the line directly above."""
        return self.match_waiver(line, tag, rule) is not None

    # ------------------------------------------------------ AST helpers
    def literal(self, name: str):
        """The literal value of a module-level ``NAME = <literal>``
        assignment (the registry-reading path — no imports)."""
        for node in self.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        return ast.literal_eval(node.value)
            elif isinstance(node, ast.AnnAssign):
                if (isinstance(node.target, ast.Name)
                        and node.target.id == name
                        and node.value is not None):
                    return ast.literal_eval(node.value)
        raise KeyError(f"{self.rel}: no module-level literal {name!r}")

    def literal_line(self, name: str, key: str) -> int:
        """Best-effort line number of ``key`` inside the ``NAME``
        literal's source span (for findings pointing at registry
        entries)."""
        pat = re.compile(r"[\"']" + re.escape(key) + r"[\"']")
        for i, ln in enumerate(self.lines, start=1):
            if pat.search(ln):
                return i
        return 1

    def qualname_at(self, line: int) -> str:
        """Dotted def/class path enclosing ``line`` ('' at module
        level)."""
        best: list = []

        def descend(node, stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                    end = getattr(child, "end_lineno", child.lineno)
                    if child.lineno <= line <= end:
                        trail = stack + [child.name]
                        if len(trail) > len(best):
                            best[:] = trail
                        descend(child, trail)
                else:
                    descend(child, stack)

        descend(self.tree, [])
        return ".".join(best)


def repo_root() -> str:
    """The repository root: the parent of the ``photon_tpu`` package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _iter_rel_paths(root: str) -> Iterable[str]:
    pkg = os.path.join(root, "photon_tpu")
    for base, dirs, names in os.walk(pkg):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for n in sorted(names):
            if n.endswith(".py"):
                yield os.path.relpath(os.path.join(base, n), root)
    if os.path.exists(os.path.join(root, "bench.py")):
        yield "bench.py"
    benches = os.path.join(root, "benches")
    if os.path.isdir(benches):
        for n in sorted(os.listdir(benches)):
            if n.endswith(".py"):
                yield os.path.join("benches", n)


class Context:
    """Everything the rules see: parsed files + the repo root. Rules may
    add findings for unparseable files via ``parse_errors``."""

    def __init__(self, root: str, files: dict, parse_errors: list):
        self.root = root
        self.files = files  # rel -> SourceFile
        self.parse_errors = parse_errors  # [(rel, message)]

    def get(self, rel: str) -> Optional[SourceFile]:
        return self.files.get(rel.replace(os.sep, "/"))

    def package_files(self) -> list:
        return [f for rel, f in sorted(self.files.items())
                if rel.startswith("photon_tpu/")]

    def tests_text(self) -> str:
        """Concatenated raw text of tests/*.py — for orphan checks that
        accept a test as the knob's reader of record."""
        out = []
        tdir = os.path.join(self.root, "tests")
        if os.path.isdir(tdir):
            for n in sorted(os.listdir(tdir)):
                if n.endswith(".py"):
                    try:
                        with open(os.path.join(tdir, n)) as fh:
                            out.append(fh.read())
                    except OSError:
                        pass
        return "\n".join(out)


def load_context(root: Optional[str] = None) -> Context:
    root = root or repo_root()
    files: dict = {}
    errors: list = []
    for rel in _iter_rel_paths(root):
        rel = rel.replace(os.sep, "/")
        try:
            with open(os.path.join(root, rel)) as fh:
                text = fh.read()
            files[rel] = SourceFile(rel, text)
        except (OSError, SyntaxError) as e:
            errors.append((rel, f"{type(e).__name__}: {e}"))
    return Context(root, files, errors)


def load_baseline(path: Optional[str] = None) -> set:
    """Fingerprints of baselined findings. Ships EMPTY (see module
    docstring)."""
    path = path or os.path.join(os.path.dirname(__file__), "baseline.json")
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return set()
    return {(f["rule"], f["path"], f["key"])
            for f in doc.get("findings", [])}


def _changed_files(root: str) -> Optional[set]:
    """Working-tree files changed vs HEAD (--changed); None if git is
    unavailable (the caller degrades to a full run)."""
    try:
        out = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=30)
        if out.returncode != 0:
            return None
    except (OSError, subprocess.TimeoutExpired):
        return None
    changed = set()
    for ln in out.stdout.splitlines():
        part = ln[3:].strip()
        if " -> " in part:
            part = part.split(" -> ", 1)[1]
        changed.add(part.strip('"'))
    return changed


def run_lint(root: Optional[str] = None, only: Optional[list] = None,
             changed: bool = False,
             baseline: Optional[set] = None) -> dict:
    """Run every rule; returns {"findings", "suppressed", "n_files",
    "n_rules", "ok"}. ``only`` filters by rule name; ``changed``
    restricts FINDINGS to files with working-tree changes (rules still
    see the whole repo — cross-file invariants need it)."""
    from photon_tpu.lint import rules as _rules

    ctx = load_context(root)
    baseline = load_baseline() if baseline is None else baseline
    findings: list = []
    suppressed: list = []
    for rel, msg in ctx.parse_errors:
        findings.append(Finding("parse", rel, 1, msg, key="parse"))
    n_rules = 0
    used: dict = {}  # rel -> set of waiver linenos that covered a finding
    for name, (fn, tag, _doc) in _rules.RULES.items():
        if only and name not in only:
            continue
        n_rules += 1
        for f in fn(ctx):
            src = ctx.get(f.path)
            at = (src.match_waiver(f.line, tag, rule=name)
                  if src is not None else None)
            if at is not None:
                suppressed.append(f)
                used.setdefault(f.path, set()).add(at)
            else:
                findings.append(f)
    if not only or "suppression" in only:
        n_rules += 1
        for rel, src in sorted(ctx.files.items()):
            for line, tag in src.bad_suppressions:
                findings.append(Finding(
                    "suppression", rel, line,
                    f"suppression comment for tag {tag!r} has no reason "
                    "string — a reason is mandatory",
                    key=f"{tag}@{line}"))
        if not only:
            # stale-waiver check: photon-form waivers on lines where the
            # named rule no longer fires are themselves findings. Only
            # meaningful on a full run — with a rule filter most waivers
            # would look stale.
            for rel, src in sorted(ctx.files.items()):
                for at, (kind, name, _r) in sorted(
                        src.suppressions.items()):
                    if kind == "legacy" or at in used.get(rel, set()):
                        continue
                    findings.append(Finding(
                        "suppression", rel, at,
                        f"stale waiver: `photon:` comment for {name!r} "
                        "on a line where that rule no longer fires — "
                        "remove the waiver",
                        key=f"stale:{name}@{at}"))
    findings = [f for f in findings if f.fingerprint not in baseline]
    if changed:
        ch = _changed_files(ctx.root)
        if ch is not None:
            findings = [f for f in findings if f.path in ch]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return {"findings": findings, "suppressed": suppressed,
            "n_files": len(ctx.files), "n_rules": n_rules,
            "ok": not findings}
