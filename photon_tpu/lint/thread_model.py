"""Whole-program thread model: the repo-wide concurrency facts the
per-function rules can't see.

Built once per lint Context from the same parsed ASTs (jax-free, no
imports of the audited code), this module answers three questions the
concurrency rules and the ``--threads`` CLI both consume:

1. **Thread inventory** — every thread/process entry point in the repo
   (``threading.Thread(target=...)``, executor ``submit`` targets and
   initializers, spawn-context ``Process(target=...)``) with the call
   graph reachable from each entry. Processes are inventoried but NOT
   treated as sharing memory (spawn context: separate address space).
2. **Lock-order graph** — which locks are acquired while which others
   are held, across call boundaries (``f`` holds L and calls ``g`` that
   takes M ⇒ edge L→M). A cycle is a potential deadlock.
3. **Guarded-by bindings** — for every instance attribute / module
   global written outside ``__init__``, the set of locks definitely held
   at each write (lexically held ∪ locks held at EVERY call path into
   the writing function), plus the set of thread roles that can execute
   the write. State written from ≥2 roles with no common lock is the
   race the guarded_by rule reports.

Resolution is deliberately best-effort and under-approximating: calls
resolve through ``self`` methods, same-module functions, imports,
constructor-typed / annotation-typed attributes and locals. An
unresolvable call contributes no edge — the model never invents
reachability, so its findings point at real paths.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Optional

__all__ = ["ThreadModel", "build_thread_model"]

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_QUEUE_CTORS = {"queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
                "queue.PriorityQueue", "Queue", "SimpleQueue"}
_EXEC_SUFFIX = ("ThreadPoolExecutor", "ProcessPoolExecutor")
_SUBPROCESS = {"subprocess.run", "subprocess.call", "subprocess.check_call",
               "subprocess.check_output", "run", "check_call",
               "check_output"}
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "popleft", "appendleft", "remove", "clear",
             "discard"}


def _dotted(func) -> str:
    parts: list = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    elif parts:
        parts.append("?")
    return ".".join(reversed(parts))


def _kw(call: ast.Call, name: str):
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _str_const(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _module_of(rel: str) -> str:
    mod = rel[:-3].replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


def _ann_class_name(ann) -> Optional[str]:
    """Dotted class name out of an annotation, unwrapping Optional[...]"""
    if ann is None:
        return None
    if isinstance(ann, ast.Subscript) \
            and _dotted(ann.value) in ("Optional", "typing.Optional"):
        ann = ann.slice
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value  # string annotation: "ProgramLadder"
    name = _dotted(ann)
    return name or None


@dataclasses.dataclass
class ClassInfo:
    key: str            # rel::Name
    rel: str
    module: str
    name: str
    lineno: int
    locks: set = dataclasses.field(default_factory=set)
    queues: set = dataclasses.field(default_factory=set)
    threads: set = dataclasses.field(default_factory=set)
    executors: set = dataclasses.field(default_factory=set)
    attr_types: dict = dataclasses.field(default_factory=dict)  # attr->dotted
    methods: dict = dataclasses.field(default_factory=dict)     # name->fnkey


@dataclasses.dataclass
class CallSite:
    line: int
    held: tuple          # lock ids held lexically at the call
    targets: tuple       # resolved function keys
    dotted: str


@dataclasses.dataclass
class Write:
    attr: str            # "rel::Class.attr" or "rel::<global>.name"
    line: int
    held: tuple


@dataclasses.dataclass
class FunctionInfo:
    key: str             # rel::qual
    rel: str
    module: str
    cls: Optional[str]   # enclosing class name
    qual: str
    name: str
    lineno: int
    node: object = None
    acquired: list = dataclasses.field(default_factory=list)   # (lock, line)
    lexical_edges: list = dataclasses.field(default_factory=list)
    calls: list = dataclasses.field(default_factory=list)      # CallSite
    writes: list = dataclasses.field(default_factory=list)     # Write
    blockers: list = dataclasses.field(default_factory=list)   # (desc,line,held)
    local_defs: dict = dataclasses.field(default_factory=dict)  # name->fnkey

    @property
    def public(self) -> bool:
        n = self.name
        return not n.startswith("_") or (n.startswith("__")
                                         and n.endswith("__"))


@dataclasses.dataclass
class Entry:
    kind: str            # "thread" | "executor" | "process"
    label: str           # thread name literal / prefix / target short name
    rel: str
    line: int
    targets: tuple       # function keys
    created_in: str      # function key of the spawn site
    shares_memory: bool


class ThreadModel:
    """See module docstring. Build with :func:`build_thread_model`."""

    def __init__(self):
        self.functions: dict = {}     # key -> FunctionInfo
        self.classes: dict = {}       # key -> ClassInfo
        self.entries: list = []       # Entry
        self.module_locks: dict = {}  # module -> set of global lock names
        self.lock_edges: dict = {}    # (a, b) -> (rel, line, via)
        self.lock_decls: dict = {}    # lock id -> (rel, line)
        self.cycles: list = []        # [(lock, ...), ...] canonical tuples
        self.reach: dict = {}         # entry index -> frozenset of fn keys
        self.client_reach: frozenset = frozenset()
        self.roles: dict = {}         # fn key -> tuple of role labels
        self.inherited: dict = {}     # fn key -> frozenset of locks
        self.shared: dict = {}        # attr -> {"roles", "locks", "writes"}

    # ------------------------------------------------------------ queries
    def function_roles(self, key: str) -> tuple:
        return self.roles.get(key, ())

    def effective_locks(self, fn: FunctionInfo, held: tuple) -> frozenset:
        return frozenset(held) | self.inherited.get(fn.key, frozenset())

    def thread_names(self) -> set:
        return {e.label for e in self.entries}

    # ---------------------------------------------------------- rendering
    def to_doc(self) -> dict:
        threads = []
        for i, e in enumerate(self.entries):
            threads.append({
                "kind": e.kind, "label": e.label, "created_at":
                f"{e.rel}:{e.line}",
                "targets": [t.split("::", 1)[1] for t in e.targets],
                "reachable_fns": len(self.reach.get(i, ())),
                "shares_memory": e.shares_memory,
            })
        edges = [{"from": a, "to": b, "at": f"{w[0]}:{w[1]}", "via": w[2]}
                 for (a, b), w in sorted(self.lock_edges.items())]
        shared = {}
        for attr, info in sorted(self.shared.items()):
            shared[attr] = {
                "roles": sorted(info["roles"]),
                "locks": sorted(info["locks"]),
                "n_writes": len(info["writes"]),
            }
        return {"threads": threads,
                "locks": sorted(self.lock_decls),
                "lock_edges": edges,
                "lock_cycles": [list(c) for c in self.cycles],
                "guarded_by": shared}

    def render(self) -> str:
        doc = self.to_doc()
        out = [f"thread inventory ({len(doc['threads'])} entries):"]
        for t in doc["threads"]:
            mem = "" if t["shares_memory"] else "  [separate memory]"
            out.append(f"  {t['kind']:<9s} {t['label']:<24s} "
                       f"{t['created_at']:<44s} -> "
                       f"{', '.join(t['targets']) or '?'} "
                       f"({t['reachable_fns']} fns){mem}")
        out.append(f"locks ({len(doc['locks'])}):")
        for lk in doc["locks"]:
            out.append(f"  {lk}")
        out.append(f"lock-order edges ({len(doc['lock_edges'])}):")
        for e in doc["lock_edges"]:
            out.append(f"  {e['from']} -> {e['to']}  (at {e['at']}, "
                       f"{e['via']})")
        if doc["lock_cycles"]:
            out.append("LOCK CYCLES (potential deadlock):")
            for c in doc["lock_cycles"]:
                out.append("  " + " -> ".join(c + [c[0]]))
        out.append(f"guarded-by bindings ({len(doc['guarded_by'])} "
                   "multi-thread attrs):")
        for attr, info in doc["guarded_by"].items():
            locks = "{" + ", ".join(info["locks"]) + "}" if info["locks"] \
                else "UNGUARDED"
            out.append(f"  {attr:<52s} roles={{{', '.join(info['roles'])}}} "
                       f"locks={locks}")
        return "\n".join(out)

    def render_dot(self) -> str:
        out = ["digraph lock_order {", "  rankdir=LR;"]
        for lk in sorted(self.lock_decls):
            out.append(f'  "{lk}";')
        in_cycle = {n for c in self.cycles for n in c}
        for (a, b), w in sorted(self.lock_edges.items()):
            color = ' [color=red]' if a in in_cycle and b in in_cycle else ""
            out.append(f'  "{a}" -> "{b}"{color};  // {w[0]}:{w[1]}')
        out.append("}")
        return "\n".join(out)


# ============================================================== builder

class _Builder:
    def __init__(self, ctx):
        self.ctx = ctx
        self.m = ThreadModel()
        self.imports: dict = {}          # module -> {name: dotted target}
        self.cls_by_dotted: dict = {}    # "mod.Class" -> ClassInfo
        self.fn_by_dotted: dict = {}     # "mod.fn" -> key
        self.global_types: dict = {}     # "mod.name" -> dotted class

    # -------------------------------------------------------- pass 1: index
    def index(self) -> None:
        for rel, src in sorted(self.ctx.files.items()):
            module = _module_of(rel)
            self.imports[module] = self._import_map(src.tree, module)
            self.m.module_locks[module] = set()
            for node in src.tree.body:
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call) \
                        and _dotted(node.value.func) in _LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.m.module_locks[module].add(t.id)
                            self.m.lock_decls[f"{module}.{t.id}"] = (
                                rel, node.lineno)
                if isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    cname = _ann_class_name(node.annotation)
                    if cname:
                        self.global_types[f"{module}.{node.target.id}"] \
                            = cname
            self._index_scope(rel, module, src.tree, cls=None, prefix="")

    def _index_scope(self, rel, module, node, cls, prefix) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                key = f"{rel}::{child.name}"
                ci = ClassInfo(key=key, rel=rel, module=module,
                               name=child.name, lineno=child.lineno)
                self.m.classes[key] = ci
                self.cls_by_dotted[f"{module}.{child.name}"] = ci
                self._index_scope(rel, module, child, cls=ci,
                                  prefix=f"{prefix}{child.name}.")
                self._scan_class_attrs(ci, child)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                key = f"{rel}::{qual}"
                fi = FunctionInfo(key=key, rel=rel, module=module,
                                  cls=cls.name if cls else None, qual=qual,
                                  name=child.name, lineno=child.lineno,
                                  node=child)
                self.m.functions[key] = fi
                if cls is not None and "." not in qual.replace(
                        cls.name + ".", "", 1):
                    cls.methods[child.name] = key
                if cls is None and prefix == "":
                    self.fn_by_dotted[f"{module}.{child.name}"] = key
                self._index_scope(rel, module, child, cls=cls,
                                  prefix=f"{qual}.")
            else:
                self._index_scope(rel, module, child, cls, prefix)

    def _import_map(self, tree, module) -> dict:
        out: dict = {}
        pkg_parts = module.split(".")
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
                    if a.asname:
                        out[a.asname] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = ".".join(pkg_parts[:len(pkg_parts) - node.level
                                              + 1])
                    src_mod = f"{base}.{node.module}" if node.module \
                        else base
                else:
                    src_mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = f"{src_mod}.{a.name}"
        return out

    def _scan_class_attrs(self, ci: ClassInfo, cls_node) -> None:
        for meth in cls_node.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            ann_params = {a.arg: _ann_class_name(a.annotation)
                          for a in meth.args.args if a.annotation}
            for node in ast.walk(meth):
                tgt = val = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    tgt, val = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    tgt, val = node.target, node.value
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                attr = tgt.attr
                if isinstance(node, ast.AnnAssign):
                    cname = _ann_class_name(node.annotation)
                    if cname:
                        ci.attr_types[attr] = cname
                if isinstance(val, ast.Call):
                    d = _dotted(val.func)
                    if d in _LOCK_CTORS:
                        ci.locks.add(attr)
                        self.m.lock_decls[
                            f"{ci.module}.{ci.name}.{attr}"] = (
                            ci.rel, node.lineno)
                    elif d in _QUEUE_CTORS:
                        ci.queues.add(attr)
                    elif d.endswith("Thread") and _kw(val, "target"):
                        ci.threads.add(attr)
                    elif d.endswith(_EXEC_SUFFIX):
                        ci.executors.add(attr)
                    else:
                        ci.attr_types.setdefault(attr, d)
                elif isinstance(val, ast.Name) and val.id in ann_params \
                        and ann_params[val.id]:
                    ci.attr_types.setdefault(attr, ann_params[val.id])

    # ------------------------------------------------- symbol resolution
    def _resolve_class(self, module: str, dotted: str) -> \
            Optional[ClassInfo]:
        if not dotted:
            return None
        parts = dotted.split(".")
        imp = self.imports.get(module, {})
        if parts[0] in imp:
            full = ".".join([imp[parts[0]]] + parts[1:])
        else:
            full = f"{module}.{dotted}"
        ci = self.cls_by_dotted.get(full)
        if ci is None and "." not in dotted:
            ci = self.cls_by_dotted.get(f"{module}.{dotted}")
        return ci

    def _resolve_call(self, fn: FunctionInfo, call: ast.Call,
                      local_types: dict) -> tuple:
        """Resolved function keys for one call (possibly empty)."""
        func = call.func
        module = fn.module
        # self.meth(...) / self.attr.meth(...)
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self" and fn.cls:
                ci = self.m.classes.get(f"{fn.rel}::{fn.cls}")
                if ci and func.attr in ci.methods:
                    return (ci.methods[func.attr],)
                return ()
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and fn.cls:
                ci = self.m.classes.get(f"{fn.rel}::{fn.cls}")
                tname = ci.attr_types.get(base.attr) if ci else None
                tci = self._resolve_class(module, tname) if tname else None
                if tci and func.attr in tci.methods:
                    return (tci.methods[func.attr],)
                return ()
            if isinstance(base, ast.Name):
                tname = local_types.get(base.id)
                tci = self._resolve_class(module, tname) if tname else None
                if tci and func.attr in tci.methods:
                    return (tci.methods[func.attr],)
        dotted = _dotted(func)
        if not dotted or "?" in dotted:
            return ()
        parts = dotted.split(".")
        imp = self.imports.get(module, {})
        if parts[0] == "self":
            return ()
        if parts[0] in local_types and len(parts) == 2:
            tci = self._resolve_class(module, local_types[parts[0]])
            if tci and parts[1] in tci.methods:
                return (tci.methods[parts[1]],)
            return ()
        if len(parts) == 1:
            if parts[0] in fn.local_defs:
                return (fn.local_defs[parts[0]],)
            hit = self.fn_by_dotted.get(f"{module}.{parts[0]}")
            if hit:
                return (hit,)
        if parts[0] in imp:
            full = ".".join([imp[parts[0]]] + parts[1:])
        else:
            full = f"{module}.{dotted}"
        hit = self.fn_by_dotted.get(full)
        if hit:
            return (hit,)
        ci = self.cls_by_dotted.get(full)
        if ci:  # constructor: __init__ is reachable
            init = ci.methods.get("__init__")
            return (init,) if init else ()
        # mod.Class.method
        head, _, meth = full.rpartition(".")
        ci = self.cls_by_dotted.get(head)
        if ci and meth in ci.methods:
            return (ci.methods[meth],)
        return ()

    def _resolve_lock(self, expr, fn: FunctionInfo) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and fn.cls:
            ci = self.m.classes.get(f"{fn.rel}::{fn.cls}")
            if ci and expr.attr in ci.locks:
                return f"{fn.module}.{fn.cls}.{expr.attr}"
        if isinstance(expr, ast.Name) \
                and expr.id in self.m.module_locks.get(fn.module, ()):
            return f"{fn.module}.{expr.id}"
        return None

    # --------------------------------------------------- pass 2: bodies
    def scan_bodies(self) -> None:
        for fn in self.m.functions.values():
            self._scan_function(fn)

    def _local_types(self, fn: FunctionInfo) -> dict:
        """var -> dotted class name, from ctor calls, annotated params,
        and typed-global aliasing (flow-insensitive, last wins)."""
        out: dict = {}
        node = fn.node
        for a in list(node.args.args) + list(node.args.kwonlyargs):
            cname = _ann_class_name(a.annotation)
            if cname:
                out[a.arg] = cname
        for n in ast.walk(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                var = n.targets[0].id
                if isinstance(n.value, ast.Call):
                    d = _dotted(n.value.func)
                    if d in _QUEUE_CTORS:
                        out[var] = "@queue"
                    elif d.endswith("Thread") and _kw(n.value, "target"):
                        out[var] = "@thread"
                    elif d.endswith(_EXEC_SUFFIX):
                        out[var] = "@executor:" + (
                            "process" if d.endswith("ProcessPoolExecutor")
                            else "thread")
                    elif self._resolve_class(fn.module, d):
                        out[var] = d
                elif isinstance(n.value, ast.Name):
                    g = self.global_types.get(
                        f"{fn.module}.{n.value.id}")
                    if g:
                        out[var] = g
        return out

    def _scan_function(self, fn: FunctionInfo) -> None:
        node = fn.node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn.local_defs[child.name] = f"{fn.rel}::{fn.qual}." \
                    f"{child.name}"
        local_types = self._local_types(fn)
        globals_decl: set = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Global):
                globals_decl.update(n.names)
        self._visit_body(fn, list(node.body), (), local_types,
                         globals_decl)

    def _visit_body(self, fn, stmts, held, local_types, globals_decl):
        for stmt in stmts:
            self._visit(fn, stmt, held, local_types, globals_decl)

    def _visit(self, fn, node, held, local_types, globals_decl):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs scanned as their own FunctionInfo
        if isinstance(node, ast.With):
            new = list(held)
            for item in node.items:
                lock = self._resolve_lock_expr(item.context_expr, fn)
                if lock is not None:
                    fn.acquired.append((lock, node.lineno))
                    for h in new:
                        if h != lock:
                            fn.lexical_edges.append((h, lock, node.lineno))
                    new.append(lock)
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    self._visit(fn, item.context_expr, held, local_types,
                                globals_decl)
            self._visit_body(fn, node.body, tuple(new), local_types,
                             globals_decl)
            return
        if isinstance(node, ast.Call):
            self._record_call(fn, node, held, local_types, globals_decl)
            for child in ast.iter_child_nodes(node):
                self._visit(fn, child, held, local_types, globals_decl)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                    else [t]
                for e in elts:
                    self._record_write_target(fn, e, held, globals_decl)
        for child in ast.iter_child_nodes(node):
            self._visit(fn, child, held, local_types, globals_decl)

    def _resolve_lock_expr(self, expr, fn) -> Optional[str]:
        if isinstance(expr, ast.Call):  # e.g. contextlib.suppress(...)
            return None
        return self._resolve_lock(expr, fn)

    def _infra_attr(self, fn, attr: str) -> bool:
        ci = self.m.classes.get(f"{fn.rel}::{fn.cls}") if fn.cls else None
        if ci is None:
            return False
        return attr in ci.locks or attr in ci.queues \
            or attr in ci.threads or attr in ci.executors

    def _record_write_target(self, fn, e, held, globals_decl) -> None:
        if isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name) \
                and e.value.id == "self" and fn.cls:
            if not self._infra_attr(fn, e.attr):
                fn.writes.append(Write(f"{fn.rel}::{fn.cls}.{e.attr}",
                                       e.lineno, held))
        elif isinstance(e, ast.Name) and e.id in globals_decl:
            fn.writes.append(Write(f"{fn.rel}::<global>.{e.id}",
                                   e.lineno, held))
        elif isinstance(e, ast.Subscript):
            v = e.value
            if isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) \
                    and v.value.id == "self" and fn.cls \
                    and not self._infra_attr(fn, v.attr):
                fn.writes.append(Write(f"{fn.rel}::{fn.cls}.{v.attr}",
                                       e.lineno, held))
            elif isinstance(v, ast.Name) and v.id in globals_decl:
                fn.writes.append(Write(f"{fn.rel}::<global>.{v.id}",
                                       e.lineno, held))

    # ------------------------------------------------ call-site handling
    def _queue_typed(self, fn, base, local_types) -> bool:
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and fn.cls:
            ci = self.m.classes.get(f"{fn.rel}::{fn.cls}")
            return bool(ci) and base.attr in ci.queues
        if isinstance(base, ast.Name):
            return local_types.get(base.id) == "@queue"
        return False

    def _thread_typed(self, fn, base, local_types) -> bool:
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and fn.cls:
            ci = self.m.classes.get(f"{fn.rel}::{fn.cls}")
            return bool(ci) and base.attr in ci.threads
        if isinstance(base, ast.Name):
            return local_types.get(base.id) == "@thread"
        return False

    def _executor_kind(self, fn, base, local_types) -> Optional[str]:
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and fn.cls:
            ci = self.m.classes.get(f"{fn.rel}::{fn.cls}")
            if ci and base.attr in ci.executors:
                return "thread"
        if isinstance(base, ast.Name):
            t = local_types.get(base.id, "")
            if t.startswith("@executor:"):
                return t.split(":", 1)[1]
        return None

    def _classify_blocking(self, fn, call, dotted,
                           local_types) -> Optional[str]:
        if dotted == "open" or dotted == "io.open":
            return "file IO (open)"
        if dotted.endswith("fsync") or dotted in ("json.dump",):
            return f"file IO ({dotted})"
        if dotted in ("np.save", "np.load", "numpy.save", "numpy.load",
                      "np.savez", "numpy.savez"):
            return f"file IO ({dotted})"
        if dotted in ("time.sleep", "sleep"):
            return "time.sleep"
        if dotted.endswith("device_get"):
            return "jax.device_get (device sync)"
        if dotted in _SUBPROCESS and "." in dotted:
            return f"{dotted} (subprocess)"
        if dotted.endswith("retry_io") :
            return "faults.retry_io (sleeps between retries)"
        if not isinstance(call.func, ast.Attribute):
            return None
        base = call.func.value
        meth = call.func.attr
        timeout = _kw(call, "timeout") is not None or (
            len(call.args) >= (2 if meth == "put" else 1)
            and meth in ("put", "get", "wait", "join", "result", "acquire"))
        if meth in ("put", "get") and self._queue_typed(fn, base,
                                                        local_types):
            if not timeout and not (_kw(call, "block") is not None):
                return f"queue.Queue.{meth}() without timeout"
            return None
        if meth == "join" and (self._queue_typed(fn, base, local_types)
                               or (self._thread_typed(fn, base,
                                                      local_types)
                                   and not timeout)):
            return "untimed join()"
        if meth == "wait" and not timeout:
            return "untimed .wait() (Barrier/Event/Future)"
        if meth == "result" and not timeout:
            return "untimed Future.result()"
        return None

    def _record_call(self, fn, call, held, local_types,
                     globals_decl) -> None:
        dotted = _dotted(call.func)
        targets = self._resolve_call(fn, call, local_types)
        fn.calls.append(CallSite(call.lineno, held, targets, dotted))
        desc = self._classify_blocking(fn, call, dotted, local_types)
        if desc is not None:
            fn.blockers.append((desc, call.lineno, held))
        # mutating method on a shared attr counts as a write
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr in _MUTATORS:
            self._record_write_target(fn, call.func.value, held,
                                      globals_decl)
        # `lock.acquire()` contributes ordering edges
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "acquire":
            lock = self._resolve_lock(call.func.value, fn)
            if lock is not None:
                fn.acquired.append((lock, call.lineno))
                for h in held:
                    if h != lock:
                        fn.lexical_edges.append((h, lock, call.lineno))
        self._maybe_entry(fn, call, dotted, local_types)

    # ---------------------------------------------------- entry discovery
    def _target_keys(self, fn, expr, local_types) -> tuple:
        if expr is None:
            return ()
        if isinstance(expr, ast.Lambda):
            keys: list = []
            for c in ast.walk(expr.body):
                if isinstance(c, ast.Call):
                    keys.extend(self._resolve_call(fn, c, local_types))
            return tuple(keys)
        fake = ast.Call(func=expr, args=[], keywords=[])
        return self._resolve_call(fn, fake, local_types)

    def _maybe_entry(self, fn, call, dotted, local_types) -> None:
        if dotted.endswith("Thread") and _kw(call, "target") is not None:
            targets = self._target_keys(fn, _kw(call, "target"),
                                        local_types)
            name = _str_const(_kw(call, "name"))
            label = name or (targets[0].split("::", 1)[1].split(".")[-1]
                             if targets else "<thread>")
            self.m.entries.append(Entry("thread", label, fn.rel,
                                        call.lineno, targets, fn.key,
                                        shares_memory=True))
            return
        if (dotted.endswith(".Process") or dotted == "Process") \
                and _kw(call, "target") is not None:
            targets = self._target_keys(fn, _kw(call, "target"),
                                        local_types)
            label = _str_const(_kw(call, "name")) or (
                targets[0].split("::", 1)[1] if targets else "<process>")
            self.m.entries.append(Entry("process", label, fn.rel,
                                        call.lineno, targets, fn.key,
                                        shares_memory=False))
            return
        if dotted.endswith(_EXEC_SUFFIX):
            init = _kw(call, "initializer")
            if init is not None:
                targets = self._target_keys(fn, init, local_types)
                shares = not dotted.endswith("ProcessPoolExecutor")
                self.m.entries.append(Entry(
                    "process" if not shares else "executor",
                    (targets[0].split("::", 1)[1] if targets
                     else "<initializer>"), fn.rel, call.lineno, targets,
                    fn.key, shares_memory=shares))
            return
        if isinstance(call.func, ast.Attribute) \
                and call.func.attr == "submit" and call.args:
            kind = self._executor_kind(fn, call.func.value, local_types)
            if kind is None:
                return
            targets = self._target_keys(fn, call.args[0], local_types)
            label = (targets[0].split("::", 1)[1] if targets
                     else "<submit>")
            self.m.entries.append(Entry(
                "executor" if kind == "thread" else "process", label,
                fn.rel, call.lineno, targets, fn.key,
                shares_memory=kind == "thread"))

    # --------------------------------------------------- pass 3: fixpoints
    def analyze(self) -> None:
        m = self.m
        adj: dict = {k: set() for k in m.functions}
        callers: dict = {k: [] for k in m.functions}
        for fn in m.functions.values():
            for cs in fn.calls:
                for t in cs.targets:
                    if t in adj:
                        adj[fn.key].add(t)
                        callers[t].append((fn.key, cs))

        def bfs(seeds) -> frozenset:
            seen = set(seeds)
            frontier = list(seeds)
            while frontier:
                k = frontier.pop()
                for t in adj.get(k, ()):
                    if t not in seen:
                        seen.add(t)
                        frontier.append(t)
            return frozenset(seen)

        for i, e in enumerate(m.entries):
            m.reach[i] = bfs([t for t in e.targets if t in m.functions])
        client_seeds = [k for k, fn in m.functions.items() if fn.public]
        m.client_reach = bfs(client_seeds)

        entry_targets = {t for e in m.entries for t in e.targets}
        for k, fn in m.functions.items():
            roles: list = []
            if k in m.client_reach and not (
                    k in entry_targets and not fn.public):
                roles.append("caller")
            for i, e in enumerate(m.entries):
                if e.shares_memory and k in m.reach[i] \
                        and e.label not in roles:
                    roles.append(e.label)
            m.roles[k] = tuple(roles)

        # transitive lock acquisitions per function (for cross-call edges)
        acq: dict = {k: {a for a, _ in fn.acquired}
                     for k, fn in m.functions.items()}
        for _ in range(50):
            changed = False
            for k in m.functions:
                for t in adj[k]:
                    extra = acq[t] - acq[k]
                    if extra:
                        acq[k] |= extra
                        changed = True
            if not changed:
                break

        for fn in m.functions.values():
            for a, b, line in fn.lexical_edges:
                m.lock_edges.setdefault(
                    (a, b), (fn.rel, line, f"nested with in {fn.qual}"))
            for cs in fn.calls:
                if not cs.held:
                    continue
                for t in cs.targets:
                    for b in acq.get(t, ()):
                        for a in cs.held:
                            if a != b:
                                m.lock_edges.setdefault(
                                    (a, b),
                                    (fn.rel, cs.line,
                                     f"{fn.qual} -> "
                                     f"{t.split('::', 1)[1]}"))
        self._find_cycles()

        # inherited locks: meet over every resolved call path into fn
        inherited: dict = {k: None for k in m.functions}
        seeds = set(client_seeds) | {t for t in entry_targets
                                     if t in m.functions}
        for k in seeds:
            inherited[k] = frozenset()
        for _ in range(50):
            changed = False
            for k in m.functions:
                if k in seeds:
                    continue
                acc = None
                for ck, cs in callers[k]:
                    base = inherited[ck]
                    if base is None:
                        continue
                    site = frozenset(cs.held) | base
                    acc = site if acc is None else (acc & site)
                if acc is not None and acc != inherited[k]:
                    inherited[k] = acc
                    changed = True
            if not changed:
                break
        m.inherited = {k: (v if v is not None else frozenset())
                       for k, v in inherited.items()}

        # guarded-by: collect write sites per attr (skip __init__)
        per_attr: dict = {}
        for fn in m.functions.values():
            if fn.name == "__init__":
                continue
            eff_base = m.inherited[fn.key]
            for w in fn.writes:
                per_attr.setdefault(w.attr, []).append(
                    (fn, w.line, frozenset(w.held) | eff_base))
        for attr, sites in sorted(per_attr.items()):
            roles: set = set()
            for fn, _line, _locks in sites:
                roles.update(m.roles.get(fn.key, ()))
            if len(roles) < 2:
                continue
            common = None
            for _fn, _line, locks in sites:
                common = locks if common is None else (common & locks)
            m.shared[attr] = {
                "roles": roles, "locks": common or frozenset(),
                "writes": [(fn.key, line, locks)
                           for fn, line, locks in sites]}

    def _find_cycles(self) -> None:
        graph: dict = {}
        for (a, b) in self.m.lock_edges:
            graph.setdefault(a, set()).add(b)
        seen_cycles: set = set()
        color: dict = {}

        def dfs(n, stack):
            color[n] = 1
            stack.append(n)
            for t in graph.get(n, ()):
                if color.get(t, 0) == 1:
                    cyc = tuple(stack[stack.index(t):])
                    lo = min(range(len(cyc)), key=lambda i: cyc[i])
                    canon = cyc[lo:] + cyc[:lo]
                    if canon not in seen_cycles:
                        seen_cycles.add(canon)
                        self.m.cycles.append(canon)
                elif color.get(t, 0) == 0:
                    dfs(t, stack)
            stack.pop()
            color[n] = 2

        for n in sorted(graph):
            if color.get(n, 0) == 0:
                dfs(n, [])

    def build(self) -> ThreadModel:
        self.index()
        self.scan_bodies()
        self.analyze()
        return self.m


def build_thread_model(ctx) -> ThreadModel:
    """Build (and cache on the Context) the repo thread model."""
    cached = getattr(ctx, "_thread_model", None)
    if cached is None:
        cached = _Builder(ctx).build()
        ctx._thread_model = cached
    return cached
